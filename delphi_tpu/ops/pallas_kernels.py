"""Pallas TPU kernels for the hot statistics ops.

The single hottest kernel in the pipeline is pair co-occurrence counting
(the reference's `GROUP BY GROUPING SETS` aggregation, RepairApi.scala:231-273,
which every entropy/domain computation feeds on). The XLA fallback in
``ops/freq.py`` lowers `jnp.bincount` to scatter-adds; on TPU scatters
serialize on the VPU. The Pallas kernel here instead maps the count onto the
MXU systolic array:

    counts[Vx, Vy] = sum_r one_hot(x_r)^T @ one_hot(y_r)

tiled over rows, with the one-hot blocks materialized **only in VMEM** (never
in HBM) and contracted immediately — a classic "fuse the encode into the
matmul" pattern. HBM traffic is just the two int32 code vectors plus one
[V, V] accumulator, instead of two [n, V] one-hot matrices.

Padding: rows are padded to a multiple of the tile with the sentinel -2 so the
shifted code (-1) matches no one-hot column (NULL itself is slot 0 via the +1
shift, matching SQL GROUP BY semantics).

A second kernel computes the xlogx entropy partial sums used by
``ops/entropy.py`` (H terms of RepairApi.scala:284-394) in one VMEM pass.

Kernels run compiled on TPU and in interpret mode on CPU (tests exercise both
paths against the XLA reference implementation).
"""

import os
from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from delphi_tpu.ops.xfer import to_device

_ROW_TILE = 4096         # rows contracted per grid step
_LANE = 128              # TPU lane width; vocab padded to a multiple
_PAD_SENTINEL = -2       # shifted to -1: matches no one-hot column
_VMEM_V_LIMIT = 2048     # fall back to XLA above this padded vocab size


def _interpret_mode() -> bool:
    return jax.default_backend() != "tpu"


def pallas_policy() -> str:
    """DELPHI_PALLAS=1 forces the pallas kernels (interpret mode off-TPU),
    0 disables them, auto (default) leaves the decision to the caller's
    ``default`` (normally: only on a real TPU backend). The ONE policy
    parser shared by every pallas routing decision (pair counts in
    ops/freq.py, entropy terms in ops/entropy.py)."""
    return os.environ.get("DELPHI_PALLAS", "auto").lower()


def resolve_pallas_policy(supported: bool, default: bool) -> bool:
    """Folds the DELPHI_PALLAS policy with a kernel's capability guard:
    never run an unsupported shape, always honor an explicit 0/1, and fall
    back to the caller's backend-dependent ``default`` on auto."""
    policy = pallas_policy()
    if policy in ("0", "off", "never") or not supported:
        return False
    if policy in ("1", "on", "force"):
        return True
    return default


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


# ---------------------------------------------------------------------------
# Pair co-occurrence counts: one-hot matmul on the MXU
# ---------------------------------------------------------------------------

def _pair_count_kernel(x_ref, y_ref, out_ref):
    """Grid step i contracts one row tile into the [Vx, Vy] accumulator."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    # codes arrive pre-shifted (+1, NULL=0, pad=-1) as [ROW_TILE, 1] blocks
    x = x_ref[:, 0]
    y = y_ref[:, 0]
    vx = out_ref.shape[0]
    vy = out_ref.shape[1]

    # One-hot blocks live only in VMEM registers; built by iota compare.
    col_x = jax.lax.broadcasted_iota(jnp.int32, (_ROW_TILE, vx), 1)
    col_y = jax.lax.broadcasted_iota(jnp.int32, (_ROW_TILE, vy), 1)
    oh_x = (x[:, None] == col_x).astype(jnp.float32)
    oh_y = (y[:, None] == col_y).astype(jnp.float32)

    # [Vx, Vy] += X^T Y on the MXU (contract the row-tile axis).
    out_ref[:] += jax.lax.dot_general(
        oh_x, oh_y,
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


@partial(jax.jit, static_argnums=(2, 3, 4))
def _pair_counts_padded(x_codes: jnp.ndarray, y_codes: jnp.ndarray,
                        vx_pad: int, vy_pad: int, interpret: bool) -> jnp.ndarray:
    """Takes raw codes (NULL=-1) on device; shift and row padding are fused
    into the same XLA program so no extra host round-trip happens."""
    n = x_codes.shape[0]
    n_pad = _round_up(max(n, 1), _ROW_TILE)
    pad_cfg = (0, n_pad - n)
    x_shift = jnp.pad(x_codes.astype(jnp.int32) + 1, pad_cfg,
                      constant_values=_PAD_SENTINEL + 1)
    y_shift = jnp.pad(y_codes.astype(jnp.int32) + 1, pad_cfg,
                      constant_values=_PAD_SENTINEL + 1)
    n_tiles = n_pad // _ROW_TILE
    x2 = x_shift.reshape(-1, 1)
    y2 = y_shift.reshape(-1, 1)
    counts = pl.pallas_call(
        _pair_count_kernel,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((_ROW_TILE, 1), lambda i: (i, 0),
                         memory_space=pl.ANY if interpret else pltpu.VMEM),
            pl.BlockSpec((_ROW_TILE, 1), lambda i: (i, 0),
                         memory_space=pl.ANY if interpret else pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((vx_pad, vy_pad), lambda i: (0, 0),
                               memory_space=pl.ANY if interpret else pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((vx_pad, vy_pad), jnp.float32),
        cost_estimate=pl.CostEstimate(
            flops=2 * n_tiles * _ROW_TILE * vx_pad * vy_pad,
            bytes_accessed=8 * n_tiles * _ROW_TILE + 4 * vx_pad * vy_pad,
            transcendentals=0,
        ),
        interpret=interpret,
    )(x2, y2)
    return counts.astype(jnp.int32)


def pallas_pair_counts(x_codes: np.ndarray, y_codes: np.ndarray,
                       vx: int, vy: int) -> np.ndarray:
    """Co-occurrence count matrix [vx+1, vy+1] (slot 0 = NULL) for two int32
    code columns with NULL=-1. MXU one-hot-matmul kernel; exact counts
    (f32 accumulation is exact below 2^24 rows per shard)."""
    vx_pad = _round_up(vx + 1, _LANE)
    vy_pad = _round_up(vy + 1, _LANE)
    counts = _pair_counts_padded(to_device(x_codes), to_device(y_codes),
                                 vx_pad, vy_pad, _interpret_mode())
    return np.asarray(counts)[: vx + 1, : vy + 1]


def pallas_supported(vx: int, vy: int, n_rows: int = 0) -> bool:
    """Guards for the MXU kernel: the [Vx, Vy] f32 accumulator plus two
    one-hot row tiles must fit comfortably in ~16 MB of VMEM, and counts must
    stay exactly representable in f32 — any cell can reach n_rows, so shards
    with >= 2^24 rows fall back to the exact XLA int32 path."""
    if n_rows >= (1 << 24):
        return False
    vx_pad = _round_up(vx + 1, _LANE)
    vy_pad = _round_up(vy + 1, _LANE)
    if vx_pad > _VMEM_V_LIMIT or vy_pad > _VMEM_V_LIMIT:
        return False
    acc = vx_pad * vy_pad * 4
    tiles = _ROW_TILE * (vx_pad + vy_pad) * 4
    return acc + tiles < 12 * 1024 * 1024


# ---------------------------------------------------------------------------
# Entropy partial sums: single-pass VPU reduction
# ---------------------------------------------------------------------------

# One (1, n_pad) VMEM block per call; cap well under the ~16 MB budget.
_ENTROPY_MAX_GROUPS = 1 << 21


def entropy_pallas_supported(n_groups: int, n_rows: int) -> bool:
    """f32 exactness (total must represent n_rows exactly, same 2^24 bound as
    the pair counter) and single-block VMEM fit."""
    return n_rows < (1 << 24) and n_groups <= _ENTROPY_MAX_GROUPS


def _entropy_kernel(c_ref, n_ref, out_ref):
    c = c_ref[:]
    n_rows = n_ref[0, 0]
    nz = c > 0.0
    p = jnp.where(nz, c, 1.0) / n_rows
    h = -jnp.sum(jnp.where(nz, p * jnp.log2(p), 0.0)).reshape(1, 1)
    tot = jnp.sum(c).reshape(1, 1)
    cnt = jnp.sum(nz.astype(jnp.float32)).reshape(1, 1)
    out_ref[:] = jnp.concatenate(
        [h, tot, cnt, jnp.zeros((1, 5), jnp.float32)], axis=1)


@partial(jax.jit, static_argnums=(2,))
def _entropy_call(buf: jnp.ndarray, n_rows_arr: jnp.ndarray,
                  interpret: bool) -> jnp.ndarray:
    """Jitted (cached per n_pad shape): n_rows rides in SMEM so changing row
    counts never retraces."""
    return pl.pallas_call(
        _entropy_kernel,
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY if interpret else pltpu.VMEM),
            pl.BlockSpec(memory_space=pl.ANY if interpret else pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY if interpret else pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((1, 8), jnp.float32),
        interpret=interpret,
    )(buf, n_rows_arr)


def pallas_entropy_terms(counts: np.ndarray, n_rows: int) \
        -> Tuple[float, float, int]:
    """(h_observed, total_observed, n_observed_groups) for one count vector —
    the observed part of the corrected entropy (RepairApi.scala:306-325);
    the missing-mass correction stays in ops/entropy.py."""
    flat = counts.ravel().astype(np.float32)
    n_pad = _round_up(max(flat.size, 1), _LANE)
    buf = np.zeros((1, n_pad), dtype=np.float32)
    buf[0, : flat.size] = flat

    out = np.asarray(_entropy_call(
        to_device(buf),
        to_device(np.asarray([[float(n_rows)]], dtype=np.float32)),
        _interpret_mode()))
    return float(out[0, 0]), float(out[0, 1]), int(out[0, 2])
