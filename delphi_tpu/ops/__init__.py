"""Jitted statistical kernels over dictionary-encoded tables."""
