"""Pairwise conditional-entropy correlation statistics.

Replaces the reference's per-pair entropy SQL jobs (`RepairApi.scala:284-394`)
with vectorized log2 reductions over the dense pair-count matrices, keeping
the exact semantics:

    H(x|y) = H(x,y) - H(y)

where both entropies carry a missing-mass correction term: frequency groups
that fell below the freq-ratio threshold (or were never observed) are modeled
as `ubDomainSize` synthetic groups of average count
`max((n - observed_total) / ubDomainSize, 1)` — see RepairApi.scala:306-325
and 347-365. Domain sizes come from the ORIGINAL table stats (not bin counts),
matching the reference's quirk of passing `convertToDiscretizedTable`'s
domain_stats straight through.

If H(x|y) ~ 0 then y functionally determines x, so for each target x the
result list is sorted ascending — strongest correlate first.
"""

import math
from typing import Dict, List, Sequence, Tuple

import jax
import numpy as np

from delphi_tpu.ops.freq import FreqStats, Pair

# Below this many count groups the f64 host reduction wins; above it the
# single-pass VPU kernel (ops/pallas_kernels.py) avoids pulling big pair
# matrices through host memory.
_PALLAS_ENTROPY_MIN_GROUPS = 1 << 16


def _use_pallas_entropy(n_groups: int, n_rows: int) -> bool:
    # policy parsing + capability fold shared with the pair-count routing
    # (ops/pallas_kernels.resolve_pallas_policy) so the two cannot drift
    from delphi_tpu.ops import pallas_kernels as pk

    return pk.resolve_pallas_policy(
        pk.entropy_pallas_supported(n_groups, n_rows),
        default=jax.default_backend() == "tpu"
        and n_groups >= _PALLAS_ENTROPY_MIN_GROUPS)


def _entropy_with_correction(counts: np.ndarray, n_rows: int, ub_domain: int) \
        -> float:
    """-sum (c/n) log2 (c/n) over observed groups, plus the missing-mass
    correction for unobserved/filtered groups."""
    if _use_pallas_entropy(counts.size, n_rows):
        from delphi_tpu.ops.pallas_kernels import pallas_entropy_terms

        h, total, n_observed = pallas_entropy_terms(counts, n_rows)
        if n_rows > total:
            ub = max(ub_domain - n_observed, 1)
            avg = max((n_rows - total) / ub, 1.0)
            h += -ub * (avg / n_rows) * math.log2(avg / n_rows)
        return h

    observed = counts[counts > 0].astype(np.float64)
    total = float(observed.sum())
    p = observed / n_rows
    h = float(-(p * np.log2(p)).sum()) if observed.size else 0.0

    if n_rows > total:
        ub = max(ub_domain - observed.size, 1)
        avg = max((n_rows - total) / ub, 1.0)
        h += -ub * (avg / n_rows) * math.log2(avg / n_rows)
    return h


def compute_pairwise_stats(
        n_rows: int,
        freq: FreqStats,
        target_attr_pairs: Sequence[Pair],
        domain_stats: Dict[str, int]) -> Dict[str, List[Tuple[str, float]]]:
    """For each requested (x, y): H(x|y), grouped by x and sorted ascending.

    Mirrors `RepairApi.computePairwiseStats` (RepairApi.scala:284-394)
    including its worst-case behavior when no frequency stats survive.
    """
    if not target_attr_pairs:
        return {}

    assert n_rows > 0
    target_attrs = list(dict.fromkeys(a for p in target_attr_pairs for a in p))
    assert all(a in domain_stats for a in target_attrs)

    # H(x,y) per unordered pair — dispatch routed through the unified
    # launch planner: one "entropy" plan whose buckets are the pallas-vs-
    # host routes (each entry is an independent reduction, so grouping is
    # pure bookkeeping and the math is untouched)
    from delphi_tpu.parallel import planner

    uniq: List[Tuple[str, str]] = []
    seen = set()
    for x, y in target_attr_pairs:
        key = frozenset((x, y))
        if key not in seen:
            seen.add(key)
            uniq.append((x, y))
    mats = [freq.pair(x, y).ravel() for x, y in uniq]

    # Replicated-pipeline sharding (DELPHI_SHARD): every rank holds the
    # identical replicated pair matrices, so the pair LIST splits by a
    # deterministic greedy owner assignment (weighted by matrix size) and
    # each rank reduces only its own pairs; the scalar H(x,y) values merge
    # through one guarded gather. Each entropy is an independent float64
    # reduction over one matrix — per-pair results are bit-identical to
    # the single-process loop regardless of who computed them. A degraded
    # merge computes the missing pairs locally. H(y) stays replicated
    # (one vector per attribute — cheaper than a collective).
    from delphi_tpu.parallel import rowshard
    owners = None
    if len(uniq) > 1 and rowshard.shard_enabled():
        owners = rowshard.assign_owners([int(m.size) for m in mats])
    rank = rowshard.world()[0] if owners is not None else 0
    mine = [i for i in range(len(uniq))
            if owners is None or owners[i] == rank]

    plan = planner.plan_launches(
        "entropy",
        [planner.Piece(
            key=i, size=int(mats[i].size),
            shape=("pallas" if _use_pallas_entropy(mats[i].size, n_rows)
                   else "host",))
         for i in mine],
        persist=False)
    plan.record()
    h_local: Dict[int, float] = {}
    for launch in plan.launches:
        with plan.launch_scope(launch):
            for span in launch.spans:
                x, y = uniq[span.key]
                h_local[span.key] = _entropy_with_correction(
                    mats[span.key], n_rows,
                    int(domain_stats[x]) * int(domain_stats[y]))

    if owners is not None:
        parts = rowshard.merge_parts(h_local, site="shard.entropy.merge")
        if parts is not None:
            for p in parts:
                h_local.update(p)
        # degraded (or a peer's dict missing entries): compute whatever is
        # still absent locally — exact, just not parallel
        for i in range(len(uniq)):
            if i not in h_local:
                x, y = uniq[i]
                h_local[i] = _entropy_with_correction(
                    mats[i], n_rows,
                    int(domain_stats[x]) * int(domain_stats[y]))
    h_xy: Dict[frozenset, float] = {
        frozenset(uniq[i]): h for i, h in h_local.items()}

    # H(y) per attr
    h_y: Dict[str, float] = {}
    for a in target_attrs:
        h_y[a] = _entropy_with_correction(
            freq.single(a), n_rows, int(domain_stats[a]))

    result: Dict[str, List[Tuple[str, float]]] = {}
    for x, y in target_attr_pairs:
        result.setdefault(x, []).append((y, h_xy[frozenset((x, y))] - h_y[y]))
    for x in result:
        result[x] = sorted(result[x], key=lambda t: t[1])
    return result


def select_candidate_pairs(
        freq_for_pruning,
        attrs_to_repair: Sequence[str],
        all_attrs: Sequence[str],
        domain_stats: Dict[str, int],
        pairwise_freq_ratio_threshold: float,
        max_attrs_to_compute_pairwise_stats: int) -> List[Pair]:
    """Candidate-pair pruning by co-occurrence distinct-count ratio
    (RepairApi.scala:429-448): when a target has more candidates than the cap,
    keep pairs whose #distinct(x,y) / (|x|*|y|) is below the threshold, sorted
    ascending, truncated to the cap.

    Deliberate deviation: remaining slots fill with NEAR-FUNCTIONAL pairs —
    #distinct(x,y) close to max(|x|,|y|), i.e. the larger-domain attribute
    (almost) determines the other. The reference's ratio criterion is
    mathematically unable to keep any pair for a low-cardinality target
    (ratio >= 1/min(|x|,|y|): e.g. hospital's yes/no EmergencyService bottoms
    out at 1/3 > 0.05), which leaves such targets without correlates, hence
    without cell domains, hence beyond the weak-labeling demotion — their
    clean cells stay "errors" and get mis-repaired. Near-functional partners
    are exactly the evidence the naive-Bayes domain analysis needs there.
    (The reference's own perf suite works around this by raising the
    threshold to 1.0, test_model_perf.py:205.)

    ``freq_for_pruning`` must expose ``distinct_pair_count(x, y)``.
    """
    all_candidates = {x: [(x, y) for y in all_attrs if y != x]
                      for x in attrs_to_repair}
    if hasattr(freq_for_pruning, "warm"):
        freq_for_pruning.warm(
            p for cands in all_candidates.values()
            if len(cands) > max_attrs_to_compute_pairwise_stats
            for p in cands)

    out: List[Pair] = []
    for x in attrs_to_repair:
        candidates = all_candidates[x]
        if len(candidates) > max_attrs_to_compute_pairwise_stats:
            scored = []
            for (cx, cy) in candidates:
                co = freq_for_pruning.distinct_pair_count(cx, cy)
                dx, dy = int(domain_stats[cx]), int(domain_stats[cy])
                ratio = co / (dx * dy)
                near_fd = co / max(dx, dy)  # 1.0 == exactly functional
                scored.append((ratio, near_fd, (cx, cy)))
            kept = [s for s in scored if s[0] < pairwise_freq_ratio_threshold]
            kept.sort(key=lambda t: t[0])
            kept = kept[:max_attrs_to_compute_pairwise_stats]
            if len(kept) < max_attrs_to_compute_pairwise_stats:
                chosen = {s[2] for s in kept}
                # Exclude key-like partners (domain ~ row count): they score
                # a perfect near_fd of 1.0 trivially, but their pair counts
                # are singletons that never clear the tau threshold — wasted
                # slots carrying no generalizable evidence.
                n_rows = getattr(freq_for_pruning, "n_rows", None)
                extras = []
                for s in scored:
                    if s[2] in chosen or s[1] > 1.5:
                        continue
                    _, cy2 = s[2]
                    if n_rows and int(domain_stats[cy2]) >= 0.8 * n_rows:
                        continue
                    extras.append(s)
                extras.sort(key=lambda t: (t[1], int(domain_stats[t[2][1]])))
                kept.extend(
                    extras[:max_attrs_to_compute_pairwise_stats - len(kept)])
            out.extend(p for _, _, p in kept)
        else:
            out.extend(candidates)
    return out
