"""In-memory table catalog — the TPU-native replacement for Spark's temp-view
registry (reference `RepairBase.scala:80-99`, `RepairUtils.scala:37-45`).

Tables are pandas DataFrames registered under (optionally db-qualified) names.
The repair pipeline looks inputs up here, registers intermediates under random
names, and drops them in ``finally`` blocks — same lifecycle as the reference's
temp views, without a JVM.
"""

import threading
from typing import Dict, List, Optional, Union

import pandas as pd

from delphi_tpu.utils import get_random_string, setup_logger

_logger = setup_logger()


class AnalysisException(ValueError):
    """Raised for invalid inputs (reference `ExceptionUtils.scala:20-26`)."""


class DelphiSession:
    """Process-wide singleton holding the table catalog and config."""

    _instance: Optional["DelphiSession"] = None
    _lock = threading.Lock()

    def __init__(self) -> None:
        self._catalog: Dict[str, pd.DataFrame] = {}
        self.conf: Dict[str, str] = {}

    # -- typed config lookups ------------------------------------------------
    # Session config values are strings (they arrive via setConf); the
    # observability knobs (repair.metrics.port, stall timeouts, sample
    # intervals) need numbers, and a typo must degrade to "knob off" with a
    # warning rather than crash a run at telemetry setup.

    def _conf_number(self, key: str, cast, default):
        raw = self.conf.get(key)
        if raw is None or str(raw).strip() == "":
            return default
        try:
            return cast(str(raw).strip())
        except (TypeError, ValueError):
            _logger.warning(f"invalid value for {key}: {raw!r} "
                            f"(expected {cast.__name__}); ignoring")
            return default

    def conf_int(self, key: str, default: Optional[int] = None) -> Optional[int]:
        return self._conf_number(key, int, default)

    def conf_float(self, key: str,
                   default: Optional[float] = None) -> Optional[float]:
        return self._conf_number(key, float, default)

    @classmethod
    def get_or_create(cls) -> "DelphiSession":
        with cls._lock:
            if cls._instance is None:
                cls._instance = DelphiSession()
            return cls._instance

    # -- catalog ------------------------------------------------------------

    def register(self, name: str, df) -> str:
        # the catalog holds pandas frames OR pre-encoded tables (chunked
        # ingestion registers EncodedTable directly so the full object-dtype
        # frame never materializes; see delphi_tpu.ingest)
        from_pandas = isinstance(df, pd.DataFrame)
        if not from_pandas:
            from delphi_tpu.table import EncodedTable
            assert isinstance(df, EncodedTable), \
                f"expected pandas DataFrame or EncodedTable, got {type(df)}"
        self._catalog[name] = df
        return name

    def register_temp(self, df: pd.DataFrame, prefix: str) -> str:
        name = get_random_string(prefix)
        return self.register(name, df)

    def table(self, name: str) -> pd.DataFrame:
        entry = self.raw_entry(name)
        return entry if isinstance(entry, pd.DataFrame) else entry.to_pandas()

    def raw_entry(self, name: str):
        """The catalog object as stored (EncodedTable for chunk-ingested
        inputs), bypassing the pandas conversion of :meth:`table`."""
        if name not in self._catalog:
            raise AnalysisException(f"Table or view not found: {name}")
        return self._catalog[name]

    def exists(self, name: str) -> bool:
        return name in self._catalog

    def drop(self, name: str) -> None:
        self._catalog.pop(name, None)

    def table_names(self) -> List[str]:
        return sorted(self._catalog)

    def qualified_name(self, db_name: str, table_name: str) -> str:
        return f"{db_name}.{table_name}" if db_name else table_name

    def resolve(self, db_name: str, table_name: str) -> pd.DataFrame:
        return self.table(self.qualified_name(db_name, table_name))


def get_session() -> DelphiSession:
    return DelphiSession.get_or_create()


def resolve_input(input: Union[str, pd.DataFrame], session: Optional[DelphiSession] = None) \
        -> pd.DataFrame:
    session = session or get_session()
    if isinstance(input, str):
        return session.table(input)
    return input
