"""Row-sharded SPMD statistics kernels.

These are the distributed counterparts of :mod:`delphi_tpu.ops.freq` /
:mod:`delphi_tpu.ops.detect`: the code tensor is sharded over the mesh's
``dp`` axis, each device bincounts its row shard, and ``psum`` over ICI
replaces the Spark shuffle (reference P1, SURVEY.md §2.3)."""

from functools import partial
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from delphi_tpu.parallel.mesh import pad_rows_to_multiple, shard_map, shard_rows


def sharded_single_counts(codes: np.ndarray, v_pad: int, mesh: Mesh) -> np.ndarray:
    """Per-attribute value counts (slot 0 = NULL) over a row-sharded table.
    codes: int32[n, m] with NULL=-1; padding rows must be -2 (counted into a
    scratch slot that is dropped)."""
    dp = mesh.shape["dp"]
    padded, n = pad_rows_to_multiple(codes, dp, fill=-2)

    @partial(shard_map, mesh=mesh, in_specs=P("dp", None), out_specs=P())
    def kernel(local):
        def one(col):
            return jnp.bincount(col + 2, length=v_pad + 2)
        counts = jax.vmap(one, in_axes=1)(local)
        return jax.lax.psum(counts, "dp")

    counts = np.asarray(kernel(shard_rows(padded, mesh)))
    return counts[:, 1:]  # drop the padding slot


def sharded_pair_counts(codes: np.ndarray, pairs: Sequence[Tuple[int, int]],
                        v_pad: int, mesh: Mesh) -> np.ndarray:
    """Fused-key pair co-occurrence counts over a row-sharded table;
    returns int32[n_pairs, (v_pad+1)**2]."""
    dp = mesh.shape["dp"]
    padded, n = pad_rows_to_multiple(codes, dp, fill=-2)
    xi = jnp.asarray([p[0] for p in pairs], dtype=jnp.int32)
    yi = jnp.asarray([p[1] for p in pairs], dtype=jnp.int32)
    stride = v_pad + 1

    @partial(shard_map, mesh=mesh,
             in_specs=(P("dp", None), P(), P()), out_specs=P())
    def kernel(local, xi, yi):
        valid = local[:, 0] != -2

        def one(x, y):
            keys = (local[:, x] + 1) * stride + (local[:, y] + 1)
            keys = jnp.where(valid, keys, stride * stride)  # scratch slot
            return jnp.bincount(keys, length=stride * stride + 1)[:-1]

        counts = jax.vmap(one)(xi, yi)
        return jax.lax.psum(counts, "dp")

    return np.asarray(kernel(shard_rows(padded, mesh), xi, yi))


def sharded_null_counts(codes: np.ndarray, mesh: Mesh) -> np.ndarray:
    """#NULL cells per attribute over a row-sharded table (the distributed
    NULL detector's reduction)."""
    dp = mesh.shape["dp"]
    padded, _ = pad_rows_to_multiple(codes, dp, fill=0)

    @partial(shard_map, mesh=mesh, in_specs=P("dp", None), out_specs=P())
    def kernel(local):
        return jax.lax.psum((local == -1).sum(axis=0), "dp")

    return np.asarray(kernel(shard_rows(padded, mesh)))
