"""Row-sharded SPMD statistics kernels.

These are the distributed counterparts of :mod:`delphi_tpu.ops.freq` /
:mod:`delphi_tpu.ops.detect`: the code tensor is sharded over the mesh's
``dp`` axis, each device bincounts its row shard, and ``psum`` over ICI
replaces the Spark shuffle (reference P1, SURVEY.md §2.3)."""

from functools import partial
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from delphi_tpu.parallel.mesh import (
    mesh_is_multiprocess, pad_rows_to_multiple, shard_map,
    shard_map_unchecked, shard_rows)


def sharded_single_counts(codes: np.ndarray, v_pad: int, mesh: Mesh) -> np.ndarray:
    """Per-attribute value counts (slot 0 = NULL) over a row-sharded table.
    codes: int32[n, m] with NULL=-1; padding rows must be -2 (counted into a
    scratch slot that is dropped)."""
    dp = mesh.shape["dp"]
    padded, n = pad_rows_to_multiple(codes, dp, fill=-2)
    return sharded_single_counts_global(shard_rows(padded, mesh), v_pad, mesh)


def sharded_single_counts_global(global_codes, v_pad: int, mesh: Mesh) -> np.ndarray:
    """`sharded_single_counts` over a pre-assembled global device array —
    the entry point for sharded ingestion, where each process contributed
    its own rows via `shard_rows_process_local` (padding rows = -2) and no
    host ever saw the full table."""

    @partial(shard_map, mesh=mesh, in_specs=P("dp", None), out_specs=P())
    def kernel(local):
        def one(col):
            return jnp.bincount(col + 2, length=v_pad + 2)
        counts = jax.vmap(one, in_axes=1)(local)
        return jax.lax.psum(counts, "dp")

    counts = np.asarray(kernel(global_codes))
    return counts[:, 1:]  # drop the padding slot


def sharded_pair_counts(codes: np.ndarray, pairs: Sequence[Tuple[int, int]],
                        v_pad: int, mesh: Mesh) -> np.ndarray:
    """Fused-key pair co-occurrence counts over a row-sharded table;
    returns int32[n_pairs, (v_pad+1)**2]."""
    dp = mesh.shape["dp"]
    padded, n = pad_rows_to_multiple(codes, dp, fill=-2)
    return sharded_pair_counts_global(
        shard_rows(padded, mesh), pairs, v_pad, mesh)


def sharded_pair_counts_global(global_codes, pairs: Sequence[Tuple[int, int]],
                               v_pad: int, mesh: Mesh) -> np.ndarray:
    """`sharded_pair_counts` over a pre-assembled global device array — the
    entry point for sharded ingestion, where each process contributed its
    own rows via `shard_rows_process_local` (padding rows = -2) and no host
    ever saw the full table."""
    from delphi_tpu.ops.xfer import to_device
    # one packed [2, P] upload instead of two tiny ones (transfer ledger)
    xy = to_device(np.asarray([[p[0] for p in pairs],
                               [p[1] for p in pairs]], dtype=np.int32))
    xi, yi = xy[0], xy[1]
    stride = v_pad + 1

    @partial(shard_map, mesh=mesh,
             in_specs=(P("dp", None), P(), P()), out_specs=P())
    def kernel(local, xi, yi):
        valid = local[:, 0] != -2

        def one(x, y):
            keys = (local[:, x] + 1) * stride + (local[:, y] + 1)
            keys = jnp.where(valid, keys, stride * stride)  # scratch slot
            return jnp.bincount(keys, length=stride * stride + 1)[:-1]

        counts = jax.vmap(one)(xi, yi)
        return jax.lax.psum(counts, "dp")

    return np.asarray(kernel(global_codes, xi, yi))


def sharded_domain_scores(codes_chunk: Sequence[np.ndarray],
                          pair_tables: Sequence[np.ndarray],
                          taus: Sequence[int],
                          has_single: np.ndarray,
                          mesh: Mesh) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Cell-sharded naive-Bayes domain scoring (P1: the last heavy phase-1
    reduction to shard). Each device gathers its cells' pair-count rows and
    accumulates the EXACT integer split of the evidence weights — big =
    sum(cnt - 1 | cnt >= 2), tiny = #(cnt == 1) — so the caller's float64
    recombination is bit-identical to the single-host numpy path.

    codes_chunk: per-correlate codes of the chunk cells, each int32[cells];
    pair_tables: per-correlate [V_c + 1, v_a + 1] co-occurrence counts;
    returns (big, tiny, contributed), each [cells, v_a]."""
    k = len(codes_chunk)
    cells = len(codes_chunk[0])
    v_a = int(has_single.shape[0])
    dp = mesh.shape["dp"]

    codes = np.stack(codes_chunk, axis=1).astype(np.int32)  # [cells, k]
    padded, _ = pad_rows_to_multiple(codes, dp, fill=-1)    # pad rows: NULL -> inactive
    vc_max = max(int(t.shape[0]) for t in pair_tables)
    tables = np.zeros((k, vc_max, v_a + 1), dtype=np.int32)
    for i, t in enumerate(pair_tables):
        tables[i, :t.shape[0], :] = t
    taus_arr = np.asarray([max(int(t), 0) for t in taus], dtype=np.int32)
    hs = np.asarray(has_single, dtype=bool)

    # Multi-host: a row-sharded output spans processes and cannot be read
    # back by any single host, so the per-cell scores all-gather to every
    # device (same transient size the single-host path materializes anyway;
    # the chunked caller bounds `cells`). Keyed off the MESH, not the
    # cluster: after a rank-loss degrade the cluster is still
    # multi-process but the shrunk mesh is local and the single-host
    # readback path is the correct one.
    multihost = mesh_is_multiprocess(mesh)
    out_shard = P() if multihost else P("dp", None)

    smap = shard_map_unchecked if multihost else shard_map

    @partial(smap, mesh=mesh, in_specs=(P("dp", None), P(), P(), P()),
             out_specs=(out_shard, out_shard, out_shard))
    def kernel(local, tables, taus_arr, hs):
        def one(codes_c, table_c, tau):
            gathered = table_c[codes_c + 1][:, 1:]          # [cells, v_a]
            valid = (codes_c != -1)[:, None]
            active = (gathered > tau) & (gathered > 0) & valid & hs[None, :]
            big = jnp.where(active & (gathered >= 2), gathered - 1, 0)
            tiny = (active & (gathered == 1)).astype(jnp.int32)
            return big, tiny, active
        bigs, tinys, actives = jax.vmap(one, in_axes=(1, 0, 0))(
            local, tables, taus_arr)
        out = (bigs.sum(axis=0), tinys.sum(axis=0), actives.any(axis=0))
        if multihost:
            out = tuple(jax.lax.all_gather(o, "dp", axis=0, tiled=True)
                        for o in out)
        return out

    from delphi_tpu.ops.xfer import to_device
    big, tiny, contributed = kernel(
        shard_rows(padded, mesh), to_device(tables), to_device(taus_arr),
        to_device(hs))
    return (np.asarray(big)[:cells], np.asarray(tiny)[:cells],
            np.asarray(contributed)[:cells])


def sharded_null_counts(codes: np.ndarray, mesh: Mesh) -> np.ndarray:
    """#NULL cells per attribute over a row-sharded table (the distributed
    NULL detector's reduction)."""
    dp = mesh.shape["dp"]
    padded, _ = pad_rows_to_multiple(codes, dp, fill=0)

    @partial(shard_map, mesh=mesh, in_specs=P("dp", None), out_specs=P())
    def kernel(local):
        return jax.lax.psum((local == -1).sum(axis=0), "dp")

    return np.asarray(kernel(shard_rows(padded, mesh)))
