"""The durable-store seam: every on-disk artifact flows through here.

One persistence discipline for the whole cache root (plans, snapshot
manifests, phase/model checkpoints, provenance dumps, run reports, fleet
registrations), replacing the per-module hand-rolled writers that PR 12's
shared fleet root made dangerous — planner plans skipped fsync, fleet
registrations could be read half-written, and only phase checkpoints
detected truncation.

Envelope format (one header line + raw payload bytes)::

    #DELPHI-STORE v1 <schema> <length> <crc32hex>\\n
    <payload bytes>

The header carries a schema tag (what kind of artifact this claims to be),
the payload byte length (detects truncation — the torn-write failure mode),
and a crc32 of the payload (detects bit rot / partial overwrite). JSON
payloads stay human-readable below the header; JSONL payloads stay
line-parseable by skipping ``#``-prefixed lines.

Write protocol: same-directory temp file -> fsync -> ``os.replace`` ->
directory fsync. The directory fsync is the step every pre-seam writer
skipped: without it a crash after the rename can surface an empty or
garbage file to the next reader even though the rename "happened".

Read protocol: a validated read returns ``(payload, status)`` with status
one of ``ok`` / ``missing`` / ``legacy`` / ``corrupt``. Corruption is a
cache miss, never a crash and never a silent load: the corrupt file is
moved to ``<root>/quarantine/``, ``store.corrupt`` / ``store.quarantined``
fire, and the fault is classified ``store_corrupt`` in the resilience
taxonomy. Pre-seam files (no magic header) load through the ``legacy``
path when the caller's deserializer accepts them, so an old cache root
warms a new build.

Chaos: every write passes the resilience injection point at its registered
``store.*`` site, so ``DELPHI_FAULT_PLAN`` entries ``store.plan:1:crash``
(process exit mid-write, tmp written, rename not yet landed) and
``store.plan:1:torn_write`` (destination truncated at a deterministic
offset, writer believes it succeeded) rehearse exactly the kill -9
failure modes the envelope exists to catch.

Quota GC: ``DELPHI_STORE_QUOTA_GB`` arms a lock-file-guarded LRU sweep
(validated reads bump atime, so "recently used" is meaningful) that is
safe against concurrent fleet workers sharing one root; snapshot manifest
chains are compacted to one base first so delta serving stays O(1) on
disk. ``main.py --fsck <root>`` runs the same validation standalone.
"""

import json
import logging
import os
import pickle
import tempfile
import threading
import time
import zlib
from typing import Any, Dict, Iterable, List, Optional, Tuple

from delphi_tpu.observability import counter_inc, gauge_set

_logger = logging.getLogger(__name__)

MAGIC = b"#DELPHI-STORE"
ENVELOPE_VERSION = 1

QUARANTINE_DIR = "quarantine"
_GC_LOCK_FILE = ".store_gc.lock"
_TMP_PREFIX = ".store_"

#: Every durable-store site, with the artifact it covers. Registered in
#: resilience.KNOWN_SITES (test_transfer_guard.py asserts the two stay in
#: sync) so DELPHI_FAULT_PLAN validation covers store sites, and iterated
#: by ``bench.py --store-chaos`` — a new store consumer that forgets to
#: register here escapes the torn-write matrix and fails the guard.
STORE_SITES: Dict[str, str] = {
    "store.plan": "launch-plan documents (parallel/planner.py PlanStore)",
    "store.checkpoint": "phase checkpoints + stall/rank-loss markers",
    "store.model": "trained-model checkpoints (model.checkpoint_path)",
    "store.manifest": "snapshot manifest.json (incremental/manifest.py)",
    "store.snapshot_state": "snapshot state.pkl (incremental/manifest.py)",
    "store.provenance": "provenance ledger JSONL dumps",
    "store.report": "run-report JSON files",
    "store.fleet": "fleet worker registration files",
    "store.stream_cursor": "per-stream durable cursors "
                           "(incremental/stream.py, one file per commit)",
    "store.stream_state": "per-stream accumulated tables "
                          "(incremental/stream.py, one file per commit)",
    "store.trace": "distributed-trace part files "
                   "(observability/trace.py, one file per process)",
}

#: Schema tags paired with the sites above — fsck uses the tag embedded in
#: each envelope header to report per-store health without knowing paths.
SCHEMA_SITES: Dict[str, str] = {
    "launch_plan": "store.plan",
    "phase_ckpt": "store.checkpoint",
    "marker": "store.checkpoint",
    "model_ckpt": "store.model",
    "snapshot_manifest": "store.manifest",
    "snapshot_state": "store.snapshot_state",
    "provenance": "store.provenance",
    "run_report": "store.report",
    "fleet_reg": "store.fleet",
    "stream_cursor": "store.stream_cursor",
    "stream_state": "store.stream_state",
    "trace": "store.trace",
    "launch_ledger": "store.plan",
}

# roots this process has touched, so health endpoints can report
# process-wide quarantine occupancy without threading paths around
_seen_roots: set = set()
_seen_lock = threading.Lock()

# per-root monotonic stamp of the last background GC sweep (maybe_gc
# rate-limiting); guarded by _seen_lock
_last_gc: Dict[str, float] = {}


# -- envelope ---------------------------------------------------------------

def encode_envelope(payload: bytes, schema: str) -> bytes:
    """Frames payload bytes: magic, version, schema tag, length, crc32."""
    if not isinstance(payload, bytes):
        raise TypeError(f"payload must be bytes, got {type(payload)}")
    header = (f"{MAGIC.decode()} v{ENVELOPE_VERSION} {schema} "
              f"{len(payload)} {zlib.crc32(payload) & 0xFFFFFFFF:08x}\n")
    return header.encode("ascii") + payload


def decode_envelope(blob: bytes,
                    schema: Optional[str] = None) -> Tuple[bytes, str]:
    """Validates a framed blob and returns ``(payload, schema_tag)``.

    Raises :class:`~delphi_tpu.parallel.resilience.StoreCorrupt` on any
    defect: missing/garbled header, unknown version, schema mismatch,
    length mismatch (truncation), or crc mismatch. A blob without the
    magic prefix raises ``ValueError`` instead — that is the legacy path,
    not corruption."""
    from delphi_tpu.parallel.resilience import StoreCorrupt

    if not blob.startswith(MAGIC):
        raise ValueError("not a delphi-store envelope")
    nl = blob.find(b"\n")
    if nl < 0:
        raise StoreCorrupt("envelope header truncated (no newline)")
    try:
        fields = blob[:nl].decode("ascii").split()
    except UnicodeDecodeError as e:
        raise StoreCorrupt(f"envelope header undecodable: {e}")
    if len(fields) != 5:
        raise StoreCorrupt(
            f"envelope header malformed ({len(fields)} fields, want 5)")
    _, version, tag, length, crc_hex = fields
    if version != f"v{ENVELOPE_VERSION}":
        raise StoreCorrupt(f"unknown envelope version {version!r}")
    if schema is not None and tag != schema:
        raise StoreCorrupt(
            f"schema mismatch: file says {tag!r}, expected {schema!r}")
    try:
        want_len = int(length)
        want_crc = int(crc_hex, 16)
    except ValueError:
        raise StoreCorrupt("envelope length/crc fields unparsable")
    payload = blob[nl + 1:]
    if len(payload) != want_len:
        raise StoreCorrupt(
            f"payload truncated: {len(payload)} bytes, header "
            f"promised {want_len}")
    if (zlib.crc32(payload) & 0xFFFFFFFF) != want_crc:
        raise StoreCorrupt("payload crc32 mismatch")
    return payload, tag


# -- roots / quarantine -----------------------------------------------------

def _root_for(path: str, root: Optional[str]) -> str:
    r = os.path.abspath(root) if root else os.path.dirname(
        os.path.abspath(path))
    with _seen_lock:
        _seen_roots.add(r)
    return r


def quarantine_dir(root: str) -> str:
    return os.path.join(os.path.abspath(root), QUARANTINE_DIR)


def quarantine_count(root: Optional[str] = None) -> int:
    """Files currently sitting in quarantine under ``root`` — or, with no
    root, under every root this process has touched (the health-endpoint
    degrade signal)."""
    if root is not None:
        roots = [os.path.abspath(root)]
    else:
        with _seen_lock:
            roots = sorted(_seen_roots)
    n = 0
    for r in roots:
        try:
            n += sum(1 for e in os.scandir(quarantine_dir(r))
                     if e.is_file())
        except OSError:
            pass
    return n


def quarantine(path: str, root: str, reason: str, site: str) -> Optional[str]:
    """Moves a corrupt artifact into ``<root>/quarantine/`` (same-volume
    rename; falls back to unlink if even that fails) so it is never loaded
    again but stays inspectable. Returns the quarantined path."""
    qdir = quarantine_dir(root)
    base = os.path.basename(path)
    try:
        os.makedirs(qdir, exist_ok=True)
        dest = os.path.join(qdir, base)
        i = 1
        while os.path.exists(dest):
            dest = os.path.join(qdir, f"{base}.{i}")
            i += 1
        os.replace(path, dest)
    except OSError:
        try:
            os.unlink(path)
        except OSError:
            return None
        dest = None
    counter_inc("store.quarantined")
    _logger.warning(f"{site}: quarantined corrupt artifact {path} "
                    f"({reason})" + (f" -> {dest}" if dest else " (removed)"))
    return dest


def _note_corrupt(path: str, site: str, root: str, exc: BaseException) -> None:
    from delphi_tpu.parallel import resilience as rz
    counter_inc("store.corrupt")
    rz.note_fault(exc, site)
    quarantine(path, root, str(exc), site)


# -- atomic writes ----------------------------------------------------------

def _fsync_dir(directory: str) -> None:
    try:
        fd = os.open(directory, os.O_RDONLY)
    except OSError:
        return  # platform without directory fds; rename alone must do
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _torn_offset(site: str, length: int) -> int:
    # deterministic tear point so a chaos replay tears identically
    return zlib.crc32(site.encode()) % max(1, length)


def _inject_mid_write(site: str, blob: bytes, path: str) -> bool:
    """The store seam's chaos point, entered after the tmp file is fully
    written and fsynced, before the rename. ``crash`` plan entries exit
    the process here (handled inside resilience._fire_injection);
    ``torn_write`` entries are caught HERE: the destination gets a
    truncated copy of the envelope and the writer proceeds as if the
    write succeeded — the tear only surfaces at the next validated read.
    Any other injected kind propagates to the caller's error handling.
    Returns True when the write was torn (caller must skip the rename)."""
    from delphi_tpu.parallel import resilience as rz
    try:
        rz._maybe_inject(site)
    except rz.FaultInjected as e:
        if getattr(e, "kind", None) != "torn_write":
            raise
        cut = _torn_offset(site, len(blob))
        with open(path, "wb") as f:
            f.write(blob[:cut])
            f.flush()
            os.fsync(f.fileno())
        counter_inc("store.torn_writes")
        _logger.warning(f"{site}: injected torn write — {path} truncated "
                        f"at byte {cut} of {len(blob)}")
        return True
    return False


def write_bytes(path: str, payload: bytes, *, schema: str, site: str,
                root: Optional[str] = None) -> None:
    """Writes one envelope-framed artifact crash-consistently. Raises
    ``OSError`` upward — callers that treat persistence as best-effort
    keep their own try/except, exactly as before the seam."""
    r = _root_for(path, root)
    blob = encode_envelope(payload, schema)
    directory = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(directory, exist_ok=True)
    fd, tmp = tempfile.mkstemp(prefix=_TMP_PREFIX, dir=directory)
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        torn = _inject_mid_write(site, blob, path)
        if torn:
            os.unlink(tmp)
        else:
            os.replace(tmp, path)
            _fsync_dir(directory)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    counter_inc("store.writes")
    maybe_gc(r)


def read_bytes(path: str, *, schema: str, site: str,
               root: Optional[str] = None) -> Tuple[Optional[bytes], str]:
    """Validated read: ``(payload, "ok")``, ``(None, "missing")``,
    ``(raw_blob, "legacy")`` for pre-seam files (caller decides whether
    its deserializer accepts them), or ``(None, "corrupt")`` after the
    file has been quarantined and counted."""
    r = _root_for(path, root)
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except FileNotFoundError:
        counter_inc("store.misses")
        return None, "missing"
    except OSError as e:
        _logger.warning(f"{site}: unreadable {path}: {e}")
        counter_inc("store.misses")
        return None, "missing"
    try:
        payload, _ = decode_envelope(blob, schema)
    except ValueError:
        counter_inc("store.legacy")
        return blob, "legacy"
    except BaseException as e:
        from delphi_tpu.parallel.resilience import StoreCorrupt
        if not isinstance(e, StoreCorrupt):
            raise
        _note_corrupt(path, site, r, e)
        return None, "corrupt"
    counter_inc("store.reads")
    try:
        # LRU recency stamp for the quota sweep. Only atime moves: mtime
        # must keep meaning "content last written", so checkpoint-reuse
        # checks (and humans running `ls -l`) can tell a reused artifact
        # from a rewritten one.
        st = os.stat(path)
        os.utime(path, ns=(time.time_ns(), st.st_mtime_ns))
    except OSError:
        pass
    return payload, "ok"


def mark_corrupt(path: str, site: str, reason: str,
                 root: Optional[str] = None) -> None:
    """Quarantines a file whose ENVELOPE validated but whose payload the
    consumer could not deserialize (writer bug / legacy garbage): same
    counters and taxonomy as an envelope failure."""
    from delphi_tpu.parallel.resilience import StoreCorrupt
    _note_corrupt(path, site, _root_for(path, root), StoreCorrupt(reason))


def write_json(path: str, obj: Any, *, schema: str, site: str,
               root: Optional[str] = None, indent: Optional[int] = None,
               sort_keys: bool = True) -> None:
    # no default= fallback: a non-serializable payload must raise BEFORE
    # any file operation so an existing artifact survives intact
    body = json.dumps(obj, sort_keys=sort_keys, indent=indent) + "\n"
    write_bytes(path, body.encode("utf-8"), schema=schema, site=site,
                root=root)


def read_json(path: str, *, schema: str, site: str,
              root: Optional[str] = None) -> Tuple[Optional[Any], str]:
    payload, status = read_bytes(path, schema=schema, site=site, root=root)
    if payload is None:
        return None, status
    try:
        return json.loads(payload.decode("utf-8")), status
    except (ValueError, UnicodeDecodeError) as e:
        mark_corrupt(path, site, f"json payload unparsable: {e}", root)
        return None, "corrupt"


def write_pickle(path: str, obj: Any, *, schema: str, site: str,
                 root: Optional[str] = None) -> None:
    write_bytes(path, pickle.dumps(obj), schema=schema, site=site, root=root)


def read_pickle(path: str, *, schema: str, site: str,
                root: Optional[str] = None) -> Tuple[Optional[Any], str]:
    """Same trust boundary as the model/phase checkpoints: pickles execute
    code on load — point stores only at directories this process wrote."""
    payload, status = read_bytes(path, schema=schema, site=site, root=root)
    if payload is None:
        return None, status
    try:
        return pickle.loads(payload), status
    except Exception as e:
        mark_corrupt(path, site, f"pickle payload unparsable: {e}", root)
        return None, "corrupt"


def write_jsonl(path: str, rows: Iterable[Any], *, schema: str, site: str,
                root: Optional[str] = None) -> None:
    body = "".join(json.dumps(r, default=str) + "\n" for r in rows)
    write_bytes(path, body.encode("utf-8"), schema=schema, site=site,
                root=root)


def read_jsonl(path: str, *, schema: str, site: str,
               root: Optional[str] = None) -> Tuple[Optional[List[Any]], str]:
    payload, status = read_bytes(path, schema=schema, site=site, root=root)
    if payload is None:
        return None, status
    try:
        lines = payload.decode("utf-8").splitlines()
        return [json.loads(ln) for ln in lines
                if ln.strip() and not ln.startswith("#")], status
    except (ValueError, UnicodeDecodeError) as e:
        mark_corrupt(path, site, f"jsonl payload unparsable: {e}", root)
        return None, "corrupt"


def replace_file(src: str, dst: str) -> None:
    """Durable same-volume rename (``os.replace`` + directory fsync) for
    artifact moves that stay inside the store discipline — e.g. archiving
    a snapshot manifest into its chain."""
    os.replace(src, dst)
    _fsync_dir(os.path.dirname(os.path.abspath(dst)) or ".")


# -- quota GC ---------------------------------------------------------------

def quota_bytes() -> Optional[int]:
    """``DELPHI_STORE_QUOTA_GB`` as bytes, or None when unset/unparsable
    (GC disarmed — today's unbounded behavior)."""
    raw = os.environ.get("DELPHI_STORE_QUOTA_GB")
    if raw is None or not raw.strip():
        return None
    try:
        gb = float(raw.strip())
    except ValueError:
        _logger.warning(f"DELPHI_STORE_QUOTA_GB: unparsable {raw!r}")
        return None
    return max(0, int(gb * (1 << 30)))


def _gc_interval_s() -> float:
    raw = os.environ.get("DELPHI_STORE_GC_INTERVAL_S")
    try:
        return max(0.0, float(raw)) if raw and raw.strip() else 60.0
    except ValueError:
        return 60.0


def _gc_lock_stale_s() -> float:
    raw = os.environ.get("DELPHI_STORE_GC_LOCK_STALE_S")
    try:
        return max(1.0, float(raw)) if raw and raw.strip() else 600.0
    except ValueError:
        return 600.0


def _acquire_gc_lock(root: str, now: Optional[float] = None) -> Optional[str]:
    """O_CREAT|O_EXCL lock file: the cross-process mutual exclusion that
    keeps N fleet workers from sweeping one root concurrently. A lock
    older than DELPHI_STORE_GC_LOCK_STALE_S (default 600 s) is presumed
    abandoned by a killed sweeper and broken."""
    lock = os.path.join(root, _GC_LOCK_FILE)
    for attempt in (0, 1):
        try:
            fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            with os.fdopen(fd, "w") as f:
                f.write(f"{os.getpid()} {time.time()}\n")
            return lock
        except FileExistsError:
            try:
                age = (now if now is not None else time.time()) \
                    - os.path.getmtime(lock)
            except OSError:
                continue  # holder finished between open and stat; retry
            if attempt == 0 and age > _gc_lock_stale_s():
                _logger.warning(f"breaking stale GC lock {lock} "
                                f"({age:.0f}s old)")
                try:
                    os.unlink(lock)
                except OSError:
                    pass
                continue
            counter_inc("store.gc.lock_busy")
            return None
        except OSError:
            return None
    return None


def _is_tmp_debris(name: str) -> bool:
    return name.startswith((_TMP_PREFIX, ".snap_", ".run_report_",
                            ".provenance_", ".phase_")) \
        or name.endswith(".tmp")


def gc_sweep(root: str, quota: Optional[int] = None,
             protect: Iterable[str] = (),
             now: Optional[float] = None) -> Dict[str, Any]:
    """One quota sweep of a cache root. Under the lock: removes orphaned
    temp files (crash debris), compacts snapshot manifest chains to one
    base, then evicts least-recently-used files (validated reads bump
    atime; writes set mtime) until the root fits ``quota`` (default:
    the env quota). Paths
    under a ``protect`` prefix — the active fingerprint's warm state —
    are never evicted. Returns a summary dict; ``{"skipped": ...}`` when
    another process holds the lock or no quota applies."""
    root = os.path.abspath(root)
    quota = quota_bytes() if quota is None else quota
    if quota is None:
        return {"skipped": "no quota"}
    lock = _acquire_gc_lock(root, now=now)
    if lock is None:
        return {"skipped": "locked"}
    try:
        counter_inc("store.gc.sweeps")
        tick = now if now is not None else time.time()
        protect_abs = tuple(os.path.abspath(p) for p in protect)
        removed_tmp = 0
        compacted = 0
        entries: List[Tuple[float, int, str]] = []  # (mtime, size, path)
        from delphi_tpu.incremental import manifest as mf
        for dirpath, dirnames, filenames in os.walk(root):
            # quarantined evidence is exempt from the quota: operators
            # clear it by hand once inspected. Nested roots (per-artifact
            # directories under a shared cache root) keep their own
            # quarantine dirs, so prune by name, not just at the top.
            dirnames[:] = [d for d in dirnames if d != QUARANTINE_DIR]
            if mf.MANIFEST_FILE in filenames:
                compacted += mf.compact_chain(dirpath, keep=0)
                filenames = [n for n in os.listdir(dirpath)
                             if os.path.isfile(os.path.join(dirpath, n))]
            for name in filenames:
                path = os.path.join(dirpath, name)
                if name == _GC_LOCK_FILE:
                    continue
                try:
                    st = os.stat(path)
                except OSError:
                    continue
                if _is_tmp_debris(name):
                    # only debris OLD enough that no live writer owns it
                    if tick - st.st_mtime > 60.0:
                        try:
                            os.unlink(path)
                            removed_tmp += 1
                        except OSError:
                            pass
                    continue
                # recency = later of write (mtime) and validated read
                # (atime, stamped by read_bytes)
                entries.append((max(st.st_atime, st.st_mtime),
                                int(st.st_size), path))
        total = sum(size for _, size, _ in entries)
        evicted_files = 0
        evicted_bytes = 0
        entries.sort()  # oldest mtime first: LRU order
        for mtime, size, path in entries:
            if total <= quota:
                break
            if any(os.path.abspath(path).startswith(p)
                   for p in protect_abs):
                continue
            try:
                os.unlink(path)
            except OSError:
                continue
            total -= size
            evicted_files += 1
            evicted_bytes += size
        counter_inc("store.gc.evicted_files", evicted_files)
        gauge_set("store.root_bytes", float(total))
        if evicted_files or removed_tmp or compacted:
            _logger.info(
                f"store GC swept {root}: evicted {evicted_files} files "
                f"({evicted_bytes} bytes), {removed_tmp} tmp orphans, "
                f"{compacted} chain manifests; {total} bytes remain "
                f"(quota {quota})")
        return {"root": root, "quota": quota, "total_bytes": total,
                "evicted_files": evicted_files,
                "evicted_bytes": evicted_bytes,
                "tmp_removed": removed_tmp, "chain_compacted": compacted}
    finally:
        try:
            os.unlink(lock)
        except OSError:
            pass


def maybe_gc(root: str) -> None:
    """Opportunistic sweep after a write: fires at most once per
    DELPHI_STORE_GC_INTERVAL_S (default 60 s) per root, only when a quota
    is armed. Never raises — GC must not fail the write that triggered
    it."""
    if quota_bytes() is None:
        return
    root = os.path.abspath(root)
    tick = time.monotonic()
    with _seen_lock:
        last = _last_gc.get(root)
        if last is not None and tick - last < _gc_interval_s():
            return
        _last_gc[root] = tick
    try:
        gc_sweep(root)
    except Exception as e:  # pragma: no cover - defensive
        _logger.warning(f"store GC sweep of {root} failed: {e}")


def reset_gc_state() -> None:
    """Forgets per-root sweep stamps and seen roots (tests / benches)."""
    with _seen_lock:
        _last_gc.clear()
        _seen_roots.clear()


# -- fsck -------------------------------------------------------------------

def fsck(root: str, repair: bool = True) -> Dict[str, Any]:
    """Scans a cache root: validates every envelope, reports per-store
    health keyed by the schema tags found, and (with ``repair``)
    quarantines corrupt entries and removes orphaned temp files. Legacy
    (pre-seam) files are reported but left alone — their consumers still
    read them through the legacy path."""
    from delphi_tpu.parallel.resilience import StoreCorrupt

    root = os.path.abspath(root)
    _root_for(os.path.join(root, "x"), root)
    per_store: Dict[str, Dict[str, int]] = {}
    summary = {"root": root, "scanned": 0, "ok": 0, "legacy": 0,
               "corrupt": 0, "quarantined": 0, "tmp_removed": 0}

    def bucket(tag: str) -> Dict[str, int]:
        return per_store.setdefault(
            tag, {"ok": 0, "legacy": 0, "corrupt": 0})

    for dirpath, dirnames, filenames in os.walk(root):
        # prune quarantine dirs by name so nested per-artifact roots under
        # a shared cache root don't get their evidence re-flagged as corrupt
        dirnames[:] = [d for d in dirnames if d != QUARANTINE_DIR]
        for name in sorted(filenames):
            path = os.path.join(dirpath, name)
            if name == _GC_LOCK_FILE:
                continue
            if _is_tmp_debris(name):
                if repair:
                    try:
                        os.unlink(path)
                        summary["tmp_removed"] += 1
                    except OSError:
                        pass
                continue
            summary["scanned"] += 1
            try:
                with open(path, "rb") as f:
                    blob = f.read()
            except OSError:
                continue
            if not blob.startswith(MAGIC):
                summary["legacy"] += 1
                bucket("(legacy)")["legacy"] += 1
                continue
            try:
                _, tag = decode_envelope(blob)
            except StoreCorrupt as e:
                summary["corrupt"] += 1
                site = "store.fsck"
                nl = blob.find(b"\n")
                head = blob[:nl if 0 <= nl < 200 else 200]
                fields = head.decode("ascii", "replace").split()
                tag = fields[2] if len(fields) >= 3 else "(unreadable)"
                bucket(tag)["corrupt"] += 1
                if repair:
                    counter_inc("store.corrupt")
                    from delphi_tpu.parallel import resilience as rz
                    rz.note_fault(
                        StoreCorrupt(f"fsck: {path}: {e}"),
                        SCHEMA_SITES.get(tag, site))
                    if quarantine(path, root, str(e),
                                  SCHEMA_SITES.get(tag, site)):
                        summary["quarantined"] += 1
                continue
            summary["ok"] += 1
            bucket(tag)["ok"] += 1
    summary["per_store"] = per_store
    summary["quarantine_files"] = quarantine_count(root)
    gc = gc_sweep(root) if repair else {"skipped": "report-only"}
    summary["gc"] = gc
    return summary
