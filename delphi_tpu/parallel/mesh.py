"""Mesh construction and row sharding helpers."""

import os
import time
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.8 promotes shard_map out of experimental
    from jax import shard_map
    _SHARD_MAP_CHECK_KW = "check_vma"
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map  # type: ignore # noqa: F401
    _SHARD_MAP_CHECK_KW = "check_rep"


def shard_map_unchecked(f, *, mesh, in_specs, out_specs):
    """shard_map with replication checking disabled, spelled portably: the
    flag is ``check_vma`` on jax >= 0.8 and ``check_rep`` on the
    experimental fallback. Needed when an output is made replicated by an
    explicit ``all_gather(tiled=True)`` the checker cannot see through."""
    return shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                     **{_SHARD_MAP_CHECK_KW: False})


_active_mesh_cache: dict = {}


def mesh_is_multiprocess(mesh: Optional[Mesh]) -> bool:
    """True when ``mesh`` spans devices owned by other processes — the
    question every sharding helper actually asks (``jax.process_count()``
    answers a different one: after a rank-loss degrade the CLUSTER is
    still multi-process while the active mesh has shrunk to local
    devices, and cross-process placement paths must not be taken)."""
    if mesh is None:
        return False
    try:
        me = jax.process_index()
        return any(d.process_index != me for d in mesh.devices.flat)
    except Exception:  # pragma: no cover - backend specific
        return jax.process_count() > 1


def mesh_process_count(mesh: Optional[Mesh]) -> int:
    """Number of distinct processes contributing devices to ``mesh``."""
    if mesh is None:
        return 1
    try:
        return len({d.process_index for d in mesh.devices.flat})
    except Exception:  # pragma: no cover - backend specific
        return int(jax.process_count())


def _maybe_shrunk(mesh: Optional[Mesh]) -> Optional[Mesh]:
    """Elastic re-shard after a rank loss: once the distributed
    resilience plane latches single-host execution, every phase re-enters
    on a mesh over THIS process's devices only — same axis layout,
    cluster peers excluded — so the surviving rank keeps computing
    instead of wedging in psums that can never complete."""
    if mesh is None:
        return None
    from delphi_tpu.parallel import dist_resilience
    if not dist_resilience.single_host_latched() \
            or not mesh_is_multiprocess(mesh):
        return mesh
    key = "__shrunk__"
    if key not in _active_mesh_cache:
        me = jax.process_index()
        local = [d for d in mesh.devices.flat if d.process_index == me]
        axis = mesh.axis_names[0] if mesh.axis_names else "dp"
        _active_mesh_cache[key] = (
            Mesh(np.asarray(local), (axis,)) if local else None)
        dist_resilience.note_mesh_shrunk()
    return _active_mesh_cache[key]

# After this many consecutive failed backend probes, stop re-probing on
# every stats op and only retry after a cool-down — a recovered backend
# (e.g. a TPU tunnel coming back) is still picked up at the next window.
_PROBE_FAILURE_LIMIT = 3
_PROBE_RETRY_AFTER_S = 60.0

_local_compute_depth = 0


class local_compute:
    """Context manager that forces `get_active_mesh` to answer None: inside
    it, every generic kernel (training, inference, domain scoring) runs
    single-device on THIS process's data. The process-local repair pipeline
    (sharded ingestion, `EncodedTable.process_local`) uses it because its
    parallelism is one process per row shard — the global reductions that
    DO need the cross-process mesh (freq stats) build theirs explicitly via
    `make_mesh` instead."""

    def __enter__(self) -> "local_compute":
        global _local_compute_depth
        _local_compute_depth += 1
        return self

    def __exit__(self, *exc) -> None:
        global _local_compute_depth
        _local_compute_depth -= 1


def get_active_mesh() -> Optional[Mesh]:
    """The mesh the PIPELINE's stats kernels run on, or None for the
    single-device path. DEFAULT-ON on the target hardware: with no setting,
    a TPU backend exposing more than one device (or any multi-process
    cluster) gets a mesh over all devices — the TPU-native path is the
    default path on TPU. Override with ``DELPHI_MESH=auto`` (all local
    devices when more than one), ``DELPHI_MESH=<n>`` (first n devices), or
    ``DELPHI_MESH=off``; the session config key ``repair.mesh`` accepts the
    same values. This is the switch that turns the engine's reductions into
    psum'd SPMD programs (SURVEY.md §2.3 P1) without touching user code."""
    if _local_compute_depth:
        return None
    setting = os.environ.get("DELPHI_MESH", "")
    if not setting:
        from delphi_tpu.session import get_session
        setting = get_session().conf.get("repair.mesh", "")
    setting = setting.strip().lower()
    if setting == "":
        if "__default__" not in _active_mesh_cache:
            retry_at = _active_mesh_cache.get("__probe_retry_at__")
            if retry_at is not None and time.monotonic() < retry_at:
                # backed off after repeated probe failures: answer
                # single-device without touching the backend until the
                # cool-down elapses
                return None
            mesh, cacheable = _default_mesh()
            if not cacheable:
                # transient backend-init failure: answer single-device for
                # THIS call and retry next time — after a few consecutive
                # failures, only retry every _PROBE_RETRY_AFTER_S so a
                # persistently broken backend doesn't pay a re-init attempt
                # on every stats op, while a recovered one is still found
                fails = _active_mesh_cache.get("__probe_failures__", 0) + 1
                _active_mesh_cache["__probe_failures__"] = fails
                if fails >= _PROBE_FAILURE_LIMIT:
                    _active_mesh_cache["__probe_retry_at__"] = \
                        time.monotonic() + _PROBE_RETRY_AFTER_S
                    _active_mesh_cache["__probe_failures__"] = 0
                return None
            _active_mesh_cache.pop("__probe_failures__", None)
            _active_mesh_cache.pop("__probe_retry_at__", None)
            _active_mesh_cache["__default__"] = mesh
        return _maybe_shrunk(_active_mesh_cache["__default__"])
    if setting in ("0", "off", "none"):
        return None
    if setting != "auto" and not setting.isdigit():
        raise ValueError(
            f"DELPHI_MESH / repair.mesh must be 'auto', a device count, or "
            f"'0'/'off' to disable, but '{setting}' found")
    key = setting
    if key not in _active_mesh_cache:
        # multi-host: join the cluster before the first backend touch so
        # jax.devices() spans every host (no-op without DELPHI_COORDINATOR)
        from delphi_tpu.parallel.distributed import maybe_initialize_distributed
        maybe_initialize_distributed()
        n_devices = None if setting == "auto" else int(setting)
        available = len(jax.devices())
        if n_devices is None and available <= 1:
            _active_mesh_cache[key] = None
        else:
            _active_mesh_cache[key] = make_mesh(
                min(n_devices, available) if n_devices else None)
    return _maybe_shrunk(_active_mesh_cache[key])


def _default_mesh() -> Tuple[Optional[Mesh], bool]:
    """The no-configuration default: a dp mesh over all devices when the
    backend is TPU with >1 device, or when running multi-process (where the
    mesh is the only way the cluster's devices cooperate). CPU/GPU
    single-process defaults stay single-device — virtual CPU meshes are a
    TESTING construct, opted into via DELPHI_MESH. Returns (mesh, cacheable):
    a failed backend probe is NOT cacheable — the caller must retry it."""
    from delphi_tpu.parallel.distributed import maybe_initialize_distributed
    from delphi_tpu.parallel import resilience
    maybe_initialize_distributed()
    try:
        # hard-deadline probe: a wedged TPU runtime raises BackendInitTimeout
        # (DELPHI_INIT_DEADLINE_S) instead of hanging the run forever
        devices = resilience.probe_backend()
        n = len(devices)
        backend = jax.default_backend()
    except Exception as e:  # backend init failure -> single-device, uncached
        resilience.note_fault(e, "backend.init")
        return None, False
    if n > 1 and (backend == "tpu" or jax.process_count() > 1):
        return make_mesh(), True
    return None, True


def make_mesh(n_devices: Optional[int] = None,
              axis_names: Tuple[str, ...] = ("dp",),
              shape: Optional[Sequence[int]] = None) -> Mesh:
    """Builds a mesh over the first ``n_devices`` devices.

    With one axis the mesh is pure data-parallel over rows; pass
    ``axis_names=('dp', 'tp')`` and a ``shape`` to add model parallelism.
    """
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    n = len(devices)
    if shape is None:
        if len(axis_names) == 1:
            shape = (n,)
        elif len(axis_names) == 2:
            tp = 2 if n % 2 == 0 and n >= 2 else 1
            shape = (n // tp, tp)
        else:
            raise ValueError(f"provide `shape` for {len(axis_names)} axes")
    assert int(np.prod(shape)) == n, f"mesh shape {shape} != {n} devices"
    return Mesh(np.asarray(devices).reshape(shape), axis_names)


def pad_rows_to_multiple(array: np.ndarray, multiple: int,
                         fill) -> Tuple[np.ndarray, int]:
    """Pads axis 0 to a multiple of the dp size (shards must be equal)."""
    n = array.shape[0]
    target = ((n + multiple - 1) // multiple) * multiple
    if target == n:
        return array, n
    pad = np.full((target - n,) + array.shape[1:], fill, dtype=array.dtype)
    return np.concatenate([array, pad], axis=0), n


def shard_rows(array: np.ndarray, mesh: Mesh, axis: str = "dp"):
    """Places an array on the mesh sharded along axis 0.

    Multi-host: callers pass the GLOBAL array (every process computes the
    same host-side table today); the callback hands each ADDRESSABLE device
    exactly its shard's global index, so each process contributes only the
    rows its own mesh devices own — correct even when the mesh spans a
    subset of processes (e.g. DELPHI_MESH=<n> smaller than the cluster,
    where an even process_count split would have non-member processes
    contributing rows to shards they don't hold)."""
    spec = P(axis, *([None] * (array.ndim - 1)))
    sharding = NamedSharding(mesh, spec)
    # transfer ledger (ops/xfer.py): sharded placement is still a
    # host->device upload and must show up in the same accounting
    from delphi_tpu.ops.xfer import record_transfer
    record_transfer(array.nbytes)
    if mesh_is_multiprocess(mesh):
        return jax.make_array_from_callback(
            array.shape, sharding,
            lambda idx: np.ascontiguousarray(array[idx]))
    return jax.device_put(array, sharding)


def shard_rows_process_local(local_rows: np.ndarray, mesh: Mesh,
                             axis: str = "dp", fill=-2):
    """Assembles the GLOBAL row-sharded device array from per-process local
    row blocks (the sharded-ingestion path: no process ever holds the full
    table). Every process pads its block to the common per-process length
    (all-gathered max, rounded to its local device count) and contributes it
    via `jax.make_array_from_process_local_data`; global row order is
    process-major. Padding rows carry `fill` (-2 = the stats kernels'
    scratch slot)."""
    import jax

    n_local = local_rows.shape[0]
    ld = max(1, int(mesh.local_mesh.shape[axis]))
    if mesh_is_multiprocess(mesh):
        # bounded collective (dist.allgather_max): a dead peer degrades
        # this to the local count instead of hanging the ingestion
        from delphi_tpu.parallel.distributed import allgather_max
        per = int(allgather_max(
            np.asarray([n_local], dtype=np.int64))[0])
    else:
        per = n_local
    per = ((max(per, 1) + ld - 1) // ld) * ld
    pad = np.full((per - n_local,) + local_rows.shape[1:], fill,
                  dtype=local_rows.dtype)
    padded = np.concatenate([local_rows, pad], axis=0)
    spec = P(axis, *([None] * (local_rows.ndim - 1)))
    sharding = NamedSharding(mesh, spec)
    global_shape = (per * mesh_process_count(mesh),) + local_rows.shape[1:]
    from delphi_tpu.ops.xfer import record_transfer
    record_transfer(padded.nbytes)  # this process's contributed block
    return jax.make_array_from_process_local_data(sharding, padded, global_shape)


def padded_row_target(n: int, mesh: Optional[Mesh], axis: str = "dp") -> int:
    """Row count to pad to: the next power of two (>= 8, recompilation
    bound), raised to a multiple of the mesh's dp size so row shards are
    equal. dp sizes that are powers of two (the normal case) leave the
    power-of-two target unchanged."""
    from delphi_tpu.parallel import planner
    target = planner.pow2_pad(n, floor=8)
    if mesh is not None:
        dp = mesh.shape[axis]
        target = ((target + dp - 1) // dp) * dp
    return target
