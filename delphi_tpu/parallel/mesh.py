"""Mesh construction and row sharding helpers."""

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(n_devices: Optional[int] = None,
              axis_names: Tuple[str, ...] = ("dp",),
              shape: Optional[Sequence[int]] = None) -> Mesh:
    """Builds a mesh over the first ``n_devices`` devices.

    With one axis the mesh is pure data-parallel over rows; pass
    ``axis_names=('dp', 'tp')`` and a ``shape`` to add model parallelism.
    """
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    n = len(devices)
    if shape is None:
        if len(axis_names) == 1:
            shape = (n,)
        elif len(axis_names) == 2:
            tp = 2 if n % 2 == 0 and n >= 2 else 1
            shape = (n // tp, tp)
        else:
            raise ValueError(f"provide `shape` for {len(axis_names)} axes")
    assert int(np.prod(shape)) == n, f"mesh shape {shape} != {n} devices"
    return Mesh(np.asarray(devices).reshape(shape), axis_names)


def pad_rows_to_multiple(array: np.ndarray, multiple: int,
                         fill) -> Tuple[np.ndarray, int]:
    """Pads axis 0 to a multiple of the dp size (shards must be equal)."""
    n = array.shape[0]
    target = ((n + multiple - 1) // multiple) * multiple
    if target == n:
        return array, n
    pad = np.full((target - n,) + array.shape[1:], fill, dtype=array.dtype)
    return np.concatenate([array, pad], axis=0), n


def shard_rows(array: np.ndarray, mesh: Mesh, axis: str = "dp"):
    """Places an array on the mesh sharded along axis 0."""
    spec = P(axis, *([None] * (array.ndim - 1)))
    return jax.device_put(array, NamedSharding(mesh, spec))
