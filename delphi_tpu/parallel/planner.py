"""Unified launch planner: ONE dispatch policy for every device phase.

Before this module the pipeline had six hand-rolled launch policies — the
bucketed domain/weak-label dispatch (``ops/domain.py``), k-means row padding
(``ops/cluster.py``), the prewarm variant enumeration
(``parallel/compile_plane.py``), the private pair/distinct chunking in
``ops/freq.py``/``ops/entropy.py``/``ops/detect.py``, the escalation joint
kernel's pow2 domain buckets (``escalate/joint.py``) and the GBDT CV/boost
chunk selection (``models/gbdt.py``). Each padded, bucketed and chunked its
own way, so tuning device dispatch meant tuning six knobs. They now all
route through :func:`plan_launches`, which turns a list of :class:`Piece`
work items into a deterministic :class:`LaunchPlan`:

* pieces are split into spans of at most ``chunk`` units,
* each span pads to the next power of two (``size_floor``-bounded) so the
  number of distinct compiled variants stays logarithmic,
* same-shape spans group into buckets; a bucket splits into launches of at
  most ``batch_cap`` spans, optionally pow2-padding the batch axis,
* per-launch pad-waste is accounted (``launch.*`` counters/gauges).

Plans are pure data. When a plan store is armed (the serve plane arms
``<cache>/plans/``; ``DELPHI_PLAN_DIR`` arms one anywhere) plans persist
per table fingerprint: a warm request with an unchanged piece set loads the
stored grouping instead of replanning, and the compile plane prewarms
exactly the variants a stored plan will launch.

``DELPHI_PLAN=0`` pins the planner to the legacy grouping (no cross-bucket
merging, no persistence) for A/B runs — the grouping it emits then is
structurally identical to what the six hand-rolled policies produced, so
results are bit-identical by construction. With planning on, the only
additional transform is a bounded same-shape bucket merge that is inert for
numerics (padding rows/slots are masked or sliced off at every call site).
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import warnings
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import (Any, Callable, Dict, List, Optional, Sequence, Tuple,
                    Union)

from delphi_tpu.observability.registry import counter_inc, gauge_set

# ---------------------------------------------------------------------------
# pow2 helpers — the ONE place launch padding math lives. A static guard in
# tests/test_transfer_guard.py forbids `bit_length` pad idioms anywhere else
# in the package (minus the registered shims listed there).
# ---------------------------------------------------------------------------


def pow2_pad(n: int, floor: int = 1) -> int:
    """Next power of two >= max(n, 1), raised to ``floor``."""
    return max(int(floor), 1 << max(int(n) - 1, 0).bit_length())


def pow2_floor(n: int) -> int:
    """Largest power of two <= n (n >= 1)."""
    return 1 << (int(n).bit_length() - 1)


def round_chunks(n_rounds: int, chunk: int) -> List[int]:
    """Split ``n_rounds`` boosting rounds into fixed-size chunks plus one
    remainder — the GBDT boost-chunk policy (two compiled variants max)."""
    q, r = divmod(max(int(n_rounds), 1), int(chunk))
    return [int(chunk)] * q + ([r] if r else [])


# ---------------------------------------------------------------------------
# planner knobs: DELPHI_PLAN_* spellings, with one-time deprecation warnings
# for the legacy per-phase spellings they absorb.
# ---------------------------------------------------------------------------

_DEPRECATED_WARNED: set = set()


def _deprecated_env(legacy: str, replacement: str) -> Optional[str]:
    val = os.environ.get(legacy)
    if val is not None and legacy not in _DEPRECATED_WARNED:
        _DEPRECATED_WARNED.add(legacy)
        warnings.warn(
            f"{legacy} is deprecated; use {replacement} (the unified "
            f"launch-planner knob) instead", DeprecationWarning, stacklevel=3)
    return val


def planning_enabled() -> bool:
    """DELPHI_PLAN=0 pins the planner to the legacy grouping (A/B control):
    no bucket merging, no plan persistence."""
    return os.environ.get("DELPHI_PLAN", "1").lower() not in (
        "0", "false", "no", "off")


def merge_factor() -> int:
    """Max padded-size ratio a same-shape bucket merge may bridge
    (DELPHI_PLAN_MERGE; 0 disables merging; default 8)."""
    try:
        return int(os.environ.get("DELPHI_PLAN_MERGE", "8"))
    except ValueError:
        return 8


def chunk_cells(default: int = 1_000_000) -> int:
    """Cell budget per launch for chunked phases (domain scoring).
    ``DELPHI_PLAN_CHUNK_CELLS`` wins; the legacy per-phase spelling
    ``DELPHI_DOMAIN_CHUNK_CELLS`` is honored with a deprecation warning."""
    val = os.environ.get("DELPHI_PLAN_CHUNK_CELLS")
    if val is None:
        val = _deprecated_env("DELPHI_DOMAIN_CHUNK_CELLS",
                              "DELPHI_PLAN_CHUNK_CELLS")
    try:
        return max(1, int(val)) if val is not None else int(default)
    except ValueError:
        return int(default)


def cv_instance_cap(default: int = 16) -> int:
    """Max CV instances fused per gbdt.cv_chunk launch.
    ``DELPHI_PLAN_CV_INSTANCE_CAP`` wins; the legacy spelling
    ``DELPHI_CV_INSTANCE_CAP`` is honored with a deprecation warning."""
    val = os.environ.get("DELPHI_PLAN_CV_INSTANCE_CAP")
    if val is None:
        val = _deprecated_env("DELPHI_CV_INSTANCE_CAP",
                              "DELPHI_PLAN_CV_INSTANCE_CAP")
    try:
        return max(1, int(val)) if val is not None else int(default)
    except ValueError:
        return int(default)


# ---------------------------------------------------------------------------
# plan data model
# ---------------------------------------------------------------------------

Key = Union[int, str]
Shape = Tuple[Any, ...]


@dataclass(frozen=True)
class Piece:
    """One unit of work offered to the planner.

    ``key`` must be JSON-stable (int or str) — it is how a persisted plan
    reattaches to live work. ``size`` is the extent along the padded axis
    (rows, cells…). ``shape`` is everything else that determines the
    compiled variant (mode, vocab pads, depth…): spans only share a launch
    when their shapes are equal.
    """

    key: Key
    size: int
    shape: Shape = ()


@dataclass(frozen=True)
class Span:
    """A contiguous slice [lo, lo+size) of one piece, assigned to a launch."""

    key: Key
    lo: int
    size: int


@dataclass(frozen=True)
class Launch:
    """One batched device dispatch: ``spans`` padded to ``padded_size``
    along the work axis and ``batch_pad`` along the batch axis."""

    shape: Shape
    padded_size: int
    batch_pad: int
    spans: Tuple[Span, ...]

    @property
    def useful_units(self) -> int:
        return sum(s.size for s in self.spans)

    @property
    def padded_units(self) -> int:
        return self.padded_size * self.batch_pad


@dataclass
class LaunchPlan:
    """Deterministic grouping of pieces into padded batched launches."""

    phase: str
    launches: List[Launch]
    signature: str
    cached: bool = False
    merged_buckets: int = 0
    _recorded: bool = field(default=False, repr=False)

    @property
    def n_launches(self) -> int:
        return len(self.launches)

    @property
    def n_buckets(self) -> int:
        return len({(l.shape, l.padded_size) for l in self.launches})

    @property
    def useful_units(self) -> int:
        return sum(l.useful_units for l in self.launches)

    @property
    def padded_units(self) -> int:
        return sum(l.padded_units for l in self.launches)

    @property
    def pad_waste_ratio(self) -> float:
        padded = self.padded_units
        return 0.0 if padded <= 0 else 1.0 - self.useful_units / padded

    def record(self) -> "LaunchPlan":
        """Emit the ``launch.*`` observability family for this plan (global
        and per-phase). Idempotent per plan object so call sites can record
        unconditionally next to execution."""
        if self._recorded:
            return self
        self._recorded = True
        for scope in ("launch", f"launch.phase.{self.phase}"):
            counter_inc(f"{scope}.plans")
            counter_inc(f"{scope}.launches", self.n_launches)
            counter_inc(f"{scope}.buckets", self.n_buckets)
            counter_inc(f"{scope}.pieces", sum(len(l.spans) for l in self.launches))
            counter_inc(f"{scope}.padded_units", self.padded_units)
            counter_inc(f"{scope}.useful_units", self.useful_units)
            gauge_set(f"{scope}.pad_waste_ratio", round(self.pad_waste_ratio, 6))
        if self.merged_buckets:
            counter_inc("launch.merged_buckets", self.merged_buckets)
        return self

    def launch_scope(self, launch: "Launch"):
        """Execution scope for ONE launch of this plan: wall/device-time
        goes to the launch-cost ledger and (when a trace is active) a
        nested trace event — see ``observability/trace.py``. Call sites
        wrap the device dispatch: ``with plan.launch_scope(launch): ...``.
        A no-op context when no run recorder is active."""
        from delphi_tpu.observability import trace
        return trace.launch_scope(self, launch)

    # -- persistence (pure-data round trip) --------------------------------

    def to_payload(self) -> Dict[str, Any]:
        return {
            "signature": self.signature,
            "merged_buckets": self.merged_buckets,
            "launches": [
                {"shape": list(l.shape), "padded": l.padded_size,
                 "batch_pad": l.batch_pad,
                 "spans": [[s.key, s.lo, s.size] for s in l.spans]}
                for l in self.launches],
        }

    @classmethod
    def from_payload(cls, phase: str, payload: Dict[str, Any]) -> "LaunchPlan":
        launches = [
            Launch(shape=tuple(l["shape"]), padded_size=int(l["padded"]),
                   batch_pad=int(l["batch_pad"]),
                   spans=tuple(Span(key=s[0], lo=int(s[1]), size=int(s[2]))
                               for s in l["spans"]))
            for l in payload["launches"]]
        return cls(phase=phase, launches=launches,
                   signature=payload["signature"], cached=True,
                   merged_buckets=int(payload.get("merged_buckets", 0)))


# ---------------------------------------------------------------------------
# plan store: per-fingerprint JSON files under <root>/, armed by the serve
# plane (<cache>/plans) or DELPHI_PLAN_DIR. Plans reattach by span key; any
# signature mismatch (piece set, sizes, shapes, or policy knobs changed) is
# a miss and the phase replans.
# ---------------------------------------------------------------------------


class PlanStore:
    def __init__(self, root: str) -> None:
        self.root = root
        os.makedirs(root, exist_ok=True)
        self._lock = threading.Lock()
        self._mem: Dict[str, Dict[str, Any]] = {}

    def _path(self, fingerprint: str) -> str:
        return os.path.join(self.root, f"{fingerprint}.json")

    def _doc(self, fingerprint: str) -> Dict[str, Any]:
        with self._lock:
            doc = self._mem.get(fingerprint)
        if doc is not None:
            return doc
        from delphi_tpu.parallel import store as dstore
        doc, _status = dstore.read_json(
            self._path(fingerprint), schema="launch_plan",
            site="store.plan", root=self.root)
        if not isinstance(doc, dict):
            # missing, quarantined-corrupt, or legacy garbage: a plan-cache
            # miss either way — the phase replans and overwrites
            doc = {"version": 1, "phases": {}}
        with self._lock:
            self._mem[fingerprint] = doc
        return doc

    def load(self, fingerprint: str, phase: str) -> Optional[Dict[str, Any]]:
        return self._doc(fingerprint).get("phases", {}).get(phase)

    def save(self, fingerprint: str, phase: str,
             payload: Dict[str, Any]) -> None:
        doc = self._doc(fingerprint)
        with self._lock:
            doc.setdefault("phases", {})[phase] = payload
            body = json.dumps(doc, sort_keys=True) + "\n"
        from delphi_tpu.parallel import store as dstore
        try:
            # durable-store seam: envelope + fsync + rename + dir fsync —
            # the pre-seam writer skipped fsync entirely, so a crash could
            # land rename metadata with no data behind it
            dstore.write_bytes(self._path(fingerprint), body.encode("utf-8"),
                               schema="launch_plan", site="store.plan",
                               root=self.root)
        except OSError:
            pass  # persistence is best-effort; planning already succeeded
        gauge_set("serve.warm_plans", self.n_plans())

    def n_plans(self) -> int:
        # launch-cost ledgers (ledger.<fp>.json, observability/trace.py)
        # live beside the plans but are not plans
        try:
            return sum(1 for n in os.listdir(self.root)
                       if n.endswith(".json")
                       and not n.startswith("ledger."))
        except OSError:
            return 0

    def fingerprints(self) -> List[str]:
        try:
            return sorted(n[:-5] for n in os.listdir(self.root)
                          if n.endswith(".json")
                          and not n.startswith("ledger."))
        except OSError:
            return []


_store: Optional[PlanStore] = None
_env_store: Optional[PlanStore] = None
_tls = threading.local()


def set_plan_store(root: Optional[str]) -> Optional[PlanStore]:
    """Arm (or disarm, with None) the process plan store. The serve plane
    calls this at start() with <cache>/plans."""
    global _store
    _store = PlanStore(root) if root else None
    return _store


def get_plan_store() -> Optional[PlanStore]:
    """The armed store; falls back to DELPHI_PLAN_DIR when none was armed
    programmatically (bench/CLI runs)."""
    global _env_store
    if _store is not None:
        return _store
    root = os.environ.get("DELPHI_PLAN_DIR")
    if root:
        if _env_store is None or _env_store.root != root:
            _env_store = PlanStore(root)
        return _env_store
    return None


def current_fingerprint() -> Optional[str]:
    return getattr(_tls, "fingerprint", None)


@contextmanager
def plan_fingerprint(fingerprint: Optional[str]):
    """Scope all plan_launches calls on this thread to one table
    fingerprint (serve sets the request fingerprint; model.run derives a
    table-level one when none is active)."""
    prev = getattr(_tls, "fingerprint", None)
    _tls.fingerprint = fingerprint
    try:
        yield
    finally:
        _tls.fingerprint = prev


def table_plan_fingerprint(name: str, n_rows: int,
                           columns: Sequence[str]) -> str:
    """Cheap table-level fingerprint for plan persistence outside serve
    (which keys plans by its own request fingerprint). Collisions are
    harmless: the plan signature re-validates piece sets on load."""
    body = json.dumps([str(name), int(n_rows), list(map(str, columns))])
    return hashlib.sha1(body.encode("utf-8")).hexdigest()


def stored_launch_shapes(fingerprint: Optional[str],
                         phase: str) -> List[Tuple[Shape, int, int]]:
    """(shape, padded_size, batch_pad) triples of the persisted plans for
    ``phase`` — the compile plane prewarms exactly these variants. A phase
    that plans per work group persists under ``phase[i]`` keys; this
    aggregates them. Empty when no store, no fingerprint, or nothing
    stored."""
    store = get_plan_store()
    if store is None or not fingerprint:
        return []
    doc_phases = store._doc(fingerprint).get("phases", {})
    out: List[Tuple[Shape, int, int]] = []
    for name, payload in sorted(doc_phases.items()):
        # `phase[i]` = per-work-group plans; `phase@r<k>of<w>` = the shard
        # plane's per-rank plans (parallel/rowshard.py)
        if name != phase and not name.startswith(phase + "[") \
                and not name.startswith(phase + "@"):
            continue
        out.extend((tuple(l["shape"]), int(l["padded"]), int(l["batch_pad"]))
                   for l in payload.get("launches", []))
    return out


# ---------------------------------------------------------------------------
# the planner
# ---------------------------------------------------------------------------


def _signature(phase: str, pieces: Sequence[Piece],
               policy: Dict[str, Any]) -> str:
    body = json.dumps(
        {"phase": phase, "policy": policy,
         "pieces": [[p.key, int(p.size), list(p.shape)] for p in pieces]},
        sort_keys=True, default=str)
    return hashlib.sha1(body.encode("utf-8")).hexdigest()


def plan_launches(
    phase: str,
    pieces: Sequence[Piece],
    *,
    size_floor: int = 1,
    chunk: Optional[int] = None,
    batch_cap: Optional[Union[int, Callable[[Shape, int], int]]] = None,
    batch_width: Optional[int] = None,
    pad_batch: bool = False,
    pad_to_max: bool = False,
    merge: bool = False,
    policy_tag: str = "",
    fingerprint: Optional[str] = None,
    persist: bool = True,
) -> LaunchPlan:
    """Group ``pieces`` into a deterministic :class:`LaunchPlan`.

    * ``chunk``     — split pieces into spans of at most this many units
    * ``size_floor``— minimum padded span size (recompilation bound)
    * ``batch_cap`` — max spans per launch; int, or callable
                      ``(shape, padded_size) -> int`` for memory-derived caps
    * ``batch_width``— fixed launch width (freq's repeat-pad chunking):
                      implies cap = width and batch_pad = width
    * ``pad_batch`` — pow2-pad the batch axis (else exact span count)
    * ``pad_to_max``— pad every span in a shape bucket to the longest span
                      (percentile pools) instead of per-span pow2
    * ``merge``     — allow the bounded same-shape bucket merge (only when
                      planning is enabled; never increases launch count)
    * ``policy_tag``— extra caller knob state folded into the signature so
                      stale persisted plans invalidate

    Every piece is covered exactly once; plan order is the deterministic
    first-occurrence order of (shape, padded_size) buckets over pieces.
    """
    enabled = planning_enabled()
    policy = {
        "floor": int(size_floor), "chunk": chunk, "width": batch_width,
        "pad_batch": bool(pad_batch), "pad_to_max": bool(pad_to_max),
        "merge": bool(merge and enabled), "merge_factor": merge_factor(),
        "enabled": enabled, "tag": policy_tag,
        "cap": batch_cap if isinstance(batch_cap, int) else None,
    }
    if policy["merge"]:
        from delphi_tpu.observability import trace as _trace
        if _trace.plan_cost_enabled():
            # DELPHI_PLAN_COST=1: merges consult the launch-cost ledger.
            # The key is only present when the gate is on, so cost-gated
            # plans never collide with (or shadow) default plans in the
            # store — and the default signature is byte-identical to the
            # pre-ledger planner.
            policy["cost"] = True
    from delphi_tpu.parallel import rowshard
    shard_tag = rowshard.plan_shard_tag()
    if shard_tag:
        # replicated-pipeline sharding (DELPHI_SHARD): the rank tag rides
        # in the signature AND the store phase key, so each rank persists
        # its OWN per-shard plan (the shard extent is already in the piece
        # shapes the sharded phases pass) — a warm rerun replans zero
        # times on every rank. Absent when off: legacy signatures and
        # store slots stay byte-identical.
        policy["shard"] = shard_tag
    sig = _signature(phase, pieces, policy)

    store_phase = f"{phase}@{shard_tag}" if shard_tag else phase
    fp = fingerprint if fingerprint is not None else current_fingerprint()
    store = get_plan_store() if (persist and enabled) else None
    if store is not None and fp:
        stored = store.load(fp, store_phase)
        if stored and stored.get("signature") == sig:
            counter_inc("launch.plan_cache.hits")
            return LaunchPlan.from_payload(phase, stored)

    plan = _compute_plan(phase, pieces, sig, policy, batch_cap,
                         fingerprint=fp)

    if store is not None and fp:
        counter_inc("launch.replans")
        store.save(fp, store_phase, plan.to_payload())
    return plan


def _compute_plan(phase: str, pieces: Sequence[Piece], sig: str,
                  policy: Dict[str, Any],
                  batch_cap: Optional[Union[int, Callable[[Shape, int], int]]],
                  fingerprint: Optional[str] = None,
                  ) -> LaunchPlan:
    size_floor = policy["floor"]
    chunk = policy["chunk"]
    batch_width = policy["width"]

    # 1. chunk pieces into spans (piece order, then offset order)
    spans: List[Tuple[Span, Shape]] = []
    for p in pieces:
        if p.size <= 0:
            continue
        step = int(chunk) if chunk else p.size
        for lo in range(0, p.size, step):
            spans.append((Span(key=p.key, lo=lo,
                               size=min(step, p.size - lo)), p.shape))

    # 2. bucket by (shape, padded span size) in first-occurrence order
    buckets: Dict[Tuple[Shape, int], List[Span]] = {}
    if policy["pad_to_max"]:
        longest: Dict[Shape, int] = {}
        for s, shape in spans:
            longest[shape] = max(longest.get(shape, 0), s.size)
        for s, shape in spans:
            buckets.setdefault((shape, longest[shape]), []).append(s)
    else:
        for s, shape in spans:
            buckets.setdefault(
                (shape, pow2_pad(s.size, size_floor)), []).append(s)

    def cap_of(shape: Shape, padded: int) -> int:
        if batch_width is not None:
            return int(batch_width)
        if batch_cap is None:
            return 1 << 62
        if callable(batch_cap):
            return max(1, int(batch_cap(shape, padded)))
        return max(1, int(batch_cap))

    def launches_of(bucket_map: Dict[Tuple[Shape, int], List[Span]]) -> int:
        return sum(-(-len(members) // cap_of(shape, padded))
                   for (shape, padded), members in bucket_map.items())

    # 3. bounded same-shape merge: fold a bucket into the next-larger
    # padded size of the same shape when the total ratio stays within
    # merge_factor AND the merged grouping does not launch more often.
    merged_buckets = 0
    if policy["merge"] and policy["merge_factor"] > 0:
        factor = policy["merge_factor"]
        by_shape: Dict[Shape, List[int]] = {}
        for shape, padded in buckets:
            by_shape.setdefault(shape, []).append(padded)
        remap: Dict[Tuple[Shape, int], int] = {}
        for shape, sizes in by_shape.items():
            sizes = sorted(set(sizes))
            step_up = {a: b for a, b in zip(sizes, sizes[1:])}
            for p in sizes:
                t = p
                while t in step_up and step_up[t] // p <= factor:
                    t = step_up[t]
                if t != p:
                    remap[(shape, p)] = t
        if remap and policy.get("cost"):
            # DELPHI_PLAN_COST: drop any step-up the persisted ledger has
            # priced as > MERGE_COST_FACTOR× more expensive per useful
            # unit than leaving the bucket alone (no data → no veto)
            from delphi_tpu.observability import trace as _trace
            remap = {(shape, p): t for (shape, p), t in remap.items()
                     if _trace.merge_allowed(fingerprint, phase, shape,
                                             p, t)}
        if remap:
            candidate: Dict[Tuple[Shape, int], List[Span]] = {}
            for (shape, padded), members in buckets.items():
                target = remap.get((shape, padded), padded)
                candidate.setdefault((shape, target), []).extend(members)
            if launches_of(candidate) <= launches_of(buckets):
                merged_buckets = len(buckets) - len(candidate)
                buckets = candidate

    # 4. split buckets into launches of at most cap spans
    launches: List[Launch] = []
    for (shape, padded), members in buckets.items():
        cap = cap_of(shape, padded)
        for s in range(0, len(members), cap):
            group = members[s:s + cap]
            if batch_width is not None:
                b_pad = int(batch_width)
            elif policy["pad_batch"]:
                b_pad = pow2_pad(len(group))
            else:
                b_pad = len(group)
            launches.append(Launch(shape=shape, padded_size=padded,
                                   batch_pad=b_pad, spans=tuple(group)))

    return LaunchPlan(phase=phase, launches=launches, signature=sig,
                      merged_buckets=merged_buckets)


def padded_extent(phase: str, n: int, floor: int = 8,
                  shape: Shape = ()) -> int:
    """Single-extent convenience: the padded size the planner would assign
    one piece of ``n`` units (pow2, floored). Used by phases whose launch
    is a single padded array rather than a batch."""
    plan = plan_launches(phase, [Piece(key=0, size=max(int(n), 1),
                                       shape=shape)],
                         size_floor=floor, persist=False)
    plan.record()
    return plan.launches[0].padded_size


def plan_cv_slab_widths(n_instances: int, cap: int,
                        single_target: bool) -> List[int]:
    """Distinct launch widths the GBDT CV slab policy will use for
    ``n_instances`` fused instances — the compile plane enumerates prewarm
    variants from this instead of its former per-phase heuristic."""
    if n_instances <= 0:
        return []
    plan = plan_launches(
        "gbdt.cv", [Piece(key=i, size=1) for i in range(int(n_instances))],
        batch_cap=int(cap), pad_batch=not single_target, persist=False)
    return sorted({l.batch_pad for l in plan.launches})
