"""Resilience plane: the guarded-launch seam every device launch and upload
routes through, plus the fault-injection harness, the phase-checkpoint
store, and the backend-init hard-deadline probe.

The repair pipeline's device work all funnels through a handful of call
sites (the ops/xfer.py upload seam, the bucketed domain/weak-label launches,
the GBDT CV chunks and batched fits, the outlier-percentile batch). Each of
those sites wraps its launch in :func:`run_guarded`, which

* **classifies** any raised exception into a small fault taxonomy —
  ``init_timeout`` / ``oom`` / ``transfer`` / ``compile`` / ``transient`` —
  via :func:`classify_fault` (unclassifiable exceptions are program bugs and
  re-raise immediately);
* **retries** classified faults with bounded exponential backoff and
  deterministic jitter (:class:`RetryPolicy` — no randomness, so a replay
  with the same fault plan sleeps the same schedule);
* on retry exhaustion walks a **degradation ladder** instead of dying:
  *shrink* (signal the call site to halve its padded batch via
  :class:`ShrinkBatch` — bit-identical by construction, every launch route
  assembles per-piece results), then *evict* (drop device-resident buffers
  and re-upload through the caller's ``evict`` callback), then *CPU
  fallback* (latch ``jax.default_device(cpu)`` for the remainder of the
  current phase), and only then re-raise.

Every event lands in the run report and the live ``/metrics`` endpoint as
``resilience.*`` counters / histograms, and each degradation that changed a
decision path is stamped into the provenance ledger as a run note.

**Fault injection** (``DELPHI_FAULT_PLAN`` / ``repair.fault.plan``):
``site:nth:kind`` triples, comma-separated — e.g.
``backend.init:1:init_timeout,domain.bucket:2:oom`` — injected at the
guarded seam on the *nth* entry of a matching site (``fnmatch`` wildcards
allowed; attempts count, so ``site:1:oom,site:2:oom`` survives a retry
budget of one). Each triple fires exactly once and the injected exception
carries a realistic message so the REAL classifier path is exercised. The
extra kind ``fatal`` injects an unclassifiable error (test harness for
crash/resume). A leading rank pattern (``rank:site:nth:kind``, fnmatch
over the process index) scopes an entry to specific ranks, and the
special kinds ``stall`` (wedge the calling thread forever) and
``rank_death`` (``os._exit(17)``) drive the multi-process chaos runs —
the cross-rank half of the plane lives in
:mod:`~delphi_tpu.parallel.dist_resilience` (``guarded_collective``,
rank heartbeats, ``rank_loss`` degrade).

**Phase checkpoints** (``DELPHI_CHECKPOINT_DIR`` / ``repair.checkpoint.dir``):
:class:`PhaseCheckpointStore` persists fingerprinted per-phase outputs
(detected error cells, trained model blobs) atomically (tmp +
``os.replace``) after each phase, so a crashed or killed run resumes at the
last completed phase; the PR 2 stall watchdog routes through
:func:`on_watchdog_stall` to request a safe abort (the last completed
phase's checkpoint is already on disk) instead of only dumping stacks.

**Request scopes** (the serving plane, ``observability/serve.py``): a
long-lived process multiplexing concurrent repair sessions cannot share the
process-global latches above — one request's fault plan, CPU latch, or
abort must never leak into another in-flight session. :class:`RequestScope`
carries all of that state per request, activated thread-locally via
:func:`request_scope`:

* a **per-request fault plan** with its own fire-once/entry-count state —
  while a scope is active the process-global ``DELPHI_FAULT_PLAN`` is NOT
  consulted, so ``bench.py --serve-chaos`` can inject faults into exactly
  one of N concurrent sessions;
* a **per-request deadline**: :func:`maybe_abort` (guarded seam entries and
  phase boundaries) raises :class:`DeadlineExceeded` once it expires, and
  ``run_guarded`` clips retry backoff to the remaining budget — a retry
  schedule can never sleep a worker past its deadline;
* per-request **abort** and **CPU-fallback** latches (scoped requests skip
  the process-global latches entirely), and an optional per-request
  **checkpoint directory** override so concurrent sessions never collide on
  phase-checkpoint files.

The scope is thread-local: it covers every seam entered on the request's
worker thread (which is where the pipeline's guarded launches run), not
helper threads the pipeline may spawn internally.
"""

import contextlib

import fnmatch
import json
import logging
import os
import re
import threading
import time
import zlib
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

from delphi_tpu.observability import counter_inc, histogram_observe
from delphi_tpu.observability.provenance import active_ledger
from delphi_tpu.observability.spans import current_recorder

_logger = logging.getLogger(__name__)

_FALSY = frozenset({"0", "false", "no", "off"})

# -- fault taxonomy ----------------------------------------------------------

KIND_INIT_TIMEOUT = "init_timeout"
KIND_OOM = "oom"
KIND_TRANSFER = "transfer"
KIND_COMPILE = "compile"
KIND_TRANSIENT = "transient"
KIND_RANK_LOSS = "rank_loss"
KIND_STORE_CORRUPT = "store_corrupt"
FAULT_KINDS = (KIND_INIT_TIMEOUT, KIND_OOM, KIND_TRANSFER, KIND_COMPILE,
               KIND_TRANSIENT, KIND_RANK_LOSS, KIND_STORE_CORRUPT)


class BackendInitTimeout(RuntimeError):
    """The backend-init probe hit its hard deadline (the hanging-TPU-init
    failure mode): raised instead of stalling the run forever."""


class RankLost(RuntimeError):
    """A cross-rank interaction (host collective, heartbeat) timed out or
    failed because a peer rank is dead or wedged. Raised by
    :func:`~delphi_tpu.parallel.dist_resilience.guarded_collective` only
    when the call site supplied no local fallback; classified as
    :data:`KIND_RANK_LOSS`."""


class StoreCorrupt(RuntimeError):
    """A durable-store envelope failed validation (truncated payload, crc
    mismatch, garbled header): raised internally by parallel/store.py,
    caught by its validated-read path, and surfaced as a quarantined
    cache miss — never propagated to consumers. Classified as
    :data:`KIND_STORE_CORRUPT` so ``resilience.faults.store_corrupt``
    counts every corruption the fleet survives."""


class FaultInjected(BaseException):
    """An exception injected by the DELPHI_FAULT_PLAN harness. The message
    mimics the real runtime's error text so classify_fault exercises the
    production patterns, not a test-only shortcut.

    Derives from BaseException so an injected fault that run_guarded cannot
    absorb (kind ``fatal``, or a plan that exhausts the whole ladder) kills
    the run like a real crash would, instead of being masked by the
    pipeline's ``except Exception`` degradation fallbacks — the chaos A/B
    bit-identity check depends on injected faults surfacing loudly."""

    def __init__(self, kind: str, site: str, n: int) -> None:
        self.kind = kind
        super().__init__(_INJECT_MESSAGES.get(kind, _INJECT_MESSAGES["fatal"])
                         .format(site=site, n=n))


class ShrinkBatch(Exception):
    """Degradation signal OUT of run_guarded: the OOM ladder chose 'shrink'.
    The call site catches it, halves its padded batch, and re-invokes the
    guarded launch on each half (bit-identical: every launch route assembles
    per-piece results, so the split changes launch count, not values)."""


class RunAborted(BaseException):
    """Raised at the next guarded seam entry / phase boundary after
    request_abort — the stall watchdog's checkpoint-and-abort path.

    BaseException, not Exception: an abort must terminate the run at the
    next checkpoint, not be converted into "fall back to the sequential
    path" by a catch-all in the training pipeline."""


class DeadlineExceeded(BaseException):
    """A request-scope deadline expired: raised at the next guarded seam
    entry / phase boundary, or eagerly by ``run_guarded`` when the next
    retry backoff would sleep past the deadline.

    BaseException for the same reason as :class:`RunAborted`: a deadline
    must terminate the request promptly — letting a catch-all degradation
    fallback absorb it would keep burning a worker the client has already
    given up on. The serving plane maps it to HTTP 504."""


_INJECT_MESSAGES = {
    KIND_OOM: ("RESOURCE_EXHAUSTED: out of memory while trying to allocate "
               "buffer (injected at {site} call {n})"),
    KIND_INIT_TIMEOUT: ("DEADLINE_EXCEEDED: backend initialization timed "
                        "out (injected at {site} call {n})"),
    KIND_TRANSFER: ("INTERNAL: failed to transfer buffer to device "
                    "(injected at {site} call {n})"),
    KIND_COMPILE: ("INVALID_ARGUMENT: XLA compilation failed for module "
                   "(injected at {site} call {n})"),
    KIND_TRANSIENT: ("UNAVAILABLE: connection to coordination service "
                     "lost (injected at {site} call {n})"),
    KIND_RANK_LOSS: ("DEADLINE_EXCEEDED: collective operation timed out "
                     "waiting for remote ranks (injected at {site} "
                     "call {n})"),
    KIND_STORE_CORRUPT: ("durable store envelope failed checksum "
                         "validation (injected at {site} call {n})"),
    "torn_write": ("durable store write torn mid-flight "
                   "(injected at {site} call {n})"),
    "fatal": "injected unclassifiable fault at {site} call {n}",
}

#: Plan kinds that do not raise: ``stall`` wedges the calling thread
#: forever (a real wedge, exercised by the peers' collective watchdogs),
#: ``rank_death`` hard-exits the process (``os._exit(17)``) — the two
#: dist-chaos failure modes a 2-process A/B injects deterministically —
#: and ``crash`` hard-exits with code 23 at a durable-store seam entry
#: (tmp file written, rename not yet landed: the kill-9-mid-write tear
#: the store-chaos A/B certifies recovery from).
SPECIAL_INJECT_KINDS = frozenset({"stall", "rank_death", "crash"})

#: Plan kinds the durable-store seam handles itself: ``torn_write`` is
#: raised here as FaultInjected but caught inside parallel/store.py,
#: which truncates the destination at a deterministic offset and lets
#: the writer believe it succeeded — the tear surfaces only at the next
#: validated read.
STORE_INJECT_KINDS = frozenset({"torn_write"})

# Case-sensitive gRPC/XLA status codes; lower-case word patterns matched
# case-insensitively below. Order matters: the first matching kind wins, and
# the more specific kinds (init, oom, transfer) outrank the generic
# transient codes that often share a message.
_CODE_PATTERNS: Tuple[Tuple[str, "re.Pattern"], ...] = (
    (KIND_OOM, re.compile(r"RESOURCE_EXHAUSTED")),
    (KIND_TRANSIENT, re.compile(r"UNAVAILABLE|ABORTED|DATA_LOSS"
                                r"|INTERNAL: RecvBuf|INTERNAL: Failed to "
                                r"complete all kernels")),
)
_WORD_PATTERNS: Tuple[Tuple[str, "re.Pattern"], ...] = (
    (KIND_INIT_TIMEOUT, re.compile(
        r"backend.{0,40}init\w*.{0,40}(timed out|timeout|deadline)"
        r"|init\w*.{0,40}(timed out|deadline exceeded)"
        r"|deadline_exceeded.{0,60}init", re.IGNORECASE | re.DOTALL)),
    (KIND_RANK_LOSS, re.compile(
        r"collective.{0,60}(timed out|timeout|deadline)"
        r"|(rank|peer|process \d+).{0,40}"
        r"(lost|died|unreachable|disconnected|terminated)"
        r"|heartbeat.{0,40}(missed|stale|timed out)"
        r"|barrier.{0,40}(timed out|timeout)"
        r"|shutting down.{0,40}coordination service",
        re.IGNORECASE | re.DOTALL)),
    (KIND_OOM, re.compile(
        r"out of memory|\boom\b|exhausted|failed to allocate"
        r"|allocation.{0,30}(failed|exceed)|hbm.{0,30}exceed",
        re.IGNORECASE | re.DOTALL)),
    (KIND_TRANSFER, re.compile(
        r"failed to transfer|transfer.{0,30}(buffer|failed|error)"
        r"|copy.{0,20}to device|TransferTo\w+|device buffer.{0,20}"
        r"(lost|invalid|deleted)", re.IGNORECASE | re.DOTALL)),
    (KIND_COMPILE, re.compile(
        r"compil\w+.{0,30}(failed|error)|failed.{0,30}compil"
        r"|xla.{0,30}lower|lowering.{0,20}(failed|error)|mosaic",
        re.IGNORECASE | re.DOTALL)),
    (KIND_TRANSIENT, re.compile(
        r"connection (reset|refused|closed)|socket closed|broken pipe"
        r"|temporarily unavailable|try again", re.IGNORECASE | re.DOTALL)),
    (KIND_STORE_CORRUPT, re.compile(
        r"store (envelope|write).{0,50}"
        r"(checksum|crc|truncat|corrupt|torn)"
        r"|envelope.{0,30}failed checksum", re.IGNORECASE | re.DOTALL)),
)


def classify_fault(exc: BaseException) -> Optional[str]:
    """Maps an exception to a fault kind, or None for unclassifiable
    failures (program bugs, bad input) that must re-raise unretried. The
    resilience plane's own control-flow exceptions are never faults."""
    if isinstance(exc, (ShrinkBatch, RunAborted, DeadlineExceeded)):
        return None
    if isinstance(exc, BackendInitTimeout):
        return KIND_INIT_TIMEOUT
    if isinstance(exc, RankLost):
        return KIND_RANK_LOSS
    if isinstance(exc, StoreCorrupt):
        return KIND_STORE_CORRUPT
    msg = f"{type(exc).__name__}: {exc}"
    # init_timeout and rank_loss outrank the codes: both typically arrive
    # spelled DEADLINE_EXCEEDED/UNAVAILABLE, and the generic transient
    # match must not swallow them
    for kind, pat in _WORD_PATTERNS[:2]:
        if pat.search(msg):
            return kind
    for kind, pat in _CODE_PATTERNS:
        if pat.search(msg):
            return kind
    for kind, pat in _WORD_PATTERNS[2:]:
        if pat.search(msg):
            return kind
    return None


# -- retry policy ------------------------------------------------------------

_RETRY_CAP_S = 5.0


def _env_or_conf(env: str, conf_key: str, cast, default):
    raw = os.environ.get(env)
    if raw is None or not raw.strip():
        from delphi_tpu.session import get_session
        raw = get_session().conf.get(conf_key)
        if raw is None or not str(raw).strip():
            return default
    try:
        return cast(str(raw).strip())
    except (TypeError, ValueError):
        _logger.warning(f"{env}/{conf_key}: unparsable value {raw!r}, "
                        f"using default {default!r}")
        return default


class RetryPolicy:
    """Bounded exponential backoff with DETERMINISTIC jitter: the delay for
    (site, attempt) is a pure function — crc32-derived fraction, no RNG —
    so a replayed run with the same fault plan sleeps the same schedule and
    the fake-clock tests can assert it exactly."""

    def __init__(self, max_retries: int = 2, base_s: float = 0.05,
                 cap_s: float = _RETRY_CAP_S) -> None:
        self.max_retries = max(0, int(max_retries))
        self.base_s = max(0.0, float(base_s))
        self.cap_s = max(self.base_s, float(cap_s))

    def backoff_s(self, site: str, attempt: int) -> float:
        base = min(self.cap_s, self.base_s * (2 ** max(attempt - 1, 0)))
        frac = (zlib.crc32(f"{site}:{attempt}".encode()) % 1024) / 1024.0
        return round(base * (0.5 + 0.5 * frac), 6)


def default_policy() -> RetryPolicy:
    """The process-wide policy: ``DELPHI_RETRY_MAX`` retries per guarded
    call (default 2) starting at ``DELPHI_RETRY_BASE_S`` seconds (default
    0.05), doubling up to a 5 s cap; session-config fallbacks
    ``repair.resilience.retry_max`` / ``repair.resilience.retry_base_s``."""
    return RetryPolicy(
        max_retries=_env_or_conf("DELPHI_RETRY_MAX",
                                 "repair.resilience.retry_max", int, 2),
        base_s=_env_or_conf("DELPHI_RETRY_BASE_S",
                            "repair.resilience.retry_base_s", float, 0.05))


# -- fault injection ---------------------------------------------------------

#: Every site name passed to :func:`run_guarded` (plus the backend-init
#: probe's injection point). test_transfer_guard.py statically asserts the
#: source stays in sync, so a new guarded seam that forgets to register
#: here fails tier-1 rather than silently escaping plan validation.
KNOWN_SITES = frozenset({
    "backend.init",
    "xfer.upload",
    "freq.singles",
    "freq.pairs",
    "freq.pairs_pallas",
    "freq.distinct",
    "freq.distinct_merge",
    "fleet.dispatch",
    "autoscale.http",
    "domain.score",
    "domain.weak_label",
    "domain.bucket",
    "detect.percentile",
    "detect.rank",
    "detect.sorted_count",
    "detect.group_extrema",
    "gbdt.cv_chunk",
    "gbdt.fit_chunk",
    "escalate.joint",
    "dist.heartbeat",
    "dist.allgather_bytes",
    "dist.allgather_sum",
    "dist.allgather_any",
    "dist.allgather_max",
    "report.gather",
    # replicated-pipeline shard merges (parallel/rowshard.py): every
    # cross-rank phase merge of the DELPHI_SHARD plane — rank-scoped
    # stall/rank_death plans here rehearse a peer dying mid-phase
    "shard.detect.merge",
    "shard.freq.merge",
    "shard.distinct.merge",
    "shard.entropy.merge",
    "shard.domain.weak",
    # durable-store seam sites (parallel/store.py STORE_SITES): every
    # artifact write passes the injection point, so torn_write/crash plan
    # entries rehearse kill-mid-write at each store
    "store.plan",
    "store.checkpoint",
    "store.model",
    "store.manifest",
    "store.snapshot_state",
    "store.provenance",
    "store.report",
    "store.fleet",
    "store.fsck",
    "store.stream_cursor",
    "store.stream_state",
    "store.trace",
})

_PLAN_RE = re.compile(r"^\s*([^:\s]+)\s*:\s*(\d+)\s*:\s*([a-z_]+)\s*$")
_PLAN_RANK_RE = re.compile(r"^\s*([^:\s]+)\s*:\s*([^:\s]+)\s*:"
                           r"\s*(\d+)\s*:\s*([a-z_]+)\s*$")

_PLAN_KINDS = frozenset(FAULT_KINDS) | {"fatal"} | SPECIAL_INJECT_KINDS \
    | STORE_INJECT_KINDS


def parse_fault_plan(text: str):
    """``site:nth:kind`` triples — or rank-scoped ``rank:site:nth:kind``
    quadruples — comma-separated. ``site`` is an fnmatch pattern over
    guarded-seam site names; ``nth`` is the 1-based seam-entry count for
    that site (attempts count, so consecutive ``nth`` values hit
    consecutive retries); ``kind`` is a taxonomy kind, ``fatal``, or one
    of :data:`SPECIAL_INJECT_KINDS`. The optional leading ``rank`` is an
    fnmatch pattern over the process index, so one shared plan text
    drives a reproducible multi-process chaos run (non-matching ranks
    still count the seam entry, they just never fire the entry). Legacy
    3-field entries parse to 3-tuples unchanged; rank-scoped entries
    carry the rank pattern as a 4th element."""
    triples = []
    for part in text.split(","):
        if not part.strip():
            continue
        m = _PLAN_RE.match(part)
        rank_pat = None
        if m is None:
            m4 = _PLAN_RANK_RE.match(part)
            if not m4:
                raise ValueError(
                    f"DELPHI_FAULT_PLAN: bad triple {part!r} "
                    "(expected site:nth:kind or rank:site:nth:kind)")
            rank_pat, pat, nth, kind = (m4.group(1), m4.group(2),
                                        int(m4.group(3)), m4.group(4))
        else:
            pat, nth, kind = m.group(1), int(m.group(2)), m.group(3)
        if kind not in _PLAN_KINDS:
            raise ValueError(
                f"DELPHI_FAULT_PLAN: unknown fault kind {kind!r} "
                f"(one of {', '.join(FAULT_KINDS)}, fatal, "
                f"{', '.join(sorted(SPECIAL_INJECT_KINDS))})")
        if nth < 1:
            raise ValueError("DELPHI_FAULT_PLAN: nth is 1-based")
        triples.append((pat, nth, kind) if rank_pat is None
                       else (pat, nth, kind, rank_pat))
    return tuple(triples)


def _injection_rank() -> str:
    """The process index the rank-scoped plan entries match against.
    ``DELPHI_PROCESS_ID`` (the launcher's spelling) wins so light tests
    and pre-init code never have to touch the jax backend."""
    env = os.environ.get("DELPHI_PROCESS_ID", "")
    if env.strip().isdigit():
        return env.strip()
    try:
        from delphi_tpu.parallel import distributed
        return str(distributed.process_index())
    except Exception:
        return "0"


def _entry_hit(entry, site: str, n: int, rank_text: Optional[str]):
    """The kind to fire when plan ``entry`` matches this (site, entry
    count) on this rank, else None. ``rank_text`` is resolved lazily by
    the caller (only when the plan has rank-scoped entries at all)."""
    pat, nth, kind = entry[0], entry[1], entry[2]
    if nth != n or not fnmatch.fnmatchcase(site, pat):
        return None
    if len(entry) > 3 and entry[3] is not None:
        if not fnmatch.fnmatchcase(
                rank_text if rank_text is not None else _injection_rank(),
                entry[3]):
            return None
    return kind


def _fault_plan_text() -> str:
    env = os.environ.get("DELPHI_FAULT_PLAN")
    if env is not None:
        return env
    from delphi_tpu.session import get_session
    conf = get_session().conf.get("repair.fault.plan")
    return str(conf) if conf else ""


_plan_lock = threading.Lock()
_plan_state: Dict[str, Any] = {"text": None, "triples": (), "fired": set(),
                               "calls": {}}
_validated_plans: set = set()


def validate_fault_plan(triples: Sequence[Tuple[str, int, str]],
                        source: str = "DELPHI_FAULT_PLAN") -> Tuple[str, ...]:
    """Returns the plan's site patterns that match NO registered guarded
    site (:data:`KNOWN_SITES`) — such triples can never fire and used to
    no-op silently. Logs a one-time warning per distinct (source,
    unmatched-set) and bumps ``resilience.plan.unmatched`` once per
    unmatched pattern, so a typo'd chaos plan is loud instead of a
    false-green A/B run."""
    unmatched = tuple(sorted(
        {entry[0] for entry in triples
         if not any(fnmatch.fnmatchcase(s, entry[0])
                    for s in KNOWN_SITES)}))
    if unmatched:
        key = (source, unmatched)
        with _plan_lock:
            first = key not in _validated_plans
            if first:
                _validated_plans.add(key)
        if first:
            for _ in unmatched:
                counter_inc("resilience.plan.unmatched")
            _logger.warning(
                f"{source}: fault-plan site pattern(s) "
                f"{', '.join(repr(p) for p in unmatched)} match no "
                f"registered guarded site — these triples will never fire. "
                f"Known sites: {', '.join(sorted(KNOWN_SITES))}")
    return unmatched


def reset_fault_state() -> None:
    """Forgets fired triples, per-site call counts, and validation warnings
    (tests / benches that replay the same plan in one process)."""
    with _plan_lock:
        _plan_state.update(text=None, triples=(), fired=set(), calls={})
        _validated_plans.clear()


def _stall_forever() -> None:
    """Wedges the calling thread forever — the injected ``stall`` fault.
    Module-level seam so unit tests can monkeypatch it into a no-op
    while the dist-chaos subprocess workers really do wedge."""
    threading.Event().wait()


def _fire_injection(kind: str, site: str, n: int, source: str) -> None:
    """Fires one matched plan entry: the special kinds act (wedge / die)
    instead of raising, everything else raises :class:`FaultInjected`
    with a realistic message for the classifier."""
    counter_inc("resilience.injected")
    _logger.warning(f"{source}: injecting {kind} at {site} (call {n})")
    if kind == "stall":
        _stall_forever()
        return
    if kind == "rank_death":
        os._exit(17)
    if kind == "crash":
        # mid-write process death at a store seam: the tmp file is on
        # disk, the rename has not landed — restart must find the
        # previous artifact (or a clean miss), never a half-write
        os._exit(23)
    raise FaultInjected(kind, site, n)


def _maybe_inject(site: str) -> None:
    scope = current_scope()
    if scope is not None:
        # a request scope owns injection entirely: the process-global plan
        # is NOT consulted, so one session's chaos never leaks into another
        scope.maybe_inject(site)
        return
    text = _fault_plan_text()
    armed = None
    with _plan_lock:
        if text != _plan_state["text"]:
            _plan_state.update(text=text,
                               triples=parse_fault_plan(text) if text else (),
                               fired=set(), calls={})
            armed = _plan_state["triples"]
        triples = _plan_state["triples"]
        if not triples:
            return
        n = _plan_state["calls"].get(site, 0) + 1
        _plan_state["calls"][site] = n
        rank_text = _injection_rank() \
            if any(len(t) > 3 for t in triples) else None
        hit = None
        for i, entry in enumerate(triples):
            if i in _plan_state["fired"]:
                continue
            kind = _entry_hit(entry, site, n, rank_text)
            if kind is not None:
                _plan_state["fired"].add(i)
                hit = (kind, n)
                break
    if armed:
        validate_fault_plan(armed)
    if hit is not None:
        _fire_injection(hit[0], site, hit[1], "fault plan")


# -- request scopes (per-session isolation for the serving plane) ------------

_scope_tls = threading.local()


class RequestScope:
    """All per-request resilience state for one serving-plane session:
    a private fault plan (fire-once + per-site entry counts), an absolute
    deadline, abort and CPU-fallback latches, and an optional checkpoint-
    directory override. While a scope is active on a thread the process-
    global plan/latches are neither read nor written, so concurrent
    requests cannot observe each other through this module."""

    def __init__(self, request_id: str, *, fault_plan: str = "",
                 deadline_s: Optional[float] = None,
                 checkpoint_dir: Optional[str] = None) -> None:
        self.request_id = str(request_id)
        self.plan_triples = parse_fault_plan(fault_plan) if fault_plan else ()
        if self.plan_triples:
            validate_fault_plan(self.plan_triples,
                                f"request {self.request_id} fault plan")
        self.deadline_at = (time.monotonic() + float(deadline_s)
                            if deadline_s is not None and float(deadline_s) > 0
                            else None)
        self.checkpoint_dir = checkpoint_dir
        self.abort_reason: Optional[str] = None
        self.cpu_latch: Dict[str, Any] = {"active": False, "site": None,
                                          "device": None}
        self._lock = threading.Lock()
        self._fired: set = set()
        self._calls: Dict[str, int] = {}

    # deadline --------------------------------------------------------------

    def remaining_s(self) -> Optional[float]:
        """Seconds until the deadline (negative once past), or None when
        the request has no deadline."""
        if self.deadline_at is None:
            return None
        return self.deadline_at - time.monotonic()

    def expired(self) -> bool:
        rem = self.remaining_s()
        return rem is not None and rem <= 0.0

    # abort -----------------------------------------------------------------

    def request_abort(self, reason: str) -> None:
        """Arms this request's abort latch only — other in-flight sessions
        keep running. Raised as RunAborted at the next seam entry / phase
        boundary on the request's thread."""
        if self.abort_reason is None:
            self.abort_reason = str(reason)
            counter_inc("resilience.aborts_requested")

    # fault injection -------------------------------------------------------

    def maybe_inject(self, site: str) -> None:
        if not self.plan_triples:
            return
        with self._lock:
            n = self._calls.get(site, 0) + 1
            self._calls[site] = n
            rank_text = _injection_rank() \
                if any(len(t) > 3 for t in self.plan_triples) else None
            hit = None
            for i, entry in enumerate(self.plan_triples):
                if i in self._fired:
                    continue
                kind = _entry_hit(entry, site, n, rank_text)
                if kind is not None:
                    self._fired.add(i)
                    hit = (kind, n)
                    break
        if hit is not None:
            _fire_injection(hit[0], site, hit[1],
                            f"request {self.request_id} fault plan")


def current_scope() -> Optional[RequestScope]:
    """The RequestScope active on THIS thread, or None outside the serving
    plane (the overwhelmingly common case: one attribute read)."""
    return getattr(_scope_tls, "scope", None)


@contextlib.contextmanager
def request_scope(scope: RequestScope):
    """Activates ``scope`` for the current thread. The scope is thread-
    local by design: it covers every guarded seam entered on the request's
    worker thread; helper threads the pipeline spawns internally fall back
    to the (un-planned, un-latched) global state."""
    prev = getattr(_scope_tls, "scope", None)
    _scope_tls.scope = scope
    try:
        yield scope
    finally:
        _scope_tls.scope = prev


# -- CPU fallback latch ------------------------------------------------------

_cpu_latch: Dict[str, Any] = {"active": False, "phase": None, "site": None}


def _current_phase() -> Optional[str]:
    rec = current_recorder()
    return getattr(rec, "current_phase", None) if rec is not None else None


def cpu_fallback_active() -> bool:
    """True while the repeated-device-fault CPU latch holds. Global latch:
    scoped to the phase that latched it — it self-clears when the
    recorder's current phase moves on (the next phase gets the device
    back); without a recorder it holds until clear_cpu_fallback(). Scoped
    latch (inside a RequestScope): holds for the remainder of the request —
    the recorder's current phase is process-wide and races across
    concurrent sessions, so it cannot scope a per-request latch."""
    scope = current_scope()
    if scope is not None:
        return bool(scope.cpu_latch["active"])
    if not _cpu_latch["active"]:
        return False
    phase = _current_phase()
    if phase is not None and _cpu_latch["phase"] is not None \
            and phase != _cpu_latch["phase"]:
        clear_cpu_fallback()
        return False
    return True


def clear_cpu_fallback() -> None:
    _cpu_latch.update(active=False, phase=None, site=None)


def _latch_cpu_fallback(site: str) -> bool:
    import jax
    try:
        cpu = jax.devices("cpu")[0]
    except Exception:
        return False
    scope = current_scope()
    if scope is not None:
        scope.cpu_latch.update(active=True, site=site, device=cpu)
        return True
    _cpu_latch.update(active=True, phase=_current_phase(), site=site,
                      device=cpu)
    return True


def _cpu_device():
    import jax
    scope = current_scope()
    latch = scope.cpu_latch if scope is not None else _cpu_latch
    return jax.default_device(latch.get("device") or jax.devices("cpu")[0])


# -- abort (watchdog checkpoint-and-abort) -----------------------------------

_abort_state: Dict[str, Optional[str]] = {"reason": None}


def request_abort(reason: str) -> None:
    """Arms the abort latch: the run raises RunAborted at the next guarded
    seam entry or phase boundary — a SAFE stop, because phase checkpoints
    persist at phase end, never mid-phase."""
    if _abort_state["reason"] is None:
        _abort_state["reason"] = str(reason)
        counter_inc("resilience.aborts_requested")


def abort_requested() -> Optional[str]:
    return _abort_state["reason"]


def clear_abort() -> None:
    _abort_state["reason"] = None


def maybe_abort() -> None:
    """Raises at a safe stopping point (seam entry / phase boundary) when
    an abort or deadline applies. Inside a RequestScope only the scope's
    latches count — the process-global abort (the watchdog's) is serviced
    by the serving plane per-request, never broadcast through here, so one
    wedged session cannot kill its neighbors."""
    scope = current_scope()
    if scope is not None:
        if scope.abort_reason is not None:
            raise RunAborted(f"run aborted: {scope.abort_reason}")
        rem = scope.remaining_s()
        if rem is not None and rem <= 0.0:
            counter_inc("resilience.deadline_expired")
            raise DeadlineExceeded(
                f"request {scope.request_id} deadline exceeded "
                f"({-rem:.3f}s past)")
        return
    reason = _abort_state["reason"]
    if reason is not None:
        raise RunAborted(f"run aborted: {reason}")


def on_watchdog_stall(recorder: Any, idle_s: float) -> None:
    """The stall watchdog's checkpoint-and-abort hook. Armed when a
    checkpoint dir is configured (resume is safe) or ``DELPHI_STALL_ABORT``
    is explicitly truthy; an explicitly falsy flag disables it even with a
    checkpoint dir, restoring the PR 2 dump-stacks-only behavior."""
    flag = os.environ.get("DELPHI_STALL_ABORT")
    directory = checkpoint_dir()
    if flag is not None and flag.strip():
        enabled = flag.strip().lower() not in _FALSY
    else:
        enabled = directory is not None
    if not enabled:
        return
    counter_inc("resilience.stall_aborts")
    request_abort(f"watchdog stall: no span transition for {idle_s:.1f}s")
    if directory:
        from delphi_tpu.parallel import store as dstore
        try:
            from delphi_tpu.observability import trace as _trace
            marker = os.path.join(directory, "stall_abort.json")
            dstore.write_json(
                marker,
                {"idle_s": round(idle_s, 3),
                 "active_spans": recorder.active_spans(),
                 "transition_count": recorder.transition_count,
                 # the wedged request's trace identity: join key between
                 # this marker and the exported /trace/<id> document
                 "trace_ids": _trace.active_trace_ids(),
                 "traces": _trace.active_traces()},
                schema="marker", site="store.checkpoint", root=directory)
        except Exception as e:  # marker is best-effort evidence
            _logger.warning(f"failed to write stall marker: {e}")


# -- the guarded seam --------------------------------------------------------

def _stamp_ledger(action: str, site: str, kind: str) -> None:
    led = active_ledger()
    if led is not None:
        record = getattr(led, "record_note", None)
        if record is not None:
            record(f"resilience.{action}", f"{site}: {kind}")


def run_guarded(site: str, thunk: Callable[[], Any], *,
                can_shrink: bool = False,
                evict: Optional[Callable[[], Any]] = None,
                cpu_fallback: bool = True,
                policy: Optional[RetryPolicy] = None,
                sleep: Optional[Callable[[float], None]] = None,
                classify: Callable[[BaseException], Optional[str]]
                = classify_fault) -> Any:
    """Runs one device launch/upload under the resilience plane. See the
    module docstring for the retry + degradation-ladder contract. ``sleep``
    is injectable so tier-1 tests run the schedule against a fake clock."""
    pol = policy or default_policy()
    do_sleep = sleep if sleep is not None else time.sleep
    scope = current_scope()
    attempt = 0
    budget = pol.max_retries
    evicted = False
    while True:
        maybe_abort()
        attempt += 1
        try:
            _maybe_inject(site)
            if cpu_fallback_active():
                with _cpu_device():
                    return thunk()
            return thunk()
        except (ShrinkBatch, RunAborted):
            raise
        except (Exception, FaultInjected) as exc:
            kind = classify(exc)
            if kind is None:
                raise
            counter_inc(f"resilience.faults.{kind}")
            _logger.warning(
                f"{site}: classified {kind} fault on attempt {attempt}: "
                f"{type(exc).__name__}: {exc}")
            if budget > 0:
                budget -= 1
                delay = pol.backoff_s(site, attempt)
                if scope is not None:
                    # clip the retry schedule to the request's remaining
                    # deadline: sleeping past it would wedge a worker the
                    # client has already abandoned
                    rem = scope.remaining_s()
                    if rem is not None and delay >= rem:
                        counter_inc("resilience.deadline_clipped")
                        raise DeadlineExceeded(
                            f"request {scope.request_id}: {site} retry "
                            f"backoff {delay:.3f}s exceeds remaining "
                            f"deadline {max(rem, 0.0):.3f}s") from exc
                counter_inc("resilience.retries")
                histogram_observe("resilience.backoff_seconds", delay)
                do_sleep(delay)
                continue
            # retry budget exhausted: walk the degradation ladder
            # (shrink -> evict -> CPU fallback), cheapest escalation first
            if can_shrink:
                counter_inc("resilience.degrade.shrink")
                _stamp_ledger("shrink", site, kind)
                _logger.warning(f"{site}: degrading — shrink batch ({kind})")
                raise ShrinkBatch(site) from exc
            if evict is not None and not evicted:
                evicted = True
                counter_inc("resilience.degrade.evict")
                _stamp_ledger("evict", site, kind)
                _logger.warning(
                    f"{site}: degrading — evicting device residency and "
                    f"re-uploading ({kind})")
                evict()
                budget = pol.max_retries
                continue
            already_latched = (scope.cpu_latch["active"] if scope is not None
                               else _cpu_latch["active"])
            if cpu_fallback and not already_latched \
                    and _latch_cpu_fallback(site):
                counter_inc("resilience.degrade.cpu_fallback")
                _stamp_ledger("cpu_fallback", site, kind)
                _logger.warning(
                    f"{site}: degrading — CPU backend for the remainder "
                    f"of the phase ({kind})")
                budget = pol.max_retries
                continue
            raise


# -- backend-init hard-deadline probe ----------------------------------------

def init_deadline_s() -> float:
    """Hard deadline for the backend-init probe in seconds:
    ``DELPHI_INIT_DEADLINE_S`` / ``repair.init.deadline_s`` (default 180;
    0 disables and probes inline with no deadline)."""
    return _env_or_conf("DELPHI_INIT_DEADLINE_S", "repair.init.deadline_s",
                        float, 180.0)


def probe_backend(deadline_s: Optional[float] = None,
                  probe: Optional[Callable[[], Any]] = None):
    """``jax.devices()`` under a hard deadline, probed from a daemon thread:
    a hanging TPU init (the BENCH_TPU_MEASURED.md failure mode) raises
    :class:`BackendInitTimeout` within the deadline instead of stalling the
    run — the caller degrades to the single-device/CPU path. The wedged
    probe thread is daemonic and leaks by design (it cannot be cancelled);
    ``probe`` is injectable for tests."""
    deadline = init_deadline_s() if deadline_s is None else float(deadline_s)
    _maybe_inject("backend.init")

    def _probe():
        import jax
        return jax.devices()

    fn = probe if probe is not None else _probe
    if deadline <= 0:
        return fn()
    out: Dict[str, Any] = {}

    def work():
        try:
            out["devices"] = fn()
        except BaseException as e:  # pragma: no cover - backend specific
            out["error"] = e

    t = threading.Thread(target=work, daemon=True,
                         name="delphi-backend-probe")
    t.start()
    t.join(deadline)
    if t.is_alive():
        raise BackendInitTimeout(
            f"backend initialization timed out after {deadline:.1f}s "
            "(DELPHI_INIT_DEADLINE_S hard deadline); degrading")
    if "error" in out:
        raise out["error"]
    return out["devices"]


def note_fault(exc: BaseException, site: str) -> Optional[str]:
    """Classifies and counts a fault handled OUTSIDE run_guarded (e.g. the
    mesh probe, whose retry-after policy predates this plane). Returns the
    kind, or None when unclassifiable."""
    kind = classify_fault(exc)
    if kind is not None:
        counter_inc(f"resilience.faults.{kind}")
        _logger.warning(f"{site}: classified {kind} fault: "
                        f"{type(exc).__name__}: {exc}")
    return kind


# -- phase checkpoint store --------------------------------------------------

def checkpoint_dir() -> Optional[str]:
    """``DELPHI_CHECKPOINT_DIR`` / ``repair.checkpoint.dir``, or None when
    run-level phase checkpointing is off (the default). An active
    RequestScope's ``checkpoint_dir`` overrides both (empty string =
    explicitly disabled for this request) so concurrent sessions never
    collide on ``phase_*.pkl`` files."""
    scope = current_scope()
    if scope is not None and scope.checkpoint_dir is not None:
        return scope.checkpoint_dir.strip() or None
    env = os.environ.get("DELPHI_CHECKPOINT_DIR")
    if env is not None and env.strip():
        return env.strip()
    from delphi_tpu.session import get_session
    conf = get_session().conf.get("repair.checkpoint.dir")
    return str(conf).strip() if conf and str(conf).strip() else None


_PHASE_SAFE = re.compile(r"[^A-Za-z0-9_.-]+")


def fingerprint_digest(fingerprint: Dict[str, Any]) -> str:
    """Stable hex digest of a fingerprint dict: canonical JSON (sorted
    keys, non-JSON leaves stringified) through sha1. The identity the
    incremental plane's snapshot manifests and the checkpoint stores share
    — equal fingerprints digest equal across processes and hosts."""
    import hashlib
    canonical = json.dumps(fingerprint, sort_keys=True, default=str)
    return hashlib.sha1(canonical.encode("utf-8", "replace")).hexdigest()


class PhaseCheckpointStore:
    """Fingerprinted per-phase pickles under one directory. Same trust
    boundary as the model checkpoint (model.py): checkpoints are plain
    pickles — point the directory only at files this process (or you)
    wrote. Persistence rides the durable-store seam (parallel/store.py,
    site ``store.checkpoint``): envelope-framed, crash-consistent writes,
    and corrupt/truncated checkpoints quarantined as cache misses."""

    VERSION = 1

    def __init__(self, directory: str, fingerprint: Dict[str, Any]) -> None:
        self.directory = directory
        self.fingerprint = fingerprint
        # compact identity for logs and for cross-referencing a checkpoint
        # with the snapshot manifest that produced it
        self.digest = fingerprint_digest(fingerprint)

    def _path(self, phase: str) -> str:
        return os.path.join(self.directory,
                            f"phase_{_PHASE_SAFE.sub('_', phase)}.pkl")

    def load(self, phase: str) -> Optional[Any]:
        from delphi_tpu.parallel import store as dstore
        path = self._path(phase)
        payload, status = dstore.read_pickle(
            path, schema="phase_ckpt", site="store.checkpoint",
            root=self.directory)
        if status == "missing":
            counter_inc("resilience.checkpoint.misses")
            return None
        if status == "corrupt":
            # truncated/corrupt envelope or pickle (killed mid-write,
            # disk corruption, wrong file): quarantined by the store
            # seam, counted here too, recompute
            _logger.warning(f"Ignoring corrupt phase checkpoint {path}")
            counter_inc("resilience.checkpoint.corrupt")
            return None
        if not isinstance(payload, dict) \
                or payload.get("version") != self.VERSION \
                or payload.get("fingerprint") != self.fingerprint:
            _logger.warning(
                f"Ignoring stale phase checkpoint {path}: input/options "
                "changed since it was written")
            counter_inc("resilience.checkpoint.stale")
            return None
        counter_inc("resilience.checkpoint.hits")
        _logger.info(f"Resuming phase '{phase}' from checkpoint {path} "
                     f"(fingerprint {self.digest[:12]})")
        return payload["payload"]

    def save(self, phase: str, payload: Any) -> None:
        from delphi_tpu.parallel import store as dstore
        try:
            dstore.write_pickle(
                self._path(phase),
                {"version": self.VERSION,
                 "fingerprint": self.fingerprint,
                 "phase": phase,
                 "payload": payload},
                schema="phase_ckpt", site="store.checkpoint",
                root=self.directory)
            counter_inc("resilience.checkpoint.saves")
            _logger.info(
                f"Phase '{phase}' checkpointed to {self._path(phase)}")
        except Exception as e:
            # a failed checkpoint write must never fail the run itself
            _logger.warning(f"Failed to write phase checkpoint for "
                            f"'{phase}': {e}")
