"""Distributed repair-model training steps (SPMD over a Mesh).

Two shapes of parallelism, matching how the framework trains at scale:

* :func:`logreg_train_step` — one optimizer step of the multinomial
  logistic-regression head with rows sharded over ``dp`` AND the class axis
  sharded over ``tp``: the softmax runs distributed (pmax/psum over ``tp``
  for the log-sum-exp) and gradients reduce with ``psum`` over ``dp``.
* :func:`gbdt_histogram_round` — one boosting round with rows sharded over
  ``dp``: each device builds local gradient/hessian histograms for its row
  shard, histograms ``psum`` over ICI (the reference's Spark shuffle,
  SURVEY.md P1/P2), and every device derives identical split decisions.

These are what `__graft_entry__.dryrun_multichip` compiles and runs over a
virtual mesh.
"""

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from delphi_tpu.observability import counter_inc
from delphi_tpu.parallel.mesh import shard_map


def logreg_train_step(mesh: Mesh, lr: float = 0.1, l2: float = 1e-4):
    """Returns a jitted (W, b, X, y) -> (W, b, loss) SGD step with
    X: P('dp', None), y: P('dp'), W: P(None, 'tp'), b: P('tp')."""
    counter_inc("parallel.logreg_step_programs")

    @partial(shard_map, mesh=mesh,
             in_specs=(P(None, "tp"), P("tp"), P("dp", None), P("dp")),
             out_specs=(P(None, "tp"), P("tp"), P()))
    def step(W, b, X, y):
        # local logits: [n/dp, K/tp]
        logits = X @ W + b
        # distributed log-sum-exp over the class axis
        local_max = logits.max(axis=1, keepdims=True)
        gmax = jax.lax.pmax(local_max, "tp")
        sumexp = jax.lax.psum(jnp.exp(logits - gmax).sum(axis=1, keepdims=True), "tp")
        logp = logits - gmax - jnp.log(sumexp)

        # one-hot of y restricted to this shard's class slice
        k_local = W.shape[1]
        tp_idx = jax.lax.axis_index("tp")
        local_classes = tp_idx * k_local + jnp.arange(k_local)
        onehot = (y[:, None] == local_classes[None, :]).astype(jnp.float32)

        n_global = jax.lax.psum(jnp.float32(X.shape[0]), "dp")
        loss = -jax.lax.psum((onehot * logp).sum(), ("dp", "tp")) / n_global

        dlogits = (jnp.exp(logp) - onehot) / n_global
        dW = jax.lax.psum(X.T @ dlogits, "dp") + 2.0 * l2 * W
        db = jax.lax.psum(dlogits.sum(axis=0), "dp")
        return W - lr * dW, b - lr * db, loss

    return jax.jit(step)


def gbdt_histogram_round(mesh: Mesh, depth: int, n_bins: int,
                         reg_lambda: float = 1.0, lr: float = 0.1):
    """Returns a jitted (bins, grad, hess) -> (feat, thr, leaf, new_pred_delta)
    single boosting round with rows sharded over 'dp'.

    bins: P('dp', None) int32 [n, d]; grad/hess: P('dp') f32.
    Every device computes the same tree from psum'd histograms, then applies
    it to its local rows; outputs are replicated tree arrays plus the
    row-sharded prediction delta.
    """
    counter_inc("parallel.gbdt_round_programs")
    n_nodes = 1 << depth

    @partial(shard_map, mesh=mesh,
             in_specs=(P("dp", None), P("dp"), P("dp")),
             out_specs=(P(), P(), P(), P("dp")))
    def round_fn(bins, grad, hess):
        n, d = bins.shape
        feat = jnp.zeros(n_nodes - 1, dtype=jnp.int32)
        thr = jnp.full(n_nodes - 1, n_bins, dtype=jnp.int32)
        node = jnp.zeros(n, dtype=jnp.int32)

        for level in range(depth):
            n_level = 1 << level
            flat = ((node[:, None] * d + jnp.arange(d)[None, :]) * n_bins
                    + bins).reshape(-1)
            size = n_level * d * n_bins
            hg = jnp.zeros(size, jnp.float32).at[flat].add(jnp.repeat(grad, d))
            hh = jnp.zeros(size, jnp.float32).at[flat].add(jnp.repeat(hess, d))
            # the Spark shuffle, TPU-style: histograms reduce over ICI
            hg = jax.lax.psum(hg, "dp").reshape(n_level, d, n_bins)
            hh = jax.lax.psum(hh, "dp").reshape(n_level, d, n_bins)

            GL, HL = jnp.cumsum(hg, axis=2), jnp.cumsum(hh, axis=2)
            G, H = GL[:, :, -1:], HL[:, :, -1:]
            GR, HR = G - GL, H - HL
            gain = (GL * GL / (HL + reg_lambda) + GR * GR / (HR + reg_lambda)
                    - G * G / (H + reg_lambda))
            gain = gain.at[:, :, -1].set(-jnp.inf)

            flat_gain = gain.reshape(n_level, d * n_bins)
            best = jnp.argmax(flat_gain, axis=1)
            best_gain = jnp.take_along_axis(flat_gain, best[:, None], axis=1)[:, 0]
            best_f = jnp.where(best_gain > 0, (best // n_bins).astype(jnp.int32), 0)
            best_b = jnp.where(best_gain > 0, (best % n_bins).astype(jnp.int32),
                               n_bins)

            offset = n_level - 1
            feat = jax.lax.dynamic_update_slice(feat, best_f, (offset,))
            thr = jax.lax.dynamic_update_slice(thr, best_b, (offset,))
            go_right = bins[jnp.arange(n), best_f[node]] > best_b[node]
            node = node * 2 + go_right.astype(jnp.int32)

        leaf_g = jax.lax.psum(jnp.zeros(n_nodes, jnp.float32).at[node].add(grad), "dp")
        leaf_h = jax.lax.psum(jnp.zeros(n_nodes, jnp.float32).at[node].add(hess), "dp")
        leaf = -leaf_g / (leaf_h + reg_lambda) * lr
        return feat, thr, leaf, leaf[node]

    return jax.jit(round_fn)
