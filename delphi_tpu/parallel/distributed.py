"""Multi-host substrate: `jax.distributed` wiring over DCN.

The reference scales out through Spark's driver/executor RPC + shuffle
service (SURVEY.md §2.3); the TPU-native equivalent is one JAX process per
host joined through `jax.distributed.initialize`, after which
`jax.devices()` spans every host's chips and the existing mesh/shard_map
programs run their psums over ICI within a slice and DCN across slices —
no code changes above this layer.

Opt-in via environment (mirrors how launchers like GKE/SLURM inject rank
info):

    DELPHI_COORDINATOR=<host:port>   enables multi-host init (required)
    DELPHI_NUM_PROCESSES=<n>         optional when the launcher provides it
    DELPHI_PROCESS_ID=<i>            optional when the launcher provides it

Single-process runs (no DELPHI_COORDINATOR) are a no-op.

Every host collective below routes through
:func:`~delphi_tpu.parallel.dist_resilience.guarded_collective` — a
bounded watchdog seam (``DELPHI_COLLECTIVE_TIMEOUT_S``) that classifies a
wedged or dead peer as a ``rank_loss`` fault and degrades to the local
fallback instead of hanging forever. Each collective carries a registered
site name (``dist.allgather_*``) so the ``DELPHI_FAULT_PLAN`` chaos
harness can target it; the raw ``multihost_utils.process_allgather``
transport appears ONLY inside the ``_gather`` thunks here (a static guard
in tests/test_transfer_guard.py enforces that)."""

import os

from delphi_tpu.utils import setup_logger

_logger = setup_logger()

_initialized = False


def maybe_initialize_distributed() -> bool:
    """Idempotently joins the multi-host cluster when DELPHI_COORDINATOR is
    set. Must run before the first backend touch (jax.devices()); callers
    in this package invoke it from mesh construction and the batch entry
    point. Returns True when running multi-host. After a successful join
    the distributed resilience plane starts the local liveness toucher
    and runs the first membership heartbeat, so a peer that wedges during
    startup is detected here — bounded — rather than at the first real
    collective."""
    global _initialized
    coordinator = os.environ.get("DELPHI_COORDINATOR", "")
    if not coordinator:
        return False
    if _initialized:
        return True

    import jax

    # CPU-backed clusters (localhost benches, the dist-chaos A/B, CI) need
    # an explicit cross-process collectives implementation: without one,
    # every process_allgather dies with "Multiprocess computations aren't
    # implemented on the CPU backend". Must land before the CPU client is
    # created; a no-op for TPU-backed runs.
    try:
        platforms = str(jax.config.jax_platforms or
                        os.environ.get("JAX_PLATFORMS", ""))
        if "cpu" in platforms:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # pragma: no cover - older jaxlib without gloo
        pass

    kwargs = {"coordinator_address": coordinator}
    num = os.environ.get("DELPHI_NUM_PROCESSES", "")
    pid = os.environ.get("DELPHI_PROCESS_ID", "")
    if num:
        kwargs["num_processes"] = int(num)
    if pid:
        kwargs["process_id"] = int(pid)
    jax.distributed.initialize(**kwargs)
    _initialized = True
    _logger.info(
        f"jax.distributed initialized: process {jax.process_index()} of "
        f"{jax.process_count()}, {len(jax.devices())} global devices")
    from delphi_tpu.parallel import dist_resilience
    dist_resilience.start_liveness()
    dist_resilience.ensure_membership()
    return True


def process_count() -> int:
    """Number of processes in the cluster. Every collective in this module
    routes its single-process short-circuit through here (rather than
    calling ``jax.process_count()`` inline) so tests can fake a multi-host
    topology by monkeypatching one function."""
    import jax

    return jax.process_count()


def process_index() -> int:
    """This process's rank; the companion of :func:`process_count`."""
    import jax

    return jax.process_index()


def allgather_host_bytes(payload: bytes,
                         site: str = "dist.allgather_bytes") -> list:
    """All-gathers one opaque byte string per process (vocab unification for
    sharded ingestion). Two rounds over the device collective: lengths first,
    then the max-padded payloads — the multi-host analog of the driver
    collecting every executor's dictionary. Degraded (peer lost): returns
    only this process's payload."""
    import numpy as np

    if process_count() == 1:
        return [payload]
    from delphi_tpu.parallel.dist_resilience import guarded_collective

    def _gather():
        from jax.experimental import multihost_utils
        length = np.asarray([len(payload)], dtype=np.int32)
        lengths = np.asarray(
            multihost_utils.process_allgather(length)).reshape(-1)
        max_len = int(lengths.max())
        padded = np.zeros(max_len, dtype=np.uint8)
        padded[:len(payload)] = np.frombuffer(payload, dtype=np.uint8)
        gathered = np.asarray(multihost_utils.process_allgather(padded))
        return [gathered[i, :int(lengths[i])].tobytes()
                for i in range(len(lengths))]

    return guarded_collective(site, _gather, fallback=lambda: [payload])


def allgather_bytes_or_none(payload: bytes, site: str):
    """:func:`allgather_host_bytes`, but a degraded gather (fewer payloads
    than live processes — a peer was declared lost mid-collective) returns
    ``None`` instead of silently shrinking to the local payload. The
    replicated-pipeline shard merges (parallel/rowshard.py) need the
    distinction: a partial merge of per-shard phase outputs would be a
    silently-wrong lower bound, so on ``None`` the caller recomputes its
    full range locally — exact, just not parallel."""
    world = process_count()
    if world == 1:
        return [payload]
    gathered = allgather_host_bytes(payload, site=site)
    if len(gathered) != world:
        return None
    return gathered


def allgather_sum(arr):
    """Elementwise sum of a small numeric array across processes (global
    counts from per-shard counts). Identity when single-process or after
    a rank-loss degrade (the local shard's counts stand alone)."""
    import numpy as np

    arr = np.asarray(arr)
    if process_count() == 1:
        return arr
    from delphi_tpu.parallel.dist_resilience import guarded_collective

    def _gather():
        from jax.experimental import multihost_utils
        return np.asarray(multihost_utils.process_allgather(arr)).sum(axis=0)

    return guarded_collective("dist.allgather_sum", _gather,
                              fallback=lambda: arr)


def allgather_any(mask):
    """Elementwise logical OR of a small bool array across processes
    (global presence masks from per-shard masks)."""
    import numpy as np

    mask = np.asarray(mask, dtype=bool)
    if process_count() == 1:
        return mask
    from delphi_tpu.parallel.dist_resilience import guarded_collective

    def _gather():
        from jax.experimental import multihost_utils
        return np.asarray(
            multihost_utils.process_allgather(mask)).any(axis=0)

    return guarded_collective("dist.allgather_any", _gather,
                              fallback=lambda: mask)


def allgather_max(arr):
    """Elementwise max of a small numeric array across processes."""
    import numpy as np

    arr = np.asarray(arr)
    if process_count() == 1:
        return arr
    from delphi_tpu.parallel.dist_resilience import guarded_collective

    def _gather():
        from jax.experimental import multihost_utils
        return np.asarray(multihost_utils.process_allgather(arr)).max(axis=0)

    return guarded_collective("dist.allgather_max", _gather,
                              fallback=lambda: arr)


def allgather_pickled(obj, site: str = "dist.allgather_bytes") -> list:
    """All-gathers one picklable object per process (training-sample frames
    and trained models in the process-local pipeline). Returns the P
    objects in process order on every process; just ``[obj]`` after a
    rank-loss degrade. ``site`` lets high-level callers label their seam
    (the report aggregation passes ``report.gather``)."""
    import pickle

    return [pickle.loads(b)
            for b in allgather_host_bytes(pickle.dumps(obj), site=site)]
