"""Multi-host substrate: `jax.distributed` wiring over DCN.

The reference scales out through Spark's driver/executor RPC + shuffle
service (SURVEY.md §2.3); the TPU-native equivalent is one JAX process per
host joined through `jax.distributed.initialize`, after which
`jax.devices()` spans every host's chips and the existing mesh/shard_map
programs run their psums over ICI within a slice and DCN across slices —
no code changes above this layer.

Opt-in via environment (mirrors how launchers like GKE/SLURM inject rank
info):

    DELPHI_COORDINATOR=<host:port>   enables multi-host init (required)
    DELPHI_NUM_PROCESSES=<n>         optional when the launcher provides it
    DELPHI_PROCESS_ID=<i>            optional when the launcher provides it

Single-process runs (no DELPHI_COORDINATOR) are a no-op.
"""

import os

from delphi_tpu.utils import setup_logger

_logger = setup_logger()

_initialized = False


def maybe_initialize_distributed() -> bool:
    """Idempotently joins the multi-host cluster when DELPHI_COORDINATOR is
    set. Must run before the first backend touch (jax.devices()); callers
    in this package invoke it from mesh construction and the batch entry
    point. Returns True when running multi-host."""
    global _initialized
    coordinator = os.environ.get("DELPHI_COORDINATOR", "")
    if not coordinator:
        return False
    if _initialized:
        return True

    import jax

    kwargs = {"coordinator_address": coordinator}
    num = os.environ.get("DELPHI_NUM_PROCESSES", "")
    pid = os.environ.get("DELPHI_PROCESS_ID", "")
    if num:
        kwargs["num_processes"] = int(num)
    if pid:
        kwargs["process_id"] = int(pid)
    jax.distributed.initialize(**kwargs)
    _initialized = True
    _logger.info(
        f"jax.distributed initialized: process {jax.process_index()} of "
        f"{jax.process_count()}, {len(jax.devices())} global devices")
    return True


def process_count() -> int:
    """Number of processes in the cluster. Every collective in this module
    routes its single-process short-circuit through here (rather than
    calling ``jax.process_count()`` inline) so tests can fake a multi-host
    topology by monkeypatching one function."""
    import jax

    return jax.process_count()


def process_index() -> int:
    """This process's rank; the companion of :func:`process_count`."""
    import jax

    return jax.process_index()


def allgather_host_bytes(payload: bytes) -> list:
    """All-gathers one opaque byte string per process (vocab unification for
    sharded ingestion). Two rounds over the device collective: lengths first,
    then the max-padded payloads — the multi-host analog of the driver
    collecting every executor's dictionary."""
    import numpy as np
    from jax.experimental import multihost_utils

    if process_count() == 1:
        return [payload]
    length = np.asarray([len(payload)], dtype=np.int32)
    lengths = np.asarray(
        multihost_utils.process_allgather(length)).reshape(-1)
    max_len = int(lengths.max())
    padded = np.zeros(max_len, dtype=np.uint8)
    padded[:len(payload)] = np.frombuffer(payload, dtype=np.uint8)
    gathered = np.asarray(multihost_utils.process_allgather(padded))
    return [gathered[i, :int(lengths[i])].tobytes()
            for i in range(len(lengths))]



def allgather_sum(arr):
    """Elementwise sum of a small numeric array across processes (global
    counts from per-shard counts). Identity when single-process."""
    import numpy as np

    arr = np.asarray(arr)
    if process_count() == 1:
        return arr
    from jax.experimental import multihost_utils
    return np.asarray(multihost_utils.process_allgather(arr)).sum(axis=0)


def allgather_any(mask):
    """Elementwise logical OR of a small bool array across processes
    (global presence masks from per-shard masks)."""
    import numpy as np

    mask = np.asarray(mask, dtype=bool)
    if process_count() == 1:
        return mask
    from jax.experimental import multihost_utils
    return np.asarray(
        multihost_utils.process_allgather(mask)).any(axis=0)


def allgather_max(arr):
    """Elementwise max of a small numeric array across processes."""
    import numpy as np

    arr = np.asarray(arr)
    if process_count() == 1:
        return arr
    from jax.experimental import multihost_utils
    return np.asarray(multihost_utils.process_allgather(arr)).max(axis=0)


def allgather_pickled(obj) -> list:
    """All-gathers one picklable object per process (training-sample frames
    and trained models in the process-local pipeline). Returns the P
    objects in process order on every process."""
    import pickle

    return [pickle.loads(b)
            for b in allgather_host_bytes(pickle.dumps(obj))]
