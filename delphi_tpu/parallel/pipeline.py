"""Bounded producer/consumer pipelining for the repair pipeline's
host-prep / device-train overlap.

The training phases alternate host-side featurization (pandas/numpy: decode
the training sample, fit-encode features, bin/pad fold tensors) with device
launches (CV chunks, boosting chunks). Sequentially the device idles during
every prep and the host idles during every launch; :func:`run_pipelined`
overlaps them with ONE background prepare thread feeding a bounded queue
while the calling thread keeps consuming in order.

Determinism contract — results must be BIT-IDENTICAL with the pipeline on
or off, so the shape is deliberately conservative:

- ``prepare`` runs in item order on the single producer thread (no
  reordering, no multi-thread fan-out);
- ``consume`` runs in item order on the CALLING thread (device dispatch
  order, logging order and model-side effects are exactly the sequential
  loop's);
- an exception from ``prepare(k)`` or ``consume(k)`` surfaces at the same
  item index it would have sequentially — results prepared ahead of a
  failure are discarded, never consumed.

``prepare`` must not depend on side effects of later ``consume`` calls
(every call site here preps from inputs fixed before the loop starts).

The DISABLED path is a plain sequential loop: no queue, no thread —
``threading.active_count()`` is untouched. Toggle with ``DELPHI_PIPELINE``
(1/0) or the ``repair.pipeline.enabled`` session config; the default
(``auto``) enables overlap only when the device is not the host CPU, where
producer and consumer would fight for the same cores.
"""

import os
import queue
import threading
import time
from typing import Any, Callable, List, Sequence

from delphi_tpu.observability import counter_inc, histogram_observe
from delphi_tpu.utils import setup_logger

_logger = setup_logger()

_TRUTHY = ("1", "true", "on", "yes")
_FALSY = ("0", "false", "off", "no")

# How many items the producer may run ahead of the consumer. 2 is enough to
# hide one prep behind one launch; more only grows peak host memory (each
# queued slot holds a full prepared training set).
_DEFAULT_DEPTH = 2


def _flag_state() -> Any:
    """Tri-state toggle: True/False when forced, None for auto.
    DELPHI_PIPELINE beats the repair.pipeline.enabled session config."""
    raw = os.environ.get("DELPHI_PIPELINE")
    if raw is None:
        try:
            from delphi_tpu.session import get_session
            raw = get_session().conf.get("repair.pipeline.enabled")
        except Exception:
            raw = None
    if raw is None:
        return None
    v = str(raw).strip().lower()
    if v in _TRUTHY:
        return True
    if v in _FALSY:
        return False
    return None


def enabled() -> bool:
    """Whether prep/launch overlap is on (see module docstring)."""
    state = _flag_state()
    if state is not None:
        return state
    try:
        import jax
        return jax.default_backend() != "cpu"
    except Exception:
        return False


def run_pipelined(items: Sequence[Any],
                  prepare: Callable[[Any], Any],
                  consume: Callable[[Any, Any], Any],
                  depth: int = _DEFAULT_DEPTH) -> List[Any]:
    """Runs ``consume(item, prepare(item))`` over ``items``, overlapping
    ``prepare`` of the next items with ``consume`` of the current one.
    Returns the list of ``consume`` results, in item order."""
    from delphi_tpu.parallel.resilience import maybe_abort

    items = list(items)
    if len(items) <= 1 or not enabled():
        # the sequential loop IS the disabled path: zero threads, zero queues
        out = []
        for it in items:
            maybe_abort()
            out.append(consume(it, prepare(it)))
        return out

    counter_inc("pipeline.runs")
    counter_inc("pipeline.items", len(items))
    stop = threading.Event()
    q: "queue.Queue" = queue.Queue(maxsize=max(1, int(depth)))

    def _producer() -> None:
        for idx, it in enumerate(items):
            if stop.is_set():
                return
            try:
                prep = prepare(it)
            except BaseException as e:
                # delivered (and re-raised) at idx, preserving sequential
                # error order; nothing past a failed prepare ever runs
                q.put((idx, None, e))
                return
            q.put((idx, prep, None))

    producer = threading.Thread(target=_producer, daemon=True,
                                name="delphi-pipeline-prepare")
    producer.start()
    results: List[Any] = []
    try:
        for _ in range(len(items)):
            # watchdog checkpoint-and-abort: stop dispatching queued work
            # as soon as an abort is armed (prepared-ahead items discard)
            maybe_abort()
            t0 = time.perf_counter()
            idx, prep, err = q.get()
            histogram_observe("pipeline.consumer_wait_seconds",
                              time.perf_counter() - t0)
            if err is not None:
                raise err
            results.append(consume(items[idx], prep))
        return results
    finally:
        stop.set()
        # unblock a producer parked on a full queue, then wait for it to
        # exit so no prepare thread outlives its call
        while producer.is_alive():
            try:
                q.get_nowait()
            except queue.Empty:
                pass
            producer.join(timeout=0.05)
