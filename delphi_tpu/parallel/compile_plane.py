"""Compile-and-dispatch plane: persistent XLA compile cache + AOT prewarm.

Compilation dominates small-table TPU runs: every padded GBDT shape variant
pays a full XLA compile the first time it launches, serialized against the
pipeline. This module takes that cost off the critical path twice over:

1. **Persistent compilation cache** — ``DELPHI_COMPILE_CACHE_DIR`` (env) or
   ``repair.compile.cache_dir`` (session config) points
   ``jax_compilation_cache_dir`` at a durable directory (layered over the
   fingerprinted default the package picks at import, see
   ``delphi_tpu/__init__.py``), and jax.monitoring cache events are forwarded
   into the run's metrics registry as ``compile_cache.hits`` /
   ``compile_cache.misses`` / ``compile_cache.requests`` counters plus
   retrieval/saved-time histograms, so the run report shows exactly how much
   compile time the cache returned. ``DELPHI_COMPILE_CACHE_MIN_S`` /
   ``repair.compile.min_compile_secs`` lowers the persistence threshold
   (the smoke bench sets 0 so even sub-second CPU compiles persist).

2. **AOT shape-grid prewarm** — the GBDT training shapes are fully
   enumerable before training starts: power-of-two/2048-step row targets
   (`train_row_target`), 8-multiple feature pads, objective/class buckets
   ({binary, multiclass×{4,8}, regression}), CV slab widths (`_CV_INSTANCE_CAP`
   slices), and per-(depth, rounds) config-group widths from the search grid.
   :func:`maybe_start_prewarm` derives the reachable variants from the
   validated input table and lowers+compiles them on ONE background daemon
   thread while ingest/detect still run, so the train phase starts against a
   warm executable cache. The thread shuts down on the first error (a wrong
   plan must not keep burning compile threads) and always honors
   :meth:`PrewarmHandle.stop`.

Everything here is observability-grade: failures log and degrade, never
propagate into the run.
"""

import os
import threading
import time
from typing import Any, Dict, List, Optional

from delphi_tpu.utils import setup_logger

_logger = setup_logger()

_TRUTHY = ("1", "true", "on", "yes")
_FALSY = ("0", "false", "off", "no")

# jax.monitoring event name -> metrics-registry counter. The names are
# jax-internal but stable across the 0.4.x line; unknown events no-op.
_EVENT_COUNTERS = {
    "/jax/compilation_cache/cache_hits": "compile_cache.hits",
    "/jax/compilation_cache/cache_misses": "compile_cache.misses",
    "/jax/compilation_cache/compile_requests_use_cache":
        "compile_cache.requests",
}
_DURATION_HISTOGRAMS = {
    "/jax/compilation_cache/cache_retrieval_time_sec":
        "compile_cache.retrieval_seconds",
    "/jax/compilation_cache/compile_time_saved_sec":
        "compile_cache.saved_seconds",
}

_listener_lock = threading.Lock()
_listeners_installed = False
_configured_dir: Optional[str] = None


def _conf(key: str) -> Optional[str]:
    try:
        from delphi_tpu.session import get_session
        raw = get_session().conf.get(key)
        return str(raw) if raw is not None and str(raw).strip() else None
    except Exception:
        return None


# ---------------------------------------------------------------------------
# Persistent cache wiring + telemetry
# ---------------------------------------------------------------------------

def configure_cache() -> Optional[str]:
    """Applies the run-level compile-cache overrides on top of the
    import-time default: cache directory (env beats session config) and the
    minimum-compile-time persistence threshold. Returns the effective cache
    directory (None when persistent caching is off entirely)."""
    global _configured_dir
    try:
        import jax
    except Exception:
        return None
    try:
        current = jax.config.jax_compilation_cache_dir
    except Exception:
        current = None
    target = os.environ.get("DELPHI_COMPILE_CACHE_DIR") \
        or _conf("repair.compile.cache_dir")
    if target:
        target = os.path.abspath(os.path.expanduser(str(target)))
        if target != current:
            try:
                os.makedirs(target, exist_ok=True)
                jax.config.update("jax_compilation_cache_dir", target)
                # jax binds its persistent-cache object to the directory
                # configured at FIRST use and ignores later config updates;
                # reset so the run-level override genuinely re-points disk
                # reads/writes
                try:
                    from jax._src import compilation_cache as _cc
                    _cc.reset_cache()
                except Exception:
                    pass
                _logger.info(f"persistent compile cache: {target}")
                current = target
            except Exception as e:
                _logger.warning(
                    f"cannot use compile cache dir {target}: {e}")
    min_s = os.environ.get("DELPHI_COMPILE_CACHE_MIN_S")
    if min_s is None or not str(min_s).strip():
        min_s = _conf("repair.compile.min_compile_secs")
    if min_s is not None:
        try:
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              float(min_s))
        except Exception as e:
            _logger.warning(
                f"invalid compile-cache min-seconds {min_s!r}: {e}")
    _configured_dir = current
    return current


def install_cache_listeners() -> None:
    """Forwards jax.monitoring compilation-cache events into the ACTIVE
    run's metrics registry. Installed once per process (jax offers no
    unregister), the forwarding closures read the current recorder at fire
    time — runs without a recorder cost one dict probe per event."""
    global _listeners_installed
    with _listener_lock:
        if _listeners_installed:
            return
        _listeners_installed = True
    try:
        from jax import monitoring

        def _on_event(event: str, **kw: Any) -> None:
            name = _EVENT_COUNTERS.get(event)
            if name is None:
                return
            from delphi_tpu.observability import spans
            rec = spans._current
            if rec is not None:
                rec.registry.inc(name)

        def _on_duration(event: str, duration: float, **kw: Any) -> None:
            name = _DURATION_HISTOGRAMS.get(event)
            if name is None:
                return
            from delphi_tpu.observability import spans
            rec = spans._current
            if rec is not None:
                rec.registry.observe(name, duration)

        monitoring.register_event_listener(_on_event)
        monitoring.register_event_duration_secs_listener(_on_duration)
    except Exception as e:
        _logger.debug(f"compile-cache listeners unavailable: {e}")


def record_cache_dir_stats() -> None:
    """Snapshots the cache directory's entry count and total bytes into the
    active registry (``compile_cache.entries`` / ``compile_cache.dir_bytes``
    gauges) — jax emits no size events, so the plane walks the directory.
    No-op (and no disk walk) without an active recorder."""
    from delphi_tpu.observability import spans
    if spans._current is None:
        return
    d = _configured_dir
    if d is None:
        try:
            import jax
            d = jax.config.jax_compilation_cache_dir
        except Exception:
            d = None
    if not d or not os.path.isdir(d):
        return
    total = 0
    entries = 0
    try:
        with os.scandir(d) as it:
            for entry in it:
                if entry.is_file(follow_symlinks=False):
                    entries += 1
                    total += entry.stat(follow_symlinks=False).st_size
    except OSError:
        return
    from delphi_tpu.observability import gauge_set
    gauge_set("compile_cache.entries", entries)
    gauge_set("compile_cache.dir_bytes", total)


# ---------------------------------------------------------------------------
# AOT shape-grid prewarm
# ---------------------------------------------------------------------------

class PrewarmHandle:
    """Owns the background prewarm thread; ``stop()`` is safe to call any
    number of times and after natural completion."""

    def __init__(self) -> None:
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.error: Optional[BaseException] = None
        self.compiled = 0
        self.planned = 0

    @property
    def alive(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def stop(self, timeout: Optional[float] = 5.0) -> None:
        """Signals the worker to stop after its in-flight compile and
        optionally waits for it. The thread is a daemon: a worker stuck
        inside one XLA compile past ``timeout`` cannot block the run."""
        self._stop.set()
        t = self._thread
        if t is not None and timeout:
            t.join(timeout)


def _prewarm_worker(handle: PrewarmHandle,
                    variants: List[Dict[str, Any]]) -> None:
    from delphi_tpu.observability import counter_inc, histogram_observe
    for v in variants:
        if handle._stop.is_set():
            break
        t0 = time.perf_counter()
        try:
            from delphi_tpu.models.gbdt import aot_compile_cv_chunk
            aot_compile_cv_chunk(**v)
        except BaseException as e:
            # shutdown on first error: a variant that won't lower means the
            # plan disagrees with the kernels (shape drift, backend hiccup)
            # — record it and leave the real shapes to plain JIT
            handle.error = e
            counter_inc("compile_plane.prewarm_errors")
            try:
                from delphi_tpu.parallel.resilience import note_fault
                note_fault(e, "compile.prewarm")
            except Exception:  # taxonomy is telemetry, never fatal here
                pass
            _logger.warning(
                f"AOT prewarm stopped on {v}: {type(e).__name__}: {e}")
            break
        handle.compiled += 1
        counter_inc("compile_plane.prewarmed")
        histogram_observe("compile_plane.prewarm_seconds",
                          time.perf_counter() - t0)
    record_cache_dir_stats()


def start_prewarm(variants: List[Dict[str, Any]]) -> PrewarmHandle:
    handle = PrewarmHandle()
    handle.planned = len(variants)
    if variants:
        t = threading.Thread(target=_prewarm_worker,
                             args=(handle, list(variants)),
                             daemon=True, name="delphi-aot-prewarm")
        handle._thread = t
        t.start()
    return handle


def prewarm_enabled() -> bool:
    """``DELPHI_PREWARM`` env / ``repair.compile.prewarm`` config; the auto
    default prewarns only off-host devices — on the CPU backend the compile
    threads would steal the very cores the pipeline computes on."""
    raw = os.environ.get("DELPHI_PREWARM")
    if raw is None:
        raw = _conf("repair.compile.prewarm")
    if raw is not None:
        v = str(raw).strip().lower()
        if v in _TRUTHY:
            return True
        if v in _FALSY:
            return False
    try:
        import jax
        return jax.default_backend() != "cpu"
    except Exception:
        return False


def _prewarm_budget() -> int:
    raw = os.environ.get("DELPHI_PREWARM_BUDGET") \
        or _conf("repair.compile.prewarm_budget")
    try:
        return max(0, int(raw)) if raw is not None else 32
    except (TypeError, ValueError):
        _logger.warning(f"invalid prewarm budget {raw!r}; using 32")
        return 32


def plan_prewarm_variants(table: Any, continuous_columns: List[str],
                          row_id: str, targets: Optional[List[str]],
                          max_training_rows: int,
                          opts: Dict[str, str]) -> List[Dict[str, Any]]:
    """Enumerates the padded CV-chunk shape variants phase 2 can reach,
    from facts that are static once the input table is validated: row/
    feature pad targets, per-column objective/class buckets, the trimmed
    search grid's (depth, rounds) config groups, and the slab widths the
    batched search will stack. Mesh lowering is not prewarmed yet — with an
    active mesh the plan is empty."""
    import jax

    from delphi_tpu import train as _train
    from delphi_tpu.models import gbdt as _gbdt
    from delphi_tpu.parallel.mesh import get_active_mesh
    from delphi_tpu.utils import get_option_value

    if get_active_mesh() is not None:
        return []
    cpu = jax.default_backend() == "cpu"
    n_rows = int(table.n_rows)
    columns = [c for c in table.column_names if c != row_id]
    if targets:
        wanted = set(targets)
        columns = [c for c in columns if c in wanted]
    domain = table.domain_stats()
    continuous = set(continuous_columns)

    from delphi_tpu.parallel import planner

    n_splits = int(get_option_value(opts, *_train._opt_n_splits))
    max_evals = int(get_option_value(opts, *_train._opt_max_evals))
    n_train = max(1, min(n_rows, int(max_training_rows)))
    if n_train < n_splits * 2:
        return []  # no CV search at this size, nothing to warm

    # Plan-derived grid: when a persisted launch plan exists for this
    # table fingerprint, prewarm EXACTLY the (shape, width) variants its
    # gbdt.cv launches will request — no heuristics, no wasted compiles.
    stored = planner.stored_launch_shapes(
        planner.current_fingerprint(), "gbdt.cv")
    if stored:
        variants = []
        seen = set()
        for shape, _padded, width in stored:
            try:
                (depth, rounds, s_n_pad, s_d_pad, s_n_bins, objective, k,
                 n_cfg) = shape
            except ValueError:
                continue  # stored by an older layout; fall back below
            for chunk in sorted(set(planner.round_chunks(
                    int(rounds), _gbdt._CHUNK_ROUNDS))):
                vkey = (chunk, int(depth), objective, int(k), int(width),
                        int(n_cfg), int(s_n_pad), int(s_d_pad))
                if vkey in seen:
                    continue
                seen.add(vkey)
                variants.append(dict(
                    chunk=chunk, depth=int(depth), n_bins=int(s_n_bins),
                    n_nodes=1 << int(depth), objective=objective, k=int(k),
                    width=int(width), n_cfg=int(n_cfg), n_pad=int(s_n_pad),
                    d_pad=int(s_d_pad)))
        if variants:
            budget = _prewarm_budget()
            if len(variants) > budget:
                variants = variants[:budget]
            return variants
    n_pad = _gbdt.train_row_target(n_train, None)
    # feature estimate: one feature column per non-target attribute (the
    # compact GBDT design); a miss only wastes one warmed variant
    n_feat = max(1, len(table.column_names) - 2)
    d_pad = max(8, -(-n_feat // 8) * 8)
    n_bins = 64  # max_bin caps at 63 (gbdt), binner width is max_bin + 1

    # bucket the targets exactly like the batched search groups them:
    # (objective, class bucket, trimmed-grid signature)
    buckets: Dict[tuple, int] = {}
    for c in columns:
        is_discrete = c not in continuous
        if is_discrete:
            k_real = int(domain.get(c, 0))
            if k_real <= 1:
                continue
            num_class = k_real
            if k_real <= 2:
                objective, k = "binary", 1
            elif k_real <= _gbdt.MAX_MULTICLASS:
                objective = "multiclass"
                k = next(b for b in (4, 8, 16, 24, _gbdt.MAX_MULTICLASS)
                         if b >= k_real)
            else:
                continue  # routed to the logistic head, not GBDT
        else:
            objective, k, num_class = "regression", 1, 0
        if not _gbdt.gbdt_supported(is_discrete, num_class):
            continue
        grid = _train._trimmed_grid(is_discrete, num_class, max_evals,
                                    opts, cpu)
        if len(grid) <= 1:
            continue  # single-config grids skip CV entirely
        sig = tuple(tuple(sorted(cfg.items())) for cfg in grid)
        key = (objective, k, sig)
        buckets[key] = buckets.get(key, 0) + 1

    variants: List[Dict[str, Any]] = []
    seen = set()
    for (objective, k, sig), n_targets in buckets.items():
        grid = [dict(s) for s in sig]
        groups: Dict[tuple, int] = {}
        for cfg in grid:
            depth = int(cfg.get("max_depth", 7))
            rounds = _gbdt._cfg_rounds_for(cfg, objective, k)
            groups[(depth, rounds)] = groups.get((depth, rounds), 0) + 1
        # slab widths the search will launch: derived from the SAME
        # planner policy gbdt_cv_grid_search_multi uses (single targets
        # keep their exact fold count, multi-target slabs pad to powers of
        # two under the instance cap), so the grid cannot drift from the
        # real dispatch
        total = n_targets * n_splits
        cap = planner.cv_instance_cap(default=_gbdt._CV_INSTANCE_CAP)
        widths = set(planner.plan_cv_slab_widths(
            total, cap, single_target=n_targets == 1))
        for (depth, _rounds), n_cfg in groups.items():
            for width in sorted(widths):
                vkey = (depth, objective, k, width, n_cfg)
                if vkey in seen:
                    continue
                seen.add(vkey)
                variants.append(dict(
                    chunk=_gbdt._CHUNK_ROUNDS, depth=depth, n_bins=n_bins,
                    n_nodes=1 << depth, objective=objective, k=k,
                    width=width, n_cfg=n_cfg, n_pad=n_pad, d_pad=d_pad))

    budget = _prewarm_budget()
    if len(variants) > budget:
        _logger.info(
            f"AOT prewarm plan truncated to budget: {budget} of "
            f"{len(variants)} variants (DELPHI_PREWARM_BUDGET raises it)")
        variants = variants[:budget]
    return variants


def maybe_start_prewarm(table: Any, continuous_columns: List[str],
                        row_id: str, targets: Optional[List[str]],
                        max_training_rows: int,
                        opts: Dict[str, str]) -> Optional[PrewarmHandle]:
    """Run-start hook: applies the cache config, installs the cache-event
    listeners, and (when prewarm is enabled and applicable) kicks off the
    background AOT compile of the planned shape grid. Never raises."""
    try:
        configure_cache()
        install_cache_listeners()
        if not prewarm_enabled():
            return None
        variants = plan_prewarm_variants(
            table, continuous_columns, row_id, targets,
            max_training_rows, opts)
        if not variants:
            return None
        from delphi_tpu.observability import gauge_set
        gauge_set("compile_plane.prewarm_planned", len(variants))
        _logger.info(
            f"AOT prewarm: compiling {len(variants)} shape variants on a "
            "background thread")
        return start_prewarm(variants)
    except Exception as e:
        _logger.warning(f"AOT prewarm unavailable: {e}")
        return None
