"""Distributed resilience plane: bounded collectives, rank liveness, and
coordinated elastic degrade.

PR 7's resilience plane is strictly single-process: it guards device
launches, not cross-rank interactions. On a multi-host cluster every
``allgather_*`` in :mod:`~delphi_tpu.parallel.distributed` is an unbounded
blocking call, so one wedged or dead rank hangs every healthy rank forever
— including the report-aggregation collective at ``stop_recording``, which
then silently loses the whole run report. This module extends the plane
across ranks:

* :func:`guarded_collective` — the seam every host collective routes
  through. The collective body runs on a watchdog thread under a
  configurable deadline (``DELPHI_COLLECTIVE_TIMEOUT_S`` /
  ``repair.collective.timeout_s``, default 120 s, ``0`` disables); on
  expiry the fault is classified as ``rank_loss`` and the caller degrades
  deterministically through its ``fallback`` instead of hanging.
  Collectives are never retried: a failed collective cannot be re-entered
  unilaterally (the peers may already have moved on), so ANY classified
  cross-rank failure degrades immediately — the cluster-scope analog of
  the PR 7 shrink→evict→CPU-latch ladder is timeout→latch-single-host.
* **Rank heartbeat / membership** — :func:`ensure_membership` piggybacks a
  cheap rank-id all-gather on the guarded seam at deterministic sync
  points (after ``jax.distributed`` init and before report aggregation),
  so ranks agree on who is alive before entering a sharded phase.
  Heartbeat collectives run ONLY at such sync points: a background-thread
  collective would deadlock the cluster (collectives must be entered by
  every rank in the same order), so only the local **liveness file**
  toucher (``DELPHI_LIVENESS_DIR`` / ``repair.liveness.dir``, period
  ``DELPHI_HEARTBEAT_S``) runs on a thread — pure local I/O. After a
  collective timeout the liveness files diagnose each peer: a stale file
  means the process died, a fresh one means it is alive but stalled, no
  file means unknown.
* **Coordinated degrade** — :func:`declare_rank_lost` counts the loss
  (``resilience.dist.*``), stamps the provenance ledger, writes a
  ``rank_loss.json`` marker next to the phase checkpoints
  (``DELPHI_CHECKPOINT_DIR``: the last completed phase's checkpoint is the
  consistent barrier a restarted cluster resumes from), and latches
  **single-host execution** for the remainder of the run: every later
  collective short-circuits to its local fallback and
  :func:`~delphi_tpu.parallel.mesh.get_active_mesh` re-enters on the
  shrunk, process-local mesh (``resilience.dist.mesh_shrunk``).

All clocks and waits are module-level seams (``_monotonic``, ``_wall``,
``_wait``) so tier-1 tests drive the deadline logic against a fake clock.
"""

import logging
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from delphi_tpu.observability import counter_inc

_logger = logging.getLogger(__name__)

# injectable time/wait seams (fake-clock tests)
_monotonic = time.monotonic
_wall = time.time


def _wait(event: threading.Event, timeout_s: float) -> bool:
    """Waits for the collective worker; True when it finished in time.
    Module-level seam so tests can force a timeout without sleeping."""
    return event.wait(timeout_s)


# -- configuration -----------------------------------------------------------

def collective_timeout_s() -> float:
    """Watchdog deadline for one host collective in seconds:
    ``DELPHI_COLLECTIVE_TIMEOUT_S`` / ``repair.collective.timeout_s``
    (default 120; generous because phase-2 training gathers real frames).
    ``0`` disables the watchdog and restores unbounded blocking."""
    from delphi_tpu.parallel.resilience import _env_or_conf
    return _env_or_conf("DELPHI_COLLECTIVE_TIMEOUT_S",
                        "repair.collective.timeout_s", float, 120.0)


def heartbeat_interval_s() -> float:
    """Liveness-file touch period in seconds: ``DELPHI_HEARTBEAT_S`` /
    ``repair.heartbeat.interval_s`` (default 15; ``0`` disables the
    toucher thread). A peer's file older than 3x this is considered
    dead."""
    from delphi_tpu.parallel.resilience import _env_or_conf
    return _env_or_conf("DELPHI_HEARTBEAT_S",
                        "repair.heartbeat.interval_s", float, 15.0)


def liveness_dir() -> Optional[str]:
    """Shared directory for per-rank liveness files
    (``DELPHI_LIVENESS_DIR`` / ``repair.liveness.dir``), or None when the
    liveness seam is off (the default). Must be visible to every rank
    (shared filesystem, or localhost benches) for cross-rank diagnosis."""
    from delphi_tpu.parallel.resilience import _env_or_conf
    d = _env_or_conf("DELPHI_LIVENESS_DIR", "repair.liveness.dir", str, "")
    return d.strip() or None


# -- distributed degrade state -----------------------------------------------

_lock = threading.Lock()
_state: Dict[str, Any] = {
    "latched": False, "latch_site": None, "reason": None,
    "lost": set(), "alive": None, "expected": None,
    "diagnosis": {}, "aggregation_incomplete": False,
    "mesh_shrunk": False,
}


def single_host_latched() -> bool:
    """True after a rank loss: every collective short-circuits to its
    local fallback and the active mesh shrinks to this process's devices
    for the remainder of the run."""
    return _state["latched"]


def degraded_ranks() -> List[int]:
    """Sorted ranks declared lost so far (empty when healthy)."""
    with _lock:
        return sorted(_state["lost"])


def aggregation_incomplete() -> bool:
    return _state["aggregation_incomplete"]


def mark_aggregation_incomplete() -> None:
    """Report aggregation degraded to this rank's own view (a peer was
    lost before or during the ``report.gather`` collective)."""
    with _lock:
        first = not _state["aggregation_incomplete"]
        _state["aggregation_incomplete"] = True
    if first:
        counter_inc("resilience.dist.aggregation_incomplete")


def note_mesh_shrunk() -> None:
    """mesh.py reports the first re-entry on the shrunk process-local
    mesh (counted once per run)."""
    with _lock:
        first = not _state["mesh_shrunk"]
        _state["mesh_shrunk"] = True
    if first:
        counter_inc("resilience.dist.mesh_shrunk")


def reset_dist_state() -> None:
    """Forgets latches, lost ranks, and membership (tests / benches that
    replay scenarios in one process); stops the liveness toucher."""
    stop_liveness()
    with _lock:
        _state.update(latched=False, latch_site=None, reason=None,
                      lost=set(), alive=None, expected=None,
                      diagnosis={}, aggregation_incomplete=False,
                      mesh_shrunk=False)


def report_section() -> Optional[Dict[str, Any]]:
    """The run report's ``dist`` section, or None for single-process runs
    that never touched the membership protocol (schema v6)."""
    with _lock:
        touched = (_state["latched"] or _state["lost"]
                   or _state["aggregation_incomplete"]
                   or _state["alive"] is not None)
        if not touched:
            return None
        return {
            "expected_ranks": _state["expected"],
            "alive_ranks": (list(_state["alive"])
                            if _state["alive"] is not None else None),
            "degraded_ranks": sorted(_state["lost"]),
            "single_host_latched": bool(_state["latched"]),
            "latch_site": _state["latch_site"],
            "reason": _state["reason"],
            "diagnosis": {str(r): v for r, v in _state["diagnosis"].items()},
            "aggregation_incomplete": bool(_state["aggregation_incomplete"]),
            "mesh_shrunk": bool(_state["mesh_shrunk"]),
        }


# -- liveness files ----------------------------------------------------------
# The primitives below take EXPLICIT paths/directories so any membership
# domain can reuse them: a jax.distributed cluster keys members by rank
# (this module's own env-driven wrappers), and the serve fleet keys them by
# worker id (observability/fleet.py points scan_membership at its fleet
# dir). One file format, one staleness rule, two consumers.

_toucher: Dict[str, Any] = {"thread": None, "stop": None}

_LIVENESS_PREFIX = "rank_"
_LIVENESS_SUFFIX = ".alive"


def member_liveness_path(directory: str, member) -> str:
    """Liveness file for one member (a rank in a cluster, a worker id in
    a serve fleet) under an explicit membership directory."""
    return os.path.join(directory,
                        f"{_LIVENESS_PREFIX}{member}{_LIVENESS_SUFFIX}")


def touch_liveness_file(path: str) -> None:
    """Stamps one liveness file (wall-clock seconds as text — file
    CONTENT, not mtime, so fake-clock tests and clock-skewed hosts read
    one consistent timebase). Best-effort: liveness is evidence, never a
    failure source."""
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(repr(float(_wall())))
        os.replace(tmp, path)
    except Exception as e:  # pragma: no cover - filesystem specific
        _logger.warning(f"liveness touch failed: {e}")


def liveness_file_age_s(path: Optional[str],
                        now: Optional[float] = None) -> Optional[float]:
    """Seconds since the liveness file at ``path`` was stamped, or None
    when the file is absent/unreadable (member never registered, or
    already unregistered)."""
    if not path or not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            stamp = float(f.read().split()[0])
    except Exception:
        return None
    return max(0.0, (now if now is not None else float(_wall())) - stamp)


def diagnose_liveness_file(path: Optional[str], interval_s: float,
                           now: Optional[float] = None) -> str:
    """Membership diagnosis for one liveness file: ``live`` (stamp
    fresher than 3x the heartbeat interval), ``dead`` (stale stamp — the
    member stopped touching it), or ``unknown`` (no file)."""
    age = liveness_file_age_s(path, now=now)
    if age is None:
        return "unknown"
    return "live" if age <= 3.0 * max(interval_s, 0.001) else "dead"


def scan_membership(directory: str, interval_s: float,
                    now: Optional[float] = None) -> Dict[str, Dict[str, Any]]:
    """Scans a membership directory for liveness files and returns
    ``{member_id: {"age_s": float|None, "status": live|dead|unknown}}``.
    The reusable membership reader: the fleet router derives its worker
    ring from this, the same files the cluster's post-timeout peer
    diagnosis reads."""
    members: Dict[str, Dict[str, Any]] = {}
    try:
        entries = os.listdir(directory)
    except OSError:
        return members
    for name in sorted(entries):
        if not (name.startswith(_LIVENESS_PREFIX)
                and name.endswith(_LIVENESS_SUFFIX)):
            continue
        member = name[len(_LIVENESS_PREFIX):-len(_LIVENESS_SUFFIX)]
        path = os.path.join(directory, name)
        members[member] = {
            "age_s": liveness_file_age_s(path, now=now),
            "status": diagnose_liveness_file(path, interval_s, now=now),
        }
    return members


def _liveness_path(rank: int) -> Optional[str]:
    d = liveness_dir()
    return member_liveness_path(d, int(rank)) if d else None


def touch_liveness() -> None:
    """Writes this rank's liveness stamp (see
    :func:`touch_liveness_file`) under ``DELPHI_LIVENESS_DIR``."""
    from delphi_tpu.parallel import distributed as dist
    try:
        path = _liveness_path(dist.process_index())
    except Exception:
        return
    if not path:
        return
    touch_liveness_file(path)


def peer_liveness_age_s(rank: int, now: Optional[float] = None
                        ) -> Optional[float]:
    """Seconds since ``rank`` last touched its liveness file, or None
    when the seam is off / the rank never wrote one."""
    return liveness_file_age_s(_liveness_path(rank), now=now)


def diagnose_peer(rank: int, now: Optional[float] = None) -> str:
    """Post-timeout diagnosis for one peer: ``dead`` (stale liveness
    file — the process stopped touching it), ``stalled`` (fresh file —
    alive but wedged in or before the collective), or ``unknown`` (no
    liveness seam / no file)."""
    age = peer_liveness_age_s(rank, now=now)
    if age is None:
        return "unknown"
    return "stalled" if age <= 3.0 * max(heartbeat_interval_s(), 0.001) \
        else "dead"


def start_liveness() -> bool:
    """Starts the background liveness toucher (local file I/O only — NO
    collectives run off-thread; see module docstring). Idempotent; False
    when the seam is unconfigured or the interval is 0."""
    interval = heartbeat_interval_s()
    if liveness_dir() is None or interval <= 0:
        return False
    touch_liveness()
    with _lock:
        t = _toucher["thread"]
        if t is not None and t.is_alive():
            return True
        stop = threading.Event()
        t = threading.Thread(target=_touch_loop, args=(stop,),
                             daemon=True, name="delphi-liveness")
        _toucher.update(thread=t, stop=stop)
    t.start()
    return True


def _touch_loop(stop: threading.Event) -> None:
    while not stop.wait(max(0.05, heartbeat_interval_s())):
        touch_liveness()


def stop_liveness() -> None:
    with _lock:
        t, stop = _toucher["thread"], _toucher["stop"]
        _toucher.update(thread=None, stop=None)
    if stop is not None:
        stop.set()
    if t is not None and t.is_alive():
        t.join(timeout=1.0)


# -- coordinated degrade -----------------------------------------------------

def _write_loss_marker(site: str, reason: str, lost: List[int],
                       diagnosis: Dict[int, str]) -> None:
    """Marker next to the phase checkpoints: the last completed phase's
    checkpoint (saved by the existing PhaseCheckpointStore machinery at
    every phase boundary) is the consistent barrier a restarted cluster
    resumes from; the marker records why the mesh shrank."""
    from delphi_tpu.parallel import distributed as dist
    from delphi_tpu.parallel import resilience as rz
    directory = rz.checkpoint_dir()
    if not directory:
        return
    from delphi_tpu.parallel import store as dstore
    try:
        dstore.write_json(
            os.path.join(directory, "rank_loss.json"),
            {"site": site, "reason": reason,
             "lost_ranks": sorted(int(r) for r in lost),
             "diagnosis": {str(r): v for r, v in diagnosis.items()},
             "surviving_rank": int(dist.process_index()),
             "wall_time": float(_wall())},
            schema="marker", site="store.checkpoint", root=directory)
    except Exception as e:  # marker is best-effort evidence
        _logger.warning(f"failed to write rank_loss marker: {e}")


def declare_rank_lost(site: str, *, reason: str) -> List[int]:
    """A cross-rank interaction at ``site`` failed or timed out: declare
    every unconfirmed peer lost, diagnose each through the liveness
    files, count the transitions, checkpoint the marker, and latch
    single-host execution. Deterministic: same inputs, same transitions
    — every counter and note below is asserted by the dist-chaos A/B.
    Returns the ranks newly declared lost."""
    from delphi_tpu.parallel import distributed as dist
    from delphi_tpu.parallel import resilience as rz
    me = dist.process_index()
    n = dist.process_count()
    peers = [r for r in range(n) if r != me]
    diagnosis = {r: diagnose_peer(r) for r in peers}
    with _lock:
        new = [r for r in peers if r not in _state["lost"]]
        _state["lost"].update(peers)
        first = not _state["latched"]
        if first:
            _state["latched"] = True
            _state["latch_site"] = site
            _state["reason"] = reason
        _state["diagnosis"].update(diagnosis)
        _state["expected"] = max(int(_state["expected"] or 0), n)
    counter_inc(f"resilience.faults.{rz.KIND_RANK_LOSS}")
    for _ in new:
        counter_inc("resilience.dist.rank_loss")
    if first:
        counter_inc("resilience.dist.single_host_latch")
        rz._stamp_ledger("rank_loss", site, rz.KIND_RANK_LOSS)
        _write_loss_marker(site, reason, new or peers, diagnosis)
        _logger.warning(
            f"{site}: rank(s) {sorted(new or peers)} declared lost "
            f"({reason}); diagnosis {diagnosis} — latching single-host "
            f"execution for the remainder of the run")
    return new


# -- the guarded collective seam ---------------------------------------------

def guarded_collective(site: str, thunk: Callable[[], Any], *,
                       fallback: Optional[Callable[[], Any]] = None,
                       timeout_s: Optional[float] = None) -> Any:
    """Runs one host collective under the distributed resilience plane.

    Single-process: runs ``thunk`` inline (no watchdog, no seam cost
    beyond one ``process_count`` read). After a single-host latch:
    returns ``fallback()`` without touching the collective (the peers
    are gone — entering would hang). Multi-process: the fault-injection
    seam fires on the CALLER thread (an injected ``stall`` wedges this
    rank exactly where a real wedge would), then ``thunk`` runs on a
    daemon watchdog thread bounded by the deadline. On expiry or on any
    classified cross-rank failure the rank degrades via
    :func:`declare_rank_lost` and returns ``fallback()`` — collectives
    are never retried (see module docstring). Unclassifiable errors
    re-raise: program bugs must stay loud."""
    from delphi_tpu.parallel import distributed as dist
    from delphi_tpu.parallel import resilience as rz
    rz.maybe_abort()
    if dist.process_count() <= 1:
        return thunk()
    if single_host_latched():
        if fallback is not None:
            return fallback()
        raise rz.RankLost(
            f"collective at {site} entered after single-host latch "
            f"(lost ranks {degraded_ranks()}) with no local fallback")
    try:
        rz._maybe_inject(site)
    except rz.FaultInjected as exc:
        if rz.classify_fault(exc) == rz.KIND_RANK_LOSS \
                and fallback is not None:
            declare_rank_lost(site, reason=f"injected rank loss: {exc}")
            return fallback()
        raise
    deadline = collective_timeout_s() if timeout_s is None \
        else float(timeout_s)
    if deadline <= 0:
        return thunk()
    out: Dict[str, Any] = {}
    done = threading.Event()

    def _work():
        try:
            out["value"] = thunk()
        except BaseException as e:
            out["error"] = e
        finally:
            done.set()

    t = threading.Thread(target=_work, daemon=True,
                         name=f"delphi-collective-{site}")
    t.start()
    if not _wait(done, deadline):
        # the wedged collective thread is daemonic and leaks by design
        # (it cannot be cancelled) — the whole point is that THIS thread
        # gets to keep making progress
        counter_inc("resilience.dist.collective_timeouts")
        _logger.warning(
            f"{site}: collective timed out after {deadline:.1f}s "
            f"(DELPHI_COLLECTIVE_TIMEOUT_S) — degrading")
        declare_rank_lost(
            site, reason=f"collective timed out after {deadline:.1f}s")
        if fallback is not None:
            return fallback()
        raise rz.RankLost(
            f"collective operation at {site} timed out after "
            f"{deadline:.1f}s waiting for remote ranks")
    if "error" in out:
        exc = out["error"]
        kind = rz.classify_fault(exc)
        if kind is not None and fallback is not None:
            counter_inc(f"resilience.faults.{kind}")
            declare_rank_lost(
                site, reason=f"collective failed "
                f"({kind}): {type(exc).__name__}: {exc}")
            return fallback()
        raise exc
    return out["value"]


# -- rank heartbeat / membership ---------------------------------------------

def ensure_membership(site: str = "dist.heartbeat") -> List[int]:
    """The rank heartbeat: a cheap rank-id all-gather through the guarded
    seam, run at deterministic sync points only (after distributed init,
    before report aggregation — every rank enters in the same order or
    not at all). Touches this rank's liveness file, records the agreed
    membership, and returns the alive ranks; a timeout degrades through
    the standard rank-loss path and returns just this rank."""
    from delphi_tpu.parallel import distributed as dist
    me = int(dist.process_index())
    n = int(dist.process_count())
    touch_liveness()
    if n <= 1 or single_host_latched():
        return [me]

    def _gather():
        import numpy as np
        from jax.experimental import multihost_utils
        return [int(r) for r in np.asarray(
            multihost_utils.process_allgather(
                np.asarray([me], dtype=np.int32))).reshape(-1)]

    alive = guarded_collective(site, _gather, fallback=lambda: [me])
    alive = sorted(set(alive))
    with _lock:
        _state["alive"] = list(alive)
        _state["expected"] = max(int(_state["expected"] or 0), n)
    counter_inc("resilience.dist.heartbeats")
    return alive
