"""Row/group sharding of the REPLICATED repair pipeline (``DELPHI_SHARD``).

Three cross-process modes now coexist and must not be confused:

* **process-local tables** (sharded ingestion): each process holds ONLY its
  rows and the whole pipeline runs off the shards — see
  :mod:`delphi_tpu.parallel.sharded` and docs/source/scaling.rst.
* **the device mesh** (``DELPHI_MESH``): row-sharding across local devices
  inside one process.
* **THIS plane** (``DELPHI_SHARD=1`` on a multi-process cluster): every
  process holds the FULL table — the normal replicated batch path — and
  phase 1–3 analysis work (NULL detection scans, freq/pair counting,
  distinct-pair pruning, conditional entropy, weak-label domain scoring)
  splits across the process mesh by contiguous row span or by whole work
  groups. Partial results merge through the guarded collectives in
  :mod:`delphi_tpu.parallel.distributed` with EXACT algebra only —
  integer count sums, fused-key set unions, disjoint-group ORs — so the
  merged arrays are bit-identical to the single-process computation,
  never an approximation or a lower bound.

Degradation contract (the dist-resilience taxonomy): every merge helper
returns ``None`` when the gather came back degraded — a peer was declared
lost (``resilience.dist.rank_loss``) and the collective plane latched
single-host. The call site then recomputes the FULL range locally — still
exact — and :func:`shard_enabled` reads False for every later phase
(``single_host_latched``), so one rank loss costs at most one phase's
worth of local recompute and the run completes with the same bytes it
would have produced alone.

Determinism: all ranks hold identical replicated inputs, so span math,
greedy owner assignment and the per-phase merge sequence are identical
everywhere — collectives always line up across ranks.
"""

import os
from typing import List, Optional, Sequence, Tuple

from delphi_tpu.observability import counter_inc

_FALSY = frozenset({"", "0", "false", "no", "off"})

# Below this many rows the merge round-trips cost more than the split
# saves; the whole table stays on every rank (exactly the legacy path).
_DEFAULT_MIN_ROWS = 4096


def shard_min_rows() -> int:
    """Row floor under which sharding stays off (``DELPHI_SHARD_MIN_ROWS``,
    default 4096)."""
    try:
        return int(os.environ.get("DELPHI_SHARD_MIN_ROWS", "")
                   or _DEFAULT_MIN_ROWS)
    except ValueError:
        return _DEFAULT_MIN_ROWS


def shard_enabled() -> bool:
    """True when the replicated-pipeline shard plane is live: opted in
    (``DELPHI_SHARD`` truthy — OFF by default, so single-process runs and
    the process-local/mesh modes are byte-for-byte untouched), more than
    one process in the cluster, and the collective plane not degraded to
    single-host by a rank loss."""
    if os.environ.get("DELPHI_SHARD", "").strip().lower() in _FALSY:
        return False
    from delphi_tpu.parallel import dist_resilience as dr
    if dr.single_host_latched():
        return False
    from delphi_tpu.parallel import distributed as dist
    try:
        return dist.process_count() > 1
    except Exception:  # pragma: no cover - backend not initialized
        return False


def world() -> Tuple[int, int]:
    """(rank, world size) of this process."""
    from delphi_tpu.parallel import distributed as dist

    return dist.process_index(), dist.process_count()


def active_span(n_rows: int) -> Optional[Tuple[int, int]]:
    """This rank's contiguous ``[lo, hi)`` row span of an ``n_rows`` table,
    or ``None`` when sharding is off (disabled, single-process, degraded,
    or the table is under the row floor). The split is the standard
    balanced partition — ``lo = r*n//W`` — identical on every rank."""
    if not shard_enabled():
        return None
    n = int(n_rows)
    if n < shard_min_rows():
        return None
    rank, wsize = world()
    if n < wsize * 4:
        # degenerate split (a rank could land an empty span); not worth it
        return None
    lo = rank * n // wsize
    hi = (rank + 1) * n // wsize
    gauge = hi - lo
    counter_inc("shard.spans")
    from delphi_tpu.observability import gauge_set
    gauge_set("shard.rows", gauge)
    return (lo, hi)


def plan_shard_tag() -> Optional[str]:
    """Rank tag folded into launch-plan signatures and store keys
    (``r<rank>of<world>``) when the shard plane is live: per-shard plans
    persist per rank, so a warm rerun replans zero times on EVERY rank;
    when off (the default) the tag is absent and plan signatures stay
    byte-identical to the legacy planner."""
    if not shard_enabled():
        return None
    rank, wsize = world()
    return f"r{rank}of{wsize}"


def assign_owners(sizes: Sequence[int]) -> List[int]:
    """Deterministic greedy LPT owner assignment: items (work groups,
    entropy pair matrices) sorted by descending size, each assigned to the
    least-loaded rank, ties broken by index / lowest rank. All ranks
    derive the identical assignment from the identical replicated
    sizes."""
    rank, wsize = world()
    loads = [0] * wsize
    owners = [0] * len(sizes)
    order = sorted(range(len(sizes)), key=lambda i: (-int(sizes[i]), i))
    for i in order:
        r = min(range(wsize), key=lambda r: (loads[r], r))
        owners[i] = r
        loads[r] += max(int(sizes[i]), 1)
    return owners


def merge_parts(obj, site: str) -> Optional[list]:
    """All ranks' ``obj`` in rank order (pickled byte-gather through the
    guarded collective at ``site``), or ``None`` when the gather came back
    degraded — the caller must then recompute its full range locally
    (exactly; partial merges are never returned). Counts ``shard.merges``
    on success, ``shard.degraded`` on the None path."""
    import pickle

    from delphi_tpu.parallel import distributed as dist

    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    parts = dist.allgather_bytes_or_none(payload, site)
    if parts is None:
        counter_inc("shard.degraded")
        return None
    try:
        out = [pickle.loads(b) for b in parts]
    except Exception:  # pragma: no cover - corrupt peer payload
        counter_inc("shard.degraded")
        return None
    counter_inc("shard.merges")
    return out
