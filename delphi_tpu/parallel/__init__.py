"""Multi-chip parallelism: device meshes, row-sharded kernels, distributed
training steps.

The reference's only parallelism is Spark data parallelism (SURVEY.md §2.3);
here the equivalents are explicit SPMD programs over a
``jax.sharding.Mesh``:

* ``dp`` (rows)   — replaces Spark's executor task parallelism / shuffles;
  frequency counts, entropies and GBDT histograms reduce with ``psum`` over
  ICI instead of shuffling.
* ``tp`` (model)  — shards wide model dimensions (class axis of the
  per-attribute heads), the analog the reference never had.

Multi-host scale-out uses `jax.distributed.initialize` + the same mesh
spanning hosts (collectives ride ICI within a slice, DCN across).
"""

from delphi_tpu.parallel.mesh import make_mesh, shard_rows
