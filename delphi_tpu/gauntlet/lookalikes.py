"""Seeded lookalikes for the absent ``/root/reference`` testdata.

This container (and most CI hosts) does not carry the reference testdata
tree, which used to fail 60+ tests at collection and left ``bench.py``
unrunnable. :func:`materialize_testdata` writes deterministic lookalike
files — same filenames, same shapes, same statistical skeleton as the
reference fixtures — into a local directory, and
``tests/conftest.py`` / ``bench.resolve_testdata()`` point at it when
the real tree is missing (``DELPHI_TESTDATA`` overrides both ways).

The lookalikes are *pinned* by the test suite: the adult table's null
positions, value histograms, FD structure (Relationship -> Sex with two
planted violations at tids 4 and 11), and the repair ground truth in
``adult_clean.csv`` / ``adult_repair.csv`` all satisfy the exact
assertions in tests/test_misc.py, test_model.py, test_table.py,
test_errors.py and test_model_features.py. The hospital table keeps the
reference's 1000x19 shape and FD grammar; flights keeps the raha layout
(wide dirty table + long ``correct_val`` truth). Files that encode
measurements of the *real* datasets (iris/boston RMSE baselines,
hospital error-cell inventories) are deliberately NOT synthesized —
tests that need them skip instead.

Everything is derived from fixed tables or ``numpy.random.RandomState``
streams: two materializations are byte-identical.
"""

import os
import tempfile
from typing import Dict, List

import numpy as np
import pandas as pd

#: files this module can synthesize (relative to the testdata root)
SYNTHESIZED = (
    "adult.csv", "adult_clean.csv", "adult_repair.csv",
    "adult_constraints.txt",
    "hospital.csv", "hospital_constraints.txt",
    "iris.csv",
    "raha/flights.csv", "raha/flights_clean.csv",
)

_MARKER = ".delphi_synth_complete"

ADULT_CONSTRAINTS = (
    't1&EQ(t1.Sex,"Female")&EQ(t1.Relationship,"Husband")\n'
    't1&EQ(t1.Sex,"Male")&EQ(t1.Relationship,"Wife")\n'
)

HOSPITAL_CONSTRAINTS = (
    "t1&t2&EQ(t1.HospitalName,t2.HospitalName)&IQ(t1.ZipCode,t2.ZipCode)\n"
    "t1&t2&EQ(t1.HospitalName,t2.HospitalName)&IQ(t1.City,t2.City)\n"
    "t1&t2&EQ(t1.HospitalName,t2.HospitalName)"
    "&IQ(t1.PhoneNumber,t2.PhoneNumber)\n"
    "t1&t2&EQ(t1.MeasureCode,t2.MeasureCode)&IQ(t1.MeasureName,t2.MeasureName)\n"
    "t1&t2&EQ(t1.ZipCode,t2.ZipCode)&IQ(t1.State,t2.State)\n"
    "t1&t2&EQ(t1.City,t2.City)&IQ(t1.CountyName,t2.CountyName)\n"
)


def adult_tables() -> Dict[str, pd.DataFrame]:
    """The 20-row adult lookalike, its clean version, and the repair
    ground truth. Hand-built (not sampled) because the suite pins it
    cell-by-cell: 7 nulls at fixed positions, Sex histogram 10/7,
    Income 14/4, Relationship->Sex broken only at tids 4 and 11."""
    relationship = ["Husband", "Husband", "Wife", "Wife", "Husband",
                    "Own-child", "Husband", "Husband", "Wife", "Unmarried",
                    "Husband", "Husband", "Husband", "Husband", "Wife",
                    "Own-child", "Husband", "Unmarried", "Husband",
                    "Own-child"]
    sex_clean = ["Male", "Male", "Female", "Female", "Female",
                 "Male", "Male", "Male", "Female", "Female",
                 "Male", "Female", "Male", "Male", "Female",
                 "Male", "Male", "Female", "Male", "Male"]
    age_clean = {"Husband": "31-50", "Wife": "22-30",
                 "Own-child": "18-21", "Unmarried": "22-30"}
    age = [age_clean[r] for r in relationship]
    for t in (4, 10, 16):           # a few older husbands: keeps
        age[t] = ">50"              # Relationship->Age non-deterministic
    education = ["Some-college", "HS-grad", "Bachelors", "HS-grad",
                 "Masters", "HS-grad", "Masters", "Some-college",
                 "Bachelors", "Bachelors", "Masters", "HS-grad",
                 "Some-college", "Bachelors", "HS-grad", "Some-college",
                 "Masters", "HS-grad", "Bachelors", "HS-grad"]
    occupation = ["Exec-managerial", "Craft-repair", "Prof-specialty",
                  "Sales", "Craft-repair", "Student", "Exec-managerial",
                  "Craft-repair", "Prof-specialty", "Sales",
                  "Prof-specialty", "Craft-repair", "Exec-managerial",
                  "Sales", "Prof-specialty", "Student", "Exec-managerial",
                  "Sales", "Exec-managerial", "Student"]
    country = ["United-States"] * 20
    country[9], country[17], country[19] = "India", "India", "Mexico"
    more_than = {0, 6, 10, 13, 16}  # 16 is null in the dirty table
    income = ["MoreThan50K" if t in more_than else "LessThan50K"
              for t in range(20)]

    clean = pd.DataFrame({
        "tid": list(range(20)),
        "Age": age, "Education": education, "Occupation": occupation,
        "Relationship": relationship, "Sex": sex_clean,
        "Country": country, "Income": income,
    })
    dirty = clean.copy()
    null_cells = [(3, "Sex"), (5, "Age"), (5, "Income"), (7, "Sex"),
                  (12, "Age"), (12, "Sex"), (16, "Income")]
    for t, a in null_cells:
        dirty.loc[t, a] = None
    repair = pd.DataFrame(
        [(t, a, clean.loc[t, a]) for t, a in sorted(null_cells)],
        columns=["tid", "attribute", "repaired"])
    return {"adult.csv": dirty, "adult_clean.csv": clean,
            "adult_repair.csv": repair}


def hospital_table(n_hospitals: int = 50, rows_each: int = 20,
                   seed: int = 11) -> pd.DataFrame:
    """1000 x 19(+tid) hospital lookalike: per-hospital FDs
    (name -> city/zip/phone, zip -> state, city -> county,
    measure code -> measure name) with seeded typo violations so the
    reference constraint file detects a non-empty cell set."""
    rng = np.random.RandomState(seed)
    conditions = ["heart attack", "heart failure", "pneumonia",
                  "surgical infection prevention", "children s asthma care"]
    measures = {f"mx-{c[:4].strip()}-{j}": f"measure {c} {j}"
                for c in conditions for j in range(3)}
    mcodes = sorted(measures)
    rows: List[Dict[str, str]] = []
    tid = 0
    for h in range(n_hospitals):
        state = "al" if h % 2 == 0 else "ak"
        zipc = f"{35000 + h:05d}"
        city = f"city{h % 17}"
        base = {
            "ProviderNumber": f"{10000 + h}",
            "HospitalName": f"hospital {h} medical center",
            "Address1": f"{100 + h} main street",
            "Address2": "", "Address3": "",
            "City": city, "State": state, "ZipCode": zipc,
            "CountyName": f"county{h % 17}",
            "PhoneNumber": f"{2050000000 + h * 137:010d}",
            "HospitalType": "acute care hospitals",
            "HospitalOwner": ["government - federal", "proprietary",
                              "voluntary non-profit - private"][h % 3],
            "EmergencyService": "yes" if h % 3 else "no",
        }
        for r in range(rows_each):
            code = mcodes[(h + r) % len(mcodes)]
            cond = conditions[(h + r) % len(conditions)]
            row = dict(base)
            row.update({
                "tid": str(tid),
                "Condition": cond,
                "MeasureCode": code,
                "MeasureName": measures[code],
                "Score": f"{rng.randint(5, 100)}%",
                "Sample": f"{rng.randint(1, 999)} patients",
                "Stateavg": f"{state}_{code}",
            })
            rows.append(row)
            tid += 1
    df = pd.DataFrame(rows)
    df = df[["tid", "ProviderNumber", "HospitalName", "Address1",
             "Address2", "Address3", "City", "State", "ZipCode",
             "CountyName", "PhoneNumber", "HospitalType", "HospitalOwner",
             "EmergencyService", "Condition", "MeasureCode", "MeasureName",
             "Score", "Sample", "Stateavg"]]
    # seeded corruption: FD-violating typos + a few blanks, ~2% of rows
    bad = rng.choice(len(df), size=24, replace=False)
    for k, i in enumerate(sorted(bad)):
        col = ["City", "ZipCode", "PhoneNumber", "MeasureName",
               "State", "CountyName"][k % 6]
        v = str(df.iloc[i, df.columns.get_loc(col)])
        df.iloc[i, df.columns.get_loc(col)] = \
            ("x" + v[1:]) if v else "x"
    blanks = rng.choice(len(df), size=8, replace=False)
    for i in blanks:
        df.iloc[i, df.columns.get_loc("Score")] = np.nan
    return df


def iris_table(seed: int = 5) -> pd.DataFrame:
    """150-row iris lookalike: four numeric columns clustered by species
    (so numeric repairs have signal) plus a handful of planted nulls for
    the CLI chunked-vs-whole repair comparison."""
    rng = np.random.RandomState(seed)
    parts = []
    centers = {
        "setosa": (5.0, 3.4, 1.5, 0.2),
        "versicolor": (5.9, 2.8, 4.3, 1.3),
        "virginica": (6.6, 3.0, 5.6, 2.0),
    }
    for species, (sl, sw, pl, pw) in centers.items():
        parts.append(pd.DataFrame({
            "sepal_length": np.round(rng.normal(sl, 0.3, 50), 1),
            "sepal_width": np.round(rng.normal(sw, 0.3, 50), 1),
            "petal_length": np.round(rng.normal(pl, 0.4, 50), 1),
            "petal_width": np.round(rng.normal(pw, 0.2, 50), 1),
            "species": species,
        }))
    df = pd.concat(parts, ignore_index=True)
    df.insert(0, "tid", range(len(df)))
    for i, col in ((7, "sepal_length"), (31, "sepal_width"),
                   (64, "petal_length"), (88, "petal_width"),
                   (112, "sepal_length"), (140, "petal_width")):
        df.loc[i, col] = np.nan
    return df


def flights_tables(n_rows: int = 2376, seed: int = 3) \
        -> Dict[str, pd.DataFrame]:
    """raha-layout flights lookalike: a wide dirty table keyed by
    ``tuple_id`` where the times are functions of the flight number, plus
    the long-format clean truth (``tuple_id, attribute, correct_val``)
    covering every cell, exactly how ``bench.flights`` consumes it."""
    rng = np.random.RandomState(seed)
    flight = rng.randint(0, 180, size=n_rows)
    clean = pd.DataFrame({
        "tuple_id": [str(i + 1) for i in range(n_rows)],
        "src": [f"src{i % 5}" for i in flight],
        "flight": [f"fl-{i:04d}" for i in flight],
        "sched_dep_time": [f"{6 + i % 16}:{(i * 7) % 60:02d}"
                           for i in flight],
        "act_dep_time": [f"{6 + i % 16}:{(i * 7 + 9) % 60:02d}"
                         for i in flight],
        "sched_arr_time": [f"{8 + i % 14}:{(i * 11) % 60:02d}"
                           for i in flight],
    })
    dirty = clean.copy()
    attrs = ["sched_dep_time", "act_dep_time", "sched_arr_time"]
    bad = rng.choice(n_rows, size=int(0.18 * n_rows), replace=False)
    for i in sorted(bad):
        col = attrs[i % len(attrs)]
        kind = i % 3
        v = clean.iloc[i, clean.columns.get_loc(col)]
        if kind == 0:
            dirty.iloc[i, dirty.columns.get_loc(col)] = None
        elif kind == 1:
            dirty.iloc[i, dirty.columns.get_loc(col)] = v.replace(":", ".")
        else:
            donor = int(rng.randint(n_rows))
            dirty.iloc[i, dirty.columns.get_loc(col)] = \
                clean.iloc[donor, clean.columns.get_loc(col)]
    truth = clean.melt(id_vars=["tuple_id"], var_name="attribute",
                       value_name="correct_val")
    return {"raha/flights.csv": dirty, "raha/flights_clean.csv": truth}


def _atomic_write(path: str, write_fn) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path),
                               prefix=".synth_tmp_")
    try:
        with os.fdopen(fd, "w") as f:
            write_fn(f)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def default_root() -> str:
    """Stable per-user materialization directory (overridable for tests
    via an explicit ``materialize_testdata(root)`` argument)."""
    base = tempfile.gettempdir()
    return os.path.join(base, f"delphi_synth_testdata_{os.getuid()}")


def materialize_testdata(root: str = "") -> str:
    """Writes every synthesizable testdata file under ``root`` (atomic
    per-file, idempotent via a completion marker) and returns the root.
    Safe under concurrent callers: files land via ``os.replace`` and the
    marker is written last."""
    root = root or default_root()
    marker = os.path.join(root, _MARKER)
    if os.path.exists(marker):
        return root
    frames: Dict[str, pd.DataFrame] = {}
    frames.update(adult_tables())
    frames["hospital.csv"] = hospital_table()
    frames["iris.csv"] = iris_table()
    frames.update(flights_tables())
    for rel, df in frames.items():
        _atomic_write(os.path.join(root, rel),
                      lambda f, df=df: df.to_csv(f, index=False))
    _atomic_write(os.path.join(root, "adult_constraints.txt"),
                  lambda f: f.write(ADULT_CONSTRAINTS))
    _atomic_write(os.path.join(root, "hospital_constraints.txt"),
                  lambda f: f.write(HOSPITAL_CONSTRAINTS))
    _atomic_write(marker, lambda f: f.write("ok\n"))
    return root
