"""Scenario gauntlet: generated workloads, error injectors, and
downstream-accuracy scoring (docs/source/gauntlet.rst).

Every quality number before this subsystem was flights @ 2376 rows — one
dataset, one error mix. The gauntlet stresses what the pipeline actually
claims to handle with **zero external testdata**:

* :mod:`delphi_tpu.gauntlet.scenarios` — a registry of deterministic,
  seeded scenario generators (planted functional dependencies, numeric
  regression signal, missing-value-heavy, wide 50+ column, correlated
  multi-attribute corruption), each with a scale series (2k → 100k+
  rows) and a clean/dirty/ground-truth-cells triple.
* :mod:`delphi_tpu.gauntlet.inject` — composable seeded error injectors
  (nulls, typos/transpositions, numeric outliers, value swaps,
  FD-violating correlated corruption) that record the exact injected
  cell set, so precision/recall are computed against known truth.
* :mod:`delphi_tpu.gauntlet.score` + :mod:`delphi_tpu.gauntlet.runner` —
  per-scenario cell-level P/R/F1, scorecard + escalation summaries from
  the provenance ledger, and a BoostClean-style downstream metric (train
  a small model on dirty vs repaired vs clean, report the accuracy gap
  closed), emitted as the run report's versioned ``gauntlet`` section.
* :mod:`delphi_tpu.gauntlet.lookalikes` — seeded lookalikes for the
  absent ``/root/reference`` testdata (adult/hospital/iris/flights +
  constraint files), so tier-1 and ``bench.py`` run everywhere.

Entry points: ``bench.py --gauntlet`` / ``--gauntlet-smoke`` and
``python -m delphi_tpu.main --gauntlet`` (with ``--baseline-report`` +
``--drift-fail-over`` for CI gating).
"""

from delphi_tpu.gauntlet.inject import (FDViolationInjector, NullInjector,
                                        OutlierInjector, SwapInjector,
                                        TypoInjector, inject)
from delphi_tpu.gauntlet.scenarios import (SCENARIOS, Scenario,
                                           generate_scenario, scenario_names)

__all__ = [
    "FDViolationInjector", "NullInjector", "OutlierInjector",
    "SwapInjector", "TypoInjector", "inject",
    "SCENARIOS", "Scenario", "generate_scenario", "scenario_names",
]
