"""Composable seeded error injectors for the scenario gauntlet.

Each injector corrupts a deterministic subset of cells in a clean table
and reports **exactly** which cells it touched, so scenario scoring can
compute precision/recall against known truth instead of eyeballing
output. Three invariants hold by construction (pinned by
``tests/test_gauntlet.py``):

* **Determinism** — the same ``(clean, injectors, seed)`` triple yields a
  byte-identical dirty table and injected-cell set on every run and every
  platform. All randomness flows through ``numpy.random.RandomState``
  (MT19937 — stable across numpy versions and OSes), with per-injector
  streams derived from ``crc32(name) ^ seed`` so appending an injector
  never perturbs the ones before it.
* **No double corruption** — a shared ``taken`` set makes every cell the
  property of at most one injector; a cell corrupted twice would make the
  "injected set" lie about what the detector is being graded on.
* **Injected ⊆ truth** — :func:`inject` returns the dirty frame together
  with a ``{(tid, attribute): clean_value}`` map covering every corrupted
  cell (a value swap corrupts *two* cells; both are recorded).

Injectors mutate positionally (``DataFrame.iloc``) and identify cells by
``(row_id value, column name)`` in the returned truth map, matching the
repair-candidate frame the pipeline emits.
"""

import zlib
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np
import pandas as pd

Cell = Tuple[str, str]


def _stream(seed: int, name: str) -> np.random.RandomState:
    """Independent, platform-stable random stream per (seed, injector)."""
    return np.random.RandomState(
        (int(seed) * 1000003 + zlib.crc32(name.encode())) % (2 ** 31 - 1))


def _eligible_rows(df: pd.DataFrame, column: str,
                   taken: set, row_id: str) -> List[int]:
    """Positional indices whose (row, column) cell is non-null and not yet
    owned by another injector, in frame order (deterministic)."""
    tids = df[row_id].astype(str)
    mask = df[column].notna().to_numpy()
    return [i for i in range(len(df))
            if mask[i] and (tids.iloc[i], column) not in taken]


class Injector:
    """Base class: picks ``rate`` of the eligible cells per column and
    rewrites each through :meth:`corrupt`."""

    name = "base"

    def __init__(self, columns: Sequence[str], rate: float = 0.05):
        self.columns = list(columns)
        self.rate = float(rate)

    def corrupt(self, value: Any, column: str, df: pd.DataFrame,
                rng: np.random.RandomState) -> Any:
        raise NotImplementedError

    def apply(self, dirty: pd.DataFrame, clean: pd.DataFrame,
              rng: np.random.RandomState, taken: set,
              row_id: str) -> Dict[Cell, Any]:
        injected: Dict[Cell, Any] = {}
        tids = clean[row_id].astype(str)
        for column in self.columns:
            rows = _eligible_rows(dirty, column, taken, row_id)
            if not rows:
                continue
            k = max(1, int(round(self.rate * len(rows))))
            picked = sorted(rng.choice(len(rows), size=min(k, len(rows)),
                                       replace=False).tolist())
            col_pos = dirty.columns.get_loc(column)
            for p in picked:
                i = rows[p]
                old = clean.iloc[i, clean.columns.get_loc(column)]
                new = self.corrupt(old, column, clean, rng)
                if new is old or (pd.notna(new) and new == old):
                    continue
                cell = (tids.iloc[i], column)
                dirty.iloc[i, col_pos] = new
                taken.add(cell)
                injected[cell] = old
        return injected


class NullInjector(Injector):
    """Blanks cells (None for object columns, NaN for numeric)."""

    name = "null"

    def corrupt(self, value, column, df, rng):
        return np.nan if pd.api.types.is_numeric_dtype(df[column]) else None


class TypoInjector(Injector):
    """String typos: adjacent-character transposition, character drop, or
    character substitution — the OCR/keyboard error family."""

    name = "typo"

    _SUBS = "xqzjk7"

    def corrupt(self, value, column, df, rng):
        s = str(value)
        if len(s) < 2:
            return s + self._SUBS[rng.randint(len(self._SUBS))]
        kind = rng.randint(3)
        i = rng.randint(len(s) - 1)
        if kind == 0:                                   # transposition
            out = s[:i] + s[i + 1] + s[i] + s[i + 2:]
            if out != s:
                return out
            kind = 1
        if kind == 1:                                   # drop
            return s[:i] + s[i + 1:]
        sub = self._SUBS[rng.randint(len(self._SUBS))]  # substitution
        while sub == s[i]:
            sub = self._SUBS[rng.randint(len(self._SUBS))]
        return s[:i] + sub + s[i + 1:]


class OutlierInjector(Injector):
    """Numeric outliers: scale the value far outside the column's range
    (sign flips included), the BoostClean numeric-corruption family."""

    name = "outlier"

    _FACTORS = (13.0, -11.0, 47.0, 101.0)

    def corrupt(self, value, column, df, rng):
        base = float(value)
        factor = self._FACTORS[rng.randint(len(self._FACTORS))]
        shift = float(df[column].abs().max() or 1.0)
        return base * factor + shift * (3.0 if factor > 0 else -3.0)


class SwapInjector(Injector):
    """Swaps the values of two rows in the same column — both cells are
    wrong afterwards and both land in the injected set."""

    name = "swap"

    def apply(self, dirty, clean, rng, taken, row_id):
        injected: Dict[Cell, Any] = {}
        tids = clean[row_id].astype(str)
        for column in self.columns:
            rows = _eligible_rows(dirty, column, taken, row_id)
            if len(rows) < 2:
                continue
            pairs = max(1, int(round(self.rate * len(rows) / 2)))
            col_pos = dirty.columns.get_loc(column)
            clean_pos = clean.columns.get_loc(column)
            for _ in range(pairs):
                if len(rows) < 2:
                    break
                a_idx, b_idx = rng.choice(len(rows), size=2,
                                          replace=False).tolist()
                a, b = rows[a_idx], rows[b_idx]
                va, vb = clean.iloc[a, clean_pos], clean.iloc[b, clean_pos]
                # remove both from the candidate pool either way; identical
                # values would make the "corruption" a no-op lie
                rows = [r for r in rows if r not in (a, b)]
                if va == vb:
                    continue
                dirty.iloc[a, col_pos] = vb
                dirty.iloc[b, col_pos] = va
                for i, old in ((a, va), (b, vb)):
                    cell = (tids.iloc[i], column)
                    taken.add(cell)
                    injected[cell] = old
        return injected


class FDViolationInjector(Injector):
    """FD-violating correlated corruption: for a planted dependency
    ``lhs -> rhs_columns``, rewrite a row's rhs cells with the rhs values
    of a donor row whose lhs differs — every touched cell then disagrees
    with what the dependency demands, and the corruption is *correlated
    across attributes* (the escalation joint tier's home turf)."""

    name = "fd_violation"

    def __init__(self, lhs: str, rhs_columns: Sequence[str],
                 rate: float = 0.05):
        super().__init__(rhs_columns, rate)
        self.lhs = lhs

    def apply(self, dirty, clean, rng, taken, row_id):
        injected: Dict[Cell, Any] = {}
        tids = clean[row_id].astype(str)
        lhs_vals = clean[self.lhs].astype(str)
        # rows where EVERY rhs cell is still free — a half-corrupted row
        # would break the no-double-corruption invariant
        rows = [i for i in range(len(clean))
                if all((tids.iloc[i], c) not in taken for c in self.columns)
                and all(pd.notna(dirty.iloc[i, dirty.columns.get_loc(c)])
                        for c in self.columns)]
        if len(rows) < 2:
            return injected
        k = max(1, int(round(self.rate * len(rows))))
        picked = sorted(rng.choice(len(rows), size=min(k, len(rows)),
                                   replace=False).tolist())
        for p in picked:
            i = rows[p]
            donors = [j for j in rows
                      if lhs_vals.iloc[j] != lhs_vals.iloc[i]]
            if not donors:
                continue
            d = donors[rng.randint(len(donors))]
            for column in self.columns:
                cpos = clean.columns.get_loc(column)
                old, new = clean.iloc[i, cpos], clean.iloc[d, cpos]
                if old == new:
                    continue
                cell = (tids.iloc[i], column)
                dirty.iloc[i, dirty.columns.get_loc(column)] = new
                taken.add(cell)
                injected[cell] = old
        return injected


def inject(clean: pd.DataFrame, injectors: Sequence[Injector], seed: int,
           row_id: str = "tid") -> Tuple[pd.DataFrame, Dict[Cell, Any]]:
    """Runs the injector stack over a copy of ``clean`` and returns
    ``(dirty, truth)`` where ``truth`` maps every injected ``(tid,
    attribute)`` cell to its clean value. Injector order matters (earlier
    injectors claim cells first); each injector draws from its own seeded
    stream so the composition is deterministic as a whole."""
    dirty = clean.copy()
    taken: set = set()
    truth: Dict[Cell, Any] = {}
    for idx, injector in enumerate(injectors):
        rng = _stream(seed + idx, injector.name)
        hits = injector.apply(dirty, clean, rng, taken, row_id)
        overlap = set(hits) & set(truth)
        if overlap:     # taken-set bug guard: never corrupt a cell twice
            raise AssertionError(f"cells corrupted twice: {sorted(overlap)}")
        truth.update(hits)
    return dirty, truth
