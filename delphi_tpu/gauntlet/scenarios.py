"""Deterministic seeded scenario generators for the gauntlet.

Each scenario is a recipe for a *family* of tables: a seeded clean-table
builder, an injector stack (:mod:`delphi_tpu.gauntlet.inject`), a
detector/constraint spec for the repair run, and a downstream-learning
task (label column + classification/regression) for the BoostClean-style
accuracy triple. :func:`generate_scenario` materializes one member as a
:class:`ScenarioData` — clean frame, dirty frame, and the ground-truth
map of every injected cell — at any row count in the scenario's scale
series (2k → 100k+; smokes use smaller cuts of the same recipe).

None of this touches external testdata: every value is derived from the
row index and a ``numpy.random.RandomState`` stream, so the same
``(name, rows, seed)`` triple is byte-identical everywhere.

The registry covers the claims the pipeline makes beyond flights:

* ``fd_categorical`` — categorical attributes governed by planted
  functional dependencies (city → state → region), corrupted by typos,
  nulls, and FD-violating rewrites; constraints ride along as DC text.
* ``numeric_regression`` — numeric columns carrying a ground-truth
  linear signal, corrupted by large outliers and nulls; exercises the
  regression branch of model training (pinned by tests).
* ``missing_heavy`` — a mostly-categorical table where 20%+ of cells in
  the target attributes are blanked; repair = imputation at scale.
* ``wide`` — 50+ columns in correlated groups; stresses per-attribute
  model fan-out and launch planning.
* ``correlated_multi`` — multi-attribute corruption correlated across
  columns of the same row (the escalation joint tier's home turf).
"""

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np
import pandas as pd

from delphi_tpu.gauntlet.inject import (Cell, FDViolationInjector, Injector,
                                        NullInjector, OutlierInjector,
                                        SwapInjector, TypoInjector, inject)

#: default scale series every scenario supports (rows)
SCALES = (2_000, 20_000, 100_000)


@dataclass
class ScenarioData:
    """One materialized scenario instance."""
    name: str
    clean: pd.DataFrame
    dirty: pd.DataFrame
    truth: Dict[Cell, Any]          # (tid, attribute) -> clean value
    row_id: str
    label: str                      # downstream target column
    task: str                       # "classification" | "regression"
    constraints: Optional[str]      # DC text for ConstraintErrorDetector
    regexes: List[Tuple[str, str]]  # (attr, pattern) for RegExErrorDetector
    targets: List[str]              # repair target attributes
    outlier_detection: bool = False


@dataclass
class Scenario:
    """A registered scenario recipe."""
    name: str
    description: str
    build_clean: Callable[[int, np.random.RandomState], pd.DataFrame]
    injectors: Callable[[], List[Injector]]
    label: str
    task: str
    constraints: Optional[str] = None
    regexes: List[Tuple[str, str]] = field(default_factory=list)
    targets: Optional[List[str]] = None
    outlier_detection: bool = False
    scales: Tuple[int, ...] = SCALES

    def generate(self, rows: int, seed: int = 0) -> ScenarioData:
        rng = np.random.RandomState(seed * 7919 + len(self.name))
        clean = self.build_clean(rows, rng)
        assert "tid" in clean.columns
        dirty, truth = inject(clean, self.injectors(), seed, row_id="tid")
        targets = self.targets or [
            c for c in clean.columns if c != "tid"]
        return ScenarioData(
            name=self.name, clean=clean, dirty=dirty, truth=truth,
            row_id="tid", label=self.label, task=self.task,
            constraints=self.constraints, regexes=list(self.regexes),
            targets=targets, outlier_detection=self.outlier_detection)


def _tids(n: int) -> List[str]:
    return [str(i) for i in range(n)]


# ---------------------------------------------------------------------------
# clean-table builders (all vectorized; 100k+ rows stay cheap)
# ---------------------------------------------------------------------------

def _fd_categorical_clean(n: int, rng: np.random.RandomState) -> pd.DataFrame:
    """city -> state -> region FD chain + an independent channel column.
    The region label is a pure function of city/state, so a downstream
    classifier on clean data is near-perfect and every corrupted feature
    cell costs it accuracy."""
    city = rng.randint(0, 24, size=n)
    state = city % 12
    region = state % 4
    channel = rng.randint(0, 3, size=n)
    return pd.DataFrame({
        "tid": _tids(n),
        "city": [f"city_{i:02d}" for i in city],
        "state": [f"state_{i:02d}" for i in state],
        "region": [f"region_{i}" for i in region],
        "channel": [f"ch_{i}" for i in channel],
    })


def _numeric_regression_clean(n: int,
                              rng: np.random.RandomState) -> pd.DataFrame:
    """Numeric features + a target carrying a real linear signal with a
    categorical group offset; all float columns have (essentially) all-
    distinct values, so the discrete-threshold check routes them to the
    continuous/regression path."""
    x0 = rng.uniform(-2.0, 2.0, size=n)
    x1 = rng.uniform(0.0, 4.0, size=n)
    x2 = rng.uniform(-1.0, 1.0, size=n)
    g = rng.randint(0, 6, size=n)
    noise = rng.normal(0.0, 0.25, size=n)
    y = 3.0 * x0 - 2.0 * x1 + 1.5 * g + noise
    return pd.DataFrame({
        "tid": _tids(n),
        "x0": np.round(x0, 6),
        "x1": np.round(x1, 6),
        "x2": np.round(x2, 6),
        "group": [f"g{i}" for i in g],
        "y": np.round(y, 6),
    })


def _missing_heavy_clean(n: int, rng: np.random.RandomState) -> pd.DataFrame:
    """Strongly cross-correlated categoricals, so heavy missingness stays
    imputable: tier/band/grade are functions of a latent level."""
    level = rng.randint(0, 10, size=n)
    seg = rng.randint(0, 4, size=n)
    return pd.DataFrame({
        "tid": _tids(n),
        "level": [f"lv{i}" for i in level],
        "tier": [f"t{i // 2}" for i in level],
        "band": [f"b{i % 5}" for i in level],
        "grade": [f"gr{(i + s) % 6}" for i, s in zip(level, seg)],
        "segment": [f"s{i}" for i in seg],
    })


def _wide_clean(n: int, rng: np.random.RandomState) -> pd.DataFrame:
    """56 attribute columns in 8 correlated groups of 7: every column in
    group g is a distinct renaming of that group's latent factor, so each
    has clean FD structure to learn while the table stresses per-attribute
    model fan-out."""
    data: Dict[str, Any] = {"tid": _tids(n)}
    for g in range(8):
        latent = rng.randint(0, 5, size=n)
        for j in range(7):
            data[f"a{g}_{j}"] = [f"g{g}c{j}v{(v + j) % 5}" for v in latent]
    return pd.DataFrame(data)


def _correlated_multi_clean(n: int,
                            rng: np.random.RandomState) -> pd.DataFrame:
    """One driver column jointly determines three dependents — corruption
    correlated across a row's dependents is exactly what single-attribute
    repair misreads and the escalation joint tier untangles."""
    k = rng.randint(0, 9, size=n)
    return pd.DataFrame({
        "tid": _tids(n),
        "key": [f"k{i}" for i in k],
        "d0": [f"u{i % 3}" for i in k],
        "d1": [f"v{(i * 2) % 9}" for i in k],
        "d2": [f"{100 + i}-{20 + (i * 3) % 10}" for i in k],
    })


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

SCENARIOS: Dict[str, Scenario] = {}


def _register(s: Scenario) -> Scenario:
    SCENARIOS[s.name] = s
    return s


_register(Scenario(
    name="fd_categorical",
    description="planted city->state->region FDs; typos + nulls + "
                "FD-violating rewrites; DC constraints ride along",
    build_clean=_fd_categorical_clean,
    injectors=lambda: [
        TypoInjector(["state"], rate=0.02),
        NullInjector(["state", "region"], rate=0.03),
        FDViolationInjector("city", ["state", "region"], rate=0.02),
    ],
    label="region", task="classification",
    constraints="city->state;state->region",
    regexes=[("state", "^state_[0-9]{2}$")],
    targets=["state", "region"],
))

_register(Scenario(
    name="numeric_regression",
    description="numeric features + linear-signal target; large outliers "
                "+ nulls; exercises the regression training branch",
    build_clean=_numeric_regression_clean,
    injectors=lambda: [
        OutlierInjector(["y", "x0"], rate=0.03),
        NullInjector(["x1", "y"], rate=0.03),
    ],
    label="y", task="regression",
    targets=["x0", "x1", "y"],
    outlier_detection=True,
))

_register(Scenario(
    name="missing_heavy",
    description="20%+ of target cells blanked across correlated "
                "categoricals; repair = imputation at scale",
    build_clean=_missing_heavy_clean,
    injectors=lambda: [
        NullInjector(["tier", "band", "grade"], rate=0.22),
    ],
    label="segment", task="classification",
    targets=["tier", "band", "grade"],
))

_register(Scenario(
    name="wide",
    description="56 columns in 8 correlated groups; stresses per-attribute "
                "model fan-out and launch planning",
    build_clean=_wide_clean,
    injectors=lambda: [
        NullInjector([f"a{g}_0" for g in range(8)], rate=0.04),
        TypoInjector(["a0_1", "a4_1"], rate=0.03),
        SwapInjector(["a2_2"], rate=0.04),
    ],
    label="a7_0", task="classification",
    targets=[f"a{g}_0" for g in range(8)] + ["a0_1", "a4_1", "a2_2"],
    scales=(2_000, 10_000, 50_000),
))

_register(Scenario(
    name="correlated_multi",
    description="corruption correlated across a row's dependent columns "
                "(escalation joint tier's home turf)",
    build_clean=_correlated_multi_clean,
    injectors=lambda: [
        FDViolationInjector("key", ["d0", "d1", "d2"], rate=0.04),
        NullInjector(["d1", "d2"], rate=0.03),
    ],
    label="d0", task="classification",
    constraints="key->d0;key->d1;key->d2",
    regexes=[("d2", "^[0-9]{3}-[0-9]{2}$")],
    targets=["d0", "d1", "d2"],
))


def scenario_names() -> List[str]:
    return sorted(SCENARIOS)


def generate_scenario(name: str, rows: int, seed: int = 0) -> ScenarioData:
    """Materializes one scenario instance; raises ``KeyError`` for an
    unknown name (``scenario_names()`` lists the registry)."""
    return SCENARIOS[name].generate(rows, seed)
