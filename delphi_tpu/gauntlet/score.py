"""Per-scenario scoring: cell-level P/R/F1 and the BoostClean-style
downstream-accuracy triple.

Cell scoring is the flights metric generalized to numeric repairs:
precision over every repair the pipeline emitted, recall over the
injected ground-truth set, with numeric values matched under a small
tolerance (a regression model that lands within noise of the clean value
has repaired the cell; demanding bit-equality of floats would score the
regression path as permanently broken).

Downstream scoring follows BoostClean (PAPERS.md): train the same small
model three times — on the dirty, repaired, and clean versions of the
train split — evaluate each against the *clean* test split, and report
the fraction of the dirty→clean accuracy gap the repair closed
(``gap_closed = (repaired - dirty) / (clean - dirty)``). The model is a
fixed-seed sklearn decision tree (classification accuracy / regression
R²), so the triple is deterministic for a deterministic scenario.
"""

import math
from typing import Any, Dict, Optional

import numpy as np
import pandas as pd

from delphi_tpu.gauntlet.scenarios import ScenarioData

#: numeric match tolerances: relative OR absolute (scenario noise scale)
REL_TOL = 0.2
ABS_TOL = 0.5

#: deterministic downstream split: rows with (pos % 10) >= 7 are test
TEST_MOD = 10
TEST_CUT = 7


def _as_float(v: Any) -> Optional[float]:
    try:
        f = float(v)
        return f if math.isfinite(f) else None
    except (TypeError, ValueError):
        return None


def values_match(pred: Any, true: Any) -> bool:
    """Repair correctness for one cell: exact string equality, except when
    both sides are numeric — then within ``REL_TOL`` relative or
    ``ABS_TOL`` absolute error."""
    if pd.isna(pred) or pd.isna(true):
        return False
    pf, tf = _as_float(pred), _as_float(true)
    if pf is not None and tf is not None:
        return abs(pf - tf) <= max(REL_TOL * abs(tf), ABS_TOL)
    return str(pred) == str(true)


def score_cells(repair_frame: Optional[pd.DataFrame],
                truth: Dict[Any, Any]) -> Dict[str, Any]:
    """Cell-level precision/recall/F1 of a repair-candidates frame
    (tid/attribute/repaired) against the injected ground truth."""
    by_cell: Dict[Any, Any] = {}
    if repair_frame is not None and len(repair_frame):
        by_cell = {(str(r), str(a)): v for r, a, v in
                   zip(repair_frame["tid"], repair_frame["attribute"],
                       repair_frame["repaired"])}
    correct = sum(1 for k, v in by_cell.items()
                  if k in truth and values_match(v, truth[k]))
    p = correct / len(by_cell) if by_cell else 0.0
    r = correct / len(truth) if truth else 0.0
    f1 = 2 * p * r / (p + r) if p + r else 0.0
    return {
        "injected": len(truth), "repairs": len(by_cell),
        "correct": correct, "precision": round(p, 4),
        "recall": round(r, 4), "f1": round(f1, 4),
    }


def apply_repairs(dirty: pd.DataFrame, repair_frame: Optional[pd.DataFrame],
                  row_id: str = "tid") -> pd.DataFrame:
    """Splices a repair-candidates frame back into the dirty table (the
    ``repair_data`` view, done host-side so scoring never depends on the
    pipeline's own writeback path)."""
    out = dirty.copy()
    if repair_frame is None or not len(repair_frame):
        return out
    pos = {t: i for i, t in enumerate(out[row_id].astype(str))}
    for r, a, v in zip(repair_frame["tid"], repair_frame["attribute"],
                       repair_frame["repaired"]):
        i = pos.get(str(r))
        if i is None or a not in out.columns:
            continue
        if pd.api.types.is_numeric_dtype(out[a]):
            v = _as_float(v)
            if v is None:
                continue
        out.iloc[i, out.columns.get_loc(a)] = v
    return out


def _encode_features(frames: Dict[str, pd.DataFrame], feature_cols,
                     numeric_cols) -> Dict[str, np.ndarray]:
    """One consistent encoding across the dirty/repaired/clean variants:
    shared category codes for object columns (so 'the same value' gets the
    same code everywhere), sentinel-filled numerics (trees split around
    it)."""
    encoded: Dict[str, np.ndarray] = {}
    vocab: Dict[str, Dict[str, int]] = {}
    for c in feature_cols:
        if c in numeric_cols:
            continue
        values = sorted({str(v) for f in frames.values()
                         for v in f[c].dropna()})
        vocab[c] = {v: i for i, v in enumerate(values)}
    for tag, f in frames.items():
        cols = []
        for c in feature_cols:
            if c in numeric_cols:
                cols.append(pd.to_numeric(f[c], errors="coerce")
                            .fillna(-1e9).to_numpy(dtype=np.float64))
            else:
                cols.append(f[c].map(
                    lambda v: vocab[c].get(str(v), -1) if pd.notna(v)
                    else -1).to_numpy(dtype=np.float64))
        encoded[tag] = np.column_stack(cols)
    return encoded


def downstream_score(data: ScenarioData, repaired: pd.DataFrame,
                     seed: int = 0) -> Dict[str, Any]:
    """The dirty-vs-repaired-vs-clean downstream triple for one scenario.

    Train on each variant's train split, evaluate on the clean test split
    (corrupted labels poison training — that cost is part of the metric —
    but evaluation must be against truth). Rows whose label is null in a
    variant are dropped from that variant's train split only.
    """
    from sklearn.tree import DecisionTreeClassifier, DecisionTreeRegressor

    label = data.label
    n = len(data.clean)
    is_test = np.array([(i % TEST_MOD) >= TEST_CUT for i in range(n)])
    feature_cols = [c for c in data.clean.columns
                    if c not in (data.row_id, label)]
    numeric_cols = {c for c in feature_cols
                    if pd.api.types.is_numeric_dtype(data.clean[c])}
    frames = {"dirty": data.dirty, "repaired": repaired, "clean": data.clean}
    X = _encode_features(frames, feature_cols, numeric_cols)

    regression = data.task == "regression"
    if regression:
        y = {t: pd.to_numeric(f[label], errors="coerce").to_numpy()
             for t, f in frames.items()}
    else:
        labels = sorted({str(v) for f in frames.values()
                         for v in f[label].dropna()})
        lmap = {v: i for i, v in enumerate(labels)}
        y = {t: f[label].map(lambda v: lmap.get(str(v), -1)
                             if pd.notna(v) else -1).to_numpy()
             for t, f in frames.items()}

    X_test = X["clean"][is_test]
    y_test = y["clean"][is_test]
    scores: Dict[str, float] = {}
    for tag in ("dirty", "repaired", "clean"):
        Xt, yt = X[tag][~is_test], y[tag][~is_test]
        keep = np.isfinite(yt) if regression else (yt >= 0)
        Xt, yt = Xt[keep], yt[keep]
        if regression:
            model = DecisionTreeRegressor(max_depth=8, random_state=seed)
        else:
            model = DecisionTreeClassifier(max_depth=8, random_state=seed)
        model.fit(Xt, yt)
        scores[tag] = round(float(model.score(X_test, y_test)), 4)

    denom = scores["clean"] - scores["dirty"]
    gap_closed = None
    if abs(denom) > 1e-9:
        gap_closed = round(
            max(-2.0, min(2.0, (scores["repaired"] - scores["dirty"])
                          / denom)), 4)
    return {
        "task": data.task,
        "metric": "r2" if regression else "accuracy",
        "dirty": scores["dirty"], "repaired": scores["repaired"],
        "clean": scores["clean"], "gap_closed": gap_closed,
        "train_rows": int((~is_test).sum()), "test_rows": int(is_test.sum()),
    }
