"""Drives gauntlet scenarios through the real repair pipeline and
assembles the versioned ``gauntlet`` report section.

Each scenario runs under its own run recorder with an in-memory
provenance ledger, so the per-attribute scorecards and escalation
summary land in the per-scenario result exactly as they do for a
production run — the gauntlet measures the pipeline the users get, not
a test double. The per-scenario result carries:

* cell-level precision/recall/F1 against the injected ground truth,
* the full per-attribute scorecards (drift-gate input) + their summary,
* the escalation summary when the escalation tier ran,
* the BoostClean downstream triple (dirty/repaired/clean + gap closed),
* the ``train.*`` counters (``train.regressors`` pins the regression
  branch for the numeric scenario).

``repairs_enabled=False`` is the deliberate degradation used by the gate
self-test: detection and scoring still run, but no repairs are applied —
every scenario's F1 collapses, which the per-scenario drift gate
(:func:`delphi_tpu.observability.drift.evaluate_gauntlet`) must catch.

Env knobs (mirrored by ``bench.py --gauntlet`` flags):
``DELPHI_GAUNTLET_ROWS``, ``DELPHI_GAUNTLET_SEED``,
``DELPHI_GAUNTLET_SCENARIOS`` (comma-separated registry names).
"""

import os
import time
from typing import Any, Dict, List, Optional

from delphi_tpu.gauntlet.scenarios import (SCENARIOS, ScenarioData,
                                           generate_scenario, scenario_names)
from delphi_tpu.gauntlet.score import (apply_repairs, downstream_score,
                                       score_cells)
from delphi_tpu.utils import setup_logger

_logger = setup_logger()

#: version of the run report's ``gauntlet`` section (bump on shape change)
GAUNTLET_REPORT_VERSION = 1

DEFAULT_ROWS = 2_000


def _detectors(data: ScenarioData) -> List[Any]:
    from delphi_tpu.errors import (ConstraintErrorDetector,
                                   GaussianOutlierErrorDetector,
                                   NullErrorDetector, RegExErrorDetector)
    dets: List[Any] = [NullErrorDetector()]
    for attr, pattern in data.regexes:
        dets.append(RegExErrorDetector(attr, pattern))
    if data.constraints:
        dets.append(ConstraintErrorDetector(constraints=data.constraints))
    if data.outlier_detection:
        dets.append(GaussianOutlierErrorDetector())
    return dets


def run_scenario(data: ScenarioData, seed: int = 0,
                 repairs_enabled: bool = True) -> Dict[str, Any]:
    """Runs one materialized scenario end-to-end and scores it."""
    from delphi_tpu import delphi
    from delphi_tpu import observability as obs
    from delphi_tpu.session import get_session

    saved_prov = os.environ.get("DELPHI_PROVENANCE_PATH")
    os.environ.setdefault("DELPHI_PROVENANCE_PATH", ":memory:")
    name = f"gauntlet_{data.name}"
    repair_frame = None
    scorecards = None
    escalation = None
    counters: Dict[str, int] = {}
    error: Optional[str] = None
    t0 = time.time()
    try:
        if repairs_enabled:
            get_session().register(name, data.dirty.copy())
            rec = obs.start_recording(f"gauntlet.{data.name}")
            try:
                repair_frame = delphi.repair \
                    .setTableName(name) \
                    .setRowId(data.row_id) \
                    .setErrorDetectors(_detectors(data)) \
                    .setTargets(list(data.targets)) \
                    .run()
            finally:
                obs.stop_recording(rec)
                get_session().drop(name)
            if rec is not None:
                scorecards = getattr(rec, "scorecards", None)
                escalation = getattr(rec, "escalation", None)
                counters = {
                    k: int(v) for k, v in
                    rec.registry.snapshot()["counters"].items()
                    if k.startswith(("train.", "escalation.", "repair."))}
    except Exception as e:            # a broken scenario must not hide the rest
        error = f"{type(e).__name__}: {e}"
        _logger.warning(f"gauntlet scenario {data.name} failed: {error}")
    finally:
        if saved_prov is None:
            os.environ.pop("DELPHI_PROVENANCE_PATH", None)
        else:
            os.environ["DELPHI_PROVENANCE_PATH"] = saved_prov
    elapsed = time.time() - t0

    from delphi_tpu.observability import scorecard_summary
    repaired = apply_repairs(data.dirty, repair_frame, data.row_id)
    result = {
        "rows": int(len(data.clean)),
        "attributes": int(len(data.clean.columns) - 1),
        "targets": list(data.targets),
        "repairs_enabled": bool(repairs_enabled),
        "repair": score_cells(repair_frame, data.truth),
        "scorecards": scorecards,
        "scorecard_summary": scorecard_summary(scorecards),
        "escalation": escalation,
        "counters": counters,
        "downstream": downstream_score(data, repaired, seed=seed),
        "elapsed_s": round(elapsed, 3),
    }
    if error:
        result["error"] = error
    return result


def run_gauntlet(names: Optional[List[str]] = None,
                 rows: Optional[int] = None,
                 seed: Optional[int] = None,
                 repairs_enabled: bool = True,
                 heartbeat=None) -> Dict[str, Any]:
    """Runs the named scenarios (default: the full registry) and returns
    the versioned gauntlet report section."""
    if names is None:
        env_names = os.environ.get("DELPHI_GAUNTLET_SCENARIOS", "")
        names = [n.strip() for n in env_names.split(",") if n.strip()] \
            or scenario_names()
    unknown = [n for n in names if n not in SCENARIOS]
    if unknown:
        raise KeyError(f"unknown gauntlet scenarios: {unknown} "
                       f"(registry: {scenario_names()})")
    if rows is None:
        rows = int(os.environ.get("DELPHI_GAUNTLET_ROWS", DEFAULT_ROWS))
    if seed is None:
        seed = int(os.environ.get("DELPHI_GAUNTLET_SEED", "0"))

    scenarios: Dict[str, Any] = {}
    for n in names:
        if heartbeat:
            heartbeat(f"gauntlet scenario {n} ({rows} rows)")
        data = generate_scenario(n, rows, seed)
        scenarios[n] = run_scenario(data, seed=seed,
                                    repairs_enabled=repairs_enabled)

    f1s = [s["repair"]["f1"] for s in scenarios.values()]
    gaps = [s["downstream"]["gap_closed"] for s in scenarios.values()
            if s["downstream"]["gap_closed"] is not None]
    return {
        "version": GAUNTLET_REPORT_VERSION,
        "seed": int(seed),
        "rows": int(rows),
        "repairs_enabled": bool(repairs_enabled),
        "scenarios": scenarios,
        "mean_f1": round(sum(f1s) / len(f1s), 4) if f1s else 0.0,
        "mean_gap_closed":
            round(sum(gaps) / len(gaps), 4) if gaps else None,
    }


def emit_gauntlet_metrics(registry: Any, report: Dict[str, Any]) -> None:
    """Lands a gauntlet report's aggregates as ``gauntlet.*`` counters and
    gauges on a metrics registry (the live ``/metrics`` plane pre-seeds
    the same names so dashboards see zeros before the first run)."""
    scenarios = report.get("scenarios", {})
    registry.inc("gauntlet.scenarios", len(scenarios))
    for s in scenarios.values():
        registry.inc("gauntlet.cells_injected",
                     s["repair"]["injected"])
        registry.inc("gauntlet.repairs", s["repair"]["repairs"])
        registry.inc("gauntlet.repairs_correct",
                     s["repair"]["correct"])
        if s.get("error"):
            registry.inc("gauntlet.scenario_errors")
    registry.set_gauge("gauntlet.mean_f1", report.get("mean_f1") or 0.0)
    if report.get("mean_gap_closed") is not None:
        registry.set_gauge("gauntlet.mean_gap_closed",
                           report["mean_gap_closed"])
    for name, s in scenarios.items():
        registry.set_gauge(f"gauntlet.{name}.f1", s["repair"]["f1"])
        gap = s["downstream"].get("gap_closed")
        if gap is not None:
            registry.set_gauge(f"gauntlet.{name}.gap_closed", gap)
