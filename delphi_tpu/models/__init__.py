"""Repair model families (JAX): feature encoding, linear/MLP heads, GBDT.

These replace the reference's LightGBM + hyperopt training stack
(`python/repair/train.py:89-229`) with jitted JAX models that keep the same
scikit-learn-like duck type (``classes_``, ``predict``, ``predict_proba``)
expected by the repair pipeline (reference model.py:44-100).
"""

from delphi_tpu.models.encoding import FeatureEncoder
from delphi_tpu.models.linear import LogisticRegressionModel, MLPRegressorModel
