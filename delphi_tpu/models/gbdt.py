"""Histogram gradient-boosted decision trees in pure JAX — the flagship
repair-model family, replacing LightGBM (reference train.py:89-229).

TPU-first design:
* features are quantile-binned once into an ``int32[n, d]`` bin tensor
  (NaN/missing = bin 0), so each boosting round is dense integer arithmetic;
* trees grow depth-wise with FIXED shapes: level ``t`` owns node ids
  ``[0, 2^t)``, histograms are ``[2^D, d, B]`` scatter-adds (XLA lowers them
  to one-hot matmuls on the MXU), and split selection is an argmax over the
  padded (feature, bin) grid — no data-dependent control flow;
* the whole boosting loop is a single ``lax.scan`` over rounds, multiclass
  trains K trees per round via ``vmap`` over the class axis.

Objectives: L2 regression, binary logistic, multiclass softmax — with
balanced class weights like the reference's `class_weight='balanced'`
(train.py:105), which drives its characteristic minority-class repairs.
"""

from functools import partial
from typing import Any, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd

MAX_MULTICLASS = 24


def gbdt_supported(is_discrete: bool, num_class: int) -> bool:
    """K class-trees per round get expensive fast; very wide multiclass
    targets route to the logistic head instead (train.py)."""
    return (not is_discrete) or num_class <= MAX_MULTICLASS


# ---------------------------------------------------------------------------
# Binning
# ---------------------------------------------------------------------------

class _Binner:
    """Quantile binning; bin 0 is reserved for NaN/missing."""

    def __init__(self, max_bin: int) -> None:
        self.max_bin = max_bin
        self.edges: List[np.ndarray] = []

    def fit(self, X: np.ndarray) -> "_Binner":
        self.edges = []
        for j in range(X.shape[1]):
            col = X[:, j]
            col = col[~np.isnan(col)]
            uniq = np.unique(col)
            if len(uniq) <= 1:
                self.edges.append(np.array([np.inf]))
            elif len(uniq) <= self.max_bin:
                self.edges.append((uniq[1:] + uniq[:-1]) / 2.0)
            else:
                qs = np.quantile(col, np.linspace(0, 1, self.max_bin + 1)[1:-1])
                self.edges.append(np.unique(qs))
        return self

    @property
    def n_bins(self) -> int:
        return max((len(e) + 1 for e in self.edges), default=1) + 1  # +1 NaN bin

    def transform(self, X: np.ndarray) -> np.ndarray:
        n, d = X.shape
        out = np.zeros((n, d), dtype=np.int32)
        for j in range(d):
            col = X[:, j]
            bins = np.searchsorted(self.edges[j], col, side="left") + 1
            out[:, j] = np.where(np.isnan(col), 0, bins)
        return out


# ---------------------------------------------------------------------------
# Tree building / prediction kernels
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("depth", "n_bins", "n_nodes"))
def _build_tree(bins, grad, hess, weight, depth, n_bins, n_nodes,
                reg_lambda, min_split_gain, min_child_weight):
    """Grows one depth-wise tree. Returns (feat[int32 n_nodes-1],
    thr[int32 n_nodes-1], leaf[f32 n_nodes]) with all-left sentinel splits
    (thr = n_bins) for terminated nodes."""
    n, d = bins.shape

    feat = jnp.zeros(n_nodes - 1, dtype=jnp.int32)
    thr = jnp.full(n_nodes - 1, n_bins, dtype=jnp.int32)
    node = jnp.zeros(n, dtype=jnp.int32)

    for level in range(depth):
        n_level = 1 << level
        # histograms over (node, feature, bin)
        flat = (node[:, None] * d + jnp.arange(d)[None, :]) * n_bins + bins
        flat = flat.reshape(-1)
        size = n_level * d * n_bins
        hg = jnp.zeros(size, jnp.float32).at[flat].add(
            jnp.repeat(grad, d)).reshape(n_level, d, n_bins)
        hh = jnp.zeros(size, jnp.float32).at[flat].add(
            jnp.repeat(hess, d)).reshape(n_level, d, n_bins)
        hw = jnp.zeros(size, jnp.float32).at[flat].add(
            jnp.repeat(weight, d)).reshape(n_level, d, n_bins)

        GL = jnp.cumsum(hg, axis=2)
        HL = jnp.cumsum(hh, axis=2)
        WL = jnp.cumsum(hw, axis=2)
        G = GL[:, :, -1:]
        H = HL[:, :, -1:]
        W = WL[:, :, -1:]
        GR, HR, WR = G - GL, H - HL, W - WL

        gain = (GL * GL / (HL + reg_lambda)
                + GR * GR / (HR + reg_lambda)
                - G * G / (H + reg_lambda))
        ok = (WL >= min_child_weight) & (WR >= min_child_weight)
        gain = jnp.where(ok, gain, -jnp.inf)
        # never split on the last bin (right side empty by construction)
        gain = gain.at[:, :, -1].set(-jnp.inf)

        flat_gain = gain.reshape(n_level, d * n_bins)
        best = jnp.argmax(flat_gain, axis=1)
        best_gain = jnp.take_along_axis(flat_gain, best[:, None], axis=1)[:, 0]
        best_f = (best // n_bins).astype(jnp.int32)
        best_b = (best % n_bins).astype(jnp.int32)
        do_split = best_gain > min_split_gain
        best_f = jnp.where(do_split, best_f, 0)
        best_b = jnp.where(do_split, best_b, n_bins)  # sentinel: all rows left

        offset = n_level - 1
        feat = jax.lax.dynamic_update_slice(feat, best_f, (offset,))
        thr = jax.lax.dynamic_update_slice(thr, best_b, (offset,))

        go_right = bins[jnp.arange(n), best_f[node]] > best_b[node]
        node = node * 2 + go_right.astype(jnp.int32)

    leaf_g = jnp.zeros(n_nodes, jnp.float32).at[node].add(grad)
    leaf_h = jnp.zeros(n_nodes, jnp.float32).at[node].add(hess)
    leaf = -leaf_g / (leaf_h + reg_lambda)
    return feat, thr, leaf, node


@partial(jax.jit, static_argnames=("depth",))
def _predict_tree(bins, feat, thr, leaf, depth):
    n = bins.shape[0]
    node = jnp.zeros(n, dtype=jnp.int32)
    for level in range(depth):
        offset = (1 << level) - 1
        f = feat[offset + node]
        b = thr[offset + node]
        go_right = bins[jnp.arange(n), f] > b
        node = node * 2 + go_right.astype(jnp.int32)
    return leaf[node]


# ---------------------------------------------------------------------------
# Boosting
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("n_rounds", "depth", "n_bins", "n_nodes",
                                   "objective", "k"))
def _boost(bins, y, weight, n_rounds, depth, n_bins, n_nodes, objective, k,
           lr, reg_lambda, min_split_gain, min_child_weight, base_score):
    """Runs the full boosting loop as one lax.scan; returns stacked trees."""
    n = bins.shape[0]

    def grad_hess(F):
        if objective == "regression":
            return (F - y)[None, :] * weight[None, :], weight[None, :]
        if objective == "binary":
            p = jax.nn.sigmoid(F)
            return ((p - y) * weight)[None, :], \
                jnp.maximum(p * (1 - p), 1e-6)[None, :] * weight[None, :]
        # multiclass softmax: F is [k, n]
        p = jax.nn.softmax(F, axis=0)
        onehot = jax.nn.one_hot(y.astype(jnp.int32), k, axis=0, dtype=jnp.float32)
        return (p - onehot) * weight[None, :], \
            jnp.maximum(p * (1 - p), 1e-6) * weight[None, :]

    def one_round(F, _):
        g, h = grad_hess(F)

        def build(gk, hk):
            return _build_tree(bins, gk, hk, weight, depth, n_bins, n_nodes,
                               reg_lambda, min_split_gain, min_child_weight)

        feat, thr, leaf, node = jax.vmap(build)(g, h)  # [k_trees, ...]
        leaf = leaf * lr
        delta = jnp.take_along_axis(leaf, node, axis=1)  # [k_trees, n]
        F = F + (delta[0] if objective != "multiclass" else delta)
        return F, (feat, thr, leaf)

    if objective == "multiclass":
        F0 = jnp.broadcast_to(base_score[:, None], (k, n))
    else:
        F0 = jnp.full((n,), base_score[0])
    _, trees = jax.lax.scan(one_round, F0, None, length=n_rounds)
    return trees


@partial(jax.jit, static_argnames=("n_rounds", "depth", "objective", "k"))
def _predict_boosted(bins, feats, thrs, leaves, n_rounds, depth, objective, k,
                     base_score):
    n = bins.shape[0]

    def score_tree(carry, tree):
        feat, thr, leaf = tree

        def one(fa, ta, la):
            return _predict_tree(bins, fa, ta, la, depth)

        delta = jax.vmap(one)(feat, thr, leaf)  # [k_trees, n]
        return carry + (delta[0] if objective != "multiclass" else delta), None

    if objective == "multiclass":
        F0 = jnp.broadcast_to(base_score[:, None], (k, n))
    else:
        F0 = jnp.full((n,), base_score[0])
    F, _ = jax.lax.scan(score_tree, F0, (feats, thrs, leaves))
    return F


# ---------------------------------------------------------------------------
# Public model
# ---------------------------------------------------------------------------

class GradientBoostedTreesModel:
    """LightGBM-style GBDT with the repair pipeline's model duck type."""

    def __init__(self, is_discrete: bool, num_class: int,
                 n_estimators: int = 300, learning_rate: float = 0.1,
                 max_depth: int = 5, max_bin: int = 255,
                 min_split_gain: float = 0.0, reg_lambda: float = 1.0,
                 min_child_weight: float = 1.0,
                 class_weight: str = "balanced") -> None:
        self.is_discrete = is_discrete
        self.num_class = num_class
        self.n_estimators = min(n_estimators, 200)
        self.learning_rate = learning_rate
        self.max_depth = max_depth
        self.max_bin = min(max_bin, 63)
        self.min_split_gain = min_split_gain
        self.reg_lambda = reg_lambda
        self.min_child_weight = min_child_weight
        self.class_weight = class_weight
        self.loss_: float = 0.0
        self._classes: Optional[np.ndarray] = None

    @property
    def classes_(self) -> np.ndarray:
        assert self._classes is not None
        return self._classes

    def _as_matrix(self, X: Any) -> np.ndarray:
        if isinstance(X, pd.DataFrame):
            X = X.to_numpy()
        return np.asarray(X, dtype=np.float64)

    @staticmethod
    def _pad(arr: np.ndarray, value: float = 0) -> np.ndarray:
        """Pads rows to the next power of two so fold/dataset size changes
        don't trigger XLA recompilation."""
        n = arr.shape[0]
        target = max(8, 1 << (n - 1).bit_length())
        if target == n:
            return arr
        pad_shape = (target - n,) + arr.shape[1:]
        return np.concatenate([arr, np.full(pad_shape, value, arr.dtype)], axis=0)

    def fit(self, X: Any, y: Any) -> "GradientBoostedTreesModel":
        Xm = self._as_matrix(X)
        n, d = Xm.shape
        self._binner = _Binner(self.max_bin).fit(Xm)
        bins = jnp.asarray(self._pad(self._binner.transform(Xm)))
        self._n_bins = self._binner.n_bins
        self._n_nodes = 1 << self.max_depth

        if self.is_discrete:
            codes, classes = pd.factorize(np.asarray(y), sort=True)
            self._classes = np.asarray(classes)
            k = len(classes)
            counts = np.bincount(codes, minlength=k).astype(np.float64)
            if self.class_weight == "balanced":
                w = (len(codes) / (k * np.maximum(counts, 1.0)))[codes]
            else:
                w = np.ones(n)
            if k <= 2:
                self._objective = "binary"
                self._k = 1
                yv = codes.astype(np.float32)
                pos = float((w * yv).sum() / w.sum())
                pos = min(max(pos, 1e-6), 1 - 1e-6)
                base = np.array([np.log(pos / (1 - pos))], dtype=np.float32)
            else:
                self._objective = "multiclass"
                self._k = k
                # bound the k-trees-per-round cost
                self.n_estimators = min(self.n_estimators, max(40, 400 // k))
                yv = codes.astype(np.float32)
                priors = np.zeros(k)
                np.add.at(priors, codes, w)
                priors = np.maximum(priors / priors.sum(), 1e-9)
                base = np.log(priors).astype(np.float32)
        else:
            self._objective = "regression"
            self._k = 1
            yv = pd.to_numeric(pd.Series(np.asarray(y)), errors="coerce") \
                .to_numpy(dtype=np.float64)
            assert not np.isnan(yv).any(), "y must not contain NULLs"
            # Heavily right-skewed nonnegative targets (e.g. crime rates) fit
            # much better in log space; LightGBM's leaf-wise growth absorbs
            # skew implicitly, this is the depth-wise equivalent.
            std = yv.std()
            skew = float(((yv - yv.mean()) ** 3).mean() / (std ** 3)) if std > 0 else 0.0
            self._log_target = bool((yv >= 0).all() and skew > 2.0)
            if self._log_target:
                yv = np.log1p(yv)
            yv = yv.astype(np.float32)
            w = np.ones(n)
            base = np.array([float(yv.mean())], dtype=np.float32)
            self._classes = np.array([])

        self._base = base
        trees = _boost(
            bins, jnp.asarray(self._pad(np.asarray(yv, np.float32))),
            jnp.asarray(self._pad(np.asarray(w, np.float32))),
            self.n_estimators, self.max_depth, self._n_bins, self._n_nodes,
            self._objective, max(self._k, 1),
            self.learning_rate, self.reg_lambda, self.min_split_gain,
            self.min_child_weight, jnp.asarray(base))
        self._trees = jax.device_get(trees)
        return self

    def _raw_scores(self, X: Any) -> np.ndarray:
        Xm = self._as_matrix(X)
        n = Xm.shape[0]
        bins = jnp.asarray(self._pad(self._binner.transform(Xm)))
        feats, thrs, leaves = (jnp.asarray(t) for t in self._trees)
        F = _predict_boosted(bins, feats, thrs, leaves, self.n_estimators,
                             self.max_depth, self._objective, max(self._k, 1),
                             jnp.asarray(self._base))
        F = np.asarray(F)
        return F[..., :n]

    def predict_proba(self, X: Any) -> np.ndarray:
        assert self.is_discrete
        F = self._raw_scores(X)
        if self._objective == "binary":
            p = 1.0 / (1.0 + np.exp(-F))
            return np.stack([1 - p, p], axis=1)
        z = F - F.max(axis=0, keepdims=True)
        e = np.exp(z)
        return (e / e.sum(axis=0, keepdims=True)).T

    def predict(self, X: Any) -> np.ndarray:
        if self.is_discrete:
            return self.classes_[self.predict_proba(X).argmax(axis=1)]
        pred = self._raw_scores(X)
        if getattr(self, "_log_target", False):
            pred = np.expm1(pred)
        return pred
