"""Histogram gradient-boosted decision trees in pure JAX — the flagship
repair-model family, replacing LightGBM (reference train.py:89-229).

TPU-first design:
* features are quantile-binned once into an ``int32[n, d]`` bin tensor
  (NaN/missing = bin 0), so each boosting round is dense integer arithmetic;
* trees grow depth-wise with FIXED shapes: level ``t`` owns node ids
  ``[0, 2^t)``, histograms are ``[2^D, d, B]`` scatter-adds (XLA lowers them
  to one-hot matmuls on the MXU), and split selection is an argmax over the
  padded (feature, bin) grid — no data-dependent control flow;
* the whole boosting loop is a single ``lax.scan`` over rounds, multiclass
  trains K trees per round via ``vmap`` over the class axis.

Objectives: L2 regression, binary logistic, multiclass softmax — with
balanced class weights like the reference's `class_weight='balanced'`
(train.py:105), which drives its characteristic minority-class repairs.
"""

import os
from functools import lru_cache, partial
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd

from delphi_tpu.parallel.resilience import run_guarded

MAX_MULTICLASS = 24


def _donation_enabled() -> bool:
    """Whether top-level boosting launches donate the margin-carry buffer
    (F0) to the output: the carry is the largest live tensor of a chunked
    fit, and donation lets XLA reuse its HBM allocation in place instead of
    holding input and output simultaneously. ``DELPHI_DONATE`` (1/0)
    forces; the auto default donates everywhere except the CPU backend,
    where XLA ignores donation and warns about it."""
    raw = os.environ.get("DELPHI_DONATE")
    if raw is not None:
        v = raw.strip().lower()
        if v in ("1", "true", "on", "yes"):
            return True
        if v in ("0", "false", "off", "no"):
            return False
    return jax.default_backend() != "cpu"


def gbdt_supported(is_discrete: bool, num_class: int) -> bool:
    """K class-trees per round get expensive fast; very wide multiclass
    targets route to the logistic head instead (train.py)."""
    return (not is_discrete) or num_class <= MAX_MULTICLASS


# ---------------------------------------------------------------------------
# Binning
# ---------------------------------------------------------------------------

class _Binner:
    """Quantile binning; bin 0 is reserved for NaN/missing."""

    def __init__(self, max_bin: int) -> None:
        self.max_bin = max_bin
        self.edges: List[np.ndarray] = []

    def fit(self, X: np.ndarray) -> "_Binner":
        self.edges = []
        for j in range(X.shape[1]):
            col = X[:, j]
            col = col[~np.isnan(col)]
            uniq = np.unique(col)
            if len(uniq) <= 1:
                self.edges.append(np.array([np.inf]))
            elif len(uniq) <= self.max_bin:
                self.edges.append((uniq[1:] + uniq[:-1]) / 2.0)
            else:
                qs = np.quantile(col, np.linspace(0, 1, self.max_bin + 1)[1:-1])
                self.edges.append(np.unique(qs))
        return self

    @property
    def n_bins(self) -> int:
        # Fixed at max_bin+1 (not the data-dependent max edge count) so every
        # target column compiles against the same histogram width — one XLA
        # program serves the whole per-attribute model loop.
        return self.max_bin + 1

    def transform(self, X: np.ndarray) -> np.ndarray:
        n, d = X.shape
        out = np.zeros((n, d), dtype=np.int32)
        for j in range(d):
            col = X[:, j]
            bins = np.searchsorted(self.edges[j], col, side="left") + 1
            out[:, j] = np.where(np.isnan(col), 0, bins)
        return out


# ---------------------------------------------------------------------------
# Tree building / prediction kernels
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("depth", "n_bins", "n_nodes", "axis_name",
                                   "use_scatter", "use_counts",
                                   "hess_is_weight"))
def _build_tree(bins, grad, hess, weight, depth, n_bins, n_nodes,
                reg_lambda, min_split_gain, min_child_weight,
                min_child_samples, axis_name=None, bin1h2d=None,
                use_scatter=None, use_counts=True, hess_is_weight=False):
    """Grows one depth-wise tree. Returns (feat[int32 n_nodes-1],
    thr[int32 n_nodes-1], leaf[f32 n_nodes]) with all-left sentinel splits
    (thr = n_bins) for terminated nodes. Rows with weight 0 (padding /
    held-out CV rows) are excluded from the row count: ``min_child_samples``
    bounds the UNWEIGHTED participating rows per child (LightGBM's
    min_child_samples) so heavily-upweighted rare classes cannot carve
    single-row leaves.

    The histogram channel set is STATIC: the counts channel exists only
    when ``min_child_samples`` is actually in play (``use_counts``), and
    for the L2 objective hessian == weight (``hess_is_weight``) so the
    weight channel is dropped — per level that's 2 channels instead of 4
    for regression and 3 for default classification, directly scaling the
    histogram contraction (MXU rows on TPU, segment adds on CPU)."""
    n, d = bins.shape

    feat = jnp.zeros(n_nodes - 1, dtype=jnp.int32)
    thr = jnp.full(n_nodes - 1, n_bins, dtype=jnp.int32)
    node = jnp.zeros(n, dtype=jnp.int32)

    # Histogram strategy is platform-static. TPU: one-hot MATMULS — scatters
    # serialize on the VPU (measured ~100x slower here and able to crash the
    # worker in large vmapped batches), while hist[l,f,b] =
    # sum_n node1h[n,l] * val[n] * bin1h[n,f,b] is exactly an
    # (C*n_level, n) @ (n, d*B) contraction the MXU eats. bin1h is
    # loop-invariant — callers that build many trees (the boosting scan's
    # class-tree vmap) pass it in so it materializes once, not per tree.
    # CPU: segment-sum scatter-adds — O(n*d) work instead of the matmul's
    # O(n*d*B) FLOPs; XLA:CPU lowers them to decent serial scatter loops
    # (measured ~4x faster end-to-end on the CV grid at B=64).
    if use_scatter is None:
        use_scatter = jax.default_backend() == "cpu"
    if bin1h2d is None and not use_scatter:
        bin1h2d = jax.nn.one_hot(bins, n_bins,
                                 dtype=jnp.float32).reshape(n, d * n_bins)
    channels = [grad, hess]
    w_slot = 1 if hess_is_weight else len(channels)
    if not hess_is_weight:
        channels.append(weight)
    c_slot = len(channels) if use_counts else -1
    if use_counts:
        channels.append((weight > 0).astype(jnp.float32))
    vals = jnp.stack(channels)  # (C, n)
    C = len(channels)

    for level in range(depth):
        n_level = 1 << level
        if use_scatter:
            seg = (node[:, None] * d + jnp.arange(d)[None, :]) * n_bins + bins
            data = jnp.broadcast_to(vals[:, :, None], (C, n, d))
            hist = jax.vmap(lambda v: jax.ops.segment_sum(
                v.reshape(-1), seg.reshape(-1),
                num_segments=n_level * d * n_bins))(
                data.reshape(C, n * d)).reshape(C, n_level, d, n_bins)
        else:
            node1h = jax.nn.one_hot(node, n_level, dtype=jnp.float32)  # (n, l)
            weighted = vals[:, :, None] * node1h[None]  # (C, n, n_level)
            lhs = weighted.transpose(0, 2, 1).reshape(C * n_level, n)
            # HIGHEST precision: the TPU's default matmul mode rounds f32
            # operands to bf16, which perturbs split gains enough to flip
            # near-tie argmaxes vs the exact-sum semantics
            hist = jax.lax.dot_general(
                lhs, bin1h2d, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST)  # (C*n_level, d*B)
            hist = hist.reshape(C, n_level, d, n_bins)

        if axis_name is not None:
            # rows are sharded over the mesh: local histograms reduce over
            # ICI — the TPU form of the reference's Spark shuffle (P1/P2)
            hist = jax.lax.psum(hist, axis_name)
        hg, hh, hw = hist[0], hist[1], hist[w_slot]

        GL = jnp.cumsum(hg, axis=2)
        HL = jnp.cumsum(hh, axis=2)
        WL = jnp.cumsum(hw, axis=2)
        G = GL[:, :, -1:]
        H = HL[:, :, -1:]
        W = WL[:, :, -1:]
        GR, HR, WR = G - GL, H - HL, W - WL

        gain = (GL * GL / (HL + reg_lambda)
                + GR * GR / (HR + reg_lambda)
                - G * G / (H + reg_lambda))
        ok = (WL >= min_child_weight) & (WR >= min_child_weight)
        if use_counts:
            CL = jnp.cumsum(hist[c_slot], axis=2)
            Ct = CL[:, :, -1:]
            CR = Ct - CL
            ok = ok & (CL >= min_child_samples) & (CR >= min_child_samples)
        gain = jnp.where(ok, gain, -jnp.inf)
        # never split on the last bin (right side empty by construction)
        gain = gain.at[:, :, -1].set(-jnp.inf)

        flat_gain = gain.reshape(n_level, d * n_bins)
        best = jnp.argmax(flat_gain, axis=1)
        best_gain = jnp.take_along_axis(flat_gain, best[:, None], axis=1)[:, 0]
        best_f = (best // n_bins).astype(jnp.int32)
        best_b = (best % n_bins).astype(jnp.int32)
        do_split = best_gain > min_split_gain
        best_f = jnp.where(do_split, best_f, 0)
        best_b = jnp.where(do_split, best_b, n_bins)  # sentinel: all rows left

        offset = n_level - 1
        feat = jax.lax.dynamic_update_slice(feat, best_f, (offset,))
        thr = jax.lax.dynamic_update_slice(thr, best_b, (offset,))

        go_right = bins[jnp.arange(n), best_f[node]] > best_b[node]
        node = node * 2 + go_right.astype(jnp.int32)

    leaf1h = jax.nn.one_hot(node, n_nodes, dtype=jnp.float32)  # (n, n_nodes)
    leaf_gh = jnp.matmul(jnp.stack([grad, hess]), leaf1h,
                         precision=jax.lax.Precision.HIGHEST)  # (2, n_nodes)
    if axis_name is not None:
        leaf_gh = jax.lax.psum(leaf_gh, axis_name)
    leaf = -leaf_gh[0] / (leaf_gh[1] + reg_lambda)
    return feat, thr, leaf, node


@partial(jax.jit, static_argnames=("depth",))
def _predict_tree(bins, feat, thr, leaf, depth):
    n = bins.shape[0]
    node = jnp.zeros(n, dtype=jnp.int32)
    for level in range(depth):
        offset = (1 << level) - 1
        f = feat[offset + node]
        b = thr[offset + node]
        go_right = bins[jnp.arange(n), f] > b
        node = node * 2 + go_right.astype(jnp.int32)
    return leaf[node]


# ---------------------------------------------------------------------------
# Boosting
# ---------------------------------------------------------------------------

# Boosting runs in fixed-size chunks of this many rounds: ONE compiled chunk
# program serves every total round count (25, 50, ... 200), which is what
# makes per-target early stopping free of recompilation — the reference gets
# the same effect from LightGBM's dynamic `early_stopping_rounds`
# (train.py:193-200) because its trees are built by interpreted C++.
_CHUNK_ROUNDS = 25

# CV macro-F1 past which further search cannot pay for itself: repair picks
# argmax cells, so a config above this is essentially solved and both the
# within-group chunk loop and the cross-group loop stop here.
_GOOD_ENOUGH_F1 = 0.995


def _round_chunks(n_rounds: int) -> List[int]:
    # boost-chunk policy lives in the unified launch planner (two compiled
    # variants max: the fixed chunk plus one remainder)
    from delphi_tpu.parallel import planner
    return planner.round_chunks(n_rounds, _CHUNK_ROUNDS)


_BOOST_STATIC = ("n_rounds", "depth", "n_bins", "n_nodes", "objective", "k",
                 "axis_name", "collect_trees", "use_counts")


def _boost_impl(bins, y, weight, F0, n_rounds, depth, n_bins, n_nodes,
                objective, k, lr, reg_lambda, min_split_gain,
                min_child_weight, min_child_samples=20.0, axis_name=None,
                collect_trees=True, use_counts=True):
    """Runs ``n_rounds`` boosting rounds as one lax.scan, RESUMING from the
    margin state ``F0`` (rows-first: [n], or [n, k] for multiclass — the
    layout row sharding understands). Returns (F, stacked trees), F
    rows-first again, so fits advance in fixed-size chunks with the carry
    living on device between launches. ``collect_trees=False`` drops the
    stacked tree outputs (the CV scorer only needs the margins — the carry
    F IS the model's prediction on every row, held-out weight-0 rows
    included, so CV never runs a separate predict pass)."""
    n = bins.shape[0]

    def grad_hess(F):
        if objective == "regression":
            return (F - y)[None, :] * weight[None, :], weight[None, :]
        if objective == "binary":
            p = jax.nn.sigmoid(F)
            return ((p - y) * weight)[None, :], \
                jnp.maximum(p * (1 - p), 1e-6)[None, :] * weight[None, :]
        # multiclass softmax: F is [k, n]
        p = jax.nn.softmax(F, axis=0)
        onehot = jax.nn.one_hot(y.astype(jnp.int32), k, axis=0, dtype=jnp.float32)
        return (p - onehot) * weight[None, :], \
            jnp.maximum(p * (1 - p), 1e-6) * weight[None, :]

    use_scatter = jax.default_backend() == "cpu"
    bin1h2d = None if use_scatter else \
        jax.nn.one_hot(bins, n_bins, dtype=jnp.float32) \
        .reshape(n, bins.shape[1] * n_bins)

    def one_round(F, _):
        g, h = grad_hess(F)

        def build(gk, hk):
            return _build_tree(bins, gk, hk, weight, depth, n_bins, n_nodes,
                               reg_lambda, min_split_gain, min_child_weight,
                               min_child_samples, axis_name, bin1h2d,
                               use_scatter=use_scatter,
                               use_counts=use_counts,
                               hess_is_weight=(objective == "regression"))

        feat, thr, leaf, node = jax.vmap(build)(g, h)  # [k_trees, ...]
        leaf = leaf * lr
        delta = jnp.take_along_axis(leaf, node, axis=1)  # [k_trees, n]
        F = F + (delta[0] if objective != "multiclass" else delta)
        return F, ((feat, thr, leaf) if collect_trees else None)

    F_init = F0.T if objective == "multiclass" else F0
    F, trees = jax.lax.scan(one_round, F_init, None, length=n_rounds)
    F_out = F.T if objective == "multiclass" else F
    return (F_out, trees) if collect_trees else F_out


# Jitted alias every in-graph caller traces through (jit is transparent
# under an outer jit/vmap/shard_map, so nested use inlines).
_boost = partial(jax.jit, static_argnames=_BOOST_STATIC)(_boost_impl)


@lru_cache(maxsize=2)
def _boost_chunk_fn(donate: bool):
    """Top-level chunked-fit entry. Donation aliases the F0 carry buffer to
    the output F so the carry's HBM allocation is reused in place across
    chunk launches. Aliasing is part of the compiled executable (and the
    persistent compile-cache key), so AOT prewarm must compile through the
    SAME callable the runtime launches — hence this shared accessor rather
    than per-call jit wrappers."""
    if not donate:
        return _boost
    return jax.jit(_boost_impl, static_argnames=_BOOST_STATIC,
                   donate_argnums=(3,))


def _init_margin(base: np.ndarray, n: int, objective: str, k: int) -> np.ndarray:
    """Rows-first initial margin state from per-class base scores."""
    base = np.asarray(base, np.float32)
    if objective == "multiclass":
        return np.broadcast_to(base[None, :], (n, k)).copy()
    return np.full((n,), base[0], np.float32)


def train_row_target(n: int, mesh: Any = None) -> int:
    """Training-row pad target: power of two below 4096 (the recompilation
    bound matters most for tiny per-attribute fits), then the next multiple
    of 2048. The training path is capped by `model.max_training_row_num`
    (10k default), so the variant count stays small while the default cap
    pads 10000 -> 10240 instead of 16384 — a free 1.6x on every histogram
    and gather in phases 2's hot loops. Prediction keeps power-of-two
    padding: dirty-row counts vary per attribute, so fine-grained targets
    there would multiply compiled variants."""
    if n <= 4096:
        from delphi_tpu.parallel.mesh import padded_row_target
        return padded_row_target(n, mesh)
    target = -(-n // 2048) * 2048
    if mesh is not None:
        dp = int(mesh.shape["dp"])
        target = -(-target // dp) * dp
    return target


@partial(jax.jit, static_argnames=("n_rounds", "depth", "objective", "k",
                                   "axis_name"))
def _predict_boosted(bins, feats, thrs, leaves, n_rounds, depth, objective, k,
                     base_score, axis_name=None):
    n = bins.shape[0]

    def score_tree(carry, tree):
        feat, thr, leaf = tree

        def one(fa, ta, la):
            return _predict_tree(bins, fa, ta, la, depth)

        delta = jax.vmap(one)(feat, thr, leaf)  # [k_trees, n]
        return carry + (delta[0] if objective != "multiclass" else delta), None

    if objective == "multiclass":
        F0 = jnp.broadcast_to(base_score[:, None], (k, n))
    else:
        F0 = jnp.full((n,), base_score[0])
    if axis_name is not None:
        # newer jax demands an explicit varying cast inside shard_map;
        # 0.4.x has neither pcast nor pvary and infers it from use
        if hasattr(jax.lax, "pcast"):
            F0 = jax.lax.pcast(F0, (axis_name,), to="varying")
        elif hasattr(jax.lax, "pvary"):
            F0 = jax.lax.pvary(F0, (axis_name,))
    F, _ = jax.lax.scan(score_tree, F0, (feats, thrs, leaves))
    return F


# ---------------------------------------------------------------------------
# Multi-chip (mesh) training and inference
# ---------------------------------------------------------------------------

@lru_cache(maxsize=128)
def _mesh_boost_fn(mesh, n_rounds, depth, n_bins, n_nodes, objective, k,
                   lr, reg_lambda, min_split_gain, min_child_weight,
                   min_child_samples):
    """Cached, jitted shard_map program for one (mesh, hyperparameter)
    combination — per-attribute fits with the same shapes reuse the same
    compiled executable instead of retracing. Takes and returns the
    rows-first margin carry (sharded over dp) so chunked fits resume
    across launches without gathering F."""
    from jax.sharding import PartitionSpec as P

    from delphi_tpu.parallel.mesh import shard_map

    def fn(bins_l, y_l, w_l, F0_l):
        return _boost(bins_l, y_l, w_l, F0_l, n_rounds, depth, n_bins,
                      n_nodes, objective, k, lr, reg_lambda, min_split_gain,
                      min_child_weight, min_child_samples, axis_name="dp",
                      use_counts=min_child_samples > 0)

    F_spec = P("dp", None) if objective == "multiclass" else P("dp")
    return jax.jit(shard_map(
        fn, mesh=mesh,
        in_specs=(P("dp", None), P("dp"), P("dp"), F_spec),
        out_specs=(F_spec, (P(), P(), P()))),
        donate_argnums=(3,) if _donation_enabled() else ())


@lru_cache(maxsize=128)
def _mesh_predict_fn(mesh, n_rounds, depth, objective, k):
    from jax.sharding import PartitionSpec as P

    from delphi_tpu.parallel.mesh import shard_map

    # Multi-host: row-sharded predictions span processes, so they
    # all-gather to every device and each host reads the full vector
    # (single-host meshes skip the collective and fetch the sharded array).
    multihost = jax.process_count() > 1
    row_axis = 1 if objective == "multiclass" else 0

    def fn(bins_l, feats, thrs, leaves, base):
        F = _predict_boosted(bins_l, feats, thrs, leaves, n_rounds,
                             depth, objective, k, base, axis_name="dp")
        if multihost:
            F = jax.lax.all_gather(F, "dp", axis=row_axis, tiled=True)
        return F

    if multihost:
        from delphi_tpu.parallel.mesh import shard_map_unchecked as smap
        out_spec = P()
    else:
        smap = shard_map
        out_spec = P(None, "dp") if objective == "multiclass" else P("dp")
    return jax.jit(smap(
        fn, mesh=mesh,
        in_specs=(P("dp", None), P(), P(), P(), P()),
        out_specs=out_spec))


def _mesh_predict(mesh, bins, feats, thrs, leaves, n_rounds, depth,
                  objective, k, base):
    """Row-sharded batched inference over the mesh (reference P3: the
    grouped-map repair UDF, model.py:1054-1135). No collectives: every
    device scores its own row shard against the replicated trees."""
    from delphi_tpu.parallel.mesh import shard_rows

    fn = _mesh_predict_fn(mesh, n_rounds, depth, objective, k)
    return fn(shard_rows(bins, mesh), jnp.asarray(feats), jnp.asarray(thrs),
              jnp.asarray(leaves), jnp.asarray(base))


# ---------------------------------------------------------------------------
# Batched cross-validation grid search
# ---------------------------------------------------------------------------

def _cv_stats(F, y, val_mask, y_cmp, log_flag, inv_scale, cw_corr,
              class_valid, objective, kk, axis_name):
    """On-device CV scoring statistics from the boosting margin carry:
    a [kk, kk] confusion-count matrix over the held-out rows for
    classifiers (val_mask picks the fold's real rows; padding rows carry
    mask 0), or [scaled sse, count] for regressors — tiny tensors, so early
    stopping never fetches full prediction vectors to the host.

    Regression errors are normalized by the target's RMS (``inv_scale``)
    before the f32 accumulation: large-magnitude targets (salary-scale SSE
    ~1e13) would otherwise lose ~7 significant digits in float32 — f64 is
    not an option on TPU. The host rescales the SSE back in float64, so the
    reported score keeps the reference's -MSE semantics."""
    if objective == "regression":
        pred = jnp.where(log_flag > 0, jnp.expm1(F), F)
        err = (pred - y_cmp) * inv_scale
        out = jnp.stack([jnp.sum(val_mask * err * err),
                         jnp.sum(val_mask)])
    else:
        if objective == "binary":
            p = jax.nn.sigmoid(F)
            # deploy-parity: importance-correct back to true priors before
            # the argmax, exactly as predict_proba does
            pred = (p / cw_corr[1] > (1 - p) / cw_corr[0]).astype(jnp.int32)
        else:
            logp = jax.nn.log_softmax(F, axis=1)  # [n, k]
            adj = logp - jnp.log(cw_corr)[None, :]
            adj = jnp.where(class_valid[None, :] > 0, adj, -jnp.inf)
            pred = jnp.argmax(adj, axis=1).astype(jnp.int32)
        idx = y.astype(jnp.int32) * kk + pred
        out = jax.ops.segment_sum(val_mask, idx,
                                  num_segments=kk * kk).reshape(kk, kk)
    if axis_name is not None:
        out = jax.lax.psum(out, axis_name)
    return out


@lru_cache(maxsize=128)
def _cv_chunk_fn(mesh, chunk, depth, n_bins, n_nodes, objective, k):
    """One early-stopping CV step: every (instance, config) pair of a shape
    group advances ``chunk`` boosting rounds from its carried margin state
    and scores its held-out rows on device. An INSTANCE is a (target, fold)
    pair — the single-target search stacks its folds, and the batched
    multi-target path (reference P2, the pandas-UDF training fan-out,
    model.py:817-926) stacks every pending target's folds into the same
    launch, which is what turns phase 2 from N small sequential fits into a
    few device-saturating ones. Per-instance scoring tensors (y_cmp,
    cw_corr, class_valid, inv_scale) ride the vmapped axis so instances
    from different targets scored correctly. Under a mesh, rows shard over
    dp with psum'd histograms."""
    axis_name = "dp" if mesh is not None else None
    kk = 2 if objective == "binary" else max(k, 1)

    def fn(bins, y_, weight, val_mask, y_cmp, log_flag, inv_scale, cw_corr,
           class_valid, F, lrs, reg_lambdas, min_split_gains,
           min_child_weights):
        def one(F1, lr, reg_lambda, min_split_gain, min_child_weight):
            F2 = _boost(bins, y_, weight, F1, chunk, depth, n_bins, n_nodes,
                        objective, k, lr, reg_lambda, min_split_gain,
                        min_child_weight, 0.0, axis_name=axis_name,
                        collect_trees=False, use_counts=False)
            stats = _cv_stats(F2, y_, val_mask, y_cmp, log_flag, inv_scale,
                              cw_corr, class_valid, objective, kk, axis_name)
            return F2, stats

        return jax.vmap(one)(F, lrs, reg_lambdas, min_split_gains,
                             min_child_weights)

    # The margin carry F (arg 9) is donated between chunk launches: every
    # caller rebinds it (``sd["F"], s = fn(..., sd["F"], ...)``), and it is
    # the dominant live tensor of the whole CV search.
    donate = (9,) if _donation_enabled() else ()
    if mesh is None:
        # Single device: batch the instance axis into the same launch too —
        # (instances × configs) advance in one XLA program per chunk.
        return jax.jit(jax.vmap(
            fn, in_axes=(0, 0, 0, 0, 0, 0, 0, 0, 0, 0,
                         None, None, None, None)), donate_argnums=donate)

    from jax.sharding import PartitionSpec as P

    from delphi_tpu.parallel.mesh import shard_map

    F_spec = P(None, "dp", None) if objective == "multiclass" \
        else P(None, "dp")
    return jax.jit(shard_map(
        fn, mesh=mesh,
        in_specs=(P("dp", None), P("dp"), P("dp"), P("dp"), P("dp"), P(),
                  P(), P(), P(), F_spec, P(), P(), P(), P()),
        out_specs=(F_spec, P())), donate_argnums=donate)


def aot_compile_cv_chunk(*, chunk: int, depth: int, n_bins: int,
                         n_nodes: int, objective: str, k: int, width: int,
                         n_cfg: int, n_pad: int, d_pad: int) -> Any:
    """Ahead-of-time lowers and compiles one single-device CV-chunk shape
    variant — the phase-2 hot program — so the first real launch of that
    shape finds a warm executable (in-process jit cache via the identical
    lowering, cross-process via the persistent compile cache). Compiles
    through the exact callable :func:`_cv_chunk_fn` hands the runtime:
    donation/aliasing is part of the executable, so a lookalike wrapper
    would warm a different cache key."""
    from jax import ShapeDtypeStruct as S
    fn = _cv_chunk_fn(None, chunk, depth, n_bins, n_nodes, objective, k)
    kk = 2 if objective == "binary" else max(k, 1)
    f32 = jnp.float32
    F = S((width, n_cfg, n_pad, k), f32) if objective == "multiclass" \
        else S((width, n_cfg, n_pad), f32)
    return fn.lower(
        S((width, n_pad, d_pad), jnp.int32),            # bins
        S((width, n_pad), f32), S((width, n_pad), f32),  # y, weight
        S((width, n_pad), f32), S((width, n_pad), f32),  # val_mask, y_cmp
        S((width,), f32), S((width,), f32),              # log_flag, inv_scale
        S((width, kk), f32), S((width, kk), f32),        # cw_corr, class_valid
        F,
        S((n_cfg,), f32), S((n_cfg,), f32),              # lrs, reg_lambdas
        S((n_cfg,), f32), S((n_cfg,), f32),              # msgs, mcws
    ).compile()


def _f1_from_confusion(conf: np.ndarray, k_real: int) -> float:
    """Macro-F1 from a confusion-count matrix, averaging over the classes
    present in the fold's truth — identical semantics to
    ``encoding.f1_macro`` (classes = unique(y_true))."""
    conf = np.asarray(conf, np.float64)[:k_real, :k_real]
    truth_counts = conf.sum(axis=1)
    f1s = []
    for c in range(k_real):
        if truth_counts[c] <= 0:
            continue
        tp = conf[c, c]
        fp = conf[:, c].sum() - tp
        fn = truth_counts[c] - tp
        p = tp / (tp + fp) if tp + fp > 0 else 0.0
        r = tp / (tp + fn) if tp + fn > 0 else 0.0
        f1s.append(2 * p * r / (p + r) if p + r > 0 else 0.0)
    return float(np.mean(f1s)) if f1s else 0.0


def _cv_prepare_target(X: Any, y: Any, is_discrete: bool, n_splits: int,
                       class_weight: str,
                       template: "GradientBoostedTreesModel",
                       mesh: Any) -> Optional[dict]:
    """Per-target CV preprocessing shared by the single- and multi-target
    grid searches: factorized labels + balanced weights, per-fold binning
    (bin edges and the regression log-target decision come from the fold's
    TRAINING rows only, so an instance's scores match a standalone per-fold
    fit), padded fold tensors, and the per-target scoring constants.
    Returns None when no fold is usable (degenerate labels)."""
    Xm = template._as_matrix(X)
    n = Xm.shape[0]
    y_arr = np.asarray(y)
    per_class_w = None
    yv64 = None
    if is_discrete:
        codes, classes = pd.factorize(y_arr, sort=True)
        k_real = len(classes)
        counts = np.bincount(codes, minlength=k_real).astype(np.float64)
        if class_weight == "balanced":
            from delphi_tpu.models.encoding import balanced_class_weights
            per_class_w = balanced_class_weights(counts, len(codes))
            w_full = per_class_w[codes]
        else:
            w_full = np.ones(n)
        if k_real <= 2:
            objective, k = "binary", 1
        else:
            objective = "multiclass"
            k = next(b for b in (4, 8, 16, 24, MAX_MULTICLASS) if b >= k_real)
        yv = codes.astype(np.float32)
        kk = 2 if objective == "binary" else k
        cw_corr = np.ones(kk, np.float32)
        if per_class_w is not None:
            m = min(k_real, kk)
            cw_corr[:m] = per_class_w[:m]
        class_valid = (np.arange(kk) < k_real).astype(np.float32)
        y_cmp = np.zeros(n, np.float32)  # unused for classifiers
        scale = 1.0
    else:
        objective, k, k_real = "regression", 1, 0
        yv64 = pd.to_numeric(pd.Series(y_arr), errors="coerce") \
            .to_numpy(dtype=np.float64)
        yv = yv64.astype(np.float32)
        w_full = np.ones(n)
        cw_corr = np.ones(1, np.float32)
        class_valid = np.ones(1, np.float32)
        y_cmp = yv64.astype(np.float32)  # original-space comparison target
        # RMS normalizer: the on-device SSE accumulates in f32, which loses
        # ~7 significant digits on raw salary-scale targets; errors are
        # scored as (err/scale)^2 on device and rescaled in f64 on host
        scale = float(np.sqrt(np.mean(yv64 ** 2))) if n else 1.0
        if not np.isfinite(scale) or scale <= 0:
            scale = 1.0

    rng = np.random.RandomState(42)
    order = rng.permutation(n)
    folds = np.array_split(order, max(2, min(n_splits, n)))
    folds = [f for f in folds if len(f)]

    n_pad = template._pad(np.zeros(n, np.float32), mesh=mesh,
                          train=True).shape[0]

    fold_bins, fold_y, fold_log = [], [], []
    for fold in folds:
        train_mask = np.ones(n, dtype=bool)
        train_mask[fold] = False
        binner_f = _Binner(template.max_bin).fit(Xm[train_mask])
        fold_bins.append(template._pad(template._pad_feature_dim(
            binner_f.transform(Xm)), mesh=mesh, train=True))
        if is_discrete:
            fold_y.append(template._pad(yv, mesh=mesh, train=True))
            fold_log.append(False)
        else:
            ytr = yv64[train_mask]
            std = ytr.std()
            skew = float(((ytr - ytr.mean()) ** 3).mean() / (std ** 3)) \
                if std > 0 else 0.0
            log_f = bool((ytr >= 0).all() and skew > 2.0)
            yv_f = (np.log1p(yv64) if log_f else yv64).astype(np.float32)
            fold_y.append(template._pad(yv_f, mesh=mesh, train=True))
            fold_log.append(log_f)

    instances = []
    for fi, fold in enumerate(folds):
        train_mask = np.ones(n, dtype=bool)
        train_mask[fold] = False
        if is_discrete and len(np.unique(yv[train_mask])) < 2:
            continue
        w = np.where(train_mask, w_full, 0.0).astype(np.float32)
        yv_f = fold_y[fi][:n]
        if objective == "binary":
            pos = float((w * yv_f).sum() / max(w.sum(), 1e-9))
            pos = min(max(pos, 1e-6), 1 - 1e-6)
            base = np.array([np.log(pos / (1 - pos))], dtype=np.float32)
        elif objective == "multiclass":
            priors = np.zeros(k)
            np.add.at(priors, yv_f.astype(np.int64), w)
            priors = np.maximum(priors / max(priors.sum(), 1e-9), 1e-13)
            base = np.log(priors).astype(np.float32)
        else:
            base = np.array(
                [float((w * yv_f).sum() / max(w.sum(), 1e-9))], np.float32)

        val = np.zeros(n_pad, np.float32)
        val[fold] = 1.0
        instances.append(dict(
            bins=fold_bins[fi], y=fold_y[fi],
            w=template._pad(w, mesh=mesh, train=True), val=val, base=base,
            log=1.0 if fold_log[fi] else 0.0))

    if not instances:
        return None
    return dict(
        objective=objective, k=k, k_real=k_real, n=n, n_pad=n_pad,
        d_pad=int(instances[0]["bins"].shape[1]),
        n_bins=template.max_bin + 1, y_cmp=template._pad(
            y_cmp, mesh=mesh, train=True),
        scale=scale, cw_corr=cw_corr, class_valid=class_valid,
        template=template, is_discrete=is_discrete, instances=instances)


def _cfg_rounds_for(cfg: dict, objective: str, k: int) -> int:
    r = min(int(cfg.get("n_estimators", 200)), 200)
    if objective == "multiclass":
        r = min(r, max(40, 400 // k))
    return r


# Instance-axis width per CV launch: bounds both device memory (the TPU
# histogram path materializes a [W, n, d*n_bins] one-hot) and the number of
# compiled slab-width variants (tails pad to powers of two).
_CV_INSTANCE_CAP = 16


def gbdt_cv_grid_search_multi(preps: List[Optional[dict]],
                              configs: List[dict], timeout_s: float = 0.0,
                              good_enough: float = _GOOD_ENOUGH_F1) \
        -> List[Tuple[int, float, int, bool]]:
    """Chunked early-stopping K-fold CV grid search over MANY targets in
    shared device launches — the batched replacement for the reference's
    per-attribute pandas-UDF training fan-out (reference model.py:817-926):
    every (target, fold) pair whose static shape matches ((depth, rounds)
    config group, padded rows/features, objective, class bucket) stacks
    into ONE vmapped launch per boosting chunk, so N per-attribute searches
    cost a few device-saturating programs instead of N small sequential
    ones.

    Per-target bookkeeping reproduces the single-target semantics exactly:
    classifiers rank by their best checkpoint with 2-chunk patience,
    regressors by the latest horizon; a perfect or good-enough macro-F1
    retires the target from ALL remaining groups. A retired target's
    instances keep advancing inside already-stacked launches (they cannot
    leave a compiled program), but their stats are frozen — results match
    the sequential path.

    Returns one (best config index, mean CV score, best round count,
    timed_out) tuple per prep; a None prep yields (0, -inf, 0, False).
    ``timed_out`` distinguishes a deadline-truncated search from a genuine
    early stop, so callers only shrink the final fit's round budget when
    the round count was actually CV-proven."""
    import os
    import time
    deadline = time.monotonic() + timeout_s if timeout_s > 0 else None

    from delphi_tpu.parallel.mesh import get_active_mesh
    mesh = get_active_mesh()

    T = len(preps)
    best_by_cfg: List[Dict[int, Tuple[float, int]]] = [{} for _ in range(T)]
    done = [p is None for p in preps]
    timed_out = False
    # timed is PER TARGET: a target retired (done) or fully searched before
    # the deadline keeps its CV-proven round count even when another
    # target's group later trips the deadline
    timed = [False] * T
    patience_chunks = 2
    eps = 1e-12
    # static per-instance tensors are identical across (depth, rounds)
    # config groups — place them once per distinct instance set, not once
    # per group (the single-target search alone has 2-3 groups per call)
    slab_static_cache: Dict[Tuple, Any] = {}
    mesh_static_cache: Dict[Tuple[int, int], Any] = {}

    from jax.sharding import NamedSharding, PartitionSpec as P

    def place(arr, spec):
        if mesh is None:
            return jnp.asarray(arr)
        sharding = NamedSharding(mesh, spec)
        if jax.process_count() > 1:
            return jax.make_array_from_callback(
                arr.shape, sharding,
                lambda idx: np.ascontiguousarray(np.asarray(arr)[idx]))
        return jax.device_put(np.asarray(arr), sharding)

    # Work units: a (depth, rounds) config group fused with the static
    # tensor dims; targets sharing a key share its launches. Insertion
    # order preserves each target's sequential group order.
    merged: Dict[Tuple, List[int]] = {}
    for t, prep in enumerate(preps):
        if prep is None:
            continue
        tgroups: Dict[Tuple[int, int], List[int]] = {}
        for ci, cfg in enumerate(configs):
            depth = int(cfg.get("max_depth", prep["template"].max_depth))
            rounds = _cfg_rounds_for(cfg, prep["objective"], prep["k"])
            tgroups.setdefault((depth, rounds), []).append(ci)
        for (depth, rounds), cfg_idx in tgroups.items():
            key = (depth, rounds, prep["n_pad"], prep["d_pad"],
                   prep["n_bins"], prep["objective"], prep["k"],
                   tuple(cfg_idx))
            merged.setdefault(key, []).append(t)

    for gi_group, (key, t_members) in enumerate(merged.items()):
        if timed_out:
            break
        (g_depth, g_rounds, n_pad, d_pad, n_bins, objective, k,
         cfg_tuple) = key
        members = [t for t in t_members if not done[t]]
        if not members:
            continue
        cfg_indices = list(cfg_tuple)
        n_cfg = len(cfg_indices)
        is_discrete = preps[members[0]]["is_discrete"]
        tmpl = preps[members[0]]["template"]
        lrs = jnp.asarray([configs[ci].get("learning_rate", 0.1)
                           for ci in cfg_indices], jnp.float32)
        regs = jnp.asarray([configs[ci].get("reg_lambda", 1.0)
                            for ci in cfg_indices], jnp.float32)
        msgs = jnp.asarray([tmpl.min_split_gain] * n_cfg, jnp.float32)
        mcws = jnp.asarray([configs[ci].get("min_child_weight", 1.0)
                            for ci in cfg_indices], jnp.float32)

        inst = [(t, j) for t in members
                for j in range(len(preps[t]["instances"]))]
        F_shape = (n_pad, k) if objective == "multiclass" else (n_pad,)

        def init_F(t, j):
            e = preps[t]["instances"][j]
            return np.broadcast_to(
                _init_margin(e["base"], n_pad, objective, k),
                (n_cfg,) + F_shape).copy()

        if mesh is not None:
            # rows shard over dp: instances launch one by one, like the
            # sequential mesh path; static tensors place once per instance
            # across all config groups
            F_spec_m = P(None, "dp", None) if objective == "multiclass" \
                else P(None, "dp")
            dev = []
            for (t, j) in inst:
                if (t, j) not in mesh_static_cache:
                    p, e = preps[t], preps[t]["instances"][j]
                    mesh_static_cache[(t, j)] = [
                        place(e["bins"], P("dp", None)),
                        place(e["y"], P("dp")), place(e["w"], P("dp")),
                        place(e["val"], P("dp")), place(p["y_cmp"], P("dp")),
                        jnp.float32(e["log"]),
                        jnp.float32(1.0 / p["scale"]),
                        jnp.asarray(p["cw_corr"]),
                        jnp.asarray(p["class_valid"])]
                dev.append(mesh_static_cache[(t, j)]
                           + [place(init_F(t, j), F_spec_m)])
            slabs = None
        else:
            # slab split + width via the unified launch planner; the plan
            # persists per table fingerprint so the compile plane prewarms
            # exactly the (width, shape) variants a warm request launches.
            # DELPHI_PLAN_CV_INSTANCE_CAP is the cap knob (legacy
            # DELPHI_CV_INSTANCE_CAP spelling honored with a warning).
            from delphi_tpu.parallel import planner
            cap = planner.cv_instance_cap(default=_CV_INSTANCE_CAP)
            slab_plan = planner.plan_launches(
                f"gbdt.cv[{gi_group}]",
                [planner.Piece(key=i, size=1,
                               shape=(g_depth, g_rounds, n_pad, d_pad,
                                      n_bins, objective, k, n_cfg))
                 for i in range(len(inst))],
                batch_cap=cap, pad_batch=(T > 1))
            slab_plan.record()
            slabs = [[inst[span.key] for span in launch.spans]
                     for launch in slab_plan.launches]
            slab_widths = [launch.batch_pad for launch in slab_plan.launches]

            def stack_pad(arrs, W, fill, dtype=None):
                out = np.stack([np.asarray(a) for a in arrs])
                if dtype is not None:
                    out = out.astype(dtype)
                if out.shape[0] < W:
                    pad = np.full((W - out.shape[0],) + out.shape[1:], fill,
                                  out.dtype)
                    out = np.concatenate([out, pad])
                return jnp.asarray(out)

            slab_data = []
            for slab, W in zip(slabs, slab_widths):
                # multi-target slabs pad the instance axis to a power of
                # two (few compiled width variants; dummy all-zero-weight
                # rows are cheap relative to a fresh compile); the
                # single-target search keeps its exact fold count — its
                # width never varies, so padding would only waste FLOPs
                skey = tuple(slab)
                if skey not in slab_static_cache:
                    es = [preps[t]["instances"][j] for (t, j) in slab]
                    ps = [preps[t] for (t, j) in slab]
                    slab_static_cache[skey] = dict(
                        bins=stack_pad([e["bins"] for e in es], W, 0),
                        y=stack_pad([e["y"] for e in es], W, 0),
                        w=stack_pad([e["w"] for e in es], W, 0),
                        val=stack_pad([e["val"] for e in es], W, 0),
                        ycmp=stack_pad([p["y_cmp"] for p in ps], W, 0),
                        log=stack_pad(
                            [np.float32(e["log"]) for e in es], W, 0),
                        iscale=stack_pad(
                            [np.float32(1.0 / p["scale"]) for p in ps],
                            W, 1),
                        cw=stack_pad([p["cw_corr"] for p in ps], W, 1),
                        valid=stack_pad(
                            [p["class_valid"] for p in ps], W, 1))
                slab_data.append(dict(
                    slab_static_cache[skey], n=len(slab),
                    F=stack_pad([init_F(t, j) for (t, j) in slab], W, 0)))

        rounds_done = 0
        active = {t: True for t in members}
        no_improve = {t: 0 for t in members}
        stats_buf: List[Any] = [None] * len(inst)
        for chunk in _round_chunks(g_rounds):
            # the retirement check runs BEFORE the deadline check: a search
            # that concluded naturally (patience / good-enough) must not be
            # reported as timed out just because the clock crossed the
            # deadline on the same iteration
            if not any(active[t] and not done[t] for t in members):
                break
            if deadline is not None and time.monotonic() > deadline:
                timed_out = True
                break
            fn = _cv_chunk_fn(mesh, chunk, g_depth, n_bins, 1 << g_depth,
                              objective, k)
            if mesh is not None:
                # per-instance launches: retired targets' instances simply
                # skip (their stats are frozen and never read again)
                rows = []
                for i, dvi in enumerate(dev):
                    t = inst[i][0]
                    if done[t] or not active[t]:
                        rows.append(stats_buf[i])
                        continue
                    dvi[9], s = run_guarded(
                        "gbdt.cv_chunk",
                        lambda dvi=dvi: fn(*dvi, lrs, regs, msgs, mcws))
                    rows.append(np.asarray(jax.device_get(s)))
                stats_buf = rows
                stats_np = np.stack(rows)
            else:
                parts = []
                for sd, launch in zip(slab_data, slab_plan.launches):
                    with slab_plan.launch_scope(launch):
                        sd["F"], s = run_guarded(
                            "gbdt.cv_chunk",
                            lambda sd=sd: fn(
                                sd["bins"], sd["y"], sd["w"], sd["val"],
                                sd["ycmp"], sd["log"], sd["iscale"],
                                sd["cw"], sd["valid"], sd["F"],
                                lrs, regs, msgs, mcws))
                    parts.append(np.asarray(jax.device_get(s))[:sd["n"]])
                stats_np = np.concatenate(parts, axis=0)
            rounds_done += chunk

            for t in members:
                if done[t] or not active[t]:
                    continue
                prep = preps[t]
                rows_t = [i for i, (tt, _) in enumerate(inst) if tt == t]
                improved = False
                for jj, ci in enumerate(cfg_indices):
                    fold_scores = []
                    for i in rows_t:
                        s = stats_np[i, jj]
                        if is_discrete:
                            fold_scores.append(
                                _f1_from_confusion(s, prep["k_real"]))
                        else:
                            # rescale the normalized SSE back in float64
                            sse = float(s[0]) * prep["scale"] ** 2
                            fold_scores.append(-sse / max(float(s[1]), 1.0))
                    mean = float(np.mean(fold_scores))
                    if is_discrete:
                        # classifiers rank by their best checkpoint, and
                        # the recorded round count sizes the final fit
                        if mean > best_by_cfg[t].get(ci, (-np.inf, 0))[0] + eps:
                            best_by_cfg[t][ci] = (mean, rounds_done)
                            improved = True
                    else:
                        # regressors rank by the LATEST horizon: their
                        # final fit trains the full round budget, so
                        # selection must score the behavior that deploys
                        best_by_cfg[t][ci] = (mean, rounds_done)
                    # a PERFECT classifier score cannot be beaten: retire
                    # the target from every remaining chunk and group
                    if is_discrete and fold_scores \
                            and min(fold_scores) >= 1.0 - 1e-12:
                        done[t] = True
                if done[t]:
                    continue
                # good-enough stop: later chunks AND groups cannot pay for
                # themselves for this target
                if is_discrete and any(
                        best_by_cfg[t].get(ci, (-np.inf, 0))[0] >= good_enough
                        for ci in cfg_indices):
                    done[t] = True
                    continue
                if improved:
                    no_improve[t] = 0
                elif is_discrete:
                    # patience applies to classifiers only: regressors
                    # deploy at the full round budget and must reach it
                    no_improve[t] += 1
                    if no_improve[t] >= patience_chunks:
                        active[t] = False

        if timed_out:
            # the deadline interrupted this group MID-SEARCH: only the
            # targets still actively improving lose their round counts — a
            # best checkpoint recorded while chunks were still advancing
            # may under-state the useful round budget. Targets that
            # concluded (done, patience-stopped) keep their CV-proven
            # rounds, and groups the deadline prevented from ever running
            # cannot invalidate rounds recorded by completed ones.
            for t in members:
                if active[t] and not done[t]:
                    timed[t] = True

    out: List[Tuple[int, float, int, bool]] = []
    for t in range(T):
        if not best_by_cfg[t]:
            out.append((0, -np.inf, 0, timed[t] or
                        (timed_out and preps[t] is not None and not done[t])))
            continue
        best_ci = max(best_by_cfg[t], key=lambda ci: best_by_cfg[t][ci][0])
        best_score, best_rounds = best_by_cfg[t][best_ci]
        out.append((best_ci, best_score, best_rounds, timed[t]))
    return out


def gbdt_cv_grid_search(X: np.ndarray, y: Any, is_discrete: bool,
                        configs: List[dict], n_splits: int,
                        class_weight: str,
                        template: "GradientBoostedTreesModel",
                        timeout_s: float = 0.0,
                        good_enough: float = _GOOD_ENOUGH_F1) \
        -> Tuple[int, float, int, bool]:
    """Single-target K-fold CV grid search: a one-element call into
    :func:`gbdt_cv_grid_search_multi` (folds still stack into one vmapped
    launch per config shape group, with chunked early stopping —
    LightGBM's ``early_stopping_rounds`` semantics, reference
    train.py:193-200, at ``_CHUNK_ROUNDS`` granularity).

    Returns (best config index, mean CV score, best round count,
    timed_out); the round count is the SMALLEST checkpoint where the
    winning config reached its best score. Scores keep the reference's
    hyperopt metrics (train.py:158): macro-F1 for classifiers, -MSE for
    regressors. ``timeout_s`` > 0 bounds the search like the reference's
    hyperopt timeout (train.py:196)."""
    from delphi_tpu.parallel.mesh import get_active_mesh
    prep = _cv_prepare_target(X, y, is_discrete, n_splits, class_weight,
                              template, get_active_mesh())
    return gbdt_cv_grid_search_multi(
        [prep], configs, timeout_s=timeout_s, good_enough=good_enough)[0]


# ---------------------------------------------------------------------------
# Public model
# ---------------------------------------------------------------------------

class GradientBoostedTreesModel:
    """LightGBM-style GBDT with the repair pipeline's model duck type."""

    def __init__(self, is_discrete: bool, num_class: int,
                 n_estimators: int = 300, learning_rate: float = 0.1,
                 max_depth: int = 5, max_bin: int = 255,
                 min_split_gain: float = 0.0, reg_lambda: float = 1.0,
                 min_child_weight: float = 1.0,
                 min_child_samples: float = 0.0,
                 class_weight: str = "balanced") -> None:
        self.is_discrete = is_discrete
        self.num_class = num_class
        self.n_estimators = min(n_estimators, 200)
        self.learning_rate = learning_rate
        self.max_depth = max_depth
        self.max_bin = min(max_bin, 63)
        self.min_split_gain = min_split_gain
        self.reg_lambda = reg_lambda
        self.min_child_weight = min_child_weight
        self.min_child_samples = min_child_samples
        self.class_weight = class_weight
        self.loss_: float = 0.0
        self._classes: Optional[np.ndarray] = None

    @property
    def classes_(self) -> np.ndarray:
        assert self._classes is not None
        return self._classes

    def _as_matrix(self, X: Any) -> np.ndarray:
        if isinstance(X, pd.DataFrame):
            X = X.to_numpy()
        return np.asarray(X, dtype=np.float64)

    @staticmethod
    def _pad(arr: np.ndarray, value: float = 0, mesh: Any = None,
             train: bool = False) -> np.ndarray:
        """Pads rows to the next power of two so fold/dataset size changes
        don't trigger XLA recompilation; under an active mesh, also to a
        multiple of the dp size so row shards are equal. ``train=True``
        switches to the finer training-row target (see
        :func:`train_row_target`)."""
        from delphi_tpu.parallel.mesh import padded_row_target
        n = arr.shape[0]
        target = train_row_target(n, mesh) if train \
            else padded_row_target(n, mesh)
        if target == n:
            return arr
        pad_shape = (target - n,) + arr.shape[1:]
        return np.concatenate([arr, np.full(pad_shape, value, arr.dtype)], axis=0)

    @staticmethod
    def _pad_feature_dim(bins: np.ndarray) -> np.ndarray:
        """Pads the feature axis to the next multiple of 8 so per-attribute
        models with nearly-equal feature counts share one compiled program.
        Padded features are constant (NaN bin 0): their best split gain is
        exactly 0, which never beats ``gain > min_split_gain``, so they are
        dead weight in the histogram only — never chosen."""
        d = bins.shape[1]
        target = max(8, -(-d // 8) * 8)
        if target == d:
            return bins
        return np.concatenate(
            [bins, np.zeros((bins.shape[0], target - d), bins.dtype)], axis=1)

    def _fit_prepare(self, X: Any, y: Any, mesh: Any) \
            -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, float]:
        """Everything in a fit that happens BEFORE boosting: binning, label
        factorization, class weights, base margins, padding. Returns
        (bins, y, w, F0, min_child_samples) as padded host arrays and sets
        the model's inference state, so the batched multi-target fit path
        can prepare each model and run the boosting chunks of a whole shape
        group in shared vmapped launches."""
        Xm = self._as_matrix(X)
        n, d = Xm.shape
        self._binner = _Binner(self.max_bin).fit(Xm)
        bins_np = self._pad(self._pad_feature_dim(
            self._binner.transform(Xm)), mesh=mesh, train=True)
        self._n_bins = self._binner.n_bins
        self._n_nodes = 1 << self.max_depth

        if self.is_discrete:
            codes, classes = pd.factorize(np.asarray(y), sort=True)
            self._classes = np.asarray(classes)
            k = len(classes)
            counts = np.bincount(codes, minlength=k).astype(np.float64)
            if self.class_weight == "balanced":
                from delphi_tpu.models.encoding import balanced_class_weights
                per_class_w = balanced_class_weights(counts, len(codes))
                w = per_class_w[codes]
                self._fit_class_weights = per_class_w
            else:
                w = np.ones(n)
                self._fit_class_weights = None
            if k <= 2:
                self._objective = "binary"
                self._k = 1
                yv = codes.astype(np.float32)
                pos = float((w * yv).sum() / w.sum())
                pos = min(max(pos, 1e-6), 1 - 1e-6)
                base = np.array([np.log(pos / (1 - pos))], dtype=np.float32)
            else:
                self._objective = "multiclass"
                # Bucket the class-tree axis ({4,8,16,24}) so targets with
                # similar cardinality share one compiled boosting program;
                # padded classes get a ~-inf prior and are never the label,
                # so their gradients (and trees) are zero.
                k_pad = next(b for b in (4, 8, 16, 24, MAX_MULTICLASS)
                             if b >= k)
                self._k = k_pad
                # bound the k-trees-per-round cost
                self.n_estimators = min(self.n_estimators, max(40, 400 // k_pad))
                yv = codes.astype(np.float32)
                priors = np.zeros(k_pad)
                np.add.at(priors, codes, w)
                priors = np.maximum(priors / priors.sum(), 1e-13)
                base = np.log(priors).astype(np.float32)
        else:
            self._objective = "regression"
            self._k = 1
            yv = pd.to_numeric(pd.Series(np.asarray(y)), errors="coerce") \
                .to_numpy(dtype=np.float64)
            assert not np.isnan(yv).any(), "y must not contain NULLs"
            # Heavily right-skewed nonnegative targets (e.g. crime rates) fit
            # much better in log space; LightGBM's leaf-wise growth absorbs
            # skew implicitly, this is the depth-wise equivalent.
            std = yv.std()
            skew = float(((yv - yv.mean()) ** 3).mean() / (std ** 3)) if std > 0 else 0.0
            self._log_target = bool((yv >= 0).all() and skew > 2.0)
            if self._log_target:
                yv = np.log1p(yv)
            yv = yv.astype(np.float32)
            w = np.ones(n)
            base = np.array([float(yv.mean())], dtype=np.float32)
            self._classes = np.array([])

        self._base = base
        yv_p = self._pad(np.asarray(yv, np.float32), mesh=mesh, train=True)
        w_p = self._pad(np.asarray(w, np.float32), mesh=mesh, train=True)
        # Optional leaf row-count floor (LightGBM's min_child_samples).
        # Default 0: prior recalibration in predict_proba already guards
        # against upweighted rare typo classes, and a hard floor costs
        # accuracy on tight local structure (e.g. boston RAD).
        mcs = self.min_child_samples if self.is_discrete else 0.0
        F0 = _init_margin(base, bins_np.shape[0], self._objective,
                          max(self._k, 1))
        return bins_np, yv_p, w_p, F0, mcs

    def _set_trees(self, parts: List[Any], n_rounds: Optional[int] = None) \
            -> None:
        """Installs the boosted trees from per-chunk (feat, thr, leaf)
        stacks, optionally truncated to ``n_rounds``: boosting is
        prefix-deterministic (round r never depends on later rounds), so a
        longer run truncated to r rounds IS the r-round model — the batched
        fit trains a whole shape group to its max budget and each model
        keeps its own prefix."""
        parts = [jax.device_get(t) for t in parts]
        trees = tuple(
            np.concatenate([p[i] for p in parts], axis=0) for i in range(3))
        if n_rounds is not None and trees[0].shape[0] > n_rounds:
            trees = tuple(t[:n_rounds] for t in trees)
        self.n_estimators = int(trees[0].shape[0])
        self._trees = trees

    def fit(self, X: Any, y: Any) -> "GradientBoostedTreesModel":
        from delphi_tpu.parallel.mesh import get_active_mesh
        mesh = get_active_mesh()
        bins_np, yv_p, w_p, F, mcs = self._fit_prepare(X, y, mesh)
        return self._fit_boost_prepared(mesh, bins_np, yv_p, w_p, F, mcs)

    def _fit_boost_prepared(self, mesh, bins_np, yv_p, w_p, F, mcs) \
            -> "GradientBoostedTreesModel":
        # Chunked fit: the margin carry stays on device between fixed-size
        # chunk launches, so any CV-selected round count (the early-stopping
        # driver below) reuses the SAME compiled chunk program instead of
        # compiling one scan per distinct n_estimators.
        parts: List[Any] = []
        if mesh is not None:
            from delphi_tpu.parallel.mesh import shard_rows
            bins_dev = shard_rows(bins_np, mesh)
            y_dev = shard_rows(yv_p, mesh)
            w_dev = shard_rows(w_p, mesh)
            F = shard_rows(F, mesh)
            for chunk in _round_chunks(self.n_estimators):
                step = _mesh_boost_fn(
                    mesh, chunk, self.max_depth, self._n_bins, self._n_nodes,
                    self._objective, max(self._k, 1),
                    float(self.learning_rate), float(self.reg_lambda),
                    float(self.min_split_gain), float(self.min_child_weight),
                    float(mcs))
                F, trees = step(bins_dev, y_dev, w_dev, F)
                parts.append(trees)
        else:
            bins_dev = jnp.asarray(bins_np)
            y_dev = jnp.asarray(yv_p)
            w_dev = jnp.asarray(w_p)
            F = jnp.asarray(F)
            boost = _boost_chunk_fn(_donation_enabled())
            for chunk in _round_chunks(self.n_estimators):
                F, trees = boost(
                    bins_dev, y_dev, w_dev, F, chunk, self.max_depth,
                    self._n_bins, self._n_nodes, self._objective,
                    max(self._k, 1), self.learning_rate, self.reg_lambda,
                    self.min_split_gain, self.min_child_weight, mcs,
                    use_counts=mcs > 0)
                parts.append(trees)
        self._set_trees(parts)
        return self

    def _raw_scores(self, X: Any) -> np.ndarray:
        from delphi_tpu.parallel.mesh import get_active_mesh
        mesh = get_active_mesh()
        Xm = self._as_matrix(X)
        n = Xm.shape[0]
        bins_np = self._pad(self._pad_feature_dim(
            self._binner.transform(Xm)), mesh=mesh)
        if mesh is not None:
            F = _mesh_predict(mesh, bins_np, *self._trees,
                              self.n_estimators, self.max_depth,
                              self._objective, max(self._k, 1), self._base)
        else:
            feats, thrs, leaves = (jnp.asarray(t) for t in self._trees)
            F = _predict_boosted(bins_np, feats, thrs, leaves,
                                 self.n_estimators, self.max_depth,
                                 self._objective, max(self._k, 1),
                                 jnp.asarray(self._base))
        F = np.asarray(F)
        return F[..., :n]

    def _recalibrate(self, probs: np.ndarray) -> np.ndarray:
        """Importance-corrects probabilities back to the TRUE class priors.

        Training reweights classes (balanced weights w_c), so the model
        estimates p_q(y|x) under the reweighted distribution q(y) ∝
        count_c * w_c. Dividing by w_c and renormalizing recovers
        p(y|x) under the empirical priors — so ultra-rare noise classes
        (undetected typos) keep their minority recall during training but
        cannot win ambiguous repair predictions on priors they don't have."""
        w = getattr(self, "_fit_class_weights", None)
        if w is None:
            return probs
        corrected = probs / np.maximum(w[None, :], 1e-12)
        return corrected / np.maximum(
            corrected.sum(axis=1, keepdims=True), 1e-12)

    def predict_proba(self, X: Any) -> np.ndarray:
        assert self.is_discrete
        F = self._raw_scores(X)
        if self._objective == "binary":
            p = 1.0 / (1.0 + np.exp(-F))
            return self._recalibrate(np.stack([1 - p, p], axis=1))
        F = F[: len(self.classes_)]  # drop padded bucket classes
        z = F - F.max(axis=0, keepdims=True)
        e = np.exp(z)
        return self._recalibrate((e / e.sum(axis=0, keepdims=True)).T)

    def predict(self, X: Any) -> np.ndarray:
        if self.is_discrete:
            return self.classes_[self.predict_proba(X).argmax(axis=1)]
        pred = self._raw_scores(X)
        if getattr(self, "_log_target", False):
            pred = np.expm1(pred)
        return pred


# ---------------------------------------------------------------------------
# Batched multi-target final fits
# ---------------------------------------------------------------------------

# Model-axis width per batched fit launch: bounds the TPU histogram path's
# [M, n, d*n_bins] one-hot materialization.
_FIT_BATCH_CAP = 8


_BOOST_BATCH_STATIC = ("n_rounds", "depth", "n_bins", "n_nodes", "objective",
                       "k", "use_counts")


def _boost_batch_impl(bins, y, w, F0, lrs, regs, msgs, mcws, mcss, n_rounds,
                      depth, n_bins, n_nodes, objective, k, use_counts):
    """One boosting chunk for a stacked batch of models (the final-fit side
    of the reference's per-attribute training fan-out, model.py:817-926):
    vmap over the model axis with per-model dynamic hyperparameters, so a
    whole shape group of per-attribute fits advances in one launch."""
    def one(b, yy, ww, f0, lr, rg, ms, mcw, mcs):
        return _boost(b, yy, ww, f0, n_rounds, depth, n_bins, n_nodes,
                      objective, k, lr, rg, ms, mcw, mcs,
                      use_counts=use_counts)

    return jax.vmap(one)(bins, y, w, F0, lrs, regs, msgs, mcws, mcss)


_boost_batch = partial(jax.jit,
                       static_argnames=_BOOST_BATCH_STATIC)(_boost_batch_impl)


@lru_cache(maxsize=2)
def _boost_batch_fn(donate: bool):
    """Batched-fit chunk entry; see :func:`_boost_chunk_fn` for why the
    donating variant is a distinct shared callable."""
    if not donate:
        return _boost_batch
    return jax.jit(_boost_batch_impl, static_argnames=_BOOST_BATCH_STATIC,
                   donate_argnums=(3,))


def gbdt_fit_batch(entries: List[Tuple["GradientBoostedTreesModel",
                                       Any, Any]]) -> None:
    """Fits many GBDT models in shared vmapped launches: models are
    prepared individually (binning, class weights — host work), grouped by
    their static compile dims (depth, bins/nodes, objective, class bucket,
    padded tensor shape, counts channel), and each group boosts to its MAX
    round budget in `_FIT_BATCH_CAP`-wide chunked launches; every model
    then keeps its own round-count prefix of the stacked trees (boosting is
    prefix-deterministic, see `_set_trees`). Under a mesh the models fit
    one at a time with rows sharded over dp — there the mesh is the
    batching axis. Singleton groups take the plain chunked fit."""
    from delphi_tpu.parallel.mesh import get_active_mesh
    mesh = get_active_mesh()
    if mesh is not None or len(entries) <= 1:
        for m, X, y in entries:
            m.fit(X, y)
        return

    prepped = []
    for m, X, y in entries:
        prepped.append((m,) + m._fit_prepare(X, y, None))

    groups: Dict[Tuple, List[int]] = {}
    for i, (m, bins_np, yv_p, w_p, F0, mcs) in enumerate(prepped):
        key = (m.max_depth, m._n_bins, m._n_nodes, m._objective,
               max(m._k, 1), bins_np.shape, bool(mcs > 0))
        groups.setdefault(key, []).append(i)

    work: List[Tuple[Tuple, List[int]]] = []
    for key, idxs in groups.items():
        if len(idxs) == 1:
            m, bins_np, yv_p, w_p, F0, mcs = prepped[idxs[0]]
            m._fit_boost_prepared(None, bins_np, yv_p, w_p, F0, mcs)
            continue
        for s in range(0, len(idxs), _FIT_BATCH_CAP):
            work.append((key, idxs[s:s + _FIT_BATCH_CAP]))

    def _stage(item):
        # Host side of one sub-batch: stack the prepared tensors and start
        # their device transfer. Under the pipeline this runs on the
        # prepare thread, so sub-batch s+1's inputs are already resident
        # when sub-batch s's chunk loop drains.
        _key, sub = item
        models = [prepped[i][0] for i in sub]
        bins = jnp.asarray(np.stack([prepped[i][1] for i in sub]))
        ys = jnp.asarray(np.stack([prepped[i][2] for i in sub]))
        ws = jnp.asarray(np.stack([prepped[i][3] for i in sub]))
        F = jnp.asarray(np.stack([prepped[i][4] for i in sub]))
        lrs = jnp.asarray([m.learning_rate for m in models], jnp.float32)
        regs = jnp.asarray([m.reg_lambda for m in models], jnp.float32)
        msgs = jnp.asarray([m.min_split_gain for m in models], jnp.float32)
        mcws = jnp.asarray([m.min_child_weight for m in models],
                           jnp.float32)
        mcss = jnp.asarray([prepped[i][5] for i in sub], jnp.float32)
        return models, bins, ys, ws, F, lrs, regs, msgs, mcws, mcss

    def _launch(item, staged):
        key, _sub = item
        depth, n_bins, n_nodes, objective, k, _shape, use_counts = key
        models, bins, ys, ws, F, lrs, regs, msgs, mcws, mcss = staged
        boost = _boost_batch_fn(_donation_enabled())
        rounds_max = max(m.n_estimators for m in models)
        parts = []
        for chunk in _round_chunks(rounds_max):
            # guarded launch; note the donated-F caveat: a REAL fault that
            # fires after donation invalidates F, and the retry's
            # deleted-array error is unclassifiable and re-raises — only
            # faults at launch entry (injection, dispatch) retry cleanly
            F, trees = run_guarded(
                "gbdt.fit_chunk",
                lambda F=F: boost(
                    bins, ys, ws, F, lrs, regs, msgs, mcws, mcss, chunk,
                    depth, n_bins, n_nodes, objective, k, use_counts))
            parts.append(jax.device_get(trees))
        for mi, m in enumerate(models):
            own = [tuple(np.asarray(t)[mi] for t in p) for p in parts]
            m._set_trees(own, n_rounds=m.n_estimators)

    from delphi_tpu.parallel.pipeline import run_pipelined
    run_pipelined(work, _stage, _launch)
