"""Histogram gradient-boosted decision trees in pure JAX — the flagship
repair-model family, replacing LightGBM (reference train.py:89-229).

TPU-first design:
* features are quantile-binned once into an ``int32[n, d]`` bin tensor
  (NaN/missing = bin 0), so each boosting round is dense integer arithmetic;
* trees grow depth-wise with FIXED shapes: level ``t`` owns node ids
  ``[0, 2^t)``, histograms are ``[2^D, d, B]`` scatter-adds (XLA lowers them
  to one-hot matmuls on the MXU), and split selection is an argmax over the
  padded (feature, bin) grid — no data-dependent control flow;
* the whole boosting loop is a single ``lax.scan`` over rounds, multiclass
  trains K trees per round via ``vmap`` over the class axis.

Objectives: L2 regression, binary logistic, multiclass softmax — with
balanced class weights like the reference's `class_weight='balanced'`
(train.py:105), which drives its characteristic minority-class repairs.
"""

from functools import lru_cache, partial
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd

MAX_MULTICLASS = 24


def gbdt_supported(is_discrete: bool, num_class: int) -> bool:
    """K class-trees per round get expensive fast; very wide multiclass
    targets route to the logistic head instead (train.py)."""
    return (not is_discrete) or num_class <= MAX_MULTICLASS


# ---------------------------------------------------------------------------
# Binning
# ---------------------------------------------------------------------------

class _Binner:
    """Quantile binning; bin 0 is reserved for NaN/missing."""

    def __init__(self, max_bin: int) -> None:
        self.max_bin = max_bin
        self.edges: List[np.ndarray] = []

    def fit(self, X: np.ndarray) -> "_Binner":
        self.edges = []
        for j in range(X.shape[1]):
            col = X[:, j]
            col = col[~np.isnan(col)]
            uniq = np.unique(col)
            if len(uniq) <= 1:
                self.edges.append(np.array([np.inf]))
            elif len(uniq) <= self.max_bin:
                self.edges.append((uniq[1:] + uniq[:-1]) / 2.0)
            else:
                qs = np.quantile(col, np.linspace(0, 1, self.max_bin + 1)[1:-1])
                self.edges.append(np.unique(qs))
        return self

    @property
    def n_bins(self) -> int:
        # Fixed at max_bin+1 (not the data-dependent max edge count) so every
        # target column compiles against the same histogram width — one XLA
        # program serves the whole per-attribute model loop.
        return self.max_bin + 1

    def transform(self, X: np.ndarray) -> np.ndarray:
        n, d = X.shape
        out = np.zeros((n, d), dtype=np.int32)
        for j in range(d):
            col = X[:, j]
            bins = np.searchsorted(self.edges[j], col, side="left") + 1
            out[:, j] = np.where(np.isnan(col), 0, bins)
        return out


# ---------------------------------------------------------------------------
# Tree building / prediction kernels
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("depth", "n_bins", "n_nodes", "axis_name",
                                   "use_scatter", "use_counts",
                                   "hess_is_weight"))
def _build_tree(bins, grad, hess, weight, depth, n_bins, n_nodes,
                reg_lambda, min_split_gain, min_child_weight,
                min_child_samples, axis_name=None, bin1h2d=None,
                use_scatter=None, use_counts=True, hess_is_weight=False):
    """Grows one depth-wise tree. Returns (feat[int32 n_nodes-1],
    thr[int32 n_nodes-1], leaf[f32 n_nodes]) with all-left sentinel splits
    (thr = n_bins) for terminated nodes. Rows with weight 0 (padding /
    held-out CV rows) are excluded from the row count: ``min_child_samples``
    bounds the UNWEIGHTED participating rows per child (LightGBM's
    min_child_samples) so heavily-upweighted rare classes cannot carve
    single-row leaves.

    The histogram channel set is STATIC: the counts channel exists only
    when ``min_child_samples`` is actually in play (``use_counts``), and
    for the L2 objective hessian == weight (``hess_is_weight``) so the
    weight channel is dropped — per level that's 2 channels instead of 4
    for regression and 3 for default classification, directly scaling the
    histogram contraction (MXU rows on TPU, segment adds on CPU)."""
    n, d = bins.shape

    feat = jnp.zeros(n_nodes - 1, dtype=jnp.int32)
    thr = jnp.full(n_nodes - 1, n_bins, dtype=jnp.int32)
    node = jnp.zeros(n, dtype=jnp.int32)

    # Histogram strategy is platform-static. TPU: one-hot MATMULS — scatters
    # serialize on the VPU (measured ~100x slower here and able to crash the
    # worker in large vmapped batches), while hist[l,f,b] =
    # sum_n node1h[n,l] * val[n] * bin1h[n,f,b] is exactly an
    # (C*n_level, n) @ (n, d*B) contraction the MXU eats. bin1h is
    # loop-invariant — callers that build many trees (the boosting scan's
    # class-tree vmap) pass it in so it materializes once, not per tree.
    # CPU: segment-sum scatter-adds — O(n*d) work instead of the matmul's
    # O(n*d*B) FLOPs; XLA:CPU lowers them to decent serial scatter loops
    # (measured ~4x faster end-to-end on the CV grid at B=64).
    if use_scatter is None:
        use_scatter = jax.default_backend() == "cpu"
    if bin1h2d is None and not use_scatter:
        bin1h2d = jax.nn.one_hot(bins, n_bins,
                                 dtype=jnp.float32).reshape(n, d * n_bins)
    channels = [grad, hess]
    w_slot = 1 if hess_is_weight else len(channels)
    if not hess_is_weight:
        channels.append(weight)
    c_slot = len(channels) if use_counts else -1
    if use_counts:
        channels.append((weight > 0).astype(jnp.float32))
    vals = jnp.stack(channels)  # (C, n)
    C = len(channels)

    for level in range(depth):
        n_level = 1 << level
        if use_scatter:
            seg = (node[:, None] * d + jnp.arange(d)[None, :]) * n_bins + bins
            data = jnp.broadcast_to(vals[:, :, None], (C, n, d))
            hist = jax.vmap(lambda v: jax.ops.segment_sum(
                v.reshape(-1), seg.reshape(-1),
                num_segments=n_level * d * n_bins))(
                data.reshape(C, n * d)).reshape(C, n_level, d, n_bins)
        else:
            node1h = jax.nn.one_hot(node, n_level, dtype=jnp.float32)  # (n, l)
            weighted = vals[:, :, None] * node1h[None]  # (C, n, n_level)
            lhs = weighted.transpose(0, 2, 1).reshape(C * n_level, n)
            # HIGHEST precision: the TPU's default matmul mode rounds f32
            # operands to bf16, which perturbs split gains enough to flip
            # near-tie argmaxes vs the exact-sum semantics
            hist = jax.lax.dot_general(
                lhs, bin1h2d, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST)  # (C*n_level, d*B)
            hist = hist.reshape(C, n_level, d, n_bins)

        if axis_name is not None:
            # rows are sharded over the mesh: local histograms reduce over
            # ICI — the TPU form of the reference's Spark shuffle (P1/P2)
            hist = jax.lax.psum(hist, axis_name)
        hg, hh, hw = hist[0], hist[1], hist[w_slot]

        GL = jnp.cumsum(hg, axis=2)
        HL = jnp.cumsum(hh, axis=2)
        WL = jnp.cumsum(hw, axis=2)
        G = GL[:, :, -1:]
        H = HL[:, :, -1:]
        W = WL[:, :, -1:]
        GR, HR, WR = G - GL, H - HL, W - WL

        gain = (GL * GL / (HL + reg_lambda)
                + GR * GR / (HR + reg_lambda)
                - G * G / (H + reg_lambda))
        ok = (WL >= min_child_weight) & (WR >= min_child_weight)
        if use_counts:
            CL = jnp.cumsum(hist[c_slot], axis=2)
            Ct = CL[:, :, -1:]
            CR = Ct - CL
            ok = ok & (CL >= min_child_samples) & (CR >= min_child_samples)
        gain = jnp.where(ok, gain, -jnp.inf)
        # never split on the last bin (right side empty by construction)
        gain = gain.at[:, :, -1].set(-jnp.inf)

        flat_gain = gain.reshape(n_level, d * n_bins)
        best = jnp.argmax(flat_gain, axis=1)
        best_gain = jnp.take_along_axis(flat_gain, best[:, None], axis=1)[:, 0]
        best_f = (best // n_bins).astype(jnp.int32)
        best_b = (best % n_bins).astype(jnp.int32)
        do_split = best_gain > min_split_gain
        best_f = jnp.where(do_split, best_f, 0)
        best_b = jnp.where(do_split, best_b, n_bins)  # sentinel: all rows left

        offset = n_level - 1
        feat = jax.lax.dynamic_update_slice(feat, best_f, (offset,))
        thr = jax.lax.dynamic_update_slice(thr, best_b, (offset,))

        go_right = bins[jnp.arange(n), best_f[node]] > best_b[node]
        node = node * 2 + go_right.astype(jnp.int32)

    leaf1h = jax.nn.one_hot(node, n_nodes, dtype=jnp.float32)  # (n, n_nodes)
    leaf_gh = jnp.matmul(jnp.stack([grad, hess]), leaf1h,
                         precision=jax.lax.Precision.HIGHEST)  # (2, n_nodes)
    if axis_name is not None:
        leaf_gh = jax.lax.psum(leaf_gh, axis_name)
    leaf = -leaf_gh[0] / (leaf_gh[1] + reg_lambda)
    return feat, thr, leaf, node


@partial(jax.jit, static_argnames=("depth",))
def _predict_tree(bins, feat, thr, leaf, depth):
    n = bins.shape[0]
    node = jnp.zeros(n, dtype=jnp.int32)
    for level in range(depth):
        offset = (1 << level) - 1
        f = feat[offset + node]
        b = thr[offset + node]
        go_right = bins[jnp.arange(n), f] > b
        node = node * 2 + go_right.astype(jnp.int32)
    return leaf[node]


# ---------------------------------------------------------------------------
# Boosting
# ---------------------------------------------------------------------------

# Boosting runs in fixed-size chunks of this many rounds: ONE compiled chunk
# program serves every total round count (25, 50, ... 200), which is what
# makes per-target early stopping free of recompilation — the reference gets
# the same effect from LightGBM's dynamic `early_stopping_rounds`
# (train.py:193-200) because its trees are built by interpreted C++.
_CHUNK_ROUNDS = 25

# CV macro-F1 past which further search cannot pay for itself: repair picks
# argmax cells, so a config above this is essentially solved and both the
# within-group chunk loop and the cross-group loop stop here.
_GOOD_ENOUGH_F1 = 0.995


def _round_chunks(n_rounds: int) -> List[int]:
    q, r = divmod(max(int(n_rounds), 1), _CHUNK_ROUNDS)
    return [_CHUNK_ROUNDS] * q + ([r] if r else [])


@partial(jax.jit, static_argnames=("n_rounds", "depth", "n_bins", "n_nodes",
                                   "objective", "k", "axis_name",
                                   "collect_trees", "use_counts"))
def _boost(bins, y, weight, F0, n_rounds, depth, n_bins, n_nodes, objective,
           k, lr, reg_lambda, min_split_gain, min_child_weight,
           min_child_samples=20.0, axis_name=None, collect_trees=True,
           use_counts=True):
    """Runs ``n_rounds`` boosting rounds as one lax.scan, RESUMING from the
    margin state ``F0`` (rows-first: [n], or [n, k] for multiclass — the
    layout row sharding understands). Returns (F, stacked trees), F
    rows-first again, so fits advance in fixed-size chunks with the carry
    living on device between launches. ``collect_trees=False`` drops the
    stacked tree outputs (the CV scorer only needs the margins — the carry
    F IS the model's prediction on every row, held-out weight-0 rows
    included, so CV never runs a separate predict pass)."""
    n = bins.shape[0]

    def grad_hess(F):
        if objective == "regression":
            return (F - y)[None, :] * weight[None, :], weight[None, :]
        if objective == "binary":
            p = jax.nn.sigmoid(F)
            return ((p - y) * weight)[None, :], \
                jnp.maximum(p * (1 - p), 1e-6)[None, :] * weight[None, :]
        # multiclass softmax: F is [k, n]
        p = jax.nn.softmax(F, axis=0)
        onehot = jax.nn.one_hot(y.astype(jnp.int32), k, axis=0, dtype=jnp.float32)
        return (p - onehot) * weight[None, :], \
            jnp.maximum(p * (1 - p), 1e-6) * weight[None, :]

    use_scatter = jax.default_backend() == "cpu"
    bin1h2d = None if use_scatter else \
        jax.nn.one_hot(bins, n_bins, dtype=jnp.float32) \
        .reshape(n, bins.shape[1] * n_bins)

    def one_round(F, _):
        g, h = grad_hess(F)

        def build(gk, hk):
            return _build_tree(bins, gk, hk, weight, depth, n_bins, n_nodes,
                               reg_lambda, min_split_gain, min_child_weight,
                               min_child_samples, axis_name, bin1h2d,
                               use_scatter=use_scatter,
                               use_counts=use_counts,
                               hess_is_weight=(objective == "regression"))

        feat, thr, leaf, node = jax.vmap(build)(g, h)  # [k_trees, ...]
        leaf = leaf * lr
        delta = jnp.take_along_axis(leaf, node, axis=1)  # [k_trees, n]
        F = F + (delta[0] if objective != "multiclass" else delta)
        return F, ((feat, thr, leaf) if collect_trees else None)

    F_init = F0.T if objective == "multiclass" else F0
    F, trees = jax.lax.scan(one_round, F_init, None, length=n_rounds)
    F_out = F.T if objective == "multiclass" else F
    return (F_out, trees) if collect_trees else F_out


def _init_margin(base: np.ndarray, n: int, objective: str, k: int) -> np.ndarray:
    """Rows-first initial margin state from per-class base scores."""
    base = np.asarray(base, np.float32)
    if objective == "multiclass":
        return np.broadcast_to(base[None, :], (n, k)).copy()
    return np.full((n,), base[0], np.float32)


def train_row_target(n: int, mesh: Any = None) -> int:
    """Training-row pad target: power of two below 4096 (the recompilation
    bound matters most for tiny per-attribute fits), then the next multiple
    of 2048. The training path is capped by `model.max_training_row_num`
    (10k default), so the variant count stays small while the default cap
    pads 10000 -> 10240 instead of 16384 — a free 1.6x on every histogram
    and gather in phases 2's hot loops. Prediction keeps power-of-two
    padding: dirty-row counts vary per attribute, so fine-grained targets
    there would multiply compiled variants."""
    if n <= 4096:
        from delphi_tpu.parallel.mesh import padded_row_target
        return padded_row_target(n, mesh)
    target = -(-n // 2048) * 2048
    if mesh is not None:
        dp = int(mesh.shape["dp"])
        target = -(-target // dp) * dp
    return target


@partial(jax.jit, static_argnames=("n_rounds", "depth", "objective", "k",
                                   "axis_name"))
def _predict_boosted(bins, feats, thrs, leaves, n_rounds, depth, objective, k,
                     base_score, axis_name=None):
    n = bins.shape[0]

    def score_tree(carry, tree):
        feat, thr, leaf = tree

        def one(fa, ta, la):
            return _predict_tree(bins, fa, ta, la, depth)

        delta = jax.vmap(one)(feat, thr, leaf)  # [k_trees, n]
        return carry + (delta[0] if objective != "multiclass" else delta), None

    if objective == "multiclass":
        F0 = jnp.broadcast_to(base_score[:, None], (k, n))
    else:
        F0 = jnp.full((n,), base_score[0])
    if axis_name is not None:
        F0 = jax.lax.pcast(F0, (axis_name,), to="varying")
    F, _ = jax.lax.scan(score_tree, F0, (feats, thrs, leaves))
    return F


# ---------------------------------------------------------------------------
# Multi-chip (mesh) training and inference
# ---------------------------------------------------------------------------

@lru_cache(maxsize=128)
def _mesh_boost_fn(mesh, n_rounds, depth, n_bins, n_nodes, objective, k,
                   lr, reg_lambda, min_split_gain, min_child_weight,
                   min_child_samples):
    """Cached, jitted shard_map program for one (mesh, hyperparameter)
    combination — per-attribute fits with the same shapes reuse the same
    compiled executable instead of retracing. Takes and returns the
    rows-first margin carry (sharded over dp) so chunked fits resume
    across launches without gathering F."""
    from jax.sharding import PartitionSpec as P

    from delphi_tpu.parallel.mesh import shard_map

    def fn(bins_l, y_l, w_l, F0_l):
        return _boost(bins_l, y_l, w_l, F0_l, n_rounds, depth, n_bins,
                      n_nodes, objective, k, lr, reg_lambda, min_split_gain,
                      min_child_weight, min_child_samples, axis_name="dp",
                      use_counts=min_child_samples > 0)

    F_spec = P("dp", None) if objective == "multiclass" else P("dp")
    return jax.jit(shard_map(
        fn, mesh=mesh,
        in_specs=(P("dp", None), P("dp"), P("dp"), F_spec),
        out_specs=(F_spec, (P(), P(), P()))))


@lru_cache(maxsize=128)
def _mesh_predict_fn(mesh, n_rounds, depth, objective, k):
    from jax.sharding import PartitionSpec as P

    from delphi_tpu.parallel.mesh import shard_map

    # Multi-host: row-sharded predictions span processes, so they
    # all-gather to every device and each host reads the full vector
    # (single-host meshes skip the collective and fetch the sharded array).
    multihost = jax.process_count() > 1
    row_axis = 1 if objective == "multiclass" else 0

    def fn(bins_l, feats, thrs, leaves, base):
        F = _predict_boosted(bins_l, feats, thrs, leaves, n_rounds,
                             depth, objective, k, base, axis_name="dp")
        if multihost:
            F = jax.lax.all_gather(F, "dp", axis=row_axis, tiled=True)
        return F

    if multihost:
        from delphi_tpu.parallel.mesh import shard_map_unchecked as smap
        out_spec = P()
    else:
        smap = shard_map
        out_spec = P(None, "dp") if objective == "multiclass" else P("dp")
    return jax.jit(smap(
        fn, mesh=mesh,
        in_specs=(P("dp", None), P(), P(), P(), P()),
        out_specs=out_spec))


def _mesh_predict(mesh, bins, feats, thrs, leaves, n_rounds, depth,
                  objective, k, base):
    """Row-sharded batched inference over the mesh (reference P3: the
    grouped-map repair UDF, model.py:1054-1135). No collectives: every
    device scores its own row shard against the replicated trees."""
    from delphi_tpu.parallel.mesh import shard_rows

    fn = _mesh_predict_fn(mesh, n_rounds, depth, objective, k)
    return fn(shard_rows(bins, mesh), jnp.asarray(feats), jnp.asarray(thrs),
              jnp.asarray(leaves), jnp.asarray(base))


# ---------------------------------------------------------------------------
# Batched cross-validation grid search
# ---------------------------------------------------------------------------

def _cv_stats(F, y, val_mask, y_cmp, log_flag, cw_corr, class_valid,
              objective, kk, axis_name):
    """On-device CV scoring statistics from the boosting margin carry:
    a [kk, kk] confusion-count matrix over the held-out rows for
    classifiers (val_mask picks the fold's real rows; padding rows carry
    mask 0), or [sse, count] for regressors — tiny tensors, so early
    stopping never fetches full prediction vectors to the host."""
    if objective == "regression":
        pred = jnp.where(log_flag > 0, jnp.expm1(F), F)
        out = jnp.stack([jnp.sum(val_mask * (pred - y_cmp) ** 2),
                         jnp.sum(val_mask)])
    else:
        if objective == "binary":
            p = jax.nn.sigmoid(F)
            # deploy-parity: importance-correct back to true priors before
            # the argmax, exactly as predict_proba does
            pred = (p / cw_corr[1] > (1 - p) / cw_corr[0]).astype(jnp.int32)
        else:
            logp = jax.nn.log_softmax(F, axis=1)  # [n, k]
            adj = logp - jnp.log(cw_corr)[None, :]
            adj = jnp.where(class_valid[None, :] > 0, adj, -jnp.inf)
            pred = jnp.argmax(adj, axis=1).astype(jnp.int32)
        idx = y.astype(jnp.int32) * kk + pred
        out = jax.ops.segment_sum(val_mask, idx,
                                  num_segments=kk * kk).reshape(kk, kk)
    if axis_name is not None:
        out = jax.lax.psum(out, axis_name)
    return out


@lru_cache(maxsize=128)
def _cv_chunk_fn(mesh, chunk, depth, n_bins, n_nodes, objective, k):
    """One early-stopping CV step: every (fold, config) instance of a shape
    group advances ``chunk`` boosting rounds from its carried margin state
    and scores its held-out rows on device. Sharing the fold tensors lets
    XLA emit shared-rhs batched contractions for the histograms (one bin
    one-hot read serves every config). Under a mesh, rows shard over dp
    with psum'd histograms (reference P2, the pandas-UDF training fan-out,
    train.py:163-209 / model.py:817-926)."""
    axis_name = "dp" if mesh is not None else None
    kk = 2 if objective == "binary" else max(k, 1)

    def fn(bins, y_, weight, val_mask, y_cmp, log_flag, cw_corr, class_valid,
           F, lrs, reg_lambdas, min_split_gains, min_child_weights):
        def one(F1, lr, reg_lambda, min_split_gain, min_child_weight):
            F2 = _boost(bins, y_, weight, F1, chunk, depth, n_bins, n_nodes,
                        objective, k, lr, reg_lambda, min_split_gain,
                        min_child_weight, 0.0, axis_name=axis_name,
                        collect_trees=False, use_counts=False)
            stats = _cv_stats(F2, y_, val_mask, y_cmp, log_flag, cw_corr,
                              class_valid, objective, kk, axis_name)
            return F2, stats

        return jax.vmap(one)(F, lrs, reg_lambdas, min_split_gains,
                             min_child_weights)

    if mesh is None:
        # Single device: batch the FOLD axis into the same launch too —
        # (folds × configs) instances advance in one XLA program per chunk.
        return jax.jit(jax.vmap(
            fn, in_axes=(0, 0, 0, 0, None, 0, None, None, 0,
                         None, None, None, None)))

    from jax.sharding import PartitionSpec as P

    from delphi_tpu.parallel.mesh import shard_map

    F_spec = P(None, "dp", None) if objective == "multiclass" \
        else P(None, "dp")
    return jax.jit(shard_map(
        fn, mesh=mesh,
        in_specs=(P("dp", None), P("dp"), P("dp"), P("dp"), P("dp"), P(),
                  P(), P(), F_spec, P(), P(), P(), P()),
        out_specs=(F_spec, P())))


def _f1_from_confusion(conf: np.ndarray, k_real: int) -> float:
    """Macro-F1 from a confusion-count matrix, averaging over the classes
    present in the fold's truth — identical semantics to
    ``encoding.f1_macro`` (classes = unique(y_true))."""
    conf = np.asarray(conf, np.float64)[:k_real, :k_real]
    truth_counts = conf.sum(axis=1)
    f1s = []
    for c in range(k_real):
        if truth_counts[c] <= 0:
            continue
        tp = conf[c, c]
        fp = conf[:, c].sum() - tp
        fn = truth_counts[c] - tp
        p = tp / (tp + fp) if tp + fp > 0 else 0.0
        r = tp / (tp + fn) if tp + fn > 0 else 0.0
        f1s.append(2 * p * r / (p + r) if p + r > 0 else 0.0)
    return float(np.mean(f1s)) if f1s else 0.0


def gbdt_cv_grid_search(X: np.ndarray, y: Any, is_discrete: bool,
                        configs: List[dict], n_splits: int,
                        class_weight: str,
                        template: "GradientBoostedTreesModel",
                        timeout_s: float = 0.0) -> Tuple[int, float, int]:
    """K-fold CV over a hyperparameter grid in one batched device launch per
    static-shape group (configs sharing tree depth vmap together; others get
    their own launches), with chunked EARLY STOPPING: boosting advances in
    ``_CHUNK_ROUNDS``-round chunks, each chunk scores every instance's
    held-out rows on device (confusion counts / SSE — no prediction fetch),
    and a group stops once no config has improved for two consecutive
    chunks — LightGBM's ``early_stopping_rounds`` semantics (reference
    train.py:193-200) at chunk granularity.

    Returns (best config index, its mean CV score, best round count); the
    round count is the SMALLEST checkpoint where the winning config reached
    its best score, so the final fit trains only as many rounds as CV
    proved useful instead of the full round cap.

    Scores match the sequential path's metrics: macro-F1 for classifiers,
    -MSE for regressors (the scorers the reference feeds hyperopt,
    train.py:158). Each fold bins (and, for regression, log-transforms)
    from its training rows only, so an instance's scores match a
    standalone per-fold fit.

    ``timeout_s`` > 0 bounds the search like the reference's hyperopt
    timeout (train.py:196): once exceeded, the best config so far wins.
    """
    import time
    deadline = time.monotonic() + timeout_s if timeout_s > 0 else None
    Xm = template._as_matrix(X)
    n = Xm.shape[0]
    n_bins = template.max_bin + 1

    y_arr = np.asarray(y)
    per_class_w = None
    if is_discrete:
        codes, classes = pd.factorize(y_arr, sort=True)
        k_real = len(classes)
        counts = np.bincount(codes, minlength=k_real).astype(np.float64)
        if class_weight == "balanced":
            from delphi_tpu.models.encoding import balanced_class_weights
            per_class_w = balanced_class_weights(counts, len(codes))
            w_full = per_class_w[codes]
        else:
            w_full = np.ones(n)
        if k_real <= 2:
            objective, k = "binary", 1
        else:
            objective = "multiclass"
            k = next(b for b in (4, 8, 16, 24, MAX_MULTICLASS) if b >= k_real)
        yv = codes.astype(np.float32)
        kk = 2 if objective == "binary" else k
        cw_corr = np.ones(kk, np.float32)
        if per_class_w is not None:
            m = min(k_real, kk)
            cw_corr[:m] = per_class_w[:m]
        class_valid = (np.arange(kk) < k_real).astype(np.float32)
        y_cmp = np.zeros(n, np.float32)  # unused for classifiers
    else:
        objective, k, k_real = "regression", 1, 0
        yv64 = pd.to_numeric(pd.Series(y_arr), errors="coerce") \
            .to_numpy(dtype=np.float64)
        w_full = np.ones(n)
        cw_corr = np.ones(1, np.float32)
        class_valid = np.ones(1, np.float32)
        y_cmp = yv64.astype(np.float32)  # original-space comparison target

    def cfg_depth(cfg: dict) -> int:
        return int(cfg.get("max_depth", template.max_depth))

    def cfg_rounds(cfg: dict) -> int:
        r = min(int(cfg.get("n_estimators", 200)), 200)
        if objective == "multiclass":
            r = min(r, max(40, 400 // k))
        return r

    rng = np.random.RandomState(42)
    order = rng.permutation(n)
    folds = np.array_split(order, max(2, min(n_splits, n)))
    folds = [f for f in folds if len(f)]

    from delphi_tpu.parallel.mesh import get_active_mesh
    mesh = get_active_mesh()
    n_pad = template._pad(np.zeros(n, np.float32), mesh=mesh,
                          train=True).shape[0]

    # Per-fold preprocessing matches a standalone fit on the fold's training
    # rows exactly: bin edges (and, for regression, the log-target decision)
    # come from the training rows only; all rows are then transformed with
    # the fold's edges so held-out predictions fall out of the same program.
    fold_bins, fold_y, fold_log = [], [], []
    for fold in folds:
        train_mask = np.ones(n, dtype=bool)
        train_mask[fold] = False
        binner_f = _Binner(template.max_bin).fit(Xm[train_mask])
        fold_bins.append(template._pad(template._pad_feature_dim(
            binner_f.transform(Xm)), mesh=mesh, train=True))
        if is_discrete:
            fold_y.append(template._pad(yv, mesh=mesh, train=True))
            fold_log.append(False)
        else:
            ytr = yv64[train_mask]
            std = ytr.std()
            skew = float(((ytr - ytr.mean()) ** 3).mean() / (std ** 3)) \
                if std > 0 else 0.0
            log_f = bool((ytr >= 0).all() and skew > 2.0)
            yv_f = (np.log1p(yv64) if log_f else yv64).astype(np.float32)
            fold_y.append(template._pad(yv_f, mesh=mesh, train=True))
            fold_log.append(log_f)

    # Configs sharing (depth, round cap) advance together; configs that
    # differ in those STATIC dims form separate groups, each chunk still a
    # single launch — every config is trained with its own true
    # hyperparameters.
    groups: Dict[Tuple[int, int], List[int]] = {}
    for ci, cfg in enumerate(configs):
        groups.setdefault((cfg_depth(cfg), cfg_rounds(cfg)), []).append(ci)

    # Per-fold tensors (weights, base scores, validation masks, device
    # placement) are group-independent: prepare and place them once.
    fold_prep = []
    for fi, fold in enumerate(folds):
        train_mask = np.ones(n, dtype=bool)
        train_mask[fold] = False
        if is_discrete and len(np.unique(yv[train_mask])) < 2:
            continue
        w = np.where(train_mask, w_full, 0.0).astype(np.float32)
        yv_f = fold_y[fi][:n]
        if objective == "binary":
            pos = float((w * yv_f).sum() / max(w.sum(), 1e-9))
            pos = min(max(pos, 1e-6), 1 - 1e-6)
            base = np.array([np.log(pos / (1 - pos))], dtype=np.float32)
        elif objective == "multiclass":
            priors = np.zeros(k)
            np.add.at(priors, yv_f.astype(np.int64), w)
            priors = np.maximum(priors / max(priors.sum(), 1e-9), 1e-13)
            base = np.log(priors).astype(np.float32)
        else:
            base = np.array(
                [float((w * yv_f).sum() / max(w.sum(), 1e-9))], np.float32)

        val = np.zeros(n_pad, np.float32)
        val[fold] = 1.0
        fold_prep.append((fi, fold, fold_bins[fi], fold_y[fi],
                          template._pad(w, mesh=mesh, train=True), val,
                          base))

    if not fold_prep:
        return 0, -np.inf, 0

    from jax.sharding import NamedSharding, PartitionSpec as P

    def place(arr, spec):
        if mesh is None:
            return jnp.asarray(arr)
        sharding = NamedSharding(mesh, spec)
        if jax.process_count() > 1:
            return jax.make_array_from_callback(
                arr.shape, sharding,
                lambda idx: np.ascontiguousarray(np.asarray(arr)[idx]))
        return jax.device_put(np.asarray(arr), sharding)

    y_cmp_dev = place(template._pad(y_cmp, mesh=mesh, train=True), P("dp"))
    cw_dev = jnp.asarray(cw_corr)
    valid_dev = jnp.asarray(class_valid)

    if mesh is None:
        bins_dev = jnp.stack([jnp.asarray(p[2]) for p in fold_prep])
        ys_dev = jnp.stack([jnp.asarray(p[3]) for p in fold_prep])
        ws_dev = jnp.stack([jnp.asarray(p[4]) for p in fold_prep])
        vals_dev = jnp.stack([jnp.asarray(p[5]) for p in fold_prep])
    else:
        bins_dev = [place(p[2], P("dp", None)) for p in fold_prep]
        ys_dev = [place(p[3], P("dp")) for p in fold_prep]
        ws_dev = [place(p[4], P("dp")) for p in fold_prep]
        vals_dev = [place(p[5], P("dp")) for p in fold_prep]
    logs_np = np.asarray(
        [1.0 if fold_log[p[0]] else 0.0 for p in fold_prep], np.float32)

    # best (score, rounds) per config; rounds = smallest checkpoint at the
    # config's best score (strict-improvement updates keep it minimal)
    best_by_cfg: Dict[int, Tuple[float, int]] = {}
    timed_out = False
    stop_all = False
    patience_chunks = 2
    eps = 1e-12
    F_spec_m = P(None, "dp", None) if objective == "multiclass" \
        else P(None, "dp")

    for (g_depth, g_rounds), cfg_indices in groups.items():
        if timed_out or stop_all:
            break
        n_cfg = len(cfg_indices)
        lrs = jnp.asarray([configs[ci].get("learning_rate", 0.1)
                           for ci in cfg_indices], jnp.float32)
        regs = jnp.asarray([configs[ci].get("reg_lambda", 1.0)
                            for ci in cfg_indices], jnp.float32)
        msgs = jnp.asarray([template.min_split_gain] * n_cfg, jnp.float32)
        mcws = jnp.asarray([configs[ci].get("min_child_weight", 1.0)
                            for ci in cfg_indices], jnp.float32)

        # margin carries, one per (fold, config) instance
        if mesh is None:
            F = jnp.stack([
                jnp.broadcast_to(
                    jnp.asarray(_init_margin(p[6], n_pad, objective, k)),
                    (n_cfg,) + ((n_pad, k) if objective == "multiclass"
                                else (n_pad,)))
                for p in fold_prep])
        else:
            F = [place(np.broadcast_to(
                    _init_margin(p[6], n_pad, objective, k),
                    (n_cfg,) + ((n_pad, k) if objective == "multiclass"
                                else (n_pad,))).copy(), F_spec_m)
                 for p in fold_prep]

        rounds_done = 0
        no_improve = 0
        for chunk in _round_chunks(g_rounds):
            if deadline is not None and time.monotonic() > deadline:
                timed_out = True
                break
            fn = _cv_chunk_fn(mesh, chunk, g_depth, n_bins, 1 << g_depth,
                              objective, k)
            if mesh is None:
                # one launch advances every (fold, config) instance
                F, stats = fn(bins_dev, ys_dev, ws_dev, vals_dev, y_cmp_dev,
                              jnp.asarray(logs_np), cw_dev, valid_dev, F,
                              lrs, regs, msgs, mcws)
                stats_np = np.asarray(jax.device_get(stats))
            else:
                stats_parts = []
                for i in range(len(fold_prep)):
                    F[i], s = fn(bins_dev[i], ys_dev[i], ws_dev[i],
                                 vals_dev[i], y_cmp_dev,
                                 jnp.float32(logs_np[i]), cw_dev, valid_dev,
                                 F[i], lrs, regs, msgs, mcws)
                    stats_parts.append(np.asarray(jax.device_get(s)))
                stats_np = np.stack(stats_parts)  # [n_folds, n_cfg, ...]
            rounds_done += chunk

            improved = False
            for j, ci in enumerate(cfg_indices):
                fold_scores = []
                for i in range(len(fold_prep)):
                    s = stats_np[i, j]
                    if is_discrete:
                        fold_scores.append(_f1_from_confusion(s, k_real))
                    else:
                        fold_scores.append(-float(s[0] / max(s[1], 1.0)))
                mean = float(np.mean(fold_scores))
                if is_discrete:
                    # classifiers rank by their best checkpoint, and the
                    # recorded round count sizes the final fit
                    if mean > best_by_cfg.get(ci, (-np.inf, 0))[0] + eps:
                        best_by_cfg[ci] = (mean, rounds_done)
                        improved = True
                else:
                    # regressors rank by the LATEST horizon: their final
                    # fit trains the full round budget, so selection must
                    # score the behavior that will actually deploy (MSE
                    # keeps creeping down with rounds; a lucky early
                    # checkpoint must not pick the config). Patience below
                    # is classifier-only, so no improvement flag needed.
                    best_by_cfg[ci] = (mean, rounds_done)
                # Early exit on a PERFECT classifier score: a config at
                # macro-F1 1.0 on every fold cannot be beaten — remaining
                # chunks AND groups are pure cost (on easy targets like
                # hospital State this halves the search).
                if is_discrete and min(fold_scores) >= 1.0 - 1e-12:
                    stop_all = True
            if stop_all:
                break
            # Good-enough stop WITHIN the group too: further chunks are
            # cost in both the search and the final fit they size.
            if is_discrete and any(
                    best_by_cfg.get(ci, (-np.inf, 0))[0] >= _GOOD_ENOUGH_F1
                    for ci in cfg_indices):
                break
            if improved:
                no_improve = 0
            elif is_discrete:
                # patience applies to classifiers only: their final fit
                # trains the best checkpoint's rounds, so stopping early is
                # consistent. Regressors deploy at the full round budget and
                # rank by the latest horizon, so their search must reach it.
                no_improve += 1
                if no_improve >= patience_chunks:
                    break

        # Good-enough group stop: later shape groups' launches cannot pay
        # for themselves either.
        if is_discrete and best_by_cfg and \
                max(s for s, _ in best_by_cfg.values()) >= _GOOD_ENOUGH_F1:
            break

    if not best_by_cfg:
        return 0, -np.inf, 0
    best_ci = max(best_by_cfg, key=lambda ci: best_by_cfg[ci][0])
    best_score, best_rounds = best_by_cfg[best_ci]
    return best_ci, best_score, best_rounds


# ---------------------------------------------------------------------------
# Public model
# ---------------------------------------------------------------------------

class GradientBoostedTreesModel:
    """LightGBM-style GBDT with the repair pipeline's model duck type."""

    def __init__(self, is_discrete: bool, num_class: int,
                 n_estimators: int = 300, learning_rate: float = 0.1,
                 max_depth: int = 5, max_bin: int = 255,
                 min_split_gain: float = 0.0, reg_lambda: float = 1.0,
                 min_child_weight: float = 1.0,
                 min_child_samples: float = 0.0,
                 class_weight: str = "balanced") -> None:
        self.is_discrete = is_discrete
        self.num_class = num_class
        self.n_estimators = min(n_estimators, 200)
        self.learning_rate = learning_rate
        self.max_depth = max_depth
        self.max_bin = min(max_bin, 63)
        self.min_split_gain = min_split_gain
        self.reg_lambda = reg_lambda
        self.min_child_weight = min_child_weight
        self.min_child_samples = min_child_samples
        self.class_weight = class_weight
        self.loss_: float = 0.0
        self._classes: Optional[np.ndarray] = None

    @property
    def classes_(self) -> np.ndarray:
        assert self._classes is not None
        return self._classes

    def _as_matrix(self, X: Any) -> np.ndarray:
        if isinstance(X, pd.DataFrame):
            X = X.to_numpy()
        return np.asarray(X, dtype=np.float64)

    @staticmethod
    def _pad(arr: np.ndarray, value: float = 0, mesh: Any = None,
             train: bool = False) -> np.ndarray:
        """Pads rows to the next power of two so fold/dataset size changes
        don't trigger XLA recompilation; under an active mesh, also to a
        multiple of the dp size so row shards are equal. ``train=True``
        switches to the finer training-row target (see
        :func:`train_row_target`)."""
        from delphi_tpu.parallel.mesh import padded_row_target
        n = arr.shape[0]
        target = train_row_target(n, mesh) if train \
            else padded_row_target(n, mesh)
        if target == n:
            return arr
        pad_shape = (target - n,) + arr.shape[1:]
        return np.concatenate([arr, np.full(pad_shape, value, arr.dtype)], axis=0)

    @staticmethod
    def _pad_feature_dim(bins: np.ndarray) -> np.ndarray:
        """Pads the feature axis to the next multiple of 8 so per-attribute
        models with nearly-equal feature counts share one compiled program.
        Padded features are constant (NaN bin 0): their best split gain is
        exactly 0, which never beats ``gain > min_split_gain``, so they are
        dead weight in the histogram only — never chosen."""
        d = bins.shape[1]
        target = max(8, -(-d // 8) * 8)
        if target == d:
            return bins
        return np.concatenate(
            [bins, np.zeros((bins.shape[0], target - d), bins.dtype)], axis=1)

    def fit(self, X: Any, y: Any) -> "GradientBoostedTreesModel":
        from delphi_tpu.parallel.mesh import get_active_mesh
        mesh = get_active_mesh()
        Xm = self._as_matrix(X)
        n, d = Xm.shape
        self._binner = _Binner(self.max_bin).fit(Xm)
        bins_np = self._pad(self._pad_feature_dim(
            self._binner.transform(Xm)), mesh=mesh, train=True)
        self._n_bins = self._binner.n_bins
        self._n_nodes = 1 << self.max_depth

        if self.is_discrete:
            codes, classes = pd.factorize(np.asarray(y), sort=True)
            self._classes = np.asarray(classes)
            k = len(classes)
            counts = np.bincount(codes, minlength=k).astype(np.float64)
            if self.class_weight == "balanced":
                from delphi_tpu.models.encoding import balanced_class_weights
                per_class_w = balanced_class_weights(counts, len(codes))
                w = per_class_w[codes]
                self._fit_class_weights = per_class_w
            else:
                w = np.ones(n)
                self._fit_class_weights = None
            if k <= 2:
                self._objective = "binary"
                self._k = 1
                yv = codes.astype(np.float32)
                pos = float((w * yv).sum() / w.sum())
                pos = min(max(pos, 1e-6), 1 - 1e-6)
                base = np.array([np.log(pos / (1 - pos))], dtype=np.float32)
            else:
                self._objective = "multiclass"
                # Bucket the class-tree axis ({4,8,16,24}) so targets with
                # similar cardinality share one compiled boosting program;
                # padded classes get a ~-inf prior and are never the label,
                # so their gradients (and trees) are zero.
                k_pad = next(b for b in (4, 8, 16, 24, MAX_MULTICLASS)
                             if b >= k)
                self._k = k_pad
                # bound the k-trees-per-round cost
                self.n_estimators = min(self.n_estimators, max(40, 400 // k_pad))
                yv = codes.astype(np.float32)
                priors = np.zeros(k_pad)
                np.add.at(priors, codes, w)
                priors = np.maximum(priors / priors.sum(), 1e-13)
                base = np.log(priors).astype(np.float32)
        else:
            self._objective = "regression"
            self._k = 1
            yv = pd.to_numeric(pd.Series(np.asarray(y)), errors="coerce") \
                .to_numpy(dtype=np.float64)
            assert not np.isnan(yv).any(), "y must not contain NULLs"
            # Heavily right-skewed nonnegative targets (e.g. crime rates) fit
            # much better in log space; LightGBM's leaf-wise growth absorbs
            # skew implicitly, this is the depth-wise equivalent.
            std = yv.std()
            skew = float(((yv - yv.mean()) ** 3).mean() / (std ** 3)) if std > 0 else 0.0
            self._log_target = bool((yv >= 0).all() and skew > 2.0)
            if self._log_target:
                yv = np.log1p(yv)
            yv = yv.astype(np.float32)
            w = np.ones(n)
            base = np.array([float(yv.mean())], dtype=np.float32)
            self._classes = np.array([])

        self._base = base
        yv_p = self._pad(np.asarray(yv, np.float32), mesh=mesh, train=True)
        w_p = self._pad(np.asarray(w, np.float32), mesh=mesh, train=True)
        # Optional leaf row-count floor (LightGBM's min_child_samples).
        # Default 0: prior recalibration in predict_proba already guards
        # against upweighted rare typo classes, and a hard floor costs
        # accuracy on tight local structure (e.g. boston RAD).
        mcs = self.min_child_samples if self.is_discrete else 0.0
        # Chunked fit: the margin carry stays on device between fixed-size
        # chunk launches, so any CV-selected round count (the early-stopping
        # driver below) reuses the SAME compiled chunk program instead of
        # compiling one scan per distinct n_estimators.
        F = _init_margin(base, bins_np.shape[0], self._objective,
                         max(self._k, 1))
        parts: List[Any] = []
        if mesh is not None:
            from delphi_tpu.parallel.mesh import shard_rows
            bins_dev = shard_rows(bins_np, mesh)
            y_dev = shard_rows(yv_p, mesh)
            w_dev = shard_rows(w_p, mesh)
            F = shard_rows(F, mesh)
            for chunk in _round_chunks(self.n_estimators):
                step = _mesh_boost_fn(
                    mesh, chunk, self.max_depth, self._n_bins, self._n_nodes,
                    self._objective, max(self._k, 1),
                    float(self.learning_rate), float(self.reg_lambda),
                    float(self.min_split_gain), float(self.min_child_weight),
                    float(mcs))
                F, trees = step(bins_dev, y_dev, w_dev, F)
                parts.append(trees)
        else:
            bins_dev = jnp.asarray(bins_np)
            y_dev = jnp.asarray(yv_p)
            w_dev = jnp.asarray(w_p)
            F = jnp.asarray(F)
            for chunk in _round_chunks(self.n_estimators):
                F, trees = _boost(
                    bins_dev, y_dev, w_dev, F, chunk, self.max_depth,
                    self._n_bins, self._n_nodes, self._objective,
                    max(self._k, 1), self.learning_rate, self.reg_lambda,
                    self.min_split_gain, self.min_child_weight, mcs,
                    use_counts=mcs > 0)
                parts.append(trees)
        parts = [jax.device_get(t) for t in parts]
        self._trees = tuple(
            np.concatenate([p[i] for p in parts], axis=0) for i in range(3))
        return self

    def _raw_scores(self, X: Any) -> np.ndarray:
        from delphi_tpu.parallel.mesh import get_active_mesh
        mesh = get_active_mesh()
        Xm = self._as_matrix(X)
        n = Xm.shape[0]
        bins_np = self._pad(self._pad_feature_dim(
            self._binner.transform(Xm)), mesh=mesh)
        if mesh is not None:
            F = _mesh_predict(mesh, bins_np, *self._trees,
                              self.n_estimators, self.max_depth,
                              self._objective, max(self._k, 1), self._base)
        else:
            feats, thrs, leaves = (jnp.asarray(t) for t in self._trees)
            F = _predict_boosted(bins_np, feats, thrs, leaves,
                                 self.n_estimators, self.max_depth,
                                 self._objective, max(self._k, 1),
                                 jnp.asarray(self._base))
        F = np.asarray(F)
        return F[..., :n]

    def _recalibrate(self, probs: np.ndarray) -> np.ndarray:
        """Importance-corrects probabilities back to the TRUE class priors.

        Training reweights classes (balanced weights w_c), so the model
        estimates p_q(y|x) under the reweighted distribution q(y) ∝
        count_c * w_c. Dividing by w_c and renormalizing recovers
        p(y|x) under the empirical priors — so ultra-rare noise classes
        (undetected typos) keep their minority recall during training but
        cannot win ambiguous repair predictions on priors they don't have."""
        w = getattr(self, "_fit_class_weights", None)
        if w is None:
            return probs
        corrected = probs / np.maximum(w[None, :], 1e-12)
        return corrected / np.maximum(
            corrected.sum(axis=1, keepdims=True), 1e-12)

    def predict_proba(self, X: Any) -> np.ndarray:
        assert self.is_discrete
        F = self._raw_scores(X)
        if self._objective == "binary":
            p = 1.0 / (1.0 + np.exp(-F))
            return self._recalibrate(np.stack([1 - p, p], axis=1))
        F = F[: len(self.classes_)]  # drop padded bucket classes
        z = F - F.max(axis=0, keepdims=True)
        e = np.exp(z)
        return self._recalibrate((e / e.sum(axis=0, keepdims=True)).T)

    def predict(self, X: Any) -> np.ndarray:
        if self.is_discrete:
            return self.classes_[self.predict_proba(X).argmax(axis=1)]
        pred = self._raw_scores(X)
        if getattr(self, "_log_target", False):
            pred = np.expm1(pred)
        return pred
