"""Feature encoding for repair models.

Replaces the reference's category_encoders SumEncoder/OrdinalEncoder split
(`model.py:701-729`) with a single encoder that one-hot encodes discrete
features (with a dedicated unknown/NULL slot — `handle_unknown='impute'`
behavior) and standardizes continuous ones (NaN -> mean, i.e. 0 after
standardization). The output is a dense float32 design matrix, the natural
input layout for MXU matmuls.
"""

from typing import Dict, List, Optional, Sequence

import numpy as np
import pandas as pd


def _vocab_codes(series: pd.Series, vocab: Dict[str, int],
                 default: int) -> np.ndarray:
    """``vocab.get(str(v), default)`` per cell (NULL -> default), computed
    over the DISTINCT raw values: one C-speed factorize pass plus a
    vocab-sized Python loop instead of a per-row lambda. Distinct raw
    values sharing a string form hit the same vocab entry, exactly like
    the per-row ``str(v)`` lookup (with -0.0 folded into +0.0 so the probe
    string matches the encode-side normalization in table.py)."""
    from delphi_tpu.table import normalize_neg_zero
    try:
        codes, uniques = pd.factorize(normalize_neg_zero(series.to_numpy()),
                                      use_na_sentinel=True)
    except TypeError:
        # unhashable cell values (e.g. ad-hoc object columns) — per-row path
        return series.map(
            lambda v: vocab.get(str(v), default) if pd.notna(v) else default
        ).to_numpy(dtype=np.int64)
    if len(uniques) == 0:  # all-NULL column
        return np.full(len(codes), default, dtype=np.int64)
    lut = np.fromiter((vocab.get(str(v), default) for v in uniques),
                      dtype=np.int64, count=len(uniques))
    return np.where(codes >= 0,
                    lut[np.maximum(codes, 0)],
                    np.int64(default))


def f1_macro(y_true: np.ndarray, y_pred: np.ndarray) -> float:
    """Macro-averaged F1 over the classes present in ``y_true`` — the CV
    scorer for classifier model selection (the same metric the reference
    feeds hyperopt, train.py:158). Shared by the sequential and batched CV
    paths so their scores cannot diverge."""
    classes = np.unique(y_true)
    f1s = []
    for c in classes:
        tp = float(((y_pred == c) & (y_true == c)).sum())
        fp = float(((y_pred == c) & (y_true != c)).sum())
        fn = float(((y_pred != c) & (y_true == c)).sum())
        p = tp / (tp + fp) if tp + fp > 0 else 0.0
        r = tp / (tp + fn) if tp + fn > 0 else 0.0
        f1s.append(2 * p * r / (p + r) if p + r > 0 else 0.0)
    return float(np.mean(f1s)) if f1s else 0.0


def balanced_class_weights(counts: np.ndarray, n: int,
                           damped: bool = True) -> np.ndarray:
    """Balanced class weights, optionally square-root-damped.

    The reference trains LightGBM with sklearn-style ``class_weight=
    'balanced'`` (``n / (k * count)``, train.py:105). On dirty tables that
    scheme gives one-row noise classes (undetected typos like 'yex'/'ax' in
    hospital) weights hundreds of times larger than the majority class, so
    repair-time predictions on masked rows collapse into typo leaves. With
    ``damped=True`` (the GBDT head) the sqrt keeps the minority-vs-majority
    ordering but compresses the ratio quadratically — minority recall stays,
    typo classes stop winning. The logistic head uses ``damped=False`` (the
    reference's exact scheme): its huge-cardinality targets depend on strong
    minority upweighting (flights repair F1 drops measurably without it)."""
    k = len(counts)
    raw = n / (k * np.maximum(counts.astype(np.float64), 1.0))
    return np.sqrt(raw) if damped else raw


class OneHotDesign:
    """Compact factorization of :class:`FeatureEncoder`'s dense design
    matrix. The dense matrix is block-sparse — one 1.0 per discrete-feature
    block — so ``X @ W`` is really an embedding gather: storing the per-block
    LOCAL hot index (``cat_idx``) plus the dense continuous columns lets the
    logistic head train with O(n * F * k) gathers instead of O(n * D * k)
    matmul FLOPs (D is the summed vocab width, often hundreds of times F).
    ``layout`` records each feature's dense column span so the dense matrix
    (or dense-equivalent weights) can always be reconstructed."""

    def __init__(self, cat_idx: np.ndarray, cont: np.ndarray,
                 cat_sizes: List[int], layout: List[tuple],
                 width: int) -> None:
        self.cat_idx = cat_idx      # int32 [n, Fc], local index per block
        self.cont = cont            # float32 [n, Fd]
        self.cat_sizes = cat_sizes  # [Fc] block widths (vocab + unknown slot)
        self.layout = layout        # [("cat"|"cont", dense_start, slot)]
        self.width = width          # dense column count

    @property
    def shape(self):
        return (self.cat_idx.shape[0], self.width)

    def __len__(self) -> int:
        return self.cat_idx.shape[0]

    def dense(self) -> np.ndarray:
        n = len(self)
        out = np.zeros((n, self.width), dtype=np.float32)
        rows = np.arange(n)
        for kind, start, slot in self.layout:
            if kind == "cat":
                out[rows, start + self.cat_idx[:, slot]] = 1.0
            else:
                out[:, start] = self.cont[:, slot]
        return out


class FeatureEncoder:
    """fit/transform over pandas feature frames -> float32 [n, D]."""

    def __init__(self, features: Sequence[str], continuous: Sequence[str],
                 max_onehot: int = 256) -> None:
        self.features = list(features)
        self.continuous = [c for c in continuous if c in self.features]
        self.max_onehot = max_onehot
        self._vocab: Dict[str, Dict[str, int]] = {}
        self._mean: Dict[str, float] = {}
        self._std: Dict[str, float] = {}
        self.n_dims = 0
        self._fitted = False

    def fit(self, X: pd.DataFrame) -> "FeatureEncoder":
        self.n_dims = 0
        for f in self.features:
            if f in self.continuous:
                v = pd.to_numeric(X[f], errors="coerce").to_numpy(dtype=np.float64)
                mean = float(np.nanmean(v)) if np.isfinite(v).any() else 0.0
                std = float(np.nanstd(v))
                self._mean[f] = mean
                self._std[f] = std if std > 0 else 1.0
                self.n_dims += 1
            else:
                values = X[f].dropna().astype(str)
                counts = values.value_counts()
                vocab = {v: i for i, v in enumerate(counts.index[: self.max_onehot])}
                self._vocab[f] = vocab
                self.n_dims += len(vocab) + 1  # +1 unknown/NULL slot
        self._fitted = True
        return self

    def transform(self, X: pd.DataFrame) -> np.ndarray:
        assert self._fitted, "fit() must be called before transform()"
        n = len(X)
        out = np.zeros((n, self.n_dims), dtype=np.float32)
        d = 0
        for f in self.features:
            if f in self.continuous:
                v = pd.to_numeric(X[f], errors="coerce").to_numpy(dtype=np.float64)
                v = (v - self._mean[f]) / self._std[f]
                out[:, d] = np.where(np.isnan(v), 0.0, v).astype(np.float32)
                d += 1
            else:
                vocab = self._vocab[f]
                width = len(vocab) + 1
                idx = _vocab_codes(X[f], vocab, len(vocab))
                out[np.arange(n), d + idx] = 1.0
                d += width
        return out

    def fit_transform(self, X: pd.DataFrame) -> np.ndarray:
        return self.fit(X).transform(X)

    def transform_compact(self, X: pd.DataFrame) -> OneHotDesign:
        """Same encoding as :meth:`transform` in the factored
        :class:`OneHotDesign` form (``design.dense()`` reproduces
        ``transform(X)`` exactly)."""
        assert self._fitted, "fit() must be called before transform_compact()"
        n = len(X)
        cat_cols, cat_sizes, cont_cols, layout = [], [], [], []
        d = 0
        for f in self.features:
            if f in self.continuous:
                v = pd.to_numeric(X[f], errors="coerce").to_numpy(dtype=np.float64)
                v = (v - self._mean[f]) / self._std[f]
                layout.append(("cont", d, len(cont_cols)))
                cont_cols.append(np.where(np.isnan(v), 0.0, v).astype(np.float32))
                d += 1
            else:
                vocab = self._vocab[f]
                width = len(vocab) + 1
                layout.append(("cat", d, len(cat_cols)))
                cat_cols.append(_vocab_codes(X[f], vocab, len(vocab))
                                .astype(np.int32))
                cat_sizes.append(width)
                d += width
        cat_idx = np.stack(cat_cols, axis=1) if cat_cols \
            else np.zeros((n, 0), np.int32)
        cont = np.stack(cont_cols, axis=1) if cont_cols \
            else np.zeros((n, 0), np.float32)
        return OneHotDesign(cat_idx, cont, cat_sizes, layout, self.n_dims)

    def fit_transform_compact(self, X: pd.DataFrame) -> OneHotDesign:
        return self.fit(X).transform_compact(X)


class OrdinalEncoder:
    """Discrete values -> ordinal codes (unknown/NULL -> -1), continuous kept
    raw. The bin-friendly layout used by the GBDT models."""

    def __init__(self, features: Sequence[str], continuous: Sequence[str]) -> None:
        self.features = list(features)
        self.continuous = [c for c in continuous if c in self.features]
        self._vocab: Dict[str, Dict[str, int]] = {}
        self._fitted = False

    def fit(self, X: pd.DataFrame) -> "OrdinalEncoder":
        for f in self.features:
            if f not in self.continuous:
                values = X[f].dropna().astype(str).unique()
                self._vocab[f] = {v: i for i, v in enumerate(values)}
        self._fitted = True
        return self

    def transform(self, X: pd.DataFrame) -> np.ndarray:
        assert self._fitted
        cols = []
        for f in self.features:
            if f in self.continuous:
                cols.append(pd.to_numeric(X[f], errors="coerce")
                            .to_numpy(dtype=np.float64))
            else:
                vocab = self._vocab[f]
                codes = _vocab_codes(X[f], vocab, -1).astype(np.float64)
                codes[codes < 0] = np.nan
                cols.append(codes)
        return np.stack(cols, axis=1) if cols else np.zeros((len(X), 0))

    def fit_transform(self, X: pd.DataFrame) -> np.ndarray:
        return self.fit(X).transform(X)
