"""Feature encoding for repair models.

Replaces the reference's category_encoders SumEncoder/OrdinalEncoder split
(`model.py:701-729`) with a single encoder that one-hot encodes discrete
features (with a dedicated unknown/NULL slot — `handle_unknown='impute'`
behavior) and standardizes continuous ones (NaN -> mean, i.e. 0 after
standardization). The output is a dense float32 design matrix, the natural
input layout for MXU matmuls.
"""

from typing import Dict, List, Optional, Sequence

import numpy as np
import pandas as pd


class FeatureEncoder:
    """fit/transform over pandas feature frames -> float32 [n, D]."""

    def __init__(self, features: Sequence[str], continuous: Sequence[str],
                 max_onehot: int = 256) -> None:
        self.features = list(features)
        self.continuous = [c for c in continuous if c in self.features]
        self.max_onehot = max_onehot
        self._vocab: Dict[str, Dict[str, int]] = {}
        self._mean: Dict[str, float] = {}
        self._std: Dict[str, float] = {}
        self.n_dims = 0
        self._fitted = False

    def fit(self, X: pd.DataFrame) -> "FeatureEncoder":
        self.n_dims = 0
        for f in self.features:
            if f in self.continuous:
                v = pd.to_numeric(X[f], errors="coerce").to_numpy(dtype=np.float64)
                mean = float(np.nanmean(v)) if np.isfinite(v).any() else 0.0
                std = float(np.nanstd(v))
                self._mean[f] = mean
                self._std[f] = std if std > 0 else 1.0
                self.n_dims += 1
            else:
                values = X[f].dropna().astype(str)
                counts = values.value_counts()
                vocab = {v: i for i, v in enumerate(counts.index[: self.max_onehot])}
                self._vocab[f] = vocab
                self.n_dims += len(vocab) + 1  # +1 unknown/NULL slot
        self._fitted = True
        return self

    def transform(self, X: pd.DataFrame) -> np.ndarray:
        assert self._fitted, "fit() must be called before transform()"
        n = len(X)
        out = np.zeros((n, self.n_dims), dtype=np.float32)
        d = 0
        for f in self.features:
            if f in self.continuous:
                v = pd.to_numeric(X[f], errors="coerce").to_numpy(dtype=np.float64)
                v = (v - self._mean[f]) / self._std[f]
                out[:, d] = np.where(np.isnan(v), 0.0, v).astype(np.float32)
                d += 1
            else:
                vocab = self._vocab[f]
                width = len(vocab) + 1
                idx = X[f].map(
                    lambda v: vocab.get(str(v), len(vocab)) if pd.notna(v) else len(vocab)
                ).to_numpy(dtype=np.int64)
                out[np.arange(n), d + idx] = 1.0
                d += width
        return out

    def fit_transform(self, X: pd.DataFrame) -> np.ndarray:
        return self.fit(X).transform(X)


class OrdinalEncoder:
    """Discrete values -> ordinal codes (unknown/NULL -> -1), continuous kept
    raw. The bin-friendly layout used by the GBDT models."""

    def __init__(self, features: Sequence[str], continuous: Sequence[str]) -> None:
        self.features = list(features)
        self.continuous = [c for c in continuous if c in self.features]
        self._vocab: Dict[str, Dict[str, int]] = {}
        self._fitted = False

    def fit(self, X: pd.DataFrame) -> "OrdinalEncoder":
        for f in self.features:
            if f not in self.continuous:
                values = X[f].dropna().astype(str).unique()
                self._vocab[f] = {v: i for i, v in enumerate(values)}
        self._fitted = True
        return self

    def transform(self, X: pd.DataFrame) -> np.ndarray:
        assert self._fitted
        cols = []
        for f in self.features:
            if f in self.continuous:
                cols.append(pd.to_numeric(X[f], errors="coerce")
                            .to_numpy(dtype=np.float64))
            else:
                vocab = self._vocab[f]
                codes = X[f].map(
                    lambda v: vocab.get(str(v), -1) if pd.notna(v) else -1
                ).to_numpy(dtype=np.float64)
                codes[codes < 0] = np.nan
                cols.append(codes)
        return np.stack(cols, axis=1) if cols else np.zeros((len(X), 0))

    def fit_transform(self, X: pd.DataFrame) -> np.ndarray:
        return self.fit(X).transform(X)
