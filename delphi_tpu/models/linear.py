"""Jitted linear / MLP repair-model heads.

The classifier is a multinomial logistic regression over one-hot features and
the regressor a small MLP — both trained full-batch with optax.adam inside a
``lax.while_loop`` so the whole optimization compiles to a single XLA program
(no per-step Python) and exits as soon as the loss plateaus instead of always
paying the step cap. Rows are padded to the next power of two to bound XLA
recompilation across the per-attribute model loop.

They expose the scikit-learn-like duck type (``classes_`` / ``predict`` /
``predict_proba``) that the repair pipeline expects (reference
model.py:44-100, train.py:232-234).
"""

from functools import lru_cache, partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pandas as pd


def _pad_rows(X: np.ndarray, *arrays: np.ndarray, mesh: Any = None):
    # training-row pad target: fits are capped by model.max_training_row_num,
    # so the finer granularity saves real FLOPs (10000 -> 10240, not 16384)
    # without multiplying compiled variants
    from delphi_tpu.models.gbdt import train_row_target
    n = X.shape[0]
    padded = train_row_target(n, mesh)
    if padded == n:
        mask = np.ones(n, dtype=np.float32)
        return X, arrays, mask
    pad = padded - n
    Xp = np.concatenate([X, np.zeros((pad, X.shape[1]), X.dtype)], axis=0)
    outs = tuple(np.concatenate([a, np.zeros((pad,) + a.shape[1:], a.dtype)], axis=0)
                 for a in arrays)
    mask = np.concatenate([np.ones(n, np.float32), np.zeros(pad, np.float32)])
    return Xp, outs, mask


def _pad_cols(X: np.ndarray) -> np.ndarray:
    """Pads the feature axis to the next power of two so the per-attribute
    training loop reuses one compiled program across one-hot widths; padded
    columns are all-zero, so their weights only see the L2 pull and stay 0."""
    from delphi_tpu.parallel import planner

    d = X.shape[1]
    target = planner.pow2_pad(d, floor=8)
    if target == d:
        return X
    return np.concatenate(
        [X, np.zeros((X.shape[0], target - d), X.dtype)], axis=1)


@partial(jax.jit, static_argnames=("n_steps", "axis_name"))
def _fit_logreg(X, y, mask, class_weights, l2, lr, n_steps, axis_name=None):
    n, d = X.shape
    k = class_weights.shape[0]
    W = jnp.zeros((d, k), dtype=jnp.float32)
    b = jnp.zeros((k,), dtype=jnp.float32)
    opt = optax.adam(lr)
    state = opt.init((W, b))
    sample_w = mask * class_weights[y]
    denom_local = sample_w.sum()
    if axis_name is not None:
        # rows sharded over dp: the weighted-row normalizer is global, the
        # L2 term is divided by the shard count so the psum of per-device
        # losses/grads counts it exactly once
        denom = jnp.maximum(jax.lax.psum(denom_local, axis_name), 1.0)
        reg_scale = 1.0 / jax.lax.psum(1.0, axis_name)
    else:
        denom = jnp.maximum(denom_local, 1.0)
        reg_scale = 1.0

    def loss_fn(params):
        W, b = params
        logits = X @ W + b
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, y[:, None], axis=1)[:, 0]
        return (sample_w * nll).sum() / denom + reg_scale * l2 * jnp.sum(W * W)

    def one_step(params, state):
        loss, grads = jax.value_and_grad(loss_fn)(params)
        if axis_name is not None:
            # data-parallel allreduce keeps params identical on all devices
            loss = jax.lax.psum(loss, axis_name)
            grads = jax.lax.psum(grads, axis_name)
        updates, state = opt.update(grads, state)
        params = optax.apply_updates(params, updates)
        return params, state, loss

    # Convergence early exit: full-batch adam on the (convex) multinomial
    # objective plateaus well before the step cap on most attributes — a
    # while_loop with a relative loss tolerance stops there, cutting the
    # dominant phase-2 cost at scale. The psum'd loss is identical on every
    # device, so the mesh path exits in lockstep.
    tol = 1e-6

    def cond(carry):
        i, _, _, prev, cur = carry
        return (i < n_steps) & ((i < 20) |
                                (jnp.abs(prev - cur) > tol * (1.0 + jnp.abs(cur))))

    def body(carry):
        i, params, state, _, cur = carry
        params, state, loss = one_step(params, state)
        return i + 1, params, state, cur, loss

    _, params, _, _, last_loss = jax.lax.while_loop(
        cond, body, (jnp.int32(0), (W, b), state,
                     jnp.float32(jnp.inf), jnp.float32(jnp.inf)))
    return params, last_loss


@partial(jax.jit, static_argnames=("n_steps", "n_vocab"))
def _fit_logreg_gather(gid, cont, fmask, y, mask, class_weights, l2, lr,
                       n_steps, n_vocab):
    """The logistic head on the FACTORED one-hot design (OneHotDesign):
    ``X @ W`` over a block-one-hot matrix is an embedding gather, so the
    per-step cost drops from O(n * D * k) matmul FLOPs to O(n * F * k)
    gathers (D = summed vocab width, F = feature count). Identical
    objective, weights and convergence rule as `_fit_logreg` — the dense
    matmul IS this gather, so both paths optimize the same loss surface.
    Used on CPU hosts where the dense one-hot matmul dominates phase 2;
    accelerators keep the dense MXU path."""
    n, fc = gid.shape
    k = class_weights.shape[0]
    Wcat = jnp.zeros((n_vocab, k), dtype=jnp.float32)
    Wcont = jnp.zeros((cont.shape[1], k), dtype=jnp.float32)
    b = jnp.zeros((k,), dtype=jnp.float32)
    opt = optax.adam(lr)
    state = opt.init((Wcat, Wcont, b))
    sample_w = mask * class_weights[y]
    denom = jnp.maximum(sample_w.sum(), 1.0)

    def loss_fn(params):
        Wcat, Wcont, b = params
        g = (Wcat[gid] * fmask[None, :, None]).sum(axis=1)  # [n, k]
        logits = g + cont @ Wcont + b
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, y[:, None], axis=1)[:, 0]
        return (sample_w * nll).sum() / denom \
            + l2 * (jnp.sum(Wcat * Wcat) + jnp.sum(Wcont * Wcont))

    tol = 1e-6

    def cond(carry):
        i, _, _, prev, cur = carry
        return (i < n_steps) & ((i < 20) |
                                (jnp.abs(prev - cur) > tol * (1.0 + jnp.abs(cur))))

    def body(carry):
        i, params, state, _, cur = carry
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, state = opt.update(grads, state)
        params = optax.apply_updates(params, updates)
        return i + 1, params, state, cur, loss

    _, params, _, _, last_loss = jax.lax.while_loop(
        cond, body, (jnp.int32(0), (Wcat, Wcont, b), state,
                     jnp.float32(jnp.inf), jnp.float32(jnp.inf)))
    return params, last_loss


@lru_cache(maxsize=128)
def _mesh_logreg_fn(mesh, l2, lr, n_steps):
    """Cached, jitted shard_map program per (mesh, hyperparameters) so
    repeated per-attribute fits reuse one compiled executable."""
    from jax.sharding import PartitionSpec as P

    from delphi_tpu.parallel.mesh import shard_map

    def fn(X_l, y_l, m_l, cw):
        return _fit_logreg(X_l, y_l, m_l, cw, l2, lr, n_steps, axis_name="dp")

    return jax.jit(shard_map(fn, mesh=mesh,
                             in_specs=(P("dp", None), P("dp"), P("dp"), P()),
                             out_specs=((P(), P()), P())))


def _mesh_fit_logreg(mesh, X, y, mask, class_weights, l2, lr, n_steps):
    """Logistic-head training with rows sharded over the mesh's dp axis and
    per-step psum'd gradients (reference P2, SURVEY.md §2.3)."""
    from delphi_tpu.parallel.mesh import shard_rows

    step = _mesh_logreg_fn(mesh, float(l2), float(lr), int(n_steps))
    return step(shard_rows(X, mesh), shard_rows(y, mesh),
                shard_rows(mask, mesh), jnp.asarray(class_weights))


@partial(jax.jit, static_argnames=("n_steps", "hidden"))
def _fit_mlp_regressor(X, y, mask, l2, lr, n_steps, hidden, seed):
    n, d = X.shape
    key = jax.random.PRNGKey(seed)
    k1, k2, k3 = jax.random.split(key, 3)
    params = {
        "w1": jax.random.normal(k1, (d, hidden), jnp.float32) * jnp.sqrt(2.0 / max(d, 1)),
        "b1": jnp.zeros((hidden,), jnp.float32),
        "w2": jax.random.normal(k2, (hidden, hidden), jnp.float32) * jnp.sqrt(2.0 / hidden),
        "b2": jnp.zeros((hidden,), jnp.float32),
        "w3": jax.random.normal(k3, (hidden, 1), jnp.float32) * jnp.sqrt(2.0 / hidden),
        "b3": jnp.zeros((1,), jnp.float32),
    }
    opt = optax.adam(lr)
    state = opt.init(params)
    denom = jnp.maximum(mask.sum(), 1.0)

    def forward(p, X):
        h = jax.nn.relu(X @ p["w1"] + p["b1"])
        h = jax.nn.relu(h @ p["w2"] + p["b2"])
        return (h @ p["w3"] + p["b3"])[:, 0]

    def loss_fn(p):
        pred = forward(p, X)
        mse = (mask * (pred - y) ** 2).sum() / denom
        reg = sum(jnp.sum(p[k] ** 2) for k in ("w1", "w2", "w3"))
        return mse + l2 * reg

    # Same convergence early exit as the logistic head, with a tighter
    # relative tolerance: the MLP objective is non-convex and adam's loss
    # can plateau briefly before further descent, so only a near-exact
    # plateau stops early (the iris/boston RMSE gates pin the quality).
    tol = 1e-7

    def cond(carry):
        i, _, _, prev, cur = carry
        return (i < n_steps) & ((i < 50) |
                                (jnp.abs(prev - cur) > tol * (1.0 + jnp.abs(cur))))

    def body(carry):
        i, p, s, _, cur = carry
        loss, grads = jax.value_and_grad(loss_fn)(p)
        updates, s = opt.update(grads, s)
        p = optax.apply_updates(p, updates)
        return i + 1, p, s, cur, loss

    _, params, _, _, last_loss = jax.lax.while_loop(
        cond, body, (jnp.int32(0), params, state,
                     jnp.float32(jnp.inf), jnp.float32(jnp.inf)))
    return params, last_loss


@jax.jit
def _mlp_forward(params, X):
    h = jax.nn.relu(X @ params["w1"] + params["b1"])
    h = jax.nn.relu(h @ params["w2"] + params["b2"])
    return (h @ params["w3"] + params["b3"])[:, 0]


class LogisticRegressionModel:
    """Multinomial logistic regression with balanced class weights (the
    reference trains LightGBM with class_weight='balanced', train.py:105)."""

    def __init__(self, n_steps: int = 300, lr: float = 0.2, l2: float = 1e-4) -> None:
        self.n_steps = n_steps
        self.lr = lr
        self.l2 = l2
        self._params: Optional[Any] = None
        self._classes: Optional[np.ndarray] = None
        self.loss_: float = 0.0

    @property
    def classes_(self) -> np.ndarray:
        assert self._classes is not None
        return self._classes

    def fit(self, X: Any, y: "pd.Series") -> "LogisticRegressionModel":
        from delphi_tpu.models.encoding import OneHotDesign

        codes, classes = pd.factorize(np.asarray(y), sort=True)
        assert (codes >= 0).all(), "y must not contain NULLs"
        self._classes = np.asarray(classes)
        k = len(classes)
        # Bucket the class axis to the next multiple of 8 (shared compiled
        # program across targets); padded classes have weight 0 and are never
        # a label, so they only add dead softmax columns.
        k_pad = max(8, -(-k // 8) * 8)
        counts = np.bincount(codes, minlength=k_pad).astype(np.float32)
        class_weights = np.zeros(k_pad, np.float32)
        from delphi_tpu.models.encoding import balanced_class_weights
        class_weights[:k] = balanced_class_weights(
            counts[:k], len(codes), damped=False)

        from delphi_tpu.parallel.mesh import get_active_mesh
        mesh = get_active_mesh()
        self._compact = None
        import os
        if isinstance(X, OneHotDesign) and X.cat_idx.shape[1] > 0 \
                and mesh is None and jax.default_backend() == "cpu" \
                and os.environ.get("DELPHI_DENSE_LOGREG") != "1":
            self._fit_compact(X, codes, class_weights)
            return self
        if isinstance(X, OneHotDesign):
            X = X.dense()  # accelerators keep the dense MXU matmul path
        Xp, (yp,), mask = _pad_rows(_pad_cols(np.asarray(X, np.float32)),
                                    codes.astype(np.int32), mesh=mesh)
        if mesh is not None:
            params, loss = _mesh_fit_logreg(
                mesh, Xp, yp, mask, class_weights, self.l2, self.lr,
                self.n_steps)
        else:
            params, loss = _fit_logreg(
                jnp.asarray(Xp), jnp.asarray(yp), jnp.asarray(mask),
                jnp.asarray(class_weights), self.l2, self.lr, self.n_steps)
        self._params = jax.device_get(params)
        self.loss_ = float(loss)
        return self

    def _fit_compact(self, X: Any, codes: np.ndarray,
                     class_weights: np.ndarray) -> None:
        """Gather-path training from a OneHotDesign (CPU hosts)."""
        sizes = np.asarray(X.cat_sizes, np.int64)
        offsets = np.concatenate([[0], np.cumsum(sizes[:-1])])
        gid = (offsets[None, :] + X.cat_idx).astype(np.int32)
        n, fc = gid.shape
        # pad features to a multiple of 4 and the vocab to a power of two so
        # per-attribute fits share compiled programs; padded feature slots
        # point at row 0 with fmask 0 (no logit contribution)
        fc_pad = max(4, -(-fc // 4) * 4)
        if fc_pad != fc:
            gid = np.concatenate(
                [gid, np.zeros((n, fc_pad - fc), np.int32)], axis=1)
        fmask = (np.arange(fc_pad) < fc).astype(np.float32)
        v = int(sizes.sum())
        from delphi_tpu.parallel import planner
        v_pad = planner.pow2_pad(v, floor=16)
        cont = _pad_cols(X.cont) if X.cont.shape[1] else \
            np.zeros((n, 8), np.float32)
        gid_p, (yp, cont_p), mask = _pad_rows(gid, codes.astype(np.int32),
                                              cont)
        params, loss = _fit_logreg_gather(
            jnp.asarray(gid_p), jnp.asarray(cont_p), jnp.asarray(fmask),
            jnp.asarray(yp), jnp.asarray(mask), jnp.asarray(class_weights),
            self.l2, self.lr, self.n_steps, v_pad)
        self._compact = {
            "offsets": offsets, "sizes": sizes, "fc": fc, "fc_pad": fc_pad,
            "layout": X.layout, "width": X.width,
        }
        self._params = jax.device_get(params)
        self.loss_ = float(loss)

    def _dense_weights(self) -> Any:
        """Dense-equivalent (W, b) reconstructed from gather-path params via
        the recorded design layout (for callers handing in dense arrays)."""
        Wcat, Wcont, b = self._params
        c = self._compact
        W = np.zeros((c["width"], Wcat.shape[1]), np.float32)
        for kind, start, slot in c["layout"]:
            if kind == "cat":
                o = int(c["offsets"][slot])
                W[start:start + int(c["sizes"][slot])] = \
                    Wcat[o:o + int(c["sizes"][slot])]
            else:
                W[start] = Wcont[slot]
        return W, b

    def predict_proba(self, X: Any) -> np.ndarray:
        from delphi_tpu.models.encoding import OneHotDesign
        assert self._params is not None
        k = len(self.classes_)
        if getattr(self, "_compact", None) is not None:
            if isinstance(X, OneHotDesign):
                Wcat, Wcont, b = self._params
                c = self._compact
                gid = (c["offsets"][None, :] + X.cat_idx).astype(np.int64)
                logits = Wcat[gid].sum(axis=1) + b
                if X.cont.shape[1]:
                    logits = logits + X.cont @ Wcont[:X.cont.shape[1]]
            else:
                W, b = self._dense_weights()
                logits = np.asarray(X, np.float32) @ W + b
        else:
            if isinstance(X, OneHotDesign):
                X = X.dense()
            W, b = self._params
            logits = _pad_cols(np.asarray(X, np.float32)) @ W + b
        logits = logits[:, :k]  # drop padded bucket classes
        logits -= logits.max(axis=1, keepdims=True)
        e = np.exp(logits)
        # NOTE: no prior recalibration here, unlike the GBDT head. The
        # logistic head serves huge-cardinality targets whose true repairs
        # are often rare values (e.g. flights times); correcting toward the
        # empirical priors measurably hurts repair F1 there, while the typo-
        # class failure mode it guards against lives in low-cardinality
        # GBDT targets.
        return e / e.sum(axis=1, keepdims=True)

    def predict(self, X: np.ndarray) -> np.ndarray:
        probs = self.predict_proba(X)
        return self.classes_[probs.argmax(axis=1)]


class MLPRegressorModel:
    """Small MLP regressor with standardized targets."""

    def __init__(self, n_steps: int = 500, lr: float = 0.01, l2: float = 1e-5,
                 hidden: int = 64, seed: int = 42) -> None:
        self.n_steps = n_steps
        self.lr = lr
        self.l2 = l2
        self.hidden = hidden
        self.seed = seed
        self._params: Optional[Any] = None
        self._y_mean = 0.0
        self._y_std = 1.0
        self.loss_: float = 0.0

    @property
    def classes_(self) -> np.ndarray:
        return np.array([])

    def fit(self, X: Any, y: "pd.Series") -> "MLPRegressorModel":
        from delphi_tpu.models.encoding import OneHotDesign
        if isinstance(X, OneHotDesign):
            X = X.dense()
        yv = pd.to_numeric(pd.Series(np.asarray(y)), errors="coerce") \
            .to_numpy(dtype=np.float64)
        assert not np.isnan(yv).any(), "y must not contain NULLs"
        self._y_mean = float(yv.mean())
        self._y_std = float(yv.std()) or 1.0
        yn = ((yv - self._y_mean) / self._y_std).astype(np.float32)

        Xp, (yp,), mask = _pad_rows(_pad_cols(np.asarray(X, np.float32)), yn)
        params, loss = _fit_mlp_regressor(
            jnp.asarray(Xp), jnp.asarray(yp), jnp.asarray(mask),
            self.l2, self.lr, self.n_steps, self.hidden, self.seed)
        self._params = params
        self.loss_ = float(loss)
        return self

    def predict(self, X: Any) -> np.ndarray:
        from delphi_tpu.models.encoding import OneHotDesign
        if isinstance(X, OneHotDesign):
            X = X.dense()
        assert self._params is not None
        pred = np.asarray(_mlp_forward(
            self._params,
            jnp.asarray(_pad_cols(np.asarray(X, np.float32)))))
        return pred * self._y_std + self._y_mean

    def predict_proba(self, X: np.ndarray) -> np.ndarray:
        raise NotImplementedError("regressors have no probability output")
