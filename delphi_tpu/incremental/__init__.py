"""Incremental repair plane: snapshot manifests, delta planning, and
drift-gated model reuse.

Connects the pieces earlier subsystems left on the table — per-cell
provenance with drift gates, fingerprint-keyed device-resident codes,
phase checkpoints, and the warm serving process — into "re-repair only
what changed":

* :mod:`~delphi_tpu.incremental.manifest` — snapshot manifests persisting
  per-column content fingerprints plus chunked row-block fingerprints
  under ``DELPHI_SNAPSHOT_DIR`` / ``repair.snapshot.dir`` (atomic,
  versioned, mergeable across hosts like run reports).
* :mod:`~delphi_tpu.incremental.planner` — diffs an incoming table against
  the manifest into clean/dirty columns and unchanged/updated/appended
  rows, then expands the dirty row set through the constraint dependency
  graph (:mod:`~delphi_tpu.incremental.depgraph`).
* :mod:`~delphi_tpu.incremental.executor` — threads the plan through the
  existing phases: detection/domain/training re-run only on the planned
  row subset, frozen per-attribute models are reused when the drift gate
  (PSI over the snapshot value histograms) says the attribute hasn't
  moved, and the new per-cell decisions splice into the prior result frame
  and provenance ledger (each spliced cell stamped ``reused`` /
  ``recomputed``).
* :mod:`~delphi_tpu.incremental.stream` — the streaming repair plane:
  chained delta ingestion with a per-stream durable cursor (generational,
  written through the store seam with verified read-back), idempotent
  re-apply, bounded-staleness backpressure, and drift-gated background
  retrains swapped atomically into the snapshot state.

See docs/source/incremental.rst.
"""

from delphi_tpu.incremental.executor import (  # noqa: F401
    incremental_requested, run_incremental, snapshot_dir_for,
)
from delphi_tpu.incremental.manifest import (  # noqa: F401
    MANIFEST_VERSION, build_manifest, load_manifest, load_state,
    merge_manifests, write_snapshot,
)
from delphi_tpu.incremental.planner import DeltaPlan, plan_delta  # noqa: F401
from delphi_tpu.incremental.stream import (  # noqa: F401
    StreamBusy, StreamCommitError, StreamManager, StreamSession,
)
