"""Delta planner: diff an incoming table against a snapshot manifest.

The planner is pure — it looks at the table and the manifest and produces
a :class:`DeltaPlan`; no I/O, no counters, no phase execution. The plan
classifies

* **columns** as clean (every overlap block fingerprint matches) or dirty,
* **rows** as unchanged, updated (inside a differing fingerprint block —
  block granularity, so a one-cell edit replans at most ``block_rows``
  rows per differing block) or appended (past the snapshot's row count),

then expands the dirty row set through the constraint dependency graph
(:mod:`~delphi_tpu.incremental.depgraph`) so every row whose
denial-constraint neighborhood touched a dirty row is re-examined, and
gates per-attribute model reuse on a PSI drift check between the
snapshot's value histograms and the incoming table's.

Anything that breaks the delta contract (schema change, shrunk table,
re-keyed row ids, different option set) surfaces as ``fallback_reason``
and the executor runs the full pipeline instead — incremental mode never
errors where a full run would succeed.
"""

import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from delphi_tpu.constraints import Predicate
from delphi_tpu.incremental.depgraph import expand_dirty_rows
from delphi_tpu.incremental.manifest import (
    fingerprint_values, value_histogram, value_strings,
)
from delphi_tpu.observability.drift import population_stability_index
from delphi_tpu.table import EncodedTable

__all__ = ["DeltaPlan", "plan_delta", "drift_max_setting"]

# PSI above this between the snapshot histogram and the incoming table's
# marks the attribute drifted (0.1 is the folklore "moderate shift" knee;
# see observability/drift.py)
_DEFAULT_DRIFT_MAX = 0.1


def drift_max_setting() -> float:
    """``DELPHI_INCREMENTAL_DRIFT_MAX`` env over the
    ``repair.incremental.drift_max`` session conf (default 0.1)."""
    env = os.environ.get("DELPHI_INCREMENTAL_DRIFT_MAX")
    if env:
        return float(env)
    from delphi_tpu.session import get_session
    conf = get_session().conf.get("repair.incremental.drift_max")
    return float(conf) if conf else _DEFAULT_DRIFT_MAX


@dataclass
class DeltaPlan:
    """What the executor runs: either a usable delta (``fallback_reason``
    is None) or a fall-back-to-full-run verdict."""
    fallback_reason: Optional[str] = None
    clean_columns: List[str] = field(default_factory=list)
    dirty_columns: List[str] = field(default_factory=list)
    rows_unchanged: int = 0
    updated_rows: np.ndarray = field(
        default_factory=lambda: np.empty(0, dtype=np.int64))
    appended_rows: np.ndarray = field(
        default_factory=lambda: np.empty(0, dtype=np.int64))
    expanded_rows: np.ndarray = field(
        default_factory=lambda: np.empty(0, dtype=np.int64))
    planned_rows: np.ndarray = field(
        default_factory=lambda: np.empty(0, dtype=np.int64))
    reusable_attrs: List[str] = field(default_factory=list)
    drifted_attrs: List[str] = field(default_factory=list)
    drift_psi: Dict[str, float] = field(default_factory=dict)

    @property
    def usable(self) -> bool:
        return self.fallback_reason is None

    def summary(self) -> Dict[str, Any]:
        """The run-report / recorder face of the plan."""
        return {
            "fallback_reason": self.fallback_reason,
            "columns_clean": len(self.clean_columns),
            "columns_dirty": len(self.dirty_columns),
            "rows_unchanged": int(self.rows_unchanged),
            "rows_updated": int(len(self.updated_rows)),
            "rows_appended": int(len(self.appended_rows)),
            "rows_expanded": int(len(self.expanded_rows)),
            "rows_planned": int(len(self.planned_rows)),
            "attrs_reusable": list(self.reusable_attrs),
            "attrs_drifted": list(self.drifted_attrs),
            "drift_psi": {k: round(v, 6)
                          for k, v in sorted(self.drift_psi.items())},
        }


def _aligned_hist_counts(cur: Dict[str, Any], base: Dict[str, Any]):
    """Aligns two value_histogram() dicts into parallel count vectors over
    the union of their value keys plus the __other__ / __null__ buckets."""
    keys = sorted(set(cur.get("values", {})) | set(base.get("values", {})))
    c = [float(cur.get("values", {}).get(k, 0)) for k in keys]
    b = [float(base.get("values", {}).get(k, 0)) for k in keys]
    c += [float(cur.get("other", 0)), float(cur.get("null", 0))]
    b += [float(base.get("other", 0)), float(base.get("null", 0))]
    return c, b


def plan_delta(table: EncodedTable, manifest: Optional[Dict[str, Any]],
               constraints: Sequence[Sequence[Predicate]] = (),
               options_digest: str = "",
               drift_max: Optional[float] = None) -> DeltaPlan:
    """Diffs ``table`` against ``manifest`` into a :class:`DeltaPlan`.

    Block fingerprints are recomputed with the MANIFEST's ``block_rows``
    (not the current setting), so a snapshot written under one chunk size
    diffs correctly after the knob changes.
    """
    if manifest is None:
        return DeltaPlan(fallback_reason="no_manifest")
    if manifest["row_id"]["name"] != table.row_id \
            or manifest["row_id"]["kind"] != table.row_id_kind:
        return DeltaPlan(fallback_reason="row_id_mismatch")
    if manifest.get("options_digest", "") != options_digest:
        return DeltaPlan(fallback_reason="options_changed")
    if set(manifest["columns"]) != set(table.column_names):
        return DeltaPlan(fallback_reason="schema_changed")
    n, n0 = table.n_rows, int(manifest["n_rows"])
    if n < n0:
        return DeltaPlan(fallback_reason="rows_removed")
    block = int(manifest["block_rows"])

    # the overlap's row ids must be byte-identical: the splice keys prior
    # per-cell decisions by row id, so a re-keyed table is a new table
    rid_vals = value_strings(table, table.row_id)[:n0]
    _, rid_blocks = fingerprint_values(rid_vals, block)
    if rid_blocks != list(manifest["row_id"]["block_sha1"]):
        return DeltaPlan(fallback_reason="row_ids_changed")

    drift_max = drift_max_setting() if drift_max is None else float(drift_max)
    plan = DeltaPlan()
    updated_mask = np.zeros(n0, dtype=bool)
    for name in table.column_names:
        entry = manifest["columns"][name]
        vals = value_strings(table, name)
        _, blocks = fingerprint_values(vals[:n0], block)
        base_blocks = list(entry["block_sha1"])
        diff = [i for i, (x, y) in enumerate(zip(blocks, base_blocks))
                if x != y]
        if diff:
            plan.dirty_columns.append(name)
            for i in diff:
                updated_mask[i * block:min((i + 1) * block, n0)] = True
        else:
            plan.clean_columns.append(name)
        # drift gate: snapshot histogram vs the incoming table's
        psi = population_stability_index(
            *_aligned_hist_counts(value_histogram(table, name),
                                  entry["histogram"]))
        plan.drift_psi[name] = psi
        if psi > drift_max:
            plan.drifted_attrs.append(name)
        elif not diff:
            plan.reusable_attrs.append(name)

    plan.updated_rows = np.nonzero(updated_mask)[0].astype(np.int64)
    plan.appended_rows = np.arange(n0, n, dtype=np.int64)
    plan.rows_unchanged = int(n0 - len(plan.updated_rows))
    dirty = np.concatenate([plan.updated_rows, plan.appended_rows])
    plan.planned_rows = expand_dirty_rows(table, constraints, dirty)
    plan.expanded_rows = np.setdiff1d(plan.planned_rows, dirty,
                                      assume_unique=False)
    return plan
