"""Delta executor: thread a :class:`~delphi_tpu.incremental.planner.
DeltaPlan` through the existing pipeline phases.

The executor owns the incremental run's lifecycle:

1. resolve the snapshot directory and load the prior manifest + state,
2. plan the delta (:func:`~delphi_tpu.incremental.planner.plan_delta`),
3. run the UNMODIFIED pipeline (``RepairModel._run``) on the planned row
   subset only — detection, domain analysis, and training all see a table
   holding just those rows (``EncodedTable.take_rows``), with frozen
   per-attribute models pre-seeded for every attribute the drift gate
   cleared so those targets skip training entirely,
4. splice the subset's repair candidates into the prior frame (prior rows
   keep their decisions, planned rows get fresh ones) in the exact
   row-major order a from-scratch run emits, and splice the provenance
   ledger the same way (``splice: reused`` / ``recomputed`` per cell),
5. write the updated snapshot for the next delta.

Anything that breaks the delta contract falls back to a full run with a
one-time warning and an ``incremental.fallback`` counter — incremental
mode never errors where a full run would succeed. The full run then
populates a fresh snapshot, so the NEXT invocation rides the delta.
"""

import os
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np
import pandas as pd

from delphi_tpu.incremental import manifest as mf
from delphi_tpu.incremental.planner import DeltaPlan, plan_delta
from delphi_tpu.observability import counter_inc
from delphi_tpu.observability.provenance import active_ledger
from delphi_tpu.observability.spans import current_recorder
from delphi_tpu.parallel.resilience import fingerprint_digest
from delphi_tpu.table import EncodedTable
from delphi_tpu.utils import setup_logger

_logger = setup_logger()

_TRUTHY = frozenset({"1", "true", "yes", "on"})

# one warning per (directory, reason) per process: a serving loop hitting
# the same unusable snapshot on every request must not spam the log
_warned: set = set()

# option keys that must NOT enter the snapshot's options digest: they
# configure the snapshot machinery itself (or point at relocatable paths),
# so flipping them cannot invalidate prior repair decisions
_DIGEST_EXCLUDED_OPTS = frozenset({
    "model.checkpoint_path", "repair.snapshot.dir", "repair.incremental"})


def incremental_requested(model: Any) -> bool:
    """Whether this run should try the delta path. Per-model option
    ``repair.incremental`` wins (serve sets it per request, so concurrent
    requests never race an env flip), then the ``DELPHI_INCREMENTAL`` env,
    then the ``repair.incremental`` session conf."""
    if model._opt_incremental.key in model.opts:
        return bool(model._get_option_value(*model._opt_incremental))
    env = os.environ.get("DELPHI_INCREMENTAL")
    if env is not None:
        return env.strip().lower() in _TRUTHY
    from delphi_tpu.session import get_session
    conf = get_session().conf.get("repair.incremental")
    if conf is not None:
        return str(conf).strip().lower() in _TRUTHY
    return False


def snapshot_dir_for(model: Any) -> Optional[str]:
    """The snapshot directory, same precedence as
    :func:`incremental_requested`: model option ``repair.snapshot.dir``,
    then ``DELPHI_SNAPSHOT_DIR``, then the session conf."""
    if model._opt_snapshot_dir.key in model.opts:
        v = str(model._get_option_value(*model._opt_snapshot_dir)).strip()
        return v or None
    env = os.environ.get("DELPHI_SNAPSHOT_DIR")
    if env is not None and env.strip():
        return env.strip()
    from delphi_tpu.session import get_session
    conf = get_session().conf.get("repair.snapshot.dir")
    return str(conf).strip() if conf and str(conf).strip() else None


def options_digest(model: Any) -> str:
    """Identity of everything that shapes repair decisions BESIDES the
    table content: expert options, targets, detectors, setter knobs. A
    snapshot written under different options must not feed a delta run."""
    return fingerprint_digest({
        "version": 1,
        "opts": {k: v for k, v in sorted(model.opts.items())
                 if k not in _DIGEST_EXCLUDED_OPTS},
        "row_id": model.row_id,
        "targets": sorted(model.targets),
        "detectors": sorted(str(d) for d in model.error_detectors),
        "discrete_thres": int(model.discrete_thres),
        "repair_by_rules": bool(model.repair_by_rules),
        "rebalancing": bool(model.training_data_rebalancing_enabled),
    })


def _parsed_constraints(model: Any, table: EncodedTable,
                        input_name: str) -> List[Sequence[Any]]:
    """Every detector's denial-constraint predicate lists, for dirty-set
    expansion. Detectors without constraints contribute nothing (their
    checks are row-local, so no expansion is needed)."""
    from delphi_tpu.errors import ConstraintErrorDetector
    preds: List[Sequence[Any]] = []
    for d in model.error_detectors:
        if isinstance(d, ConstraintErrorDetector):
            preds.extend(d.parsed_constraints(table, input_name).predicates)
    return preds


def _warn_once(directory: str, reason: str) -> None:
    counter_inc("incremental.fallback")
    key = (directory, reason)
    if key not in _warned:
        _warned.add(key)
        _logger.warning(
            f"Incremental repair requested but falling back to a full run "
            f"({reason}; snapshot dir: {directory or '<unset>'}). The full "
            f"run repopulates the snapshot, so the next run rides the "
            f"delta. This warning is emitted once per process.")


def _empty_candidates(model: Any) -> pd.DataFrame:
    return pd.DataFrame(
        columns=[model.row_id, "attribute", "current_value", "repaired"])


def _splice_frames(model: Any, table: EncodedTable,
                   prior: Optional[pd.DataFrame], fresh: pd.DataFrame,
                   planned_rids: set, rid_strs: List[str]) -> pd.DataFrame:
    """Prior rows outside the plan + fresh rows, in the row-major order
    (global row position, then attribute column rank) a from-scratch run's
    ``_extract_repair_candidates`` emits — so a clean-append delta frame is
    bit-identical to the full run's."""
    cols = [model.row_id, "attribute", "current_value", "repaired"]
    if prior is None or not len(prior):
        prior = _empty_candidates(model)
    if "repaired" not in fresh.columns:
        # the subset was already clean: _run_impl's early return carries no
        # repaired column, and there is nothing to splice from it
        fresh = _empty_candidates(model)
    keep = ~prior[model.row_id].astype(str).isin(planned_rids)
    combined = pd.concat([prior[keep][cols], fresh[cols]],
                         ignore_index=True)
    if not len(combined):
        return combined
    pos_of = {rid: i for i, rid in enumerate(rid_strs)}
    gpos = combined[model.row_id].astype(str).map(pos_of).fillna(-1)
    rank_of = {name: i for i, name in enumerate(table.column_names)}
    ranks = combined["attribute"].map(rank_of).fillna(-1).astype(np.int64)
    order = np.lexsort((ranks.to_numpy(),
                        gpos.to_numpy(dtype=np.int64)))
    return combined.iloc[order].reset_index(drop=True)


def _save_snapshot(model: Any, table: EncodedTable, directory: str,
                   digest: str, frame: pd.DataFrame,
                   models: Optional[Any],
                   ledger_entries: Optional[List[Dict[str, Any]]]
                   ) -> Optional[str]:
    """Returns the written snapshot id — the chain head a streaming
    client's next delta must cite as its parent — or None when
    persistence failed (best-effort, never fails the run)."""
    try:
        manifest = mf.build_manifest(table, options_digest=digest)
        state = {
            "frame": frame,
            "models": dict(models) if models else {},
            "ledger_entries": ledger_entries,
        }
        mf.write_snapshot(directory, manifest, state)
        counter_inc("incremental.snapshots_written")
        return manifest.get("snapshot_id")
    except Exception as e:
        # snapshot persistence must never fail the run that produced it
        _logger.warning(f"Failed to write snapshot to {directory}: {e}")
        return None


def run_incremental(model: Any, table: EncodedTable, input_name: str,
                    continuous_columns: List[str],
                    run_flags: Tuple[bool, ...]) \
        -> Tuple[pd.DataFrame, float, Dict[str, Any]]:
    """The incremental entry point ``RepairModel._run_checked`` dispatches
    to. Returns ``(frame, elapsed_s, summary)`` exactly where ``_run``
    returns ``(frame, elapsed_s)``; the summary lands in the run info and
    the run report's ``incremental`` section."""
    started = time.monotonic()
    directory = snapshot_dir_for(model) or ""
    digest = options_digest(model)

    def fallback(reason: str) -> Tuple[pd.DataFrame, float, Dict[str, Any]]:
        _warn_once(directory, reason)
        df, elapsed = model._run(table, input_name, continuous_columns,
                                 *run_flags)
        plain_mode = not any(run_flags)
        snapshot_id = None
        if directory and plain_mode and not table.process_local:
            led = active_ledger()
            snapshot_id = _save_snapshot(
                model, table, directory, digest, df,
                getattr(model, "_last_models", None),
                led.entries() if led is not None else None)
        summary = {"mode": "full", "fallback_reason": reason,
                   "snapshot_dir": directory or None,
                   "snapshot_id": snapshot_id}
        _publish(summary)
        return df, elapsed, summary

    if not directory:
        return fallback("no_snapshot_dir")
    # the delta path covers the plain repair-candidates mode only: the
    # other run modes (PMF/score/ML/detect-only/full-frame) don't produce
    # the row-spliceable candidates frame the snapshot stores
    if any(run_flags):
        return fallback("unsupported_run_mode")
    if model.repair_by_rules or model.repair_validation_enabled \
            or model.error_cells is not None:
        return fallback("unsupported_options")
    if table.process_local:
        return fallback("process_local_table")

    rid_strs = mf.value_strings(table, table.row_id)
    if len(set(rid_strs)) != table.n_rows:
        # the splice keys prior decisions by row id — duplicates would
        # silently cross-wire rows (same class of failure as a missing
        # row-id column)
        return fallback("row_id_not_unique")

    manifest = mf.load_manifest(directory)
    state = mf.load_state(directory) if manifest is not None else None
    if manifest is not None and state is None:
        return fallback("snapshot_state_missing")
    if state is not None and not isinstance(state.get("frame"),
                                            pd.DataFrame):
        return fallback("snapshot_state_invalid")

    try:
        constraints = _parsed_constraints(model, table, input_name)
    except Exception as e:
        _logger.warning(f"Constraint parsing failed during delta "
                        f"planning: {e}")
        return fallback("constraint_parse_failed")

    plan = plan_delta(table, manifest, constraints, options_digest=digest)
    if not plan.usable:
        return fallback(plan.fallback_reason or "unusable_plan")

    prior_frame: pd.DataFrame = state["frame"]
    prior_models: Dict[str, Any] = state.get("models") or {}
    prior_entries = state.get("ledger_entries") or []
    planned = plan.planned_rows
    planned_rids = {rid_strs[int(p)] for p in planned}
    frozen = {y: m for y, m in prior_models.items()
              if y in plan.reusable_attrs}

    summary = plan.summary()
    summary.update({"mode": "delta", "snapshot_dir": directory,
                    "base_snapshot": manifest.get("snapshot_id")})

    if not len(planned):
        # nothing changed: the prior frame IS the answer
        df = prior_frame.copy().reset_index(drop=True)
        led = active_ledger()
        reused, recomputed = (len(prior_entries), 0)
        if led is not None:
            reused, recomputed = led.splice_prior_entries(prior_entries)
        _count(plan, models_reused=0, models_retrained=0,
               cells_reused=reused, cells_recomputed=recomputed)
        summary.update({"models_reused": 0, "models_retrained": 0,
                        "cells_spliced_reused": reused,
                        "cells_recomputed": recomputed,
                        # snapshot untouched: the prior head stays the
                        # chain head a streaming client must cite
                        "snapshot_id": manifest.get("snapshot_id")})
        _publish(summary)
        return df, time.monotonic() - started, summary

    sub = table.take_rows(planned)
    model._incremental_frozen_models = frozen
    try:
        fresh_df, _ = model._run(sub, input_name, continuous_columns,
                                 *run_flags)
    finally:
        model._incremental_frozen_models = None

    sub_models = dict(getattr(model, "_last_models", None) or [])
    models_reused = sorted(set(frozen) & set(sub_models))
    models_retrained = sorted(set(sub_models) - set(frozen))

    df = _splice_frames(model, table, prior_frame, fresh_df,
                        planned_rids, rid_strs)

    led = active_ledger()
    reusable_entries = [e for e in prior_entries
                        if str(e.get("row_id")) not in planned_rids]
    reused, recomputed = (len(reusable_entries), 0)
    if led is not None:
        reused, recomputed = led.splice_prior_entries(reusable_entries)
    merged_entries = led.entries() if led is not None \
        else reusable_entries

    # next delta's baseline: spliced frame, frozen+retrained models,
    # spliced ledger — over the CURRENT table's manifest
    merged_models = dict(prior_models)
    merged_models.update(sub_models)
    snapshot_id = _save_snapshot(model, table, directory, digest, df,
                                 merged_models, merged_entries)

    _count(plan, models_reused=len(models_reused),
           models_retrained=len(models_retrained),
           cells_reused=reused, cells_recomputed=recomputed)
    summary.update({"snapshot_id": snapshot_id,
                    "models_reused": len(models_reused),
                    "models_retrained": len(models_retrained),
                    "models_reused_attrs": models_reused,
                    "cells_spliced_reused": reused,
                    "cells_recomputed": recomputed})
    _publish(summary)
    elapsed = time.monotonic() - started
    _logger.info(
        f"Incremental repair: {len(planned)}/{table.n_rows} rows "
        f"replanned ({len(plan.updated_rows)} updated, "
        f"{len(plan.appended_rows)} appended, {len(plan.expanded_rows)} "
        f"pulled in by constraints), {len(models_reused)} models reused, "
        f"{len(models_retrained)} retrained, {reused} cells spliced from "
        f"the prior run in {elapsed:.3f}s")
    return df, elapsed, summary


def _count(plan: DeltaPlan, models_reused: int, models_retrained: int,
           cells_reused: int, cells_recomputed: int) -> None:
    counter_inc("incremental.runs")
    counter_inc("incremental.columns_reused", len(plan.clean_columns))
    counter_inc("incremental.columns_dirty", len(plan.dirty_columns))
    counter_inc("incremental.rows_unchanged", int(plan.rows_unchanged))
    counter_inc("incremental.rows_updated", int(len(plan.updated_rows)))
    counter_inc("incremental.rows_appended", int(len(plan.appended_rows)))
    counter_inc("incremental.rows_replanned", int(len(plan.planned_rows)))
    counter_inc("incremental.models_reused", models_reused)
    counter_inc("incremental.models_retrained", models_retrained)
    counter_inc("incremental.cells_spliced_reused", cells_reused)
    counter_inc("incremental.cells_recomputed", cells_recomputed)


def _publish(summary: Dict[str, Any]) -> None:
    """Lands the delta summary on the active run recorder so the run
    report's ``incremental`` section (report.py, schema v4) carries it."""
    rec = current_recorder()
    if rec is not None:
        rec.incremental = summary
