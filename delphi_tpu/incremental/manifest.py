"""Snapshot manifests: content identity of an encoded table, persisted.

A snapshot directory holds two files:

* ``manifest.json`` — versioned, JSON, atomic: per-column whole-content
  fingerprints, chunked row-block fingerprints (``block_rows`` rows per
  block), capped per-column value histograms (the drift gate's baseline),
  and the row-id column's fingerprints. Everything the delta planner needs
  to diff an incoming table WITHOUT touching the prior data.
* ``state.pkl`` — pickle, atomic: the prior repair-candidates frame, the
  trained per-attribute models, and the provenance ledger entries the
  executor splices reused cells from. Same trust boundary as the model /
  phase checkpoints (plain pickles — point the directory only at files
  this process wrote).

Fingerprints hash the DECODED value strings (vocab spellings), so they are
invariant under column reorder (columns key by name), vocab permutation
(two encodings of the same data always agree), and block-size changes (the
whole-column fingerprint never looks at block boundaries).
"""

import hashlib
import os
import re
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from delphi_tpu.table import EncodedTable
from delphi_tpu.utils import setup_logger

_logger = setup_logger()

MANIFEST_VERSION = 1
MANIFEST_FILE = "manifest.json"
STATE_FILE = "state.pkl"

# rows per fingerprint block: DELPHI_SNAPSHOT_BLOCK_ROWS /
# repair.snapshot.block_rows (block granularity of the updated-row diff)
_DEFAULT_BLOCK_ROWS = 4096
# value histograms keep the top-K values by count; the tail folds into
# "__other__" so a manifest never grows with the domain
_HISTOGRAM_TOP_K = 64

_NULL_SENTINEL = "\x00NULL"
_SEP = "\x1f"


def block_rows_setting() -> int:
    """``DELPHI_SNAPSHOT_BLOCK_ROWS`` env over the
    ``repair.snapshot.block_rows`` session conf (default 4096)."""
    env = os.environ.get("DELPHI_SNAPSHOT_BLOCK_ROWS")
    if env:
        return max(1, int(env))
    from delphi_tpu.session import get_session
    conf = get_session().conf.get("repair.snapshot.block_rows")
    return max(1, int(conf)) if conf else _DEFAULT_BLOCK_ROWS


def _sha1(text: str) -> str:
    return hashlib.sha1(text.encode("utf-8", "replace")).hexdigest()


def value_strings(table: EncodedTable, name: str) -> List[str]:
    """Per-row canonical value spellings of one column (NULL -> sentinel):
    the byte stream both the whole-column and the block fingerprints hash."""
    if name == table.row_id:
        return [str(v) for v in table.row_id_values.tolist()]
    decoded = table.column(name).decode()
    return [_NULL_SENTINEL if v is None else str(v) for v in decoded.tolist()]


def fingerprint_values(values: List[str], block: int) -> Tuple[str, List[str]]:
    """(whole-column sha1, per-block sha1 list). The whole fingerprint hashes
    the full value stream directly — block boundaries never enter it — so it
    is stable across ``block_rows`` settings; a block fingerprint depends
    only on its own rows."""
    whole = _sha1(_SEP.join(values))
    blocks = [_sha1(_SEP.join(values[lo:lo + block]))
              for lo in range(0, len(values), block)]
    return whole, blocks


def value_histogram(table: EncodedTable, name: str) -> Dict[str, Any]:
    """Capped value-count histogram of one column — the snapshot-side
    baseline the planner's PSI drift gate compares future runs against."""
    col = table.column(name)
    codes = col.codes
    valid = codes >= 0
    counts = np.bincount(codes[valid], minlength=len(col.vocab)) \
        if valid.any() else np.zeros(len(col.vocab), dtype=np.int64)
    # deterministic top-K: count desc, then spelling asc
    order = sorted(range(len(counts)), key=lambda i: (-int(counts[i]),
                                                      str(col.vocab[i])))
    top = [i for i in order[:_HISTOGRAM_TOP_K] if counts[i] > 0]
    values = {str(col.vocab[i]): int(counts[i]) for i in top}
    other = int(counts.sum()) - sum(values.values())
    return {"values": values, "other": other,
            "null": int((~valid).sum())}


def build_manifest(table: EncodedTable, options_digest: str = "",
                   mode: str = "repair_candidates",
                   block: Optional[int] = None) -> Dict[str, Any]:
    """Builds the manifest dict for an encoded table (no I/O)."""
    from delphi_tpu.parallel.resilience import fingerprint_digest
    block = block or block_rows_setting()
    rid_values = value_strings(table, table.row_id)
    rid_whole, rid_blocks = fingerprint_values(rid_values, block)
    columns: Dict[str, Any] = {}
    for c in table.columns:
        vals = value_strings(table, c.name)
        whole, blocks = fingerprint_values(vals, block)
        columns[c.name] = {
            "kind": c.kind,
            "value_sha1": whole,
            "block_sha1": blocks,
            "histogram": value_histogram(table, c.name),
        }
    manifest = {
        "version": MANIFEST_VERSION,
        "row_id": {"name": table.row_id, "kind": table.row_id_kind,
                   "value_sha1": rid_whole, "block_sha1": rid_blocks},
        "n_rows": int(table.n_rows),
        "block_rows": int(block),
        "columns": columns,
        "options_digest": options_digest,
        "mode": mode,
        "merged": False,
    }
    manifest["snapshot_id"] = fingerprint_digest(manifest)[:16]
    return manifest


def merge_manifests(a: Dict[str, Any], b: Dict[str, Any]) -> Dict[str, Any]:
    """Merges two ROW-SHARDED manifests of one logical table (multi-host:
    each process snapshots its shard, ranks merge like run reports). Block
    fingerprint lists concatenate in argument order and histograms sum; the
    whole-column fingerprints combine as a hash chain, so a merged manifest
    supports block-level diffing and drift gating but not the whole-column
    short-circuit (``merged`` is set and the planner knows)."""
    if a.get("version") != b.get("version") \
            or a.get("block_rows") != b.get("block_rows") \
            or a["row_id"]["name"] != b["row_id"]["name"] \
            or set(a["columns"]) != set(b["columns"]):
        raise ValueError("manifests are not shards of one table "
                         "(version/block_rows/row_id/columns differ)")
    from delphi_tpu.parallel.resilience import fingerprint_digest
    out: Dict[str, Any] = {
        "version": a["version"],
        "row_id": {
            "name": a["row_id"]["name"], "kind": a["row_id"]["kind"],
            "value_sha1": _sha1(a["row_id"]["value_sha1"]
                                + b["row_id"]["value_sha1"]),
            "block_sha1": list(a["row_id"]["block_sha1"])
            + list(b["row_id"]["block_sha1"]),
        },
        "n_rows": int(a["n_rows"]) + int(b["n_rows"]),
        "block_rows": a["block_rows"],
        "columns": {},
        "options_digest": a.get("options_digest", ""),
        "mode": a.get("mode", "repair_candidates"),
        "merged": True,
    }
    for name, ca in a["columns"].items():
        cb = b["columns"][name]
        hist = {"values": dict(ca["histogram"]["values"]),
                "other": int(ca["histogram"]["other"])
                + int(cb["histogram"]["other"]),
                "null": int(ca["histogram"]["null"])
                + int(cb["histogram"]["null"])}
        for v, n in cb["histogram"]["values"].items():
            hist["values"][v] = hist["values"].get(v, 0) + int(n)
        out["columns"][name] = {
            "kind": ca["kind"],
            "value_sha1": _sha1(ca["value_sha1"] + cb["value_sha1"]),
            "block_sha1": list(ca["block_sha1"]) + list(cb["block_sha1"]),
            "histogram": hist,
        }
    out["snapshot_id"] = fingerprint_digest(out)[:16]
    return out


# -- persistence --------------------------------------------------------------
#
# All snapshot I/O rides the durable-store seam (parallel/store.py):
# envelope-framed crash-consistent writes at sites ``store.manifest`` /
# ``store.snapshot_state``, with corrupt/truncated files quarantined as
# misses (the caller falls back to a full run, which repopulates).

#: archived chain manifests: ``manifest.<snapshot_id>.json``
_CHAIN_RE = re.compile(r"^manifest\.([0-9a-f]{16})\.json$")

#: default chain length retained at write time (DELPHI_SNAPSHOT_CHAIN_KEEP)
_DEFAULT_CHAIN_KEEP = 4


def chain_keep_setting() -> int:
    """``DELPHI_SNAPSHOT_CHAIN_KEEP``: how many superseded manifests the
    delta chain retains after each snapshot write (default 4). The quota
    GC sweep and fsck compact harder — down to the single live base — so
    delta serving stays O(1) on disk regardless of run count."""
    env = os.environ.get("DELPHI_SNAPSHOT_CHAIN_KEEP")
    try:
        return max(0, int(env)) if env and env.strip() else \
            _DEFAULT_CHAIN_KEEP
    except ValueError:
        return _DEFAULT_CHAIN_KEEP


def chain_files(directory: str) -> List[str]:
    """Archived chain manifests, oldest first (by mtime, name-tiebroken)."""
    try:
        names = [n for n in os.listdir(directory) if _CHAIN_RE.match(n)]
    except OSError:
        return []
    paths = [os.path.join(directory, n) for n in names]

    def key(p: str):
        try:
            return (os.path.getmtime(p), p)
        except OSError:
            return (0.0, p)
    return sorted(paths, key=key)


def compact_chain(directory: str, keep: Optional[int] = None) -> int:
    """Folds a snapshot's manifest chain down to ``keep`` archived entries
    (default: the env setting) plus the live base ``manifest.json``.
    Returns the number of chain files removed."""
    keep = chain_keep_setting() if keep is None else max(0, int(keep))
    files = chain_files(directory)
    removed = 0
    for path in files[:max(0, len(files) - keep)]:
        try:
            os.unlink(path)
            removed += 1
        except OSError:
            pass
    if removed:
        from delphi_tpu.observability import counter_inc
        counter_inc("store.chain_compacted", removed)
        _logger.info(f"Compacted snapshot manifest chain in {directory}: "
                     f"removed {removed} superseded manifests "
                     f"(keeping {keep})")
    return removed


def write_snapshot(directory: str, manifest: Dict[str, Any],
                   state: Dict[str, Any]) -> None:
    """Persists a snapshot crash-consistently: the state pickle lands
    before the manifest, so a reader never sees a manifest pointing at a
    half-written state (a kill between the two leaves the PREVIOUS
    snapshot's manifest paired with the new state — detected by the
    fingerprint diff, which falls back to a full run). A superseded
    manifest is archived into the delta chain
    (``manifest.<snapshot_id>.json``) and the chain is compacted to
    ``DELPHI_SNAPSHOT_CHAIN_KEEP`` entries."""
    from delphi_tpu.parallel import store as dstore
    os.makedirs(directory, exist_ok=True)
    dstore.write_pickle(os.path.join(directory, STATE_FILE), state,
                        schema="snapshot_state",
                        site="store.snapshot_state", root=directory)
    live = os.path.join(directory, MANIFEST_FILE)
    prior = load_manifest(directory)
    if prior is not None and prior.get("snapshot_id") \
            and prior.get("snapshot_id") != manifest.get("snapshot_id"):
        archived = os.path.join(
            directory, f"manifest.{prior['snapshot_id']}.json")
        try:
            dstore.replace_file(live, archived)
            manifest = dict(manifest)
            manifest["parent_snapshot_id"] = prior["snapshot_id"]
        except OSError as e:
            _logger.warning(f"could not archive superseded manifest "
                            f"{live}: {e}")
    dstore.write_json(live, manifest, schema="snapshot_manifest",
                      site="store.manifest", root=directory, indent=1)
    compact_chain(directory)
    _logger.info(f"Snapshot {manifest.get('snapshot_id')} written to "
                 f"{directory} ({manifest.get('n_rows')} rows)")


def load_manifest(directory: str) -> Optional[Dict[str, Any]]:
    """Loads a manifest, or None when missing/corrupt/unknown-version (the
    caller falls back to a full run either way). A corrupt file is
    quarantined by the store seam, never silently loaded."""
    from delphi_tpu.parallel import store as dstore
    path = os.path.join(directory, MANIFEST_FILE)
    manifest, status = dstore.read_json(
        path, schema="snapshot_manifest", site="store.manifest",
        root=directory)
    if status in ("missing", "corrupt"):
        return None
    if not isinstance(manifest, dict) \
            or manifest.get("version") != MANIFEST_VERSION:
        _logger.warning(f"Ignoring snapshot manifest {path}: "
                        "unknown version")
        return None
    return manifest


def load_state(directory: str) -> Optional[Dict[str, Any]]:
    """Loads the state pickle (prior frame / models / ledger entries), or
    None when missing or unreadable (corrupt pickles are quarantined)."""
    from delphi_tpu.parallel import store as dstore
    state, status = dstore.read_pickle(
        os.path.join(directory, STATE_FILE), schema="snapshot_state",
        site="store.snapshot_state", root=directory)
    if status in ("missing", "corrupt"):
        return None
    return state if isinstance(state, dict) else None
