"""Constraint dependency expansion for the delta planner.

A denial constraint couples rows: re-examining only the changed rows would
miss violations a changed row introduces (or resolves) against unchanged
partners. For every two-tuple constraint, rows sharing the constraint's
full cross-tuple EQ key form an equivalence class — two rows can only
violate the constraint together when every cross-tuple EQ predicate holds,
i.e. when they agree on ALL key attributes (the same grouping the
violation kernel in :mod:`delphi_tpu.ops.detect` exploits). So the dirty
neighborhood of a changed row is exactly its EQ-key group, per constraint:
any group containing a dirty row is pulled into the plan wholesale, and
groups with no dirty member keep their prior decisions.

Rows carrying a NULL in a key attribute never satisfy the EQ predicates,
so they pair with nobody and are not pulled in through that constraint.
Constraints with no usable EQ key (no cross-tuple EQ predicate, or an
asymmetric ``EQ(t1.a, t2.b)``) couple arbitrary row pairs; they expand to
every row — the conservative answer that keeps the plan correct.
"""

from typing import List, Sequence

import numpy as np

from delphi_tpu.constraints import Predicate
from delphi_tpu.table import EncodedTable

__all__ = ["expand_dirty_rows", "constraint_eq_keys"]


def constraint_eq_keys(preds: Sequence[Predicate]) -> List[str]:
    """The cross-tuple EQ key attributes of one constraint, or an empty
    list when the constraint has no row-grouping key (one-tuple, no
    cross-tuple EQ, or asymmetric EQ)."""
    if all(not p.is_cross_tuple for p in preds):
        return []  # one-tuple: row-local, expansion not needed
    keys: List[str] = []
    for p in preds:
        if not p.is_cross_tuple or p.sign != "EQ":
            continue
        if str(p.left) != str(p.right):
            return []  # asymmetric EQ: not an equivalence relation
        if str(p.left) not in keys:
            keys.append(str(p.left))
    return keys


def expand_dirty_rows(table: EncodedTable,
                      constraints: Sequence[Sequence[Predicate]],
                      dirty_rows: np.ndarray) -> np.ndarray:
    """Expands a dirty row-position set through the constraint graph.

    Returns the sorted union of ``dirty_rows`` and every row sharing a full
    cross-tuple EQ key with a dirty row under any constraint. The expansion
    is one pass (groups are equivalence classes per constraint, so pulled
    rows cannot pull further rows through the SAME constraint; a pulled row
    is itself re-examined, not re-written, so cross-constraint chaining is
    not needed for plan correctness)."""
    dirty_rows = np.asarray(dirty_rows, dtype=np.int64)
    if not len(dirty_rows) or not constraints:
        return np.unique(dirty_rows)
    n = table.n_rows
    planned = np.zeros(n, dtype=bool)
    planned[dirty_rows] = True

    for preds in constraints:
        two_tuple = any(p.is_cross_tuple for p in preds)
        if not two_tuple:
            continue
        keys = constraint_eq_keys(preds)
        if not keys:
            # no usable grouping key: the constraint couples arbitrary row
            # pairs, so any dirty row taints every row
            planned[:] = True
            break
        keys = [k for k in keys if table.has_column(k)]
        if not keys:
            continue
        key_codes = table.codes(keys)
        groupable = (key_codes >= 0).all(axis=1)
        if not groupable.any():
            continue
        _, inverse = np.unique(key_codes[groupable], axis=0,
                               return_inverse=True)
        group_of = np.full(n, -1, dtype=np.int64)
        group_of[np.nonzero(groupable)[0]] = inverse
        dirty_groups = np.unique(group_of[planned & groupable])
        if len(dirty_groups):
            planned |= groupable & np.isin(group_of, dirty_groups)

    return np.nonzero(planned)[0].astype(np.int64)
