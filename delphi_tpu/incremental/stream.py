"""Streaming repair plane: crash-exact continuous delta ingestion.

A :class:`StreamSession` sustains a *chain* of repair requests: a client
streams appended/updated partitions, each request carries ``(stream id,
seq, parent snapshot id)`` where ``parent`` is the previous response's
snapshot id, and the server accumulates the concatenated table and runs
the incremental executor over it against a per-stream snapshot
directory. The invariant the whole plane defends: after delta N the
stream's end-state (repair frame + spliced provenance) is bit-identical
to ONE batch run over the concatenation of deltas 1..N — streaming is an
execution strategy, never a different answer.

**Durable cursor.** Every committed delta writes, through the durable-
store seam (:mod:`delphi_tpu.parallel.store`), a *new generation* of two
files under the stream directory::

    table.<seq>.pkl     the accumulated input table   (site store.stream_state)
    cursor.<seq>.json   the commit record             (site store.stream_cursor)

in that order, with a validated read-back after each write. Generations
never overwrite each other, so a torn write of generation N (the store's
``torn_write`` fault truncates the destination in place with the writer
believing success) can never destroy generation N-1 — and the read-back
converts believed-success into detected-failure *before* the delta is
acknowledged: the write is retried once (the quarantine of the torn file
makes room), and if it still cannot be verified the delta fails with the
last durable cursor echoed so the client resends. An acknowledged delta
is therefore durable by construction. The snapshot directory itself
(manifest + state) is a pure cache: if a crash tears it, the next delta
falls back to a full run over the durable accumulated table
(``incremental.fallback``) and repopulates it — same end state.

**Idempotent re-apply.** ``seq`` must be exactly ``cursor.seq + 1``. A
re-sent delta (``seq <= cursor.seq``) with matching content digest is
acknowledged as a duplicate with the current cursor (the at-least-once
retry loop after a worker death or router re-dispatch); a same-``seq``
digest mismatch, a gap, or a ``parent`` that does not match the durable
head are 409 conflicts carrying the cursor so the client can resync.

**Recovery.** A session constructed over a directory that already holds
a durable cursor (worker restart, or a fleet survivor inheriting the
chain through the shared cache root) scans cursor generations newest-
first, quarantining corrupt ones, and resumes at the newest generation
whose cursor AND table both validate. The session reports
``recovering=True`` (surfaced as ``/healthz`` degraded) until the first
post-recovery delta commits.

**Backpressure.** :class:`StreamManager` bounds in-flight deltas per
stream (``DELPHI_STREAM_MAX_INFLIGHT``); past the bound admission
answers 429 with the durable cursor echoed, and the ``stream.lag_rows``
gauge exposes rows admitted but not yet durably repaired — the
bounded-staleness signal.

**Drift-gated background retrain.** Per-attribute value histograms are
baselined at model-training time (not per step — a slow drift moves each
step's histogram only slightly, so the per-delta PSI gate in the planner
keeps reusing frozen models and the stream never blocks). When the PSI
of the accumulated table against the *training-time* baseline crosses
``DELPHI_STREAM_DRIFT_MAX``, a replacement model trains off-thread over
a copy of the accumulated table and is atomically swapped into the
snapshot state through the store seam under the session lock
(``stream.retrain.swaps``); baselines refresh at the swap, so the
trigger re-arms only on the next real drift.

Retention: cursor/table generations are pruned to
``DELPHI_STREAM_KEEP`` after each commit, and the snapshot chain rides
the existing ``DELPHI_SNAPSHOT_CHAIN_KEEP`` compaction + store quota GC.
"""

import hashlib
import os
import re
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import pandas as pd

from delphi_tpu.incremental import manifest as mf
from delphi_tpu.incremental.planner import (
    _aligned_hist_counts, drift_max_setting,
)
from delphi_tpu.observability import counter_inc, gauge_set
from delphi_tpu.observability.drift import population_stability_index
from delphi_tpu.parallel import store as dstore
from delphi_tpu.utils import setup_logger

_logger = setup_logger()

__all__ = [
    "StreamBusy", "StreamCommitError", "StreamManager", "StreamSession",
    "keep_setting", "max_inflight_setting", "stream_drift_max_setting",
    "validate_stream_id",
]

_DEF_MAX_INFLIGHT = 2
_DEF_KEEP = 2

_CURSOR_RE = re.compile(r"^cursor\.(\d{8})\.json$")
_TABLE_FMT = "table.{seq:08d}.pkl"
_CURSOR_FMT = "cursor.{seq:08d}.json"

#: extra write attempt after a failed read-back before giving up — one
#: retry absorbs a single torn write (the quarantine clears the debris)
_COMMIT_ATTEMPTS = 2


def max_inflight_setting() -> int:
    """``DELPHI_STREAM_MAX_INFLIGHT`` env over the
    ``repair.stream.max_inflight`` session conf (default 2): deltas a
    single stream may have admitted-but-uncommitted before admission
    answers 429 + cursor echo."""
    env = os.environ.get("DELPHI_STREAM_MAX_INFLIGHT")
    if env:
        return max(1, int(env))
    from delphi_tpu.session import get_session
    conf = get_session().conf.get("repair.stream.max_inflight")
    return max(1, int(conf)) if conf else _DEF_MAX_INFLIGHT


def keep_setting() -> int:
    """``DELPHI_STREAM_KEEP`` env over the ``repair.stream.keep`` session
    conf (default 2): cursor/table generations retained per stream. The
    floor is 2 — one generation of headroom is what makes a torn write of
    the newest generation recoverable."""
    env = os.environ.get("DELPHI_STREAM_KEEP")
    if env:
        return max(2, int(env))
    from delphi_tpu.session import get_session
    conf = get_session().conf.get("repair.stream.keep")
    return max(2, int(conf)) if conf else _DEF_KEEP


def stream_drift_max_setting() -> float:
    """``DELPHI_STREAM_DRIFT_MAX`` env over the
    ``repair.stream.drift_max`` session conf; defaults to the
    incremental planner's drift knee. This gate compares against the
    *training-time* baseline, so it accumulates drift the planner's
    step-over-step gate cannot see."""
    env = os.environ.get("DELPHI_STREAM_DRIFT_MAX")
    if env:
        return float(env)
    from delphi_tpu.session import get_session
    conf = get_session().conf.get("repair.stream.drift_max")
    return float(conf) if conf else drift_max_setting()


def validate_stream_id(stream_id: Any) -> str:
    """Same filename-safe alphabet as serve's ``base_snapshot`` ids: a
    request body must never be able to escape the streams root."""
    sid = str(stream_id or "")
    if not sid or len(sid) > 64 \
            or not all(c.isalnum() or c in "._-" for c in sid) \
            or sid.startswith("."):
        raise ValueError(
            f"bad stream id {stream_id!r}: expected 1-64 chars from "
            "[A-Za-z0-9._-], not starting with '.'")
    return sid


def delta_digest(delta: pd.DataFrame) -> str:
    """Content digest of one delta partition — the idempotency key a
    re-sent delta is matched on."""
    blob = delta.to_json(orient="split", default_handler=str)
    return hashlib.sha1(blob.encode()).hexdigest()


class StreamBusy(Exception):
    """Per-stream backpressure refusal (HTTP 429): the stream already has
    ``max_inflight`` admitted-but-uncommitted deltas. Carries the durable
    cursor so the client knows exactly where to resume."""

    def __init__(self, stream_id: str, cursor: Optional[Dict[str, Any]],
                 retry_after_s: float = 1.0) -> None:
        self.stream_id = stream_id
        self.cursor = cursor
        self.retry_after_s = retry_after_s
        super().__init__(
            f"stream {stream_id}: in-flight delta bound reached")


class StreamCommitError(Exception):
    """A commit write could not be verified even after retry — the delta
    is NOT acknowledged; the client must resend from the durable
    cursor."""


def _public_cursor(cursor: Optional[Dict[str, Any]]
                   ) -> Optional[Dict[str, Any]]:
    """The client-facing cursor: everything but the (bulky, server-
    internal) drift baselines."""
    if cursor is None:
        return None
    return {k: v for k, v in cursor.items() if k != "baselines"}


class StreamSession:
    """One stream's server-side handle. All durable state lives on disk
    under ``directory``; the in-memory accumulated table is a cache a
    restart or failover rebuilds from the newest valid generation."""

    def __init__(self, stream_id: str, directory: str,
                 store_root: Optional[str] = None) -> None:
        self.stream_id = validate_stream_id(stream_id)
        self.directory = directory
        self.store_root = store_root or directory
        self.snapshot_dir = os.path.join(directory, "snapshot")
        self.lock = threading.RLock()
        self.cursor: Optional[Dict[str, Any]] = None
        self.table: Optional[pd.DataFrame] = None
        # admission slots (guarded by the manager's lock, not self.lock —
        # admission must never block behind an executing delta)
        self.pending = 0
        self.pending_rows = 0
        self._retrain_pending = False
        self._retrain_thread: Optional[threading.Thread] = None
        os.makedirs(directory, exist_ok=True)
        self._load_durable()
        # a durable cursor found at construction means this process did
        # not produce the in-memory state it is about to serve from: the
        # session is in recovery replay until the next commit proves the
        # rebuilt state live (surfaced as /healthz degraded)
        self.recovering = self.cursor is not None
        if self.recovering:
            counter_inc("stream.recoveries")
            _logger.info(
                f"stream {self.stream_id}: recovered at durable cursor "
                f"seq={self.cursor['seq']} "
                f"snapshot={self.cursor.get('snapshot_id')}")

    # -- durable state -------------------------------------------------------

    def _table_path(self, seq: int) -> str:
        return os.path.join(self.directory, _TABLE_FMT.format(seq=seq))

    def _cursor_path(self, seq: int) -> str:
        return os.path.join(self.directory, _CURSOR_FMT.format(seq=seq))

    def _generations(self) -> List[int]:
        """Cursor generation seqs present on disk, newest first."""
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        seqs = [int(m.group(1)) for m in
                (_CURSOR_RE.match(n) for n in names) if m]
        return sorted(seqs, reverse=True)

    def _load_durable(self) -> None:
        """Resume point: the newest generation whose cursor AND table
        both validate. Corrupt generations are quarantined by the store
        reads themselves; stepping past one is exactly the torn-write
        recovery path."""
        for seq in self._generations():
            cursor, status = dstore.read_json(
                self._cursor_path(seq), schema="stream_cursor",
                site="store.stream_cursor", root=self.store_root)
            if status != "ok" or not isinstance(cursor, dict):
                continue
            table, tstatus = dstore.read_pickle(
                self._table_path(seq), schema="stream_state",
                site="store.stream_state", root=self.store_root)
            if tstatus != "ok" or not isinstance(table, pd.DataFrame):
                _logger.warning(
                    f"stream {self.stream_id}: cursor generation {seq} "
                    f"has no valid table ({tstatus}); stepping back")
                continue
            self.cursor, self.table = cursor, table
            return

    def durable_cursor(self) -> Optional[Dict[str, Any]]:
        return _public_cursor(self.cursor)

    def _state_frame(self) -> Optional[pd.DataFrame]:
        state = mf.load_state(self.snapshot_dir)
        frame = (state or {}).get("frame")
        return frame if isinstance(frame, pd.DataFrame) else None

    def _write_verified(self, path: str, write: Callable[[], None],
                        read: Callable[[], Tuple[Any, str]],
                        what: str) -> None:
        """Write-then-validated-read-back: the conversion of a torn write
        the writer believed succeeded into a detected failure *before*
        the delta is acknowledged. One retry (the read-back quarantined
        the torn file); a second failure refuses the commit."""
        for attempt in range(_COMMIT_ATTEMPTS):
            write()
            _, status = read()
            if status == "ok":
                return
            counter_inc("stream.commit_retries")
            _logger.warning(
                f"stream {self.stream_id}: {what} write did not verify "
                f"({status}), attempt {attempt + 1}/{_COMMIT_ATTEMPTS}")
        raise StreamCommitError(
            f"stream {self.stream_id}: {what} could not be durably "
            f"written after {_COMMIT_ATTEMPTS} attempts")

    def _commit(self, seq: int, digest: str, table: pd.DataFrame,
                snapshot_id: Optional[str],
                baselines: Dict[str, Any]) -> Dict[str, Any]:
        """Table generation first, cursor generation LAST — the cursor is
        the commit point. A crash between the two leaves the previous
        cursor authoritative and the un-acked delta re-sendable."""
        tpath, cpath = self._table_path(seq), self._cursor_path(seq)
        self._write_verified(
            tpath,
            lambda: dstore.write_pickle(
                tpath, table, schema="stream_state",
                site="store.stream_state", root=self.store_root),
            lambda: dstore.read_pickle(
                tpath, schema="stream_state",
                site="store.stream_state", root=self.store_root),
            "accumulated table")
        cursor = {
            "version": 1,
            "stream_id": self.stream_id,
            "seq": int(seq),
            "snapshot_id": snapshot_id,
            "delta_sha1": digest,
            "rows_total": int(len(table)),
            "baselines": baselines,
            "updated_at": float(time.time()),
        }
        self._write_verified(
            cpath,
            lambda: dstore.write_json(
                cpath, cursor, schema="stream_cursor",
                site="store.stream_cursor", root=self.store_root),
            lambda: dstore.read_json(
                cpath, schema="stream_cursor",
                site="store.stream_cursor", root=self.store_root),
            "cursor")
        self._prune(int(seq))
        return cursor

    def _prune(self, head_seq: int) -> None:
        keep = keep_setting()
        for seq in self._generations():
            if seq <= head_seq - keep:
                for path in (self._cursor_path(seq), self._table_path(seq)):
                    try:
                        os.remove(path)
                    except OSError:
                        pass

    # -- drift gate / background retrain -------------------------------------

    def _current_histograms(self) -> Dict[str, Any]:
        manifest = mf.load_manifest(self.snapshot_dir)
        if not manifest:
            return {}
        return {name: col.get("histogram")
                for name, col in (manifest.get("columns") or {}).items()
                if col.get("histogram")}

    def _drifted_attrs(self, hists: Dict[str, Any],
                       baselines: Dict[str, Any]) -> List[str]:
        drift_max = stream_drift_max_setting()
        out = []
        for name, base in baselines.items():
            cur = hists.get(name)
            if not cur:
                continue
            psi = population_stability_index(
                *_aligned_hist_counts(cur, base))
            if psi > drift_max:
                out.append(name)
        return sorted(out)

    def _maybe_retrain(self, retrain_fn: Optional[Callable],
                       hists: Dict[str, Any]) -> None:
        """Training-time-baseline drift gate. The replacement trains
        off-thread over a copy of the accumulated table; only the swap
        itself takes the session lock, so the stream keeps committing
        deltas against the frozen models while training runs."""
        if retrain_fn is None or self._retrain_pending or not hists:
            return
        baselines = (self.cursor or {}).get("baselines") or {}
        drifted = self._drifted_attrs(hists, baselines)
        if not drifted:
            return
        self._retrain_pending = True
        counter_inc("stream.retrain.triggers")
        snapshot_table = self.table.copy()
        trigger_hists = dict(hists)
        _logger.info(f"stream {self.stream_id}: drift past the stream "
                     f"gate on {drifted}; background retrain started")

        def _work() -> None:
            try:
                models = retrain_fn(snapshot_table)
                with self.lock:
                    self._swap_models(dict(models or {}), trigger_hists)
            except Exception as e:
                counter_inc("stream.retrain.failed")
                _logger.warning(f"stream {self.stream_id}: background "
                                f"retrain failed: {e}")
            finally:
                self._retrain_pending = False

        t = threading.Thread(
            target=_work, daemon=True,
            name=f"delphi-stream-retrain-{self.stream_id[:8]}")
        t.start()
        self._retrain_thread = t

    def _swap_models(self, models: Dict[str, Any],
                     trigger_hists: Dict[str, Any]) -> None:
        """Atomic swap of the frozen per-attribute models in the snapshot
        state (one store-seam write — readers see old or new, never a
        mix), with the drift baselines refreshed to the trigger-time
        histograms so the gate re-arms instead of re-firing."""
        state = mf.load_state(self.snapshot_dir)
        if state is None:
            _logger.warning(f"stream {self.stream_id}: no snapshot state "
                            "to swap retrained models into")
            return
        merged = dict(state.get("models") or {})
        merged.update(models)
        state["models"] = merged
        dstore.write_pickle(
            os.path.join(self.snapshot_dir, "state.pkl"), state,
            schema="snapshot_state", site="store.snapshot_state",
            root=self.store_root)
        if self.cursor is not None:
            baselines = dict(self.cursor.get("baselines") or {})
            for name in models:
                if name in trigger_hists:
                    baselines[name] = trigger_hists[name]
            self.cursor["baselines"] = baselines
        counter_inc("stream.retrain.swaps")
        _logger.info(f"stream {self.stream_id}: retrained models for "
                     f"{sorted(models)} swapped into the snapshot")

    def retrain_join(self, timeout_s: float = 60.0) -> None:
        """Test/drain hook: wait for an in-flight background retrain."""
        t = self._retrain_thread
        if t is not None:
            t.join(timeout=timeout_s)

    # -- the protocol --------------------------------------------------------

    def apply(self, seq: Any, parent: Optional[str],
              delta: pd.DataFrame, run_fn: Callable,
              retrain_fn: Optional[Callable] = None
              ) -> Tuple[int, Dict[str, Any]]:
        """Applies one chained delta. ``run_fn(accumulated_df,
        snapshot_dir, seq) -> (frame_df, incremental_summary)`` runs the
        actual repair (serve and the CLI each bring their own); the
        returned body carries ``frame_df`` (a DataFrame the transport
        layer serializes) plus the cursor. Returns ``(http_status,
        body)``."""
        with self.lock:
            counter_inc("stream.deltas")
            try:
                seq = int(seq)
            except (TypeError, ValueError):
                return 400, {"status": "bad_request",
                             "error": f"bad stream seq: {seq!r}"}
            if seq < 1:
                return 400, {"status": "bad_request",
                             "error": f"stream seq must be >= 1, got {seq}"}
            digest = delta_digest(delta)
            cur = self.cursor
            cur_seq = int(cur["seq"]) if cur else 0

            if seq <= cur_seq:
                if seq == cur_seq and cur.get("delta_sha1") != digest:
                    counter_inc("stream.conflicts")
                    return 409, {
                        "status": "conflict",
                        "error": f"seq {seq} already committed with "
                                 "different delta content",
                        "cursor": _public_cursor(cur)}
                # at-least-once retry after a worker death / re-dispatch:
                # acknowledge idempotently with the durable cursor (and,
                # for the head seq, the committed frame — so a re-sent
                # final delta still yields the full answer)
                counter_inc("stream.duplicates")
                body = {"status": "duplicate", "seq": seq,
                        "cursor": _public_cursor(cur),
                        "stream": self._stream_info()}
                if seq == cur_seq:
                    frame = self._state_frame()
                    if frame is not None:
                        # canonical ordering, same as a committed delta's
                        # response: a duplicate ack is byte-identical
                        body["frame_df"] = frame.sort_values(
                            list(frame.columns)).reset_index(drop=True)
                self.recovering = False
                return 200, body

            if seq != cur_seq + 1:
                counter_inc("stream.conflicts")
                return 409, {
                    "status": "gap",
                    "error": f"expected seq {cur_seq + 1}, got {seq}",
                    "cursor": _public_cursor(cur)}
            if parent and cur is None:
                counter_inc("stream.conflicts")
                return 409, {
                    "status": "parent_mismatch",
                    "error": "stream has no durable cursor; restart at "
                             "seq 1 without a parent snapshot",
                    "cursor": None}
            if parent and cur is not None \
                    and cur.get("snapshot_id") \
                    and parent != cur.get("snapshot_id"):
                counter_inc("stream.conflicts")
                return 409, {
                    "status": "parent_mismatch",
                    "error": f"parent snapshot {parent} does not match "
                             f"the durable head "
                             f"{cur.get('snapshot_id')}",
                    "cursor": _public_cursor(cur)}

            if self.table is None:
                accumulated = delta.reset_index(drop=True)
            else:
                accumulated = pd.concat([self.table, delta],
                                        ignore_index=True)

            frame, summary = run_fn(accumulated, self.snapshot_dir, seq)
            snapshot_id = (summary or {}).get("snapshot_id")

            # training-time drift baselines: seeded from the histograms
            # the FIRST run (which trains every model) saw, refreshed per
            # attribute only when a retrain swaps that attribute's model
            hists = self._current_histograms()
            baselines = dict((cur or {}).get("baselines") or {})
            for name, hist in hists.items():
                baselines.setdefault(name, hist)

            self.cursor = self._commit(seq, digest, accumulated,
                                       snapshot_id, baselines)
            self.table = accumulated
            self.recovering = False
            counter_inc("stream.commits")
            self._maybe_retrain(retrain_fn, hists)

            body = {"status": "ok", "seq": seq,
                    "cursor": _public_cursor(self.cursor),
                    "stream": self._stream_info(),
                    "frame_df": frame}
            if summary is not None:
                body["incremental"] = summary
            return 200, body

    def _stream_info(self) -> Dict[str, Any]:
        cur = self.cursor or {}
        return {"id": self.stream_id, "seq": int(cur.get("seq", 0)),
                "snapshot_id": cur.get("snapshot_id"),
                "rows_total": int(cur.get("rows_total", 0))}


def load_durable_cursor(directory: str, store_root: Optional[str] = None
                        ) -> Optional[Dict[str, Any]]:
    """The newest valid cursor under one stream directory WITHOUT
    rebuilding the session (no table unpickle) — what /drain reports as
    the resume point, including for streams this process never served."""
    try:
        names = os.listdir(directory)
    except OSError:
        return None
    seqs = sorted((int(m.group(1)) for m in
                   (_CURSOR_RE.match(n) for n in names) if m),
                  reverse=True)
    for seq in seqs:
        cursor, status = dstore.read_json(
            os.path.join(directory, _CURSOR_FMT.format(seq=seq)),
            schema="stream_cursor", site="store.stream_cursor",
            root=store_root or directory)
        if status == "ok" and isinstance(cursor, dict):
            return _public_cursor(cursor)
    return None


class StreamManager:
    """All streams of one server: lazy per-stream sessions under
    ``root``, per-stream admission slots, and the aggregate gauges
    (``stream.lag_rows`` / ``stream.active`` / ``stream.recovering``)."""

    def __init__(self, root: str, store_root: Optional[str] = None) -> None:
        self.root = root
        self.store_root = store_root or root
        self._sessions: Dict[str, StreamSession] = {}
        self._lock = threading.Lock()

    def session(self, stream_id: Any) -> StreamSession:
        sid = validate_stream_id(stream_id)
        with self._lock:
            sess = self._sessions.get(sid)
        if sess is not None:
            return sess
        # construction (the durable scan) happens outside the manager
        # lock; a racing second constructor loses and is discarded
        fresh = StreamSession(sid, os.path.join(self.root, sid),
                              store_root=self.store_root)
        with self._lock:
            sess = self._sessions.setdefault(sid, fresh)
        self._publish_gauges()
        return sess

    def admit(self, stream_id: Any, rows: int,
              retry_after_s: float = 1.0) -> StreamSession:
        """Backpressure check at admission time (HTTP thread, before the
        job queue): bounded in-flight deltas per stream."""
        sess = self.session(stream_id)
        limit = max_inflight_setting()
        with self._lock:
            if sess.pending >= limit:
                counter_inc("stream.backpressure_429")
                raise StreamBusy(sess.stream_id, sess.durable_cursor(),
                                 retry_after_s=retry_after_s)
            sess.pending += 1
            sess.pending_rows += max(0, int(rows))
        self._publish_gauges()
        return sess

    def release(self, stream_id: Any, rows: int) -> None:
        try:
            sid = validate_stream_id(stream_id)
        except ValueError:
            return
        with self._lock:
            sess = self._sessions.get(sid)
            if sess is None:
                return
            sess.pending = max(0, sess.pending - 1)
            sess.pending_rows = max(0, sess.pending_rows - max(0, int(rows)))
        self._publish_gauges()

    def lag_rows(self) -> int:
        with self._lock:
            return sum(s.pending_rows for s in self._sessions.values())

    def recovering_count(self) -> int:
        with self._lock:
            return sum(1 for s in self._sessions.values() if s.recovering)

    def active_count(self) -> int:
        with self._lock:
            return len(self._sessions)

    def _publish_gauges(self) -> None:
        gauge_set("stream.lag_rows", self.lag_rows())
        gauge_set("stream.active", self.active_count())
        gauge_set("stream.recovering", self.recovering_count())

    def durable_cursors(self) -> Dict[str, Any]:
        """Resume points for every stream under the root — disk is the
        authority, so a drain reports chains this process never touched
        (they arrived via the shared fleet cache root)."""
        out: Dict[str, Any] = {}
        try:
            names = os.listdir(self.root)
        except OSError:
            return out
        for name in sorted(names):
            d = os.path.join(self.root, name)
            if not os.path.isdir(d) or name == "quarantine":
                continue
            cursor = load_durable_cursor(d, store_root=self.store_root)
            if cursor is not None:
                out[name] = cursor
        return out
