"""Update-cost functions (reference `python/repair/costs.py:25-78`).

`compute(x, y)` returns None when either side is falsy, matching the
reference's guard. The vectorized `compute_many` path is used by the PMF
cost-weighting kernels; it routes through the native C++ batch Levenshtein
when available (see `native/`), falling back to the python-Levenshtein
extension.
"""

import pickle
from abc import ABCMeta, abstractmethod
from typing import Callable, List, Optional, Sequence, Union

import numpy as np

from delphi_tpu.utils.native import get_levenshtein

Value = Union[str, int, float]


class UpdateCostFunction(metaclass=ABCMeta):

    def __init__(self, targets: List[str] = []) -> None:
        self.targets: List[str] = targets

    @abstractmethod
    def _compute_impl(self, x: Value, y: Value) -> Optional[float]:
        pass

    def compute(self, x: Optional[Value], y: Optional[Value]) -> Optional[float]:
        return self._compute_impl(x, y) if x and y else None

    def compute_many(self, x: Optional[Value], ys: Sequence[Optional[Value]]) \
            -> Optional[List[Optional[float]]]:
        if not x or ys is None:
            return None
        return [self.compute(x, y) for y in ys]


class Levenshtein(UpdateCostFunction):
    """Edit-distance cost (reference costs.py:38-49)."""

    def __init__(self, targets: List[str] = []) -> None:
        UpdateCostFunction.__init__(self, targets)

    def __str__(self) -> str:
        params = f'targets={",".join(self.targets)}' if self.targets else ""
        return f"{self.__class__.__name__}({params})"

    def _compute_impl(self, x: Value, y: Value) -> Optional[float]:
        return float(_levenshtein_distance(str(x), str(y)))

    def compute_many(self, x: Optional[Value], ys: Sequence[Optional[Value]]) \
            -> Optional[List[Optional[float]]]:
        if not x or ys is None:
            return None
        return _batch_levenshtein(str(x), ys)


class UserDefinedUpdateCostFunction(UpdateCostFunction):
    """Wraps a user lambda f(x, y) -> float (reference costs.py:52-78)."""

    def __init__(self, f: Callable[[str, str], float], targets: List[str] = []) -> None:
        UpdateCostFunction.__init__(self, targets)
        try:
            ret = f("x", "y")
            if type(ret) is not float:
                raise TypeError
        except Exception:
            raise ValueError("`f` should take two values and return a float cost value")
        # pickle for executor transport parity; cloudpickle when available
        try:
            import cloudpickle
            self.pickled_f = cloudpickle.dumps(f)
            self._loads = cloudpickle.loads
        except ImportError:
            self.pickled_f = pickle.dumps(f)
            self._loads = pickle.loads

    def __str__(self) -> str:
        params = f'targets={",".join(self.targets)}' if self.targets else ""
        return f"{self.__class__.__name__}({params})"

    def _compute_impl(self, x: Value, y: Value) -> Optional[float]:
        if not hasattr(self, "_f"):
            self._f = self._loads(self.pickled_f)
        try:
            return float(self._f(str(x), str(y)))
        except Exception:
            return None


# -- Levenshtein backends ----------------------------------------------------

def _python_levenshtein(x: str, y: str) -> int:
    try:
        import Levenshtein as _lev
        return int(_lev.distance(x, y))
    except ImportError:
        # classic two-row DP fallback
        if len(x) < len(y):
            x, y = y, x
        prev = list(range(len(y) + 1))
        for i, cx in enumerate(x, 1):
            cur = [i]
            for j, cy in enumerate(y, 1):
                cur.append(min(prev[j] + 1, cur[j - 1] + 1,
                               prev[j - 1] + (cx != cy)))
            prev = cur
        return prev[-1]


def _levenshtein_distance(x: str, y: str) -> int:
    native = get_levenshtein()
    if native is not None:
        return native.distance(x, y)
    return _python_levenshtein(x, y)


def _batch_levenshtein(x: str, ys: Sequence[Optional[Value]]) -> List[Optional[float]]:
    native = get_levenshtein()
    if native is not None:
        return native.batch_distance(x, ys)
    return [float(_python_levenshtein(x, str(y))) if y else None for y in ys]


