"""Tier A: learned pattern repair.

Generalizes :class:`delphi_tpu.regex_repair.RegexStructureRepair` from a
user-supplied pattern to INDUCED ones: each attribute's high-confidence
clean values (the cells the masking pass did NOT null) are tokenized into
runs of digits, runs of letters, and separator literals; when one run
structure covers a supermajority of the clean values, it becomes a pattern
string in the restricted grammar that ``regex_repair`` already lexes —

* a run whose literal text varies across values -> a PATTERN token
  (``[0-9]{m,n}`` / ``[A-Za-z]{m,n}`` with the observed length range),
* a run whose literal text is identical across values -> a CONSTANT token
  (the salvage relaxes it to ``.{1,len}`` and rebuilds it verbatim, which
  is exactly what repairs a corrupted separator or unit suffix),

anchored ``^...$``. The induced repairer is then applied to the routed
cells whose current value breaks the structure; values already matching
are left for the joint tier (their problem is semantic, not syntactic).

Induction is pure host-side string work over at most a few thousand clean
spellings per attribute — the expensive escalation math lives in tier B.
"""

import re
from typing import Dict, List, Optional, Sequence, Tuple

from delphi_tpu.regex_repair import RegexStructureRepair

#: fraction of clean values that must share one run structure
MIN_SUPPORT = 0.9
#: minimum clean values before induction is even attempted
MIN_CLEAN = 4
#: clean spellings sampled per attribute (deterministic head — the encoded
#: column's first-appearance order, not a random draw)
MAX_CLEAN = 4096

_DIGITS = frozenset("0123456789")
_LETTERS = frozenset("ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz")
#: non-alphanumeric chars the restricted CONSTANT grammar can express
_SEPARATORS = frozenset(" _%-")


def _runs(value: str) -> Optional[List[Tuple[str, str]]]:
    """Maximal same-class runs of ``value`` as ``(class, text)`` with class
    ``D`` (digits), ``L`` (letters) or ``S`` (separators); ``None`` when the
    value contains a char the restricted grammar cannot express."""
    out: List[Tuple[str, str]] = []
    i, n = 0, len(value)
    while i < n:
        ch = value[i]
        if ch in _DIGITS:
            cls, charset = "D", _DIGITS
        elif ch in _LETTERS:
            cls, charset = "L", _LETTERS
        elif ch in _SEPARATORS:
            cls, charset = "S", _SEPARATORS
        else:
            return None
        j = i + 1
        while j < n and value[j] in charset:
            j += 1
        out.append((cls, value[i:j]))
        i = j
    return out


def induce_pattern(clean_values: Sequence[str]) -> Optional[str]:
    """One restricted-grammar pattern string covering the majority run
    structure of ``clean_values``, or ``None`` when no structure reaches
    :data:`MIN_SUPPORT` (free-text attributes must never induce — a pattern
    that "repairs" prose would be a corruption engine)."""
    vals = [v for v in clean_values[:MAX_CLEAN] if v]
    if len(vals) < MIN_CLEAN:
        return None
    groups: Dict[Tuple[str, ...], List[List[Tuple[str, str]]]] = {}
    total = 0
    for v in vals:
        runs = _runs(v)
        if runs is None:
            continue
        total += 1
        # separators key by literal (the grammar cannot express a varying
        # separator); digit/letter runs key by class only
        key = tuple(c if c != "S" else f"S:{t}" for c, t in runs)
        groups.setdefault(key, []).append(runs)
    if total < MIN_CLEAN:
        return None
    key, members = max(groups.items(), key=lambda kv: (len(kv[1]), kv[0]))
    if len(members) / total < MIN_SUPPORT:
        return None
    n_runs = len(members[0])
    parts: List[str] = []
    has_pattern = has_constant = False
    for slot in range(n_runs):
        cls = members[0][slot][0]
        texts = {m[slot][1] for m in members}
        if cls == "S" or len(texts) == 1:
            parts.append(next(iter(texts)))
            has_constant = True
        else:
            lens = [len(m[slot][1]) for m in members]
            char_class = "[0-9]" if cls == "D" else "[A-Za-z]"
            parts.append(f"{char_class}{{{min(lens)},{max(lens)}}}")
            has_pattern = True
    # a constants-only pattern can only reproduce one literal string, and a
    # patterns-only one has no structure to salvage around — neither repairs
    if not (has_pattern and has_constant):
        return None
    return "^" + "".join(parts) + "$"


class InducedPatternRepair:
    """An induced pattern plus its strict form: ``repair`` returns a value
    only for cells that BREAK the structure and whose salvage lands back
    inside it."""

    def __init__(self, pattern: str) -> None:
        self.pattern = pattern
        self._salvage = RegexStructureRepair(pattern)
        self._strict = re.compile(pattern)

    def matches(self, value: Optional[str]) -> bool:
        return value is not None and self._strict.fullmatch(value) is not None

    def repair(self, value: Optional[str]) -> Optional[str]:
        if value is None or self.matches(value):
            return None
        out = self._salvage(value)
        if out is None or out == value or not self.matches(out):
            return None
        return out


def induce_for_attributes(clean_values: Dict[str, Sequence[str]]) \
        -> Dict[str, InducedPatternRepair]:
    """Per-attribute induced repairers (attributes with no stable structure
    simply don't appear)."""
    out: Dict[str, InducedPatternRepair] = {}
    for attr in sorted(clean_values):
        pattern = induce_pattern(list(clean_values[attr]))
        if pattern is None:
            continue
        try:
            out[attr] = InducedPatternRepair(pattern)
        except Exception:
            continue  # induced string outside the grammar: skip, never raise
    return out
