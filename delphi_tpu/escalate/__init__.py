"""Escalation tier: confidence-routed second-pass repair.

The provenance plane names exactly which cells the statistical models are
unsure about; this subsystem routes ONLY those cells — under a strict
per-run budget — through three pluggable tiers, walked in order:

* **Tier A, learned patterns** (:mod:`~delphi_tpu.escalate.patterns`) —
  per-attribute token-structure patterns induced from clean cells, applied
  through the existing restricted-grammar salvage; fixes syntactic breaks.
* **Tier B, joint inference** (:mod:`~delphi_tpu.escalate.joint` over the
  :mod:`delphi_tpu.ops.joint` kernel) — HoloClean-style message passing on
  a factor graph from the co-occurrence statistics, shape-bucketed batched
  device launches; fixes semantically wrong values via correlated context.
* **Tier C, external adapter** (:mod:`~delphi_tpu.escalate.adapter`) —
  arbitrary external repairers behind an explicit allow flag
  (``DELPHI_ESCALATE_ADAPTER``) and a call budget; HARD OFF by default.

Every escalated decision lands in the provenance ledger with its tier and
reason, scorecards grow a per-tier section, ``escalation.*`` counters show
on live ``/metrics``, and the run report carries the summary (schema v5).
Enable with ``DELPHI_ESCALATE`` / the ``repair.escalate`` option (serve
accepts it per request); see docs/source/escalation.rst.
"""

import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from delphi_tpu.escalate.adapter import (  # noqa: F401
    MockAdapter, RepairAdapter, adapter_allowed, adapter_call_limit,
    resolve_adapter,
)
from delphi_tpu.escalate.patterns import induce_for_attributes
from delphi_tpu.escalate.router import Budget, RoutedCell, select_candidates
from delphi_tpu.observability import counter_inc
from delphi_tpu.observability import provenance as _prov
from delphi_tpu.utils import setup_logger

_logger = setup_logger()

_TRUTHY = frozenset({"1", "true", "yes", "on"})

TIER_PATTERN = "pattern"
TIER_JOINT = "joint"
TIER_ADAPTER = "adapter"

#: cap on the cell lists embedded in the run-report summary
_SUMMARY_CELL_CAP = 1024

DEFAULT_BUDGET = 256
DEFAULT_ITERS = 8


# -- configuration (option wins -> env -> session conf, the same
# precedence as the incremental plane: serve sets options per request, so
# concurrent requests never race an env flip) ------------------------------

def escalation_requested(model: Any) -> bool:
    if model._opt_escalate.key in model.opts:
        # parse the raw spelling rather than legacy string truthiness:
        # an explicit "repair.escalate=false" must mean OFF
        raw = str(model.opts[model._opt_escalate.key])
        return raw.strip().lower() in _TRUTHY
    env = os.environ.get("DELPHI_ESCALATE")
    if env is not None:
        return env.strip().lower() in _TRUTHY
    from delphi_tpu.session import get_session
    conf = get_session().conf.get("repair.escalate")
    if conf is not None:
        return str(conf).strip().lower() in _TRUTHY
    return False


def _conf_knob(model: Any, opt: Any, env_name: str, conf_key: str,
               cast: Any, default: Any) -> Any:
    if opt.key in model.opts:
        return cast(model._get_option_value(*opt))
    env = os.environ.get(env_name)
    if env is not None:
        try:
            return cast(env)
        except ValueError:
            return default
    from delphi_tpu.session import get_session
    conf = get_session().conf.get(conf_key)
    if conf is not None:
        try:
            return cast(conf)
        except ValueError:
            return default
    return default


def conf_threshold(model: Any) -> float:
    return float(_conf_knob(model, model._opt_escalate_conf,
                            "DELPHI_ESCALATE_CONF", "repair.escalate.conf",
                            float, _prov.LOW_CONFIDENCE))


def cell_budget(model: Any) -> int:
    return max(0, int(_conf_knob(
        model, model._opt_escalate_budget, "DELPHI_ESCALATE_BUDGET",
        "repair.escalate.budget", int, DEFAULT_BUDGET)))


def joint_iters(model: Any) -> int:
    return max(1, int(_conf_knob(
        model, model._opt_escalate_iters, "DELPHI_ESCALATE_ITERS",
        "repair.escalate.iters", int, DEFAULT_ITERS)))


# -- orchestration ---------------------------------------------------------

def _clean_values(masked: Any, attrs: List[str]) \
        -> Tuple[Dict[str, List[str]], Dict[str, List[Tuple[str, int]]]]:
    """Per-attribute clean spellings (for pattern induction) and
    ``(value, count)`` candidates sorted most-frequent-first (for the
    adapter tier) from the masked table's surviving cells."""
    values: Dict[str, List[str]] = {}
    candidates: Dict[str, List[Tuple[str, int]]] = {}
    for attr in attrs:
        col = masked.column(attr)
        codes = col.codes[col.codes >= 0]
        values[attr] = [str(v) for v in col.vocab[codes[:4096]]]
        counts = np.bincount(codes, minlength=col.domain_size)
        cand = [(str(col.vocab[i]), int(counts[i]))
                for i in np.nonzero(counts)[0]]
        cand.sort(key=lambda vc: (-vc[1], vc[0]))
        candidates[attr] = cand[:32]
    return values, candidates


def maybe_escalate(model: Any, masked: Any, error_cells_df: Any,
                   error_row_pos: np.ndarray, repaired_rows_df: Any,
                   target_columns: List[str],
                   continuous_columns: List[str]) -> Dict[str, Any]:
    """Runs the escalation pass in place over ``repaired_rows_df`` (the
    single-shot repaired block, rows aligned with ``error_row_pos``) and
    returns the summary embedded in the run report. The caller guarantees
    an active provenance ledger — routing IS a ledger read."""
    from delphi_tpu.errors import ROW_IDX

    led = _prov.active_ledger()
    summary: Dict[str, Any] = {
        "requested": True,
        "conf_threshold": conf_threshold(model),
        "routed": 0,
        "escalated": 0,
        "budget": {"limit": cell_budget(model), "spent": 0,
                   "exhausted": False},
        "tiers": {
            TIER_PATTERN: {"attempts": 0, "repairs": 0},
            TIER_JOINT: {"attempts": 0, "repairs": 0},
            TIER_ADAPTER: {"allowed": adapter_allowed(model),
                           "calls": 0, "attempts": 0, "repairs": 0},
        },
        "routed_cells": [],
        "escalated_cells": [],
    }
    if led is None:
        summary["skipped"] = "no_ledger"
        return summary

    discrete_targets = [a for a in target_columns
                        if a not in set(continuous_columns)]
    rid_np = error_cells_df[model._row_id].to_numpy(dtype=object)
    attrs_np = error_cells_df["attribute"].to_numpy(dtype=object)
    rows_np = error_cells_df[ROW_IDX].to_numpy().astype(np.int64)
    curs_np = error_cells_df["current_value"].to_numpy(dtype=object)
    cell_index = {(str(r), str(a)): (int(p), c)
                  for r, a, p, c in zip(rid_np, attrs_np, rows_np, curs_np)}

    cands = select_candidates(led.entries(), cell_index,
                              summary["conf_threshold"], discrete_targets)
    summary["routed"] = len(cands)
    summary["routed_cells"] = [[c.row_id, c.attribute]
                               for c in cands[:_SUMMARY_CELL_CAP]]
    counter_inc("escalation.routed", len(cands))
    for c in cands:
        led.record_escalation_routed(c.row_id, c.attribute, c.route_reason)
    if not cands:
        return summary

    budget = Budget(summary["budget"]["limit"])
    col_pos = {a: i for i, a in enumerate(repaired_rows_df.columns)}
    resolved: Dict[Tuple[str, str], str] = {}

    def _apply(cell: RoutedCell, tier: str, reason: str, value: str,
               confidence: Optional[float] = None) -> None:
        local = int(np.searchsorted(error_row_pos, cell.row_pos))
        repaired_rows_df.iat[local, col_pos[cell.attribute]] = value
        led.record_escalation(cell.row_id, cell.attribute, tier, reason,
                              value, confidence)
        resolved[cell.key] = value
        summary["tiers"][tier]["repairs"] += 1
        summary["escalated"] += 1
        if len(summary["escalated_cells"]) < _SUMMARY_CELL_CAP:
            summary["escalated_cells"].append(
                [cell.row_id, cell.attribute, tier, value])
        counter_inc(f"escalation.{tier}.repairs")

    # -- tier A: learned pattern repair (syntactic breaks) -----------------
    routed_attrs = sorted({c.attribute for c in cands})
    clean_vals, clean_cands = _clean_values(masked, routed_attrs)
    repairers = induce_for_attributes(clean_vals)
    counter_inc("escalation.pattern.induced", len(repairers))
    for cell in cands:
        rep = repairers.get(cell.attribute)
        if rep is None or cell.current_value is None:
            continue
        if not budget.take():
            break
        summary["tiers"][TIER_PATTERN]["attempts"] += 1
        counter_inc("escalation.pattern.attempts")
        fixed = rep.repair(cell.current_value)
        if fixed is not None:
            _apply(cell, TIER_PATTERN, _prov.REASON_ESCALATED_PATTERN, fixed)

    # -- tier B: joint inference (semantic errors via correlated context) --
    if not budget.exhausted:
        from delphi_tpu.escalate.joint import run_joint_tier
        joint_cells: List[RoutedCell] = []
        for cell in cands:
            if cell.key in resolved:
                continue
            if not budget.take():
                break
            joint_cells.append(cell)
        summary["tiers"][TIER_JOINT]["attempts"] = len(joint_cells)
        for p in run_joint_tier(masked, joint_cells,
                                summary["conf_threshold"],
                                joint_iters(model)):
            _apply(p.cell, TIER_JOINT, _prov.REASON_ESCALATED_JOINT,
                   p.value, p.belief)

    # -- tier C: external adapter (explicitly enabled only) ----------------
    if not budget.exhausted and summary["tiers"][TIER_ADAPTER]["allowed"]:
        ext = resolve_adapter(model)
        if ext is not None:
            call_limit = adapter_call_limit()
            decoded: Dict[int, Dict[str, Any]] = {}
            batch: List[Tuple[RoutedCell, Dict[str, Any]]] = []
            for cell in cands:
                if cell.key in resolved:
                    continue
                if not budget.take():
                    break
                batch.append((cell, {
                    "row_id": cell.row_id,
                    "attribute": cell.attribute,
                    "current_value": cell.current_value,
                    "row": decoded.setdefault(cell.row_pos, {
                        c.name: (str(c.vocab[c.codes[cell.row_pos]])
                                 if c.codes[cell.row_pos] >= 0 else None)
                        for c in masked.columns}),
                    "candidates": clean_cands.get(cell.attribute, []),
                }))
            # one repair() call per attribute batch, call-budget capped
            by_attr: Dict[str, List[Tuple[RoutedCell, Dict[str, Any]]]] = {}
            for cell, req in batch:
                by_attr.setdefault(cell.attribute, []).append((cell, req))
            for attr in sorted(by_attr):
                if summary["tiers"][TIER_ADAPTER]["calls"] >= call_limit:
                    counter_inc("escalation.adapter.call_budget_exhausted")
                    break
                group = by_attr[attr]
                summary["tiers"][TIER_ADAPTER]["calls"] += 1
                summary["tiers"][TIER_ADAPTER]["attempts"] += len(group)
                counter_inc("escalation.adapter.calls")
                try:
                    proposals = ext.repair([req for _, req in group])
                except Exception as e:
                    _logger.warning(
                        f"escalation adapter failed on '{attr}': {e}")
                    continue
                for (cell, _), value in zip(group, proposals or []):
                    if value is not None and str(value) != cell.current_value:
                        _apply(cell, TIER_ADAPTER,
                               _prov.REASON_ESCALATED_ADAPTER, str(value))

    if budget.exhausted:
        counter_inc("escalation.budget_exhausted")
    summary["budget"]["spent"] = budget.spent
    summary["budget"]["exhausted"] = budget.exhausted
    counter_inc("escalation.escalated", summary["escalated"])
    return summary
