"""Tier C: the external repair-adapter interface. HARD OFF BY DEFAULT.

An adapter is an arbitrary external repairer (an LLM endpoint, a human
review queue, a vendor API) behind a two-line contract: ``repair(batch)``
takes a list of request dicts and returns one proposed value (or ``None``)
per request. Because the adapter is the one tier whose behavior this repo
cannot vouch for, it is fenced three ways:

* **allow flag** — :func:`resolve_adapter` is the ONLY construction path
  (a static guard test enforces this), and its first act is the
  :func:`adapter_allowed` check: unless ``DELPHI_ESCALATE_ADAPTER`` (or the
  per-request ``repair.escalate.adapter`` option) is explicitly set to a
  non-false value, it returns ``None`` and no adapter code runs at all;
* **call budget** — ``DELPHI_ESCALATE_ADAPTER_CALLS`` caps ``repair``
  invocations per run (a proxy for tokens/dollars), on top of the router's
  per-cell budget;
* **provenance** — every adapter decision lands in the ledger under its
  own reason, so an audit can always separate adapter output from the
  statistical pipeline's.

The built-in ``mock`` adapter is deterministic (mode imputation over the
clean values the orchestrator hands it) so tests and the bench A/B can
exercise the full tier-C path without any external dependency.
"""

import importlib
import os
from typing import Any, Dict, List, Optional

from delphi_tpu.utils import setup_logger

_logger = setup_logger()

_FALSY = frozenset({"", "0", "false", "no", "off"})

#: adapter ``repair()`` invocations allowed per run (env override below)
DEFAULT_ADAPTER_CALLS = 8


class RepairAdapter:
    """External-repairer contract. ``batch`` items carry ``row_id``,
    ``attribute``, ``current_value``, ``row`` (the cell's decoded row as an
    attribute->value dict) and ``candidates`` (clean ``(value, count)``
    pairs sorted most-frequent-first, value ascending on ties). Return one
    proposed spelling or ``None`` per item, same order."""

    name = "adapter"

    def repair(self, batch: List[Dict[str, Any]]) -> List[Optional[str]]:
        raise NotImplementedError


class MockAdapter(RepairAdapter):
    """Deterministic stand-in: proposes each cell's most frequent clean
    value (lexicographically smallest on ties) when it differs from the
    current value."""

    name = "mock"

    def repair(self, batch: List[Dict[str, Any]]) -> List[Optional[str]]:
        out: List[Optional[str]] = []
        for req in batch:
            cands = req.get("candidates") or []
            top = str(cands[0][0]) if cands else None
            out.append(top if top is not None
                       and top != req.get("current_value") else None)
        return out


def adapter_spec(model: Any = None) -> str:
    """The raw adapter setting, same precedence as every other escalation
    knob: per-model option first (serve sets it per request), then env,
    then session conf."""
    if model is not None and model._opt_escalate_adapter.key in model.opts:
        return str(model._get_option_value(*model._opt_escalate_adapter))
    env = os.environ.get("DELPHI_ESCALATE_ADAPTER")
    if env is not None:
        return env
    from delphi_tpu.session import get_session
    conf = get_session().conf.get("repair.escalate.adapter")
    return str(conf) if conf is not None else ""


def adapter_allowed(model: Any = None) -> bool:
    """True only when the operator EXPLICITLY enabled the adapter tier.
    Absent, empty, or any false spelling -> off; there is no default-on
    path anywhere."""
    return adapter_spec(model).strip().lower() not in _FALSY


def adapter_call_limit() -> int:
    try:
        return max(0, int(os.environ.get(
            "DELPHI_ESCALATE_ADAPTER_CALLS", str(DEFAULT_ADAPTER_CALLS))))
    except ValueError:
        return DEFAULT_ADAPTER_CALLS


def resolve_adapter(model: Any = None) -> Optional[RepairAdapter]:
    """The single gatekeeper: ``None`` unless :func:`adapter_allowed`.
    ``mock`` (or a bare truthy flag) resolves to :class:`MockAdapter`;
    ``module:Class`` imports an external implementation — a bad spec
    disables the tier with a warning rather than failing the run."""
    if not adapter_allowed(model):
        return None
    spec = adapter_spec(model).strip()
    if spec.lower() in {"mock", "1", "true", "yes", "on"}:
        return MockAdapter()
    if ":" in spec:
        mod_name, _, cls_name = spec.partition(":")
        try:
            cls = getattr(importlib.import_module(mod_name), cls_name)
            adapter = cls()
            if not callable(getattr(adapter, "repair", None)):
                raise TypeError(f"{spec} has no repair() method")
            return adapter
        except Exception as e:
            _logger.warning(
                f"escalation adapter '{spec}' failed to load ({e}); "
                f"tier C disabled for this run")
            return None
    _logger.warning(f"unrecognized escalation adapter spec '{spec}'; "
                    f"tier C disabled for this run")
    return None
