"""Tier B: HoloClean-style joint inference over correlated attributes.

Builds a factor graph for the routed cells out of statistics the pipeline
already computes (:func:`delphi_tpu.ops.freq.compute_freq_stats` over the
MASKED table, so every count comes from cells believed clean):

* **unary potentials** — Laplace-smoothed log prior of each candidate value
  plus one log-conditional term per OBSERVED same-row context attribute
  (``log P(a = v | c = u)`` from the pair count matrices);
* **pairwise potentials** — the same conditionals between two UNKNOWN cells
  that share a row, which is what single-cell scoring cannot do: two
  routed cells in one row constrain each other through the iteration.

Cells bucket by the power-of-two pad of their candidate-domain size, each
bucket pads ``(n, K, V)`` and runs as ONE jit-compiled device launch of
:func:`delphi_tpu.ops.joint.joint_beliefs` (upload seam + ``run_guarded``
-> transfer ledger + resilience plane). Cross-bucket neighbor coupling is
dropped — those neighbors still contribute as observed context would not,
but their pair statistics do via the unary prior; the alternative (one
bucket padded to the global max V) wastes quadratically more FLOPs on the
``[V, V]`` potentials.

Proposals are accepted when the converged belief clears both the routing
threshold and the cell's original confidence — joint inference must be
MORE sure than the model it is overriding.
"""

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from delphi_tpu.escalate.router import RoutedCell
from delphi_tpu.observability import counter_inc
from delphi_tpu.ops.freq import compute_freq_stats
from delphi_tpu.ops.joint import NEG_INF, joint_beliefs

#: Laplace smoothing for every count-derived log potential
ALPHA = 0.5
#: largest candidate domain joint inference will model (the pairwise
#: potentials are [V, V] per neighbor edge — quadratic memory)
MAX_DOMAIN = 64
#: observed context attributes folded into each cell's unary potential
CTX_CAP = 4
#: same-row unknown neighbors kept per cell (column order, deterministic)
NBR_CAP = 4


class JointProposal:
    __slots__ = ("cell", "value", "belief")

    def __init__(self, cell: RoutedCell, value: str, belief: float) -> None:
        self.cell = cell
        self.value = value
        self.belief = belief


def _log_cond(pair_uv: np.ndarray, single_u: np.ndarray,
              v_size: int) -> np.ndarray:
    """log P(v | u) with Laplace smoothing; ``pair_uv`` is [U+1, V+1] raw
    counts (slot 0 = NULL), returns [U, V] over the non-NULL values."""
    num = pair_uv[1:, 1:].astype(np.float64) + ALPHA
    den = single_u[1:].astype(np.float64)[:, None] + ALPHA * v_size
    return np.log(num / den)


def run_joint_tier(masked: Any, cells: List[RoutedCell],
                   conf_threshold: float, iters: int) -> List[JointProposal]:
    """Joint inference over ``cells`` against the ``masked`` encoded table
    (error cells already nulled). Returns accepted proposals; counters
    ``escalation.joint.*`` record launches/cells/proposals."""
    if not cells:
        return []
    name_to_col = {c.name: c for c in masked.columns}
    # participating attributes: discrete enough for the [V, V] potentials
    attrs = [c.name for c in masked.columns
             if c.name in {x.attribute for x in cells}
             and 1 <= c.domain_size <= MAX_DOMAIN]
    attr_set = set(attrs)
    todo = [c for c in cells if c.attribute in attr_set]
    if not todo:
        return []
    # context attributes: reasonably discrete columns (including the
    # targets themselves — a routed cell is context for OTHER attributes'
    # cells only when observed, which the per-cell masking below enforces);
    # capped so the all-pairs stat pass stays bounded on wide tables
    ctx_attrs = [c.name for c in masked.columns
                 if 1 <= c.domain_size <= MAX_DOMAIN]
    needed = list(dict.fromkeys(attrs + ctx_attrs))[:16]
    ctx_attrs = [a for a in ctx_attrs if a in set(needed)]
    pairs = [(a, b) for i, a in enumerate(needed) for b in needed[i + 1:]]
    stats = compute_freq_stats(masked, needed, pairs)

    routed_keys = {(c.row_pos, c.attribute) for c in todo}
    by_row: Dict[int, List[int]] = {}
    for i, c in enumerate(todo):
        by_row.setdefault(c.row_pos, []).append(i)

    # bucket by padded domain size so one compiled executable serves every
    # attribute whose vocabulary lands in the same power-of-two band — the
    # grouping comes from the unified launch planner. The v_pad axis is the
    # piece SHAPE (never merged: the softmax reduction order over the
    # domain axis must stay per-vocabulary-band); only the cell batch axis
    # is planner-padded.
    from delphi_tpu.parallel import planner
    plan = planner.plan_launches(
        "escalation.joint",
        [planner.Piece(
            key=i, size=1,
            shape=(planner.pow2_pad(name_to_col[c.attribute].domain_size),))
         for i, c in enumerate(todo)],
        pad_batch=True, persist=False)
    plan.record()

    proposals: List[JointProposal] = []
    for launch in sorted(plan.launches, key=lambda l: l.shape[0]):
        v_pad = int(launch.shape[0])
        members = [span.key for span in launch.spans]
        n_pad = launch.batch_pad
        unary = np.full((n_pad, v_pad), NEG_INF, dtype=np.float32)
        unary[:, 0] = 0.0  # padded rows: a defined softmax, discarded below
        nbr_idx = np.full((n_pad, NBR_CAP), -1, dtype=np.int32)
        nbr_pot = np.zeros((n_pad, NBR_CAP, v_pad, v_pad), dtype=np.float32)
        slot_of = {idx: s for s, idx in enumerate(members)}

        for s, idx in enumerate(members):
            cell = todo[idx]
            a = cell.attribute
            col = name_to_col[a]
            va = col.domain_size
            single_a = stats.single(a, filtered=False)
            n_obs = float(single_a[1:].sum())
            u = np.log((single_a[1:].astype(np.float64) + ALPHA)
                       / (n_obs + ALPHA * va))
            # observed context: same-row cells that are NOT routed unknowns
            n_ctx = 0
            for c_attr in ctx_attrs:
                if c_attr == a or n_ctx >= CTX_CAP:
                    continue
                if (cell.row_pos, c_attr) in routed_keys:
                    continue
                code = int(name_to_col[c_attr].codes[cell.row_pos])
                if code < 0 or not stats.has_pair(c_attr, a):
                    continue
                cond = _log_cond(stats.pair(c_attr, a, filtered=False),
                                 stats.single(c_attr, filtered=False), va)
                u = u + cond[code]
                n_ctx += 1
            unary[s, :va] = u.astype(np.float32)
            unary[s, va:] = NEG_INF
            # unknown neighbors: other routed cells of this row, same bucket
            k = 0
            for j in by_row.get(cell.row_pos, []):
                if j == idx or k >= NBR_CAP:
                    continue
                other = todo[j]
                if other.attribute == a or j not in slot_of:
                    continue
                b_attr = other.attribute
                if not stats.has_pair(b_attr, a):
                    continue
                vb = name_to_col[b_attr].domain_size
                pot = _log_cond(stats.pair(b_attr, a, filtered=False),
                                stats.single(b_attr, filtered=False), va)
                nbr_idx[s, k] = slot_of[j]
                nbr_pot[s, k, :vb, :va] = pot.astype(np.float32)
                k += 1

        with plan.launch_scope(launch):
            beliefs = joint_beliefs(unary, nbr_idx, nbr_pot, iters)
        counter_inc("escalation.joint.launches")
        counter_inc("escalation.joint.cells", len(members))

        for s, idx in enumerate(members):
            cell = todo[idx]
            col = name_to_col[cell.attribute]
            va = col.domain_size
            b = beliefs[s, :va]
            v = int(np.argmax(b))
            value = str(col.vocab[v])
            accept_at = max(conf_threshold, cell.confidence or 0.0)
            if value != cell.current_value and float(b[v]) >= accept_at:
                proposals.append(JointProposal(cell, value, float(b[v])))
    counter_inc("escalation.joint.proposals", len(proposals))
    return proposals
