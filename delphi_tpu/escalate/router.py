"""Confidence router: selects escalation candidates from the live ledger.

The provenance plane already names exactly which cells the statistical
models are unsure about; the router turns that signal into a deterministic,
budget-capped work list:

* cells whose recorded top-posterior ``confidence`` is below the threshold
  (``DELPHI_ESCALATE_CONF``, default the scorecards' low-confidence line),
* cells the one-tuple DC minimizer kept under its distinct
  ``confidence_unavailable_keep_all`` fallback (it could not score the
  row's options, so nothing vouches for them), and
* cells with no usable confidence at all (point predictions / rule paths
  never record one) that some phase decided on.

Candidates sort most-uncertain-first (missing confidence before low
confidence, then by ``(confidence, attribute, row_id)``) so a budget always
spends itself on the cells the pipeline knows least about, and two runs of
the same table route identically.
"""

from dataclasses import dataclass
from typing import Any, Dict, Iterable, List, Optional, Tuple

from delphi_tpu.observability import provenance as _prov


@dataclass
class RoutedCell:
    """One escalation candidate, carrying everything the tiers need."""

    row_id: str
    attribute: str
    row_pos: int                 # global row position in the input table
    current_value: Optional[str]
    confidence: Optional[float]
    route_reason: str            # why the router selected it

    @property
    def key(self) -> Tuple[str, str]:
        return (self.row_id, self.attribute)


ROUTE_LOW_CONFIDENCE = "low_confidence"
ROUTE_CONFIDENCE_UNAVAILABLE = "confidence_unavailable"
ROUTE_DC_KEEP_ALL = "dc_keep_all"


class Budget:
    """Strict per-run escalation budget, charged once per cell x tier
    attempt. ``take()`` answers "may I attempt one more cell?" and flips
    ``exhausted`` the first time the answer is no — the orchestrator then
    stops routing mid-tier, keeping every decision already made."""

    def __init__(self, limit: int) -> None:
        self.limit = max(0, int(limit))
        self.spent = 0
        self.exhausted = False

    def take(self, n: int = 1) -> bool:
        if self.spent + n > self.limit:
            self.exhausted = True
            return False
        self.spent += n
        return True

    def remaining(self) -> int:
        return max(0, self.limit - self.spent)


def _sort_key(cell: RoutedCell) -> Tuple[int, float, str, str]:
    # missing confidence first (the pipeline knows NOTHING about these),
    # then ascending confidence; attribute/row_id break ties so the order
    # is total and reproducible
    missing = 0 if cell.confidence is None else 1
    conf = -1.0 if cell.confidence is None else float(cell.confidence)
    return (missing, conf, cell.attribute, cell.row_id)


def select_candidates(entries: Iterable[Dict[str, Any]],
                      cell_index: Dict[Tuple[str, str], Tuple[int, Any]],
                      conf_threshold: float,
                      target_attrs: Iterable[str]) -> List[RoutedCell]:
    """Routes ledger ``entries`` against the run's error cells.

    ``cell_index`` maps ``(row_id, attribute)`` to ``(row_pos,
    current_value)`` for every error cell the repair phase actually saw —
    ledger entries outside it (non-targeted attributes, weak-label-demoted
    cells) never route. Returns the full sorted candidate list; the
    orchestrator applies the budget while walking tiers."""
    targets = set(target_attrs)
    out: List[RoutedCell] = []
    for e in entries:
        attr = str(e.get("attribute"))
        if attr not in targets:
            continue
        rid = str(e.get("row_id"))
        at = cell_index.get((rid, attr))
        if at is None:
            continue
        reason = e.get("decision_reason")
        if reason == _prov.REASON_WEAK_LABEL_CLEAN:
            continue  # domain analysis demoted the cell to clean
        conf = e.get("confidence")
        if conf is not None:
            try:
                conf = float(conf)
            except (TypeError, ValueError):
                conf = None
        if reason == _prov.REASON_CONFIDENCE_UNAVAILABLE:
            route = ROUTE_DC_KEEP_ALL
        elif conf is None:
            route = ROUTE_CONFIDENCE_UNAVAILABLE
        elif conf < conf_threshold:
            route = ROUTE_LOW_CONFIDENCE
        else:
            continue
        row_pos, current = at
        out.append(RoutedCell(
            row_id=rid, attribute=attr, row_pos=int(row_pos),
            current_value=None if current is None else str(current),
            confidence=conf, route_reason=route))
    out.sort(key=_sort_key)
    return out
