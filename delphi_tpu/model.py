"""RepairModel: the 3-phase repair pipeline (detect -> train -> repair).

API-compatible re-implementation of the reference's
`python/repair/model.py:103-1537` — same fluent setters, option keys,
exclusive run() flags, SCARE-style split of clean/dirty rows, FD rule models,
PMF computation, cost weighting and maximal-likelihood repair — built on the
encoded-table kernels instead of Spark SQL + LightGBM:

* error detection / stats / domain analysis: :mod:`delphi_tpu.errors`
* per-attribute stat models: :mod:`delphi_tpu.models` (JAX)
* repair inference: batched predictions over the dirty-row block

DataFrames in and out are pandas.
"""

import contextlib
import copy
import hashlib
import heapq
import os
import pickle
import zlib
from collections import namedtuple
from typing import Any, Dict, List, Optional, Set, Tuple, Union

import numpy as np
import pandas as pd

from delphi_tpu.costs import UpdateCostFunction
from delphi_tpu.depgraph import compute_functional_dep_map, compute_functional_deps
from delphi_tpu.errors import (
    ConstraintErrorDetector, ErrorDetector, ErrorModel, RegExErrorDetector, ROW_IDX)
from delphi_tpu.models import FeatureEncoder
from delphi_tpu.regex_repair import RegexStructureRepair
from delphi_tpu.session import get_session
from delphi_tpu.table import (
    EncodedTable, KIND_FRACTIONAL, KIND_INTEGRAL, check_input_table)
from delphi_tpu.train import (
    build_model, compute_class_nrow_stdv, rebalance_training_data, train_option_keys)
from delphi_tpu.observability import active_ledger, counter_inc, gauge_set
from delphi_tpu.observability import provenance as _prov
from delphi_tpu.parallel import resilience as _resilience
from delphi_tpu.utils import (
    argtype_check, elapsed_time, get_option_value, job_phase, log_based_on_level,
    phase_span, profile_trace, setup_logger, to_list_str)

_logger = setup_logger()


class PoorModel:
    """Constant predictor fallback (reference model.py:44-61)."""

    def __init__(self, v: Any) -> None:
        self.v = v

    @property
    def classes_(self) -> Any:
        return np.array([self.v])

    def predict(self, X: Any) -> Any:
        return [self.v] * len(X)

    def predict_proba(self, X: Any) -> Any:
        return [np.array([1.0])] * len(X)


class FunctionalDepModel:
    """Rule model looking values up in an FD map x -> y
    (reference model.py:64-100)."""

    def __init__(self, x: str, fd_map: Dict[str, str]) -> None:
        self.fd_map = fd_map
        # sorted: str-set iteration order varies with hash randomization,
        # which would make classes_ (and PMF tie-breaking) vary across runs
        self.classes = sorted(set(fd_map.values()))
        self.x = x
        self.fd_keypos_map = {c: i for i, c in enumerate(self.classes)}

    @property
    def classes_(self) -> Any:
        return np.array(self.classes)

    def predict(self, X: pd.DataFrame) -> Any:
        return [self.fd_map.get(x, None) for x in X[self.x]]

    def predict_proba(self, X: pd.DataFrame) -> Any:
        pmf = []
        for x in X[self.x]:
            if x in self.fd_map:
                probs = np.zeros(len(self.classes))
                probs[self.fd_keypos_map[self.fd_map[x]]] = 1.0
                pmf.append(probs)
            else:
                _logger.warning(f'Unknown "{self.x}" domain value found: {x}')
                pmf.append(None)
        return pmf


def repair_attrs_from(updates_df: pd.DataFrame, base_df: pd.DataFrame,
                      row_id: str, continuous_cols: Dict[str, str]) -> pd.DataFrame:
    """Applies (row_id, attribute, repaired) updates into a table, with
    type-aware casts for continuous columns (RepairMiscApi.scala:184-247)."""
    need = {row_id, "attribute", "repaired"}
    if not need.issubset(updates_df.columns):
        from delphi_tpu.session import AnalysisException
        raise AnalysisException(
            f"Table must have '{row_id}', 'attribute', and 'repaired' columns")

    out = base_df.copy()
    row_index = pd.Index(out[row_id])
    for attr, group in updates_df.groupby("attribute"):
        if attr not in out.columns:
            continue
        pos = row_index.get_indexer(group[row_id])
        present = pos >= 0
        rows = pos[present]
        if not len(rows):
            continue
        reps = pd.Series(group["repaired"].to_numpy(dtype=object)[present],
                         dtype=object)
        non_null = reps.notna().to_numpy()
        if attr in continuous_cols and non_null.any():
            conv = reps[non_null].astype(float)
            if continuous_cols[attr] == KIND_INTEGRAL:
                conv = conv.round().astype("int64")
            reps = reps.copy()
            reps[non_null] = conv.astype(object)
        values = reps.to_numpy(dtype=object)
        col = out[attr].copy()
        if pd.api.types.is_integer_dtype(col.dtype) and not non_null.all():
            col = col.astype("float64")
        elif pd.api.types.is_integer_dtype(col.dtype):
            values = pd.Series(values).astype("int64").to_numpy()
        # assign as a list: pandas accepts elementwise coercion for lists
        # where it rejects whole object-dtype arrays
        col.iloc[rows] = list(values)
        out[attr] = col
    return out


class RepairModel:
    """Fluent repair-model builder (reference model.py:103-1537)."""

    _option = namedtuple("_option", "key default_value type_class validator err_msg")

    _opt_max_training_row_num = \
        _option("model.max_training_row_num", 10000, int,
                lambda v: v >= 10, "`{}` should be greater than and equal to 10")
    _opt_max_training_column_num = \
        _option("model.max_training_column_num", 65536, int,
                lambda v: v >= 2, "`{}` should be greater than 1")
    _opt_small_domain_threshold = \
        _option("model.small_domain_threshold", 12, int,
                lambda v: v >= 3, "`{}` should be greater than 2")
    _opt_repair_by_regex_disabled = \
        _option("model.rule.repair_by_regex.disabled", True, bool, None, None)
    _opt_repair_by_nearest_values_disabled = \
        _option("model.rule.repair_by_nearest_values.disabled", True, bool, None, None)
    _opt_merge_threshold = \
        _option("model.rule.merge_threshold", 2.0, float, None, None)
    _opt_repair_by_functional_deps_disabled = \
        _option("model.rule.repair_by_functional_deps.disabled", False, bool, None, None)
    _opt_max_domain_size = \
        _option("model.rule.max_domain_size", 1000, int,
                lambda v: v > 10, "`{}` should be greater than 10")
    _opt_cost_weight = \
        _option("repair.pmf.cost_weight", 0.1, float,
                lambda v: v > 0.0, "`{}` should be positive")
    _opt_prob_threshold = \
        _option("repair.pmf.prob_threshold", 0.0, float, None, None)
    _opt_prob_top_k = \
        _option("repair.pmf.prob_top_k", 32, int,
                lambda v: v >= 3, "`{}` should be greater than 2")
    _opt_checkpoint_path = \
        _option("model.checkpoint_path", "", str, None, None)
    _opt_snapshot_dir = \
        _option("repair.snapshot.dir", "", str, None, None)
    _opt_incremental = \
        _option("repair.incremental", False, bool, None, None)
    _opt_escalate = \
        _option("repair.escalate", False, bool, None, None)
    _opt_escalate_conf = \
        _option("repair.escalate.conf", 0.5, float,
                lambda v: 0.0 <= v <= 1.0, "`{}` should be in [0.0, 1.0]")
    _opt_escalate_budget = \
        _option("repair.escalate.budget", 256, int,
                lambda v: v >= 0, "`{}` should be greater than or equal to 0")
    _opt_escalate_iters = \
        _option("repair.escalate.iters", 8, int,
                lambda v: v >= 1, "`{}` should be greater than 0")
    _opt_escalate_adapter = \
        _option("repair.escalate.adapter", "", str, None, None)

    option_keys = set([
        _opt_max_training_row_num.key,
        _opt_max_training_column_num.key,
        _opt_small_domain_threshold.key,
        _opt_repair_by_regex_disabled.key,
        _opt_repair_by_nearest_values_disabled.key,
        _opt_merge_threshold.key,
        _opt_repair_by_functional_deps_disabled.key,
        _opt_max_domain_size.key,
        _opt_cost_weight.key,
        _opt_prob_threshold.key,
        _opt_prob_top_k.key,
        _opt_checkpoint_path.key,
        _opt_snapshot_dir.key,
        _opt_incremental.key,
        _opt_escalate.key,
        _opt_escalate_conf.key,
        _opt_escalate_budget.key,
        _opt_escalate_iters.key,
        _opt_escalate_adapter.key,
        *ErrorModel.option_keys,
        *train_option_keys])

    def __init__(self) -> None:
        super().__init__()
        self.db_name: str = ""
        self.input: Optional[Union[str, pd.DataFrame]] = None
        self.row_id: Optional[str] = None
        self.targets: List[str] = []

        self.error_cells: Optional[Union[str, pd.DataFrame]] = None
        self.error_detectors: List[ErrorDetector] = []
        self.discrete_thres: int = 80

        self.parallel_stat_training_enabled: bool = False
        self.training_data_rebalancing_enabled: bool = False
        self.repair_by_rules: bool = False

        self.repair_delta: Optional[int] = None
        self.repair_validation_enabled: bool = False

        self.cf: Optional[UpdateCostFunction] = None
        self.opts: Dict[str, str] = {}

        self._session = get_session()
        self._registered_views: List[str] = []

    # -- fluent setters ------------------------------------------------------

    @argtype_check  # type: ignore
    def setDbName(self, db_name: str) -> "RepairModel":
        """Sets the database prefix used to qualify ``table_name``
        inputs (reference model.py:236-252). Incompatible with DataFrame
        inputs.

        :param db_name: database name (e.g. ``"default"``).
        """
        if type(self.input) is pd.DataFrame:
            raise ValueError("Can not specify a database name when input is `DataFrame`")
        self.db_name = db_name
        return self

    @argtype_check  # type: ignore
    def setTableName(self, table_name: str) -> "RepairModel":
        """Sets the input by registered table/view name
        (reference model.py:254-268).

        :param table_name: name registered in the session catalog.
        """
        if not table_name:
            raise ValueError("`table_name` should have at least character")
        self.input = table_name
        return self

    @argtype_check  # type: ignore
    def setInput(self, input: Union[str, pd.DataFrame]) -> "RepairModel":
        """Sets the input table: either a registered table/view name
        or a pandas DataFrame (reference model.py:270-288).

        :param input: table name or DataFrame holding the dirty data.
        """
        if type(input) is str:
            self.setTableName(input)
        else:
            self.db_name = ""
            self.input = input
        return self

    @argtype_check  # type: ignore
    def setRowId(self, row_id: str) -> "RepairModel":
        """Names the column holding the unique row identifier
        (reference model.py:290-304). Required before ``run()``.

        :param row_id: row-id column name (must be unique per row).
        """
        if not row_id:
            raise ValueError("`row_id` should have at least character")
        self.row_id = row_id
        return self

    @argtype_check  # type: ignore
    def setTargets(self, attrs: List[str]) -> "RepairModel":
        """Restricts detection/repair to the given attributes
        (reference model.py:306-320); all discretizable attributes are
        candidates by default.

        :param attrs: non-empty list of attribute names.
        """
        if len(attrs) == 0:
            raise ValueError("`attrs` should have at least one attribute")
        self.targets = attrs
        return self

    @argtype_check  # type: ignore
    def setErrorCells(self, error_cells: Union[str, pd.DataFrame]) -> "RepairModel":
        """Supplies ground-truth error cells — a table/DataFrame
        with ``(row_id, attribute)`` columns — skipping the error-detection
        phase's detectors (reference model.py:322-352). ``setRowId`` must be
        called first.

        :param error_cells: table name or DataFrame of known error cells.
        """
        if type(error_cells) is str and not error_cells:
            raise ValueError("`error_cells` should have at least character")
        if self.row_id is None:
            raise ValueError("`setRowId` should be called before specifying error cells")
        df = error_cells if type(error_cells) is pd.DataFrame \
            else self._session.table(str(error_cells))
        if not all(c in df.columns for c in [self._row_id, "attribute"]):
            raise ValueError(
                f"Error cells should have `{self.row_id}` and `attribute` in columns")
        self.error_cells = error_cells
        return self

    @argtype_check  # type: ignore
    def setErrorDetectors(self, detectors: List[ErrorDetector]) -> "RepairModel":
        """Sets the detectors that propose noisy cells in
        phase 1 (reference model.py:354-372): ``NullErrorDetector``,
        ``DomainValues``, ``RegExErrorDetector``, ``ConstraintErrorDetector``,
        outlier detectors, or custom ``ScikitLearnBackedErrorDetector``.

        :param detectors: list of :class:`ErrorDetector` instances.
        """
        self.error_detectors = detectors
        return self

    @argtype_check  # type: ignore
    def setDiscreteThreshold(self, thres: int) -> "RepairModel":
        """Sets the max domain size for an attribute to
        stay discrete; continuous attributes equi-width bin into this many
        buckets (reference model.py:374-388, RepairApi.scala:126-149).

        :param thres: threshold in ``[2, 65536)`` (default 80).
        """
        if int(thres) < 2:
            raise ValueError(f"`thres` should be bigger than 1, got {thres}")
        self.discrete_thres = thres
        return self

    @argtype_check  # type: ignore
    def setParallelStatTrainingEnabled(self, enabled: bool) -> "RepairModel":
        """Selects BATCHED multi-target training for
        phase 2 — the TPU-native analog of the reference's parallel
        pandas-UDF fan-out (reference model.py:383-395): every pending
        target's CV search and final fit stack into shared vmapped device
        launches (see :func:`delphi_tpu.train.build_models_batched`)
        instead of running one target at a time. Accelerator backends take
        the batched path by default; this flag opts the CPU backend in too
        (``DELPHI_BATCH_TRAIN=1/0`` force-overrides either way).

        :param enabled: ``True`` to batch per-attribute training.
        """
        self.parallel_stat_training_enabled = enabled
        return self

    @argtype_check  # type: ignore
    def setTrainingDataRebalancingEnabled(self, enabled: bool) -> "RepairModel":
        """Enables class rebalancing of
        training rows toward the median class size before fitting
        classifiers (reference model.py:397-409, train.py:242-293).

        :param enabled: ``True`` to oversample/undersample per class.
        """
        self.training_data_rebalancing_enabled = enabled
        return self

    @argtype_check  # type: ignore
    def setRepairByRules(self, enabled: bool) -> "RepairModel":
        """Enables rule-based repairs before model training:
        regex structure repair, nearest-value merging (with a cost
        function), and functional-dependency rules (reference
        model.py:411-427). Fine-grained control via the
        ``model.rule.*`` options.

        :param enabled: ``True`` to try rule repairs first.
        """
        self.repair_by_rules = enabled
        return self

    @argtype_check  # type: ignore
    def setRepairDelta(self, delta: int) -> "RepairModel":
        """Caps how many repairs the maximal-likelihood mode
        keeps: the ``delta`` highest-scoring updates win (reference
        model.py:429-443).

        :param delta: positive number of updates to apply.
        """
        if delta <= 0:
            raise ValueError(f"Repair delta should be positive, got {delta}")
        self.repair_delta = int(delta)
        return self

    @argtype_check  # type: ignore
    def setUpdateCostFunction(self, cf: UpdateCostFunction) -> "RepairModel":
        """Sets the cost of changing value x into y,
        used to weight PMFs and maximal-likelihood scores (reference
        model.py:445-462): :class:`Levenshtein` or a
        :class:`UserDefinedUpdateCostFunction`.

        :param cf: an :class:`UpdateCostFunction` instance.
        """
        self.cf = cf
        return self

    @argtype_check  # type: ignore
    def option(self, key: str, value: str) -> "RepairModel":
        """Sets one expert option by key (reference model.py:478-496),
        validated against the registered ``model.*`` / ``error.*`` /
        ``repair.*`` keys; invalid keys raise, invalid values warn (or
        raise under testing).

        :param key: option name (e.g. ``"model.max_training_row_num"``).
        :param value: option value as a string.
        """
        if key not in self.option_keys:
            raise ValueError(f"Non-existent key specified: key={key}")
        self.opts[key] = value
        return self

    # -- internal helpers ----------------------------------------------------

    def _get_option_value(self, *args) -> Any:  # type: ignore
        return get_option_value(self.opts, *args)

    @property
    def _row_id(self) -> str:
        return str(self.row_id)

    @property
    def _input_frame(self) -> Tuple[pd.DataFrame, str]:
        if type(self.input) is pd.DataFrame:
            return self.input, "input"
        name = self._session.qualified_name(self.db_name, str(self.input))
        return self._session.table(name), name

    @property
    def _error_cells_frame(self) -> Optional[pd.DataFrame]:
        if self.error_cells is None:
            return None
        df = self.error_cells if type(self.error_cells) is pd.DataFrame \
            else self._session.table(str(self.error_cells))
        return df[[self._row_id, "attribute"]]

    @property
    def _repair_by_regex_enabled(self) -> bool:
        return not bool(self._get_option_value(*self._opt_repair_by_regex_disabled)) \
            and self.repair_by_rules

    @property
    def _repair_by_nearest_values_enabled(self) -> bool:
        return not bool(self._get_option_value(*self._opt_repair_by_nearest_values_disabled)) \
            and self.repair_by_rules and self.cf is not None

    @property
    def _repair_by_functional_deps_enabled(self) -> bool:
        return not bool(self._get_option_value(*self._opt_repair_by_functional_deps_disabled)) \
            and self.repair_by_rules

    def _filter_columns_from(self, df: pd.DataFrame, targets: List[str],
                             negate: bool = False) -> pd.DataFrame:
        mask = df["attribute"].isin(targets)
        return df[~mask if negate else mask].reset_index(drop=True)

    # -- phase 1: error detection --------------------------------------------

    def _detect_errors(self, table: EncodedTable, input_name: str,
                       continuous_columns: List[str]) -> Any:
        error_model = ErrorModel(
            row_id=self._row_id,
            targets=self.targets,
            discrete_thres=self.discrete_thres,
            error_detectors=self.error_detectors,
            error_cells=self._error_cells_frame,
            opts=self.opts)
        result = error_model.detect(table, input_name, continuous_columns)
        # keep ONLY phase 1's per-detector cell frames (stashing the whole
        # ErrorModel would pin its discretized table + freq stats through
        # phases 2-3) so the one-tuple DC repair minimization never re-runs
        # detection; the set view materializes lazily from the frames —
        # they are None unless a constraint detector ran
        self._phase1_non_constraint_frames = error_model._non_constraint_frames
        return result

    # -- phase 2 helpers: rule-based repairs ----------------------------------

    def _empty_repaired_cells_frame(self) -> pd.DataFrame:
        return pd.DataFrame(
            columns=[self._row_id, "attribute", "current_value", "repaired", ROW_IDX])

    def _repair_by_regexs(self, error_cells_df: pd.DataFrame) \
            -> Tuple[pd.DataFrame, pd.DataFrame]:
        regex_detectors = [d for d in self.error_detectors
                           if isinstance(d, RegExErrorDetector)]
        if not regex_detectors:
            return error_cells_df, self._empty_repaired_cells_frame()

        regexs = [(d.attr, d.regex) for d in regex_detectors]
        _logger.info(f"[Repairing Phase] Repairing data using regexs: {to_list_str(regexs)}")

        repaired_frames = []
        for attr, regex in regexs:
            target_cells = error_cells_df[error_cells_df["attribute"] == attr]
            if len(target_cells) == 0:
                continue
            try:
                repairer = RegexStructureRepair(regex)
            except Exception as e:
                _logger.warning(
                    f"Repairing using regex '{regex}' (attr='{attr}') failed because: {e}")
                continue
            repaired = [repairer(cv) if cv is not None else None
                        for cv in target_cells["current_value"]]
            fixed = target_cells.assign(repaired=repaired)
            fixed = fixed[fixed["repaired"].notna()]
            if len(fixed):
                repaired_frames.append(fixed)

        if not repaired_frames:
            return error_cells_df, self._empty_repaired_cells_frame()
        repaired_cells_df = pd.concat(repaired_frames, ignore_index=True)
        keys = set(zip(repaired_cells_df[self._row_id], repaired_cells_df["attribute"]))
        keep = [
            (r, a) not in keys
            for r, a in zip(error_cells_df[self._row_id], error_cells_df["attribute"])
        ]
        return error_cells_df[keep].reset_index(drop=True), repaired_cells_df

    def _repair_by_nearest_values(self, masked: EncodedTable,
                                  error_cells_df: pd.DataFrame,
                                  target_columns: List[str],
                                  integral_columns: Set[str]) \
            -> Tuple[pd.DataFrame, pd.DataFrame]:
        assert self.cf is not None
        cf_targets = self.cf.targets
        targets = [c for c in target_columns if c in cf_targets] if cf_targets \
            else target_columns
        if not targets:
            return error_cells_df, self._empty_repaired_cells_frame()

        merge_threshold = self._get_option_value(*self._opt_merge_threshold)
        # Per-target domain = the vocab entries still present after masking.
        # Vocab spellings already match the distance space: str(int(v)) for
        # integral attrs ('100', not the NULL-padded float view's '100.0'),
        # str(float(v)) for fractional, raw strings otherwise (encode_column).
        domains: Dict[str, List[str]] = {}
        for c in targets:
            col = masked.column(c)
            present = np.unique(col.codes[col.codes >= 0])
            domains[c] = [str(v) for v in col.vocab[present]]

        # One nearest-value resolution per unique (attribute, current value):
        # every duplicate dirty cell reuses it, and each resolution is one
        # batched (native) Levenshtein call over the whole domain.
        ec = error_cells_df.reset_index(drop=True)
        attrs = ec["attribute"].to_numpy(dtype=object)
        curs = ec["current_value"].to_numpy(dtype=object)
        repaired_vals = np.full(len(ec), None, dtype=object)
        resolved: Dict[Tuple[str, Any], Optional[str]] = {}
        for i in range(len(ec)):
            dvs = domains.get(attrs[i])
            cur = curs[i]
            if not dvs or cur is None:
                continue
            key = (attrs[i], cur)
            if key not in resolved:
                resolved[key] = self._nearest_value(cur, dvs, merge_threshold)
            repaired_vals[i] = resolved[key]

        mask = np.array([v is not None for v in repaired_vals], dtype=bool)
        repaired_df = ec[mask].assign(repaired=repaired_vals[mask]) \
            if mask.any() else self._empty_repaired_cells_frame()
        error_df = ec[~mask].reset_index(drop=True) if (~mask).any() \
            else error_cells_df.iloc[0:0]
        return error_df, repaired_df

    def _nearest_value(self, cur: Any, dvs: List[str],
                       merge_threshold: float) -> Optional[str]:
        """The reference's per-cell scan (model.py:583-622 analog): repair to
        the unique lowest-cost domain value when it is under the merge
        threshold and strictly beats the runner-up."""
        assert self.cf is not None
        costs = self.cf.compute_many(cur, dvs)
        if costs is None:
            return None
        scored = sorted(((c, v) for c, v in zip(costs, dvs) if c is not None))
        if len(scored) >= 2 and scored[0][0] <= merge_threshold \
                and scored[0][0] < scored[1][0]:
            return scored[0][1]
        return None

    def _repair_by_rules(self, masked: EncodedTable,
                         error_cells_df: pd.DataFrame, target_columns: List[str],
                         integral_columns: Set[str]) \
            -> Tuple[pd.DataFrame, pd.DataFrame]:
        led = active_ledger()

        def _record_rule_repairs(frame: pd.DataFrame, reason: str) -> None:
            if led is not None and len(frame):
                led.record_decisions(
                    frame[self._row_id].to_numpy(),
                    frame["attribute"].to_numpy(dtype=object),
                    _prov.DECISION_REPAIRED, reason,
                    repaired=frame["repaired"].to_numpy(dtype=object))

        repaired_dfs = [self._empty_repaired_cells_frame()]
        if self._repair_by_regex_enabled:
            error_cells_df, by_regex = self._repair_by_regexs(error_cells_df)
            _record_rule_repairs(by_regex, _prov.REASON_RULE_REGEX_STRUCTURE)
            repaired_dfs.append(by_regex)
        if self._repair_by_nearest_values_enabled:
            error_cells_df, by_nv = self._repair_by_nearest_values(
                masked, error_cells_df, target_columns, integral_columns)
            _record_rule_repairs(by_nv, _prov.REASON_RULE_NEAREST_VALUE)
            repaired_dfs.append(by_nv)
        repaired_by_rules = pd.concat(repaired_dfs, ignore_index=True)
        return error_cells_df, repaired_by_rules

    # -- phase 2: model training ----------------------------------------------

    def _select_features(self, pairwise_attr_stats: Dict[str, Any], y: str,
                         features: List[str]) -> List[str]:
        """Correlation-ranked feature pruning (reference model.py:677-699)."""
        # Engine-internal detail routed by `repair.logLevel` (hidden at the
        # default TRACE level, like the reference's logBasedOnLevel).
        log_based_on_level(
            lambda: f"selecting features for y={y} from candidates {features} "
            f"using pairwise stats {pairwise_attr_stats.get(y)}")
        max_cols = int(self._get_option_value(*self._opt_max_training_column_num))
        if max_cols < len(features) and y in pairwise_attr_stats:
            heap: List[Tuple[float, str]] = []
            for f, corr in map(tuple, pairwise_attr_stats[y]):
                if f in features:
                    heapq.heappush(heap, (float(corr), f))
            fts = [heapq.heappop(heap) for _ in range(len(heap))]
            top_k: List[Tuple[float, str]] = []
            for corr, f in fts:
                if len(top_k) <= 1 or (float(corr) >= 0.0 and len(top_k) < max_cols):
                    top_k.append((float(corr), f))
            if not top_k:
                # No rankable pairwise stats for y (candidate-pair pruning can
                # drop every pair on small/low-correlation data) — selection
                # cannot rank, so take the first max_cols features instead of
                # training a featureless model; the user's column cap holds.
                _logger.info(
                    "[Repair Model Training Phase] no pairwise stats for {}; "
                    "keeping the first {} of {} features".format(
                        y, min(max_cols, len(features)), len(features)))
                return features[:max_cols]
            _logger.info(
                "[Repair Model Training Phase] {} features ({}) selected from {} "
                "features".format(
                    len(top_k), to_list_str([f"{f}:{c}" for c, f in top_k]),
                    len(features)))
            features = [f for _, f in top_k]
        return features

    @staticmethod
    def _encode_features(transformers: List[Any], X: Any,
                         fit: bool = False, compact: bool = True) -> Any:
        """Runs the feature transformers, routing FeatureEncoder through the
        factored one-hot design (the linear heads' gather path) unless the
        caller needs a dense, row-indexable matrix (``compact=False``, e.g.
        rebalancing). The single dispatch point keeps the train- and
        predict-side encodings in lockstep."""
        for t in transformers:
            use_compact = compact and isinstance(t, FeatureEncoder)
            if fit:
                X = t.fit_transform_compact(X) if use_compact \
                    else t.fit_transform(X)
            else:
                X = t.transform_compact(X) if use_compact else t.transform(X)
        return X

    def _create_transformers(self, domain_stats: Dict[str, Any],
                             features: List[str],
                             continuous_columns: List[str],
                             is_discrete: bool = True,
                             num_class: int = 0) -> List[Any]:
        from delphi_tpu.models.encoding import OrdinalEncoder
        from delphi_tpu.models.gbdt import gbdt_supported
        if gbdt_supported(is_discrete, num_class):
            # tree models consume ordinal codes + raw continuous values,
            # like the reference's ce.OrdinalEncoder -> LightGBM path
            return [OrdinalEncoder(features, continuous_columns)]
        return [FeatureEncoder(features, continuous_columns)]

    def _get_functional_deps(self, column_names: List[str],
                             target_columns: List[str]) \
            -> Optional[Dict[str, List[str]]]:
        constraint_detectors = [d for d in self.error_detectors
                                if isinstance(d, ConstraintErrorDetector)]
        if len(constraint_detectors) == 1:
            ced = constraint_detectors[0]
            constraint_targets = [c for c in target_columns if c in ced.targets] \
                if ced.targets else target_columns
            return compute_functional_deps(
                pd.DataFrame(columns=column_names), ced.constraint_path,
                ced.constraints, constraint_targets)
        elif len(constraint_detectors) > 1:
            _logger.warning(
                "Multiple constraint classes not supported for detecting functional deps")
            return None
        return None

    def _sample_training_positions(self, positions: np.ndarray) -> np.ndarray:
        """Downsamples the candidate row positions to the training-row cap.

        `pd.Series(positions).sample(...)` makes the same positional draw the
        old full-frame `df.sample(...)` made (pandas samples on axis length
        alone), so the selected rows — and their order — are identical to
        sampling the materialized frame."""
        training_data_num = len(positions)
        max_rows = int(self._get_option_value(*self._opt_max_training_row_num))
        if training_data_num > max_rows:
            ratio = float(max_rows) / training_data_num
            _logger.info(
                f"To reduce training data, extracts {ratio * 100.0}% samples "
                f"from {training_data_num} rows")
            return pd.Series(positions).sample(
                frac=ratio, random_state=42).to_numpy()
        return positions

    def _prepare_training_task(self, y: str, masked: EncodedTable,
                               float_cols: Tuple[str, ...],
                               continuous_columns: List[str],
                               feature_map: Dict[str, List[str]],
                               transformer_map: Dict[str, List[Any]]) \
            -> Optional[Tuple[Any, Any, int]]:
        """Host-side training-set assembly for one target: sample to the
        row cap, decode only the sample to pandas, fit-encode features,
        optionally rebalance. Returns (X, y_series, n_rows) or None when
        the target has no clean rows."""
        y_codes = masked.column(y).codes
        valid_pos = np.flatnonzero(y_codes >= 0)
        if len(valid_pos) == 0:
            return None
        sel_pos = self._sample_training_positions(valid_pos)
        train_pdf = masked.to_pandas(
            rows=sel_pos, columns=list(feature_map[y]) + [y],
            integral_as_float=float_cols)
        is_discrete = y not in continuous_columns
        X, y_ = self._encode_training_frame(
            y, train_pdf, is_discrete, feature_map, transformer_map)
        return X, y_, len(train_pdf)

    def _encode_training_frame(self, y: str, train_pdf: pd.DataFrame,
                               is_discrete: bool,
                               feature_map: Dict[str, List[str]],
                               transformer_map: Dict[str, List[Any]]) \
            -> Tuple[Any, Any]:
        """Fit-encodes a decoded training frame (+ optional rebalancing) —
        shared by the local and the process-local (gathered-frame) training
        paths so the encoding semantics cannot drift apart."""
        # linear-head targets train from the factored one-hot design —
        # gathers instead of dense-width matmuls (rebalancing needs row
        # indexing, so it keeps dense)
        X: Any = self._encode_features(
            transformer_map[y], train_pdf[feature_map[y]], fit=True,
            compact=not (is_discrete
                         and self.training_data_rebalancing_enabled))
        if is_discrete and self.training_data_rebalancing_enabled:
            return rebalance_training_data(X, train_pdf[y], y)
        return X, train_pdf[y]

    def _use_batched_training(self, n_pending: int) -> bool:
        """Whether phase 2 trains its targets through the BATCHED path
        (`train.build_models_batched`): multi-target CV searches and final
        fits stack into shared vmapped launches — the TPU-native analog of
        the reference's parallel pandas-UDF fan-out (model.py:817-926).
        Selected by ``setParallelStatTrainingEnabled(True)``, and by
        default on accelerator backends, where N small sequential fits are
        exactly the launch-bound profile that leaves the device idle; the
        CPU backend defaults to the sequential path (same total FLOPs, and
        the batched group fit pays for the group's max round budget).
        ``DELPHI_BATCH_TRAIN=1/0`` forces the choice."""
        import os
        setting = os.environ.get("DELPHI_BATCH_TRAIN", "auto")
        if setting == "1":
            return True
        if setting == "0":
            return False
        if n_pending <= 1:
            return False
        if self.parallel_stat_training_enabled:
            return True
        import jax
        return jax.default_backend() != "cpu"

    def _build_repair_stat_models(
            self, models: Dict[str, Any], masked: EncodedTable,
            float_cols: Tuple[str, ...],
            target_columns: List[str], continuous_columns: List[str],
            num_class_map: Dict[str, int],
            feature_map: Dict[str, List[str]],
            transformer_map: Dict[str, List[Any]]) -> Dict[str, Any]:
        """Builds the remaining per-attribute stat models. Two routes
        (selection: `_use_batched_training`): the batched path trains every
        target's CV search and final fit in shared vmapped device launches
        (reference's parallel fan-out, model.py:817-926); the sequential
        path fits one target at a time. Training rows decode lazily either
        way: only the (capped) per-target sample ever materializes to
        pandas."""
        pending = [c for c in target_columns if c not in models]

        if masked.process_local:
            return self._build_stat_models_sharded(
                models, masked, float_cols, target_columns,
                continuous_columns, num_class_map, feature_map,
                transformer_map, pending)

        if self._use_batched_training(len(pending)):
            tasks = []
            for y in pending:
                # progress index counts prior models AND queued tasks, so
                # the Building/Skipping lines stay monotonic like the
                # sequential branch's
                index = len(models) + len(tasks) + 1
                prep = self._prepare_training_task(
                    y, masked, float_cols, continuous_columns, feature_map,
                    transformer_map)
                if prep is None:
                    _logger.info(
                        "Skipping {}/{} model... type=classfier y={} "
                        "num_class={}".format(index, len(target_columns), y,
                                              num_class_map[y]))
                    models[y] = (PoorModel(None), feature_map[y], None)
                    continue
                X, y_, n_rows = prep
                is_discrete = y not in continuous_columns
                _logger.info(
                    "Building {}/{} model... type={} y={} features={} "
                    "#rows={}{}".format(
                        index, len(target_columns),
                        "classfier" if is_discrete else "regressor", y,
                        to_list_str(feature_map[y]), n_rows,
                        f" #class={num_class_map[y]}"
                        if num_class_map[y] > 0 else ""))
                tasks.append((y, X, y_, is_discrete, num_class_map[y]))
            if tasks:
                from delphi_tpu.train import build_models_batched
                _logger.info(
                    f"Training {len(tasks)} models in batched device "
                    "launches...")
                out = build_models_batched(tasks, self.opts)
                for y, X, y_, is_discrete, num_class in tasks:
                    (model, score), elapsed = out[y]
                    if model is None:
                        model = PoorModel(None)
                    _logger.info(
                        f"Finishes building '{y}' model...  score={score} "
                        f"elapsed={elapsed}s")
                    models[y] = (model, feature_map[y], transformer_map[y])
            return models

        def _prep_target(y: str) -> Any:
            # host featurization only (lazy decode + fit-encode); under the
            # pipeline this overlaps the previous target's device training
            return self._prepare_training_task(
                y, masked, float_cols, continuous_columns, feature_map,
                transformer_map)

        def _train_target(y: str, prep: Any) -> None:
            # runs in target order on the calling thread: progress logs and
            # the models dict mutate exactly as the sequential loop's
            index = len(models) + 1
            if prep is None:
                _logger.info(
                    "Skipping {}/{} model... type=classfier y={} num_class={}".format(
                        index, len(target_columns), y, num_class_map[y]))
                models[y] = (PoorModel(None), feature_map[y], None)
                return
            X, y_, n_rows = prep
            is_discrete = y not in continuous_columns
            model_type = "classfier" if is_discrete else "regressor"
            _logger.info(
                "Building {}/{} model... type={} y={} features={} #rows={}{}".format(
                    index, len(target_columns), model_type, y,
                    to_list_str(feature_map[y]), n_rows,
                    f" #class={num_class_map[y]}" if num_class_map[y] > 0 else ""))
            (model, score), elapsed = build_model(
                X, y_, is_discrete, num_class_map[y], n_jobs=-1, opts=self.opts)
            if model is None:
                model = PoorModel(None)
            _logger.info(
                f"Finishes building '{y}' model...  score={score} elapsed={elapsed}s")
            models[y] = (model, feature_map[y], transformer_map[y])

        from delphi_tpu.parallel.pipeline import run_pipelined
        run_pipelined(pending, _prep_target, _train_target)
        return models

    def _build_stat_models_sharded(
            self, models: Dict[str, Any], masked: EncodedTable,
            float_cols: Tuple[str, ...], target_columns: List[str],
            continuous_columns: List[str], num_class_map: Dict[str, int],
            feature_map: Dict[str, List[str]],
            transformer_map: Dict[str, List[Any]],
            pending: List[str]) -> Dict[str, Any]:
        """Phase-2 training for PROCESS-LOCAL shards — the multi-host form
        of the reference's task-parallel pandas-UDF fan-out
        (model.py:817-926): for every pending target, each process
        contributes its shard's (capped) training sample through an
        all-gather; targets then train round-robin across processes off the
        identical gathered frames, and the fitted models all-gather back so
        every process can repair its own dirty rows. No process ever holds
        more than the capped samples (max_training_row_num x P rows per
        target in flight)."""
        import jax

        from delphi_tpu.parallel.distributed import allgather_pickled

        rank, world = jax.process_index(), jax.process_count()
        max_rows = int(self._get_option_value(*self._opt_max_training_row_num))
        own: Dict[str, Any] = {}
        for i, y in enumerate(pending):
            index = len(models) + i + 1
            # local sample; EVERY rank participates in the gather (the
            # collective sequence must match across shards), zero-row
            # shards contribute an empty frame
            y_codes = masked.column(y).codes
            valid_pos = np.flatnonzero(y_codes >= 0)
            sel_pos = self._sample_training_positions(valid_pos) \
                if len(valid_pos) else valid_pos
            local_pdf = masked.to_pandas(
                rows=sel_pos, columns=list(feature_map[y]) + [y],
                integral_as_float=float_cols)
            train_pdf = pd.concat(allgather_pickled(local_pdf),
                                  ignore_index=True)
            if len(train_pdf) > max_rows:
                # deterministic global re-cap (every process computes the
                # same draw over the identical gathered frame)
                train_pdf = train_pdf.sample(
                    frac=float(max_rows) / len(train_pdf),
                    random_state=42).reset_index(drop=True)
            if len(train_pdf) == 0:
                _logger.info(
                    "Skipping {}/{} model... type=classfier y={} "
                    "num_class={}".format(index, len(target_columns), y,
                                          num_class_map[y]))
                models[y] = (PoorModel(None), feature_map[y], None)
                continue
            if i % world != rank:
                continue  # another process owns this target's fit
            is_discrete = y not in continuous_columns
            X, y_ = self._encode_training_frame(
                y, train_pdf, is_discrete, feature_map, transformer_map)
            _logger.info(
                "Building {}/{} model... type={} y={} features={} "
                "#rows={}{}".format(
                    index, len(target_columns),
                    "classfier" if is_discrete else "regressor", y,
                    to_list_str(feature_map[y]), len(train_pdf),
                    f" #class={num_class_map[y]}"
                    if num_class_map[y] > 0 else ""))
            (model, score), elapsed = build_model(
                X, y_, is_discrete, num_class_map[y], n_jobs=-1,
                opts=self.opts)
            if model is None:
                model = PoorModel(None)
            _logger.info(
                f"Finishes building '{y}' model...  score={score} "
                f"elapsed={elapsed}s")
            own[y] = (model, feature_map[y], transformer_map[y])

        # one all-gather distributes every process's fitted models
        for part in allgather_pickled(own):
            models.update(part)
        assert len(models) == len(target_columns), \
            (sorted(models), target_columns)
        return models

    def _resolve_prediction_order(self, models: Dict[str, Any],
                                  target_columns: List[str]) -> List[Any]:
        """Orders FD models after the attributes they depend on
        (reference model.py:928-953)."""
        pred_ordered_models = []
        error_columns = copy.deepcopy(target_columns)

        for y in target_columns:
            (model, x, transformers) = models[y]
            if not isinstance(model, FunctionalDepModel):
                pred_ordered_models.append((y, models[y]))
                error_columns.remove(y)

        while len(error_columns) > 0:
            columns = copy.deepcopy(error_columns)
            for y in columns:
                (model, x, transformers) = models[y]
                if x[0] not in error_columns:
                    pred_ordered_models.append((y, models[y]))
                    error_columns.remove(y)
            assert len(error_columns) < len(columns)

        _logger.info("Resolved prediction order dependencies: {}".format(
            to_list_str([x[0] for x in pred_ordered_models])))
        assert len(pred_ordered_models) == len(target_columns)
        return pred_ordered_models

    @job_phase(name="repair model training")
    def _build_repair_models(self, masked: EncodedTable,
                             float_cols: Tuple[str, ...],
                             target_columns: List[str],
                             continuous_columns: List[str],
                             domain_stats: Dict[str, Any],
                             pairwise_attr_stats: Dict[str, Any]) -> List[Any]:
        # SCARE-style (see reference model.py:959-984): train per-attribute
        # conditional models P(e_y | clean attrs) on rows whose y is clean;
        # FD rules substitute for training where a clean attribute determines y.
        # Works off the encoded int32 table: class counts and NULL masks come
        # from the code arrays, and only FD inputs + capped training samples
        # ever decode to pandas.
        train_columns = masked.column_names

        functional_deps = self._get_functional_deps(train_columns, target_columns) \
            if self._repair_by_functional_deps_enabled else None
        if functional_deps and masked.process_local:
            # an FD rule's value map would come from THIS shard's pairs
            # only — different maps on different processes. Stat models
            # (trained on the gathered global sample) repair those targets
            # instead.
            _logger.info(
                "Functional-dep rule models are disabled on process-local "
                "shards; their targets train stat models")
            functional_deps = None
        if functional_deps:
            _logger.info(f"Functional deps found: {functional_deps}")

        _logger.info(
            "[Repair Model Training Phase] Building {} models to repair the cells "
            "in {}".format(len(target_columns), to_list_str(target_columns)))

        models: Dict[str, Any] = {}
        num_class_map: Dict[str, int] = {}
        # the incremental executor pre-seeds frozen models for attributes the
        # drift gate cleared; those targets skip class counting and training
        frozen: Dict[str, Any] = getattr(
            self, "_incremental_frozen_models", None) or {}
        for y, m in frozen.items():
            if y in target_columns:
                models[y] = m
        if models:
            _logger.info("Reusing {} frozen repair models: {}".format(
                len(models), to_list_str(sorted(models))))

        for y in target_columns:
            if y in models:
                continue
            index = len(models) + 1
            input_columns = [c for c in train_columns if c != y]
            is_discrete = y not in continuous_columns
            y_col = masked.column(y)
            y_valid = y_col.codes >= 0
            class_present = None
            if is_discrete and masked.process_local:
                # class counts are GLOBAL facts: union per-shard presence
                from delphi_tpu.parallel.distributed import allgather_any
                class_present = np.zeros(max(y_col.domain_size, 1),
                                         dtype=bool)
                class_present[np.unique(y_col.codes[y_valid])] = True
                class_present = allgather_any(class_present)
                num_class_map[y] = int(class_present.sum())
            else:
                num_class_map[y] = int(len(np.unique(y_col.codes[y_valid]))) \
                    if is_discrete else 0

            if is_discrete and num_class_map[y] <= 1:
                _logger.info(
                    "Skipping {}/{} model... type=rule y={} num_class={}".format(
                        index, len(target_columns), y, num_class_map[y]))
                v = None
                if num_class_map[y] == 1:
                    if class_present is not None:
                        v = y_col.vocab[int(np.argmax(class_present))]
                    elif bool(y_valid.any()):
                        v = y_col.vocab[y_col.codes[int(np.argmax(y_valid))]]
                models[y] = (PoorModel(v), input_columns, None)

            if y not in models and functional_deps is not None and y in functional_deps:
                max_domain = int(self._get_option_value(*self._opt_max_domain_size))
                fx = [x for x in functional_deps[y]
                      if int(domain_stats[x]) < max_domain]
                if len(fx) > 0:
                    fd_frame = masked.to_pandas(
                        columns=[fx[0], y], integral_as_float=float_cols)
                    fd_map = compute_functional_dep_map(fd_frame, fx[0], y)
                    # Coverage guard (improvement over the reference, whose
                    # FunctionalDepModel returns None — an unrepairable cell —
                    # for every x value absent from the map, model.py:86-87):
                    # when masking left too many x groups without a surviving
                    # y (so the map covers few rows), a trained stat model
                    # repairs those cells instead of giving up on them.
                    x_vals = fd_frame[fx[0]].dropna().astype(str)
                    coverage = float(x_vals.isin(fd_map.keys()).mean()) \
                        if len(x_vals) else 0.0
                    if coverage >= 0.8:
                        _logger.info(
                            "Building {}/{} model... type=rule(FD: X->y)  y={}(|y|={}) "
                            "X={}(|X|={})".format(
                                index, len(target_columns), y, num_class_map[y],
                                fx[0], domain_stats[fx[0]]))
                        models[y] = (FunctionalDepModel(fx[0], fd_map), [fx[0]], None)
                    else:
                        _logger.info(
                            f"Skipping FD rule for y={y} (X={fx[0]} covers only "
                            f"{coverage:.0%} of rows); falling back to a stat model")

        if len(models) != len(target_columns):
            feature_map: Dict[str, List[str]] = {}
            transformer_map: Dict[str, List[Any]] = {}
            for y in [c for c in target_columns if c not in models]:
                input_columns = [c for c in train_columns if c != y]
                features = self._select_features(pairwise_attr_stats, y, input_columns)
                feature_map[y] = features
                transformer_map[y] = self._create_transformers(
                    domain_stats, features, continuous_columns,
                    is_discrete=y not in continuous_columns,
                    num_class=num_class_map[y])
            models = self._build_repair_stat_models(
                models, masked, float_cols, target_columns, continuous_columns,
                num_class_map, feature_map, transformer_map)

        assert len(models) == len(target_columns)

        if any(isinstance(m, FunctionalDepModel) for m, _, _ in models.values()):
            return self._resolve_prediction_order(models, target_columns)
        return list(models.items())

    # -- phase 3: repair -------------------------------------------------------

    @job_phase(name="repairing")
    def _repair(self, models: List[Any], continuous_columns: List[str],
                dirty_rows_df: pd.DataFrame, error_cells_df: pd.DataFrame,
                compute_repair_candidate_prob: bool,
                maximal_likelihood_repair: bool) -> pd.DataFrame:
        """Batched repair inference: for each model (in dependency order)
        predict the NULL cells of its target column over the whole dirty-row
        block at once (replaces the grouped-map repair UDF,
        reference model.py:1062-1143)."""
        _logger.info(
            f"[Repairing Phase] Computing {len(error_cells_df)} repair updates in "
            f"{len(dirty_rows_df)} rows...")

        integral_columns = {
            c: True for c in dirty_rows_df.columns
            if pd.api.types.is_integer_dtype(dirty_rows_df[c].dtype)}
        need_pmf = compute_repair_candidate_prob or maximal_likelihood_repair

        led = active_ledger()
        pdf = dirty_rows_df.reset_index(drop=True).copy()
        for y, (model, features, transformers) in models:
            missing = pdf[y].isna()
            miss_idx = np.nonzero(missing.to_numpy())[0]
            if len(miss_idx) == 0:
                continue
            miss_rids = pdf[self._row_id].to_numpy()[miss_idx] \
                if led is not None else None

            # Inference only over the rows whose y cell actually needs a
            # repair — the clean cells of the dirty block keep their values.
            X: Any = pdf[features].iloc[miss_idx]
            if transformers:
                X = self._encode_features(transformers, X)

            if need_pmf and y not in continuous_columns:
                predicted = model.predict_proba(X)
                classes_str = [str(c) for c in model.classes_.tolist()]
                if led is not None:
                    led.record_posterior(y, miss_rids, classes_str,
                                         np.asarray(predicted,
                                                    dtype=np.float64),
                                         domain_size=len(classes_str))

                def _to_pmf(probs: Any) -> Dict[str, Any]:
                    if probs is None:
                        return {"classes": [], "probs": []}
                    return {"classes": classes_str,
                            "probs": np.asarray(probs, dtype=np.float64)}

                filled = pdf[y].astype(object)
                filled.iloc[miss_idx] = [_to_pmf(p) for p in predicted]
                pdf[y] = filled
            else:
                predicted = np.asarray(model.predict(X))
                if led is not None:
                    # ledger-only posterior: the plain prediction path never
                    # calls predict_proba, so the top-k comes from an extra
                    # launch (an opt-in cost, paid only with the flag set);
                    # models without predict_proba record a degenerate top-1
                    try:
                        if y not in continuous_columns \
                                and hasattr(model, "predict_proba") \
                                and hasattr(model, "classes_"):
                            led.record_posterior(
                                y, miss_rids,
                                [str(c) for c in model.classes_.tolist()],
                                np.asarray(model.predict_proba(X),
                                           dtype=np.float64),
                                domain_size=len(model.classes_))
                        else:
                            led.record_point_predictions(y, miss_rids,
                                                         predicted)
                    except Exception:
                        led.record_point_predictions(y, miss_rids, predicted)
                if y in integral_columns:
                    vals = np.round(pd.to_numeric(
                        pd.Series(predicted), errors="coerce").to_numpy())
                    filled = pdf[y].astype("float64")
                elif pd.api.types.is_float_dtype(pdf[y].dtype):
                    vals = pd.to_numeric(
                        pd.Series(predicted), errors="coerce").to_numpy(dtype=np.float64)
                    filled = pdf[y].copy()
                else:
                    vals = predicted.astype(object)
                    filled = pdf[y].astype(object)
                filled.iloc[miss_idx] = vals
                pdf[y] = filled
        return pdf

    def _one_tuple_dc_plan(self, table: EncodedTable,
                           continuous_columns: List[str],
                           error_cells_df: pd.DataFrame) -> Optional[Dict[str, Any]]:
        """Precomputes everything the one-tuple DC minimization needs, ONCE
        per run (the chunked repair path reuses it across chunks): the
        parsed all-constant constraints, their violating rows, the flagged
        cells' current values, and the cells any NON-constraint detector
        also flagged (those repairs are never reverted — the constraint pass
        has no business undoing an outlier/regex/domain finding; the set is
        captured during phase 1, never re-detected). Returns None when
        minimization does not apply: no ConstraintErrorDetector, no
        one-tuple DCs, or user-supplied error cells (ground truth is not
        ours to second-guess)."""
        from delphi_tpu.constraints import Constant
        from delphi_tpu.ops.detect import _one_tuple_violations

        if self.error_cells is not None:
            return None
        detectors = [d for d in self.error_detectors
                     if isinstance(d, ConstraintErrorDetector)]
        if not detectors:
            return None

        one_tuple = []
        for d in detectors:
            try:
                parsed = d.parsed_constraints(table, str(self.input))
            except Exception:
                continue
            one_tuple += [preds for preds in parsed.predicates
                          if all(isinstance(p.right, Constant) for p in preds)]
        if not one_tuple:
            return None

        frames = getattr(self, "_phase1_non_constraint_frames", None)
        if frames is None:
            # detectors never ran (defensive: this path requires
            # error_cells None and a constraint detector, so phase 1 must
            # have populated the capture)
            _logger.warning(
                "Skipping one-tuple DC minimization (phase-1 detector "
                "capture unavailable)")
            return None
        protected: set = set()
        for f in frames:
            protected |= set(zip(f[ROW_IDX].astype(int), f["attribute"]))

        flagged: Dict[int, Dict[str, Any]] = {}
        for r, a, cur in zip(error_cells_df[ROW_IDX].astype(int),
                             error_cells_df["attribute"],
                             error_cells_df["current_value"]):
            flagged.setdefault(int(r), {})[a] = cur

        plans = []
        for preds in one_tuple:
            viol = np.nonzero(_one_tuple_violations(table, preds))[0]
            if viol.size:
                plans.append((preds, viol))
        if not plans:
            return None
        return {"plans": plans, "flagged": flagged, "protected": protected,
                "kinds": {c.name: c.kind for c in table.columns}}

    def _minimize_one_tuple_dc_repairs(
            self, table: EncodedTable, plan: Optional[Dict[str, Any]],
            pos: np.ndarray, repaired_rows_df: pd.DataFrame,
            models: List[Any]) -> pd.DataFrame:
        """Constraint-aware minimal repair for one-tuple denial constraints.

        A one-tuple DC (all-constant predicates, e.g. Sex=Female &
        Relationship=Husband) flags EVERY referenced attribute of a violating
        row, and the models then repair each flagged cell independently —
        even though changing any ONE of them already satisfies the
        constraint. When several flagged cells of a row would individually
        satisfy the DC, keep only the repair the models are most confident
        in and revert the others to their (non-NULL) current values: the
        minimal-change repair HoloClean-style systems aim for. Cells the
        constraint still needs, cells with NULL currents, and cells another
        detector flagged keep their repairs; rows where model confidence is
        unavailable for every option are left untouched."""
        if plan is None or not len(repaired_rows_df):
            return repaired_rows_df

        flagged = plan["flagged"]
        protected = plan["protected"]
        kinds = plan["kinds"]
        pos_index = {int(p): i for i, p in enumerate(pos)}

        def spell(attr: str, value: Any) -> Optional[str]:
            """The vocab spelling of a value — what _one_tuple_violations
            compares against the literal (str(int)/str(float) for numeric
            kinds, the raw string otherwise)."""
            if _is_null(value):
                return None
            kind = kinds.get(attr)
            try:
                if kind == KIND_INTEGRAL:
                    return str(int(float(value)))
                if kind == KIND_FRACTIONAL:
                    return str(float(value))
            except (TypeError, ValueError):
                pass
            return str(value)

        def pred_holds(p: Any, attr: str, value: Any) -> bool:
            s = spell(attr, value)
            lit = p.right.literal
            if s is None:
                # NULL <=> const is false; NOT(...) true; orders false
                return p.sign == "IQ"
            if p.sign == "EQ":
                return s == lit
            if p.sign == "IQ":
                return s != lit
            if kinds.get(attr) in (KIND_INTEGRAL, KIND_FRACTIONAL):
                try:
                    lv, rv = float(s), float(lit)
                except ValueError:
                    return False
                return lv < rv if p.sign == "LT" else lv > rv
            return s < lit if p.sign == "LT" else s > lit

        def batch_confidence(attr: str, row_is: List[int]) -> Optional[np.ndarray]:
            """P(model predicts the repaired value) for many rows in one
            predict_proba launch; None disables minimization for these rows
            (a failed confidence must not degrade into an arbitrary pick)."""
            for y, (model, features, transformers) in models:
                if y != attr:
                    continue
                try:
                    X: Any = repaired_rows_df[features].iloc[row_is]
                    if transformers:
                        X = self._encode_features(transformers, X)
                    probs = np.asarray(model.predict_proba(X))
                    classes = [str(c) for c in model.classes_.tolist()]
                    vals = [str(repaired_rows_df.at[repaired_rows_df.index[i],
                                                    attr]) for i in row_is]
                    idx = [classes.index(v) if v in classes else -1
                           for v in vals]
                    return np.asarray(
                        [probs[j, k] if k >= 0 else np.nan
                         for j, k in enumerate(idx)], dtype=np.float64)
                except Exception:
                    return None
            return None

        led = active_ledger()

        def _record_keep_all(cands: List[Any]) -> None:
            # the distinct "confidence unavailable -> keep all repairs"
            # fallback: every fixable cell of the affected rows keeps its
            # model repair, with the sticky reason explaining why no
            # minimization happened
            if led is None:
                return
            for _i, r, _row_flagged, fixable, _options in cands:
                rid = table.row_id_values[r]
                for a in fixable:
                    led.record_decision(
                        rid, a, _prov.DECISION_REPAIRED,
                        _prov.REASON_CONFIDENCE_UNAVAILABLE)

        out = repaired_rows_df
        # (frame position, attr) -> the ORIGINAL model repair, recorded the
        # first time any plan reverts that cell (later plans reverting the
        # same cell see the already-reverted value, which is not a repair) —
        # the post-pass below undoes reverts that overlapping-attribute
        # plans invalidated
        revert_log: Dict[Tuple[int, str], Any] = {}
        for plan_idx, (preds, viol_rows) in enumerate(plan["plans"]):
            dc_attrs = [a for p in preds for a in p.references]
            # only this chunk's rows (the plan's rows are global)
            in_chunk = viol_rows[np.isin(viol_rows, pos)] \
                if len(viol_rows) > len(pos_index) // 4 else \
                [r for r in viol_rows if int(r) in pos_index]
            candidates = []  # (i, row_flagged, options)
            need_conf: Dict[str, List[int]] = {}
            for r in in_chunk:
                i = pos_index.get(int(r))
                if i is None:
                    continue
                row_flagged = flagged.get(int(r), {})
                fixable = [a for a in dc_attrs
                           if a in row_flagged and a in out.columns
                           and (int(r), a) not in protected
                           and not _is_null(row_flagged[a])]
                if len(fixable) < 2:
                    continue
                fixable_set = set(fixable)

                def satisfied_by(only: str) -> bool:
                    # `only` takes its repair, other revertible flagged cells
                    # take their current values; everything else (unflagged
                    # attrs, must-keep repairs) reads the repaired frame
                    def val(a: str) -> Any:
                        if a != only and a in fixable_set:
                            return row_flagged[a]
                        return out.at[out.index[i], a]
                    return not all(pred_holds(p, p.references[0],
                                              val(p.references[0]))
                                   for p in preds)

                options = [a for a in fixable if satisfied_by(a)]
                if len(options) < 1:
                    continue
                candidates.append((i, int(r), row_flagged, fixable, options))
                for a in options:
                    need_conf.setdefault(a, []).append(i)

            conf: Dict[Tuple[str, int], float] = {}
            usable = True
            for a, row_is in need_conf.items():
                scores = batch_confidence(a, row_is)
                if scores is None:
                    usable = False
                    break
                for i, s in zip(row_is, scores):
                    conf[(a, i)] = float(s)
            if not usable:
                _record_keep_all(candidates)
                continue

            for i, r, row_flagged, fixable, options in candidates:
                scored = [(conf.get((a, i), np.nan), a) for a in options]
                if any(np.isnan(s) for s, _ in scored):
                    _record_keep_all([(i, r, row_flagged, fixable, options)])
                    continue  # confidence unavailable -> keep all repairs
                best = max(scored)[1]
                reverted = []
                for a in fixable:
                    if a != best:
                        revert_log.setdefault(
                            (i, a), out.at[out.index[i], a])
                        out.at[out.index[i], a] = row_flagged[a]
                        reverted.append(a)
                if led is not None and reverted:
                    rid = table.row_id_values[r]
                    for a in reverted:
                        led.record_decision(rid, a, _prov.DECISION_KEPT,
                                            _prov.REASON_DC_MINIMIZED)
                if reverted:
                    _logger.info(
                        "[Repairing Phase] one-tuple DC on row {}: keeping "
                        "the '{}' repair and reverting {} (constraint "
                        "satisfied by a single change)".format(
                            table.row_id_values[r], best,
                            to_list_str(reverted, quote=True)))

        # Plans apply sequentially against the mutated frame, so with two
        # DCs sharing an attribute a later plan's revert can re-violate an
        # earlier constraint (its kept repair depended on a cell the later
        # plan put back). Fixpoint pass: re-evaluate every processed
        # constraint on the FINAL row state; a still-violated constraint
        # gets ALL reverted cells among its referenced attributes restored
        # to their original model repairs — whichever plan reverted them.
        # Each (row, cell) restores at most once and restores only move the
        # row toward the un-minimized all-repairs state (which satisfied
        # every constraint), so the loop is monotone and terminates.
        if revert_log:
            touched_rows = {i for i, _ in revert_log}
            for _ in range(len(plan["plans"]) + 1):
                changed = False
                for preds, _ in plan["plans"]:
                    attrs = {a for p in preds for a in p.references}
                    for i in touched_rows:
                        restorable = [a for a in attrs
                                      if (i, a) in revert_log]
                        if not restorable:
                            continue
                        violated = all(
                            pred_holds(p, p.references[0],
                                       out.at[out.index[i],
                                              p.references[0]])
                            for p in preds)
                        if violated:
                            for a in restorable:
                                out.at[out.index[i], a] = \
                                    revert_log.pop((i, a))
                                if led is not None:
                                    # the revert was undone: drop the
                                    # provisional dc_minimized_revert so the
                                    # extraction pass re-derives the outcome
                                    led.clear_decision(
                                        table.row_id_values[pos[i]], a)
                            changed = True
                if not changed:
                    break
        return out

    def _flatten(self, df: pd.DataFrame) -> pd.DataFrame:
        """(row_id, attribute, value) long view (RepairMiscApi.scala:41-49);
        values keep their python objects (PMF dicts pass through). Column-
        vectorized: homogeneous columns convert with pandas ops, only
        mixed/object columns fall back to a per-element pass."""
        cols = [c for c in df.columns if c != self._row_id]
        n = len(df)
        mat = np.empty((n, len(cols)), dtype=object)
        for j, c in enumerate(cols):
            mat[:, j] = _flatten_column(df[c])
        return pd.DataFrame({
            self._row_id: np.repeat(df[self._row_id].to_numpy(dtype=object),
                                    len(cols)),
            "attribute": np.tile(np.array(cols, dtype=object), n),
            "value": mat.reshape(-1),
        }, columns=[self._row_id, "attribute", "value"])

    def _pmf_records_for_attr(self, attr: str, group: pd.DataFrame,
                              weighted: bool, weight: float,
                              threshold: float, top_k: int) -> np.ndarray:
        """Builds the per-cell PMF records of one attribute as matrix ops:
        all cells of an attribute share one model, hence one class list, so
        their probs stack into an (n, k) matrix. Cost weighting batches the
        Levenshtein calls per *unique* current value, normalization and
        top-k run as numpy array ops (replaces the reference's per-row
        Python loops, model.py:1174-1225)."""
        vals = group["value"].to_numpy(dtype=object)
        curs = group["current_value"].to_numpy(dtype=object)
        rids = group[self._row_id].to_numpy(dtype=object)
        n = len(vals)
        records = np.empty(n, dtype=object)

        classes_of = [v.get("classes", []) if isinstance(v, dict) else []
                      for v in vals]
        nonempty = np.array([len(c) > 0 for c in classes_of], dtype=bool)
        for i in np.nonzero(~nonempty)[0]:
            records[i] = {
                self._row_id: rids[i], "attribute": attr,
                "current_value": {"value": curs[i], "prob": 0.0}, "pmf": []}
        ne_idx = np.nonzero(nonempty)[0]
        if len(ne_idx) == 0:
            return records

        classes = classes_of[ne_idx[0]]
        k = len(classes)
        if any(len(classes_of[i]) != k for i in ne_idx):
            # distinct models for one attribute can't happen in this pipeline;
            # defensive split so a future caller still gets correct output
            for sub_k, sub in pd.Series(ne_idx).groupby(
                    [len(classes_of[i]) for i in ne_idx]):
                sub_group = group.iloc[sub.to_numpy()]
                records[sub.to_numpy()] = self._pmf_records_for_attr(
                    attr, sub_group, weighted, weight, threshold, top_k)
            return records

        P = np.stack([np.asarray(vals[i]["probs"], dtype=np.float64)[:k]
                      for i in ne_idx])
        curs_ne = curs[ne_idx]

        if weighted:
            codes, uniques = pd.factorize(pd.Series(curs_ne, dtype=object),
                                          use_na_sentinel=True)
            # one weight row per unique current value (batched Levenshtein),
            # plus a trailing all-ones row that null/falsy currents (code -1)
            # index into — those keep their unweighted probs, like the
            # reference's `costs is None` branch
            W = np.ones((len(uniques) + 1, k), dtype=np.float64)
            for u, cur in enumerate(uniques):
                costs = self.cf.compute_many(cur, classes) \
                    if self.cf is not None else None
                if costs is not None:
                    W[u] = [1.0 / (1.0 + weight * c) if c is not None else 1.0
                            for c in costs]
            P = P * W[codes]
            totals = P.sum(axis=1, keepdims=True)
            np.divide(P, totals, out=P, where=totals > 0)

        class_idx = {}
        for j, c in enumerate(classes):
            class_idx.setdefault(c, j)
        cur_pos = np.array([class_idx.get(c, -1) for c in curs_ne])
        cur_probs = np.where(
            cur_pos >= 0, P[np.arange(len(ne_idx)), np.where(
                cur_pos >= 0, cur_pos, 0)], 0.0)

        kk = min(int(top_k), k)
        order = np.argsort(-P, axis=1, kind="stable")[:, :kk]
        top_probs = np.take_along_axis(P, order, axis=1)
        classes_arr = np.array(classes, dtype=object)
        top_classes = classes_arr[order]
        counts = np.minimum((P > threshold).sum(axis=1), kk)

        for r, i in enumerate(ne_idx):
            records[i] = {
                self._row_id: rids[i], "attribute": attr,
                "current_value": {"value": curs[i],
                                  "prob": float(cur_probs[r])},
                "pmf": [{"class": top_classes[r, j],
                         "prob": float(top_probs[r, j])}
                        for j in range(counts[r])]}
        return records

    def _compute_repair_pmf(self, repaired_rows_df: pd.DataFrame,
                            error_cells_df: pd.DataFrame,
                            continuous_columns: List[str]) -> pd.DataFrame:
        """PMF extraction + cost weighting + top-k filtering
        (reference model.py:1174-1225), vectorized per attribute. Only the
        attributes that carry error cells flatten — the inner join discards
        every other column's cells anyway."""
        error_attrs = set(error_cells_df["attribute"].unique())
        flat = self._flatten(repaired_rows_df[
            [self._row_id]
            + [c for c in repaired_rows_df.columns if c in error_attrs]])
        keys = error_cells_df[[self._row_id, "attribute", "current_value"]]
        joined = flat.merge(keys, on=[self._row_id, "attribute"], how="inner")

        continuous = set(continuous_columns)
        discrete = joined[~joined["attribute"].isin(continuous)] \
            .reset_index(drop=True)

        threshold = float(self._get_option_value(*self._opt_prob_threshold))
        top_k = int(self._get_option_value(*self._opt_prob_top_k))
        weight = float(self._get_option_value(*self._opt_cost_weight))
        cf_targets = set(self.cf.targets) if self.cf is not None else set()
        if self.cf is not None and cf_targets:
            _logger.info(f"[Repairing Phase] {self.cf} computing weighting probs...")

        records = np.empty(len(discrete), dtype=object)
        for attr, group in discrete.groupby("attribute", sort=False):
            weighted = self.cf is not None and \
                (not cf_targets or attr in cf_targets)
            idx = group.index.to_numpy()
            records[idx] = self._pmf_records_for_attr(
                str(attr), group, weighted, weight, threshold, top_k)
        out = list(records)

        if continuous:
            cont = joined[joined["attribute"].isin(continuous)]
            for rid, a, v, cur in zip(
                    cont[self._row_id], cont["attribute"], cont["value"],
                    cont["current_value"]):
                out.append({
                    self._row_id: rid,
                    "attribute": a,
                    "current_value": {"value": cur, "prob": 0.0},
                    "pmf": [{"class": v, "prob": 1.0}],
                })

        pmf_df = pd.DataFrame(
            out, columns=[self._row_id, "attribute", "current_value", "pmf"])
        assert len(pmf_df) == len(error_cells_df)
        led = active_ledger()
        if led is not None and len(pmf_df):
            # overwrite the raw posterior with the cost-weighted top-k the
            # candidate selection actually ranks on
            for attr, group in pmf_df.groupby("attribute", sort=False):
                led.record_pmf_topk(str(attr),
                                    group[self._row_id].tolist(),
                                    group["pmf"].tolist())
        return pmf_df

    def _finish_candidate_prob(self, pmf_df: pd.DataFrame,
                               compute_repair_prob: bool) -> pd.DataFrame:
        """Result shaping for the candidate-probability modes (reference
        model.py:1204-1225), shared by the whole-block and the chunked
        at-scale paths."""
        pmf_df = pmf_df.assign(
            current_value=[cv["value"] for cv in pmf_df["current_value"]])
        if compute_repair_prob:
            return pd.DataFrame({
                self._row_id: pmf_df[self._row_id],
                "attribute": pmf_df["attribute"],
                "current_value": pmf_df["current_value"],
                "repaired": [p[0]["class"] if p else None
                             for p in pmf_df["pmf"]],
                "prob": [p[0]["prob"] if p else None for p in pmf_df["pmf"]],
            })
        return pmf_df

    def _compute_score(self, pmf_df: pd.DataFrame) -> pd.DataFrame:
        """Log-likelihood-ratio x cost-discount score (reference
        model.py:1227-1248). Vectorized: cost lookups dedupe to one
        `cf.compute` per unique (base, repaired) pair, the score math runs
        as numpy array ops."""
        assert self.cf is not None
        pmfs = pmf_df["pmf"].tolist()
        curs = pmf_df["current_value"].tolist()
        rep_class = [p[0]["class"] if p else None for p in pmfs]
        rep_prob = np.array([p[0]["prob"] if p else 1e-6 for p in pmfs],
                            dtype=np.float64)
        cur_val = [c["value"] for c in curs]
        cur_prob = np.array([c["prob"] for c in curs], dtype=np.float64)
        base = [cv if cv is not None else rc
                for cv, rc in zip(cur_val, rep_class)]

        pair_cost: Dict[Tuple[Any, Any], Optional[float]] = {}
        costs = np.empty(len(pmfs), dtype=np.float64)
        for i, key in enumerate(zip(base, rep_class)):
            if key not in pair_cost:
                pair_cost[key] = self.cf.compute(*key)
            c = pair_cost[key]
            costs[i] = c if c is not None else 256.0

        cur_prob = np.where(cur_prob > 0.0, cur_prob, 1e-6)
        score = np.log(np.maximum(rep_prob, 1e-300) / cur_prob) / (1.0 + costs)
        return pd.DataFrame({
            self._row_id: pmf_df[self._row_id].to_numpy(),
            "attribute": pmf_df["attribute"].to_numpy(),
            "current_value": np.array(cur_val, dtype=object),
            "repaired": np.array(rep_class, dtype=object),
            "score": score.astype(float),
        }, columns=[self._row_id, "attribute", "current_value", "repaired", "score"])

    def _maximal_likelihood_repair(self, score_df: pd.DataFrame,
                                   error_cells_df: pd.DataFrame) -> pd.DataFrame:
        """Keeps the top `repair_delta` updates by score percentile
        (reference model.py:1259-1277)."""
        assert self.repair_delta is not None
        num_error_cells = len(error_cells_df)
        percent = min(1.0, 1.0 - self.repair_delta / num_error_cells)
        thres = float(np.percentile(score_df["score"].to_numpy(), percent * 100.0)) \
            if len(score_df) else 0.0
        selected = score_df["score"].to_numpy() >= thres
        top = score_df[selected].drop(columns=["score"])
        led = active_ledger()
        if led is not None and len(score_df):
            rids = score_df[self._row_id].to_numpy()
            attrs = score_df["attribute"].to_numpy(dtype=object)
            reps = score_df["repaired"].to_numpy(dtype=object)
            if selected.any():
                led.record_decisions(rids[selected], attrs[selected],
                                     _prov.DECISION_REPAIRED,
                                     _prov.REASON_MAXIMAL_LIKELIHOOD,
                                     repaired=reps[selected])
            if (~selected).any():
                led.record_decisions(rids[~selected], attrs[~selected],
                                     _prov.DECISION_BELOW_THRESHOLD,
                                     _prov.REASON_BELOW_SCORE_THRESHOLD)
        _logger.info(
            "[Repairing Phase] {} repair updates (delta={}) selected among {} "
            "candidates".format(len(top), self.repair_delta, num_error_cells))
        return top.reset_index(drop=True)

    def _continuous_kind_map(self, table: EncodedTable) -> Dict[str, str]:
        return {c.name: c.kind for c in table.columns if c.is_numeric}

    def _repair_attrs(self, repair_updates: Union[str, pd.DataFrame],
                      base_table: Union[str, pd.DataFrame],
                      table: EncodedTable) -> pd.DataFrame:
        updates = repair_updates if type(repair_updates) is pd.DataFrame \
            else self._session.table(str(repair_updates))
        base = base_table if type(base_table) is pd.DataFrame \
            else self._session.table(str(base_table))
        return repair_attrs_from(updates, base, self._row_id,
                                 self._continuous_kind_map(table))

    @job_phase(name="validating")
    def _validate_repairs(self, repair_candidates: pd.DataFrame,
                          repaired_rows: pd.DataFrame,
                          clean_rows: pd.DataFrame,
                          original_rows: Optional[pd.DataFrame] = None
                          ) -> pd.DataFrame:
        """Post-repair constraint validation — implements the check the
        reference leaves as a TODO (model.py:1279-1285: "statistical models
        notoriously ignore specified integrity constraints"): the repaired
        dirty rows re-encode together with the clean context, every
        ConstraintErrorDetector's denial constraints re-evaluate over the
        result (the same device kernels phase 1 uses), and candidates whose
        repaired cell introduces a violation are dropped — the cell stays
        unrepaired rather than swapping one violation for another.

        When ``original_rows`` (the UNMASKED dirty rows) is given, a
        candidate is dropped only if its cell violates AFTER the repair and
        did NOT already violate BEFORE it: a correct repair landing next to
        a pre-existing violation among the "clean" rows (undetected, so it
        survives into the context) stays kept instead of being blamed for a
        violation it didn't cause. Without ``original_rows`` the before-set
        is empty and every after-violation drops (the conservative legacy
        behavior)."""
        _logger.info("[Validation Phase] Validating {} repair candidates...".format(
            len(repair_candidates)))
        detectors = [d for d in self.error_detectors
                     if isinstance(d, ConstraintErrorDetector)]
        if not detectors or not len(repair_candidates):
            return repair_candidates

        from delphi_tpu.ops.detect import detect_constraint_violations
        from delphi_tpu.table import encode_table

        candidate_attrs = sorted(set(repair_candidates["attribute"]))

        def violating_cells(dirty_block: pd.DataFrame) -> Optional[set]:
            full = pd.concat([clean_rows, dirty_block], ignore_index=True)
            try:
                encoded = encode_table(full, self._row_id)
            except Exception as e:  # never fail the run on a validation error
                _logger.warning(
                    f"Repair validation skipped: {e.__class__}: {e}")
                return None
            cells: set = set()
            rid_vals = full[self._row_id].to_numpy()
            for d in detectors:
                try:
                    parsed = d.parsed_constraints(encoded, str(self.input))
                except Exception as e:
                    _logger.warning(
                        f"Repair validation skipped for {d}: {e}")
                    continue
                if parsed.is_empty:
                    continue
                for rows, attr in detect_constraint_violations(
                        encoded, parsed, candidate_attrs):
                    cells.update(
                        (rid, attr) for rid in rid_vals[rows].tolist())
            return cells

        after = violating_cells(repaired_rows)
        if after is None or not after:
            return repair_candidates
        before = violating_cells(original_rows) \
            if original_rows is not None else set()
        violating = after - (before or set())

        if not violating:
            return repair_candidates
        keys = list(zip(repair_candidates[self._row_id].tolist(),
                        repair_candidates["attribute"].tolist()))
        keep = np.array([k not in violating for k in keys])
        dropped = int((~keep).sum())
        if dropped:
            led = active_ledger()
            if led is not None:
                dropped_df = repair_candidates[~keep]
                led.record_decisions(
                    dropped_df[self._row_id].to_numpy(),
                    dropped_df["attribute"].to_numpy(dtype=object),
                    _prov.DECISION_KEPT, _prov.REASON_VALIDATION_VIOLATION)
            _logger.info(
                f"[Validation Phase] Dropped {dropped}/{len(keys)} repairs "
                "that introduce integrity-constraint violations")
        return repair_candidates[keep].reset_index(drop=True)

    # -- run ------------------------------------------------------------------

    # -- checkpoint/resume ----------------------------------------------------
    #
    # The reference never persists trained models (SURVEY.md §5: pickling is
    # transport-only, model.py:910/921, with an acknowledged checkpoint TODO at
    # model.py:1094). Here `option("model.checkpoint_path", dir)` saves the
    # trained per-attribute models after phase 2 and reuses them on the next
    # run when the target-column set matches, so repeated repairs of a table
    # (or a re-run after an inference-phase failure) skip training entirely.

    def _checkpoint_file(self) -> str:
        path = self._get_option_value(*self._opt_checkpoint_path)
        return os.path.join(path, "repair_models.pkl") if path else ""

    @staticmethod
    def _table_content_sha1(table: EncodedTable) -> str:
        """Cheap content hash over an encoded table, shared by the model
        checkpoint and the phase-checkpoint store (the hashed bytes are
        unchanged from the original model-checkpoint implementation)."""
        sampled = os.environ.get("DELPHI_CHECKPOINT_SAMPLED_HASH") == "1"
        stride = max(1, table.n_rows // 65536) if sampled else 1
        h = hashlib.sha1()
        h.update(b"sampled" if sampled else b"full")
        h.update(np.int64(table.n_rows).tobytes())
        for c in table.columns:
            h.update(c.name.encode("utf-8", "replace"))
            h.update("\x00".join(str(v) for v in c.vocab).encode(
                "utf-8", "replace"))
            if sampled:
                h.update(np.ascontiguousarray(c.codes[::stride]).tobytes())
                if table.n_rows:
                    h.update(np.ascontiguousarray(c.codes[-1:]).tobytes())
            else:
                # crc32 accepts any buffer — no .tobytes() copy (a second
                # ~400MB allocation per column at the 1e8-row north star)
                crc = zlib.crc32(np.ascontiguousarray(c.codes))
                h.update(np.uint32(crc).tobytes())
        return h.hexdigest()

    def _checkpoint_fingerprint(self, masked: EncodedTable,
                                target_columns: List[str]) -> Dict[str, Any]:
        """Identity of a trained-model set: the input table name, its shape
        and schema, a cheap content hash, and every model.* option. A
        checkpoint is only reused when all of these match, so a different
        table (or the same table with edited rows/options) retrains."""
        # Content hash over the encoded table: full vocabularies (new/renamed
        # values always flip it) plus, by default, a FULL pass over every
        # code column via crc32 (~GB/s, memory-bandwidth bound — negligible
        # next to the runs worth checkpointing), so any single-cell edit
        # flips the fingerprint. DELPHI_CHECKPOINT_SAMPLED_HASH=1 opts into
        # the bounded stride sample instead (~O(1) rows hashed), accepting
        # that an edit off the sample lattice reusing existing vocab entries
        # can slip past.
        content = self._table_content_sha1(masked)
        return {
            "version": 4,
            "input": self._session.qualified_name(
                self.db_name,
                self.input if isinstance(self.input, str) else "<dataframe>"),
            "targets": sorted(target_columns),
            "columns": [self._row_id] + masked.column_names,
            "n_rows": int(masked.n_rows),
            "content_sha1": content,
            # Every expert option is part of the identity: error.* knobs shape
            # the stats that feed feature selection, model.* shape training.
            # (repair.pmf.* retrains unnecessarily but never reuses stale.)
            # `model.checkpoint_path` itself is excluded so a relocated
            # checkpoint directory still validates against its contents.
            "opts": {k: v for k, v in sorted(self.opts.items())
                     if k != self._opt_checkpoint_path.key},
            # Setter-based knobs that change which models get built.
            "discrete_thres": int(self.discrete_thres),
            "repair_by_rules": bool(self.repair_by_rules),
            "rebalancing": bool(self.training_data_rebalancing_enabled),
        }

    def _load_model_checkpoint(self, fingerprint: Dict[str, Any]) -> Optional[List[Any]]:
        # Trust boundary: checkpoints are plain pickles, and unpickling runs
        # arbitrary code. Point `model.checkpoint_path` only at directories
        # you (or this process) wrote — never at untrusted files. This is the
        # same boundary the reference draws around its pickled model blobs
        # (reference python/repair/model.py:910,921 transports models with
        # CloudPickle under the same assumption).
        ckpt = self._checkpoint_file()
        if not ckpt:
            return None
        from delphi_tpu.parallel import store as dstore
        payload, status = dstore.read_pickle(
            ckpt, schema="model_ckpt", site="store.model")
        if status == "missing":
            return None
        if status == "corrupt":
            # quarantined by the store seam — retrain, never half-load
            _logger.warning(f"Ignoring corrupt model checkpoint {ckpt}")
            return None
        if not isinstance(payload, dict) or "models" not in payload:
            _logger.warning(
                f"Ignoring model checkpoint {ckpt}: unrecognized format")
            return None
        if payload.get("fingerprint") != fingerprint:
            _logger.warning(
                f"Ignoring stale model checkpoint {ckpt}: "
                "input/targets/options changed since it was written")
            return None
        _logger.info(f"Loaded {len(payload['models'])} repair models from {ckpt}")
        return payload["models"]

    def _save_model_checkpoint(self, models: List[Any],
                               fingerprint: Dict[str, Any]) -> None:
        ckpt = self._checkpoint_file()
        if not ckpt:
            return
        from delphi_tpu.parallel import store as dstore
        try:
            # durable-store seam (site ``store.model``): the pre-seam
            # writer was a plain pickle.dump with no tmp file, no fsync
            # and no rename — the single worst torn-write exposure in the
            # cache root
            dstore.write_pickle(
                ckpt, {"fingerprint": fingerprint, "models": models},
                schema="model_ckpt", site="store.model")
            _logger.info(f"Saved {len(models)} repair models to {ckpt}")
        except Exception as e:
            _logger.warning(f"Failed to write model checkpoint {ckpt}: {e}")

    # -- phase-level checkpoint/resume (resilience plane) ---------------------
    #
    # Orthogonal to `model.checkpoint_path` (which caches trained models
    # across runs keyed on the MASKED table): `DELPHI_CHECKPOINT_DIR` /
    # `repair.checkpoint.dir` persists each pipeline phase's outputs keyed on
    # the INPUT table, so a run killed mid-pipeline (crash, watchdog
    # checkpoint-and-abort) resumes at the last completed phase with
    # bit-identical results.

    def _phase_fingerprint(self, table: EncodedTable,
                           continuous_columns: List[str]) -> Dict[str, Any]:
        """Identity of a run's phase outputs: everything they deterministically
        derive from — the input table (name, schema, content hash), the
        continuous-column split, and every expert option/setter knob."""
        return {
            "version": 1,
            "input": self._session.qualified_name(
                self.db_name,
                self.input if isinstance(self.input, str) else "<dataframe>"),
            "columns": [self._row_id] + table.column_names,
            "n_rows": int(table.n_rows),
            "content_sha1": self._table_content_sha1(table),
            "continuous": sorted(continuous_columns),
            "opts": dict(sorted(self.opts.items())),
            "targets": sorted(self.cf.targets) if self.cf is not None else [],
            "discrete_thres": int(self.discrete_thres),
            "repair_by_rules": bool(self.repair_by_rules),
            "rebalancing": bool(self.training_data_rebalancing_enabled),
        }

    def _phase_checkpoint_store(
            self, table: EncodedTable, continuous_columns: List[str]
    ) -> Optional["_resilience.PhaseCheckpointStore"]:
        directory = _resilience.checkpoint_dir()
        if not directory:
            return None
        if table.process_local:
            # phase payloads are per-process row shards here; resuming one
            # shard against another's checkpoint would silently mix rows
            _logger.warning("phase checkpointing skipped: not supported on "
                            "process-local (sharded-ingestion) tables")
            return None
        try:
            fp = self._phase_fingerprint(table, continuous_columns)
        except Exception as e:  # checkpointing must never fail the run
            _logger.warning(f"phase checkpointing disabled: {e}")
            return None
        return _resilience.PhaseCheckpointStore(directory, fp)

    @elapsed_time  # type: ignore
    def _run(self, table: EncodedTable, input_name: str,
             continuous_columns: List[str], detect_errors_only: bool,
             compute_repair_candidate_prob: bool, compute_repair_prob: bool,
             compute_repair_score: bool, repair_data: bool,
             maximal_likelihood_repair: bool) -> pd.DataFrame:
        if table.process_local:
            # Process-local (sharded-ingestion) pipeline: this process holds
            # only its row shard. Global reductions (freq stats, class
            # presence, training samples) run through cross-process
            # collectives; everything row-dimensional — detection, domain
            # scoring, inference — runs per process on its own device
            # (`local_compute` pins the generic kernels off the global
            # mesh), and the returned frame covers THIS process's rows.
            if compute_repair_candidate_prob or maximal_likelihood_repair:
                raise ValueError(
                    "PMF/maximal-likelihood modes are not supported on "
                    "process-local (sharded-ingestion) tables yet")
            if self.repair_by_rules:
                raise ValueError(
                    "setRepairByRules is not supported on process-local "
                    "(sharded-ingestion) tables yet")
            if self.repair_validation_enabled:
                # validation would re-encode only THIS shard's rows, so a
                # repair violating a constraint against another shard's
                # rows would silently survive — refuse rather than degrade
                raise ValueError(
                    "repair validation is not supported on process-local "
                    "(sharded-ingestion) tables yet: it would check "
                    "constraints against this shard's rows only")
            from delphi_tpu.parallel.mesh import local_compute
            with local_compute():
                return self._run_impl(
                    table, input_name, continuous_columns,
                    detect_errors_only, compute_repair_candidate_prob,
                    compute_repair_prob, compute_repair_score, repair_data,
                    maximal_likelihood_repair)
        return self._run_impl(
            table, input_name, continuous_columns, detect_errors_only,
            compute_repair_candidate_prob, compute_repair_prob,
            compute_repair_score, repair_data, maximal_likelihood_repair)

    def _run_impl(self, table: EncodedTable, input_name: str,
                  continuous_columns: List[str], detect_errors_only: bool,
                  compute_repair_candidate_prob: bool,
                  compute_repair_prob: bool,
                  compute_repair_score: bool, repair_data: bool,
                  maximal_likelihood_repair: bool) -> pd.DataFrame:
        phase_store = self._phase_checkpoint_store(table, continuous_columns)

        #######################################################################
        # 1. Error Detection Phase
        #######################################################################
        detect_ckpt = phase_store.load("detect") if phase_store else None
        if detect_ckpt is not None:
            error_cells_df, target_columns, pairwise_attr_stats, \
                domain_stats = detect_ckpt
        else:
            _logger.info(
                f"[Error Detection Phase] Detecting errors in a table "
                f"`{input_name}`... ")
            error_cells_df, target_columns, pairwise_attr_stats, \
                domain_stats = self._detect_errors(
                    table, input_name, continuous_columns)
            if phase_store:
                phase_store.save("detect", (error_cells_df, target_columns,
                                            pairwise_attr_stats, domain_stats))
        # watchdog checkpoint-and-abort lands between phases: the completed
        # phase's checkpoint is already on disk, so the resume is lossless
        _resilience.maybe_abort()
        gauge_set("pipeline.error_cells", int(len(error_cells_df)))
        gauge_set("pipeline.target_columns", len(target_columns))

        if detect_errors_only:
            return error_cells_df.drop(columns=[ROW_IDX], errors="ignore")

        total_error_cells = len(error_cells_df)
        if table.process_local:
            # zero LOCAL cells must not diverge this shard from the global
            # control flow: its collectives pair with the other shards'
            from delphi_tpu.parallel.distributed import allgather_sum
            total_error_cells = int(allgather_sum(
                np.asarray([total_error_cells], dtype=np.int64))[0])
        if total_error_cells == 0:
            _logger.info("Any error cell not found, so the input data is already clean")
            if repair_data:
                return table.to_pandas()
            return pd.DataFrame(
                columns=[self._row_id, "attribute", "current_value"])

        if len(target_columns) == 0:
            raise ValueError(
                "At least one valid discretizable feature is needed to repair error "
                "cells, but no such feature found")

        error_cells_df = self._filter_columns_from(error_cells_df, target_columns)

        #######################################################################
        # 2. Repair Model Training Phase
        #######################################################################
        # The table never materializes to pandas here (the reference masks via
        # views without materializing either, RepairApi.scala:171-211): phases
        # 2-3 run off the encoded int32 table, decoding only the sampled
        # training rows and the dirty-row block. This is what keeps the
        # 1e8-row single-host run inside memory.
        masked = table.with_nulls_at_arrays(
            error_cells_df[ROW_IDX].to_numpy().astype(np.int64),
            error_cells_df["attribute"].to_numpy(dtype=object))
        # dtype snapshot: an integral column that carries NULLs after masking
        # decodes to float64 in every downstream frame, even if rule repairs
        # later fill all of its NULLs (the old full-frame decode fixed dtypes
        # at this point, and subset decodes must agree with it)
        nan_flags = np.asarray([
            c.kind == KIND_INTEGRAL and c.numeric is not None
            and bool(np.isnan(c.numeric).any()) for c in masked.columns])
        if table.process_local:
            # dtype decisions must agree across shards (gathered training
            # frames concatenate, and output spellings must be uniform)
            from delphi_tpu.parallel.distributed import allgather_any
            nan_flags = allgather_any(nan_flags)
        float_cols = tuple(
            c.name for c, f in zip(masked.columns, nan_flags) if f)

        repaired_by_rules_df = None
        if self.repair_by_rules:
            integral_columns = {
                c.name for c in table.columns if c.kind == KIND_INTEGRAL}
            error_cells_df, repaired_by_rules_df = self._repair_by_rules(
                masked, error_cells_df, target_columns, integral_columns)
            if len(repaired_by_rules_df):
                masked = masked.with_updates(list(zip(
                    repaired_by_rules_df[ROW_IDX].astype(int),
                    repaired_by_rules_df["attribute"],
                    repaired_by_rules_df["repaired"])))

        error_row_pos = np.unique(
            error_cells_df[ROW_IDX].to_numpy().astype(np.int64))
        gauge_set("repair.dirty_rows", int(len(error_row_pos)))

        # checkpoint identity is content-hashed per process; process-local
        # shards would fingerprint (and race) P different hashes, so the
        # sharded pipeline skips checkpointing
        fingerprint = self._checkpoint_fingerprint(masked, target_columns) \
            if self._checkpoint_file() and not table.process_local else {}
        # resume layering: the phase store (keyed on the input table) is
        # checked first, then the cross-run model checkpoint (keyed on the
        # masked table), then training runs for real
        models = phase_store.load("train") if phase_store else None
        if models is None:
            models = self._load_model_checkpoint(fingerprint) if fingerprint else None
            if models is None:
                models = self._build_repair_models(
                    masked, float_cols, target_columns, continuous_columns,
                    domain_stats, pairwise_attr_stats)
                if fingerprint:
                    self._save_model_checkpoint(models, fingerprint)
            else:
                counter_inc("train.checkpoint_hits")
            if phase_store:
                phase_store.save("train", models)
        # the incremental executor snapshots the trained models after the
        # run, so a later delta run can freeze undrifted attributes
        self._last_models = models
        _resilience.maybe_abort()
        for _, (model, _, _) in models:
            if isinstance(model, PoorModel):
                counter_inc("train.poor_models")
            elif isinstance(model, FunctionalDepModel):
                counter_inc("train.fd_rule_models")
            else:
                counter_inc("train.stat_models")
                # task split: continuous targets route to the regression
                # branch (is_discrete=False); the gauntlet's numeric
                # scenario pins train.regressors > 0
                is_discrete = getattr(model, "is_discrete", None)
                if is_discrete is False:
                    counter_inc("train.regressors")
                elif is_discrete is True:
                    counter_inc("train.classifiers")

        #######################################################################
        # 3. Repair Phase
        #######################################################################
        need_pmf = compute_repair_candidate_prob or maximal_likelihood_repair
        dc_plan = self._one_tuple_dc_plan(
            table, continuous_columns, error_cells_df) if not need_pmf else None
        chunk_rows = int(os.environ.get("DELPHI_REPAIR_CHUNK_ROWS", "2000000"))

        # confidence-routed escalation (delphi_tpu/escalate) applies only to
        # the direct-repair paths: the PMF / maximal-likelihood modes return
        # distributions, not decisions, so there is nothing to escalate
        escalate_requested = False
        if not need_pmf:
            from delphi_tpu import escalate as _escalate
            escalate_requested = _escalate.escalation_requested(self)

        if maximal_likelihood_repair:
            assert len(continuous_columns) == 0
            assert len(self.cf.targets) == 0  # type: ignore
            assert not self._repair_by_nearest_values_enabled, \
                "repairing data by nearest values not supported in this path"
        elif compute_repair_candidate_prob:
            assert not self._repair_by_nearest_values_enabled, \
                "repairing data by nearest values not supported in this path"

        if need_pmf and not repair_data \
                and chunk_rows > 0 and len(error_row_pos) > chunk_rows:
            # PMF / maximal-likelihood at scale (reference shape:
            # model.py:1174-1277): the dirty block, the repaired block, and
            # the flattened PMF join frames exist only per chunk of dirty
            # rows — the carried outputs (PMF records / per-cell scores) are
            # error-cell-sized, and the ML percentile runs once over the
            # concatenated global scores.
            ecf_rows = error_cells_df[ROW_IDX].to_numpy().astype(np.int64)
            pmf_parts: List[pd.DataFrame] = []
            score_parts: List[pd.DataFrame] = []
            for start in range(0, len(error_row_pos), chunk_rows):
                counter_inc("repair.chunks")
                pos = error_row_pos[start:start + chunk_rows]
                # error_row_pos is sorted-unique, so a chunk's cells are
                # exactly the cells in its row range
                cells_chunk = error_cells_df[
                    (ecf_rows >= pos[0]) & (ecf_rows <= pos[-1])]
                dirty_chunk = masked.to_pandas(
                    rows=pos, integral_as_float=float_cols)
                repaired_chunk = self._repair(
                    models, continuous_columns, dirty_chunk, cells_chunk,
                    compute_repair_candidate_prob, maximal_likelihood_repair)
                if maximal_likelihood_repair:
                    score_parts.append(self._compute_score(
                        self._compute_repair_pmf(
                            repaired_chunk, cells_chunk, [])))
                else:
                    pmf_parts.append(self._compute_repair_pmf(
                        repaired_chunk, cells_chunk, continuous_columns))
            if maximal_likelihood_repair:
                score_df = pd.concat(score_parts, ignore_index=True)
                if compute_repair_score:
                    return score_df
                return self._maximal_likelihood_repair(
                    score_df, error_cells_df)
            return self._finish_candidate_prob(
                pd.concat(pmf_parts, ignore_index=True), compute_repair_prob)

        if not (need_pmf or repair_data or self.repair_validation_enabled
                or self.repair_by_rules or escalate_requested) \
                and chunk_rows > 0 and len(error_row_pos) > chunk_rows:
            # candidates-only at scale: decode + repair + extract per chunk of
            # dirty rows so no full dirty block ever materializes at once
            parts = []
            ecf_rows = error_cells_df[ROW_IDX].to_numpy().astype(np.int64)
            for start in range(0, len(error_row_pos), chunk_rows):
                counter_inc("repair.chunks")
                pos = error_row_pos[start:start + chunk_rows]
                dirty_chunk = masked.to_pandas(
                    rows=pos, integral_as_float=float_cols)
                repaired_chunk = self._repair(
                    models, continuous_columns, dirty_chunk, error_cells_df,
                    compute_repair_candidate_prob, maximal_likelihood_repair)
                repaired_chunk = self._minimize_one_tuple_dc_repairs(
                    table, dc_plan, pos, repaired_chunk, models)
                # pre-slice the chunk's cells (error_row_pos is sorted, so a
                # chunk's cells are exactly the cells in its row range):
                # the extraction then touches only chunk-sized arrays
                cells_chunk = error_cells_df[
                    (ecf_rows >= pos[0]) & (ecf_rows <= pos[-1])]
                parts.append(self._extract_repair_candidates(
                    repaired_chunk, cells_chunk, target_columns, pos))
            # row-major per chunk + ascending chunks = global row-major,
            # identical to the one-shot path's order
            return pd.concat(parts, ignore_index=True)

        counter_inc("repair.chunks")
        dirty_rows_df = masked.to_pandas(
            rows=error_row_pos, integral_as_float=float_cols)
        repaired_rows_df = self._repair(
            models, continuous_columns, dirty_rows_df, error_cells_df,
            compute_repair_candidate_prob, maximal_likelihood_repair)
        repaired_rows_df = self._minimize_one_tuple_dc_repairs(
            table, dc_plan, error_row_pos, repaired_rows_df, models)

        if escalate_requested:
            # after DC minimization, before the result frames are shaped:
            # the escalated values flow into BOTH the repaired-data concat
            # and the candidate extraction below
            from delphi_tpu import escalate as _escalate
            with phase_span("escalation"):
                esc_summary = _escalate.maybe_escalate(
                    self, masked, error_cells_df, error_row_pos,
                    repaired_rows_df, target_columns, continuous_columns)
            self._last_escalation = esc_summary
            from delphi_tpu.observability import current_recorder
            rec = current_recorder()
            if rec is not None:
                rec.escalation = esc_summary

        if compute_repair_candidate_prob and not maximal_likelihood_repair:
            pmf_df = self._compute_repair_pmf(
                repaired_rows_df, error_cells_df, continuous_columns)
            return self._finish_candidate_prob(pmf_df, compute_repair_prob)

        if maximal_likelihood_repair:
            pmf_df = self._compute_repair_pmf(repaired_rows_df, error_cells_df, [])
            score_df = self._compute_score(pmf_df)
            if compute_repair_score:
                return score_df

            top_delta_repairs_df = self._maximal_likelihood_repair(
                score_df, error_cells_df)
            if not repair_data:
                return top_delta_repairs_df
            repaired_rows_df = self._repair_attrs(
                top_delta_repairs_df, dirty_rows_df, table)

        if repair_data:
            clean_pos = np.setdiff1d(
                np.arange(table.n_rows, dtype=np.int64), error_row_pos,
                assume_unique=True)
            clean_rows_df = masked.to_pandas(
                rows=clean_pos, integral_as_float=float_cols)
            clean_df = pd.concat([clean_rows_df, repaired_rows_df], ignore_index=True)
            assert len(clean_df) == table.n_rows
            return clean_df

        repair_candidates_df = self._extract_repair_candidates(
            repaired_rows_df, error_cells_df, target_columns, error_row_pos)

        if self.repair_by_rules and repaired_by_rules_df is not None \
                and len(repaired_by_rules_df):
            extra = repaired_by_rules_df[
                [self._row_id, "attribute", "current_value", "repaired"]]
            repair_candidates_df = pd.concat(
                [repair_candidates_df, extra], ignore_index=True)
        if self.repair_validation_enabled:
            clean_pos = np.setdiff1d(
                np.arange(table.n_rows, dtype=np.int64), error_row_pos,
                assume_unique=True)
            clean_rows_df = masked.to_pandas(
                rows=clean_pos, integral_as_float=float_cols)
            # the UNMASKED dirty rows: the before-frame of the validation
            # diff, so a cell that already violated pre-repair can't get a
            # correct repair dropped for a violation it didn't introduce
            original_rows_df = table.to_pandas(
                rows=error_row_pos, integral_as_float=float_cols)
            repair_candidates_df = self._validate_repairs(
                repair_candidates_df, repaired_rows_df, clean_rows_df,
                original_rows_df)
        return repair_candidates_df

    def _extract_repair_candidates(self, repaired_rows_df: pd.DataFrame,
                                   error_cells_df: pd.DataFrame,
                                   target_columns: List[str],
                                   row_pos: np.ndarray) -> pd.DataFrame:
        """Result shaping for the candidates path, INTEGER-KEYED: the
        repaired block's rows correspond positionally to ``row_pos`` (the
        sorted global row positions it was decoded from), so each error
        cell's repaired value is a direct positional gather + one
        stringify pass per attribute — no melt of the repaired block and
        no object-key join (the reference shapes the same result via a SQL
        flatten + join, model.py:1391-1408; those passes dominated the
        repair tail at the 1e8-row scale). Output reproduces the legacy
        flatten+join shape exactly: stringified repaired values, row-major
        order (a row's cells together, attributes in column order), and
        the keep rule `repaired IS NULL OR NOT(current <=> repaired)` —
        repairs that changed the value or stayed NULL ("couldn't
        repair")."""
        empty = pd.DataFrame(
            columns=[self._row_id, "attribute", "current_value", "repaired"])
        cells_rows = error_cells_df[ROW_IDX].to_numpy().astype(np.int64)
        if not len(row_pos) or not len(cells_rows):
            return empty
        in_chunk = (cells_rows >= row_pos[0]) & (cells_rows <= row_pos[-1])
        if not in_chunk.all():
            error_cells_df = error_cells_df[in_chunk]
            cells_rows = cells_rows[in_chunk]
            if not len(cells_rows):
                return empty
        local = np.searchsorted(row_pos, cells_rows)
        attrs_np = error_cells_df["attribute"].to_numpy(dtype=object)
        curs_np = error_cells_df["current_value"].to_numpy(dtype=object)
        # object dtype for legacy parity: the reference's SQL flatten+join
        # keyed row ids as plain values, so an integer-keyed table must not
        # come back with a numpy-int64 column where callers (and the
        # provenance ledger) expect Python scalars
        rid_np = error_cells_df[self._row_id].to_numpy(dtype=object)
        attr_codes, attr_uniques = pd.factorize(attrs_np)
        col_rank = {a: i for i, a in enumerate(repaired_rows_df.columns)}
        target_set = set(target_columns)
        repaired = np.empty(len(cells_rows), dtype=object)
        valid = np.zeros(len(cells_rows), dtype=bool)
        for ai, attr in enumerate(attr_uniques):
            if attr not in target_set or attr not in col_rank:
                continue  # the legacy inner join dropped these cells
            m = attr_codes == ai
            repaired[m] = _flatten_column(
                repaired_rows_df[attr].iloc[local[m]])
            valid[m] = True
        # pandas turns None into NaN on assignment, so test via _is_null
        # rather than `is None`
        keep = valid & np.fromiter(
            (_is_null(r) or not _null_safe_eq(c, r)
             for c, r in zip(curs_np, repaired)),
            dtype=bool, count=len(cells_rows))
        led = active_ledger()
        if led is not None and len(cells_rows):
            # sticky-aware: a reason a more specific pass recorded (DC
            # minimization, rules, the confidence fallback) survives; the
            # generic outcome below fills in everything else
            rep_null = np.fromiter((_is_null(r) for r in repaired),
                                   dtype=bool, count=len(repaired))
            for mask, decision, reason, rep in (
                    (keep & ~rep_null, _prov.DECISION_REPAIRED,
                     _prov.REASON_MODEL_REPAIR, repaired),
                    (keep & rep_null, _prov.DECISION_KEPT,
                     _prov.REASON_NO_PREDICTION, None),
                    (valid & ~keep, _prov.DECISION_KEPT,
                     _prov.REASON_PREDICTION_MATCHES_CURRENT, None),
                    (~valid, _prov.DECISION_KEPT,
                     _prov.REASON_NOT_TARGETED, None)):
                if mask.any():
                    led.record_decisions(
                        rid_np[mask], attrs_np[mask], decision, reason,
                        repaired=rep[mask] if rep is not None else None,
                        sticky_aware=True)
        if not keep.any():
            return empty
        ranks = np.fromiter((col_rank.get(a, 0) for a in attrs_np),
                            dtype=np.int64, count=len(attrs_np))
        order = np.lexsort((ranks[keep], local[keep]))  # row-major
        idx = np.nonzero(keep)[0][order]
        return pd.DataFrame({
            self._row_id: rid_np[idx],
            "attribute": attrs_np[idx],
            "current_value": curs_np[idx],
            "repaired": repaired[idx],
        })

    def _check_input_table(self) -> Tuple[EncodedTable, str, List[str]]:
        if isinstance(self.input, str):
            # chunk-ingested inputs are already encoded in the catalog: use
            # them directly instead of decoding + re-encoding
            name = self._session.qualified_name(self.db_name, str(self.input))
            entry = self._session.raw_entry(name)
            if isinstance(entry, EncodedTable):
                from delphi_tpu.table import check_encoded_table
                table, continuous_columns = check_encoded_table(
                    entry, self._row_id, name)
                _logger.info("input_table: {} ({} rows x {} columns)".format(
                    name, table.n_rows, len(table.columns)))
                return table, name, continuous_columns
        df, input_name = self._input_frame
        table, continuous_columns = check_input_table(df, self._row_id, input_name)
        _logger.info("input_table: {} ({} rows x {} columns)".format(
            input_name, table.n_rows, len(table.columns)))
        return table, input_name, continuous_columns

    def run(self, detect_errors_only: bool = False,
            compute_repair_candidate_prob: bool = False,
            compute_repair_prob: bool = False, compute_repair_score: bool = False,
            repair_data: bool = False,
            maximal_likelihood_repair: bool = False) -> pd.DataFrame:
        """Runs the pipeline; flag semantics identical to the reference
        (model.py:1421-1537).

        When ``DELPHI_METRICS_PATH`` (or the ``repair.metrics.path`` session
        config) is set, a versioned run-report JSON — span tree, metrics
        registry snapshot, and (with ``DELPHI_PROFILE_DIR``) per-phase
        device-time attribution — is written there when the run finishes,
        whether it succeeds or fails. ``DELPHI_METRICS_PORT`` (or
        ``repair.metrics.port``) additionally serves live telemetry —
        ``/metrics``, ``/healthz``, ``/report`` — plus a stall watchdog and
        resource sampler for the run's duration, with or without a report
        path (see delphi_tpu/observability). ``DELPHI_PROVENANCE_PATH`` (or
        ``repair.provenance.path``) records a per-cell repair provenance
        ledger — detector, domain size, top-k posterior, final decision —
        written as JSONL when the run finishes (``:memory:`` keeps it
        in-process) and aggregated into per-attribute quality scorecards in
        the run report."""
        from delphi_tpu import observability as obs

        # a fresh run starts with clean resilience latches: an abort armed by
        # a previous run's watchdog (or its CPU fallback) must not leak in.
        # Inside a serving-plane RequestScope the latches are per-request
        # already, and clearing the process globals would erase another
        # in-flight session's state.
        if _resilience.current_scope() is None:
            _resilience.clear_abort()
            _resilience.clear_cpu_fallback()

        report_path = obs.metrics_path()
        recorder = None
        if report_path or obs.live_configured() or obs.provenance_configured():
            recorder = obs.start_recording(
                "repair.run", events_path=obs.events_path_for(report_path))

        # the escalation router reads the live provenance ledger; when a run
        # requests escalation without configuring provenance, arm a
        # thread-local in-memory ledger (scoped, so concurrent serve
        # sessions stay isolated and nothing is written to disk)
        import contextlib
        esc_scope: Any = contextlib.nullcontext()
        if not detect_errors_only:
            from delphi_tpu import escalate as _escalate
            if _escalate.escalation_requested(self) \
                    and _prov.active_ledger() is None \
                    and not _prov.provenance_configured():
                esc_scope = _prov.scoped_ledger(
                    _prov.ProvenanceLedger(_prov.MEMORY_PATH))

        status: str = "ok"
        error: Optional[str] = None
        run_info: Dict[str, Any] = {}
        try:
            with esc_scope:
                return self._run_checked(
                    run_info, detect_errors_only,
                    compute_repair_candidate_prob, compute_repair_prob,
                    compute_repair_score, repair_data,
                    maximal_likelihood_repair)
        except BaseException as e:
            status = "error"
            error = f"{type(e).__name__}: {e}"
            raise
        finally:
            if recorder is not None:
                obs.stop_recording(recorder)
                if report_path:
                    try:
                        obs.write_run_report(
                            obs.build_run_report(recorder, run=run_info,
                                                 status=status, error=error),
                            report_path)
                    except Exception as e:
                        # Reporting must never mask the run's own outcome.
                        _logger.warning(f"failed to write run report: {e}")

    def _run_checked(self, run_info: Dict[str, Any],
                     detect_errors_only: bool,
                     compute_repair_candidate_prob: bool,
                     compute_repair_prob: bool, compute_repair_score: bool,
                     repair_data: bool,
                     maximal_likelihood_repair: bool) -> pd.DataFrame:
        if self.input is None or self.row_id is None:
            raise ValueError("`setInput` and `setRowId` should be called before repairing")

        if maximal_likelihood_repair and self.repair_delta is None:
            raise ValueError(
                "`setRepairDelta` should be called when enabling "
                "maximal likelihood repairing")
        if maximal_likelihood_repair and self.cf is None:
            raise ValueError(
                "`setUpdateCostFunction` should be called when enabling "
                "maximal likelihood repairing")
        if maximal_likelihood_repair and len(self.cf.targets) > 0:  # type: ignore
            raise ValueError(
                "`UpdateCostFunction.targets` cannot be used when enabling "
                "maximal likelihood repairing")

        exclusive_params = [
            ("detect_errors_only", detect_errors_only),
            ("compute_repair_candidate_prob", compute_repair_candidate_prob),
            ("compute_repair_prob", compute_repair_prob),
            ("compute_repair_score", compute_repair_score),
            ("repair_data", repair_data),
        ]
        selected = [name for name, value in exclusive_params if value]
        if len(selected) > 1:
            raise ValueError("{} cannot be set to true simultaneously".format(
                to_list_str(selected, sep="/", quote=True)))

        if self._repair_by_nearest_values_enabled and \
                (maximal_likelihood_repair or compute_repair_candidate_prob or
                 compute_repair_prob or compute_repair_score):
            raise ValueError(
                "Cannot repair data by nearest values when enabling "
                "`maximal_likelihood_repair`, `compute_repair_candidate_prob`, "
                "`compute_repair_prob`, or `compute_repair_score`")

        if compute_repair_prob or compute_repair_score:
            compute_repair_candidate_prob = True
        if compute_repair_score:
            maximal_likelihood_repair = True

        with phase_span("input validation"):
            table, input_name, continuous_columns = self._check_input_table()

            if maximal_likelihood_repair and len(continuous_columns) != 0:
                raise ValueError(
                    "Cannot enable the maximal likelihood repair mode "
                    "when continous attributes found")

            if self.targets and \
                    len(set(self.targets) & set(table.column_names)) == 0:
                raise ValueError(
                    f"Target attributes not found in {input_name}: "
                    f"{to_list_str(self.targets)}")

        gauge_set("pipeline.input_rows", table.n_rows)
        gauge_set("pipeline.input_columns", len(table.columns))
        # Surface the device-resident table plane's state in every run
        # report / live scrape so transfer-ledger numbers are interpretable
        # (the A/B toggle is DELPHI_DEVICE_TABLE, see ops/xfer.py).
        from delphi_tpu.ops import xfer
        gauge_set("device_table.enabled", int(xfer.device_table_enabled()))
        # Replicated-pipeline shard plane (DELPHI_SHARD): stamp the rank/
        # world topology and this rank's row span into the run report so
        # the per-phase spans of a 2-rank A/B are attributable — and so a
        # mid-run degrade (shard.world present but shard.degraded counted)
        # is visible at a glance.
        from delphi_tpu.parallel import rowshard
        if rowshard.shard_enabled():
            s_rank, s_world = rowshard.world()
            gauge_set("shard.world", s_world)
            gauge_set("shard.rank", s_rank)
            span = rowshard.active_span(table.n_rows)
            run_info["shard"] = {
                "rank": s_rank, "world": s_world,
                "rows": [int(span[0]), int(span[1])] if span else None,
            }
        run_info.update({
            "input_table": input_name,
            "n_rows": int(table.n_rows),
            "n_columns": len(table.columns),
            "mode": (selected[0] if selected else "repair_candidates"),
        })

        # launch-plan fingerprint: the serve plane scopes requests to its
        # own request fingerprint; outside serve, a table-level one makes
        # plan persistence work for bench/CLI runs when a plan store is
        # armed (DELPHI_PLAN_DIR). Collisions are harmless — the plan
        # signature re-validates the piece set on load.
        from delphi_tpu.parallel import planner
        if planner.current_fingerprint() is None \
                and planner.get_plan_store() is not None:
            plan_scope = planner.plan_fingerprint(
                planner.table_plan_fingerprint(
                    input_name, table.n_rows,
                    [c.name for c in table.columns]))
        else:
            plan_scope = contextlib.nullcontext()
        stack = contextlib.ExitStack()
        stack.enter_context(plan_scope)

        # compile plane: cache config + AOT shape-grid prewarm start here,
        # so the training variants compile in the background while error
        # detection and domain analysis still run
        from delphi_tpu.parallel import compile_plane
        prewarm = compile_plane.maybe_start_prewarm(
            table, continuous_columns, self._row_id, self.targets,
            int(self._get_option_value(*self._opt_max_training_row_num)),
            self.opts)

        from delphi_tpu import incremental
        run_flags = (detect_errors_only, compute_repair_candidate_prob,
                     compute_repair_prob, compute_repair_score, repair_data,
                     maximal_likelihood_repair)
        self._last_incremental = None
        self._last_escalation = None
        try:
            with profile_trace("delphi.repair.run"):
                if incremental.incremental_requested(self):
                    df, elapsed, inc_summary = incremental.run_incremental(
                        self, table, input_name, continuous_columns,
                        run_flags)
                    run_info["incremental"] = inc_summary
                    # service mode echoes the summary per request
                    self._last_incremental = inc_summary
                else:
                    df, elapsed = self._run(
                        table, input_name, continuous_columns,
                        *run_flags)
        finally:
            stack.close()
            if prewarm is not None:
                prewarm.stop()
        _logger.info(f"!!!Total Processing time is {elapsed}(s)!!!")
        if self._last_escalation is not None:
            run_info["escalation"] = self._last_escalation
        run_info["elapsed_s"] = round(elapsed, 6)
        run_info["result_rows"] = int(len(df))
        return df


def _flatten_value(v: Any) -> Any:
    if v is not None and not isinstance(v, dict) and pd.isna(v):
        return None
    elif isinstance(v, (bool, np.bool_)):
        return str(int(v))
    elif isinstance(v, (int, np.integer)):
        return str(int(v))
    elif isinstance(v, (float, np.floating)):
        return str(float(v))
    elif not isinstance(v, dict) and v is not None:
        return str(v)
    return v


def _flatten_column(s: pd.Series) -> np.ndarray:
    """Stringifies one column for the long view without per-row Python work
    where the dtype allows (str(int)/str(float) formatting preserved)."""
    if pd.api.types.is_bool_dtype(s.dtype):
        return s.astype("int64").astype(str).to_numpy(dtype=object)
    if pd.api.types.is_integer_dtype(s.dtype) or pd.api.types.is_float_dtype(s.dtype):
        na = s.isna().to_numpy()
        out = s.astype(str).to_numpy(dtype=object)
        out[na] = None
        return out
    if s.dtype == object:
        inferred = pd.api.types.infer_dtype(s, skipna=True)
        if inferred in ("string", "empty"):
            arr = s.to_numpy(dtype=object).copy()
            arr[s.isna().to_numpy()] = None
            return arr
        arr = s.to_numpy(dtype=object)
        return np.array([_flatten_value(v) for v in arr], dtype=object)
    na = s.isna().to_numpy()
    out = s.astype(str).to_numpy(dtype=object)
    out[na] = None
    return out


def _is_null(v: Any) -> bool:
    return v is None or (not isinstance(v, (list, dict)) and pd.isna(v))


def _null_safe_eq(a: Any, b: Any) -> bool:
    a_null = _is_null(a)
    b_null = _is_null(b)
    if a_null or b_null:
        return a_null and b_null
    return str(a) == str(b)
