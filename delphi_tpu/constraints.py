"""Denial-constraint parsing into a typed predicate IR.

Pure-Python replacement of the reference's regex-based parser
(`DenialConstraints.scala:66-225`), HoloClean syntax:

* two-tuple:  ``t1&t2&EQ(t1.a,t2.a)&IQ(t1.b,t2.b)``
* one-tuple:  ``t1&EQ(t1.Sex,"Female")&EQ(t1.Relationship,"Husband")``
* FD sugar:   ``X->Y`` (expands to EQ(X,X) & IQ(Y,Y))

A parsed constraint is a conjunction of :class:`Predicate` objects; the
violation kernels in :mod:`delphi_tpu.ops.detect` compile them to vectorized
group/compare operations instead of SQL self-joins.
"""

import re
from dataclasses import dataclass
from typing import List, Optional, Sequence, Union

from delphi_tpu.utils import setup_logger

_logger = setup_logger()

OP_SIGNS = ("EQ", "IQ", "LT", "GT")

_IDENT_RE = re.compile(r"^[a-zA-Z]+[a-zA-Z0-9]*$")


@dataclass(frozen=True)
class AttrRef:
    name: str

    def __str__(self) -> str:
        return self.name


@dataclass(frozen=True)
class Constant:
    value: str

    def __str__(self) -> str:
        return self.value

    @property
    def literal(self) -> str:
        """The constant with surrounding quotes stripped."""
        v = self.value
        if len(v) >= 2 and v[0] == v[-1] and v[0] in "\"'":
            return v[1:-1]
        return v


Expr = Union[AttrRef, Constant]


@dataclass(frozen=True)
class Predicate:
    """sign in {EQ, IQ, LT, GT}; left binds to tuple t1, right to t2
    (or to a constant for one-tuple constraints)."""

    sign: str
    left: Expr
    right: Expr

    @property
    def references(self) -> List[str]:
        refs = []
        for e in (self.left, self.right):
            if isinstance(e, AttrRef) and e.name not in refs:
                refs.append(e.name)
        return refs

    @property
    def is_cross_tuple(self) -> bool:
        return isinstance(self.left, AttrRef) and isinstance(self.right, AttrRef)


@dataclass
class DenialConstraints:
    predicates: List[List[Predicate]]  # one conjunction per constraint
    references: List[str]

    @property
    def is_empty(self) -> bool:
        return not self.predicates


EMPTY_CONSTRAINTS = DenialConstraints([], [])


def _parse_two_tuple(t1: str, t2: str, terms: List[str]) -> List[Predicate]:
    pattern = re.compile(
        rf"({'|'.join(OP_SIGNS)})\s*\(\s*{re.escape(t1)}\.(.*)\s*,\s*{re.escape(t2)}\.(.*)\s*\)")
    preds = []
    bad = []
    for term in terms:
        m = pattern.fullmatch(term)
        if m:
            preds.append(Predicate(m.group(1), AttrRef(m.group(2).strip()),
                                   AttrRef(m.group(3).strip())))
        else:
            bad.append(term)
    if bad:
        raise ValueError(f"Illegal predicates found: {', '.join(bad)}")
    return preds


def _parse_one_tuple(t1: str, terms: List[str]) -> List[Predicate]:
    pattern = re.compile(
        rf"({'|'.join(OP_SIGNS)})\s*\(\s*{re.escape(t1)}\.(.*)\s*,\s*(.*)\)")
    preds = []
    bad = []
    for term in terms:
        m = pattern.fullmatch(term)
        if m:
            preds.append(Predicate(m.group(1), AttrRef(m.group(2).strip()),
                                   Constant(m.group(3).strip())))
        else:
            bad.append(term)
    if bad:
        raise ValueError(f"Illegal predicates found: {', '.join(bad)}")
    return preds


def parse(stmt: str) -> List[Predicate]:
    """Parses the `t1&t2&PRED&...` / `t1&PRED&...` forms
    (DenialConstraints.scala:128-182)."""
    parts = [p.strip() for p in stmt.split("&")]
    if len(parts) >= 2 and _IDENT_RE.match(parts[0]) and _IDENT_RE.match(parts[1]):
        terms = parts[2:]
        if len(terms) < 2:
            raise ValueError(
                f"At least two predicate candidates should be given, "
                f"but {len(terms)} candidates found: {stmt}")
        return _parse_two_tuple(parts[0], parts[1], terms)
    if parts and _IDENT_RE.match(parts[0]):
        terms = parts[1:]
        if len(terms) < 2:
            raise ValueError(
                f"At least two predicate candidates should be given, "
                f"but {len(terms)} candidates found: {stmt}")
        return _parse_one_tuple(parts[0], terms)
    if any(parts):
        raise ValueError(f"Failed to parse an input string: '{stmt}'")
    return []


def parse_alt(stmt: str) -> List[Predicate]:
    """Parses the `X->Y` FD sugar (DenialConstraints.scala:185-195)."""
    parts = [p.strip() for p in stmt.split("->") if p.strip()]
    if len(parts) == 2:
        x, y = parts
        return [Predicate("EQ", AttrRef(x), AttrRef(x)),
                Predicate("IQ", AttrRef(y), AttrRef(y))]
    if parts:
        raise ValueError(f"Failed to parse an input string: '{stmt}'")
    return []


def load_constraint_stmts_from_file(path: Optional[str]) -> List[str]:
    if path and path.strip():
        try:
            with open(path) as f:
                return [line.rstrip("\n") for line in f]
        except OSError:
            _logger.warning(f"Failed to load constrains from '{path}'")
            return []
    return []


def load_constraint_stmts_from_string(s: Optional[str]) -> List[str]:
    if s:
        return [p.strip() for p in s.split(";") if p.strip()]
    return []


def parse_and_verify_constraints(stmts: Sequence[str], input_name: str,
                                 table_attrs: Sequence[str]) -> DenialConstraints:
    """Parses each statement (falling back to FD sugar), then drops
    constraints that reference non-existent attributes
    (DenialConstraints.scala:82-119)."""
    parsed: List[List[Predicate]] = []
    for stmt in stmts:
        try:
            try:
                preds = parse(stmt)
            except ValueError:
                preds = parse_alt(stmt)
            if preds:
                parsed.append(preds)
        except ValueError:
            _logger.warning(f"Illegal constraint format found: {stmt}")

    refs: List[str] = []
    for preds in parsed:
        for p in preds:
            for r in p.references:
                if r not in refs:
                    refs.append(r)

    attr_set = set(table_attrs)
    absent = [r for r in refs if r not in attr_set]
    if absent:
        _logger.warning(
            f"Non-existent constraint attributes found in '{input_name}': "
            f"{', '.join(absent)}")
        kept = [preds for preds in parsed
                if all(r in attr_set for p in preds for r in p.references)]
        if not kept:
            return EMPTY_CONSTRAINTS
        return DenialConstraints(kept, [r for r in refs if r in attr_set])

    return DenialConstraints(parsed, refs)
