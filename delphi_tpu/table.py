"""Core table layer: dictionary-encoded columnar tables.

This replaces the reference's Spark-side table handling — input validation
(`RepairApi.scala:34-67`), type whitelists (`RepairBase.scala:41-44`),
discretization (`RepairApi.scala:126-169`) and error-cell NULL masking
(`RepairApi.scala:171-211`) — with a TPU-first design: every attribute is
dictionary-encoded into an ``int32`` code column (NULL = -1) so that all
downstream statistics (frequency counts, entropies, domain scoring, constraint
checks) run as dense integer kernels on device over an ``int32[rows, attrs]``
tensor instead of generated SQL.
"""

from dataclasses import dataclass, field, replace
from typing import Any, Dict, List, Optional, Sequence, Tuple

import os

import numpy as np
import pandas as pd

from delphi_tpu.session import AnalysisException
from delphi_tpu.utils import setup_logger
from delphi_tpu.utils.native import get_dict_encoder

_logger = setup_logger()

# Type kinds, mirroring the reference's whitelist (RepairBase.scala:41-44):
# integral+fractional are "continuous", strings are "discrete"; anything else
# is unsupported.
KIND_STRING = "string"
KIND_INTEGRAL = "integral"
KIND_FRACTIONAL = "fractional"

NULL_CODE = -1

def column_kind(series: pd.Series) -> str:
    dt = series.dtype
    if pd.api.types.is_bool_dtype(dt):
        raise AnalysisException(
            "Supported types are tinyint,smallint,int,bigint,float,double,string, but "
            "unsupported ones found: boolean")
    if pd.api.types.is_integer_dtype(dt):
        return KIND_INTEGRAL
    if pd.api.types.is_float_dtype(dt):
        return KIND_FRACTIONAL
    if pd.api.types.is_object_dtype(dt) or pd.api.types.is_string_dtype(dt):
        return KIND_STRING
    raise AnalysisException(
        "Supported types are tinyint,smallint,int,bigint,float,double,string, but "
        f"unsupported ones found: {dt}")


def normalize_neg_zero(values: np.ndarray) -> np.ndarray:
    """Folds -0.0 into +0.0 in float arrays. Hash-based paths (factorize,
    nunique) already treat the two as one value; normalizing before encoding
    pins the SPELLING to '0.0' regardless of which appeared first, instead of
    letting a leading -0.0 name the merged vocab entry '-0.0'."""
    if values.dtype.kind == "f":
        return np.where(values == 0.0, 0.0, values)
    return values


def _value_strings(series: pd.Series, kind: str) -> np.ndarray:
    """String representation of values, matching SQL CAST(x AS STRING).

    Formats via the DISTINCT values (factorize, then ``str()`` each unique)
    so the per-cell cost is a C-speed hash pass instead of a Python lambda
    per row — ``str(int)`` / ``str(float)`` are injective on the raw values
    (after -0.0 normalization, see ``normalize_neg_zero``), so
    first-appearance order and the produced strings are identical to the
    per-row path. Plain-string columns pass through with only NULL masking;
    object columns holding non-str values keep the exact per-row ``str()``
    semantics (distinct objects with equal string forms must still merge)."""
    if kind in (KIND_INTEGRAL, KIND_FRACTIONAL):
        codes, uniques = pd.factorize(normalize_neg_zero(series.to_numpy()),
                                      use_na_sentinel=True)
        cast = (lambda v: str(int(v))) if kind == KIND_INTEGRAL \
            else (lambda v: str(float(v)))
        lut = np.array([cast(v) for v in uniques], dtype=object)
        out = np.empty(len(codes), dtype=object)
        valid = codes >= 0
        out[valid] = lut[codes[valid]]
        out[~valid] = None
        return out
    if pd.api.types.infer_dtype(series, skipna=True) in ("string", "empty"):
        # to_numpy copies when it applies na_value, so the source series'
        # buffer is never mutated
        return series.to_numpy(dtype=object, na_value=None)
    return series.map(_cast_object_value).to_numpy(dtype=object)


def _cast_object_value(v: Any) -> Optional[str]:
    """SQL CAST(x AS STRING) for a boxed value in an object column: numerics
    widen through int/float (np.float32(0.1) spells as the double
    '0.10000000149011612', not '0.1'), matching what the value would have
    spelled in a properly typed column."""
    if pd.isna(v):
        return None
    if isinstance(v, (bool, np.bool_)):
        return str(int(v))
    if isinstance(v, (int, np.integer)):
        return str(int(v))
    if isinstance(v, (float, np.floating)):
        return str(float(v))
    return str(v)


@dataclass
class EncodedColumn:
    """One dictionary-encoded attribute.

    ``codes`` holds int32 dictionary codes (−1 for NULL) into ``vocab`` — the
    distinct value strings in first-appearance order. Numeric attributes also
    retain a float64 view (NaN for NULL) for regression / outlier kernels.
    """

    name: str
    kind: str
    codes: np.ndarray
    vocab: np.ndarray
    numeric: Optional[np.ndarray] = None

    @property
    def domain_size(self) -> int:
        """# of distinct non-NULL values (Catalyst column-stat distinctCount)."""
        return int(len(self.vocab))

    @property
    def is_numeric(self) -> bool:
        return self.kind in (KIND_INTEGRAL, KIND_FRACTIONAL)

    def null_mask(self) -> np.ndarray:
        return self.codes == NULL_CODE

    def decode(self, rows: Optional[np.ndarray] = None) -> np.ndarray:
        """Back to an object array of value strings (None for NULL).

        ``rows`` selects a positional subset (in the given order) without
        materializing the full column — the backbone of the phase-2/3
        "decode only what you train on / repair" path."""
        codes = self.codes if rows is None else self.codes[rows]
        out = np.empty(len(codes), dtype=object)
        valid = codes >= 0
        out[valid] = self.vocab[codes[valid]]
        out[~valid] = None
        return out


def encode_column(series: pd.Series, name: Optional[str] = None) -> EncodedColumn:
    """Dictionary-encodes one attribute.

    Numeric columns factorize the RAW values in one C hash pass and only
    format the (small) set of distinct values to strings — ``str(int)`` /
    ``str(float)`` are injective on the raw values, so codes and
    first-appearance order match encoding the formatted strings. String
    columns factorize their cast strings. The native C++ encoder is opt-in
    (``DELPHI_NATIVE_ENCODE=1``): its per-value ctypes marshalling costs
    more than pandas' vectorized hash table at millions of rows.
    """
    kind = column_kind(series)
    if kind in (KIND_INTEGRAL, KIND_FRACTIONAL):
        codes, raw_uniques = pd.factorize(normalize_neg_zero(series.to_numpy()),
                                          use_na_sentinel=True)
        cast = (lambda v: str(int(v))) if kind == KIND_INTEGRAL \
            else (lambda v: str(float(v)))
        uniques: Any = np.array([cast(v) for v in raw_uniques], dtype=object)
    else:
        strings = _value_strings(series, kind)
        encoder = get_dict_encoder() \
            if os.environ.get("DELPHI_NATIVE_ENCODE") == "1" else None
        if encoder is not None:
            codes, uniques = encoder.encode(strings.tolist())
        else:
            codes, uniques = pd.factorize(strings, use_na_sentinel=True)
    col = EncodedColumn(
        name=name or str(series.name),
        kind=kind,
        codes=np.asarray(codes, dtype=np.int32),
        vocab=np.asarray(uniques, dtype=object),
    )
    if kind in (KIND_INTEGRAL, KIND_FRACTIONAL):
        col.numeric = normalize_neg_zero(
            pd.to_numeric(series, errors="coerce").to_numpy(dtype=np.float64))
    return col


@dataclass
class EncodedTable:
    """A row-id column plus dictionary-encoded attribute columns.

    The ``codes()`` matrix (``int32[n_rows, n_attrs]``) is the canonical
    device-side representation: row-shardable over a mesh, NULL = −1.
    """

    row_id: str
    row_id_values: np.ndarray
    row_id_kind: str
    columns: List[EncodedColumn] = field(default_factory=list)
    # True when this table holds only THIS PROCESS's row shard of a larger
    # multi-host table (sharded ingestion): vocabularies are globally
    # unified, rows are local. The repair pipeline then runs its global
    # reductions through cross-process collectives and everything
    # row-dimensional per process — no host ever materializes the table.
    process_local: bool = False

    @property
    def n_rows(self) -> int:
        return int(len(self.row_id_values))

    @property
    def column_names(self) -> List[str]:
        return [c.name for c in self.columns]

    def column(self, name: str) -> EncodedColumn:
        for c in self.columns:
            if c.name == name:
                return c
        raise AnalysisException(f"Column '{name}' not found")

    def has_column(self, name: str) -> bool:
        return any(c.name == name for c in self.columns)

    def codes(self, names: Optional[Sequence[str]] = None) -> np.ndarray:
        cols = [self.column(n) for n in names] if names is not None else self.columns
        if not cols:
            return np.zeros((self.n_rows, 0), dtype=np.int32)
        return np.stack([c.codes for c in cols], axis=1)

    def domain_stats(self) -> Dict[str, int]:
        return {c.name: c.domain_size for c in self.columns}

    def continuous_columns(self) -> List[str]:
        return [c.name for c in self.columns if c.is_numeric]

    def value_string(self, name: str, row: int) -> Optional[str]:
        c = self.column(name)
        code = int(c.codes[row])
        return None if code == NULL_CODE else str(c.vocab[code])

    def row_index(self) -> Dict[object, int]:
        return {rid: i for i, rid in enumerate(self.row_id_values.tolist())}

    def to_pandas(self, rows: Optional[np.ndarray] = None,
                  columns: Optional[Sequence[str]] = None,
                  integral_as_float: Optional[Sequence[str]] = None) -> pd.DataFrame:
        """Decode to a pandas frame with original dtypes (numeric restored).

        ``rows`` (positional, order-preserving) and ``columns`` decode only a
        subset. Dtype restoration is decided on the FULL column — an integral
        column decodes to int64 only when the whole column is NaN-free — so a
        subset frame carries the same dtypes the full decode would, however
        the subset happens to look. ``integral_as_float``, when given (even
        empty), is the caller's COMPLETE float-forcing decision — integral
        columns named in it decode as float64, the rest as int64 with no
        per-call NaN re-scan. Callers that snapshot dtypes once and then
        decode many subsets (phase 2-3 training samples, chunked repair)
        compute it up front; passing None falls back to scanning each
        integral column for NaNs here."""
        data: Dict[str, object] = {
            self.row_id: self.row_id_values if rows is None
            else self.row_id_values[rows]}
        force_float = None if integral_as_float is None \
            else set(integral_as_float)
        cols = self.columns if columns is None \
            else [self.column(n) for n in columns]
        for c in cols:
            if c.is_numeric:
                assert c.numeric is not None
                numeric = c.numeric if rows is None else c.numeric[rows]
                as_int = c.kind == KIND_INTEGRAL and (
                    c.name not in force_float if force_float is not None
                    else not np.isnan(c.numeric).any())
                if as_int:
                    data[c.name] = numeric.astype(np.int64)
                else:
                    data[c.name] = numeric
            else:
                data[c.name] = c.decode(rows)
        return pd.DataFrame(data)

    def take_rows(self, positions: np.ndarray) -> "EncodedTable":
        """Returns a positional row-subset copy (rows in the given order).

        Vocabularies carry over unchanged — a subset column may hold unused
        vocab entries, which downstream consumers tolerate (class counts and
        domains derive from the codes actually present). The backbone of the
        incremental plane's "re-run only the planned rows" path."""
        positions = np.asarray(positions, dtype=np.int64)
        new_columns = [
            replace(
                c,
                codes=np.ascontiguousarray(c.codes[positions]),
                numeric=np.ascontiguousarray(c.numeric[positions])
                if c.numeric is not None else None)
            for c in self.columns]
        return replace(self, row_id_values=self.row_id_values[positions],
                       columns=new_columns)

    def with_updates(self, cells: Sequence[Tuple[int, str, Any]]) -> "EncodedTable":
        """Returns a copy with (row_index, attribute, value) cells updated —
        the encoded-tensor equivalent of applying rule repairs with
        `repairAttrsFrom` (RepairMiscApi.scala:184-247): continuous columns
        cast the repaired string to float (integral: rounded), and novel
        values extend the column vocab."""
        by_attr: Dict[str, List[Tuple[int, Any]]] = {}
        for row, attr, value in cells:
            by_attr.setdefault(attr, []).append((row, value))
        new_columns = []
        for c in self.columns:
            if c.name not in by_attr:
                new_columns.append(c)
                continue
            updates = by_attr[c.name]
            codes = c.codes.copy()
            numeric = c.numeric.copy() if c.numeric is not None else None
            vocab_index = {v: i for i, v in enumerate(c.vocab.tolist())}
            vocab_list = c.vocab.tolist()
            for row, value in updates:
                if value is None or (not isinstance(value, (list, dict))
                                     and pd.isna(value)):
                    codes[row] = NULL_CODE
                    if numeric is not None:
                        numeric[row] = np.nan
                    continue
                if c.kind == KIND_INTEGRAL:
                    num = float(np.round(float(value)))
                    if num == 0.0:
                        num = 0.0  # fold -0.0 (round(-0.4)) into +0.0
                    s = str(int(num))
                elif c.kind == KIND_FRACTIONAL:
                    num = float(value)
                    if num == 0.0:
                        num = 0.0  # same -0.0 fold as normalize_neg_zero
                    s = str(num)
                else:
                    num = None
                    s = str(value)
                if s not in vocab_index:
                    vocab_index[s] = len(vocab_list)
                    vocab_list.append(s)
                codes[row] = vocab_index[s]
                if numeric is not None:
                    numeric[row] = num
            new_columns.append(replace(
                c, codes=codes, numeric=numeric,
                vocab=np.asarray(vocab_list, dtype=object)))
        return replace(self, columns=new_columns)

    def with_nulls_at(self, cells: Sequence[Tuple[int, str]]) -> "EncodedTable":
        """Returns a copy with the given (row_index, attribute) cells NULLed —
        the encoded-tensor equivalent of `convertErrorCellsToNull`
        (RepairApi.scala:171-211)."""
        rows = np.fromiter((r for r, _ in cells), dtype=np.int64,
                           count=len(cells))
        attrs = np.array([a for _, a in cells], dtype=object)
        return self.with_nulls_at_arrays(rows, attrs)

    def with_nulls_at_arrays(self, rows: np.ndarray,
                             attrs: np.ndarray) -> "EncodedTable":
        """`with_nulls_at` over aligned (row positions, attribute) arrays:
        cells group per attribute through one factorize pass instead of a
        Python loop building tuples — at the 1e8-row scale the masking
        input is tens of millions of cells."""
        attr_codes, attr_uniques = pd.factorize(np.asarray(attrs, dtype=object))
        rows = np.asarray(rows, dtype=np.int64)
        by_attr: Dict[str, np.ndarray] = {
            str(a): rows[attr_codes == ai]
            for ai, a in enumerate(attr_uniques)}
        new_columns = []
        for c in self.columns:
            idx = by_attr.get(c.name)
            if idx is not None and len(idx):
                codes = c.codes.copy()
                codes[idx] = NULL_CODE
                numeric = None
                if c.numeric is not None:
                    numeric = c.numeric.copy()
                    numeric[idx] = np.nan
                new_columns.append(replace(c, codes=codes, numeric=numeric))
            else:
                new_columns.append(c)
        return replace(self, columns=new_columns)


def encode_table(df: pd.DataFrame, row_id: str) -> EncodedTable:
    if row_id not in df.columns:
        raise AnalysisException(f"Column '{row_id}' does not exist")
    table = EncodedTable(
        row_id=row_id,
        row_id_values=df[row_id].to_numpy(),
        row_id_kind=column_kind(df[row_id]),
    )
    for name in df.columns:
        if name == row_id:
            continue
        table.columns.append(encode_column(df[name], name))
    return table


def check_input_table(df: pd.DataFrame, row_id: str, qualified_name: str = "input") \
        -> Tuple[EncodedTable, List[str]]:
    """Input validation, mirroring `RepairApi.checkInputTable`
    (RepairApi.scala:34-67): type whitelist, ≥3 columns, row-id uniqueness.
    Returns the encoded table and the list of continuous (numeric) attributes.
    """
    for name in df.columns:
        column_kind(df[name])  # raises AnalysisException on unsupported types

    if len(df.columns) < 3:
        raise AnalysisException(
            f"A least three columns (`{row_id}` columns + two more ones) "
            f"in table '{qualified_name}'")

    if row_id not in df.columns:
        raise AnalysisException(f"Column '{row_id}' does not exist in '{qualified_name}'.")

    n_rows = len(df)
    n_distinct = df[row_id].nunique(dropna=False)
    if n_distinct != n_rows:
        raise AnalysisException(
            f"Uniqueness does not hold in column '{row_id}' of table '{qualified_name}' "
            f"(# of distinct '{row_id}': {n_distinct}, # of rows: {n_rows})")

    table = encode_table(df, row_id)
    return table, table.continuous_columns()


def check_encoded_table(table: EncodedTable, row_id: str,
                        qualified_name: str = "input") \
        -> Tuple[EncodedTable, List[str]]:
    """`check_input_table` for a pre-encoded table (chunked ingestion): same
    validations, no re-encode — the type whitelist already held at encode
    time, so only shape and row-id checks remain."""
    if table.row_id != row_id:
        raise AnalysisException(
            f"Column '{row_id}' does not exist in '{qualified_name}'.")
    if len(table.columns) < 2:
        raise AnalysisException(
            f"A least three columns (`{row_id}` columns + two more ones) "
            f"in table '{qualified_name}'")
    n_rows = table.n_rows
    n_distinct = len(pd.unique(table.row_id_values))
    if n_distinct != n_rows:
        raise AnalysisException(
            f"Uniqueness does not hold in column '{row_id}' of table "
            f"'{qualified_name}' (# of distinct '{row_id}': {n_distinct}, "
            f"# of rows: {n_rows})")
    return table, table.continuous_columns()


@dataclass
class DiscretizedTable:
    """The discretized view used by the stats engine.

    Continuous attributes are equi-width binned into ``[0, discrete_threshold]``
    (the reference truncates `int((v - min) / (max - min) * threshold)` so the
    max value lands in bin == threshold — `RepairApi.scala:139`); discrete
    attributes with domain size in (1, threshold] are kept as-is; everything
    else is dropped (`RepairApi.scala:126-149`).

    ``domain_stats`` intentionally records the ORIGINAL distinct counts (not
    bin counts) to match `convertToDiscretizedTable` (RepairApi.scala:151-169),
    which feeds those into entropy corrections and domain thresholds.
    """

    base: EncodedTable
    table: EncodedTable
    domain_stats: Dict[str, int]

    @property
    def column_names(self) -> List[str]:
        return self.table.column_names


def discretize_table(table: EncodedTable, discrete_threshold: int) -> DiscretizedTable:
    assert 2 <= discrete_threshold < 65536, "discreteThreshold should be in [2, 65536)."

    process_local = table.process_local

    out_columns: List[EncodedColumn] = []
    domain_stats: Dict[str, int] = {}
    for c in table.columns:
        domain_stats[c.name] = c.domain_size
        if c.is_numeric:
            assert c.numeric is not None
            valid = ~np.isnan(c.numeric)
            any_valid = bool(valid.any())
            vmin = float(np.nanmin(c.numeric)) if any_valid else np.inf
            vmax = float(np.nanmax(c.numeric)) if any_valid else -np.inf
            if process_local:
                # bin fences must come from the GLOBAL extrema so every
                # process bins its shard identically
                from delphi_tpu.parallel.distributed import allgather_max
                vmax, neg_vmin = (float(v) for v in allgather_max(
                    np.asarray([vmax, -vmin], dtype=np.float64)))
                vmin = -neg_vmin
                any_valid = np.isfinite(vmin)
            if not any_valid:
                _logger.warning(f"'{c.name}' dropped because it has no non-NULL value")
                continue
            width = vmax - vmin
            bins = np.full(table.n_rows, NULL_CODE, dtype=np.int64)
            if width > 0.0:
                scaled = (c.numeric[valid] - vmin) / width * discrete_threshold
                bins[valid] = scaled.astype(np.int64)
            else:
                bins[valid] = 0
            # Re-encode bins compactly: vocab entries are the bin values as
            # strings (what CAST(int AS STRING) would yield in the
            # reference). Process-local shards take the GLOBAL present-bin
            # union so codes stay comparable across processes.
            if process_local:
                from delphi_tpu.parallel.distributed import allgather_any
                mask = np.zeros(discrete_threshold + 1, dtype=bool)
                local_present = np.unique(bins[bins >= 0])
                mask[local_present] = True
                present = np.nonzero(allgather_any(mask))[0]
            else:
                present = np.unique(bins[bins >= 0])
            remap = {int(b): i for i, b in enumerate(present)}
            codes = np.array([remap[int(b)] if b >= 0 else NULL_CODE for b in bins],
                             dtype=np.int32)
            vocab = np.asarray([str(int(b)) for b in present], dtype=object)
            out_columns.append(EncodedColumn(name=c.name, kind=KIND_STRING,
                                             codes=codes, vocab=vocab))
        elif 1 < c.domain_size <= discrete_threshold:
            out_columns.append(c)
        else:
            _logger.warning(
                f"'{c.name}' dropped because of its unsuitable domain (size={c.domain_size})")

    discretized = EncodedTable(
        row_id=table.row_id,
        row_id_values=table.row_id_values,
        row_id_kind=table.row_id_kind,
        columns=out_columns,
        process_local=process_local,
    )
    return DiscretizedTable(base=table, table=discretized, domain_stats=domain_stats)
