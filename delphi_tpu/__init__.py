"""delphi_tpu — TPU-native statistical data repair.

A brand-new framework with the capabilities of maropu/spark-data-repair-plugin
(error-cell detection + statistical repair), built on JAX/XLA: tables are
dictionary-encoded into row-shardable ``int32`` tensors and all statistics,
detection, domain analysis, model training and repair inference run as jitted
kernels on a device mesh.

Public surface mirrors the reference:

    from delphi_tpu import delphi
    delphi.register_table("adult", df)
    repaired = delphi.repair \\
        .setInput("adult").setRowId("tid") \\
        .setErrorDetectors([NullErrorDetector()]) \\
        .run()
"""

from delphi_tpu.api import Delphi
from delphi_tpu.costs import Levenshtein, UpdateCostFunction, UserDefinedUpdateCostFunction
from delphi_tpu.errors import (
    ConstraintErrorDetector, DomainValues, ErrorDetector, GaussianOutlierErrorDetector,
    LOFOutlierErrorDetector, NullErrorDetector, RegExErrorDetector,
    ScikitLearnBackedErrorDetector, ScikitLearnBasedErrorDetector)
from delphi_tpu.misc import RepairMisc
from delphi_tpu.model import FunctionalDepModel, PoorModel, RepairModel

delphi = Delphi.getOrCreate()

__version__ = "0.1.0"

__all__ = [
    "Delphi", "delphi", "RepairModel", "RepairMisc", "PoorModel",
    "FunctionalDepModel", "ErrorDetector", "NullErrorDetector", "DomainValues",
    "RegExErrorDetector", "ConstraintErrorDetector", "GaussianOutlierErrorDetector",
    "ScikitLearnBasedErrorDetector", "ScikitLearnBackedErrorDetector",
    "LOFOutlierErrorDetector", "UpdateCostFunction", "Levenshtein",
    "UserDefinedUpdateCostFunction",
]
