"""delphi_tpu — TPU-native statistical data repair.

A brand-new framework with the capabilities of maropu/spark-data-repair-plugin
(error-cell detection + statistical repair), built on JAX/XLA: tables are
dictionary-encoded into row-shardable ``int32`` tensors and all statistics,
detection, domain analysis, model training and repair inference run as jitted
kernels on a device mesh.

Public surface mirrors the reference:

    from delphi_tpu import delphi
    delphi.register_table("adult", df)
    repaired = delphi.repair \\
        .setInput("adult").setRowId("tid") \\
        .setErrorDetectors([NullErrorDetector()]) \\
        .run()
"""

import os as _os

# Optional XLA:CPU codegen cap (DELPHI_CPU_MAX_ISA=AVX2): on current
# AVX512/AMX Xeons, wide-vocabulary one-hot matmul heads run ~2x faster with
# LLVM capped to AVX2 (512-bit scatter is microcoded and downclocks), but
# the GBDT histogram kernels lose ~10%, so the cap is opt-in rather than a
# default — measured end-to-end it is neutral on the flights/hospital
# workloads. An explicit xla_cpu_max_isa in XLA_FLAGS always wins.
_isa = _os.environ.get("DELPHI_CPU_MAX_ISA", "")
if _isa and "xla_cpu_max_isa" not in _os.environ.get("XLA_FLAGS", ""):
    _os.environ["XLA_FLAGS"] = (_os.environ.get("XLA_FLAGS", "")
                                + f" --xla_cpu_max_isa={_isa}").strip()

import jax as _jax

# Persistent XLA compilation cache: the training/stats kernels take tens of
# seconds to compile on TPU and the pipeline is typically re-run many times
# over similar shapes; caching compiled executables across processes removes
# that cost from every run after the first. Opt out with DELPHI_XLA_CACHE=0.
if _os.environ.get("DELPHI_XLA_CACHE", "1") != "0":
    try:
        import hashlib as _hashlib

        # Scope the cache by the XLA configuration AND the host CPU: entries
        # AOT-compiled under different XLA_FLAGS (e.g. the 8-virtual-device
        # test config) are not safely loadable in other configs, and
        # executables compiled on a host with different CPU features load
        # with SIGILL risk (xla's cpu_aot_loader warns loudly), so a moved
        # checkout starts a fresh cache instead of limping on a stale one.
        try:
            with open("/proc/cpuinfo") as _f:
                _cpu = next((ln for ln in _f
                             if ln.startswith(("flags", "Features"))), "")
        except OSError:
            _cpu = ""
        if not _cpu:  # non-x86/arm cpuinfo layouts
            import platform as _platform
            _cpu = _platform.processor() or _platform.machine()
        _fingerprint = _hashlib.sha1(
            (_os.environ.get("XLA_FLAGS", "") + "|"
             + _os.environ.get("JAX_PLATFORMS", "") + "|"
             + _cpu).encode()).hexdigest()[:12]
        # DELPHI_COMPILE_CACHE_DIR pins an explicit, fingerprint-free dir
        # (the compile plane's knob — callers who set it own the config
        # scoping); DELPHI_XLA_CACHE_DIR is the legacy spelling.
        _cache_dir = _os.environ.get("DELPHI_COMPILE_CACHE_DIR") \
            or _os.environ.get(
                "DELPHI_XLA_CACHE_DIR",
                _os.path.join(_os.path.expanduser("~"), ".cache",
                              f"delphi_tpu_xla_{_fingerprint}"))
        _os.makedirs(_cache_dir, exist_ok=True)
        _jax.config.update("jax_compilation_cache_dir", _cache_dir)
        _jax.config.update("jax_persistent_cache_min_compile_time_secs",
                           float(_os.environ.get(
                               "DELPHI_COMPILE_CACHE_MIN_S", 1)))
        _jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:
        pass

from delphi_tpu.api import Delphi
from delphi_tpu.costs import Levenshtein, UpdateCostFunction, UserDefinedUpdateCostFunction
from delphi_tpu.errors import (
    ConstraintErrorDetector, DomainValues, ErrorDetector, GaussianOutlierErrorDetector,
    LOFOutlierErrorDetector, NullErrorDetector, RegExErrorDetector,
    ScikitLearnBackedErrorDetector, ScikitLearnBasedErrorDetector)
from delphi_tpu.misc import RepairMisc
from delphi_tpu.model import FunctionalDepModel, PoorModel, RepairModel

delphi = Delphi.getOrCreate()

__version__ = "0.1.0"

__all__ = [
    "Delphi", "delphi", "RepairModel", "RepairMisc", "PoorModel",
    "FunctionalDepModel", "ErrorDetector", "NullErrorDetector", "DomainValues",
    "RegExErrorDetector", "ConstraintErrorDetector", "GaussianOutlierErrorDetector",
    "ScikitLearnBasedErrorDetector", "ScikitLearnBackedErrorDetector",
    "LOFOutlierErrorDetector", "UpdateCostFunction", "Levenshtein",
    "UserDefinedUpdateCostFunction",
]
