"""Elastic repair fleet: N ``RepairServer`` workers behind one router.

One warm process is one fault domain. ``FleetRouter`` scales the serving
plane out: it spawns (or attaches to) N worker processes that share one
cache root — compile cache, snapshot dirs, per-fingerprint model and
phase checkpoints all live under it — and fronts them with the same
stdlib HTTP stack as :mod:`delphi_tpu.observability.serve`.

Routing is **rendezvous hashing** on the request's table fingerprint
(:func:`~delphi_tpu.observability.serve.table_fingerprint`, the same
blob the workers' warm-table caches key on): the highest-scoring live
worker owns a fingerprint, so repeated tables land on the replica whose
device buffers, models, and compiled executables are already warm, and a
membership change only remaps the fingerprints that scored the departed
worker highest — every other fingerprint keeps its home.

Membership is **derived from the dist-resilience liveness files**: each
worker heartbeats ``rank_<id>.alive`` under the shared fleet dir (the
exact file format the PR 11 rank diagnosis reads), and the router's
:meth:`FleetRouter.refresh_membership` scan evicts any worker whose
stamp goes stale — stalled and dead look identical from outside, and
both mean "stop routing there". A cleanly draining worker unregisters
*before* closing admission, so the ring shrinks ahead of the 503s.

Failure handling on the hot path:

* a worker answering **429/503-rejected** is shedding, not broken — the
  router hops to the next-ranked live replica, bounded by
  ``DELPHI_FLEET_MAX_HOPS``, and if *every* live worker sheds it returns
  429 with the **max** observed ``Retry-After`` (never loops);
* a **connection-level failure** (refused/reset — the worker died
  between the membership check and the dispatch) is a ``fleet.dispatch``
  fault: the worker is evicted, its liveness file dropped (a genuinely
  live worker re-touches within one heartbeat and rejoins), and the
  in-flight request is **re-dispatched** to the next-ranked replica —
  idempotent because every request runs under its own ``RequestScope``
  and the response ordering is canonical, so the retry is bit-identical
  to what the dead worker would have answered;
* any other response (200/400/500/504) is definitive and returned
  as-is — a deterministic failure would only repeat elsewhere.

The evicted worker's fingerprints rendezvous-remap to the survivors,
which **rewarm from the shared cache root** (model + phase checkpoints,
compile cache) instead of recomputing from scratch.

All dispatch I/O goes through ONE guarded helper,
:meth:`FleetRouter._dispatch_once` (site ``fleet.dispatch``, registered
in ``KNOWN_SITES`` and chaos-injectable); ``tests/test_transfer_guard``
statically pins that. ``fleet.*`` counters are pre-seeded on the
router's ``/metrics`` at start, and fleet membership rides the run
report's ``dist`` section.
"""

import hashlib
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

from delphi_tpu.observability import trace as _trace
from delphi_tpu.observability.registry import (
    counter_inc, counter_value, gauge_set,
)
from delphi_tpu.observability.serve import (
    _knob_float, _knob_int, chain_fingerprint, table_fingerprint,
)
from delphi_tpu.utils import setup_logger

_logger = setup_logger()

_DEF_FLEET_WORKERS = 2
_DEF_MAX_HOPS = 3
_DEF_SPAWN_TIMEOUT_S = 180.0

#: Pre-seeded at router start so a scrape before the first request (or the
#: first fault) sees the whole fleet series at zero, not a missing metric.
_SEED_COUNTERS = (
    "fleet.requests", "fleet.dispatches", "fleet.redispatches",
    "fleet.evictions", "fleet.rejoins", "fleet.dispatch_faults",
    "fleet.all_shed", "fleet.no_workers",
    "fleet.affinity.hits", "fleet.affinity.misses",
    "fleet.affinity.chain_hits",
    "fleet.registration_corrupt",
    "autoscale.ticks", "autoscale.up", "autoscale.down",
    "autoscale.blocked_cooldown", "autoscale.blocked_hysteresis",
    "autoscale.blocked_limit",
    "trace.traces", "trace.joins", "trace.spans", "trace.exports",
    "launch.ledger.records", "launch.ledger.flushes",
    "launch.ledger.loads", "launch.ledger.consults",
    "launch.ledger.merge_vetoes",
    "store.corrupt", "store.quarantined",
)


def rendezvous_rank(fp: str, members: List[str]) -> List[str]:
    """Members ordered by rendezvous (highest-random-weight) score for
    fingerprint ``fp``, best first. Removing a member never reorders the
    survivors — only the fingerprints the departed member owned remap —
    which is exactly the warm-state-preserving property the fleet needs
    (consistent-hash rings buy the same at far more code)."""
    return sorted(
        members,
        key=lambda m: hashlib.sha1(f"{fp}|{m}".encode()).digest(),
        reverse=True)


class DispatchFault(Exception):
    """A connection-level dispatch failure (refused/reset/timeout) to one
    worker — the signal that the worker, not the request, is broken."""

    def __init__(self, worker_id: str, cause: BaseException) -> None:
        self.worker_id = worker_id
        self.cause = cause
        super().__init__(f"worker {worker_id}: "
                         f"{type(cause).__name__}: {cause}")


class FleetRouter:
    """The fleet front-end. Lifecycle: ``start()`` → (requests...) →
    ``drain()`` (SIGTERMs spawned workers, then stops) or ``stop()``.
    ``spawn=False`` attaches to externally started workers that registered
    under the same cache root."""

    def __init__(self, port: int = 0, workers: Optional[int] = None,
                 cache_dir: Optional[str] = None, spawn: bool = True,
                 max_hops: Optional[int] = None,
                 heartbeat_s: Optional[float] = None,
                 worker_env: Optional[Dict[str, Optional[str]]] = None
                 ) -> None:
        import tempfile

        self.requested_port = int(port)
        self.n_workers = workers if workers is not None else _knob_int(
            "DELPHI_FLEET_WORKERS", "repair.fleet.workers",
            _DEF_FLEET_WORKERS)
        self.n_workers = max(1, int(self.n_workers))
        cache = cache_dir or os.environ.get("DELPHI_SERVE_CACHE_DIR")
        self.cache_dir = str(cache) if cache else tempfile.mkdtemp(
            prefix="delphi_fleet_")
        self.fleet_dir = os.path.join(self.cache_dir, "fleet")
        self.spawn = bool(spawn)
        self.max_hops = max_hops if max_hops is not None else _knob_int(
            "DELPHI_FLEET_MAX_HOPS", "repair.fleet.max_hops", _DEF_MAX_HOPS)
        self.max_hops = max(1, int(self.max_hops))
        self.heartbeat_s = heartbeat_s if heartbeat_s is not None \
            else _knob_float("DELPHI_FLEET_HEARTBEAT_S",
                             "repair.fleet.heartbeat_s", 1.0)
        self.spawn_timeout_s = _knob_float(
            "DELPHI_FLEET_SPAWN_TIMEOUT_S", "repair.fleet.spawn_timeout_s",
            _DEF_SPAWN_TIMEOUT_S)
        self.dispatch_timeout_s = _knob_float(
            "DELPHI_SERVE_DEADLINE_S", "repair.serve.deadline_s",
            300.0) + 30.0
        self.worker_env = dict(worker_env or {})

        self.recorder: Optional[Any] = None
        self._own_recorder: Optional[Any] = None
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._http_thread: Optional[threading.Thread] = None
        self._stopped = threading.Event()
        self._lock = threading.Lock()
        # worker id -> registration info ({"port", "pid", "cache_dir", ...})
        self._workers: Dict[str, Dict[str, Any]] = {}
        self._evicted: Dict[str, str] = {}     # worker id -> reason
        self._live: List[str] = []
        self._procs: Dict[str, subprocess.Popen] = {}

    # -- lifecycle -----------------------------------------------------------

    @property
    def port(self) -> Optional[int]:
        return self._httpd.server_address[1] if self._httpd else None

    def start(self) -> "FleetRouter":
        from delphi_tpu import observability as obs

        os.makedirs(self.fleet_dir, exist_ok=True)
        self._own_recorder = obs.start_recording("repair.fleet")
        self.recorder = self._own_recorder or obs.current_recorder()
        if self.recorder is None:  # pragma: no cover - defensive
            raise RuntimeError("fleet router requires a run recorder")
        for name in _SEED_COUNTERS:
            counter_inc(name, 0)
        gauge_set("fleet.workers", 0)
        gauge_set("fleet.live_workers", 0)
        gauge_set("fleet.evicted_workers", 0)

        if self.spawn:
            for i in range(self.n_workers):
                self._spawn_worker(str(i))
        self._await_registrations()
        self.refresh_membership()

        self._httpd = ThreadingHTTPServer(("127.0.0.1", self.requested_port),
                                          _FleetHandler)
        self._httpd.daemon_threads = True
        self._httpd.fleet_router = self  # type: ignore[attr-defined]
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="delphi-fleet-http")
        self._http_thread.start()
        with self._lock:
            live = list(self._live)
        _logger.info(f"fleet router listening on 127.0.0.1:{self.port} "
                     f"(workers={sorted(self._workers)}, live={live}, "
                     f"cache={self.cache_dir})")
        return self

    def _worker_log_path(self, wid: str) -> str:
        return os.path.join(self.fleet_dir, f"worker_{wid}.log")

    def _spawn_worker(self, wid: str) -> None:
        repo_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env = dict(os.environ)
        env["DELPHI_FLEET_DIR"] = self.fleet_dir
        env["DELPHI_FLEET_WORKER_ID"] = wid
        # the worker's identity for rank-scoped fault plans: a plan like
        # "1:xfer.upload:1:rank_death" kills ONLY worker 1's copy of the
        # request, which is what the chaos A/B leans on
        env["DELPHI_PROCESS_ID"] = wid
        env["PYTHONPATH"] = repo_root + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
        for key, value in self.worker_env.items():
            if value is None:
                env.pop(key, None)
            else:
                env[key] = str(value)
        cmd = [sys.executable, "-m", "delphi_tpu.main", "--serve",
               "--serve-port", "0", "--serve-cache-dir", self.cache_dir]
        log = open(self._worker_log_path(wid), "w")
        try:
            proc = subprocess.Popen(cmd, env=env, cwd=repo_root,
                                    stdout=log, stderr=subprocess.STDOUT)
        finally:
            log.close()
        self._procs[wid] = proc
        _logger.info(f"spawned fleet worker {wid} (pid {proc.pid})")

    def _await_registrations(self) -> None:
        """Blocks until every spawned worker has written its registration
        file; a worker that exits before registering fails the start
        loudly with its log tail (a silently short fleet would masquerade
        as a healthy smaller one)."""
        want = set(self._procs)
        if not want:
            return
        deadline = time.monotonic() + max(1.0, self.spawn_timeout_s)
        while time.monotonic() < deadline:
            regs = self._read_registrations()
            if want <= set(regs):
                return
            for wid, proc in self._procs.items():
                if wid not in regs and proc.poll() is not None:
                    tail = ""
                    try:
                        with open(self._worker_log_path(wid)) as f:
                            tail = f.read()[-2000:]
                    except OSError:
                        pass
                    raise RuntimeError(
                        f"fleet worker {wid} exited rc={proc.returncode} "
                        f"before registering:\n{tail}")
            time.sleep(0.1)
        raise RuntimeError(
            f"fleet workers {sorted(want - set(self._read_registrations()))} "
            f"did not register within {self.spawn_timeout_s:.0f}s")

    def drain(self) -> None:
        """Graceful fleet shutdown: SIGTERM every spawned worker (each
        unregisters first, then drains its own queue), wait for them,
        then stop the router."""
        for wid, proc in self._procs.items():
            if proc.poll() is None:
                try:
                    proc.send_signal(signal.SIGTERM)
                except (ProcessLookupError, OSError):
                    pass
        for wid, proc in self._procs.items():
            try:
                proc.wait(timeout=60.0)
            except subprocess.TimeoutExpired:
                _logger.warning(f"fleet worker {wid} ignored SIGTERM; "
                                "killing")
                proc.kill()
        self.stop()

    def stop(self) -> None:
        if self._stopped.is_set():
            return
        self._stopped.set()
        for proc in self._procs.values():
            if proc.poll() is None:
                proc.kill()
        for proc in self._procs.values():
            try:
                proc.wait(timeout=10.0)
            except subprocess.TimeoutExpired:  # pragma: no cover
                pass
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            if self._http_thread is not None:
                self._http_thread.join(timeout=10.0)
            self._httpd = None
        if self._own_recorder is not None:
            from delphi_tpu import observability as obs
            obs.stop_recording(self._own_recorder)
            self._own_recorder = None
        _logger.info("fleet router stopped")

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._stopped.wait(timeout)

    # -- membership ----------------------------------------------------------

    def _read_registrations(self) -> Dict[str, Dict[str, Any]]:
        """Validated membership reads: a half-written or corrupt
        ``worker_<id>.json`` is treated as not-yet-registered (counted
        ``fleet.registration_corrupt``, quarantined by the store seam)
        instead of raising mid-route — the worker's heartbeat loop
        re-announces it on the next beat."""
        from delphi_tpu.parallel import store as dstore
        regs: Dict[str, Dict[str, Any]] = {}
        try:
            names = os.listdir(self.fleet_dir)
        except OSError:
            return regs
        for name in sorted(names):
            if not (name.startswith("worker_") and name.endswith(".json")):
                continue
            path = os.path.join(self.fleet_dir, name)
            try:
                info, status = dstore.read_json(
                    path, schema="fleet_reg", site="store.fleet",
                    root=self.fleet_dir)
            except Exception:
                counter_inc("fleet.registration_corrupt")
                continue
            if status == "corrupt":
                counter_inc("fleet.registration_corrupt")
                continue
            if not isinstance(info, dict) or "worker_id" not in info:
                # legacy garbage that json-parsed but isn't a registration
                counter_inc("fleet.registration_corrupt")
                continue
            regs[str(info["worker_id"])] = info
        return regs

    def refresh_membership(self, now: Optional[float] = None) -> List[str]:
        """One membership scan: merge worker registrations (new workers
        join the ring elastically), read every liveness file, evict
        workers whose stamp is stale or missing, rejoin workers that came
        back, and drop workers that unregistered cleanly (graceful
        departure, not an eviction). Returns the live ring."""
        from delphi_tpu.parallel import dist_resilience as dr

        regs = self._read_registrations()
        members = dr.scan_membership(self.fleet_dir, self.heartbeat_s,
                                     now=now)
        with self._lock:
            for wid, info in regs.items():
                self._workers[wid] = info
            for wid in list(self._workers):
                if wid not in regs:
                    # registration gone: the worker drained out cleanly
                    self._workers.pop(wid, None)
                    self._evicted.pop(wid, None)
                    _logger.info(f"fleet worker {wid} departed (drained)")
            live: List[str] = []
            for wid in sorted(self._workers):
                status = members.get(wid, {}).get("status", "unknown")
                if status == "live":
                    if wid in self._evicted:
                        del self._evicted[wid]
                        counter_inc("fleet.rejoins")
                        _logger.info(f"fleet worker {wid} rejoined the ring")
                    live.append(wid)
                elif wid not in self._evicted:
                    self._evicted[wid] = f"liveness {status}"
                    counter_inc("fleet.evictions")
                    _logger.warning(f"fleet worker {wid} evicted: "
                                    f"liveness {status}")
            self._live = live
            n_workers, n_evicted = len(self._workers), len(self._evicted)
        gauge_set("fleet.workers", n_workers)
        gauge_set("fleet.live_workers", len(live))
        gauge_set("fleet.evicted_workers", n_evicted)
        self._publish_dist_section()
        return list(live)

    def _evict(self, wid: str, reason: str,
               drop_liveness: bool = False) -> None:
        """Dispatch-fault eviction. ``drop_liveness`` removes the dead
        worker's liveness file so the stale stamp can't flap it back on
        the very next scan — a worker that is actually alive re-touches
        within one heartbeat and rejoins."""
        from delphi_tpu.parallel import dist_resilience as dr

        with self._lock:
            if wid in self._live:
                self._live.remove(wid)
            already = wid in self._evicted
            if not already:
                self._evicted[wid] = reason
        if not already:
            counter_inc("fleet.evictions")
            _logger.warning(f"fleet worker {wid} evicted: {reason}")
        if drop_liveness:
            try:
                os.remove(dr.member_liveness_path(self.fleet_dir, wid))
            except OSError:
                pass
        gauge_set("fleet.live_workers", len(self._live))
        gauge_set("fleet.evicted_workers", len(self._evicted))
        self._publish_dist_section()

    def _publish_dist_section(self) -> None:
        """Rolls fleet membership into the run report's ``dist`` section
        (merged over the dist-resilience section when one exists)."""
        from delphi_tpu.parallel import dist_resilience as dr

        try:
            section = dict(dr.report_section() or {})
        except Exception:  # pragma: no cover - defensive
            section = {}
        with self._lock:
            section["fleet"] = {
                "workers": sorted(self._workers),
                "live": list(self._live),
                "evicted": dict(self._evicted),
            }
        if self.recorder is not None:
            self.recorder.dist = section

    # -- dispatch ------------------------------------------------------------

    def _dispatch_once(self, wid: str, data: bytes, timeout_s: float
                       ) -> Tuple[int, Dict[str, Any], Dict[str, str]]:
        """The ONE place router→worker HTTP happens: a guarded seam at
        site ``fleet.dispatch`` (chaos-injectable, abort-aware). Returns
        ``(status, body, headers)`` for any HTTP answer — including the
        worker's 4xx/5xx — and raises :class:`DispatchFault` for
        connection-level failures, which the caller treats as the worker
        dying between the membership check and the dispatch."""
        from delphi_tpu.parallel import resilience

        resilience.maybe_abort()
        with self._lock:
            info = self._workers.get(wid)
        port = (info or {}).get("port")
        try:
            resilience._maybe_inject("fleet.dispatch")
            if not port:
                raise OSError(f"worker {wid} has no registered port "
                              "(connection refused)")
            headers = {"Content-Type": "application/json"}
            # propagate the trace across the router→worker seam: every
            # dispatch — including shed-hops and post-eviction
            # re-dispatches — carries the same trace id, so the request's
            # whole journey merges into ONE trace document
            trace_header = _trace.header_value()
            if trace_header:
                headers[_trace.TRACE_HEADER] = trace_header
            req = urllib.request.Request(
                f"http://127.0.0.1:{int(port)}/repair", data=data,
                headers=headers, method="POST")
            with urllib.request.urlopen(req, timeout=timeout_s) as resp:
                body = json.loads(resp.read() or b"{}")
                return int(resp.status), body, dict(resp.headers)
        except urllib.error.HTTPError as e:
            try:
                body = json.loads(e.read() or b"{}")
            except Exception:
                body = {"status": "error", "error": f"HTTP {e.code}"}
            return int(e.code), body, dict(e.headers or {})
        except Exception as e:
            raise DispatchFault(wid, e)

    @staticmethod
    def _retry_after_s(headers: Dict[str, str]) -> float:
        for key, value in headers.items():
            if key.lower() == "retry-after":
                try:
                    return float(value)
                except (TypeError, ValueError):
                    break
        return 1.0

    def handle_repair(self, payload: Dict[str, Any]
                      ) -> Tuple[int, Dict[str, Any], Optional[float]]:
        """Routes one /repair request: rendezvous-rank the live ring on
        the table fingerprint, dispatch to the best untried worker, hop
        on shed (429/503-rejected), evict + re-dispatch on connection
        faults, return anything else as definitive. Bounded by
        ``max_hops`` and the monotonically growing tried-set, so the
        router can never loop. Returns ``(status, body,
        retry_after_s)``."""
        from delphi_tpu.parallel import resilience

        counter_inc("fleet.requests")
        # chained requests (a stream delta or a base_snapshot follow-up)
        # route by the CHAIN-ROOT key, not the per-delta table content:
        # every link of a chain must land on the home that holds its
        # snapshot, durable cursor, and warm models — hashing the table
        # would scatter the chain across the ring on every append
        chain = chain_fingerprint(payload)
        fp = chain or table_fingerprint(payload["table"],
                                        payload["row_id"])
        data = json.dumps(payload).encode()
        tried: set = set()
        shed_retry_afters: List[float] = []
        hops = 0
        saw_worker = False
        while hops < self.max_hops:
            live = self.refresh_membership()
            ranked = rendezvous_rank(fp, live)
            candidates = [w for w in ranked if w not in tried]
            if not candidates:
                break
            saw_worker = True
            wid = candidates[0]
            tried.add(wid)
            hops += 1
            counter_inc("fleet.dispatches")
            if hops > 1:
                counter_inc("fleet.redispatches")
            # affinity: did this request land on its rendezvous home?
            if wid != ranked[0]:
                counter_inc("fleet.affinity.misses")
            else:
                counter_inc("fleet.affinity.chain_hits" if chain
                            else "fleet.affinity.hits")
            hits = counter_value("fleet.affinity.hits") \
                + counter_value("fleet.affinity.chain_hits")
            total = hits + counter_value("fleet.affinity.misses")
            if total > 0:
                gauge_set("fleet.affinity.hit_ratio",
                          round(hits / total, 6))
            _trace.instant("fleet.redispatch" if hops > 1
                           else "fleet.dispatch", worker=wid, hop=hops)
            try:
                status, body, headers = self._dispatch_once(
                    wid, data, self.dispatch_timeout_s)
            except DispatchFault as e:
                counter_inc("fleet.dispatch_faults")
                kind = resilience.classify_fault(e.cause) or "transient"
                self._evict(wid, f"dispatch fault ({kind}): {e.cause}",
                            drop_liveness=True)
                _trace.instant("fleet.dispatch_fault", worker=wid,
                               hop=hops, kind=kind)
                _logger.warning(f"fleet.dispatch fault on worker {wid} "
                                f"({kind}); re-dispatching")
                continue
            shedding = status in (429, 503) \
                and body.get("status") == "rejected"
            if shedding:
                shed_retry_afters.append(self._retry_after_s(headers))
                _trace.instant("fleet.shed_hop", worker=wid, hop=hops)
                continue
            if isinstance(body, dict):
                # replica attribution for clients and the load harness:
                # which worker answered, after how many dispatches —
                # lifted into X-Delphi-Worker / X-Delphi-Hops by do_POST
                body.setdefault("worker_id", wid)
                body["hops"] = hops
            return status, body, None
        if shed_retry_afters:
            counter_inc("fleet.all_shed")
            return (429, {"status": "rejected",
                          "error": "all live workers are shedding"},
                    max(shed_retry_afters))
        if not saw_worker:
            counter_inc("fleet.no_workers")
            return (503, {"status": "rejected",
                          "error": "no live fleet workers"}, 1.0)
        return (503, {"status": "error",
                      "error": f"no live worker completed the request "
                               f"after {hops} dispatch(es) to "
                               f"{len(tried)} worker(s)"},
                1.0)


class _FleetHandler(BaseHTTPRequestHandler):
    def log_message(self, fmt: str, *args: Any) -> None:
        _logger.debug("fleet router: " + fmt % args)

    @property
    def _router(self) -> FleetRouter:
        return self.server.fleet_router  # type: ignore[attr-defined]

    def _respond(self, status: int, body: Dict[str, Any],
                 retry_after_s: Optional[float] = None,
                 headers: Optional[Dict[str, Any]] = None) -> None:
        data = json.dumps(body).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        if retry_after_s is not None:
            self.send_header("Retry-After",
                             str(max(1, int(round(retry_after_s)))))
        for key, value in (headers or {}).items():
            self.send_header(key, str(value))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self) -> None:  # noqa: N802 (BaseHTTPRequestHandler API)
        from delphi_tpu.observability.live import (
            PROMETHEUS_CONTENT_TYPE, render_prometheus,
        )

        rt = self._router
        path = self.path.split("?", 1)[0]
        try:
            if path == "/healthz":
                live = rt.refresh_membership()
                with rt._lock:
                    evicted = dict(rt._evicted)
                    workers = {
                        wid: {"port": info.get("port"),
                              "live": wid in live,
                              "evicted_reason": evicted.get(wid)}
                        for wid, info in sorted(rt._workers.items())}
                self._respond(200, {
                    "status": "degraded" if evicted else "ok",
                    "live": live,
                    "evicted": evicted,
                    "workers": workers,
                })
            elif path == "/metrics":
                text = render_prometheus(rt.recorder).encode()
                self.send_response(200)
                self.send_header("Content-Type", PROMETHEUS_CONTENT_TYPE)
                self.send_header("Content-Length", str(len(text)))
                self.end_headers()
                self.wfile.write(text)
            elif path == "/report":
                from delphi_tpu.observability.report import build_run_report
                rt._publish_dist_section()
                report = build_run_report(rt.recorder, run={},
                                          status="serving", error=None)
                self._respond(200, report)
            elif path.startswith("/trace/"):
                doc = _trace.load_trace(path[len("/trace/"):])
                if doc is None:
                    self._respond(404, {
                        "error": "no such trace under "
                                 f"{_trace.trace_root() or '<unset>'}"})
                else:
                    self._respond(200, doc)
            else:
                self._respond(404, {"error": f"unknown path {path}"})
        except Exception as e:  # pragma: no cover - defensive
            try:
                self._respond(500, {"error": f"{type(e).__name__}: {e}"})
            except Exception:
                pass

    def do_POST(self) -> None:  # noqa: N802 (BaseHTTPRequestHandler API)
        rt = self._router
        path = self.path.split("?", 1)[0]
        try:
            if path == "/drain":
                threading.Thread(target=rt.drain, daemon=True,
                                 name="delphi-fleet-drain").start()
                self._respond(200, {"status": "draining"})
                return
            if path != "/repair":
                self._respond(404, {"error": f"unknown path {path}"})
                return
            try:
                length = int(self.headers.get("Content-Length") or 0)
                payload = json.loads(self.rfile.read(length) or b"{}")
            except (ValueError, json.JSONDecodeError) as e:
                self._respond(400, {"status": "bad_request",
                                    "error": f"bad JSON body: {e}"})
                return
            if not isinstance(payload, dict) \
                    or not isinstance(payload.get("table"), dict) \
                    or not isinstance(payload.get("row_id"), str):
                self._respond(400, {
                    "status": "bad_request",
                    "error": "body must be a JSON object with a 'table' "
                             "object and a 'row_id' string"})
                return
            # the router is where a distributed trace is born (or, when a
            # client already carries one, joined): the scope covers every
            # dispatch/shed-hop/re-dispatch instant and the header the
            # dispatch seam stamps on each worker call
            tid, parent = _trace.parse_header(
                self.headers.get(_trace.TRACE_HEADER))
            with _trace.request_scope(tid, parent) as tctx:
                status, body, retry_after_s = rt.handle_repair(payload)
                if tctx is not None and isinstance(body, dict):
                    body.setdefault("trace_id", tctx.trace_id)
            extra: Dict[str, Any] = {}
            if isinstance(body, dict):
                if body.get("worker_id") is not None:
                    extra["X-Delphi-Worker"] = body["worker_id"]
                if body.get("hops") is not None:
                    extra["X-Delphi-Hops"] = body["hops"]
                if body.get("trace_id"):
                    extra[_trace.TRACE_HEADER] = body["trace_id"]
            self._respond(status, body, retry_after_s=retry_after_s,
                          headers=extra or None)
        except Exception as e:  # pragma: no cover - defensive
            try:
                self._respond(500, {"error": f"{type(e).__name__}: {e}"})
            except Exception:
                pass


# -- queue-driven autoscaling ------------------------------------------------

_DEF_AS_MIN = 1
_DEF_AS_MAX = 4
_DEF_AS_UP_QUEUE = 4
_DEF_AS_DOWN_QUEUE = 0
_DEF_AS_UP_LAG_ROWS = 512
_DEF_AS_SUSTAIN = 3
_DEF_AS_COOLDOWN_S = 30.0
_DEF_AS_INTERVAL_S = 1.0


class AutoscalePolicy:
    """The pure scale decision — no threads, no HTTP, fully drivable by a
    fake clock.

    Signals per tick: the fleet's worst per-worker admission queue depth
    and worst ``stream.lag_rows`` (one hot replica is a problem even when
    the mean is fine). Three defenses against flapping:

    * **hysteresis** — scale-up pressure needs ``queue >= up_queue_depth``
      (or ``lag >= up_lag_rows``); scale-down needs
      ``queue <= down_queue_depth`` AND no lag pressure. The band between
      the thresholds resets both streaks;
    * **sustain** — a decision fires only after ``sustain_ticks``
      *consecutive* pressured ticks (one spiky scrape is not a trend);
    * **cooldown** — after any action, further actions are blocked for
      ``cooldown_s`` (the new worker needs time to warm and absorb load
      before it can be judged).

    ``observe`` returns ``(action, reason)`` with action one of ``"up"``
    / ``"down"`` / ``"hold"``.
    """

    def __init__(self, min_workers: Optional[int] = None,
                 max_workers: Optional[int] = None,
                 up_queue_depth: Optional[int] = None,
                 down_queue_depth: Optional[int] = None,
                 up_lag_rows: Optional[int] = None,
                 sustain_ticks: Optional[int] = None,
                 cooldown_s: Optional[float] = None) -> None:
        def knob(value, env, opt, default):
            return value if value is not None else _knob_int(env, opt,
                                                             default)

        self.min_workers = max(1, knob(min_workers, "DELPHI_AUTOSCALE_MIN",
                                       "repair.autoscale.min", _DEF_AS_MIN))
        self.max_workers = max(self.min_workers, knob(
            max_workers, "DELPHI_AUTOSCALE_MAX", "repair.autoscale.max",
            _DEF_AS_MAX))
        self.up_queue_depth = knob(up_queue_depth,
                                   "DELPHI_AUTOSCALE_UP_QUEUE",
                                   "repair.autoscale.up_queue",
                                   _DEF_AS_UP_QUEUE)
        self.down_queue_depth = knob(down_queue_depth,
                                     "DELPHI_AUTOSCALE_DOWN_QUEUE",
                                     "repair.autoscale.down_queue",
                                     _DEF_AS_DOWN_QUEUE)
        self.up_lag_rows = knob(up_lag_rows, "DELPHI_AUTOSCALE_UP_LAG_ROWS",
                                "repair.autoscale.up_lag_rows",
                                _DEF_AS_UP_LAG_ROWS)
        self.sustain_ticks = max(1, knob(sustain_ticks,
                                         "DELPHI_AUTOSCALE_SUSTAIN",
                                         "repair.autoscale.sustain",
                                         _DEF_AS_SUSTAIN))
        self.cooldown_s = cooldown_s if cooldown_s is not None \
            else _knob_float("DELPHI_AUTOSCALE_COOLDOWN_S",
                             "repair.autoscale.cooldown_s",
                             _DEF_AS_COOLDOWN_S)
        self._up_streak = 0
        self._down_streak = 0
        self._last_action_at: Optional[float] = None

    def _cooling(self, now: float) -> bool:
        return self._last_action_at is not None \
            and (now - self._last_action_at) < self.cooldown_s

    def observe(self, now: float, queue_depth: int, lag_rows: int,
                n_live: int) -> Tuple[str, str]:
        counter_inc("autoscale.ticks")
        up_pressure = queue_depth >= self.up_queue_depth \
            or lag_rows >= self.up_lag_rows
        down_pressure = queue_depth <= self.down_queue_depth \
            and lag_rows < self.up_lag_rows
        if up_pressure:
            self._up_streak += 1
            self._down_streak = 0
        elif down_pressure:
            self._down_streak += 1
            self._up_streak = 0
        else:
            # inside the hysteresis band: not hot enough to grow, not
            # idle enough to shrink — and any built-up streak dies here
            if self._up_streak or self._down_streak:
                counter_inc("autoscale.blocked_hysteresis")
            self._up_streak = self._down_streak = 0
            return "hold", "hysteresis"
        if up_pressure and self._up_streak >= self.sustain_ticks:
            if n_live >= self.max_workers:
                counter_inc("autoscale.blocked_limit")
                return "hold", "at_max"
            if self._cooling(now):
                counter_inc("autoscale.blocked_cooldown")
                return "hold", "cooldown"
            self._up_streak = self._down_streak = 0
            self._last_action_at = now
            return "up", (f"queue_depth={queue_depth} "
                          f">= {self.up_queue_depth}"
                          if queue_depth >= self.up_queue_depth
                          else f"lag_rows={lag_rows} "
                               f">= {self.up_lag_rows}")
        if down_pressure and self._down_streak >= self.sustain_ticks:
            if n_live <= self.min_workers:
                counter_inc("autoscale.blocked_limit")
                return "hold", "at_min"
            if self._cooling(now):
                counter_inc("autoscale.blocked_cooldown")
                return "hold", "cooldown"
            self._up_streak = self._down_streak = 0
            self._last_action_at = now
            return "down", (f"queue_depth={queue_depth} "
                            f"<= {self.down_queue_depth}")
        return "hold", "building"


class FleetAutoscaler:
    """Closes the elasticity loop: polls every live worker's ``/healthz``
    (queue depth, stream lag), feeds the worst-case signals through
    :class:`AutoscalePolicy`, and acts on the router — scale-up spawns
    the next worker id (it registers and rendezvous-joins the ring
    elastically), scale-down picks the highest-id live worker and
    retires it GRACEFULLY: POST ``/drain`` (the worker unregisters and
    hands back its stream cursors before refusing a single request),
    wait for its clean departure from the ring, then SIGTERM the
    process. Never SIGKILL on the happy path — a killed worker loses
    nothing durable, but a drained one sheds nothing at all.

    Every decision lands in ``autoscale.*`` counters; every action is a
    trace instant (:func:`trace.background_instant`) and a structured
    entry on :attr:`events`, which the load harness rolls into the run
    report's ``slo.autoscale`` section.
    """

    def __init__(self, router: FleetRouter,
                 policy: Optional[AutoscalePolicy] = None,
                 interval_s: Optional[float] = None,
                 now_fn=time.monotonic) -> None:
        self.router = router
        self.policy = policy or AutoscalePolicy()
        self.interval_s = interval_s if interval_s is not None \
            else _knob_float("DELPHI_AUTOSCALE_INTERVAL_S",
                             "repair.autoscale.interval_s",
                             _DEF_AS_INTERVAL_S)
        self.now_fn = now_fn
        self.events: List[Dict[str, Any]] = []
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # seams (overridden by tests to script worker health / drain) ---------

    def _http_once(self, port: int, path: str, method: str = "GET",
                   timeout_s: float = 5.0,
                   site: str = "autoscale.http") -> Optional[Dict[str, Any]]:
        """The ONE place autoscaler→worker HTTP happens (health polls and
        drain posts — never repair dispatch, which stays on the router's
        ``fleet.dispatch`` seam). Chaos-injectable at ``autoscale.http``;
        any failure means "no signal this tick", never an exception — the
        membership scan, not the autoscaler, declares workers dead."""
        from delphi_tpu.parallel import resilience
        try:
            resilience._maybe_inject("autoscale.http")
            req = urllib.request.Request(
                f"http://127.0.0.1:{int(port)}{path}",
                data=b"" if method == "POST" else None, method=method)
            with urllib.request.urlopen(req, timeout=timeout_s) as resp:
                return json.loads(resp.read() or b"{}")
        except Exception:
            return None

    def _poll_worker(self, port: int) -> Optional[Dict[str, Any]]:
        return self._http_once(port, "/healthz")

    def _post_drain(self, port: int) -> bool:
        return self._http_once(port, "/drain", method="POST",
                               timeout_s=10.0) is not None

    # signal collection ---------------------------------------------------

    def collect(self) -> Tuple[int, int, int]:
        """(worst queue_depth, worst lag_rows, live count) across the
        ring. A worker that fails its poll contributes nothing — the
        membership scan, not the autoscaler, decides whether it is
        dead."""
        live = self.router.refresh_membership()
        queue_depth = lag_rows = 0
        with self.router._lock:
            ports = {wid: (self.router._workers.get(wid) or {}).get("port")
                     for wid in live}
        for wid, port in ports.items():
            if not port:
                continue
            health = self._poll_worker(int(port))
            if not health:
                continue
            queue_depth = max(queue_depth,
                              int(health.get("queue_depth") or 0))
            lag_rows = max(lag_rows, int(
                (health.get("streams") or {}).get("lag_rows") or 0))
        gauge_set("autoscale.queue_depth", queue_depth)
        gauge_set("autoscale.lag_rows", lag_rows)
        return queue_depth, lag_rows, len(live)

    # actions --------------------------------------------------------------

    def _event(self, action: str, reason: str, worker: Optional[str],
               **extra: Any) -> None:
        event = {"action": action, "reason": reason, "worker": worker,
                 "at_s": round(self.now_fn(), 3)}
        event.update(extra)
        self.events.append(event)
        trace_id = _trace.background_instant(f"autoscale.{action}",
                                             reason=reason, worker=worker)
        if trace_id:
            event["trace_id"] = trace_id

    def _next_worker_id(self) -> str:
        with self.router._lock:
            known = set(self.router._workers) | set(self.router._procs)
        numeric = [int(w) for w in known if str(w).isdigit()]
        return str(max(numeric) + 1 if numeric else len(known))

    def scale_up(self, reason: str) -> Optional[str]:
        wid = self._next_worker_id()
        try:
            self.router._spawn_worker(wid)
        except Exception as e:
            _logger.warning(f"autoscale spawn of worker {wid} failed: {e}")
            return None
        counter_inc("autoscale.up")
        self._event("up", reason, wid)
        _logger.info(f"autoscale: spawned worker {wid} ({reason})")
        return wid

    def _pick_victim(self) -> Optional[str]:
        """Retire the highest worker id: with ids handed out in spawn
        order that is the youngest (coldest) replica, and its departure
        remaps the fewest long-lived warm fingerprints."""
        live = self.router.refresh_membership()
        if len(live) <= self.policy.min_workers:
            return None
        return sorted(live, key=lambda w: (len(w), w))[-1]

    def scale_down(self, reason: str,
                   depart_timeout_s: float = 30.0) -> Optional[str]:
        wid = self._pick_victim()
        if wid is None:
            return None
        with self.router._lock:
            port = (self.router._workers.get(wid) or {}).get("port")
        drained = bool(port) and self._post_drain(int(port))
        if drained:
            # the worker unregistered before its drain response; wait for
            # the membership scan to see the clean departure
            deadline = time.monotonic() + depart_timeout_s
            while time.monotonic() < deadline:
                if wid not in self.router.refresh_membership():
                    break
                time.sleep(0.1)
        proc = self.router._procs.get(wid)
        if proc is not None and proc.poll() is None:
            try:
                proc.send_signal(signal.SIGTERM)
            except (ProcessLookupError, OSError):
                pass
            try:
                proc.wait(timeout=depart_timeout_s)
            except subprocess.TimeoutExpired:
                _logger.warning(f"autoscale victim {wid} ignored SIGTERM; "
                                "killing")
                proc.kill()
        counter_inc("autoscale.down")
        self._event("down", reason, wid, drained=drained)
        _logger.info(f"autoscale: retired worker {wid} "
                     f"(drained={drained}, {reason})")
        return wid

    # loop -----------------------------------------------------------------

    def tick(self) -> Tuple[str, str]:
        queue_depth, lag_rows, n_live = self.collect()
        action, reason = self.policy.observe(self.now_fn(), queue_depth,
                                             lag_rows, n_live)
        if action == "up":
            self.scale_up(reason)
        elif action == "down":
            self.scale_down(reason)
        return action, reason

    def _loop(self) -> None:
        while not self._stopped.wait(self.interval_s):
            try:
                self.tick()
            except Exception as e:  # scaling must never kill the router
                _logger.warning(f"autoscale tick failed: {e}")

    def start(self) -> "FleetAutoscaler":
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="delphi-fleet-autoscaler")
        self._thread.start()
        _logger.info(
            f"fleet autoscaler on (min={self.policy.min_workers}, "
            f"max={self.policy.max_workers}, "
            f"up_queue={self.policy.up_queue_depth}, "
            f"sustain={self.policy.sustain_ticks}, "
            f"cooldown={self.policy.cooldown_s}s)")
        return self

    def stop(self) -> None:
        self._stopped.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None


def install_signal_handlers(router: FleetRouter) -> None:
    """SIGTERM/SIGINT → drain the whole fleet (main-thread only)."""
    def _handler(signum: int, frame: Any) -> None:
        _logger.info(f"signal {signum}: draining repair fleet")
        threading.Thread(target=router.drain, daemon=True,
                         name="delphi-fleet-drain").start()

    signal.signal(signal.SIGTERM, _handler)
    signal.signal(signal.SIGINT, _handler)


def run_fleet(port: int = 8080, workers: Optional[int] = None,
              cache_dir: Optional[str] = None,
              autoscale: Optional[bool] = None) -> int:
    """Blocking entry point for ``main.py --fleet N``: spawns the
    workers, starts the router (plus the queue-driven autoscaler when
    ``autoscale`` — or ``DELPHI_AUTOSCALE=1`` — asks for it), and waits
    until a drain completes."""
    if autoscale is None:
        autoscale = str(os.environ.get("DELPHI_AUTOSCALE") or "").lower() \
            in ("1", "on", "true", "yes")
    router = FleetRouter(port=port, workers=workers, cache_dir=cache_dir)
    router.start()
    scaler = FleetAutoscaler(router).start() if autoscale else None
    install_signal_handlers(router)
    print(f"delphi repair fleet on 127.0.0.1:{router.port} "
          f"({router.n_workers} workers, "
          f"autoscale {'on' if scaler else 'off'}, "
          f"cache {router.cache_dir})", flush=True)
    router.wait()
    if scaler is not None:
        scaler.stop()
    return 0
