"""Sustained-load harness: deterministic open-loop fleet load generation.

Every serve/fleet/stream number before this module came from chaos A/Bs
with a handful of requests. This is the plane that drives the fleet hard
enough for its queue/lag/shed/affinity signals to mean something, and
turns what comes back into a gated SLO ledger:

* **workload synthesis** — tables come from the gauntlet generators
  (:mod:`delphi_tpu.gauntlet.scenarios`), one distinct table fingerprint
  per (scenario, seed) pair, so a pool of hundreds of fingerprints costs
  one function call and is byte-identical per seed;
* **zipf popularity** — request fingerprints are drawn from a seeded
  zipf distribution over the pool, so a few tables are hot and most are
  cold: exactly the shape under which rendezvous warm-affinity matters;
* **mixed request kinds** — plain batch repairs, ``base_snapshot``
  incremental chains, and chained stream deltas, in a seeded mix. Chained
  kinds serialize *within* their chain (the stream protocol 409s on
  reordering) but stay open-loop *across* chains;
* **open-loop arrival schedule** — seeded exponential interarrivals over
  phase-programmed segments (warmup / steady / spike / post_kill).
  Arrivals are NEVER coupled to completions: a slow fleet means deeper
  queues and shed responses, not a politely backing-off client;
* **bounded retry discipline** — 429/503 answers are retried honoring
  ``Retry-After`` with the same deterministic crc32-jittered backoff as
  :class:`delphi_tpu.parallel.resilience.RetryPolicy`; exhausted retries
  are explicit ``load.shed`` / ``load.gave_up`` counters, never a silent
  truncation of the schedule — ``sent == answered + shed + gave_up``
  holds by construction;
* **the SLO ledger** — per-request records (latency, status, worker from
  ``X-Delphi-Worker``, hops from ``X-Delphi-Hops``, retry outcome,
  segment attribution) aggregate into the run report's ``slo`` section
  (schema v9): sustained QPS, p50/p90/p99 from the deterministic
  reservoirs, shed rate, warm-hit ratio, per-worker utilization, and
  per-segment breakdowns, with an intra-run recovery verdict (post-spike
  and post-kill p99 vs steady state).

``bench.py --load`` / ``--load-smoke`` drive this against a live
:class:`~delphi_tpu.observability.fleet.FleetRouter`;
:func:`delphi_tpu.observability.drift.evaluate_slo` gates a run against a
baseline report. Knobs (env beats defaults; documented in
``docs/source/internals.rst``): ``DELPHI_LOAD_SEED``,
``DELPHI_LOAD_REQUESTS``, ``DELPHI_LOAD_FINGERPRINTS``,
``DELPHI_LOAD_ROWS``, ``DELPHI_LOAD_RATE``, ``DELPHI_LOAD_SPIKE_X``,
``DELPHI_LOAD_ZIPF_ALPHA``, ``DELPHI_LOAD_MIX``,
``DELPHI_LOAD_RETRY_MAX``, ``DELPHI_LOAD_BASELINE``,
``DELPHI_LOAD_FAIL_OVER``.
"""

import json
import queue
import threading
import time
import zlib
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from delphi_tpu.observability.registry import _Histogram, counter_inc
from delphi_tpu.utils import setup_logger

_logger = setup_logger()

_DEF_SEED = 0
_DEF_REQUESTS = 1200
_DEF_FINGERPRINTS = 120
_DEF_ROWS = 32
_DEF_RATE_RPS = 6.0
_DEF_SPIKE_X = 3.0
_DEF_ZIPF_ALPHA = 1.1
_DEF_MIX = "batch=0.7,incremental=0.15,stream=0.15"
_DEF_RETRY_MAX = 2
_DEF_FAIL_OVER = 0.5
_RETRY_CAP_S = 5.0

#: Counters this plane owns. Pre-seeded on both the serve and fleet
#: ``/metrics`` (their ``_SEED_COUNTERS`` tuples) so a scrape before —
#: or without — any load run sees the whole series at zero.
LOAD_COUNTERS = (
    "load.requests", "load.answered", "load.ok", "load.failed",
    "load.shed", "load.gave_up", "load.retries",
    "slo.segments", "slo.recovery_violations",
)


def load_knobs() -> Dict[str, Any]:
    """The env-tunable load shape, resolved once per run (``bench.py
    --load`` reads these; ``--load-smoke`` overrides them explicitly)."""
    from delphi_tpu.observability.serve import _knob_float, _knob_int
    import os

    return {
        "seed": _knob_int("DELPHI_LOAD_SEED", "repair.load.seed", _DEF_SEED),
        "requests": _knob_int("DELPHI_LOAD_REQUESTS",
                              "repair.load.requests", _DEF_REQUESTS),
        "fingerprints": _knob_int("DELPHI_LOAD_FINGERPRINTS",
                                  "repair.load.fingerprints",
                                  _DEF_FINGERPRINTS),
        "rows": _knob_int("DELPHI_LOAD_ROWS", "repair.load.rows", _DEF_ROWS),
        "rate_rps": _knob_float("DELPHI_LOAD_RATE", "repair.load.rate",
                                _DEF_RATE_RPS),
        "spike_x": _knob_float("DELPHI_LOAD_SPIKE_X", "repair.load.spike_x",
                               _DEF_SPIKE_X),
        "zipf_alpha": _knob_float("DELPHI_LOAD_ZIPF_ALPHA",
                                  "repair.load.zipf_alpha", _DEF_ZIPF_ALPHA),
        "mix": parse_mix(os.environ.get("DELPHI_LOAD_MIX") or _DEF_MIX),
        "retry_max": _knob_int("DELPHI_LOAD_RETRY_MAX",
                               "repair.load.retry_max", _DEF_RETRY_MAX),
        "baseline": os.environ.get("DELPHI_LOAD_BASELINE") or None,
        "fail_over": _knob_float("DELPHI_LOAD_FAIL_OVER",
                                 "repair.load.fail_over", _DEF_FAIL_OVER),
    }


def parse_mix(raw: str) -> Dict[str, float]:
    """``"batch=0.7,incremental=0.2,stream=0.1"`` → normalized weights.
    Unknown kinds are rejected loudly; an all-zero mix degrades to pure
    batch (the one kind that needs no chain bookkeeping)."""
    weights = {"batch": 0.0, "incremental": 0.0, "stream": 0.0}
    for part in str(raw).split(","):
        part = part.strip()
        if not part:
            continue
        key, _, value = part.partition("=")
        key = key.strip()
        if key not in weights:
            raise ValueError(f"unknown load mix kind {key!r} "
                             f"(expected one of {sorted(weights)})")
        weights[key] = max(0.0, float(value))
    total = sum(weights.values())
    if total <= 0:
        return {"batch": 1.0, "incremental": 0.0, "stream": 0.0}
    return {k: v / total for k, v in weights.items()}


# -- workload synthesis ------------------------------------------------------


def make_tables(n_fingerprints: int, rows: int, seed: int,
                scenarios: Optional[List[str]] = None
                ) -> List[Dict[str, Any]]:
    """``n_fingerprints`` distinct JSON tables from the gauntlet
    generators: fingerprint ``i`` is scenario ``names[i % len(names)]``
    generated at seed ``seed + i`` — byte-identical per (n, rows, seed),
    with every fingerprint distinct because the generators hash their
    seed into every sampled cell. ``scenarios`` restricts the cycle
    (each scenario family is a distinct table SHAPE, hence a distinct
    compile — the smoke pins one family so compile time doesn't dominate
    a tier-1 run; the full ``--load`` uses them all)."""
    from delphi_tpu.gauntlet.scenarios import generate_scenario, \
        scenario_names

    names = list(scenarios) if scenarios else scenario_names()
    tables: List[Dict[str, Any]] = []
    for i in range(max(1, int(n_fingerprints))):
        data = generate_scenario(names[i % len(names)], rows=rows,
                                 seed=seed + i)
        split = json.loads(data.dirty.to_json(orient="split"))
        table = {c: [row[j] for row in split["data"]]
                 for j, c in enumerate(split["columns"])}
        tables.append({"index": i, "scenario": data.name,
                       "row_id": data.row_id, "table": table})
    return tables


def zipf_weights(n: int, alpha: float) -> List[float]:
    """Unnormalized zipf popularity: weight of rank ``i`` is
    ``1/(i+1)^alpha``. ``alpha`` around 1 gives the classic few-hot /
    long-cold-tail shape that makes warm affinity measurable."""
    return [1.0 / ((i + 1) ** max(0.0, float(alpha))) for i in range(n)]


# -- arrival schedule --------------------------------------------------------


@dataclass(frozen=True)
class Segment:
    """One phase of the arrival schedule. ``rate_rps`` is the open-loop
    arrival rate for ``duration_s`` seconds."""
    name: str
    duration_s: float
    rate_rps: float


def default_segments(requests: int, rate_rps: float,
                     spike_x: float) -> List[Segment]:
    """The canonical 4-phase program: warmup (10% of requests), steady
    (50%), spike (25% at ``spike_x`` times the steady rate), post_kill
    (15% — ``bench.py --load`` kills a worker at this boundary).
    Durations are derived so the expected request count lands on
    ``requests``."""
    rate = max(0.1, float(rate_rps))
    spike_rate = rate * max(1.0, float(spike_x))
    n = max(4, int(requests))
    return [
        Segment("warmup", (0.10 * n) / rate, rate),
        Segment("steady", (0.50 * n) / rate, rate),
        Segment("spike", (0.25 * n) / spike_rate, spike_rate),
        Segment("post_kill", (0.15 * n) / rate, rate),
    ]


@dataclass(frozen=True)
class Arrival:
    """One scheduled request: WHEN it fires (``at_s`` from run start, in
    segment ``segment``), WHAT it repairs (fingerprint ``fp_index`` of
    the pool), and HOW (kind; chained kinds carry their lane + seq)."""
    index: int
    at_s: float
    segment: str
    kind: str                     # "batch" | "incremental" | "stream"
    fp_index: int
    lane: Optional[str] = None    # chain id for incremental/stream kinds
    seq: int = 0                  # 1-based position within the lane


def build_schedule(segments: List[Segment], n_fingerprints: int,
                   zipf_alpha: float, mix: Dict[str, float],
                   seed: int) -> List[Arrival]:
    """The full seeded arrival schedule: exponential interarrivals per
    segment, zipf-weighted fingerprint choice, seeded kind mix. Pure —
    the same (segments, n, alpha, mix, seed) always yields the identical
    schedule, which is what makes a load run replayable."""
    import random

    rng = random.Random(zlib.crc32(f"load-schedule:{seed}".encode()))
    weights = zipf_weights(n_fingerprints, zipf_alpha)
    fp_pool = list(range(n_fingerprints))
    kinds = sorted(k for k, w in mix.items() if w > 0)
    kind_weights = [mix[k] for k in kinds]
    lane_seq: Dict[str, int] = {}
    arrivals: List[Arrival] = []
    t = 0.0
    index = 0
    for seg in segments:
        seg_end = t + max(0.0, seg.duration_s)
        while True:
            t += rng.expovariate(max(0.1, seg.rate_rps))
            if t >= seg_end:
                t = seg_end
                break
            fp = rng.choices(fp_pool, weights=weights, k=1)[0]
            kind = rng.choices(kinds, weights=kind_weights, k=1)[0]
            lane = None
            seq = 0
            if kind in ("incremental", "stream"):
                # one chain per (kind, fingerprint): every link routes to
                # the same rendezvous home (chain_fingerprint) and the
                # lane serializes seq order client-side
                lane = f"{kind[0]}{fp}"
                seq = lane_seq.get(lane, 0) + 1
                lane_seq[lane] = seq
            arrivals.append(Arrival(index=index, at_s=round(t, 6),
                                    segment=seg.name, kind=kind,
                                    fp_index=fp, lane=lane, seq=seq))
            index += 1
    return arrivals


def build_payload(arrival: Arrival, tables: List[Dict[str, Any]]
                  ) -> Dict[str, Any]:
    """The /repair body for one arrival. Batch sends the whole table;
    incremental chains repair the same table against a per-lane
    ``base_snapshot`` (link 1 populates, later links reuse it); stream
    chains send disjoint row slices as seq-ordered deltas."""
    entry = tables[arrival.fp_index % len(tables)]
    table = entry["table"]
    rid = f"load-{arrival.index}"
    base: Dict[str, Any] = {"row_id": entry["row_id"], "request_id": rid}
    if arrival.kind == "incremental":
        base["table"] = table
        base["base_snapshot"] = f"load-{arrival.lane}"
        return base
    if arrival.kind == "stream":
        row_id = entry["row_id"]
        n = len(table[row_id])
        # disjoint per-seq slice: the chain accumulates the table without
        # ever re-sending a committed row (a duplicate row set would be a
        # legitimate duplicate-delta ack, which we test elsewhere)
        step = max(1, n // 4)
        lo = ((arrival.seq - 1) * step) % n
        hi = min(n, lo + step)
        base["table"] = {c: v[lo:hi] for c, v in table.items()}
        base["stream"] = {"id": f"load-{arrival.lane}", "seq": arrival.seq}
        return base
    base["table"] = table
    return base


# -- retry discipline --------------------------------------------------------


def backoff_s(request_id: str, attempt: int, retry_after_s: float,
              cap_s: float = _RETRY_CAP_S) -> float:
    """Deterministic crc32-jittered bounded backoff, the exact discipline
    of :class:`delphi_tpu.parallel.resilience.RetryPolicy` with the
    server's ``Retry-After`` as the base: delay doubles per attempt from
    ``retry_after_s``, capped, jittered into [0.5x, 1.0x] by a pure
    function of (request id, attempt) — a replayed run sleeps the same
    schedule."""
    base = min(max(0.0, float(cap_s)),
               max(0.0, float(retry_after_s)) * (2 ** max(attempt - 1, 0)))
    frac = (zlib.crc32(f"{request_id}:{attempt}".encode()) % 1024) / 1024.0
    return round(base * (0.5 + 0.5 * frac), 6)


def _retry_after(headers: Dict[str, Any], default_s: float = 1.0) -> float:
    for key, value in (headers or {}).items():
        if str(key).lower() == "retry-after":
            try:
                return float(value)
            except (TypeError, ValueError):
                break
    return default_s


# -- the open-loop runner ----------------------------------------------------


@dataclass
class RequestRecord:
    """What one request contributed to the ledger. ``latency_s`` is
    measured from the SCHEDULED arrival (so lane head-of-line wait and
    retry backoff count against the SLO, exactly as a user would see
    them); ``outcome`` is one of ``ok`` / ``failed`` / ``shed`` /
    ``gave_up``."""
    request_id: str
    index: int
    segment: str
    kind: str
    fp_index: int
    scheduled_at_s: float
    sent_at_s: float = 0.0
    latency_s: float = 0.0
    status: Optional[int] = None
    outcome: str = "pending"
    worker: Optional[str] = None
    hops: Optional[int] = None
    retries: int = 0
    trace_id: Optional[str] = None


PostFn = Callable[[Dict[str, Any]],
                  Tuple[Optional[int], Dict[str, Any], Dict[str, Any]]]


class OpenLoopRunner:
    """Fires a schedule at a fleet, open-loop.

    The main loop sleeps to each arrival's ``at_s`` and *dispatches*
    without waiting: batch requests get their own thread; chained
    arrivals enqueue onto their lane's FIFO (one thread per lane, seq
    order preserved). Completions never back-pressure the arrival clock
    — the only coupling is the lane-internal ordering the stream
    protocol demands.

    Seams for tests: ``post_fn(payload) -> (status, body, headers)``
    (``status None`` = connection-level failure), ``now_fn`` /
    ``sleep_fn`` (fake clocks), ``on_segment(name)`` fired at each
    segment boundary (bench uses it to probe metrics and to kill the
    victim worker at ``post_kill``).
    """

    def __init__(self, schedule: List[Arrival],
                 tables: List[Dict[str, Any]], post_fn: PostFn,
                 retry_max: int = _DEF_RETRY_MAX,
                 now_fn: Callable[[], float] = time.monotonic,
                 sleep_fn: Callable[[float], None] = time.sleep,
                 on_segment: Optional[Callable[[str], None]] = None
                 ) -> None:
        self.schedule = list(schedule)
        self.tables = tables
        self.post_fn = post_fn
        self.retry_max = max(0, int(retry_max))
        self.now_fn = now_fn
        self.sleep_fn = sleep_fn
        self.on_segment = on_segment
        self.records: List[RequestRecord] = []
        self._records_lock = threading.Lock()
        self._threads: List[threading.Thread] = []
        self._lanes: Dict[str, "queue.Queue[Optional[Arrival]]"] = {}
        self._t0: float = 0.0
        self.dispatched_at: Dict[int, float] = {}  # pacing evidence
        self.duration_s: float = 0.0

    # dispatch --------------------------------------------------------------

    def _elapsed(self) -> float:
        return self.now_fn() - self._t0

    def _record(self, rec: RequestRecord) -> None:
        with self._records_lock:
            self.records.append(rec)

    def _one_request(self, arrival: Arrival) -> None:
        """One request through the bounded-retry ladder. Every terminal
        path lands in exactly one outcome bucket, so the schedule-level
        identity ``sent == answered + shed + gave_up`` cannot drift."""
        rec = RequestRecord(
            request_id=f"load-{arrival.index}", index=arrival.index,
            segment=arrival.segment, kind=arrival.kind,
            fp_index=arrival.fp_index, scheduled_at_s=arrival.at_s)
        rec.sent_at_s = self._elapsed()
        payload = build_payload(arrival, self.tables)
        counter_inc("load.requests")
        attempt = 0
        status: Optional[int] = None
        body: Dict[str, Any] = {}
        headers: Dict[str, Any] = {}
        while True:
            attempt += 1
            status, body, headers = self.post_fn(payload)
            retryable = status is None or (
                status in (429, 503)
                and (body or {}).get("status") == "rejected")
            if not retryable or attempt > self.retry_max:
                break
            rec.retries += 1
            counter_inc("load.retries")
            self.sleep_fn(backoff_s(rec.request_id, attempt,
                                    _retry_after(headers)))
        rec.status = status
        rec.latency_s = round(max(0.0, self._elapsed() - arrival.at_s), 6)
        if status is None:
            rec.outcome = "gave_up"
            counter_inc("load.gave_up")
        elif status in (429, 503) and (body or {}).get("status") \
                == "rejected":
            rec.outcome = "shed"
            counter_inc("load.shed")
        else:
            rec.outcome = "ok" if status == 200 else "failed"
            counter_inc("load.answered")
            counter_inc("load.ok" if status == 200 else "load.failed")
        worker = None
        for key, value in (headers or {}).items():
            lk = str(key).lower()
            if lk == "x-delphi-worker":
                worker = str(value)
            elif lk == "x-delphi-hops":
                try:
                    rec.hops = int(value)
                except (TypeError, ValueError):
                    pass
        rec.worker = worker if worker is not None else (
            str(body["worker_id"]) if isinstance(body, dict)
            and body.get("worker_id") is not None else None)
        if rec.hops is None and isinstance(body, dict) \
                and body.get("hops") is not None:
            try:
                rec.hops = int(body["hops"])
            except (TypeError, ValueError):
                pass
        if isinstance(body, dict) and body.get("trace_id"):
            rec.trace_id = str(body["trace_id"])
        self._record(rec)

    def _lane_loop(self, lane_q: "queue.Queue[Optional[Arrival]]") -> None:
        while True:
            arrival = lane_q.get()
            if arrival is None:
                return
            self._one_request(arrival)

    def _dispatch(self, arrival: Arrival) -> None:
        self.dispatched_at[arrival.index] = self._elapsed()
        if arrival.lane is None:
            t = threading.Thread(target=self._one_request, args=(arrival,),
                                 name=f"delphi-load-{arrival.index}",
                                 daemon=True)
            t.start()
            self._threads.append(t)
            return
        lane_q = self._lanes.get(arrival.lane)
        if lane_q is None:
            lane_q = queue.Queue()
            self._lanes[arrival.lane] = lane_q
            t = threading.Thread(target=self._lane_loop, args=(lane_q,),
                                 name=f"delphi-load-lane-{arrival.lane}",
                                 daemon=True)
            t.start()
            self._threads.append(t)
        lane_q.put(arrival)

    def run(self, join_timeout_s: float = 600.0) -> List[RequestRecord]:
        """Paces the whole schedule, then drains lanes and in-flight
        threads. Returns the records (also on ``self.records``)."""
        self._t0 = self.now_fn()
        current_segment: Optional[str] = None
        for arrival in self.schedule:
            if arrival.segment != current_segment:
                current_segment = arrival.segment
                if self.on_segment is not None:
                    try:
                        self.on_segment(arrival.segment)
                    except Exception as e:  # probes must not stop arrivals
                        _logger.warning(
                            f"load segment probe {arrival.segment!r} "
                            f"failed: {e}")
                counter_inc("slo.segments")
            delay = arrival.at_s - self._elapsed()
            if delay > 0:
                self.sleep_fn(delay)
            self._dispatch(arrival)
        for lane_q in self._lanes.values():
            lane_q.put(None)
        deadline = time.monotonic() + max(1.0, join_timeout_s)
        for t in self._threads:
            t.join(timeout=max(0.1, deadline - time.monotonic()))
        self.duration_s = round(max(self._elapsed(), 1e-9), 6)
        return self.records


# -- the SLO ledger ----------------------------------------------------------


def _percentiles(name: str, values: List[float]) -> Dict[str, Any]:
    """p50/p90/p99 (plus count/mean) through the registry's deterministic
    crc32-seeded reservoir — the same estimator the live histograms use,
    so report and /metrics percentiles agree and replays reproduce."""
    hist = _Histogram(name)
    for v in values:
        hist.observe(float(v))
    s = hist.summary()
    return {"count": s["count"], "mean": s["mean"], "p50": s["p50"],
            "p90": s["p90"], "p99": s["p99"]}


def _bucket(records: List[RequestRecord], wall_s: float) -> Dict[str, Any]:
    sent = len(records)
    by = {"ok": 0, "failed": 0, "shed": 0, "gave_up": 0}
    for r in records:
        by[r.outcome] = by.get(r.outcome, 0) + 1
    answered = by["ok"] + by["failed"]
    answered_lat = [r.latency_s for r in records
                    if r.outcome in ("ok", "failed")]
    return {
        "sent": sent,
        "answered": answered,
        "ok": by["ok"],
        "failed": by["failed"],
        "shed": by["shed"],
        "gave_up": by["gave_up"],
        "retries": sum(r.retries for r in records),
        "duration_s": round(wall_s, 3),
        "qps": round(sent / wall_s, 3) if wall_s > 0 else None,
        "answered_qps": round(answered / wall_s, 3) if wall_s > 0 else None,
        "shed_rate": round(by["shed"] / sent, 6) if sent else 0.0,
        "latency": _percentiles("slo.latency", answered_lat),
    }


def _warm_ratio(counters: Dict[str, float]) -> Optional[float]:
    hits = counters.get("fleet.affinity.hits", 0) \
        + counters.get("fleet.affinity.chain_hits", 0)
    total = hits + counters.get("fleet.affinity.misses", 0)
    return round(hits / total, 6) if total > 0 else None


def slo_section(records: List[RequestRecord], segments: List[Segment],
                duration_s: float,
                segment_counters: Optional[Dict[str, Dict[str, float]]]
                = None,
                autoscale_events: Optional[List[Dict[str, Any]]] = None,
                kill: Optional[Dict[str, Any]] = None,
                recovery_fail_over: float = _DEF_FAIL_OVER
                ) -> Dict[str, Any]:
    """The run report's ``slo`` section (schema v9) from one finished
    load run.

    ``segment_counters`` maps segment name → the *delta* of the shared
    registry's counters over that segment (the bench probes them at
    boundaries) — warm-hit ratio per segment comes from the
    ``fleet.affinity.*`` deltas. The ``recovery`` block is the intra-run
    gate: post-spike and post-kill p99 must be within
    ``recovery_fail_over`` (fractional regression) of steady-state."""
    seg_order = [s.name for s in segments]
    by_segment: Dict[str, List[RequestRecord]] = {n: [] for n in seg_order}
    for r in records:
        by_segment.setdefault(r.segment, []).append(r)
    seg_durations = {s.name: s.duration_s for s in segments}

    per_segment: Dict[str, Any] = {}
    for name in seg_order:
        recs = by_segment.get(name, [])
        bucket = _bucket(recs, seg_durations.get(name, 0.0))
        deltas = (segment_counters or {}).get(name)
        if deltas is not None:
            bucket["warm_hit_ratio"] = _warm_ratio(deltas)
        workers: Dict[str, int] = {}
        for r in recs:
            if r.worker is not None:
                workers[r.worker] = workers.get(r.worker, 0) + 1
        total_w = sum(workers.values())
        bucket["per_worker"] = {
            w: {"requests": c,
                "share": round(c / total_w, 6) if total_w else 0.0}
            for w, c in sorted(workers.items())}
        per_segment[name] = bucket

    overall = _bucket(records, duration_s)
    totals: Dict[str, float] = {}
    for deltas in (segment_counters or {}).values():
        for k, v in deltas.items():
            totals[k] = totals.get(k, 0) + v
    overall["warm_hit_ratio"] = _warm_ratio(totals) \
        if segment_counters else None
    workers_all: Dict[str, int] = {}
    for r in records:
        if r.worker is not None:
            workers_all[r.worker] = workers_all.get(r.worker, 0) + 1
    total_w = sum(workers_all.values())
    overall["per_worker"] = {
        w: {"requests": c,
            "share": round(c / total_w, 6) if total_w else 0.0}
        for w, c in sorted(workers_all.items())}

    mix: Dict[str, int] = {}
    fps = set()
    for r in records:
        mix[r.kind] = mix.get(r.kind, 0) + 1
        fps.add(r.fp_index)

    steady_p99 = (per_segment.get("steady") or {}).get(
        "latency", {}).get("p99")
    recovery: Dict[str, Any] = {"fail_over": recovery_fail_over,
                                "steady_p99_s": steady_p99}
    violations = 0
    for name in ("spike", "post_kill"):
        # the gate reads the segment AFTER the disturbance settled: the
        # spike segment itself may shed; what must recover is post-spike
        # steady behavior. "post_kill" covers both (it follows the spike
        # AND the kill).
        if name == "spike":
            continue
        seg_p99 = (per_segment.get(name) or {}).get(
            "latency", {}).get("p99")
        if steady_p99 is None or seg_p99 is None or steady_p99 <= 0:
            recovery[f"{name}_ok"] = None
            continue
        regression = max(0.0, (seg_p99 - steady_p99) / steady_p99)
        ok = regression <= recovery_fail_over
        recovery[f"{name}_p99_s"] = seg_p99
        recovery[f"{name}_regression"] = round(regression, 6)
        recovery[f"{name}_ok"] = ok
        if not ok:
            violations += 1
    recovery["violations"] = violations
    if violations:
        counter_inc("slo.recovery_violations", violations)

    consistent = overall["sent"] == (overall["answered"] + overall["shed"]
                                     + overall["gave_up"])
    return {
        "requests": {k: overall[k] for k in
                     ("sent", "answered", "ok", "failed", "shed",
                      "gave_up", "retries")},
        "consistent": consistent,
        "duration_s": overall["duration_s"],
        "qps": overall["qps"],
        "answered_qps": overall["answered_qps"],
        "shed_rate": overall["shed_rate"],
        "latency": overall["latency"],
        "warm_hit_ratio": overall["warm_hit_ratio"],
        "per_worker": overall["per_worker"],
        "per_segment": per_segment,
        "segments": [{"name": s.name, "duration_s": round(s.duration_s, 3),
                      "rate_rps": round(s.rate_rps, 3)} for s in segments],
        "mix": mix,
        "distinct_fingerprints": len(fps),
        "recovery": recovery,
        "autoscale": {"events": list(autoscale_events or [])},
        "kill": kill,
    }
