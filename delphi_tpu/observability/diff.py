"""report-diff: metric/scorecard deltas between two run-report files.

    python -m delphi_tpu.observability.diff BASELINE.json CURRENT.json

Prints counter/gauge deltas (largest relative change first), per-phase
wall-time deltas, and — for schema-v3 reports carrying provenance
scorecards — per-attribute repair-quality deltas plus the same PSI/JS
divergences the drift gate (``observability/drift.py``) computes. The
manual companion to ``main.py --baseline-report``: same math, human-readable
output, no gating.
"""

import argparse
import sys
from typing import Any, Dict, List, Optional


def _metric_maps(report: Dict[str, Any]) -> Dict[str, Dict[str, float]]:
    metrics = report.get("metrics") or {}
    return {"counters": dict(metrics.get("counters") or {}),
            "gauges": dict(metrics.get("gauges") or {})}


def _span_walls(span: Optional[Dict[str, Any]],
                out: Optional[Dict[str, float]] = None) -> Dict[str, float]:
    if out is None:
        out = {}
    if span:
        out[span.get("name", "?")] = out.get(span.get("name", "?"), 0.0) \
            + float(span.get("wall_s", 0.0))
        for child in span.get("children", []):
            _span_walls(child, out)
    return out


def build_report_diff(baseline: Dict[str, Any],
                      current: Dict[str, Any]) -> Dict[str, Any]:
    """Structured delta between two (upgraded) run reports."""
    from delphi_tpu.observability.drift import compare_scorecards

    diff: Dict[str, Any] = {"metrics": {}, "spans": {}, "scorecards": None}
    base_m, cur_m = _metric_maps(baseline), _metric_maps(current)
    for kind in ("counters", "gauges"):
        deltas = {}
        for name in sorted(set(base_m[kind]) | set(cur_m[kind])):
            b, c = base_m[kind].get(name), cur_m[kind].get(name)
            if b == c:
                continue
            deltas[name] = {
                "baseline": b, "current": c,
                "delta": None if b is None or c is None
                else round(float(c) - float(b), 6)}
        diff["metrics"][kind] = deltas

    base_w = _span_walls(baseline.get("spans"))
    cur_w = _span_walls(current.get("spans"))
    for name in sorted(set(base_w) | set(cur_w)):
        b, c = base_w.get(name), cur_w.get(name)
        if b is None or c is None or abs(c - b) > 1e-6:
            diff["spans"][name] = {
                "baseline_s": None if b is None else round(b, 3),
                "current_s": None if c is None else round(c, 3),
                "delta_s": None if b is None or c is None
                else round(c - b, 3)}

    base_cards = baseline.get("scorecards")
    cur_cards = current.get("scorecards")
    if base_cards or cur_cards:
        diff["scorecards"] = compare_scorecards(cur_cards or {},
                                                base_cards or {})
    return diff


def _fmt(v: Any) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:g}"
    return str(v)


def format_report_diff(diff: Dict[str, Any], top: int = 25) -> str:
    lines: List[str] = []
    for kind in ("counters", "gauges"):
        deltas = diff["metrics"].get(kind) or {}
        if not deltas:
            continue
        lines.append(f"{kind} ({len(deltas)} changed):")
        ranked = sorted(
            deltas.items(),
            key=lambda kv: -abs(kv[1]["delta"] or float("inf"))
            if kv[1]["delta"] is not None else float("-inf"))
        for name, d in ranked[:top]:
            lines.append(f"  {name}: {_fmt(d['baseline'])} -> "
                         f"{_fmt(d['current'])} ({_fmt(d['delta'])})")
        if len(ranked) > top:
            lines.append(f"  ... and {len(ranked) - top} more")
    if diff["spans"]:
        lines.append("phase wall time (s):")
        for name, d in sorted(diff["spans"].items(),
                              key=lambda kv: -(abs(kv[1]["delta_s"] or 0.0))):
            lines.append(f"  {name}: {_fmt(d['baseline_s'])} -> "
                         f"{_fmt(d['current_s'])} ({_fmt(d['delta_s'])})")
    cards = diff.get("scorecards")
    if cards:
        lines.append("scorecard drift (baseline -> current):")
        for attr, d in sorted(cards["per_attribute"].items()):
            if "confidence_psi" not in d:
                lines.append(f"  {attr}: {d['status']}")
                continue
            lines.append(
                f"  {attr}: confidence_psi={_fmt(d['confidence_psi'])} "
                f"repair_value_js={_fmt(d['repair_value_js'])} "
                f"repair_rate_delta={_fmt(d['repair_rate_delta'])} "
                f"cells_flagged_delta={_fmt(d['cells_flagged_delta'])}")
        lines.append(f"  max divergence: {_fmt(cards['max_divergence'])} "
                     f"(psi={_fmt(cards['max_confidence_psi'])}, "
                     f"js={_fmt(cards['max_repair_value_js'])})")
    if not lines:
        lines.append("reports are metrically identical")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m delphi_tpu.observability.diff",
        description="print metric/scorecard deltas between two run reports")
    parser.add_argument("baseline", help="baseline run-report JSON path")
    parser.add_argument("current", help="current run-report JSON path")
    parser.add_argument("--top", type=int, default=25,
                        help="max changed metrics to print per section")
    args = parser.parse_args(argv)

    from delphi_tpu.observability.report import load_run_report

    baseline = load_run_report(args.baseline)
    current = load_run_report(args.current)
    if baseline is None or current is None:
        missing = args.baseline if baseline is None else args.current
        print(f"cannot load run report: {missing}", file=sys.stderr)
        return 2
    print(format_report_diff(build_report_diff(baseline, current),
                             top=args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
