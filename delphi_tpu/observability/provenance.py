"""Per-cell repair provenance ledger + per-attribute quality scorecards.

Answers "why did this cell change?" without rerunning anything: when
``DELPHI_PROVENANCE_PATH`` (or the ``repair.provenance.path`` session
config) is set, every flagged cell accumulates one ledger entry across the
pipeline phases —

* the detector(s) that flagged it (``errors.py`` / ``ops/detect.py``,
  including the per-constraint label for denial constraints),
* the candidate domain size the naive-Bayes scoring considered
  (``ops/domain.py``),
* the model's top-k posterior with probabilities (the ``prob_top_k`` PMF
  path and the plain prediction path both hook in),
* the final decision (``repaired`` / ``kept`` / ``below_threshold``) and a
  ``decision_reason`` — including the one-tuple-DC minimization's
  "confidence unavailable -> keep all repairs" fallback, recorded as the
  distinct :data:`REASON_CONFIDENCE_UNAVAILABLE`.

The ledger follows the metrics-registry contract: instrumentation sites
read one module-level pointer (:func:`active_ledger`) and skip entirely
when it is ``None`` — a disabled run pays a single pointer check per hook.
The ledger attaches to the :class:`~delphi_tpu.observability.spans.RunRecorder`
at ``start_recording`` and finalizes at ``stop_recording``: the JSONL file
is written (unless the path is ``:memory:``) and the entries aggregate into
per-attribute **quality scorecards** (repair rate, confidence histogram,
low-confidence fraction, domain-size distribution, repaired-value counts)
that embed in the run report as schema v3 and merge across hosts through
``gather_per_process``. ``observability/drift.py`` compares scorecards
across runs.
"""

import contextlib
import json
import os
import threading
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from delphi_tpu.utils import setup_logger

_logger = setup_logger()

MEMORY_PATH = ":memory:"

DECISION_REPAIRED = "repaired"
DECISION_KEPT = "kept"
DECISION_BELOW_THRESHOLD = "below_threshold"

REASON_MODEL_REPAIR = "model_repair"
REASON_MAXIMAL_LIKELIHOOD = "maximal_likelihood"
REASON_RULE_REGEX = "rule_regex"
# user-supplied RegexStructureRepair rules record under their own label so
# they stay distinguishable from the escalation plane's INDUCED patterns
REASON_RULE_REGEX_STRUCTURE = "rule_regex_structure"
REASON_RULE_NEAREST_VALUE = "rule_nearest_value"
REASON_PREDICTION_MATCHES_CURRENT = "prediction_matches_current"
REASON_WEAK_LABEL_CLEAN = "weak_label_clean"
REASON_NOT_TARGETED = "attribute_not_targeted"
REASON_NO_PREDICTION = "no_prediction"
REASON_DC_MINIMIZED = "dc_minimized_revert"
REASON_CONFIDENCE_UNAVAILABLE = "confidence_unavailable_keep_all"
REASON_VALIDATION_VIOLATION = "validation_violation"
REASON_BELOW_SCORE_THRESHOLD = "below_score_threshold"
REASON_NO_REPAIR_ATTEMPTED = "no_repair_attempted"
# escalation-tier decisions (delphi_tpu/escalate): one reason per tier so
# an audit can separate induced-pattern, joint-inference, and external-
# adapter repairs from the statistical pipeline's
REASON_ESCALATED_PATTERN = "escalated_pattern"
REASON_ESCALATED_JOINT = "escalated_joint"
REASON_ESCALATED_ADAPTER = "escalated_adapter"

# Reasons a later, more generic decision pass (candidate extraction) must
# not overwrite: they carry WHY the generic outcome happened.
_STICKY_REASONS = frozenset({
    REASON_DC_MINIMIZED, REASON_CONFIDENCE_UNAVAILABLE,
    REASON_RULE_REGEX, REASON_RULE_REGEX_STRUCTURE,
    REASON_RULE_NEAREST_VALUE,
    REASON_ESCALATED_PATTERN, REASON_ESCALATED_JOINT,
    REASON_ESCALATED_ADAPTER,
})

CONFIDENCE_BINS = 20
LOW_CONFIDENCE = 0.5  # top-posterior threshold for "low confidence" repairs
_VALUE_CAP = 50       # distinct repaired values kept per attribute scorecard
OTHER_VALUES = "__other__"


def provenance_path() -> Optional[str]:
    """The configured ledger destination (``:memory:`` keeps it in-process
    only), or ``None`` when provenance is disabled. ``DELPHI_PROVENANCE_PATH``
    wins over the ``repair.provenance.path`` session config — the same
    precedence as every other observability toggle."""
    path = os.environ.get("DELPHI_PROVENANCE_PATH")
    if path:
        return path
    from delphi_tpu.session import get_session

    return get_session().conf.get("repair.provenance.path") or None


def provenance_configured() -> bool:
    return provenance_path() is not None


def _top_k() -> int:
    """Posterior entries kept per cell (``DELPHI_PROVENANCE_TOP_K``)."""
    try:
        return max(1, int(os.environ.get("DELPHI_PROVENANCE_TOP_K", "5")))
    except ValueError:
        return 5


def _is_null(v: Any) -> bool:
    if v is None:
        return True
    try:
        import math

        return isinstance(v, float) and math.isnan(v)
    except Exception:
        return False


def _spell(v: Any) -> Optional[str]:
    return None if _is_null(v) else str(v)


class ProvenanceLedger:
    """Accumulates one record per flagged cell, keyed by
    ``(str(row_id), attribute)``. Hooks are vectorized — one call per
    detector frame / attribute chunk, not per cell — and thread-safe (the
    batched trainer and the live ``/report`` endpoint may touch it off the
    main thread)."""

    def __init__(self, path: str) -> None:
        self.path = path
        self.top_k = _top_k()
        self.model_scores: Dict[str, float] = {}
        self._cells: Dict[Tuple[str, str], Dict[str, Any]] = {}
        # row position -> row id spelling, filled during detection (phase 1
        # frames carry both); lets position-keyed phases (domain scoring)
        # land on the same entries as id-keyed phases (repair decisions).
        self._rid_of: Dict[int, str] = {}
        self._notes: List[Dict[str, Any]] = []
        self._lock = threading.Lock()
        self._written = False

    def __len__(self) -> int:
        return len(self._cells)

    def record_note(self, kind: str, detail: str) -> None:
        """Run-level annotation (not keyed to a cell): the resilience plane
        stamps one per degradation that changed a decision path — shrink /
        evict / CPU fallback — so an audited re-run can see that this run's
        dispatch diverged from the fault-free plan and why."""
        with self._lock:
            self._notes.append({"note": kind, "detail": detail,
                                "seq": len(self._notes)})

    def notes(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(n) for n in self._notes]

    def _entry(self, rid: str, attr: str) -> Dict[str, Any]:
        key = (rid, attr)
        e = self._cells.get(key)
        if e is None:
            e = self._cells[key] = {"row_id": rid, "attribute": attr,
                                    "detectors": []}
        return e

    # -- phase 1: detection ------------------------------------------------

    def record_detection(self, detector: str, rows: Sequence[int],
                         attrs: Any, row_ids: Sequence[Any]) -> None:
        """One call per detector result frame. ``attrs`` is either an array
        aligned with ``rows`` or a single attribute name."""
        scalar_attr = isinstance(attrs, str)
        with self._lock:
            for i, rid in enumerate(row_ids):
                rid_s = str(rid)
                attr = attrs if scalar_attr else str(attrs[i])
                self._rid_of[int(rows[i])] = rid_s
                e = self._entry(rid_s, attr)
                if detector not in e["detectors"]:
                    e["detectors"].append(detector)

    def record_current_values(self, row_ids: Sequence[Any], attrs: Sequence[Any],
                              currents: Sequence[Any]) -> None:
        with self._lock:
            for rid, a, c in zip(row_ids, attrs, currents):
                self._entry(str(rid), str(a))["current_value"] = _spell(c)

    # -- phase 1b: domain analysis ----------------------------------------

    def record_domain_sizes(self, rows: Sequence[int], attr: str,
                            sizes: Sequence[int]) -> None:
        """Candidate domain size per cell, keyed by row POSITION (domain
        scoring never sees row ids; detection filled the translation)."""
        with self._lock:
            a = str(attr)
            for r, s in zip(rows, sizes):
                rid = self._rid_of.get(int(r))
                if rid is not None:
                    self._entry(rid, a)["domain_size"] = int(s)

    def record_weak_label_demotions(self, row_ids: Sequence[Any],
                                    attrs: Sequence[Any]) -> None:
        with self._lock:
            for rid, a in zip(row_ids, attrs):
                e = self._entry(str(rid), str(a))
                e["decision"] = DECISION_KEPT
                e["decision_reason"] = REASON_WEAK_LABEL_CLEAN

    # -- phase 2: training -------------------------------------------------

    def record_model_score(self, attr: str, score: Any) -> None:
        try:
            s = float(score)
        except (TypeError, ValueError):
            return
        if s == s and s not in (float("inf"), float("-inf")):
            with self._lock:
                self.model_scores[str(attr)] = s

    # -- phase 3: repair ---------------------------------------------------

    def record_posterior(self, attr: str, row_ids: Sequence[Any],
                         classes: Sequence[str], probs: Any,
                         domain_size: Optional[int] = None) -> None:
        """Top-k posterior per cell from one ``predict_proba`` launch:
        ``probs`` is an (n, k) matrix aligned with ``row_ids``; ``classes``
        the shared class list. ``domain_size`` (the model's class count)
        fills in where domain scoring didn't run for the cell or kept no
        candidates (the model then considered its full class list)."""
        import numpy as np

        P = np.asarray(probs, dtype=np.float64)
        if P.ndim != 2 or len(P) != len(row_ids):
            return
        kk = min(self.top_k, P.shape[1])
        order = np.argsort(-P, axis=1, kind="stable")[:, :kk]
        top = np.take_along_axis(P, order, axis=1)
        a = str(attr)
        with self._lock:
            for i, rid in enumerate(row_ids):
                e = self._entry(str(rid), a)
                e["top_k"] = [{"value": str(classes[j]),
                               "prob": round(float(p), 6)}
                              for j, p in zip(order[i], top[i])]
                e["confidence"] = float(top[i, 0]) if kk else None
                if domain_size is not None and not e.get("domain_size"):
                    e["domain_size"] = int(domain_size)

    def record_point_predictions(self, attr: str, row_ids: Sequence[Any],
                                 values: Sequence[Any],
                                 domain_size: Optional[int] = None) -> None:
        """Degenerate posterior for models without ``predict_proba``
        (regressors, FD rules, constant fallbacks): top-1, no probability."""
        a = str(attr)
        with self._lock:
            for rid, v in zip(row_ids, values):
                e = self._entry(str(rid), a)
                e["top_k"] = [{"value": _spell(v), "prob": None}]
                if domain_size is not None and not e.get("domain_size"):
                    e["domain_size"] = int(domain_size)

    def record_pmf_topk(self, attr: str, row_ids: Sequence[Any],
                        pmf_lists: Iterable[List[Dict[str, Any]]]) -> None:
        """Cost-weighted top-k from the ``prob_top_k`` PMF path — overwrites
        the raw posterior with what the candidate selection actually used."""
        a = str(attr)
        with self._lock:
            for rid, pmf in zip(row_ids, pmf_lists):
                if not pmf:
                    continue
                e = self._entry(str(rid), a)
                e["top_k"] = [{"value": _spell(p.get("class")),
                               "prob": round(float(p.get("prob", 0.0)), 6)}
                              for p in pmf[:self.top_k]]
                e["confidence"] = float(pmf[0].get("prob", 0.0))

    def record_decisions(self, row_ids: Sequence[Any], attrs: Any,
                         decision: str, reason: str,
                         repaired: Optional[Sequence[Any]] = None,
                         sticky_aware: bool = False) -> None:
        """Final (or provisional) decision for many cells. With
        ``sticky_aware`` the decision/repaired value still update, but a
        reason in :data:`_STICKY_REASONS` recorded by an earlier, more
        specific pass is preserved."""
        scalar_attr = isinstance(attrs, str)
        with self._lock:
            for i, rid in enumerate(row_ids):
                attr = attrs if scalar_attr else str(attrs[i])
                e = self._entry(str(rid), attr)
                e["decision"] = decision
                if not (sticky_aware
                        and e.get("decision_reason") in _STICKY_REASONS):
                    e["decision_reason"] = reason
                if repaired is not None:
                    e["repaired"] = _spell(repaired[i])

    def record_decision(self, row_id: Any, attr: str, decision: str,
                        reason: str, repaired: Any = None) -> None:
        with self._lock:
            e = self._entry(str(row_id), str(attr))
            e["decision"] = decision
            e["decision_reason"] = reason
            if repaired is not None:
                e["repaired"] = _spell(repaired)

    # -- phase 3b: escalation ----------------------------------------------

    def record_escalation_routed(self, row_id: Any, attr: str,
                                 route_reason: str) -> None:
        """Marks a cell the escalation router selected (whether or not any
        tier ends up repairing it) — the scorecards' routed counts come
        from these marks."""
        with self._lock:
            e = self._entry(str(row_id), str(attr))
            e["escalation_routed"] = route_reason

    def record_escalation(self, row_id: Any, attr: str, tier: str,
                          reason: str, repaired: Any,
                          confidence: Any = None) -> None:
        """Final decision from an escalation tier: repaired, with the tier
        stamped on the entry. The reason is sticky — the extraction pass's
        generic ``model_repair`` must not overwrite it."""
        with self._lock:
            e = self._entry(str(row_id), str(attr))
            e["decision"] = DECISION_REPAIRED
            e["decision_reason"] = reason
            e["repaired"] = _spell(repaired)
            e["escalation_tier"] = str(tier)
            if confidence is not None:
                try:
                    e["escalation_confidence"] = round(float(confidence), 6)
                except (TypeError, ValueError):
                    pass

    def clear_decision(self, row_id: Any, attr: str) -> None:
        """Undo a provisional decision (the DC fixpoint pass restoring a
        reverted repair) so the extraction pass re-derives it."""
        with self._lock:
            e = self._cells.get((str(row_id), str(attr)))
            if e is not None:
                e.pop("decision", None)
                e.pop("decision_reason", None)

    # -- incremental splice ------------------------------------------------

    def splice_prior_entries(self, prior_entries: Sequence[Dict[str, Any]],
                             recomputed_reason: str = "row_replanned",
                             reused_reason: str = "outside_delta") \
            -> Tuple[int, int]:
        """Splices a prior run's ledger entries under this (delta) run's.

        Every cell THIS run touched keeps its fresh entry, stamped
        ``splice: recomputed``; a prior entry whose cell this run did not
        touch is inserted verbatim, stamped ``splice: reused``. The caller
        pre-filters ``prior_entries`` to rows outside the delta plan — a
        replanned row's prior cells must NOT come back, since the re-run is
        their truth now (including "clean now, so no entry at all").
        Returns ``(reused, recomputed)`` counts."""
        with self._lock:
            for e in self._cells.values():
                e["splice"] = "recomputed"
                e["splice_reason"] = recomputed_reason
            reused = 0
            for p in prior_entries or []:
                key = (str(p.get("row_id")), str(p.get("attribute")))
                if key in self._cells:
                    continue
                e = dict(p)
                e["splice"] = "reused"
                e["splice_reason"] = reused_reason
                self._cells[key] = e
                reused += 1
            recomputed = len(self._cells) - reused
        return reused, recomputed

    # -- finalize ----------------------------------------------------------

    def entries(self) -> List[Dict[str, Any]]:
        """Ledger rows in insertion order, defaults filled: every entry has
        a decision/decision_reason (cells no phase decided on — e.g. a
        detect-only run — report ``kept``/``no_repair_attempted``)."""
        with self._lock:
            rows = [dict(e) for e in self._cells.values()]
        for e in rows:
            e.setdefault("decision", DECISION_KEPT)
            e.setdefault("decision_reason", REASON_NO_REPAIR_ATTEMPTED)
        return rows

    def write(self) -> None:
        """One-shot crash-consistent JSONL dump through the durable-store
        seam (site ``store.provenance``): an envelope header line (``#``
        prefixed, so line-oriented consumers skip it) followed by one JSON
        line per cell, then the run-level notes (resilience degradations)
        in the same stream, distinguished by the "note" key. ``:memory:``
        skips the file entirely."""
        if self.path == MEMORY_PATH or self._written:
            return
        self._written = True
        from delphi_tpu.parallel import store as dstore
        try:
            rows = self.entries() + self.notes()
            dstore.write_jsonl(os.path.abspath(self.path), rows,
                               schema="provenance", site="store.provenance")
            _logger.info(f"Provenance ledger written to {self.path} "
                         f"({len(self._cells)} cells)")
        except Exception as e:
            _logger.warning(f"failed to write provenance ledger: {e}")

    def scorecards(self) -> Dict[str, Dict[str, Any]]:
        return build_scorecards(self.entries(), self.model_scores)


# -- scorecards ------------------------------------------------------------


def _empty_card() -> Dict[str, Any]:
    return {
        "cells_flagged": 0,
        "cells_repaired": 0,
        "detectors": {},
        "decisions": {},
        "confidence": {"count": 0, "sum": 0.0, "min": None, "max": None,
                       "bins": [0] * CONFIDENCE_BINS},
        "domain_size": {"count": 0, "sum": 0, "min": None, "max": None,
                        "hist": {}},
        "repaired_values": {},
        "escalation": {"routed": 0, "routed_reasons": {}, "repairs": {}},
    }


def _size_bucket(size: int) -> str:
    """Power-of-two domain-size buckets: "0", "1", "2-3", "4-7", ..."""
    if size <= 0:
        return "0"
    from delphi_tpu.parallel.planner import pow2_floor
    lo = pow2_floor(size)
    hi = lo * 2 - 1
    return str(lo) if hi == lo else f"{lo}-{hi}"


def _observe(stats: Dict[str, Any], value: float) -> None:
    stats["count"] += 1
    stats["sum"] += value
    stats["min"] = value if stats["min"] is None else min(stats["min"], value)
    stats["max"] = value if stats["max"] is None else max(stats["max"], value)


def build_scorecards(entries: Iterable[Dict[str, Any]],
                     model_scores: Optional[Dict[str, float]] = None) \
        -> Dict[str, Dict[str, Any]]:
    """Aggregates ledger entries into per-attribute quality scorecards.
    Every non-derived field merges exactly across hosts (sums, mins/maxes,
    histogram-bin sums) — see :func:`merge_scorecards`."""
    cards: Dict[str, Dict[str, Any]] = {}
    for e in entries:
        card = cards.setdefault(e["attribute"], _empty_card())
        card["cells_flagged"] += 1
        for d in e.get("detectors") or ["unknown"]:
            card["detectors"][d] = card["detectors"].get(d, 0) + 1
        reason = e.get("decision_reason") or REASON_NO_REPAIR_ATTEMPTED
        card["decisions"][reason] = card["decisions"].get(reason, 0) + 1
        if e.get("decision") == DECISION_REPAIRED:
            card["cells_repaired"] += 1
            v = _spell(e.get("repaired"))
            if v is not None:
                rv = card["repaired_values"]
                rv[v] = rv.get(v, 0) + 1
        conf = e.get("confidence")
        if conf is not None and conf == conf:
            c = min(max(float(conf), 0.0), 1.0)
            _observe(card["confidence"], c)
            bins = card["confidence"]["bins"]
            bins[min(int(c * CONFIDENCE_BINS), CONFIDENCE_BINS - 1)] += 1
        ds = e.get("domain_size")
        if ds is not None:
            _observe(card["domain_size"], int(ds))
            hist = card["domain_size"]["hist"]
            b = _size_bucket(int(ds))
            hist[b] = hist.get(b, 0) + 1
        route = e.get("escalation_routed")
        if route:
            esc = card["escalation"]
            esc["routed"] += 1
            esc["routed_reasons"][route] = \
                esc["routed_reasons"].get(route, 0) + 1
        tier = e.get("escalation_tier")
        if tier:
            reps = card["escalation"]["repairs"]
            reps[tier] = reps.get(tier, 0) + 1
    for attr, card in cards.items():
        if model_scores and attr in model_scores:
            card["model_cv_score"] = round(model_scores[attr], 6)
        _cap_values(card)
        _derive(card)
    return cards


def _cap_values(card: Dict[str, Any]) -> None:
    rv = card["repaired_values"]
    if len(rv) <= _VALUE_CAP:
        return
    top = sorted(rv.items(), key=lambda kv: (-kv[1], kv[0]))
    kept = dict(top[:_VALUE_CAP])
    kept[OTHER_VALUES] = kept.get(OTHER_VALUES, 0) \
        + sum(n for _, n in top[_VALUE_CAP:])
    card["repaired_values"] = kept


def _derive(card: Dict[str, Any]) -> None:
    """(Re)computes the derived fields from the mergeable raw ones."""
    flagged = card["cells_flagged"]
    card["repair_rate"] = round(card["cells_repaired"] / flagged, 6) \
        if flagged else 0.0
    conf = card["confidence"]
    n = conf["count"]
    conf["mean"] = round(conf["sum"] / n, 6) if n else None
    low_bins = int(LOW_CONFIDENCE * CONFIDENCE_BINS)
    conf["low_confidence_fraction"] = \
        round(sum(conf["bins"][:low_bins]) / n, 6) if n else None
    ds = card["domain_size"]
    ds["mean"] = round(ds["sum"] / ds["count"], 6) if ds["count"] else None


def merge_scorecards(cards_list: Sequence[Optional[Dict[str, Any]]]) \
        -> Dict[str, Dict[str, Any]]:
    """Cluster-wide scorecard merge: counters sum, mins/maxes combine,
    histogram bins add, derived fields recompute from the merged raws."""
    merged: Dict[str, Dict[str, Any]] = {}
    for cards in cards_list:
        for attr, card in (cards or {}).items():
            m = merged.setdefault(attr, _empty_card())
            m["cells_flagged"] += card.get("cells_flagged", 0)
            m["cells_repaired"] += card.get("cells_repaired", 0)
            for field in ("detectors", "decisions", "repaired_values"):
                for k, v in card.get(field, {}).items():
                    m[field][k] = m[field].get(k, 0) + v
            for field in ("confidence", "domain_size"):
                src, dst = card.get(field, {}), m[field]
                dst["count"] += src.get("count", 0)
                dst["sum"] += src.get("sum", 0)
                for agg, op in (("min", min), ("max", max)):
                    v = src.get(agg)
                    if v is not None:
                        dst[agg] = v if dst[agg] is None else op(dst[agg], v)
            for i, v in enumerate(card.get("confidence", {}).get("bins", [])):
                if i < CONFIDENCE_BINS:
                    m["confidence"]["bins"][i] += v
            for b, v in card.get("domain_size", {}).get("hist", {}).items():
                m["domain_size"]["hist"][b] = \
                    m["domain_size"]["hist"].get(b, 0) + v
            esc_src = card.get("escalation", {})
            esc_dst = m["escalation"]
            esc_dst["routed"] += esc_src.get("routed", 0)
            for field in ("routed_reasons", "repairs"):
                for k, v in esc_src.get(field, {}).items():
                    esc_dst[field][k] = esc_dst[field].get(k, 0) + v
            if "model_cv_score" in card and "model_cv_score" not in m:
                m["model_cv_score"] = card["model_cv_score"]
    for card in merged.values():
        _cap_values(card)
        _derive(card)
    return merged


def scorecard_summary(scorecards: Optional[Dict[str, Dict[str, Any]]]) \
        -> Optional[Dict[str, Dict[str, Any]]]:
    """Compact per-attribute view for bench entries and CLI output."""
    if not scorecards:
        return None
    return {attr: {
        "cells_flagged": card.get("cells_flagged", 0),
        "repair_rate": card.get("repair_rate", 0.0),
        "low_confidence_fraction":
            card.get("confidence", {}).get("low_confidence_fraction"),
        "mean_confidence": card.get("confidence", {}).get("mean"),
    } for attr, card in sorted(scorecards.items())}


# -- recorder lifecycle ----------------------------------------------------

# The process-wide active ledger. Written only by maybe_start/finalize;
# instrumentation reads it with a single attribute load (same contract as
# spans._current / the metrics registry).
_ledger: Optional[ProvenanceLedger] = None

# Per-thread ledgers for the serving plane: each /repair request gets its
# own ledger so concurrent sessions' cells never interleave in one file.
# _scoped_count gates the thread-local lookup so the disabled path stays a
# global read + one int compare.
_scoped_tls = threading.local()
_scoped_count = 0
_scoped_lock = threading.Lock()


def active_ledger() -> Optional[ProvenanceLedger]:
    if _scoped_count:
        led = getattr(_scoped_tls, "ledger", None)
        if led is not None:
            return led
    return _ledger


@contextlib.contextmanager
def scoped_ledger(ledger: Optional[ProvenanceLedger]):
    """Routes this thread's provenance writes into ``ledger`` (a no-op
    context when None). The serving plane wraps each request's run in one
    of these; the process-global ledger, if any, is shadowed for the
    duration so per-request cells land in per-request files."""
    global _scoped_count
    if ledger is None:
        yield None
        return
    prev = getattr(_scoped_tls, "ledger", None)
    _scoped_tls.ledger = ledger
    with _scoped_lock:
        _scoped_count += 1
    try:
        yield ledger
    finally:
        _scoped_tls.ledger = prev
        with _scoped_lock:
            _scoped_count -= 1


def maybe_start(recorder: Any) -> None:
    """Attaches a fresh ledger to the recorder when provenance is
    configured. Called by ``start_recording``; nested runs keep the outer
    run's ledger."""
    global _ledger
    if _ledger is not None:
        return
    path = provenance_path()
    if not path:
        return
    _ledger = ProvenanceLedger(path)
    recorder.provenance = _ledger
    _logger.info(f"Provenance ledger active (path={path})")


def scorecards_for(recorder: Any) -> Optional[Dict[str, Any]]:
    """The recorder's scorecards: the finalized ones when available, else a
    live aggregation of the in-flight ledger (the ``/report`` endpoint)."""
    cards = getattr(recorder, "scorecards", None)
    if cards is not None:
        return cards
    led = getattr(recorder, "provenance", None)
    return led.scorecards() if led is not None else None


def finalize(recorder: Any) -> None:
    """Writes the ledger file and freezes the scorecards onto the recorder.
    Idempotent: ``main.py`` calls it early (so the drift gate can run while
    the live ``/metrics`` plane is still up) and ``stop_recording`` calls it
    again."""
    global _ledger
    led = getattr(recorder, "provenance", None)
    if led is None:
        return
    if getattr(recorder, "scorecards", None) is None:
        recorder.scorecards = led.scorecards()
    led.write()
    if _ledger is led:
        _ledger = None
