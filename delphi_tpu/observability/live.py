"""Live telemetry plane: HTTP endpoints, stall watchdog, resource sampler.

PR 1's run report is post-hoc — nothing is observable until the JSON lands.
This module is the *live* half of the observability subsystem, attached to a
:class:`~delphi_tpu.observability.spans.RunRecorder` for the duration of one
run:

* an HTTP server (stdlib ``ThreadingHTTPServer``, no dependencies) exposing

  - ``/metrics``  — Prometheus text exposition rendered from the live
    ``MetricsRegistry`` snapshot plus current-phase / span-depth gauges,
  - ``/healthz``  — liveness JSON,
  - ``/report``   — the in-flight run report (same schema as the final one,
    with ``"status": "running"``);

* a **watchdog** thread that heartbeats every thread's active span stack
  into the JSONL event stream and, when no span transition has happened for
  the stall timeout (hung XLA compile, wedged DCN collective), dumps all
  Python thread stacks via ``sys._current_frames()`` to the log and bumps
  the ``watchdog.stalls`` counter;

* a **resource sampler** thread recording process RSS and per-device HBM
  ``memory_stats()`` bytes-in-use gauges, plus a jit compile-time histogram
  fed by a ``jax.monitoring`` duration listener.

Configuration (env beats session conf; nothing here runs unless one of the
first two is set):

    DELPHI_METRICS_PORT / repair.metrics.port      serve HTTP on this port
                                                   (0 = ephemeral; read the
                                                   bound port from the log
                                                   or ``LivePlane.port``)
    DELPHI_STALL_TIMEOUT_S /                       watchdog stall threshold,
        repair.metrics.stall_timeout_s             seconds (default 300;
                                                   <= 0 disables stall
                                                   detection)
    DELPHI_RESOURCE_SAMPLE_S /                     sampler period, seconds
        repair.metrics.sample_interval_s           (default 10; <= 0 off)
    DELPHI_RESOURCE_SAMPLER                        boolean sampler toggle
                                                   (default on)
    DELPHI_METRICS_HOST                            bind address (default
                                                   127.0.0.1)

With none of them set, ``maybe_start`` is two config lookups and no thread,
socket, or listener is ever created.
"""

import json
import os
import re
import sys
import threading
import time
import traceback
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional

from delphi_tpu.utils import setup_logger

_logger = setup_logger()

DEFAULT_STALL_TIMEOUT_S = 300.0
DEFAULT_SAMPLE_INTERVAL_S = 10.0

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


# -- configuration -----------------------------------------------------------


def _parse_number(raw: Any, what: str, cast) -> Optional[float]:
    try:
        return cast(str(raw).strip())
    except (TypeError, ValueError):
        _logger.warning(f"invalid {what}: {raw!r} (ignored)")
        return None


def _env_or_conf(env_key: str, conf_key: str, cast) -> Optional[float]:
    raw = os.environ.get(env_key)
    if raw is not None and str(raw).strip() != "":
        return _parse_number(raw, env_key, cast)
    from delphi_tpu.session import get_session

    session = get_session()
    return session.conf_int(conf_key) if cast is int \
        else session.conf_float(conf_key)


def metrics_port() -> Optional[int]:
    """The configured live-server port (0 = ephemeral), or ``None`` when no
    server is requested. ``DELPHI_METRICS_PORT`` wins over the
    ``repair.metrics.port`` session config."""
    port = _env_or_conf("DELPHI_METRICS_PORT", "repair.metrics.port", int)
    return None if port is None else int(port)


def stall_timeout_s() -> Optional[float]:
    """The *explicitly configured* watchdog stall timeout, or ``None`` when
    unset (the plane then uses :data:`DEFAULT_STALL_TIMEOUT_S` if it runs
    for another reason)."""
    return _env_or_conf("DELPHI_STALL_TIMEOUT_S",
                        "repair.metrics.stall_timeout_s", float)


def sample_interval_s() -> float:
    interval = _env_or_conf("DELPHI_RESOURCE_SAMPLE_S",
                            "repair.metrics.sample_interval_s", float)
    return DEFAULT_SAMPLE_INTERVAL_S if interval is None else float(interval)


def live_configured() -> bool:
    """True when a run should activate the live plane: a metrics port is
    configured, or a stall timeout was set explicitly (watchdog-only mode
    for headless runs that just want hang diagnostics)."""
    return metrics_port() is not None or stall_timeout_s() is not None


def maybe_start(recorder: Any) -> Optional["LivePlane"]:
    """Starts the live plane for ``recorder`` when configured; returns the
    plane (also stored on ``recorder.live``) or ``None``. Cheap when
    disabled: two config lookups, no threads."""
    port = metrics_port()
    stall = stall_timeout_s()
    if port is None and stall is None:
        return None
    from delphi_tpu import observability as obs

    sampler_on = obs._flag_enabled(
        os.environ.get("DELPHI_RESOURCE_SAMPLER", "1"))
    plane = LivePlane(
        recorder, port=port,
        stall_timeout=DEFAULT_STALL_TIMEOUT_S if stall is None else stall,
        sample_interval=sample_interval_s() if sampler_on else 0.0)
    plane.start()
    recorder.live = plane
    return plane


# -- Prometheus text exposition ----------------------------------------------

_NAME_SUB = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    sanitized = _NAME_SUB.sub("_", name)
    if sanitized and sanitized[0].isdigit():
        sanitized = "_" + sanitized
    return "delphi_" + sanitized


def _prom_value(value: Any) -> str:
    if isinstance(value, bool):
        return "1" if value else "0"
    if isinstance(value, int):
        return str(value)
    return repr(float(value))


def _prom_label(value: str) -> str:
    return value.replace("\\", r"\\").replace("\n", r"\n").replace('"', r'\"')


def render_prometheus(recorder: Any) -> str:
    """The live registry plus run-level gauges in Prometheus text exposition
    format 0.0.4. Counters and gauges map 1:1; histograms render as
    summaries (p50/p90/p95/p99 quantiles over the reservoir sample)."""
    snap = recorder.registry.snapshot()
    lines: List[str] = []

    def emit(name: str, kind: str, samples: List[str]) -> None:
        lines.append(f"# TYPE {name} {kind}")
        lines.extend(samples)

    for name, value in snap["counters"].items():
        pn = _prom_name(name)
        emit(pn, "counter", [f"{pn} {_prom_value(value)}"])
    for name, value in snap["gauges"].items():
        pn = _prom_name(name)
        emit(pn, "gauge", [f"{pn} {_prom_value(value)}"])
    for name, hist in snap["histograms"].items():
        pn = _prom_name(name)
        samples = []
        for q, key in (("0.5", "p50"), ("0.9", "p90"),
                       ("0.95", "p95"), ("0.99", "p99")):
            if hist[key] is not None:
                samples.append(
                    f'{pn}{{quantile="{q}"}} {_prom_value(hist[key])}')
        samples.append(f"{pn}_sum {_prom_value(hist['sum'])}")
        samples.append(f"{pn}_count {_prom_value(hist['count'])}")
        emit(pn, "summary", samples)

    emit("delphi_run_elapsed_seconds", "gauge",
         [f"delphi_run_elapsed_seconds {recorder.elapsed_s():.6f}"])
    emit("delphi_span_depth", "gauge",
         [f"delphi_span_depth {recorder.span_depth()}"])
    emit("delphi_span_transitions_total", "counter",
         [f"delphi_span_transitions_total {recorder.transition_count}"])
    idle = time.perf_counter() - recorder.last_transition
    emit("delphi_span_idle_seconds", "gauge",
         [f"delphi_span_idle_seconds {idle:.6f}"])
    emit("delphi_current_phase_info", "gauge",
         ['delphi_current_phase_info{phase="%s"} 1'
          % _prom_label(recorder.current_phase)])
    return "\n".join(lines) + "\n"


# -- HTTP endpoints ----------------------------------------------------------


class _Handler(BaseHTTPRequestHandler):
    # the pipeline logger owns narration; default stderr access logs would
    # interleave with it on every scrape
    def log_message(self, fmt: str, *args: Any) -> None:
        _logger.debug("metrics server: " + fmt % args)

    def _respond(self, status: int, content_type: str, body: bytes) -> None:
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:  # noqa: N802 (BaseHTTPRequestHandler API)
        plane: "LivePlane" = self.server.plane  # type: ignore[attr-defined]
        path = self.path.split("?", 1)[0]
        try:
            if path == "/healthz":
                from delphi_tpu.parallel import dist_resilience
                health = {
                    "status": "ok",
                    "phase": plane.recorder.current_phase,
                    "elapsed_s": round(plane.recorder.elapsed_s(), 3),
                }
                if dist_resilience.single_host_latched():
                    # degraded, not dead: the survivor is still making
                    # progress on the shrunk mesh
                    health["status"] = "degraded"
                    health["degraded_ranks"] = \
                        dist_resilience.degraded_ranks()
                from delphi_tpu.parallel import store as dstore
                quarantined = dstore.quarantine_count()
                if quarantined:
                    # corrupt artifacts were quarantined this process:
                    # serving continues on recompute, but an operator
                    # should look at <root>/quarantine/
                    health["status"] = "degraded"
                    health["quarantined"] = quarantined
                body = json.dumps(health).encode()
                self._respond(200, "application/json", body)
            elif path == "/metrics":
                body = render_prometheus(plane.recorder).encode()
                self._respond(200, PROMETHEUS_CONTENT_TYPE, body)
            elif path == "/report":
                from delphi_tpu.observability.report import build_run_report

                report = build_run_report(
                    plane.recorder,
                    run={"in_flight": True,
                         "elapsed_s": round(plane.recorder.elapsed_s(), 3)},
                    status="running")
                body = json.dumps(report, indent=2).encode()
                self._respond(200, "application/json", body)
            elif path.startswith("/trace/"):
                from delphi_tpu.observability import trace as _trace
                trace_id = path[len("/trace/"):]
                doc = _trace.load_trace(trace_id)
                if doc is None:
                    self._respond(404, "application/json", json.dumps(
                        {"error": f"no trace {trace_id!r} under "
                                  f"{_trace.trace_root() or '<unset>'}"}
                    ).encode())
                else:
                    self._respond(200, "application/json",
                                  json.dumps(doc).encode())
            else:
                self._respond(404, "application/json",
                              b'{"error": "not found"}')
        except Exception as e:
            # a scrape failure must not kill the handler thread loudly
            _logger.warning(f"metrics endpoint {path} failed: {e}")
            try:
                self._respond(500, "application/json",
                              json.dumps({"error": str(e)}).encode())
            except Exception:
                pass


# -- watchdog ----------------------------------------------------------------


def _dump_thread_stacks(recorder: Any, idle_s: float) -> None:
    names = {t.ident: t.name for t in threading.enumerate()}
    active = recorder.active_spans()
    lines = [f"watchdog: no span transition for {idle_s:.1f}s "
             f"(active spans: {active or 'none'}); "
             "dumping all thread stacks:"]
    for ident, frame in sys._current_frames().items():
        lines.append(f"--- thread {names.get(ident, '?')} (ident {ident}) ---")
        lines.append("".join(traceback.format_stack(frame)).rstrip())
    text = "\n".join(lines)
    _logger.warning(text)
    # Also straight to stderr: a stall dump is last-resort evidence for a
    # supervisor about to kill this process (bench.py captures the tail),
    # and the library logger may have no handler attached.
    print(text, file=sys.stderr, flush=True)


class _Watchdog(threading.Thread):
    """Heartbeats the active span stacks into the event stream and detects
    stalls: a run whose recorder has seen no span transition for the timeout
    is presumed wedged (hung compile, dead DCN peer) and gets its thread
    stacks dumped — once per stall, not once per tick."""

    def __init__(self, plane: "LivePlane", timeout_s: float) -> None:
        super().__init__(name="delphi-watchdog", daemon=True)
        self._plane = plane
        self._timeout_s = timeout_s
        self._tick_s = min(1.0, max(0.05, timeout_s / 4)) \
            if timeout_s > 0 else 1.0
        self._dumped_at_transition = -1

    def run(self) -> None:
        rec = self._plane.recorder
        while not self._plane.stopped.wait(self._tick_s):
            idle_s = time.perf_counter() - rec.last_transition
            rec.emit_event({"event": "heartbeat",
                            "t_s": round(rec.elapsed_s(), 3),
                            "idle_s": round(idle_s, 3),
                            "active": rec.active_spans()})
            if self._timeout_s > 0 and idle_s >= self._timeout_s \
                    and rec.transition_count != self._dumped_at_transition:
                self._dumped_at_transition = rec.transition_count
                rec.registry.inc("watchdog.stalls")
                # active trace ids ride along so a wedged request is
                # joinable to its exported /trace/<id> document
                from delphi_tpu.observability import trace as _trace
                rec.emit_event({"event": "stall",
                                "t_s": round(rec.elapsed_s(), 3),
                                "idle_s": round(idle_s, 3),
                                "active": rec.active_spans(),
                                "traces": _trace.active_traces()})
                _dump_thread_stacks(rec, idle_s)
                # checkpoint-and-abort (parallel/resilience.py): with a
                # checkpoint dir configured (or DELPHI_STALL_ABORT), a
                # stalled run aborts at the next guarded seam entry / phase
                # boundary — the last completed phase is already persisted —
                # instead of hanging forever after the stack dump
                try:
                    from delphi_tpu.parallel.resilience import \
                        on_watchdog_stall
                    on_watchdog_stall(rec, idle_s)
                except Exception as e:
                    _logger.warning(f"stall abort hook failed: {e}")


# -- resource sampler --------------------------------------------------------


def _rss_gb() -> Optional[float]:
    try:
        with open("/proc/self/status") as f:
            for ln in f:
                if ln.startswith("VmRSS:"):
                    return round(int(ln.split()[1]) / 1024 / 1024, 4)
    except Exception:
        pass
    return None


# Extra per-sample probes other planes register (the serve plane re-samples
# its admission gauges — serve.queue_depth / serve.in_flight /
# serve.shed_ratio — so a /metrics scrape between requests stays current).
# Each hook runs inside the sampler's try/except: a broken probe degrades
# to a debug log, never stops resource sampling.
_sample_hooks_lock = threading.Lock()
_sample_hooks: List[Callable[[], None]] = []


def register_sample_hook(fn: Callable[[], None]) -> None:
    with _sample_hooks_lock:
        if fn not in _sample_hooks:
            _sample_hooks.append(fn)


def unregister_sample_hook(fn: Callable[[], None]) -> None:
    with _sample_hooks_lock:
        if fn in _sample_hooks:
            _sample_hooks.remove(fn)


class _ResourceSampler(threading.Thread):
    """Periodic process/device resource gauges: RSS, per-device HBM
    bytes-in-use. Paired with the compile-time listener this answers 'what
    was the run doing to the machine' without attaching a profiler."""

    def __init__(self, plane: "LivePlane", interval_s: float) -> None:
        super().__init__(name="delphi-resource-sampler", daemon=True)
        self._plane = plane
        self._interval_s = interval_s

    def run(self) -> None:
        while not self._plane.stopped.wait(self._interval_s):
            try:
                self._sample()
            except Exception as e:
                _logger.debug(f"resource sample failed: {e}")

    def _sample(self) -> None:
        with _sample_hooks_lock:
            hooks = list(_sample_hooks)
        for hook in hooks:
            try:
                hook()
            except Exception as e:
                _logger.debug(f"sample hook failed: {e}")
        reg = self._plane.recorder.registry
        rss = _rss_gb()
        if rss is not None:
            reg.set_gauge("process.rss_gb", rss)
            reg.max_gauge("process.peak_rss_gb", rss)
        if "jax" not in sys.modules:
            return
        import jax

        total_in_use = total_peak = 0
        seen = False
        for d in jax.local_devices():
            stats = d.memory_stats()
            if not stats:
                continue
            seen = True
            in_use = stats.get("bytes_in_use", 0)
            total_in_use += in_use
            total_peak += stats.get("peak_bytes_in_use", 0)
            reg.set_gauge(f"device.{d.id}.bytes_in_use", in_use)
        if seen:
            reg.set_gauge("device.bytes_in_use", total_in_use)
            reg.max_gauge("device.peak_bytes_in_use", total_peak)


# jit compile-time histogram: one process-wide jax.monitoring listener that
# forwards compilation durations to whatever recorder is active. Installed
# once, on the first live-plane start (listeners can't be unregistered
# portably, so the forwarding indirection keeps repeated runs from stacking).
_compile_listener_lock = threading.Lock()
_compile_listener_installed = False


def _install_compile_listener() -> None:
    global _compile_listener_installed
    with _compile_listener_lock:
        if _compile_listener_installed:
            return
        _compile_listener_installed = True
    try:
        from jax import monitoring

        def _on_duration(event: str, duration: float, **kw: Any) -> None:
            # only actual compile-path durations (trace, jaxpr->MLIR,
            # backend compile); the /jax/compilation_cache/* bookkeeping
            # durations (time SAVED, retrieval) land in the compile-plane's
            # own compile_cache.* histograms and would inflate this one
            if not event.startswith("/jax/core/compile"):
                return
            from delphi_tpu.observability import spans

            rec = spans._current
            if rec is not None:
                rec.registry.observe("jit.compile_seconds", duration)

        monitoring.register_event_duration_secs_listener(_on_duration)
    except Exception as e:
        _logger.debug(f"jit compile-time listener unavailable: {e}")


# -- the plane ---------------------------------------------------------------


class LivePlane:
    """Owns the live-telemetry threads for one recorder: HTTP server (when a
    port is configured), watchdog, and resource sampler. ``stop()`` is
    idempotent and joins everything so no thread outlives the run."""

    def __init__(self, recorder: Any, port: Optional[int],
                 stall_timeout: float = DEFAULT_STALL_TIMEOUT_S,
                 sample_interval: float = DEFAULT_SAMPLE_INTERVAL_S) -> None:
        self.recorder = recorder
        self.stopped = threading.Event()
        self._requested_port = port
        self._stall_timeout = stall_timeout
        self._sample_interval = sample_interval
        self._server: Optional[ThreadingHTTPServer] = None
        self._threads: List[threading.Thread] = []
        self.port: Optional[int] = None

    def start(self) -> None:
        if self._requested_port is not None:
            host = os.environ.get("DELPHI_METRICS_HOST", "127.0.0.1")
            self._server = ThreadingHTTPServer(
                (host, self._requested_port), _Handler)
            self._server.daemon_threads = True
            self._server.plane = self  # type: ignore[attr-defined]
            self.port = self._server.server_address[1]
            server_thread = threading.Thread(
                target=self._server.serve_forever,
                kwargs={"poll_interval": 0.1},
                name="delphi-metrics-server", daemon=True)
            server_thread.start()
            self._threads.append(server_thread)
            _logger.info(
                f"live telemetry serving on http://{host}:{self.port} "
                "(/metrics, /healthz, /report)")
        watchdog = _Watchdog(self, self._stall_timeout)
        watchdog.start()
        self._threads.append(watchdog)
        if self._sample_interval > 0:
            sampler = _ResourceSampler(self, self._sample_interval)
            sampler.start()
            self._threads.append(sampler)
        _install_compile_listener()

    def stop(self) -> None:
        self.stopped.set()
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        for thread in self._threads:
            thread.join(timeout=5)
        self._threads = []
