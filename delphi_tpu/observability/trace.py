"""Distributed request traces and the per-launch device-cost ledger.

Two halves, one plane:

* **Request traces** — every run/request gets a ``trace_id``; spans from
  :mod:`delphi_tpu.observability.spans` become Chrome/Perfetto trace
  events carrying ``(trace_id, span_id, parent_span_id)``; the serving
  and fleet planes propagate an ``X-Delphi-Trace`` header across router
  dispatch, shed-hops, idempotent re-dispatches, and stream chains, and
  the stream retrain thread joins its parent trace via
  :func:`capture`/:func:`adopt` — so one fleet-routed streaming request
  with a mid-flight worker kill yields ONE coherent trace.  Each process
  a trace touches writes its own part file
  ``trace.<trace_id>.<pid>.json`` under ``DELPHI_TRACE_DIR`` through the
  durable-store seam (site ``store.trace``); :func:`load_trace` merges
  the parts into one Chrome trace-event document, served live at
  ``GET /trace/<trace_id>``.  Sampling is deterministic on the trace id
  (``DELPHI_TRACE_SAMPLE``: keep fraction, default 1.0) so every process
  independently keeps or drops the SAME traces.  Disabled (no
  ``DELPHI_TRACE_DIR``) every per-span hook is one thread-local pointer
  check, like every other observability plane.

* **Launch-cost ledger** — each executed launch from
  :mod:`delphi_tpu.parallel.planner` records (phase, bucket shape,
  padded/useful units, plan signature) → measured wall seconds, joined
  after a profiled run with xplane-attributed device seconds: the
  ``launch:<phase>/<bucket>`` TraceAnnotation opened around each launch
  is intersected with device-side execution intervals from the captured
  ``*.xplane.pb``.  Aggregates persist beside the PlanStore as
  ``plans/ledger.<fp>.json`` (envelope-framed, site ``store.plan``) and
  feed ``main.py --plan-report`` — buckets ranked by pad-adjusted device
  milliseconds — and, behind ``DELPHI_PLAN_COST=1`` (off by default,
  bit-identical planning when off), the planner's bucket-merge choice:
  the first place observability closes the loop into the planner.
"""

import glob
import json
import os
import threading
import time
import uuid
import zlib
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Tuple

#: HTTP header carrying ``<trace_id>`` or ``<trace_id>:<parent_span_id>``
#: across the router → worker (and client → server) dispatch seam.
TRACE_HEADER = "X-Delphi-Trace"

_tls = threading.local()
_active_lock = threading.Lock()
#: thread ident -> (thread name, TraceContext) — what the stall watchdog
#: reports so a wedged request is joinable to its exported trace.
_active: Dict[int, Tuple[str, "TraceContext"]] = {}
_flush_lock = threading.Lock()

_ledger_lock = threading.Lock()
#: fingerprint -> phase -> bucket key -> aggregate entry (in-memory, not
#: yet flushed to the plan store).
_ledger: Dict[str, Dict[str, Dict[str, Dict[str, Any]]]] = {}
#: ledger-file path -> parsed doc, the DELPHI_PLAN_COST consult cache
#: (invalidated whenever a flush rewrites the file).
_disk_cache: Dict[str, Optional[Dict[str, Any]]] = {}

_TRUTHY = frozenset({"1", "true", "yes", "on"})

#: A merge candidate is vetoed when the ledger prices the merged bucket's
#: useful unit at more than this multiple of the unmerged bucket's.
MERGE_COST_FACTOR = 1.25


def _counter(name: str, value: int = 1) -> None:
    from delphi_tpu.observability.registry import counter_inc
    counter_inc(name, value)


# -- trace context ----------------------------------------------------------

def trace_root() -> Optional[str]:
    """The trace export directory, or None when tracing is disabled."""
    root = os.environ.get("DELPHI_TRACE_DIR", "").strip()
    return root or None


def sample_rate() -> float:
    raw = os.environ.get("DELPHI_TRACE_SAMPLE", "").strip()
    try:
        rate = float(raw) if raw else 1.0
    except ValueError:
        rate = 1.0
    return min(1.0, max(0.0, rate))


def new_trace_id() -> str:
    return uuid.uuid4().hex


def _sampled(trace_id: str) -> bool:
    """Deterministic on the id, so the router, every worker it dispatches
    to, and the retrain thread all agree on keep-or-drop."""
    rate = sample_rate()
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    return (zlib.crc32(trace_id.encode("utf-8")) % 10000) < rate * 10000


class TraceContext:
    """One thread's view of one trace: the span-id stack, the buffered
    trace events, and the remote parent span (from the header) that roots
    this process's spans under the caller's."""

    __slots__ = ("trace_id", "root", "remote_parent", "stack", "events")

    def __init__(self, trace_id: str, root: str,
                 remote_parent: Optional[str]) -> None:
        self.trace_id = trace_id
        self.root = root
        self.remote_parent = remote_parent
        self.stack: List[str] = []
        self.events: List[Dict[str, Any]] = []


def _current() -> Optional[TraceContext]:
    return getattr(_tls, "ctx", None)


def current_trace_id() -> Optional[str]:
    ctx = _current()
    return ctx.trace_id if ctx is not None else None


def current_span_id() -> Optional[str]:
    ctx = _current()
    if ctx is None:
        return None
    return ctx.stack[-1] if ctx.stack else ctx.remote_parent


def active_traces() -> Dict[str, str]:
    """thread name -> trace_id for every thread currently inside a trace
    scope (the watchdog's join key between a stall dump and its trace)."""
    with _active_lock:
        return {name: ctx.trace_id for name, ctx in _active.values()}


def active_trace_ids() -> List[str]:
    with _active_lock:
        return sorted({ctx.trace_id for _n, ctx in _active.values()})


def _activate(ctx: TraceContext) -> Optional[TraceContext]:
    prev = _current()
    _tls.ctx = ctx
    with _active_lock:
        _active[threading.get_ident()] = (
            threading.current_thread().name, ctx)
    return prev


def _deactivate(ctx: TraceContext, prev: Optional[TraceContext]) -> None:
    _tls.ctx = prev
    ident = threading.get_ident()
    with _active_lock:
        if prev is not None:
            _active[ident] = (threading.current_thread().name, prev)
        else:
            _active.pop(ident, None)
    _flush_ctx(ctx)


@contextmanager
def request_scope(trace_id: Optional[str] = None,
                  parent_span_id: Optional[str] = None):
    """Activates a trace on this thread for one request/run.  With no
    ``trace_id`` a fresh one is minted (``trace.traces``); an id arriving
    via the header continues the caller's trace (``trace.joins``).  A
    no-op yielding None when tracing is disabled or the id samples out.
    On exit the thread's buffered events flush to this process's part
    file."""
    root = trace_root()
    if root is None:
        yield None
        return
    fresh = trace_id is None
    tid = trace_id or new_trace_id()
    if not _sampled(tid):
        yield None
        return
    ctx = TraceContext(tid, root, parent_span_id)
    _counter("trace.traces" if fresh else "trace.joins")
    prev = _activate(ctx)
    try:
        yield ctx
    finally:
        _deactivate(ctx, prev)


def capture() -> Optional[Dict[str, Any]]:
    """Snapshot of the current trace position, handed to another thread
    (the stream retrain worker) so :func:`adopt` can join it in."""
    ctx = _current()
    if ctx is None:
        return None
    return {"trace_id": ctx.trace_id, "parent_span_id": current_span_id()}


@contextmanager
def adopt(snapshot: Optional[Dict[str, Any]]):
    """Joins a thread into the trace captured by :func:`capture` — the
    retrain thread's spans nest under the request span that spawned it.
    ``adopt(None)`` is a no-op scope."""
    if not snapshot or not snapshot.get("trace_id"):
        yield None
        return
    with request_scope(str(snapshot["trace_id"]),
                       snapshot.get("parent_span_id")) as ctx:
        yield ctx


def begin_run_scope() -> Optional[Tuple[TraceContext,
                                        Optional[TraceContext]]]:
    """Non-contextmanager trace activation for ``start_recording`` /
    ``stop_recording`` (the run-level scope whose enter and exit happen
    in different stack frames).  Returns an opaque token for
    :func:`end_run_scope`, or None when tracing is off."""
    root = trace_root()
    if root is None:
        return None
    tid = new_trace_id()
    if not _sampled(tid):
        return None
    ctx = TraceContext(tid, root, None)
    _counter("trace.traces")
    prev = _activate(ctx)
    return ctx, prev


def end_run_scope(token) -> None:
    if token is None:
        return
    ctx, prev = token
    _deactivate(ctx, prev)


# -- header propagation -----------------------------------------------------

def header_value() -> Optional[str]:
    """``<trace_id>:<parent_span_id>`` to stamp on an outbound dispatch,
    or None when no trace is active on this thread."""
    ctx = _current()
    if ctx is None:
        return None
    parent = current_span_id()
    return f"{ctx.trace_id}:{parent}" if parent else ctx.trace_id


def parse_header(value: Optional[str]) -> Tuple[Optional[str],
                                                Optional[str]]:
    """(trace_id, parent_span_id) from an ``X-Delphi-Trace`` header, or
    (None, None) for anything malformed — a bad header must never fail a
    request, only fall back to a fresh trace."""
    if not value or not isinstance(value, str):
        return None, None
    tid, _sep, parent = value.strip().partition(":")
    tid, parent = tid.strip(), parent.strip()
    def _ok(s: str) -> bool:
        return all((c.isascii() and c.isalnum()) or c in "-_" for c in s)

    if not tid or len(tid) > 64 or not _ok(tid):
        return None, None
    if parent and (len(parent) > 64 or not _ok(parent)):
        parent = ""
    return tid, (parent or None)


# -- event emission (spans.py hooks) ----------------------------------------

def span_started(span: Any) -> None:
    """Hook from ``spans.span_enter``: stamps the span with a span id and
    its trace parent, pushes it on this thread's stack.  One pointer
    check when no trace is active."""
    ctx = _current()
    if ctx is None:
        return
    span.span_id = uuid.uuid4().hex[:16]
    span.trace_parent = ctx.stack[-1] if ctx.stack else ctx.remote_parent
    span.trace_t0 = time.time()
    ctx.stack.append(span.span_id)


def span_finished(span: Any, failed: bool = False) -> None:
    """Hook from ``spans.span_exit``: emits one Chrome complete ("X")
    event.  Pops through exception-orphaned children, mirroring
    ``span_exit``'s own stack repair."""
    ctx = _current()
    if ctx is None or getattr(span, "span_id", None) is None:
        return
    while ctx.stack and ctx.stack[-1] != span.span_id:
        ctx.stack.pop()
    if ctx.stack:
        ctx.stack.pop()
    args = {"trace_id": ctx.trace_id, "span_id": span.span_id,
            "parent_span_id": span.trace_parent}
    if failed:
        args["failed"] = True
    ctx.events.append({
        "name": span.name, "ph": "X", "cat": "span",
        "ts": round(span.trace_t0 * 1e6, 3),
        "dur": round(max(0.0, float(span.wall_s or 0.0)) * 1e6, 3),
        "pid": os.getpid(), "tid": threading.get_ident(), "args": args})
    _counter("trace.spans")


def instant(name: str, **args: Any) -> None:
    """An instant event on the active trace (router dispatch decisions,
    shed-hops, re-dispatches).  No-op outside a trace scope."""
    ctx = _current()
    if ctx is None:
        return
    payload = {"trace_id": ctx.trace_id,
               "parent_span_id": current_span_id()}
    payload.update(args)
    ctx.events.append({
        "name": name, "ph": "i", "s": "p", "cat": "trace",
        "ts": round(time.time() * 1e6, 3),
        "pid": os.getpid(), "tid": threading.get_ident(),
        "args": payload})


def background_instant(name: str, **args: Any) -> Optional[str]:
    """An instant event from OUTSIDE any request — autoscale decisions,
    scale-up/scale-down lifecycle marks.  :func:`instant` deliberately
    no-ops without an active scope, so this opens a one-event trace of
    its own and flushes it immediately.  Returns the trace id, or None
    when tracing is disabled / the id samples out."""
    with request_scope() as ctx:
        if ctx is None:
            return None
        instant(name, **args)
        return ctx.trace_id


# -- part-file export / merge ----------------------------------------------

def _part_path(root: str, trace_id: str) -> str:
    return os.path.join(root, f"trace.{trace_id}.{os.getpid()}.json")


def _flush_ctx(ctx: TraceContext) -> None:
    """Appends this scope's buffered events to the process part file
    (read-merge-rewrite under a process lock, so the router thread, the
    request worker, and the retrain thread of one trace accumulate into
    one file).  Through the store seam: a torn export quarantines instead
    of producing an unparseable trace."""
    if not ctx.events:
        return
    events, ctx.events = ctx.events, []
    from delphi_tpu.parallel import store as dstore
    path = _part_path(ctx.root, ctx.trace_id)
    try:
        with _flush_lock:
            os.makedirs(ctx.root, exist_ok=True)
            doc, status = dstore.read_json(
                path, schema="trace", site="store.trace", root=ctx.root)
            if status == "ok" and isinstance(doc, dict):
                events = list(doc.get("traceEvents") or []) + events
            dstore.write_json(
                path, {"trace_id": ctx.trace_id, "pid": os.getpid(),
                       "traceEvents": events},
                schema="trace", site="store.trace", root=ctx.root)
        _counter("trace.exports")
    except Exception:  # tracing must never fail the traced request
        pass


def list_traces(root: Optional[str] = None) -> List[str]:
    root = root or trace_root()
    if not root:
        return []
    ids = set()
    for path in glob.glob(os.path.join(root, "trace.*.json")):
        parts = os.path.basename(path).split(".")
        if len(parts) >= 4:
            ids.add(parts[1])
    return sorted(ids)


def load_trace(trace_id: str,
               root: Optional[str] = None) -> Optional[Dict[str, Any]]:
    """Merges every process's part file for one trace into a single
    Chrome trace-event document (events sorted by timestamp), or None
    when no part exists."""
    root = root or trace_root()
    if not root or not trace_id or "/" in trace_id:
        return None
    from delphi_tpu.parallel import store as dstore
    events: List[Dict[str, Any]] = []
    pids = set()
    pattern = os.path.join(root, f"trace.{trace_id}.*.json")
    for path in sorted(glob.glob(pattern)):
        doc, status = dstore.read_json(
            path, schema="trace", site="store.trace", root=root)
        if status != "ok" or not isinstance(doc, dict):
            continue
        events.extend(e for e in (doc.get("traceEvents") or [])
                      if isinstance(e, dict))
        pids.add(doc.get("pid"))
    if not events:
        return None
    events.sort(key=lambda e: (e.get("ts") or 0))
    return {"trace_id": trace_id, "displayTimeUnit": "ms",
            "processes": sorted(p for p in pids if p is not None),
            "traceEvents": events}


# -- launch-cost ledger -----------------------------------------------------

def _shape_tag(shape: Any) -> str:
    """Planner shapes mix ints and symbolic tags (mode names, 'host'...);
    every element stringifies into the bucket key, with the characters the
    key format reserves (and path separators) squashed."""
    dims = []
    for d in (shape or ()):
        s = str(int(d)) if isinstance(d, (int, float)) else str(d)
        dims.append("".join(c if (c.isalnum() or c in "-_") else "_"
                            for c in s))
    return "x".join(dims) or "flat"


def bucket_key(launch: Any) -> str:
    """Stable bucket identity shared by the ledger, the per-launch
    TraceAnnotation, and --plan-report: ``<shape>:p<padded>b<batch_pad>``."""
    return f"{_shape_tag(launch.shape)}:p{launch.padded_size}" \
           f"b{launch.batch_pad}"


def launch_annotation(phase: str, launch: Any) -> str:
    return f"launch:{phase}/{bucket_key(launch)}"


def _recorder_active() -> bool:
    from delphi_tpu.observability import spans as _spans
    return _spans._current is not None


@contextmanager
def launch_scope(plan: Any, launch: Any):
    """Wraps the execution of ONE planned launch: measures wall time into
    the in-memory ledger, opens a ``launch:<phase>/<bucket>``
    TraceAnnotation so a profiled run's xplane intervals attribute device
    time back to this bucket, and emits a trace event nested under the
    enclosing phase span.  A launch that raises (e.g. the OOM
    degradation ladder shrinking the batch) records nothing — only
    executed work prices a bucket."""
    if plan is None or launch is None or not _recorder_active():
        yield
        return
    ann = None
    name = launch_annotation(plan.phase, launch)
    try:
        import jax
        ann = jax.profiler.TraceAnnotation(name)
        ann.__enter__()
    except Exception:
        ann = None
    t0 = time.perf_counter()
    try:
        yield
    except BaseException:
        if ann is not None:
            ann.__exit__(None, None, None)
        raise
    else:
        if ann is not None:
            ann.__exit__(None, None, None)
        wall_s = time.perf_counter() - t0
        _record_launch(plan, launch, wall_s)
        ctx = _current()
        if ctx is not None:
            ctx.events.append({
                "name": name, "ph": "X", "cat": "launch",
                "ts": round((time.time() - wall_s) * 1e6, 3),
                "dur": round(wall_s * 1e6, 3),
                "pid": os.getpid(), "tid": threading.get_ident(),
                "args": {"trace_id": ctx.trace_id,
                         "parent_span_id": current_span_id(),
                         "phase": plan.phase,
                         "bucket": bucket_key(launch),
                         "useful_units": launch.useful_units,
                         "padded_units": launch.padded_units,
                         "signature": plan.signature}})


def _record_launch(plan: Any, launch: Any, wall_s: float) -> None:
    from delphi_tpu.parallel import planner
    fp = planner.current_fingerprint() or "local"
    key = bucket_key(launch)
    with _ledger_lock:
        entry = _ledger.setdefault(fp, {}).setdefault(
            plan.phase, {}).setdefault(key, {
                "count": 0, "wall_s": 0.0, "device_s": 0.0,
                "useful_units": 0, "padded_units": 0,
                "signature": plan.signature})
        entry["count"] += 1
        entry["wall_s"] += float(wall_s)
        entry["useful_units"] += int(launch.useful_units)
        entry["padded_units"] += int(launch.padded_units)
        entry["signature"] = plan.signature
    _counter("launch.ledger.records")


def ledger_summary() -> Optional[Dict[str, Any]]:
    """The run report's ``launch_costs`` section: the not-yet-flushed
    in-memory aggregates plus totals.  None when nothing was recorded."""
    with _ledger_lock:
        if not _ledger:
            return None
        fingerprints = json.loads(json.dumps(_ledger))  # deep copy
    total_wall = total_device = 0.0
    n_buckets = 0
    for phases in fingerprints.values():
        for buckets in phases.values():
            for entry in buckets.values():
                total_wall += entry["wall_s"]
                total_device += entry["device_s"]
                n_buckets += 1
    return {"fingerprints": fingerprints, "buckets": n_buckets,
            "wall_s": round(total_wall, 6),
            "device_s": round(total_device, 6)}


def attach_device_costs(trace_dir: str) -> Dict[str, float]:
    """Joins a profiled run's xplane against the per-launch
    TraceAnnotations: for every ``launch:...`` annotation window, the
    overlapped device-execution nanoseconds become that bucket's
    ``device_s`` in the in-memory ledger (flushed afterwards by
    ``stop_recording``).  Returns {annotation name: device_s}."""
    try:
        from delphi_tpu.observability import report as _report
        from delphi_tpu.utils import profiling
        spaces = profiling._load_xspaces(trace_dir)
        if not spaces:
            return {}
        names = set()
        for xs in spaces:
            for plane in xs.planes:
                meta = plane.event_metadata
                values = meta.values() if hasattr(meta, "values") \
                    else [v for _k, v in meta.items()]
                for m in values:
                    n = getattr(m, "name", "")
                    if n.startswith("launch:"):
                        names.add(n)
        if not names:
            return {}
        windows = _report._annotation_windows(spaces, names)
        exec_iv = _report._device_exec_intervals(spaces)
        out: Dict[str, float] = {}
        for name, iv in windows.items():
            device_s = _report._overlap_ns(iv, exec_iv) / 1e9
            out[name] = device_s
            body = name[len("launch:"):]
            phase, _sep, bucket = body.partition("/")
            with _ledger_lock:
                for phases in _ledger.values():
                    entry = phases.get(phase, {}).get(bucket)
                    if entry is not None:
                        entry["device_s"] += device_s
        return out
    except Exception:  # attribution is best-effort evidence
        return {}


def _ledger_root(root: Optional[str] = None) -> Optional[str]:
    if root:
        return root
    from delphi_tpu.parallel import planner
    store = planner.get_plan_store()
    return store.root if store is not None else None


def flush_ledger(root: Optional[str] = None) -> int:
    """Persists and clears the in-memory ledger: per fingerprint, a
    ``ledger.<fp>.json`` beside the launch plans, merged with any prior
    generations (counts/seconds/units summed).  No plan store armed →
    aggregates stay in memory for a later flush.  Returns the number of
    ledger files written."""
    root = _ledger_root(root)
    if root is None:
        return 0
    with _ledger_lock:
        if not _ledger:
            return 0
        snapshot = dict(_ledger)
        _ledger.clear()
    from delphi_tpu.parallel import store as dstore
    written = 0
    for fp, phases in sorted(snapshot.items()):
        path = os.path.join(root, f"ledger.{fp}.json")
        try:
            os.makedirs(root, exist_ok=True)
            doc, status = dstore.read_json(
                path, schema="launch_ledger", site="store.plan", root=root)
            if status != "ok" or not isinstance(doc, dict):
                doc = {"fingerprint": fp, "phases": {}}
            for phase, buckets in phases.items():
                slot = doc.setdefault("phases", {}).setdefault(phase, {})
                for key, entry in buckets.items():
                    prior = slot.get(key)
                    if prior is None:
                        slot[key] = dict(entry)
                    else:
                        for field in ("count", "wall_s", "device_s",
                                      "useful_units", "padded_units"):
                            prior[field] = prior.get(field, 0) \
                                + entry[field]
                        prior["signature"] = entry["signature"]
            dstore.write_json(path, doc, schema="launch_ledger",
                              site="store.plan", root=root)
            _disk_cache.pop(path, None)
            written += 1
        except Exception:  # the ledger must never fail the run it prices
            continue
    if written:
        _counter("launch.ledger.flushes", written)
    return written


def load_ledger(fp: str,
                root: Optional[str] = None) -> Optional[Dict[str, Any]]:
    """One fingerprint's persisted ledger doc (consult-cached), or
    None."""
    root = _ledger_root(root)
    if root is None:
        return None
    path = os.path.join(root, f"ledger.{fp}.json")
    if path in _disk_cache:
        return _disk_cache[path]
    from delphi_tpu.parallel import store as dstore
    doc, status = dstore.read_json(path, schema="launch_ledger",
                                   site="store.plan", root=root)
    doc = doc if status == "ok" and isinstance(doc, dict) else None
    if doc is not None:
        _counter("launch.ledger.loads")
    _disk_cache[path] = doc
    return doc


def reset_state() -> None:
    """Test hook: drops in-memory aggregates and the consult cache."""
    with _ledger_lock:
        _ledger.clear()
    _disk_cache.clear()


# -- the DELPHI_PLAN_COST planner gate --------------------------------------

def plan_cost_enabled() -> bool:
    return os.environ.get(
        "DELPHI_PLAN_COST", "").strip().lower() in _TRUTHY


def _unit_cost(entry: Optional[Dict[str, Any]]) -> Optional[float]:
    """Measured seconds per USEFUL unit — padding is priced implicitly,
    since a padded launch burns device time its useful units must carry.
    Prefers device seconds (the honest number) over wall."""
    if not entry:
        return None
    useful = entry.get("useful_units") or 0
    if useful <= 0:
        return None
    cost = entry.get("device_s") or 0.0
    if cost <= 0.0:
        cost = entry.get("wall_s") or 0.0
    return (cost / useful) if cost > 0.0 else None


def merge_allowed(fingerprint: Optional[str], phase: str, shape: Any,
                  from_size: int, to_size: int,
                  root: Optional[str] = None) -> bool:
    """DELPHI_PLAN_COST consult: may the planner merge the ``from_size``
    bucket up into ``to_size``?  Vetoes only when the ledger has priced
    BOTH buckets and the merged one costs > MERGE_COST_FACTOR× more per
    useful unit — no data, no opinion (the merge proceeds as in the
    count-only heuristic)."""
    _counter("launch.ledger.consults")
    doc = load_ledger(fingerprint or "local", root=root)
    if doc is None:
        return True
    buckets = (doc.get("phases") or {}).get(phase)
    if not buckets:
        # per-chunk phases record as "<phase>[i]" — aggregate any match
        merged: Dict[str, Dict[str, Any]] = {}
        for name, bk in (doc.get("phases") or {}).items():
            base = name.split("[", 1)[0]
            if base != phase:
                continue
            for key, entry in bk.items():
                slot = merged.setdefault(key, {
                    "count": 0, "wall_s": 0.0, "device_s": 0.0,
                    "useful_units": 0, "padded_units": 0})
                for field in ("count", "wall_s", "device_s",
                              "useful_units", "padded_units"):
                    slot[field] += entry.get(field, 0)
        buckets = merged
    if not buckets:
        return True
    shape_tag = _shape_tag(shape)

    def _entry(size: int) -> Optional[Dict[str, Any]]:
        prefix = f"{shape_tag}:p{size}b"
        found = None
        for key, entry in buckets.items():
            if key.startswith(prefix):
                if found is None:
                    found = dict(entry)
                else:
                    for field in ("count", "wall_s", "device_s",
                                  "useful_units", "padded_units"):
                        found[field] = found.get(field, 0) \
                            + entry.get(field, 0)
        return found

    from_cost = _unit_cost(_entry(from_size))
    to_cost = _unit_cost(_entry(to_size))
    if from_cost is None or to_cost is None:
        return True
    if to_cost > from_cost * MERGE_COST_FACTOR:
        _counter("launch.ledger.merge_vetoes")
        return False
    return True


# -- reporting --------------------------------------------------------------

def run_trace_info() -> Optional[Dict[str, Any]]:
    """The run report's ``trace`` section for the currently active
    scope, or a pointer-only stub when tracing is armed but this thread
    holds no scope."""
    root = trace_root()
    if root is None:
        return None
    info: Dict[str, Any] = {"dir": root, "sample": sample_rate()}
    tid = current_trace_id()
    if tid is not None:
        info["trace_id"] = tid
    return info


def finalize_run(recorder: Any) -> None:
    """``stop_recording`` hook: joins xplane device time into the ledger
    (when the run was profiled), stamps the recorder with the report's
    ``trace``/``launch_costs`` sections, then flushes the ledger to the
    plan store.  Best-effort — observability never fails the run."""
    try:
        trace_dir = getattr(recorder, "trace_dir", None)
        if trace_dir:
            attach_device_costs(trace_dir)
        recorder.trace_info = run_trace_info()
        recorder.launch_costs = ledger_summary()
        flush_ledger()
    except Exception:
        pass


def plan_report(root: str) -> Dict[str, Any]:
    """``main.py --plan-report``: every persisted ledger under ``root``
    (a plans dir, or a serve cache dir containing one), buckets ranked
    by pad-adjusted device milliseconds — total measured cost scaled by
    padded/useful, i.e. what the bucket WOULD cost if every unit it
    launched were real work.  The tuning campaign reads this top-down."""
    candidates = [root, os.path.join(root, "plans")]
    ledger_root = next(
        (c for c in candidates
         if glob.glob(os.path.join(c, "ledger.*.json"))), root)
    from delphi_tpu.parallel import store as dstore
    rows: List[Dict[str, Any]] = []
    n_ledgers = 0
    for path in sorted(glob.glob(
            os.path.join(ledger_root, "ledger.*.json"))):
        doc, status = dstore.read_json(
            path, schema="launch_ledger", site="store.plan",
            root=ledger_root)
        if status != "ok" or not isinstance(doc, dict):
            continue
        n_ledgers += 1
        fp = doc.get("fingerprint") or \
            os.path.basename(path)[len("ledger."):-len(".json")]
        for phase, buckets in sorted((doc.get("phases") or {}).items()):
            for key, entry in sorted(buckets.items()):
                useful = entry.get("useful_units") or 0
                padded = entry.get("padded_units") or 0
                device_s = entry.get("device_s") or 0.0
                wall_s = entry.get("wall_s") or 0.0
                cost_ms = (device_s if device_s > 0.0 else wall_s) * 1e3
                pad_factor = (padded / useful) if useful > 0 else 1.0
                rows.append({
                    "fingerprint": fp, "phase": phase, "bucket": key,
                    "launches": entry.get("count", 0),
                    "useful_units": useful, "padded_units": padded,
                    "pad_waste": round(1.0 - (useful / padded), 4)
                    if padded > 0 else 0.0,
                    "device_ms": round(device_s * 1e3, 3),
                    "wall_ms": round(wall_s * 1e3, 3),
                    "pad_adjusted_device_ms":
                        round(cost_ms * pad_factor, 3),
                })
    rows.sort(key=lambda r: (-r["pad_adjusted_device_ms"],
                             r["fingerprint"], r["phase"], r["bucket"]))
    return {"root": ledger_root, "ledgers": n_ledgers,
            "buckets": rows}
