"""Structured run report: versioned JSON emitted at the end of
``RepairModel.run()`` (and by ``bench.py``) when ``DELPHI_METRICS_PATH`` /
``repair.metrics.path`` is set.

Schema (version 8; version 1-7 reports still load, see
:func:`load_run_report`)::

    {
      "schema_version": 8,
      "kind": "delphi_tpu.run_report",
      "created_at": "<ISO-8601 UTC>",
      "status": "ok" | "error" | "running",  # "running" from /report only
      "error": "<message>",                  # only when status == "error"
      "run":   {...},                        # caller-supplied run facts
      "env":   {backend, devices, versions},
      "metrics": {"counters": {}, "gauges": {}, "histograms": {}},
      "spans": {name, start_s, wall_s, [device_s], children: [...]},
      "device_time": {trace_dir, device_busy_s, per_phase: {}} | null,
      "per_process": null | {                # multi-host runs only
        "<rank>": {"process_index": 0,
                   "metrics": {...},         # that rank's own registry
                   "spans": {...},           # process-tagged span tree
                   "scorecards": {...}}      # that rank's own scorecards
      },
      "scorecards": null | {                 # v3+: provenance enabled only
        "<attribute>": {cells_flagged, cells_repaired, repair_rate,
                        detectors: {}, decisions: {},
                        confidence: {count, sum, min, max, mean, bins: [],
                                     low_confidence_fraction},
                        domain_size: {count, sum, min, max, mean, hist: {}},
                        repaired_values: {},
                        escalation: {routed, routed_reasons: {},
                                     repairs: {}},  # v5+
                        [model_cv_score]}
      },
      "drift": null | {...},                 # v3+: --baseline-report runs
      "incremental": null | {...},           # v4+: incremental (delta) runs
      "escalation": null | {                 # v5+: escalation-tier runs
        "requested": true, "conf_threshold": 0.5,
        "routed": 0, "escalated": 0,
        "budget": {limit, spent, exhausted},
        "tiers": {"pattern": {attempts, repairs},
                  "joint": {attempts, repairs},
                  "adapter": {allowed, calls, attempts, repairs}},
        "routed_cells": [[row_id, attribute], ...],       # capped
        "escalated_cells": [[row_id, attribute, tier, value], ...]
      },
      "trace": null | {dir, sample, [trace_id]},   # v8+: trace plane armed
      "launch_costs": null | {                     # v8+: launch ledger
        "fingerprints": {"<fp>": {"<phase>": {"<bucket>": {
            count, wall_s, device_s, useful_units, padded_units,
            signature}}}},
        "buckets": 0, "wall_s": 0.0, "device_s": 0.0
      },
      "slo": null | {                              # v9+: sustained-load SLOs
        "requests": {sent, answered, ok, failed, shed, gave_up, retries},
        "consistent": true,            # sent == answered + shed + gave_up
        "qps": 0.0, "shed_rate": 0.0,
        "latency": {count, mean, p50, p90, p99},
        "warm_hit_ratio": null | 0.0,
        "per_worker": {"<wid>": {requests, share}},
        "per_segment": {"<segment>": {...same shape...}},
        "segments": [{name, duration_s, rate_rps}, ...],
        "recovery": {fail_over, steady_p99_s, ..., violations},
        "autoscale": {"events": [{action, reason, worker, at_s}, ...]},
        "kill": null | {worker, at_segment}
      }
    }

On a multi-host cluster every rank's registry state and span tree travel
through ``parallel.distributed.allgather_pickled`` at ``stop_recording``;
the report's top-level ``metrics`` then hold the cluster-wide merge
(counters summed, gauges maxed, histogram reservoirs combined) while
``per_process`` preserves each rank's own view.

Device-time attribution joins the xplane parser in
``delphi_tpu/utils/profiling.py`` against the ``TraceAnnotation`` ranges that
``phase_span`` opens: annotation events (host-side, named after the span)
define per-phase time windows, and device execution-line events overlapping
those windows are credited to the phase.
"""

import json
import os
from datetime import datetime, timezone
from typing import Any, Dict, Iterable, List, Optional, Tuple

from delphi_tpu.utils import setup_logger

_logger = setup_logger()

REPORT_SCHEMA_VERSION = 9
SUPPORTED_SCHEMA_VERSIONS = (1, 2, 3, 4, 5, 6, 7, 8, 9)
REPORT_KIND = "delphi_tpu.run_report"

Interval = Tuple[int, int]


def _merge_intervals(intervals: List[Interval]) -> List[Interval]:
    merged: List[Interval] = []
    for s, e in sorted(intervals):
        if merged and s <= merged[-1][1]:
            merged[-1] = (merged[-1][0], max(merged[-1][1], e))
        else:
            merged.append((s, e))
    return merged


def _overlap_ns(a: List[Interval], b: List[Interval]) -> int:
    """Total overlap between two sorted, merged interval lists."""
    total = 0
    i = j = 0
    while i < len(a) and j < len(b):
        lo = max(a[i][0], b[j][0])
        hi = min(a[i][1], b[j][1])
        if lo < hi:
            total += hi - lo
        if a[i][1] < b[j][1]:
            i += 1
        else:
            j += 1
    return total


def _event_interval(line: Any, ev: Any) -> Interval:
    start = line.timestamp_ns + ev.offset_ps // 1000
    return (start, start + ev.duration_ps // 1000)


def _annotation_windows(spaces: List[Any],
                        names: Iterable[str]) -> Dict[str, List[Interval]]:
    """Per-span-name merged time windows from `TraceAnnotation` events.

    Annotations are recorded host-side, so every plane and line is scanned
    (unlike device busy time, which only looks at XLA execution lines)."""
    wanted = set(names)
    windows: Dict[str, List[Interval]] = {}
    for xs in spaces:
        for plane in xs.planes:
            meta = {m.id: m.name for m in plane.event_metadata.values()} \
                if hasattr(plane.event_metadata, "values") else \
                {k: v.name for k, v in plane.event_metadata.items()}
            for line in plane.lines:
                for ev in line.events:
                    name = meta.get(ev.metadata_id)
                    if name in wanted:
                        windows.setdefault(name, []).append(
                            _event_interval(line, ev))
    return {name: _merge_intervals(iv) for name, iv in windows.items()}


def _device_exec_intervals(spaces: List[Any]) -> List[Interval]:
    from delphi_tpu.utils.profiling import _device_planes, _exec_lines

    intervals: List[Interval] = []
    for plane in _device_planes(spaces):
        for line in _exec_lines(plane):
            for ev in line.events:
                intervals.append(_event_interval(line, ev))
    return _merge_intervals(intervals)


def attribute_device_time(trace_dir: str, span_names: Iterable[str]) \
        -> Optional[Dict[str, Any]]:
    """Joins a captured profiler trace against span names.

    Returns ``{"device_busy_s": float, "per_phase": {name: seconds}}`` or
    ``None`` when the trace is unreadable/empty (missing proto deps, no
    xplane files, no annotation events)."""
    try:
        from delphi_tpu.utils.profiling import _load_xspaces

        spaces = _load_xspaces(trace_dir)
    except Exception as e:
        _logger.warning(f"cannot parse profiler trace in {trace_dir}: {e}")
        return None
    if not spaces:
        return None
    device = _device_exec_intervals(spaces)
    windows = _annotation_windows(spaces, span_names)
    if not device or not windows:
        return None
    per_phase = {name: round(_overlap_ns(device, iv) / 1e9, 6)
                 for name, iv in sorted(windows.items())}
    busy_ns = sum(e - s for s, e in device)
    return {"device_busy_s": round(busy_ns / 1e9, 6), "per_phase": per_phase}


def _peak_rss_gb() -> Optional[float]:
    try:
        with open("/proc/self/status") as f:
            for ln in f:
                if ln.startswith("VmHWM:"):
                    return round(int(ln.split()[1]) / 1024 / 1024, 3)
    except Exception:
        pass
    return None


def _env_info() -> Dict[str, Any]:
    import platform

    info: Dict[str, Any] = {
        "python_version": platform.python_version(),
        "platform": platform.platform(),
    }
    try:
        import jax

        devices = jax.local_devices()
        info.update({
            "jax_version": jax.__version__,
            "backend": jax.default_backend(),
            "device_count": jax.device_count(),
            "local_device_count": jax.local_device_count(),
            "device_kind": devices[0].device_kind if devices else None,
            "process_index": jax.process_index(),
            "process_count": jax.process_count(),
        })
    except Exception as e:
        info["jax_error"] = f"{type(e).__name__}: {e}"
    return info


def _record_memory_gauges(registry: Any) -> None:
    """Peak RSS + jax device-memory gauges, sampled at report time."""
    rss = _peak_rss_gb()
    if rss is not None:
        registry.set_gauge("system.peak_rss_gb", rss)
    try:
        import jax

        in_use = peak = 0
        seen = False
        for d in jax.local_devices():
            stats = d.memory_stats()
            if not stats:
                continue
            seen = True
            in_use += stats.get("bytes_in_use", 0)
            peak += stats.get("peak_bytes_in_use", 0)
        if seen:
            registry.set_gauge("device.bytes_in_use", in_use)
            registry.set_gauge("device.peak_bytes_in_use", peak)
    except Exception:
        pass


def gather_per_process(recorder: Any) -> None:
    """Multi-host report aggregation (collective — every rank calls this at
    ``stop_recording``): all-gathers each rank's raw registry state and span
    tree and stores the rank-ordered payload list on
    ``recorder.per_process``. Single-process runs (and runs that never
    touched jax) are a no-op.

    BOUNDED: a membership heartbeat runs first, then the gather itself
    goes through the ``report.gather`` guarded-collective site — a dead
    or wedged peer degrades this rank to its own per-rank report, flagged
    ``aggregation_incomplete`` in the report's ``dist`` section, instead
    of hanging at shutdown and losing the report entirely."""
    import sys

    if "jax" not in sys.modules:
        return
    from delphi_tpu.parallel import distributed

    if distributed.process_count() == 1:
        return
    from delphi_tpu.observability.provenance import scorecards_for
    from delphi_tpu.parallel import dist_resilience

    dist_resilience.ensure_membership()
    payload = {
        "process_index": distributed.process_index(),
        "metrics": recorder.registry.export_state(),
        "spans": recorder.root.to_dict(),
        "scorecards": scorecards_for(recorder),
    }
    if dist_resilience.single_host_latched():
        # peers are gone (heartbeat or an earlier collective degraded):
        # this rank's own payload is the whole report
        dist_resilience.mark_aggregation_incomplete()
        recorder.per_process = [payload]
    else:
        recorder.per_process = distributed.allgather_pickled(
            payload, site="report.gather")
        if dist_resilience.single_host_latched():
            # the gather itself timed out and fell back to [payload]
            dist_resilience.mark_aggregation_incomplete()
    recorder.dist = dist_resilience.report_section()


def _tag_process(span_dict: Dict[str, Any], rank: int) -> None:
    span_dict["process"] = rank
    for child in span_dict.get("children", []):
        _tag_process(child, rank)


def _per_process_section(gathered: List[Dict[str, Any]]) \
        -> Tuple[Dict[str, Any], Dict[str, Any], Optional[Dict[str, Any]]]:
    """(per_process section, merged cluster-wide metrics, merged cluster-wide
    scorecards) from the gathered rank payloads. Ranks are keyed by gather
    order — ``allgather_pickled`` returns payloads in process order on every
    rank."""
    from delphi_tpu.observability.provenance import merge_scorecards
    from delphi_tpu.observability.registry import (
        merge_state_snapshots, state_snapshot)

    import copy

    section: Dict[str, Any] = {}
    states = []
    cards = []
    for rank, payload in enumerate(gathered):
        # deep-copied before tagging: the tag mutates in place, and gathered
        # payloads may alias (this rank's own payload, or test fakes that
        # return the same object per rank)
        spans = copy.deepcopy(payload["spans"])
        _tag_process(spans, rank)
        section[str(rank)] = {
            "process_index": rank,
            "metrics": state_snapshot(payload["metrics"]),
            "spans": spans,
            "scorecards": payload.get("scorecards"),
        }
        states.append(payload["metrics"])
        cards.append(payload.get("scorecards"))
    merged_cards = merge_scorecards(cards) if any(cards) else None
    return section, merge_state_snapshots(states), merged_cards


def build_run_report(recorder: Any,
                     run: Optional[Dict[str, Any]] = None,
                     status: str = "ok",
                     error: Optional[str] = None) -> Dict[str, Any]:
    """Assembles the versioned report dict from a finished recorder."""
    _record_memory_gauges(recorder.registry)

    root = recorder.root
    device_time = None
    if recorder.trace_dir:
        names = {s.name for s in root.walk() if s is not root}
        device_time = attribute_device_time(recorder.trace_dir, names)
        if device_time is not None:
            device_time["trace_dir"] = recorder.trace_dir
            per_phase = device_time["per_phase"]
            # Annotate span nodes in place; a name repeated across the tree
            # (e.g. chunked repair passes) only gets the per-phase total in
            # `device_time`, since windows for same-named spans are merged.
            counts: Dict[str, int] = {}
            for s in root.walk():
                counts[s.name] = counts.get(s.name, 0) + 1
            for s in root.walk():
                if counts.get(s.name) == 1 and s.name in per_phase:
                    s.device_s = per_phase[s.name]

    from delphi_tpu.observability.provenance import scorecards_for

    per_process = None
    gathered = getattr(recorder, "per_process", None)
    if gathered and len(gathered) > 1:
        per_process, metrics, scorecards = _per_process_section(gathered)
    else:
        metrics = recorder.registry.snapshot()
        scorecards = scorecards_for(recorder)

    return {
        "schema_version": REPORT_SCHEMA_VERSION,
        "kind": REPORT_KIND,
        "created_at": datetime.fromtimestamp(
            recorder.started_at, tz=timezone.utc).isoformat(),
        "status": status,
        **({"error": error} if error else {}),
        "run": run or {},
        "env": _env_info(),
        "metrics": metrics,
        "spans": root.to_dict(),
        "device_time": device_time,
        "per_process": per_process,
        "scorecards": scorecards,
        "drift": getattr(recorder, "drift", None),
        "incremental": getattr(recorder, "incremental", None),
        "escalation": getattr(recorder, "escalation", None),
        "dist": getattr(recorder, "dist", None),
        "gauntlet": getattr(recorder, "gauntlet", None),
        "trace": _trace_section(recorder),
        "launch_costs": _launch_costs_section(recorder),
        "slo": getattr(recorder, "slo", None),
    }


def _trace_section(recorder: Any) -> Optional[Dict[str, Any]]:
    """v8 ``trace`` section: the distributed-trace identity of this run
    (stamped by ``trace.finalize_run`` at stop_recording; recomputed here
    for callers that build a report mid-run, e.g. GET /report)."""
    info = getattr(recorder, "trace_info", None)
    if info is not None:
        return info
    from delphi_tpu.observability import trace as _trace
    return _trace.run_trace_info()


def _launch_costs_section(recorder: Any) -> Optional[Dict[str, Any]]:
    """v8 ``launch_costs`` section: per-bucket launch-cost aggregates
    (wall + xplane-attributed device seconds) from the launch ledger."""
    costs = getattr(recorder, "launch_costs", None)
    if costs is not None:
        return costs
    from delphi_tpu.observability import trace as _trace
    return _trace.ledger_summary()


def write_run_report(report: Dict[str, Any], path: str) -> None:
    """Crash-consistent write through the durable-store seam (site
    ``store.report``): envelope-framed (crc32 + length), same-directory
    temp file, fsync, ``os.replace``, directory fsync — a run killed
    mid-write (or a mid-write crash on a non-serializable report) never
    leaves a truncated JSON for ``load_run_report`` to silently discard,
    and any pre-existing report at ``path`` survives intact."""
    from delphi_tpu.parallel import store as dstore
    dstore.write_json(os.path.abspath(path), report, schema="run_report",
                      site="store.report", indent=2, sort_keys=False)
    _logger.info(f"Run report written to {path}")


def upgrade_run_report(report: Dict[str, Any]) -> Dict[str, Any]:
    """In-memory v1..v8 -> v9 upgrade: each version only adds keys
    (v2 added ``per_process``, v3 added ``scorecards`` and ``drift``, v4
    added ``incremental``, v5 added ``escalation``, v6 added ``dist`` —
    the distributed-resilience section, v7 added ``gauntlet`` — the
    scenario-gauntlet quality section, v8 added ``trace`` and
    ``launch_costs`` — the distributed-trace identity and per-launch
    device-cost ledger, v9 added ``slo`` — the sustained-load SLO
    ledger), so an older report becomes a valid v9 one by defaulting
    them. Consumers can rely on the v9 shape regardless of the file's
    age."""
    version = report.get("schema_version")
    if version == REPORT_SCHEMA_VERSION:
        return report
    report = dict(report)
    report.setdefault("per_process", None)   # v1 -> v2
    report.setdefault("scorecards", None)    # v2 -> v3
    report.setdefault("drift", None)         # v2 -> v3
    report.setdefault("incremental", None)   # v3 -> v4
    report.setdefault("escalation", None)    # v4 -> v5
    report.setdefault("dist", None)          # v5 -> v6
    report.setdefault("gauntlet", None)      # v6 -> v7
    report.setdefault("trace", None)         # v7 -> v8
    report.setdefault("launch_costs", None)  # v7 -> v8
    report.setdefault("slo", None)           # v8 -> v9
    report["schema_version"] = REPORT_SCHEMA_VERSION
    report["schema_version_loaded_from"] = version
    return report


def load_run_report(path: str) -> Optional[Dict[str, Any]]:
    """Loads and (when needed) upgrades a run report; ``None`` for missing
    or unreadable files and for schema versions this build doesn't know.
    Validated through the store seam: a truncated/corrupt report is
    quarantined and reads as missing; a pre-seam raw-JSON report (e.g. an
    old ``--baseline-report``) loads through the legacy path."""
    from delphi_tpu.parallel import store as dstore
    report, status = dstore.read_json(path, schema="run_report",
                                      site="store.report")
    if report is None:
        _logger.warning(f"cannot load run report {path} ({status})")
        return None
    if not isinstance(report, dict):
        _logger.warning(f"cannot load run report {path}: not a JSON object")
        return None
    version = report.get("schema_version")
    if version not in SUPPORTED_SCHEMA_VERSIONS:
        _logger.warning(
            f"run report {path} has unsupported schema version {version} "
            f"(supported: {SUPPORTED_SCHEMA_VERSIONS})")
        return None
    return upgrade_run_report(report)


def bench_entry(metric: str, value: Any, unit: str,
                extra: Optional[Dict[str, Any]] = None,
                run_report: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """One BENCH_r*.json result line, produced by the framework so bench
    entries and run reports share a schema version."""
    entry: Dict[str, Any] = {
        "schema_version": REPORT_SCHEMA_VERSION,
        "kind": "delphi_tpu.bench_entry",
        "metric": metric,
        "value": value,
        "unit": unit,
    }
    if extra:
        entry.update(extra)
    if run_report is not None:
        entry["run_report"] = run_report
    return entry
