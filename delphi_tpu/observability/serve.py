"""Serving plane: a persistent repair service over the live HTTP plane.

``RepairServer`` turns the one-shot pipeline into a long-lived process that
multiplexes concurrent repair sessions over shared warm state:

* the **persistent compile cache** (``parallel/compile_plane.py``) — armed
  once at server start, so every request after the first reuses compiled
  executables (``compile_cache.hits``);
* **device-resident column codes** (``ops/xfer.py``) — input tables are
  encoded once per content fingerprint, registered in the session catalog,
  and their uploaded code buffers survive on the column objects across
  requests;
* **trained models cached by table fingerprint** — each request points
  ``model.checkpoint_path`` at a per-fingerprint directory under the serve
  cache dir, so a repeated table skips training (``train.checkpoint_hits``)
  and a restarted server rebuilds its warm state from disk.

Robustness-first control plane:

* **admission/queueing**: a bounded queue (``DELPHI_SERVE_QUEUE_DEPTH``)
  with load-shedding — 429 + ``Retry-After`` when the queue is full, the
  process RSS exceeds ``DELPHI_SERVE_MAX_RSS_GB``, or the span heartbeat
  says the in-flight work is wedged; 503 + ``Retry-After`` while draining;
* **per-request deadlines** (``DELPHI_SERVE_DEADLINE_S`` or the request's
  ``deadline_s`` field) threaded into the resilience seam as a
  :class:`~delphi_tpu.parallel.resilience.RequestScope`: retry backoff is
  clipped to the remaining budget and expiry raises ``DeadlineExceeded`` at
  the next guarded seam / phase boundary → HTTP 504, never a wedged worker;
* **fault isolation**: each request runs under its own ``RequestScope``
  (private fault plan, abort latch, CPU latch, checkpoint dir) and its own
  provenance ledger, so one request's OOM or injected fault walks the
  degradation ladder, fails only that request, and evicts only the state it
  dirtied (its table-cache entry, device buffers, and model checkpoint) —
  other in-flight sessions stay bit-identical;
* **graceful drain**: :meth:`RepairServer.begin_drain` (or SIGTERM via
  :func:`install_signal_handlers`) stops admission; :meth:`~RepairServer.
  drain` waits a grace period, then arms each remaining request's scoped
  abort so it stops at the next phase boundary with its phase checkpoints
  on disk (resumable on resubmit), flushes per-request provenance ledgers,
  and tears the plane down.

The HTTP surface extends the PR 2 live plane: ``GET /metrics`` (Prometheus,
including all ``resilience.*`` and ``serve.*`` series), ``GET /healthz``
(admission state + queue depth), ``GET /report`` (in-flight run report),
``POST /repair`` (a micro-batched repair request), ``POST /drain``.

A ``/repair`` request body::

    {"table": {"tid": ["0", ...], "c0": [...], ...},   # column -> values
     "row_id": "tid",
     "deadline_s": 30.0,                                # optional
     "options": {"model.max_training_row_num": "64"},   # optional
     "fault_plan": "domain.bucket:1:oom",               # optional (chaos)
     "base_snapshot": "nightly",                        # optional (delta)
     "request_id": "r1"}                                # optional

and the 200 response is ``{"request_id", "status": "ok", "rows",
"frame": [...records...]}`` — ``frame`` rows are sorted by all columns so
two servers repairing the same table respond byte-identically.

``base_snapshot`` names a snapshot under the server cache dir
(``<cache_dir>/snapshots/<id>``) and switches the request onto the
incremental repair plane (:mod:`delphi_tpu.incremental`): the request
diffs its table against that snapshot's manifest, repairs only the delta,
and updates the snapshot for the next request carrying the same id. The
first request under a fresh id runs full and populates it. The id rides
per-request MODEL OPTIONS (not env), so concurrent requests against
different snapshots never race. The response echoes ``base_snapshot``
and, when the delta path ran, an ``incremental`` summary.

A ``"stream": {"id", "seq", "parent_snapshot"}`` field instead switches
the request onto the continuous ingestion plane
(:mod:`delphi_tpu.incremental.stream`): chained deltas accumulate into a
per-stream table under the cache root with a durable commit cursor,
idempotent re-apply, per-stream 429 backpressure with the cursor echoed,
drift-gated background retrains, and ``/drain`` reporting every stream's
resume point before admission closes. Fleets route chained requests by
the chain-root fingerprint so a whole chain stays on (and fails over
with) one home worker.
"""

import hashlib
import json
import os
import queue
import signal
import tempfile
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

from delphi_tpu.observability import trace as _trace
from delphi_tpu.observability.registry import (
    counter_inc, gauge_set, histogram_observe,
)
from delphi_tpu.utils import setup_logger

_logger = setup_logger()

_DEF_WORKERS = 2
_DEF_QUEUE_DEPTH = 8
_DEF_DEADLINE_S = 300.0
_DEF_RETRY_AFTER_S = 1.0
_DEF_DRAIN_GRACE_S = 30.0
_DEF_STALL_SHED_S = 120.0
_DEF_FLEET_HEARTBEAT_S = 1.0


def table_fingerprint(table: Dict[str, Any], row_id: str) -> str:
    """Content fingerprint of one /repair request's table. The SINGLE
    definition shared by the server's warm-table cache and the fleet
    router's rendezvous hashing — affinity only works because both sides
    hash the identical blob."""
    blob = json.dumps({"row_id": row_id, "table": table},
                      sort_keys=True, default=str)
    return hashlib.sha1(blob.encode()).hexdigest()


def chain_fingerprint(payload: Dict[str, Any]) -> Optional[str]:
    """Chain-root routing key for chained requests, or None for plain
    ones. A stream's deltas (and a ``base_snapshot`` chain's follow-ups)
    each carry a DIFFERENT table, so hashing the table would scatter the
    chain across the fleet; hashing the chain root (stream id /
    base_snapshot id) pins every link to the rendezvous home whose
    snapshot, cursor, and warm models the chain built."""
    stream = payload.get("stream")
    if isinstance(stream, dict) and stream.get("id"):
        return hashlib.sha1(
            f"stream|{stream['id']}".encode()).hexdigest()
    base = payload.get("base_snapshot")
    if base:
        return hashlib.sha1(f"chain|{base}".encode()).hexdigest()
    return None


def _stream_rows(payload: Dict[str, Any]) -> int:
    """Row count of one delta payload — the unit ``stream.lag_rows``
    (admitted-but-not-yet-durable staleness) is measured in."""
    table = payload.get("table") or {}
    try:
        return max((len(v) for v in table.values()
                    if isinstance(v, (list, tuple))), default=0)
    except TypeError:
        return 0


def write_fleet_registration(fleet_dir: str, path: str,
                             info: Dict[str, Any]) -> None:
    """Writes one worker registration through the durable-store seam
    (site ``store.fleet``): envelope-framed and crash-consistent, so the
    router can never json-parse a half-written announcement. Module-level
    so the store-chaos bench can tear the real writer."""
    from delphi_tpu.parallel import store as dstore
    os.makedirs(fleet_dir, exist_ok=True)
    dstore.write_json(path, info, schema="fleet_reg", site="store.fleet",
                      root=fleet_dir)

#: Counters pre-seeded to zero at server start so the Prometheus endpoint
#: always exposes the full admission/resilience series (a scrape before the
#: first fault must see `delphi_resilience_retries 0`, not a missing metric).
_SEED_COUNTERS = (
    "serve.requests", "serve.accepted", "serve.completed", "serve.failed",
    "serve.shed", "serve.rejected_draining", "serve.deadline_expired",
    "serve.aborted", "serve.handler_timeouts",
    "serve.table_cache.hits", "serve.table_cache.misses",
    "resilience.retries", "resilience.injected",
    "resilience.aborts_requested", "resilience.deadline_expired",
    "resilience.deadline_clipped", "resilience.plan.unmatched",
    "resilience.degrade.shrink", "resilience.degrade.evict",
    "resilience.degrade.cpu_fallback",
    "resilience.checkpoint.hits", "resilience.checkpoint.misses",
    "resilience.checkpoint.stale", "resilience.checkpoint.corrupt",
    "resilience.checkpoint.saves",
    "resilience.dist.rank_loss", "resilience.dist.collective_timeouts",
    "resilience.dist.single_host_latch", "resilience.dist.mesh_shrunk",
    "resilience.dist.heartbeats",
    "resilience.dist.aggregation_incomplete",
    "escalation.routed", "escalation.escalated",
    "escalation.budget_exhausted",
    "escalation.pattern.induced", "escalation.pattern.attempts",
    "escalation.pattern.repairs",
    "escalation.joint.launches", "escalation.joint.cells",
    "escalation.joint.proposals", "escalation.joint.repairs",
    "escalation.adapter.calls", "escalation.adapter.repairs",
    "escalation.adapter.call_budget_exhausted",
    "launch.plans", "launch.launches", "launch.buckets", "launch.pieces",
    "launch.padded_units", "launch.useful_units", "launch.merged_buckets",
    "launch.plan_cache.hits", "launch.replans",
    "launch.ledger.records", "launch.ledger.flushes",
    "launch.ledger.loads", "launch.ledger.consults",
    "launch.ledger.merge_vetoes",
    "trace.traces", "trace.joins", "trace.spans", "trace.exports",
    "store.writes", "store.reads", "store.misses", "store.legacy",
    "store.corrupt", "store.quarantined", "store.torn_writes",
    "store.gc.sweeps", "store.gc.evicted_files", "store.gc.lock_busy",
    "store.chain_compacted", "resilience.faults.store_corrupt",
    "stream.deltas", "stream.commits", "stream.duplicates",
    "stream.conflicts", "stream.backpressure_429", "stream.commit_retries",
    "stream.recoveries", "stream.retrain.triggers", "stream.retrain.swaps",
    "stream.retrain.failed",
    "gauntlet.scenarios", "gauntlet.scenario_errors",
    "gauntlet.cells_injected", "gauntlet.repairs",
    "gauntlet.repairs_correct",
    "load.requests", "load.answered", "load.ok", "load.failed",
    "load.shed", "load.gave_up", "load.retries",
    "slo.segments", "slo.recovery_violations",
    "autoscale.ticks", "autoscale.up", "autoscale.down",
    "autoscale.blocked_cooldown", "autoscale.blocked_hysteresis",
    "autoscale.blocked_limit",
)


def _knob_float(env: str, conf: str, default: float) -> float:
    from delphi_tpu.parallel.resilience import _env_or_conf
    return _env_or_conf(env, conf, float, default)


def _knob_int(env: str, conf: str, default: int) -> int:
    from delphi_tpu.parallel.resilience import _env_or_conf
    return _env_or_conf(env, conf, int, default)


class Rejection(Exception):
    """An admission refusal carrying its HTTP mapping. ``extra`` merges
    into the response body — stream backpressure echoes the durable
    cursor there, so a 429 tells the client exactly where to resume."""

    def __init__(self, status: int, reason: str,
                 retry_after_s: Optional[float] = None,
                 extra: Optional[Dict[str, Any]] = None) -> None:
        self.status = int(status)
        self.reason = reason
        self.retry_after_s = retry_after_s
        self.extra = extra or {}
        super().__init__(reason)


class RepairJob:
    """One admitted /repair request moving through the queue → worker →
    response pipeline. ``done`` is the handler's rendezvous; ``scope`` is
    set by the worker so drain/abandonment can arm a scoped abort."""

    def __init__(self, request_id: str, payload: Dict[str, Any],
                 deadline_at: Optional[float]) -> None:
        self.request_id = request_id
        self.payload = payload
        self.deadline_at = deadline_at  # time.monotonic() basis
        self.enqueued_at = time.perf_counter()
        self.fp: Optional[str] = None  # table fingerprint once resolved
        self.scope: Optional[Any] = None
        self.status_code: int = 500
        self.response: Dict[str, Any] = {"request_id": request_id,
                                         "status": "error",
                                         "error": "not executed"}
        self.abandoned = False
        self.done = threading.Event()

    def remaining_s(self) -> Optional[float]:
        if self.deadline_at is None:
            return None
        return self.deadline_at - time.monotonic()


class RepairServer:
    """The persistent repair service. Lifecycle: ``start()`` →
    (requests...) → ``drain()`` (or ``stop()`` for an immediate teardown).
    ``port`` is the bound HTTP port (pass 0 for ephemeral — tests)."""

    def __init__(self, port: int = 0, workers: Optional[int] = None,
                 queue_depth: Optional[int] = None,
                 cache_dir: Optional[str] = None,
                 fleet_dir: Optional[str] = None,
                 worker_id: Optional[str] = None) -> None:
        self.requested_port = int(port)
        self.workers = workers if workers is not None else _knob_int(
            "DELPHI_SERVE_WORKERS", "repair.serve.workers", _DEF_WORKERS)
        self.workers = max(1, int(self.workers))
        depth = queue_depth if queue_depth is not None else _knob_int(
            "DELPHI_SERVE_QUEUE_DEPTH", "repair.serve.queue_depth",
            _DEF_QUEUE_DEPTH)
        self.queue_depth = max(1, int(depth))
        cache = cache_dir or os.environ.get("DELPHI_SERVE_CACHE_DIR")
        if not cache:
            from delphi_tpu.session import get_session
            cache = get_session().conf.get("repair.serve.cache_dir")
        # a stable cache dir is what makes restart warm (model checkpoints,
        # phase checkpoints, compile cache all live under it); the tempdir
        # default still gives warmth within one server lifetime
        self.cache_dir = str(cache) if cache else tempfile.mkdtemp(
            prefix="delphi_serve_")
        self.default_deadline_s = _knob_float(
            "DELPHI_SERVE_DEADLINE_S", "repair.serve.deadline_s",
            _DEF_DEADLINE_S)
        self.retry_after_s = _knob_float(
            "DELPHI_SERVE_RETRY_AFTER_S", "repair.serve.retry_after_s",
            _DEF_RETRY_AFTER_S)
        self.drain_grace_s = _knob_float(
            "DELPHI_SERVE_DRAIN_GRACE_S", "repair.serve.drain_grace_s",
            _DEF_DRAIN_GRACE_S)
        self.max_rss_gb = _knob_float(
            "DELPHI_SERVE_MAX_RSS_GB", "repair.serve.max_rss_gb", 0.0)
        self.stall_shed_s = _knob_float(
            "DELPHI_SERVE_STALL_SHED_S", "repair.serve.stall_shed_s",
            _DEF_STALL_SHED_S)
        # fleet membership seam (observability/fleet.py): when armed, the
        # worker registers itself under the shared fleet dir and keeps a
        # liveness heartbeat the router's membership scan reads
        fleet = fleet_dir or os.environ.get("DELPHI_FLEET_DIR")
        self.fleet_dir = str(fleet) if fleet else None
        wid = (worker_id if worker_id is not None
               else os.environ.get("DELPHI_FLEET_WORKER_ID"))
        self.worker_id = str(wid) if wid is not None else None
        self.fleet_heartbeat_s = _knob_float(
            "DELPHI_FLEET_HEARTBEAT_S", "repair.fleet.heartbeat_s",
            _DEF_FLEET_HEARTBEAT_S)
        self._fleet_thread: Optional[threading.Thread] = None
        self._fleet_stop: Optional[threading.Event] = None

        self.recorder: Optional[Any] = None
        self._own_recorder: Optional[Any] = None
        self._queue: "queue.Queue[Optional[RepairJob]]" = queue.Queue(
            maxsize=self.queue_depth)
        self._workers: List[threading.Thread] = []
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._http_thread: Optional[threading.Thread] = None
        self._draining = False
        self._stopped = threading.Event()
        self._lock = threading.Lock()
        self._in_flight = 0
        self._active: Dict[str, RepairJob] = {}
        # table fingerprint -> (catalog name, EncodedTable)
        self._tables: Dict[str, Tuple[str, Any]] = {}
        # chained delta ingestion (incremental/stream.py) — built in
        # start() once the cache dir exists
        self.streams: Optional[Any] = None

    # -- lifecycle -----------------------------------------------------------

    @property
    def port(self) -> Optional[int]:
        return self._httpd.server_address[1] if self._httpd else None

    def _models_dir(self, fp: str) -> str:
        return os.path.join(self.cache_dir, "models", fp[:16])

    def _ckpt_dir(self, fp: str) -> str:
        return os.path.join(self.cache_dir, "ckpt", fp[:16])

    def _snapshot_dir(self, snapshot_id: str) -> str:
        """Maps a client-supplied ``base_snapshot`` id onto the server
        cache; ids are restricted to a filename-safe alphabet so a request
        body can never escape ``<cache_dir>/snapshots/``."""
        if not snapshot_id or len(snapshot_id) > 64 or \
                not all(c.isalnum() or c in "._-" for c in snapshot_id) \
                or snapshot_id.startswith("."):
            raise ValueError(
                f"bad base_snapshot id {snapshot_id!r}: expected 1-64 "
                "chars from [A-Za-z0-9._-], not starting with '.'")
        return os.path.join(self.cache_dir, "snapshots", snapshot_id)

    def start(self) -> "RepairServer":
        from delphi_tpu import observability as obs

        os.makedirs(self.cache_dir, exist_ok=True)
        # arm the persistent compile cache under the serve cache dir unless
        # one is already configured — warm compiles across requests AND
        # across restarts come from here
        if not os.environ.get("DELPHI_COMPILE_CACHE_DIR") \
                and not os.environ.get("DELPHI_XLA_CACHE_DIR"):
            os.environ["DELPHI_COMPILE_CACHE_DIR"] = os.path.join(
                self.cache_dir, "compile")
        # arm the launch-plan store next to it: plans persist per table
        # fingerprint, so a warm request skips replanning and the compile
        # plane prewarms exactly the variants the stored plan will launch
        from delphi_tpu.parallel import planner
        planner.set_plan_store(os.path.join(self.cache_dir, "plans"))
        # one long-lived recorder for the server's whole life: per-request
        # model.run() recorders nest into it (start_recording returns None
        # when one is active), so every request's metrics land in ONE
        # registry served by /metrics
        self._own_recorder = obs.start_recording("repair.serve")
        self.recorder = self._own_recorder or obs.current_recorder()
        if self.recorder is None:  # pragma: no cover - defensive
            raise RuntimeError("serving plane requires a run recorder")
        for name in _SEED_COUNTERS:
            counter_inc(name, 0)
        gauge_set("serve.queue_depth", 0)
        gauge_set("serve.in_flight", 0)
        gauge_set("serve.shed_ratio", 0)
        gauge_set("serve.draining", 0)
        gauge_set("stream.lag_rows", 0)
        gauge_set("stream.active", 0)
        gauge_set("stream.recovering", 0)
        gauge_set("gauntlet.mean_f1", 0)
        gauge_set("gauntlet.mean_gap_closed", 0)
        from delphi_tpu.incremental.stream import StreamManager
        self.streams = StreamManager(
            os.path.join(self.cache_dir, "streams"),
            store_root=self.cache_dir)
        self._rebuild_warm_state()

        for i in range(self.workers):
            t = threading.Thread(target=self._worker_loop, daemon=True,
                                 name=f"delphi-serve-worker-{i}")
            t.start()
            self._workers.append(t)

        self._httpd = ThreadingHTTPServer(("127.0.0.1", self.requested_port),
                                          _ServeHandler)
        self._httpd.daemon_threads = True
        self._httpd.repair_server = self  # type: ignore[attr-defined]
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="delphi-serve-http")
        self._http_thread.start()
        self._register_fleet_worker()
        from delphi_tpu.observability import live as _live
        _live.register_sample_hook(self._sample_admission)
        _logger.info(
            f"repair service listening on 127.0.0.1:{self.port} "
            f"(workers={self.workers}, queue={self.queue_depth}, "
            f"cache={self.cache_dir})")
        return self

    # -- fleet membership ----------------------------------------------------

    def _fleet_registration_path(self) -> Optional[str]:
        if not self.fleet_dir or self.worker_id is None:
            return None
        return os.path.join(self.fleet_dir, f"worker_{self.worker_id}.json")

    def _register_fleet_worker(self) -> None:
        """Announces this worker to the fleet router: an atomic
        registration file (the bound ephemeral port — the one fact the
        router cannot know before spawn) plus a heartbeat-refreshed
        liveness file, the same file format the dist-resilience plane
        uses for rank diagnosis."""
        reg = self._fleet_registration_path()
        if reg is None:
            return
        from delphi_tpu.parallel import dist_resilience as dr

        info = {"worker_id": self.worker_id, "port": self.port,
                "pid": os.getpid(), "cache_dir": self.cache_dir,
                "started": float(time.time())}
        write_fleet_registration(self.fleet_dir, reg, info)
        live = dr.member_liveness_path(self.fleet_dir, self.worker_id)
        dr.touch_liveness_file(live)
        stop = threading.Event()
        interval = max(0.05, float(self.fleet_heartbeat_s))

        def _beat() -> None:
            while not stop.wait(interval):
                dr.touch_liveness_file(live)
                # a quarantined (corrupt) registration reads as
                # not-yet-registered at the router; re-announce so the
                # worker rejoins the ring instead of serving invisibly
                if not os.path.exists(reg):
                    try:
                        write_fleet_registration(self.fleet_dir, reg, info)
                    except OSError as e:
                        _logger.warning(
                            f"fleet re-registration failed: {e}")

        t = threading.Thread(target=_beat, daemon=True,
                             name="delphi-fleet-heartbeat")
        t.start()
        self._fleet_stop, self._fleet_thread = stop, t
        _logger.info(f"fleet worker {self.worker_id} registered in "
                     f"{self.fleet_dir} (port {self.port})")

    def unregister_fleet_worker(self) -> None:
        """Drops this worker out of fleet membership: stops the
        heartbeat, then removes the liveness and registration files so
        the router's next membership scan routes around it. Idempotent;
        a no-op outside a fleet."""
        reg = self._fleet_registration_path()
        if reg is None:
            return
        if self._fleet_stop is not None:
            self._fleet_stop.set()
        if self._fleet_thread is not None:
            self._fleet_thread.join(timeout=5.0)
            self._fleet_thread = None
        from delphi_tpu.parallel import dist_resilience as dr
        live = dr.member_liveness_path(self.fleet_dir, self.worker_id)
        for path in (live, reg):
            try:
                os.remove(path)
            except OSError:
                pass
        _logger.info(f"fleet worker {self.worker_id} unregistered")

    def _rebuild_warm_state(self) -> None:
        """Crash-safe warm-state inventory on (re)start: count the model
        checkpoints and phase checkpoints a previous life left under the
        cache dir. They are loaded lazily — the fingerprinted stores
        validate on first use — so a restart is warm without trusting any
        in-memory state that died with the old process."""
        def _count(sub: str) -> int:
            d = os.path.join(self.cache_dir, sub)
            try:
                return len([e for e in os.listdir(d)
                            if os.path.isdir(os.path.join(d, e))
                            or e.endswith(".pkl")])
            except OSError:
                return 0
        models = _count("models")
        ckpts = _count("ckpt")
        try:
            # ledger.<fp>.json launch-cost ledgers live beside the plans
            # but are not plans
            plans = len([e for e in os.listdir(
                os.path.join(self.cache_dir, "plans"))
                if e.endswith(".json") and not e.startswith("ledger.")])
        except OSError:
            plans = 0
        gauge_set("serve.warm_models", models)
        gauge_set("serve.warm_checkpoints", ckpts)
        gauge_set("serve.warm_plans", plans)
        if models or ckpts:
            _logger.info(f"warm-state rebuild: {models} model checkpoint "
                         f"dir(s), {ckpts} phase-checkpoint dir(s) under "
                         f"{self.cache_dir}")

    def begin_drain(self) -> None:
        """Stops admission; in-flight and queued work keeps running.
        Under a fleet, membership is dropped FIRST — the router must stop
        sending new work here (its next scan sees the liveness file gone)
        before admission closes, otherwise every request routed during
        the drain window eats a 503 hop instead of landing on a live
        replica directly."""
        with self._lock:
            if self._draining:
                return
        self.unregister_fleet_worker()
        with self._lock:
            self._draining = True
        gauge_set("serve.draining", 1)
        _logger.info("repair service draining: admission closed")

    def drain(self, grace_s: Optional[float] = None) -> None:
        """Graceful shutdown: close admission, give in-flight requests
        ``grace_s`` to finish, then arm each straggler's scoped abort so it
        stops at the next guarded seam / phase boundary — its phase
        checkpoints (written at every completed phase) stay on disk, so a
        resubmitted identical request resumes instead of recomputing.
        Finally tears down workers, HTTP, and the recorder."""
        self.begin_drain()
        grace = self.drain_grace_s if grace_s is None else float(grace_s)
        deadline = time.monotonic() + max(0.0, grace)
        while time.monotonic() < deadline:
            with self._lock:
                idle = self._in_flight == 0 and self._queue.empty()
            if idle:
                break
            time.sleep(0.05)
        with self._lock:
            stragglers = list(self._active.values())
        for job in stragglers:
            if job.scope is not None:
                job.scope.request_abort("server draining")
        if stragglers:
            _logger.warning(
                f"drain grace expired: aborting {len(stragglers)} in-flight "
                "request(s) at their next checkpoint boundary")
            # give the aborts a moment to land at a seam
            settle = time.monotonic() + 10.0
            while time.monotonic() < settle:
                with self._lock:
                    if self._in_flight == 0:
                        break
                time.sleep(0.05)
        self.stop()

    def stop(self) -> None:
        """Immediate teardown (drain() calls this last)."""
        if self._stopped.is_set():
            return
        self._stopped.set()
        from delphi_tpu.observability import live as _live
        _live.unregister_sample_hook(self._sample_admission)
        self.unregister_fleet_worker()
        for _ in self._workers:
            try:
                self._queue.put_nowait(None)
            except queue.Full:  # drop a queued job slot to fit the sentinel
                try:
                    dropped = self._queue.get_nowait()
                    if dropped is not None:
                        dropped.status_code = 503
                        dropped.response = {
                            "request_id": dropped.request_id,
                            "status": "rejected",
                            "error": "server shutting down"}
                        dropped.done.set()
                except queue.Empty:
                    pass
                self._queue.put_nowait(None)
        for t in self._workers:
            t.join(timeout=10.0)
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            if self._http_thread is not None:
                self._http_thread.join(timeout=10.0)
            self._httpd = None
        if self._own_recorder is not None:
            from delphi_tpu import observability as obs
            obs.stop_recording(self._own_recorder)
            self._own_recorder = None
        # disarm the plan store armed at start() — but only if it is still
        # OURS: a later-started server (warm restart on another cache dir)
        # must keep its own store
        from delphi_tpu.parallel import planner
        store = planner.get_plan_store()
        if store is not None and \
                store.root == os.path.join(self.cache_dir, "plans"):
            planner.set_plan_store(None)
        _logger.info("repair service stopped")

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Blocks until the server is stopped (main.py --serve)."""
        return self._stopped.wait(timeout)

    # -- admission -----------------------------------------------------------

    def _sample_admission(self) -> None:
        """Re-samples the admission gauges outside the request path —
        registered with the live plane's resource sampler so a /metrics
        scrape on an idle (or wedged) server still reflects the current
        queue, not the last request's view. Also the one place
        ``serve.shed_ratio`` is derived from its component counters."""
        from delphi_tpu.observability.registry import counter_value
        gauge_set("serve.queue_depth", self._queue.qsize())
        with self._lock:
            gauge_set("serve.in_flight", self._in_flight)
        requests = counter_value("serve.requests")
        if requests > 0:
            gauge_set("serve.shed_ratio",
                      round(counter_value("serve.shed") / requests, 6))

    def submit(self, payload: Dict[str, Any]) -> RepairJob:
        """Admission control: draining → 503, overload (RSS / wedged
        heartbeat / full queue) → 429 with Retry-After. Returns the queued
        job; the caller waits on ``job.done``."""
        counter_inc("serve.requests")
        with self._lock:
            draining = self._draining
        if draining or self._stopped.is_set():
            counter_inc("serve.rejected_draining")
            raise Rejection(503, "server is draining",
                            retry_after_s=self.retry_after_s)
        if self.max_rss_gb > 0:
            from delphi_tpu.observability.live import _rss_gb
            rss = _rss_gb()
            if rss is not None and rss > self.max_rss_gb:
                counter_inc("serve.shed")
                self._sample_admission()
                raise Rejection(
                    429, f"process RSS {rss:.2f} GiB over the "
                         f"{self.max_rss_gb:.2f} GiB admission limit",
                    retry_after_s=self.retry_after_s)
        if self.stall_shed_s > 0 and self.recorder is not None:
            with self._lock:
                busy = self._in_flight > 0
            idle = time.perf_counter() - self.recorder.last_transition
            if busy and idle > self.stall_shed_s:
                counter_inc("serve.shed")
                self._sample_admission()
                raise Rejection(
                    429, f"in-flight work wedged ({idle:.0f}s without a "
                         "span heartbeat)",
                    retry_after_s=self.retry_after_s)
        request_id = str(payload.get("request_id")
                         or f"req-{time.monotonic_ns():x}")
        deadline_s = payload.get("deadline_s", self.default_deadline_s)
        try:
            deadline_s = float(deadline_s)
        except (TypeError, ValueError):
            raise Rejection(400, f"bad deadline_s: {deadline_s!r}")
        deadline_at = (time.monotonic() + deadline_s
                       if deadline_s > 0 else None)
        stream_req = payload.get("stream")
        if stream_req is not None:
            # per-stream backpressure BEFORE the shared queue: a stream
            # past its in-flight bound gets 429 + the durable cursor so
            # it resumes exactly where the server is, instead of queuing
            # deltas the chain cannot admit yet
            from delphi_tpu.incremental.stream import StreamBusy
            if not isinstance(stream_req, dict) or not stream_req.get("id"):
                raise Rejection(400, "stream must be an object with an "
                                     "'id' and a 'seq'")
            try:
                self.streams.admit(stream_req["id"], _stream_rows(payload),
                                   retry_after_s=self.retry_after_s)
            except StreamBusy as b:
                raise Rejection(
                    429, f"stream {b.stream_id} backpressure: "
                         "in-flight delta bound reached",
                    retry_after_s=b.retry_after_s,
                    extra={"cursor": b.cursor})
            except ValueError as e:
                raise Rejection(400, str(e))
        job = RepairJob(request_id, payload, deadline_at)
        try:
            self._queue.put_nowait(job)
        except queue.Full:
            if stream_req is not None:
                self.streams.release(stream_req.get("id"),
                                     _stream_rows(payload))
            counter_inc("serve.shed")
            self._sample_admission()
            raise Rejection(429, "admission queue full",
                            retry_after_s=self.retry_after_s)
        counter_inc("serve.accepted")
        gauge_set("serve.queue_depth", self._queue.qsize())
        return job

    # -- execution -----------------------------------------------------------

    def _worker_loop(self) -> None:
        while True:
            job = self._queue.get()
            if job is None:
                return
            gauge_set("serve.queue_depth", self._queue.qsize())
            histogram_observe("serve.queue_wait_seconds",
                              time.perf_counter() - job.enqueued_at)
            with self._lock:
                self._in_flight += 1
                self._active[job.request_id] = job
            gauge_set("serve.in_flight", self._in_flight)
            try:
                self._execute(job)
            except BaseException as e:  # a worker must survive anything
                _logger.warning(
                    f"request {job.request_id}: unhandled "
                    f"{type(e).__name__}: {e}")
                job.status_code = 500
                job.response = {"request_id": job.request_id,
                                "status": "error",
                                "error": f"{type(e).__name__}: {e}"}
            finally:
                with self._lock:
                    self._in_flight -= 1
                    self._active.pop(job.request_id, None)
                gauge_set("serve.in_flight", self._in_flight)
                job.done.set()

    def _resolve_table(self, payload: Dict[str, Any]) -> Tuple[str, str]:
        """Warm table cache: encode + validate once per content
        fingerprint, register the EncodedTable in the session catalog
        (device-resident code buffers then persist on its column objects
        across requests)."""
        import pandas as pd

        from delphi_tpu.session import get_session
        from delphi_tpu.table import check_input_table

        table = payload["table"]
        row_id = payload["row_id"]
        fp = table_fingerprint(table, row_id)
        with self._lock:
            cached = self._tables.get(fp)
        if cached is not None:
            counter_inc("serve.table_cache.hits")
            return cached[0], fp
        name = f"serve_{fp[:16]}"
        df = pd.DataFrame({c: pd.Series(v) for c, v in table.items()})
        encoded, _cont = check_input_table(df, row_id, name)
        get_session().register(name, encoded)
        with self._lock:
            self._tables[fp] = (name, encoded)
            n = len(self._tables)
        counter_inc("serve.table_cache.misses")
        gauge_set("serve.warm_tables", n)
        return name, fp

    def _evict_dirty(self, fp: Optional[str],
                     request_id: Optional[str] = None) -> None:
        """Drops ONLY the state a failed request dirtied: its device-
        resident code buffers (a device fault may have corrupted them;
        evicting is always safe — the next use re-uploads ground truth
        bit-identically), its fingerprint cache entry (so the next request
        re-validates and re-registers), and its per-fingerprint model
        checkpoint. Other fingerprints' warm state is untouched. The
        session-catalog entry — host-side encoded data a device fault
        cannot dirty — stays, so a concurrent request on the same table
        that already resolved the name keeps running unharmed."""
        if fp is None:
            return
        import shutil

        from delphi_tpu.ops.xfer import evict_device_codes

        with self._lock:
            entry = self._tables.pop(fp, None)
            n = len(self._tables)
        if entry is not None:
            _name, encoded = entry
            try:
                evict_device_codes(encoded.columns)
            except Exception:  # pragma: no cover - eviction is best-effort
                pass
            gauge_set("serve.warm_tables", n)
        shutil.rmtree(self._models_dir(fp), ignore_errors=True)

    def _execute(self, job: RepairJob) -> None:
        """Trace envelope around one request: continues the caller's
        trace (the ``X-Delphi-Trace`` header the handler parsed into the
        payload) or mints a fresh one when ``DELPHI_TRACE_DIR`` is armed,
        stamps the response with the trace id, and flushes any launch
        costs this request recorded to the persisted ledger."""
        parsed = job.payload.get("_trace") or (None, None)
        with _trace.request_scope(parsed[0], parsed[1]) as tctx:
            try:
                self._execute_traced(job)
            finally:
                if tctx is not None and isinstance(job.response, dict):
                    job.response.setdefault("trace_id", tctx.trace_id)
                _trace.flush_ledger()

    def _execute_traced(self, job: RepairJob) -> None:
        if job.payload.get("stream") is not None:
            self._execute_stream(job)
            return
        from delphi_tpu.api import Delphi
        from delphi_tpu.errors import NullErrorDetector
        from delphi_tpu.observability import provenance
        from delphi_tpu.parallel import resilience

        t0 = time.perf_counter()
        rid = job.request_id
        payload = job.payload
        fp: Optional[str] = None
        ledger: Optional[Any] = None
        try:
            rem = job.remaining_s()
            if rem is not None and rem <= 0:
                raise resilience.DeadlineExceeded(
                    f"request {rid} deadline expired after "
                    f"{-rem:.3f}s in the admission queue")
            name, fp = self._resolve_table(payload)
            job.fp = fp
            model = Delphi.getOrCreate().repair \
                .setTableName(name) \
                .setRowId(payload["row_id"]) \
                .setErrorDetectors([NullErrorDetector()])
            model.option("model.checkpoint_path", self._models_dir(fp))
            for key, value in (payload.get("options") or {}).items():
                model.option(str(key), str(value))
            base_snapshot = payload.get("base_snapshot")
            if base_snapshot is not None:
                # per-request model options, NOT env: concurrent requests
                # against different snapshots must not race a global flag
                snap_dir = self._snapshot_dir(str(base_snapshot))
                os.makedirs(snap_dir, exist_ok=True)
                model.option("repair.incremental", "true")
                model.option("repair.snapshot.dir", snap_dir)
            prov_dir = os.environ.get("DELPHI_SERVE_PROVENANCE_DIR")
            if prov_dir:
                os.makedirs(prov_dir, exist_ok=True)
                ledger = provenance.ProvenanceLedger(
                    os.path.join(prov_dir, f"{rid}.jsonl"))
            scope = resilience.RequestScope(
                rid, fault_plan=str(payload.get("fault_plan") or ""),
                deadline_s=rem, checkpoint_dir=self._ckpt_dir(fp))
            job.scope = scope
            from delphi_tpu.parallel import planner
            with resilience.request_scope(scope), \
                    provenance.scoped_ledger(ledger), \
                    planner.plan_fingerprint(fp):
                out = model.run()
            # canonical response ordering: sorted by all columns, so two
            # servers (or a solo run) repairing the same table respond
            # byte-identically regardless of internal work order
            out = out.sort_values(list(out.columns)).reset_index(drop=True)
            job.status_code = 200
            job.response = {
                "request_id": rid, "status": "ok", "rows": int(len(out)),
                "frame": json.loads(out.to_json(orient="records")),
            }
            if base_snapshot is not None:
                job.response["base_snapshot"] = str(base_snapshot)
                job.response["incremental"] = getattr(
                    model, "_last_incremental", None)
            # per-request escalation rides the generic options loop above
            # (repair.escalate / .conf / .budget / .adapter); echo the
            # summary so the caller sees what was routed and escalated
            esc_summary = getattr(model, "_last_escalation", None)
            if esc_summary is not None:
                job.response["escalation"] = esc_summary
            counter_inc("serve.completed")
        except resilience.DeadlineExceeded as e:
            counter_inc("serve.deadline_expired")
            job.status_code = 504
            job.response = {"request_id": rid, "status": "deadline_exceeded",
                            "error": str(e)}
        except resilience.RunAborted as e:
            # drain-time abort: phase checkpoints for every completed phase
            # are already on disk under the request's checkpoint dir
            counter_inc("serve.aborted")
            job.status_code = 503
            job.response = {
                "request_id": rid, "status": "aborted", "error": str(e),
                "resumable": fp is not None
                and os.path.isdir(self._ckpt_dir(fp)),
            }
        except KeyError as e:
            job.status_code = 400
            job.response = {"request_id": rid, "status": "bad_request",
                            "error": f"missing field {e}"}
        except BaseException as e:
            # one request's failure — injected fault, OOM past the ladder,
            # bad options, a genuine bug — is THAT request's structured
            # error; evict only the warm state it dirtied
            counter_inc("serve.failed")
            kind = resilience.classify_fault(e)
            if isinstance(e, resilience.FaultInjected):
                kind = e.kind
            job.status_code = 400 if isinstance(e, ValueError) else 500
            job.response = {"request_id": rid, "status": "error",
                            "kind": kind or type(e).__name__,
                            "error": f"{type(e).__name__}: {e}"}
            self._evict_dirty(fp, request_id=rid)
        finally:
            if ledger is not None:
                try:
                    ledger.write()
                except Exception as e:  # pragma: no cover - best effort
                    _logger.warning(f"request {rid}: provenance flush "
                                    f"failed: {e}")
            histogram_observe("serve.request_seconds",
                              time.perf_counter() - t0)

    def stream_cursors(self) -> Dict[str, Any]:
        """Durable resume points for every stream under the cache root —
        what /drain reports before closing admission."""
        if self.streams is None:
            return {}
        return self.streams.durable_cursors()

    def _execute_stream(self, job: RepairJob) -> None:
        """One chained stream delta: accumulate → incremental repair
        against the per-stream snapshot → durable cursor commit —
        serialized per stream by the session lock, idempotent under
        re-dispatch, with the background retrain hooked in. The admission
        slot taken in submit() is released here whatever happens."""
        from delphi_tpu.api import Delphi
        from delphi_tpu.errors import NullErrorDetector
        from delphi_tpu.incremental.stream import StreamCommitError
        from delphi_tpu.observability import provenance
        from delphi_tpu.parallel import resilience

        import pandas as pd

        t0 = time.perf_counter()
        rid = job.request_id
        payload = job.payload
        stream_req = payload["stream"]
        sid = str(stream_req.get("id"))
        rows = _stream_rows(payload)
        sess = None
        ledger: Optional[Any] = None
        try:
            rem = job.remaining_s()
            if rem is not None and rem <= 0:
                raise resilience.DeadlineExceeded(
                    f"request {rid} deadline expired after "
                    f"{-rem:.3f}s in the admission queue")
            sess = self.streams.session(sid)
            row_id = payload["row_id"]
            delta_df = pd.DataFrame(
                {c: pd.Series(v) for c, v in payload["table"].items()})
            chain_fp = chain_fingerprint(payload) or "stream"
            job.fp = chain_fp

            def _repair_model(name: str, incremental: bool,
                              snap_dir: Optional[str]) -> Any:
                model = Delphi.getOrCreate().repair \
                    .setTableName(name) \
                    .setRowId(row_id) \
                    .setErrorDetectors([NullErrorDetector()])
                model.option("model.checkpoint_path",
                             self._models_dir(chain_fp))
                for key, value in (payload.get("options") or {}).items():
                    model.option(str(key), str(value))
                if incremental:
                    model.option("repair.incremental", "true")
                    model.option("repair.snapshot.dir", snap_dir)
                return model

            def _registered(name: str, frame: Any) -> str:
                from delphi_tpu.session import get_session
                from delphi_tpu.table import check_input_table
                encoded, _cont = check_input_table(frame, row_id, name)
                get_session().register(name, encoded)
                return name

            def run_fn(accumulated: Any, snap_dir: str, seq: int
                       ) -> Tuple[Any, Optional[Dict[str, Any]]]:
                from delphi_tpu.session import get_session
                name = _registered(f"stream_{sid[:16]}_{seq}", accumulated)
                try:
                    os.makedirs(snap_dir, exist_ok=True)
                    model = _repair_model(name, True, snap_dir)
                    out = model.run()
                    # canonical ordering, same as the batch path: any
                    # replica (or a solo batch run) answers byte-identically
                    out = out.sort_values(
                        list(out.columns)).reset_index(drop=True)
                    return out, getattr(model, "_last_incremental", None)
                finally:
                    get_session().drop(name)

            # snapshot the request's trace position NOW: the retrain runs
            # later on its own thread, and adopt() joins its spans under
            # the request span that triggered it — one coherent trace
            trace_snap = _trace.capture()

            def retrain_fn(accumulated: Any) -> Dict[str, Any]:
                from delphi_tpu.session import get_session
                with _trace.adopt(trace_snap):
                    name = _registered(f"stream_{sid[:16]}_retrain",
                                       accumulated)
                    try:
                        model = _repair_model(name, False, None)
                        model.run()
                        return dict(
                            getattr(model, "_last_models", None) or [])
                    finally:
                        get_session().drop(name)

            # the delta splice stamps per-cell reused/recomputed decisions
            # into the chain's provenance: a per-request ledger (file under
            # DELPHI_SERVE_PROVENANCE_DIR, else in-memory) keeps those
            # stamps isolated from every other session in this process —
            # the process-global ledger would already hold other requests'
            # cells and silently swallow the splice
            prov_dir = os.environ.get("DELPHI_SERVE_PROVENANCE_DIR")
            if prov_dir:
                os.makedirs(prov_dir, exist_ok=True)
                ledger = provenance.ProvenanceLedger(
                    os.path.join(prov_dir, f"{rid}.jsonl"))
            else:
                ledger = provenance.ProvenanceLedger(provenance.MEMORY_PATH)
            scope = resilience.RequestScope(
                rid, fault_plan=str(payload.get("fault_plan") or ""),
                deadline_s=rem, checkpoint_dir=self._ckpt_dir(chain_fp))
            job.scope = scope
            with resilience.request_scope(scope), \
                    provenance.scoped_ledger(ledger):
                status, body = sess.apply(
                    stream_req.get("seq"),
                    stream_req.get("parent_snapshot"),
                    delta_df, run_fn, retrain_fn=retrain_fn)
            frame = body.pop("frame_df", None)
            if frame is not None:
                body["rows"] = int(len(frame))
                body["frame"] = json.loads(frame.to_json(orient="records"))
            body["request_id"] = rid
            job.status_code = status
            job.response = body
            counter_inc("serve.completed" if status == 200
                        else "serve.failed")
        except resilience.DeadlineExceeded as e:
            counter_inc("serve.deadline_expired")
            job.status_code = 504
            job.response = {"request_id": rid,
                            "status": "deadline_exceeded", "error": str(e)}
        except resilience.RunAborted as e:
            counter_inc("serve.aborted")
            job.status_code = 503
            job.response = {
                "request_id": rid, "status": "aborted", "error": str(e),
                "resumable": True,
                "cursor": sess.durable_cursor() if sess else None}
        except StreamCommitError as e:
            # NOT acknowledged: the client resends from the echoed cursor
            counter_inc("serve.failed")
            job.status_code = 503
            job.response = {
                "request_id": rid, "status": "error",
                "kind": "store_corrupt", "error": str(e),
                "cursor": sess.durable_cursor() if sess else None}
        except KeyError as e:
            job.status_code = 400
            job.response = {"request_id": rid, "status": "bad_request",
                            "error": f"missing field {e}"}
        except BaseException as e:
            counter_inc("serve.failed")
            kind = resilience.classify_fault(e)
            if isinstance(e, resilience.FaultInjected):
                kind = e.kind
            job.status_code = 400 if isinstance(e, ValueError) else 500
            job.response = {
                "request_id": rid, "status": "error",
                "kind": kind or type(e).__name__,
                "error": f"{type(e).__name__}: {e}",
                "cursor": sess.durable_cursor() if sess else None}
        finally:
            if ledger is not None and ledger.path != provenance.MEMORY_PATH:
                try:
                    ledger.write()
                except Exception as e:  # pragma: no cover - best effort
                    _logger.warning(f"request {rid}: provenance flush "
                                    f"failed: {e}")
            self.streams.release(sid, rows)
            histogram_observe("serve.request_seconds",
                              time.perf_counter() - t0)


class _ServeHandler(BaseHTTPRequestHandler):
    def log_message(self, fmt: str, *args: Any) -> None:
        _logger.debug("repair service: " + fmt % args)

    @property
    def _server(self) -> RepairServer:
        return self.server.repair_server  # type: ignore[attr-defined]

    def _respond(self, status: int, body: Dict[str, Any],
                 retry_after_s: Optional[float] = None,
                 content_type: str = "application/json",
                 headers: Optional[Dict[str, Any]] = None) -> None:
        data = json.dumps(body).encode()
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        if retry_after_s is not None:
            self.send_header("Retry-After",
                             str(max(1, int(round(retry_after_s)))))
        for key, value in (headers or {}).items():
            self.send_header(key, str(value))
        self.end_headers()
        self.wfile.write(data)

    def _respond_text(self, status: int, content_type: str,
                      body: bytes) -> None:
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:  # noqa: N802 (BaseHTTPRequestHandler API)
        from delphi_tpu.observability.live import (
            PROMETHEUS_CONTENT_TYPE, render_prometheus,
        )

        srv = self._server
        path = self.path.split("?", 1)[0]
        try:
            if path == "/healthz":
                from delphi_tpu.parallel import store as dstore
                quarantined = dstore.quarantine_count(srv.cache_dir)
                recovering = (srv.streams.recovering_count()
                              if srv.streams is not None else 0)
                with srv._lock:
                    # a stream in recovery replay is serving from state
                    # rebuilt off disk that no commit has confirmed yet —
                    # degraded until its next delta lands
                    status = "draining" if srv._draining else \
                        ("degraded" if quarantined or recovering
                         else "ok")
                    body = {
                        "status": status,
                        "in_flight": srv._in_flight,
                        "queue_depth": srv._queue.qsize(),
                        "warm_tables": len(srv._tables),
                        "workers": srv.workers,
                        "quarantined": quarantined,
                        "streams": {
                            "active": (srv.streams.active_count()
                                       if srv.streams is not None else 0),
                            "recovering": recovering,
                            "lag_rows": (srv.streams.lag_rows()
                                         if srv.streams is not None
                                         else 0),
                        },
                    }
                self._respond(200, body)
            elif path == "/metrics":
                text = render_prometheus(srv.recorder).encode()
                self._respond_text(200, PROMETHEUS_CONTENT_TYPE, text)
            elif path == "/report":
                from delphi_tpu.observability.report import build_run_report
                report = build_run_report(srv.recorder, run={},
                                          status="serving", error=None)
                self._respond(200, report)
            elif path.startswith("/trace/"):
                doc = _trace.load_trace(path[len("/trace/"):])
                if doc is None:
                    self._respond(404, {
                        "error": "no such trace under "
                                 f"{_trace.trace_root() or '<unset>'}"})
                else:
                    self._respond(200, doc)
            else:
                self._respond(404, {"error": f"unknown path {path}"})
        except Exception as e:  # pragma: no cover - defensive
            try:
                self._respond(500, {"error": f"{type(e).__name__}: {e}"})
            except Exception:
                pass

    def do_POST(self) -> None:  # noqa: N802 (BaseHTTPRequestHandler API)
        srv = self._server
        path = self.path.split("?", 1)[0]
        try:
            if path == "/drain":
                # cursors FIRST, response SECOND, admission closed LAST:
                # the drain reply must carry every stream's durable
                # resume point before a single delta can be refused, so
                # a mid-stream drain never strands a chain without a
                # resume point (ordering pinned by a spy test)
                cursors = srv.stream_cursors()
                self._respond(200, {"status": "draining",
                                    "resumable": True,
                                    "streams": cursors})
                srv.begin_drain()
                return
            if path != "/repair":
                self._respond(404, {"error": f"unknown path {path}"})
                return
            try:
                length = int(self.headers.get("Content-Length") or 0)
                payload = json.loads(self.rfile.read(length) or b"{}")
            except (ValueError, json.JSONDecodeError) as e:
                self._respond(400, {"status": "bad_request",
                                    "error": f"bad JSON body: {e}"})
                return
            if not isinstance(payload, dict) \
                    or not isinstance(payload.get("table"), dict) \
                    or not isinstance(payload.get("row_id"), str):
                self._respond(400, {
                    "status": "bad_request",
                    "error": "body must be a JSON object with a 'table' "
                             "object and a 'row_id' string"})
                return
            # continue the caller's trace: the router (or a client) hands
            # us its position via X-Delphi-Trace; the worker thread joins
            # it in _execute's request scope
            tid, parent = _trace.parse_header(
                self.headers.get(_trace.TRACE_HEADER))
            if tid is not None:
                payload["_trace"] = (tid, parent)
            try:
                job = srv.submit(payload)
            except Rejection as r:
                body = {"status": "rejected", "error": r.reason}
                body.update(r.extra)
                self._respond(r.status, body,
                              retry_after_s=r.retry_after_s)
                return
            # rendezvous: the worker's deadline machinery normally answers
            # well before this backstop; the +grace covers a request wedged
            # between guarded seams, and abandoning it arms a scoped abort
            # so the worker is reclaimed at the next seam
            rem = job.remaining_s()
            wait_s = None if rem is None else max(rem, 0.0) + 15.0
            if not job.done.wait(timeout=wait_s):
                job.abandoned = True
                if job.scope is not None:
                    job.scope.request_abort("client deadline abandoned")
                counter_inc("serve.handler_timeouts")
                self._respond(504, {
                    "request_id": job.request_id,
                    "status": "deadline_exceeded",
                    "error": "request did not finish within its deadline"})
                return
            extra = None
            if isinstance(job.response, dict) \
                    and job.response.get("trace_id"):
                extra = {_trace.TRACE_HEADER: job.response["trace_id"]}
            self._respond(job.status_code, job.response, headers=extra)
        except Exception as e:  # pragma: no cover - defensive
            try:
                self._respond(500, {"error": f"{type(e).__name__}: {e}"})
            except Exception:
                pass


def install_signal_handlers(server: RepairServer) -> None:
    """SIGTERM/SIGINT → graceful drain (main-thread only; ``main.py
    --serve`` calls this, tests drive ``begin_drain``/``drain``
    directly)."""
    def _handler(signum: int, frame: Any) -> None:
        _logger.info(f"signal {signum}: draining repair service")
        threading.Thread(target=server.drain, daemon=True,
                         name="delphi-serve-drain").start()

    signal.signal(signal.SIGTERM, _handler)
    signal.signal(signal.SIGINT, _handler)


def serve(port: int = 8080, workers: Optional[int] = None,
          cache_dir: Optional[str] = None) -> int:
    """Blocking entry point for ``main.py --serve``: starts the service,
    installs signal handlers, and waits until a drain completes."""
    server = RepairServer(port=port, workers=workers, cache_dir=cache_dir)
    server.start()
    install_signal_handlers(server)
    print(f"delphi repair service on 127.0.0.1:{server.port} "
          f"(cache {server.cache_dir})", flush=True)
    server.wait()
    return 0
