"""Cross-run drift gate over per-attribute quality scorecards.

Compares the current run's scorecards (``observability/provenance.py``)
against a baseline run report and quantifies how differently the repair
pipeline behaved:

* **PSI** (population stability index) on each attribute's confidence
  histogram — did the model get more/less sure of its repairs?
* **Jensen–Shannon divergence** (base 2, so in [0, 1]) on each attribute's
  repaired-value distribution — is it writing different values?
* repair-rate delta per attribute.

``main.py --baseline-report`` wires this up for CI-style regression gating:
the per-attribute and max divergences land as ``drift.*`` gauges in the
active metrics registry (so the live ``/metrics`` plane exposes them while
the server is still up) and in the run report's ``drift`` section, and
``--drift-fail-over X`` fails the run when the max divergence exceeds X.

Rule of thumb (the PSI folklore thresholds): < 0.1 no meaningful change,
0.1–0.25 moderate shift worth a look, > 0.25 the runs behave differently.
"""

import math
from typing import Any, Dict, List, Optional, Sequence, Tuple

from delphi_tpu.observability.registry import counter_inc
from delphi_tpu.utils import setup_logger

_logger = setup_logger()

_EPS = 1e-6


def _normalize(counts: Sequence[float]) -> Optional[List[float]]:
    """None when the vector carries no mass — empty, all-zero, or polluted
    by NaN/inf (a tiny baseline can surface NaN bins, and ``NaN <= 0`` is
    False, so the non-finite check must come first)."""
    total = float(sum(counts))
    if not math.isfinite(total) or total <= 0:
        return None
    return [c / total for c in counts]


def population_stability_index(current: Sequence[float],
                               baseline: Sequence[float]) -> float:
    """PSI over two aligned count vectors; zero-padded bins are smoothed
    with a small epsilon so empty bins don't blow up the log ratio. Two
    empty distributions (e.g. an attribute with no confident repairs in
    either run) diverge by 0."""
    p = _normalize(current)
    q = _normalize(baseline)
    if p is None or q is None:
        counter_inc("drift.bins_empty")
        return 0.0
    psi = 0.0
    for pi, qi in zip(p, q):
        pi = max(pi, _EPS)
        qi = max(qi, _EPS)
        psi += (pi - qi) * math.log(pi / qi)
    return psi


def jensen_shannon_divergence(current: Sequence[float],
                              baseline: Sequence[float]) -> float:
    """Base-2 JS divergence over two aligned count vectors, in [0, 1]."""
    p = _normalize(current)
    q = _normalize(baseline)
    if p is None or q is None:
        counter_inc("drift.bins_empty")
        return 0.0
    js = 0.0
    for pi, qi in zip(p, q):
        mi = 0.5 * (pi + qi)
        if pi > 0:
            js += 0.5 * pi * math.log2(pi / mi)
        if qi > 0:
            js += 0.5 * qi * math.log2(qi / mi)
    return max(js, 0.0)


def _aligned_value_counts(cur: Dict[str, int], base: Dict[str, int]) \
        -> Tuple[List[float], List[float]]:
    keys = sorted(set(cur) | set(base))
    return ([float(cur.get(k, 0)) for k in keys],
            [float(base.get(k, 0)) for k in keys])


def compare_scorecards(current: Dict[str, Any],
                       baseline: Dict[str, Any]) -> Dict[str, Any]:
    """Per-attribute drift between two scorecard maps. Attributes present
    on only one side are reported but excluded from the max divergences
    (a new/removed column is a schema change, not distribution drift)."""
    per_attr: Dict[str, Any] = {}
    for attr in sorted(set(current) | set(baseline)):
        c, b = current.get(attr), baseline.get(attr)
        if c is None or b is None:
            per_attr[attr] = {
                "status": "missing_in_current" if c is None
                else "missing_in_baseline"}
            continue
        conf_psi = population_stability_index(
            c.get("confidence", {}).get("bins", []),
            b.get("confidence", {}).get("bins", []))
        cur_rv, base_rv = _aligned_value_counts(
            c.get("repaired_values", {}), b.get("repaired_values", {}))
        rv_js = jensen_shannon_divergence(cur_rv, base_rv)
        per_attr[attr] = {
            "confidence_psi": round(conf_psi, 6),
            "repair_value_js": round(rv_js, 6),
            "repair_rate_delta": round(
                c.get("repair_rate", 0.0) - b.get("repair_rate", 0.0), 6),
            "cells_flagged_delta":
                c.get("cells_flagged", 0) - b.get("cells_flagged", 0),
        }
    scored = [v for v in per_attr.values() if "confidence_psi" in v]
    max_psi = max((v["confidence_psi"] for v in scored), default=0.0)
    max_js = max((v["repair_value_js"] for v in scored), default=0.0)
    return {
        "per_attribute": per_attr,
        "max_confidence_psi": round(max_psi, 6),
        "max_repair_value_js": round(max_js, 6),
        "max_divergence": round(max(max_psi, max_js), 6),
    }


def emit_drift_gauges(registry: Any, drift: Dict[str, Any]) -> None:
    """Lands the drift result as ``drift.*`` gauges; while the live plane is
    up they render on ``/metrics`` like every other registry gauge."""
    for attr, v in drift.get("per_attribute", {}).items():
        if "confidence_psi" not in v:
            continue
        registry.set_gauge(f"drift.{attr}.confidence_psi",
                           v["confidence_psi"])
        registry.set_gauge(f"drift.{attr}.repair_value_js",
                           v["repair_value_js"])
        registry.set_gauge(f"drift.{attr}.repair_rate_delta",
                           v["repair_rate_delta"])
    registry.set_gauge("drift.max_confidence_psi",
                       drift.get("max_confidence_psi", 0.0))
    registry.set_gauge("drift.max_repair_value_js",
                       drift.get("max_repair_value_js", 0.0))
    registry.set_gauge("drift.max_divergence",
                       drift.get("max_divergence", 0.0))
    if drift.get("failed") is not None:
        registry.set_gauge("drift.failed", 1.0 if drift["failed"] else 0.0)


def evaluate(current_scorecards: Optional[Dict[str, Any]],
             baseline_report: Optional[Dict[str, Any]],
             fail_over: Optional[float] = None,
             registry: Any = None) -> Dict[str, Any]:
    """The full drift gate: compare, attach the fail verdict, emit gauges.

    ``baseline_report`` is a loaded run report (v1/v2 reports upgrade but
    carry no scorecards — the result then flags ``baseline_missing`` and
    never fails the gate, so a freshly-introduced baseline can't block CI).
    """
    baseline_cards = (baseline_report or {}).get("scorecards") or {}
    result = compare_scorecards(current_scorecards or {}, baseline_cards)
    result["baseline_missing"] = not baseline_cards
    result["fail_over"] = fail_over
    result["failed"] = bool(
        fail_over is not None and baseline_cards
        and result["max_divergence"] > fail_over)
    if registry is not None:
        try:
            emit_drift_gauges(registry, result)
        except Exception as e:
            _logger.warning(f"failed to emit drift gauges: {e}")
    if result["failed"]:
        _logger.warning(
            "drift gate FAILED: max divergence {} > fail-over {}".format(
                result["max_divergence"], fail_over))
    return result


# -- gauntlet gate ----------------------------------------------------------
#
# The scorecard gate above is distributional: it needs repairs on both
# sides to say anything (a run that silently stops repairing shows two
# empty distributions and zero divergence). The gauntlet gate closes that
# hole with ground truth: every scenario carries its injected-cell F1 and
# downstream gap-closed, so a quality collapse is a direct, signed drop —
# not a distribution shift that might wash out.

#: downstream gap-closed lives in [-2, 2]; halve it onto the F1/divergence
#: scale so one fail-over threshold governs all three signals
_GAP_SCALE = 0.5


def compare_gauntlet(current: Dict[str, Any],
                     baseline: Dict[str, Any]) -> Dict[str, Any]:
    """Per-scenario quality drift between two gauntlet report sections.

    For each scenario present on both sides: the (positive = regression)
    drop in cell F1, the drop in downstream gap-closed, and the scorecard
    divergence (:func:`compare_scorecards`) between the two runs' per-
    attribute cards. A scenario's severity is the worst of the three;
    improvements never contribute."""
    cur_sc = current.get("scenarios") or {}
    base_sc = baseline.get("scenarios") or {}
    per_scenario: Dict[str, Any] = {}
    for name in sorted(set(cur_sc) | set(base_sc)):
        c, b = cur_sc.get(name), base_sc.get(name)
        if c is None or b is None:
            per_scenario[name] = {
                "status": "missing_in_current" if c is None
                else "missing_in_baseline"}
            continue
        f1_drop = max(0.0, float(b["repair"]["f1"]) -
                      float(c["repair"]["f1"]))
        b_gap = b.get("downstream", {}).get("gap_closed")
        c_gap = c.get("downstream", {}).get("gap_closed")
        gap_drop = max(0.0, float(b_gap) - float(c_gap)) \
            if b_gap is not None and c_gap is not None else 0.0
        cards = compare_scorecards(c.get("scorecards") or {},
                                   b.get("scorecards") or {})
        severity = max(f1_drop, _GAP_SCALE * gap_drop,
                       cards["max_divergence"])
        per_scenario[name] = {
            "f1_drop": round(f1_drop, 6),
            "gap_closed_drop": round(gap_drop, 6),
            "scorecard_divergence": cards["max_divergence"],
            "severity": round(severity, 6),
        }
    scored = [v for v in per_scenario.values() if "severity" in v]
    return {
        "per_scenario": per_scenario,
        "max_f1_drop": round(
            max((v["f1_drop"] for v in scored), default=0.0), 6),
        "max_gap_closed_drop": round(
            max((v["gap_closed_drop"] for v in scored), default=0.0), 6),
        "max_severity": round(
            max((v["severity"] for v in scored), default=0.0), 6),
    }


def emit_gauntlet_drift_gauges(registry: Any,
                               drift: Dict[str, Any]) -> None:
    for name, v in drift.get("per_scenario", {}).items():
        if "severity" not in v:
            continue
        registry.set_gauge(f"drift.gauntlet.{name}.f1_drop", v["f1_drop"])
        registry.set_gauge(f"drift.gauntlet.{name}.severity", v["severity"])
    registry.set_gauge("drift.gauntlet.max_severity",
                       drift.get("max_severity", 0.0))
    if drift.get("failed") is not None:
        registry.set_gauge("drift.gauntlet.failed",
                           1.0 if drift["failed"] else 0.0)


def evaluate_gauntlet(current_gauntlet: Optional[Dict[str, Any]],
                      baseline_report: Optional[Dict[str, Any]],
                      fail_over: Optional[float] = None,
                      registry: Any = None) -> Dict[str, Any]:
    """The per-scenario gauntlet gate: compare against the baseline run
    report's ``gauntlet`` section, attach the fail verdict, emit gauges.

    A baseline without a gauntlet section (any pre-v7 report) flags
    ``baseline_missing`` and never fails, mirroring :func:`evaluate`."""
    baseline_g = (baseline_report or {}).get("gauntlet") or {}
    result = compare_gauntlet(current_gauntlet or {}, baseline_g)
    result["baseline_missing"] = not baseline_g.get("scenarios")
    result["fail_over"] = fail_over
    result["failed"] = bool(
        fail_over is not None and not result["baseline_missing"]
        and result["max_severity"] > fail_over)
    if registry is not None:
        try:
            emit_gauntlet_drift_gauges(registry, result)
        except Exception as e:
            _logger.warning(f"failed to emit gauntlet drift gauges: {e}")
    if result["failed"]:
        _logger.warning(
            "gauntlet drift gate FAILED: max severity {} > fail-over {}"
            .format(result["max_severity"], fail_over))
    return result


# -- sustained-load SLO gate (v9 `slo` sections) -----------------------------
#
# The load harness measures what the fleet DELIVERS under open-loop
# pressure: p99 latency, sustained QPS, shed rate. The gate scores the
# current run's `slo` section against a baseline report's: a p99 that
# doubled, a QPS that halved, or a shed rate that climbed is a serving
# regression even when every repair is still bit-identical — quality
# gates can't see it, this one exists to.

#: p99 regressions are expressed as a fraction of the baseline p99 and can
#: legitimately wobble run-to-run far more than QPS/shed do; halve the
#: fraction onto the shared severity scale so one fail-over threshold
#: governs all three signals (mirroring _GAP_SCALE above).
_SLO_P99_SCALE = 0.5


def _slo_signals(cur: Dict[str, Any], base: Dict[str, Any]
                 ) -> Dict[str, Any]:
    """(positive = regression) drift of one slo bucket vs its baseline
    counterpart: fractional p99 growth, fractional QPS drop, absolute
    shed-rate increase. Severity is the worst of the three; improvements
    never contribute."""
    c_p99 = (cur.get("latency") or {}).get("p99")
    b_p99 = (base.get("latency") or {}).get("p99")
    p99_regression = max(0.0, (float(c_p99) - float(b_p99))
                         / float(b_p99)) \
        if c_p99 is not None and b_p99 and float(b_p99) > 0 else 0.0
    c_qps, b_qps = cur.get("qps"), base.get("qps")
    qps_drop = max(0.0, (float(b_qps) - float(c_qps)) / float(b_qps)) \
        if c_qps is not None and b_qps and float(b_qps) > 0 else 0.0
    shed_increase = max(0.0, float(cur.get("shed_rate") or 0.0)
                        - float(base.get("shed_rate") or 0.0))
    severity = max(_SLO_P99_SCALE * p99_regression, qps_drop,
                   shed_increase)
    return {
        "p99_regression": round(p99_regression, 6),
        "qps_drop": round(qps_drop, 6),
        "shed_rate_increase": round(shed_increase, 6),
        "severity": round(severity, 6),
    }


def compare_slo(current: Dict[str, Any],
                baseline: Dict[str, Any]) -> Dict[str, Any]:
    """SLO drift between two run-report ``slo`` sections: the overall
    bucket plus every segment present on both sides."""
    per_segment: Dict[str, Any] = {}
    cur_seg = current.get("per_segment") or {}
    base_seg = baseline.get("per_segment") or {}
    for name in sorted(set(cur_seg) | set(base_seg)):
        c, b = cur_seg.get(name), base_seg.get(name)
        if c is None or b is None:
            per_segment[name] = {
                "status": "missing_in_current" if c is None
                else "missing_in_baseline"}
            continue
        per_segment[name] = _slo_signals(c, b)
    overall = _slo_signals(current, baseline)
    scored = [v for v in per_segment.values() if "severity" in v]
    scored.append(overall)
    return {
        "overall": overall,
        "per_segment": per_segment,
        "max_p99_regression": round(
            max(v["p99_regression"] for v in scored), 6),
        "max_qps_drop": round(max(v["qps_drop"] for v in scored), 6),
        "max_shed_rate_increase": round(
            max(v["shed_rate_increase"] for v in scored), 6),
        "max_severity": round(max(v["severity"] for v in scored), 6),
    }


def emit_slo_drift_gauges(registry: Any, drift: Dict[str, Any]) -> None:
    overall = drift.get("overall") or {}
    for key in ("p99_regression", "qps_drop", "shed_rate_increase"):
        if key in overall:
            registry.set_gauge(f"drift.slo.{key}", overall[key])
    for name, v in drift.get("per_segment", {}).items():
        if "severity" in v:
            registry.set_gauge(f"drift.slo.{name}.severity", v["severity"])
    registry.set_gauge("drift.slo.max_severity",
                       drift.get("max_severity", 0.0))
    if drift.get("failed") is not None:
        registry.set_gauge("drift.slo.failed",
                           1.0 if drift["failed"] else 0.0)


def evaluate_slo(current_slo: Optional[Dict[str, Any]],
                 baseline_report: Optional[Dict[str, Any]],
                 fail_over: Optional[float] = None,
                 registry: Any = None) -> Dict[str, Any]:
    """The sustained-load SLO gate: compare against the baseline run
    report's ``slo`` section, attach the fail verdict, emit gauges.

    A baseline without an slo section (any pre-v9 report) flags
    ``baseline_missing`` and never fails, mirroring :func:`evaluate`."""
    baseline_s = (baseline_report or {}).get("slo") or {}
    result = compare_slo(current_slo or {}, baseline_s)
    result["baseline_missing"] = not baseline_s.get("requests")
    result["fail_over"] = fail_over
    result["failed"] = bool(
        fail_over is not None and not result["baseline_missing"]
        and result["max_severity"] > fail_over)
    if registry is not None:
        try:
            emit_slo_drift_gauges(registry, result)
        except Exception as e:
            _logger.warning(f"failed to emit slo drift gauges: {e}")
    if result["failed"]:
        _logger.warning(
            "slo drift gate FAILED: max severity {} > fail-over {}"
            .format(result["max_severity"], fail_over))
    return result
